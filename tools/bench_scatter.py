"""On-chip scatter/gather microbench with dedup-safe timing.

Each timed call runs a scan of T iterations whose table carry chains, so no
dispatch dedup; timing is fenced by a host read. Reports us per scatter.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fps_tpu.ops.pallas_kernels import (
    scatter_add_packed_pallas,
    scatter_add_pallas,
    gather_rows_pallas,
)

T = 256


def timeit(fn, *args):
    print("  compiling...", flush=True)
    r = fn(*args)
    print("  compiled", flush=True)
    np.asarray(jax.tree.leaves(r)[0]).ravel()[0]
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        r = fn(*args)
        np.asarray(jax.tree.leaves(r)[0]).ravel()[0]
        best = min(best, time.perf_counter() - t0)
    return best / T * 1e6


def xla_scatter(tab, ids, deltas):
    safe = jnp.where((ids >= 0) & (ids < tab.shape[0]), ids, tab.shape[0])
    return tab.at[safe].add(deltas, mode="drop")


def run(name, R, D, B, alpha=0.8):
    rng = np.random.default_rng(0)
    tab = jnp.asarray(rng.normal(0, 0.1, (R, D)), jnp.float32)
    # Realistic popularity skew: p ~ 1/rank^alpha (matches the synthetic
    # workload generators), not rng.zipf (far too head-heavy).
    pop = 1.0 / np.arange(1, R + 1) ** alpha
    pop /= pop.sum()
    cdf = np.cumsum(pop)
    ids = jnp.asarray(
        np.searchsorted(cdf, rng.random((T, B))), jnp.int32
    )
    dup = 1 - len(np.unique(np.asarray(ids[0]))) / B
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B, D)), jnp.float32)
    print(f"{name}: dup frac {dup:.2f}", flush=True)

    def scan_of(op):
        @jax.jit
        def f(tab, ids, deltas):
            def body(t, x):
                i, d = x
                return op(t, i, d), None
            return lax.scan(body, tab, (ids, deltas))[0]
        return f

    us_x = timeit(scan_of(xla_scatter), tab, ids, deltas)
    us_p = timeit(scan_of(lambda t, i, d: scatter_add_packed_pallas(t, i, d)),
                  tab, ids, deltas)
    print(f"{name:28s} R={R:7d} D={D:3d} B={B:6d}  "
          f"xla {us_x:7.1f}  packed {us_p:7.1f} us", flush=True)

    # correctness spot check vs xla
    a = np.asarray(xla_scatter(tab, ids[0], deltas[0]))
    b = np.asarray(scatter_add_packed_pallas(tab, ids[0], deltas[0]))
    err = np.max(np.abs(a - b) / (np.abs(a) + 1e-6))
    print(f"{'':28s} packed vs xla max relerr {err:.2e}")


def main():
    run("MF item (mean push D+1)", 26744, 11, 32768)
    run("MF item (raw)", 26744, 10, 32768)
    run("MF user", 138496, 10, 32768)
    run("logreg shard (1/8 of 1M)", 131072, 2, 16384 * 39 // 8)
    run("w2v 1chip", 50000, 100, 49152, alpha=0.75)


def dim1_shapes():
    """Scalar-table (D=1) kernels at the PA workload shape: XLA gather and
    scatter vs the in-kernel-lane-packed dim-1 kernels (the round-4 PA
    win; numbers quoted in fps_tpu/ops/pallas_kernels.py's dim-1 header
    and BASELINE.md). B = 2^20 ids, Zipf(0.9), ~95% duplication."""
    from fps_tpu.ops.pallas_kernels import (
        gather_rows_dim1_pallas, scatter_add_dim1_pallas,
    )

    R, B = 47_236, 16_384 * 64
    rng = np.random.default_rng(0)
    tab = jnp.asarray(rng.normal(0, 0.1, (R, 1)), jnp.float32)
    pop = 1.0 / np.arange(1, R + 1) ** 0.9
    pop /= pop.sum()
    cdf = np.cumsum(pop)
    ids = jnp.asarray(np.searchsorted(cdf, rng.random((T, B))), jnp.int32)
    dup = 1 - len(np.unique(np.asarray(ids[0]))) / B
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B, 1)), jnp.float32)
    print(f"PA shape R={R} D=1 B={B}: dup frac {dup:.2f}", flush=True)

    def scan_of(op):
        @jax.jit
        def f(tab, ids, deltas):
            def body(t, x):
                i, d = x
                return op(t, i, d), None
            return lax.scan(body, tab, (ids, deltas))[0]
        return f

    def gathers(take_fn):
        def op(t, i, d):
            v = take_fn(t, i)
            return t + 1e-12 * jnp.sum(v)  # chain so nothing is elided
        return op

    for name, fn in (
        ("xla scatter", scan_of(xla_scatter)),
        ("dim1 scatter", scan_of(
            lambda t, i, d: scatter_add_dim1_pallas(
                t, i, d, row_tile=512, batch_tile=8192))),
        ("xla gather", scan_of(gathers(lambda t, i: jnp.take(t, i, axis=0)))),
        ("dim1 gather", scan_of(gathers(gather_rows_dim1_pallas))),
    ):
        us = timeit(fn, tab, ids, deltas)
        print(f"{name:16s} {us / 1e3:8.2f} ms/call", flush=True)

    a = np.asarray(xla_scatter(tab, ids[0], deltas[0]))
    b = np.asarray(scatter_add_dim1_pallas(tab, ids[0], deltas[0]))
    print(f"dim1 scatter vs xla max abs err {np.max(np.abs(a - b)):.2e}")



def small_r_sweep():
    """The hot/cold split's claimed win regime (round-2 verdict #5): SMALL
    per-shard row counts — a large shard axis leaves each shard a thin row
    slice, where the packed one-hot MXU contraction can beat the per-row
    -transaction-bound XLA scatter. Sweep R x D at fixed batch, print the
    measured crossover. Configs whose packed-contraction FLOPs exceed ~4x
    the runtime budget are skipped — scatter_add's flop cap auto-rejects
    them in production anyway, so timing them is pure wall-clock burn."""
    from fps_tpu.ops import SCATTER_FLOP_BUDGET

    B = 32768
    for D in (10, 32, 100):
        for R in (256, 1024, 2048, 4096, 8192, 16384):
            pack = max(1, 128 // D)
            flops = -(-R // pack) * (2 * B) * 128
            if flops > 4 * SCATTER_FLOP_BUDGET:
                print(f"sweep D={D:3d} R={R:6d}: skipped "
                      f"(packed flops {flops:.1e} > 4x budget)", flush=True)
                continue
            run(f"sweep D={D}", R, D, B)


if __name__ == "__main__":
    import sys

    if len(sys.argv) == 1:
        main()
    elif sys.argv[1:] == ["sweep"]:
        small_r_sweep()
    elif sys.argv[1:] == ["dim1"]:
        dim1_shapes()
    else:
        raise SystemExit(
            f"unknown args {sys.argv[1:]!r} — usage: bench_scatter.py "
            "[sweep|dim1]  (no args = full workload-shape bench; 'sweep' "
            "= small-R crossover sweep; 'dim1' = scalar-table PA shape)"
        )
