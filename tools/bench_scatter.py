"""On-chip scatter/gather microbench with dedup-safe timing.

Each timed call runs a scan of T iterations whose table carry chains, so no
dispatch dedup; timing is fenced by a host read. Reports us per scatter.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fps_tpu.ops.pallas_kernels import (
    scatter_add_packed_pallas,
    scatter_add_pallas,
    gather_rows_pallas,
)

T = 256


def timeit(fn, *args):
    print("  compiling...", flush=True)
    r = fn(*args)
    print("  compiled", flush=True)
    np.asarray(jax.tree.leaves(r)[0]).ravel()[0]
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        r = fn(*args)
        np.asarray(jax.tree.leaves(r)[0]).ravel()[0]
        best = min(best, time.perf_counter() - t0)
    return best / T * 1e6


def xla_scatter(tab, ids, deltas):
    safe = jnp.where((ids >= 0) & (ids < tab.shape[0]), ids, tab.shape[0])
    return tab.at[safe].add(deltas, mode="drop")


def run(name, R, D, B, alpha=0.8):
    rng = np.random.default_rng(0)
    tab = jnp.asarray(rng.normal(0, 0.1, (R, D)), jnp.float32)
    # Realistic popularity skew: p ~ 1/rank^alpha (matches the synthetic
    # workload generators), not rng.zipf (far too head-heavy).
    pop = 1.0 / np.arange(1, R + 1) ** alpha
    pop /= pop.sum()
    cdf = np.cumsum(pop)
    ids = jnp.asarray(
        np.searchsorted(cdf, rng.random((T, B))), jnp.int32
    )
    dup = 1 - len(np.unique(np.asarray(ids[0]))) / B
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B, D)), jnp.float32)
    print(f"{name}: dup frac {dup:.2f}", flush=True)

    def scan_of(op):
        @jax.jit
        def f(tab, ids, deltas):
            def body(t, x):
                i, d = x
                return op(t, i, d), None
            return lax.scan(body, tab, (ids, deltas))[0]
        return f

    us_x = timeit(scan_of(xla_scatter), tab, ids, deltas)
    us_p = timeit(scan_of(lambda t, i, d: scatter_add_packed_pallas(t, i, d)),
                  tab, ids, deltas)
    print(f"{name:28s} R={R:7d} D={D:3d} B={B:6d}  "
          f"xla {us_x:7.1f}  packed {us_p:7.1f} us", flush=True)

    # correctness spot check vs xla
    a = np.asarray(xla_scatter(tab, ids[0], deltas[0]))
    b = np.asarray(scatter_add_packed_pallas(tab, ids[0], deltas[0]))
    err = np.max(np.abs(a - b) / (np.abs(a) + 1e-6))
    print(f"{'':28s} packed vs xla max relerr {err:.2e}")


def main():
    run("MF item (mean push D+1)", 26744, 11, 32768)
    run("MF item (raw)", 26744, 10, 32768)
    run("MF user", 138496, 10, 32768)
    run("logreg shard (1/8 of 1M)", 131072, 2, 16384 * 39 // 8)
    run("w2v 1chip", 50000, 100, 49152, alpha=0.75)


if __name__ == "__main__":
    main()
