"""Render fps_tpu obs/pod directories into one Chrome-trace / Perfetto
JSON — the merged causal view of a (possibly multi-host) run.

Input: one or more directories holding ``journal-*.jsonl`` files (an
``--obs-dir``, a supervisor ``--state-dir``, or a whole pod dir — the
tool walks subdirectories, so pointing it at ``pod_dir`` picks up the
pod journal, every member's supervisor journal, and every child's run
journals in one pass). Each journal line becomes a span:

* ``journal-pod.jsonl`` — the pod root span (``pod_start`` →
  shutdown/give-up), one **decision span per coordinated restart**
  (``pod_launch``/``pod_restart``, closed by the next decision), and
  instants for lease churn / fences / membership changes;
* ``journal-supervisor.jsonl`` — one span per supervisor run and one per
  **attempt** (``attempt_start``/``attempt_end`` pairs, parented to the
  pod decision that commanded them via the control record's span id,
  carrying the fencing epoch);
* ``journal-p<K>.jsonl`` — one span per training run (``run_start`` →
  ``run_end``, parented to the attempt via the env contract), per chunk
  (phase breakdown from the ``PhaseTimer`` fields on ``chunk``/``epoch``
  events), and per checkpoint publish; plus every explicit ``span``
  event a :class:`fps_tpu.obs.trace.Tracer` emitted.

The result: a ``pod_kill_one_host`` chaos run exports ONE causally
linked span tree — leader decision → per-host attempts → per-chunk
phases — instead of N disconnected per-host fragments. Open the output
in ``chrome://tracing`` or https://ui.perfetto.dev.

Pure host tool: stdlib only, no jax/numpy/fps_tpu imports (loadable by
file path from chaos scenarios and login nodes).

Usage:
  python tools/trace_export.py DIR [DIR...] [-o trace.json] [--pretty]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Serial driver phases, in pipeline order (mirrors
# fps_tpu.obs.timing.DRIVER_PHASES minus the overlapped 'prefetch' —
# this tool is deliberately import-free).
_SERIAL_PHASES = ("ingest", "place", "dispatch", "host_sync",
                  "checkpoint", "callback", "reconcile", "retier")
_OVERLAPPED_PHASES = ("prefetch",)

# Journal events rendered as zero-duration instants, by source.
_POD_INSTANTS = (
    "lease_acquired", "lease_seized", "lease_lost", "fence_written",
    "member_failed", "member_evicted", "member_readmitted",
    "member_synced", "pod_quarantine", "readmit_deferred",
    "decision_abandoned",
)
_SUP_INSTANTS = ("deadline_abort", "supervisor_restart",
                 "chunk_quarantined", "member_stall_detected",
                 "heartbeat_rejected", "supervisor_give_up")
_RUN_INSTANTS = ("checkpoint_enqueued", "checkpoint_fallback",
                 "checkpoint_fenced", "checkpoint_resplit", "rollback",
                 "preset_skip", "guard_escalated", "stall",
                 "stall_recovered", "health_abort", "serve_swap",
                 "budget_drift")

_POD_DECISIONS = ("pod_launch", "pod_restart")
_POD_TERMINALS = ("pod_shutdown", "pod_give_up")


def _read_jsonl(path: str):
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    return  # torn tail of a live/killed writer
    except OSError:
        return


def _journal_files(dirs):
    """Every journal-*.jsonl under the given dirs (recursive), with the
    immediate parent directory's basename as the host hint."""
    out = []
    for d in dirs:
        if os.path.isfile(d):
            out.append((d, os.path.basename(os.path.dirname(d))))
            continue
        for root, subdirs, files in os.walk(d):
            subdirs[:] = sorted(s for s in subdirs if s != "__pycache__")
            for f in sorted(files):
                if f.startswith("journal-") and f.endswith(".jsonl"):
                    out.append((os.path.join(root, f),
                                os.path.basename(root) or d))
    return out


class _Minted:
    """Deterministic fallback span ids for records that carry none."""

    def __init__(self):
        self.n = 0

    def __call__(self) -> str:
        self.n += 1
        return f"synth-{self.n:06d}"


def _span(name, t0, t1, rec, *, span_id, parent_id, host, cat,
          attrs=None) -> dict:
    return {
        "name": name,
        "cat": cat,
        "t0": float(t0),
        "t1": float(max(t0, t1)),
        "trace_id": rec.get("trace_id"),
        "span_id": span_id,
        "parent_id": parent_id,
        "host": host,
        "attrs": dict(attrs or {}),
    }


def _pod_spans(records, host_hint, mint) -> list[dict]:
    spans = []
    max_t = max((r.get("t", 0.0) for r in records), default=0.0)
    root = None
    decisions = []  # open decision spans, closed by the next decision
    for rec in records:
        et = rec.get("event")
        t = float(rec.get("t", 0.0))
        if et == "pod_start":
            root = _span("pod", t, max_t, rec,
                         span_id=rec.get("span_id") or mint(),
                         parent_id=None, host=rec.get("host", host_hint),
                         cat="pod",
                         attrs={k: rec.get(k) for k in
                                ("roster", "pod_size", "elastic")})
            spans.append(root)
        elif et in _POD_DECISIONS + _POD_TERMINALS:
            for d in decisions:
                d["t1"] = max(d["t0"], t)  # closed by this decision
            decisions.clear()
            if et in _POD_DECISIONS:
                s = _span(et, t, max_t, rec,
                          span_id=rec.get("span_id") or mint(),
                          parent_id=rec.get("parent_id")
                          or (root and root["span_id"]),
                          host=rec.get("host", host_hint), cat="decision",
                          attrs={k: rec.get(k) for k in
                                 ("epoch", "step", "world", "members",
                                  "failed", "reason", "restarts",
                                  "quarantined")})
                decisions.append(s)
                spans.append(s)
            else:
                spans.append(_span(
                    et, t, t, rec, span_id=rec.get("span_id") or mint(),
                    parent_id=rec.get("parent_id")
                    or (root and root["span_id"]),
                    host=rec.get("host", host_hint), cat="decision",
                    attrs={k: rec.get(k) for k in ("epoch", "reason")}))
        elif et in _POD_INSTANTS:
            attrs = {k: v for k, v in rec.items()
                     if k not in ("kind", "t", "event", "trace_id",
                                  "span_id", "parent_id")}
            spans.append(_span(
                et, t, t, rec, span_id=rec.get("span_id") or mint(),
                parent_id=rec.get("parent_id")
                or (root and root["span_id"]),
                host=rec.get("host", host_hint), cat="pod_event",
                attrs=attrs))
    return spans


def _supervisor_spans(records, host_hint, mint) -> list[dict]:
    spans = []
    max_t = max((r.get("t", 0.0) for r in records), default=0.0)
    run_span = None
    attempts = {}  # span_id -> span (open until attempt_end)
    by_attempt = {}  # attempt number -> span_id
    for rec in records:
        et = rec.get("event")
        t = float(rec.get("t", 0.0))
        if et == "supervisor_start" or et == "pod_member_start":
            run_span = _span(
                "supervise", t, max_t, rec,
                span_id=rec.get("span_id") or mint(),
                parent_id=rec.get("parent_id"),
                host=rec.get("host", host_hint), cat="supervise",
                attrs={})
            spans.append(run_span)
        elif et in ("supervised_run_end", "pod_member_end"):
            if run_span is not None:
                run_span["t1"] = max(run_span["t0"], t)
                run_span["attrs"].update(
                    {k: rec.get(k) for k in ("success", "reason")
                     if k in rec})
        elif et == "attempt_start":
            sid = rec.get("span_id") or mint()
            s = _span("attempt", t, max_t, rec, span_id=sid,
                      parent_id=rec.get("parent_id")
                      or (run_span and run_span["span_id"]),
                      host=rec.get("host", host_hint), cat="attempt",
                      attrs={k: rec.get(k) for k in
                             ("attempt", "pid", "pod_epoch")
                             if rec.get(k) is not None})
            attempts[sid] = s
            if rec.get("attempt") is not None:
                by_attempt[rec["attempt"]] = sid
            spans.append(s)
        elif et == "attempt_end":
            s = attempts.get(rec.get("span_id"))
            if s is not None:
                s["t1"] = max(s["t0"], t)
                s["attrs"].update({k: rec.get(k) for k in
                                   ("rc", "aborted", "stall_kind",
                                    "last_index", "pod_epoch")
                                   if rec.get(k) is not None})
        elif et in _SUP_INSTANTS:
            parent = by_attempt.get(rec.get("attempt"))
            attrs = {k: v for k, v in rec.items()
                     if k not in ("kind", "t", "event", "trace_id",
                                  "span_id", "parent_id", "cmd")}
            spans.append(_span(
                et, t, t, rec, span_id=rec.get("span_id") or mint(),
                parent_id=parent or (run_span and run_span["span_id"]),
                host=rec.get("host", host_hint), cat="sup_event",
                attrs=attrs))
    return spans


def _run_spans(records, host_hint, mint) -> list[dict]:
    spans = []
    max_t = max((r.get("t", 0.0) for r in records), default=0.0)
    run_span = None
    for rec in records:
        et = rec.get("event")
        t = float(rec.get("t", 0.0))
        if et == "run_start":
            run_span = _span(
                "run", t, max_t, rec,
                span_id=rec.get("span_id") or mint(),
                parent_id=rec.get("parent_id"),
                host=rec.get("host", host_hint), cat="run",
                attrs={k: rec.get(k) for k in
                       ("process", "config_digest", "run_id", "workload")
                       if rec.get(k) is not None})
            spans.append(run_span)
        elif et == "run_end":
            if run_span is not None:
                run_span["t1"] = max(run_span["t0"], t)
        elif et == "span":
            spans.append(_span(
                rec.get("span", "span"), rec.get("t0", t),
                rec.get("t1", t), rec,
                span_id=rec.get("span_id") or mint(),
                parent_id=rec.get("parent_id")
                or (run_span and run_span["span_id"]),
                host=rec.get("host", host_hint), cat="span",
                attrs={k: v for k, v in rec.items()
                       if k not in ("kind", "t", "event", "span",
                                    "trace_id", "span_id", "parent_id",
                                    "t0", "t1", "run_id")}))
        elif et in ("chunk", "epoch"):
            phases = rec.get("phases") or {}
            serial = sum(float(phases.get(p, 0.0))
                         for p in _SERIAL_PHASES)
            serial += sum(float(v) for k, v in phases.items()
                          if k not in _SERIAL_PHASES
                          and k not in _OVERLAPPED_PHASES)
            t0 = t - serial
            parent = run_span and run_span["span_id"]
            sid = mint()
            spans.append(_span(
                et, t0, t, rec, span_id=sid, parent_id=parent,
                host=rec.get("host", host_hint), cat="chunk",
                attrs={k: rec.get(k) for k in
                       ("index", "quarantined", "examples")
                       if rec.get(k) is not None}))
            cursor = t0
            for p in _SERIAL_PHASES:
                dur = float(phases.get(p, 0.0))
                if dur <= 0.0:
                    continue
                spans.append(_span(
                    p, cursor, cursor + dur, rec, span_id=mint(),
                    parent_id=sid, host=rec.get("host", host_hint),
                    cat="phase", attrs={}))
                cursor += dur
            for p in _OVERLAPPED_PHASES:
                dur = float(phases.get(p, 0.0))
                if dur > 0.0:
                    # Worker-thread time overlapped with the serial
                    # phases — rendered alongside, flagged as such.
                    spans.append(_span(
                        p, t0, t0 + dur, rec, span_id=mint(),
                        parent_id=sid, host=rec.get("host", host_hint),
                        cat="phase", attrs={"overlapped": True}))
        elif et == "checkpoint_saved":
            dur = float(rec.get("seconds", 0.0) or 0.0)
            spans.append(_span(
                "checkpoint_publish", t - dur, t, rec, span_id=mint(),
                parent_id=run_span and run_span["span_id"],
                host=rec.get("host", host_hint), cat="checkpoint",
                attrs={k: rec.get(k) for k in ("step", "bytes")
                       if rec.get(k) is not None}))
        elif et in _RUN_INSTANTS:
            attrs = {k: v for k, v in rec.items()
                     if k not in ("kind", "t", "event", "trace_id",
                                  "span_id", "parent_id", "run_id")}
            spans.append(_span(
                et, t, t, rec, span_id=rec.get("span_id") or mint(),
                parent_id=rec.get("parent_id")
                or (run_span and run_span["span_id"]),
                host=rec.get("host", host_hint), cat="run_event",
                attrs=attrs))
    return spans


def collect_spans(dirs) -> list[dict]:
    """Every span reconstructable from the journals under ``dirs`` (see
    module docstring for the per-journal synthesis rules)."""
    mint = _Minted()
    spans: list[dict] = []
    for path, host_hint in _journal_files(dirs):
        records = list(_read_jsonl(path))
        if not records:
            continue
        base = os.path.basename(path)
        if base == "journal-pod.jsonl":
            spans.extend(_pod_spans(records, host_hint, mint))
        elif base == "journal-supervisor.jsonl":
            spans.extend(_supervisor_spans(records, host_hint, mint))
        else:
            spans.extend(_run_spans(records, host_hint, mint))
    return spans


def children_of(spans) -> dict:
    """``parent span_id -> [child spans]`` index."""
    out: dict = {}
    for s in spans:
        if s.get("parent_id"):
            out.setdefault(s["parent_id"], []).append(s)
    return out


def coordinated_restart_trees(spans) -> list[dict]:
    """One entry per coordinated-restart DECISION span (``pod_restart``),
    with the child spans hanging under it (the per-host attempts the
    control record commanded). The chaos scenarios assert on this:
    exactly one tree per restart, with the fencing epoch on every child
    attempt span."""
    kids = children_of(spans)
    out = []
    for s in spans:
        if s["name"] != "pod_restart":
            continue
        out.append({
            "epoch": s["attrs"].get("epoch"),
            "span": s,
            "children": sorted(kids.get(s["span_id"], ()),
                               key=lambda c: (c.get("host") or "",
                                              c["t0"])),
        })
    return sorted(out, key=lambda e: (e["epoch"] or 0))


def export_chrome(spans) -> dict:
    """Chrome trace-event JSON (also loadable in Perfetto): one complete
    ('X') event per span, processes keyed by host, plus process-name
    metadata."""
    pids: dict = {}
    events = []
    tids = {"pod": 0, "decision": 1, "pod_event": 2, "supervise": 3,
            "attempt": 4, "sup_event": 5, "run": 6, "chunk": 7,
            "phase": 8, "checkpoint": 9, "run_event": 10, "span": 11}
    for s in sorted(spans, key=lambda x: x["t0"]):
        host = s.get("host") or "?"
        pid = pids.setdefault(host, len(pids) + 1)
        args = {"span_id": s["span_id"], "parent_id": s.get("parent_id"),
                "trace_id": s.get("trace_id"), **s["attrs"]}
        events.append({
            "name": s["name"],
            "cat": s["cat"],
            "ph": "X",
            "ts": int(s["t0"] * 1e6),
            "dur": max(1, int((s["t1"] - s["t0"]) * 1e6)),
            "pid": pid,
            "tid": tids.get(s["cat"], 12),
            "args": args,
        })
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": host}} for host, pid in pids.items()]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Export fps_tpu journals as one Chrome/Perfetto "
                    "trace")
    ap.add_argument("dirs", nargs="+",
                    help="obs / supervisor-state / pod directories "
                         "(walked recursively for journal-*.jsonl)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: stdout)")
    ap.add_argument("--pretty", action="store_true")
    args = ap.parse_args(argv)
    spans = collect_spans(args.dirs)
    if not spans:
        print(f"no journal-*.jsonl spans under {args.dirs}",
              file=sys.stderr)
        return 2
    doc = export_chrome(spans)
    text = json.dumps(doc, indent=2 if args.pretty else None,
                      allow_nan=False, default=str)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        trees = coordinated_restart_trees(spans)
        print(f"wrote {args.out}: {len(spans)} spans, "
              f"{len(trees)} coordinated restart(s)", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
