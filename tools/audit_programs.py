"""Certify the example workloads' compiled step programs and write the
machine-readable certificate JSON.

The batch CLI over ``fps_tpu.analysis`` (``docs/analysis.md``): builds
each of the six example workloads (mf, streaming_mf, logreg, w2v, pa,
ials) plus the tiered/untiered MF pair on the 8-device CPU mesh at a
small fixed audit scale, lowers the exact program the driver would
dispatch (``Trainer._get_compiled(mode).lower(...)``; the iALS
accumulate kernel for the solver workload), and runs the full pass
suite against a PINNED :class:`~fps_tpu.analysis.ProgramContract` per
``(workload, route, tiering)`` row — collective count/byte budgets,
host-transfer freedom, table donation, dtype drift, and the hot-tier
reconcile psum for the tiered row.

The budgets in :data:`BUDGETS` are the certified collective structure
of each program (the table in ``docs/analysis.md`` is generated from a
run of this tool). They are exact counts, not ceilings-with-slack: a
future PR that adds or removes a data-plane collective fails this audit
until it re-pins the budget — which is the point (the diff becomes the
review artifact).

Usage:
  python tools/audit_programs.py [--out CERTS.json] [--only mf,logreg]
                                 [--measure]
  python tools/audit_programs.py --hlo DUMP.txt [--hlo ...]
                                 [--min-bytes N]

``--measure`` prints each program's measured profile instead of
enforcing budgets — the workflow for re-pinning after a deliberate
program change. Exit status is 0 iff every selected program certifies
clean.

``--hlo`` profiles saved ``lower(...).as_text()`` dumps instead of
building workloads: no jax, no mesh, no re-exec (the analysis package
is loaded through a stub root so ``fps_tpu/__init__`` never imports
jax) — the login-node workflow for programs lowered elsewhere.

Like bench/conftest, re-execs itself into a cleaned 8-CPU-device
environment when the current process cannot see 8 devices.
"""

from __future__ import annotations

import argparse
import dataclasses
import importlib.util
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

# Audit scale: tiny but structurally faithful — every route (gathered
# pull, push scatter, SSP snapshot, hot tier, iALS normal equations)
# lowers the same op structure it has at bench scale; only the payload
# bytes shrink. Fixed so the pinned budgets are deterministic.
NU, NI, RANK = 96, 64, 8
NF, NNZ = 400, 8
VOCAB, W2V_DIM = 50, 8
LOCAL_BATCH, STEPS = 32, 4


def _reexec_if_needed() -> None:
    """Re-exec into a cleaned 8-CPU-device process (conftest pattern):
    the container's sitecustomize registers the single-chip TPU backend
    at interpreter start, too early to widen from inside."""
    spec = importlib.util.spec_from_file_location(
        "_fps_hostenv", os.path.join(_ROOT, "fps_tpu", "utils",
                                     "hostenv.py"))
    hostenv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hostenv)
    if hostenv.in_reexec():
        return
    env = hostenv.cpu_mesh_env(8)
    env["PYTHONPATH"] = os.pathsep.join(
        [_ROOT] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p])
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _load_analysis_offline():
    """Import ``fps_tpu.analysis`` without executing ``fps_tpu/__init__``
    (which imports jax): register a stub root package whose ``__path__``
    points at the real package directory, then import the subpackage
    normally — the analysis modules themselves are stdlib-only."""
    import importlib
    import types

    if "fps_tpu" not in sys.modules:
        stub = types.ModuleType("fps_tpu")
        stub.__path__ = [os.path.join(_ROOT, "fps_tpu")]
        sys.modules["fps_tpu"] = stub
    return importlib.import_module("fps_tpu.analysis")


def _offline_main(argv) -> int:
    """``--hlo`` mode: profile saved ``.as_text()`` dumps — no jax, no
    device mesh, no re-exec, so it runs on a login node against programs
    lowered elsewhere."""
    ap = argparse.ArgumentParser(
        description="profile saved StableHLO dumps (fps_tpu.analysis, "
                    "jax-free)")
    ap.add_argument("--hlo", action="append", required=True, metavar="PATH",
                    help="saved lower(...).as_text() dump (repeatable)")
    ap.add_argument("--min-bytes", type=int, default=1024,
                    help="collective payload threshold (default 1024)")
    args = ap.parse_args(argv)
    analysis = _load_analysis_offline()
    out = {}
    for path in args.hlo:
        with open(path, encoding="utf-8") as f:
            prof = analysis.collective_profile(f.read(), args.min_bytes)
        out[path] = {
            "collectives": len(prof),
            "bytes": sum(c.payload_bytes for c in prof),
            "profile": [{"kind": c.kind, "bytes": c.payload_bytes,
                         "replica_groups": c.replica_groups}
                        for c in prof],
        }
    print(json.dumps(out))
    return 0


if __name__ == "__main__" and any(
        a == "--hlo" or a.startswith("--hlo=") for a in sys.argv[1:]):
    sys.exit(_offline_main(sys.argv[1:]))

if __name__ == "__main__":
    # Only the CLI re-execs (os.execve REPLACES the process — an
    # importer reusing BUDGETS/builders must not be swallowed);
    # importers are responsible for their own device mesh.
    _reexec_if_needed()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from fps_tpu.analysis import (  # noqa: E402
    ProgramContract,
    certify,
    collective_profile,
)
from fps_tpu.core.driver import num_workers_of  # noqa: E402
from fps_tpu.core.ingest import multi_epoch_chunks  # noqa: E402
from fps_tpu.parallel.mesh import make_ps_mesh  # noqa: E402

# ---------------------------------------------------------------------------
# Pinned per-program budgets: (max_collectives, max_collective_bytes,
# per_kind_max). Measured at the audit scale above on the 8-device mesh
# (``--measure`` re-derives them); docs/analysis.md carries the same
# table with the rationale per row.
# ---------------------------------------------------------------------------

BUDGETS: dict[str, dict] = {
    # Untiered sync MF: gathered pull (all_gather) + routed push
    # (all_to_all) — the 2-collective data plane of BENCH r05.
    "mf": dict(max_collectives=2, max_collective_bytes=4096,
               per_kind_max={"all_gather": 1, "all_to_all": 1}),
    # SSP MF (streaming example's mode): the data plane is the same two
    # collectives — the sync-round snapshot all_gather lowers OUTSIDE
    # the per-step window at this audit scale (sub-threshold per step).
    "streaming_mf": dict(max_collectives=2, max_collective_bytes=4096,
                         per_kind_max={"all_gather": 1, "all_to_all": 1}),
    # Tiered MF (hot head replicated, E=2), SHARDED reconcile (PR 10,
    # arXiv:2004.13336): cold routes keep their two collectives; the
    # window reconcile is now a reduce-scatter (H*rank*4 = 1024B, each
    # replica receives its disjoint 1/S slice) + the re-broadcast
    # all_gather (1024B) in place of the old full-head psum —
    # ReplicaConsistency certifies the reduce_scatter.
    "mf_tiered": dict(max_collectives=4, max_collective_bytes=6144,
                      per_kind_max={"all_gather": 2, "all_to_all": 1,
                                    "reduce_scatter": 1}),
    # Partial head (H=32 of 64) over the GATHERED cold routes with the
    # STATIC full-batch payload — the ROADMAP scaling cliff this PR's
    # compacted row is measured against: pull = ids all_gather (1024B) +
    # vals reduce_scatter (8192B), push = ids+deltas all_gathers
    # (1024B + 8192B), plus the sharded reconcile RS+AG (1024B each).
    "mf_tiered_gathered": dict(max_collectives=6,
                               max_collective_bytes=20480,
                               per_kind_max={"all_gather": 4,
                                             "reduce_scatter": 2}),
    # The same partial head with cold_budget=8 (payload-proportional
    # routing): cold ids compact into the certified 8-wide lane, so the
    # gathered collectives shrink to O(lane) — vals RS 2048B + deltas AG
    # 2048B (the 256B id lanes fall below the 1024B payload threshold).
    # Cold-route bytes 18432 -> 4096: the statically-pinned 4.5x form of
    # the bench A/B's >= 3x acceptance claim.
    "mf_tiered_compact": dict(max_collectives=4,
                              max_collective_bytes=6144,
                              per_kind_max={"all_gather": 2,
                                            "reduce_scatter": 2}),
    # ADAPTIVE tier over the mf_tiered config (fps_tpu.tiering: mapped
    # hot set + online tracking): the cold routes and the sharded
    # reconcile RS+AG of mf_tiered (the mapped reconcile scatters by gid
    # DATA — same collectives), plus ONE all_reduce: the tracker's
    # end-of-call sketch merge (4x2048 f32 = 32768B). The slot-map/gid
    # lookups are local gathers — re-ranks swap those arrays without
    # touching this profile (rerank_byte_identity pins that claim
    # exactly).
    "mf_retier": dict(max_collectives=5, max_collective_bytes=38912,
                      per_kind_max={"all_gather": 2, "all_to_all": 1,
                                    "all_reduce": 1,
                                    "reduce_scatter": 1}),
    # Device-resident megastep over the compacted tiered config (H=32
    # of 64, cold_budget=8, K chunk segments fused into one program —
    # fps_tpu.core.megastep). The census covers BOTH cold-route
    # branches of the per-window overflow vote's lax.cond (compacted
    # and bit-identical static — the compact branch's 8-wide lanes sit
    # below the 1024B payload threshold, so the counted collectives are
    # the static branch's cold routes plus each branch's sharded
    # reconcile RS+AG). Pinned IDENTICAL for any K — the
    # megastep_k_independence check asserts the census does not move
    # between K=2 and K=4 (collective cost is O(traffic), never O(K)).
    "mf_megastep": dict(max_collectives=10, max_collective_bytes=26624,
                        per_kind_max={"all_gather": 6,
                                      "reduce_scatter": 4}),
    # Sparse logreg, gathered route + adagrad server fold.
    "logreg": dict(max_collectives=2, max_collective_bytes=3200,
                   per_kind_max={"all_gather": 1, "all_to_all": 1}),
    # Word2vec: in/out vectors for center+context+negatives across two
    # tables lower as six gathered pulls (pushes fold into the same
    # gather/scatter route — no all_to_all at this scale).
    "w2v": dict(max_collectives=6, max_collective_bytes=40448,
                per_kind_max={"all_gather": 6}),
    # Passive-aggressive shares logreg's route structure.
    "pa": dict(max_collectives=2, max_collective_bytes=3200,
               per_kind_max={"all_gather": 1, "all_to_all": 1}),
    # iALS accumulate: the fixed factor table and per-step row gathers
    # (5 all_gathers) feed the normal-equation fold; accumulators stay
    # sharded through one reduce_scatter.
    "ials": dict(max_collectives=6, max_collective_bytes=84992,
                 per_kind_max={"all_gather": 5, "reduce_scatter": 1}),
}


def _mf_pieces(mesh, *, sync_every=None, hot_tier=0, hot_sync_every=1,
               cold_budget=0, gathered=False, skew=False):
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK)
    trainer, store = online_mf(mesh, cfg, sync_every=sync_every)
    if hot_tier:
        for name, spec in store.specs.items():
            over = {}
            if gathered:
                # Force the gathered cold route: the compacted-lane rows
                # audit the payload-proportional claim, which is about
                # embedding-scale tables whose cold route cannot afford
                # table-sized dense collectives (the audit-scale table
                # would otherwise auto-resolve dense).
                over["dense_collectives"] = False
            store.specs[name] = dataclasses.replace(
                spec, hot_tier=min(hot_tier, spec.num_ids),
                cold_budget=cold_budget, **over)
        trainer.config = dataclasses.replace(
            trainer.config, hot_sync_every=hot_sync_every)
    data = synthetic_ratings(NU, NI, 2000, rank=3, seed=3)
    if skew:
        # Hot-heavy item stream (~95% head hits) so the compacted row's
        # host certifier accepts the audit chunk — the program SHAPES
        # (the pinned payloads) are data-independent; the data only
        # decides whether the compacted or the static program lowers.
        rng = np.random.default_rng(7)
        item = np.where(
            rng.random(len(data["item"])) < 0.95,
            rng.integers(0, min(hot_tier, NI) or NI,
                         len(data["item"])),
            rng.integers(min(hot_tier, NI), NI, len(data["item"])),
        ).astype(np.int32)
        data = dict(data, item=item)
    chunks = multi_epoch_chunks(
        data, 1, num_workers=num_workers_of(mesh), local_batch=LOCAL_BATCH,
        steps_per_chunk=STEPS, route_key="user", sync_every=sync_every,
        seed=11)
    return trainer, chunks


def _lower_chunk_program(trainer, chunks, mode="sync") -> str:
    """The exact per-chunk program ``fit_stream`` dispatches."""
    return trainer.lowered_chunk_text(next(iter(chunks)), mode)


def build_mf(mesh) -> str:
    return _lower_chunk_program(*_mf_pieces(mesh))


def build_streaming_mf(mesh) -> str:
    # The streaming example's distinct program is the SSP mode (chunked
    # sync_every windows over an unbounded source).
    trainer, chunks = _mf_pieces(mesh, sync_every=2)
    return _lower_chunk_program(trainer, chunks, mode="ssp")


def build_mf_tiered(mesh) -> str:
    trainer, chunks = _mf_pieces(mesh, hot_tier=32, hot_sync_every=2)
    return _lower_chunk_program(trainer, chunks)


def build_mf_tiered_gathered(mesh) -> str:
    """Partial head over the GATHERED (non-dense) cold routes, STATIC
    full-batch payload — the baseline the compacted row's >= 3x
    cold-byte claim is measured against."""
    trainer, chunks = _mf_pieces(mesh, hot_tier=32, hot_sync_every=2,
                                 gathered=True, skew=True)
    return _lower_chunk_program(trainer, chunks)


def build_mf_tiered_compact(mesh) -> str:
    """The same partial head with ``cold_budget=8``: cold ids compact
    into the certified lane, so the gathered collectives carry O(lane)
    payload — the payload-proportional routing row."""
    trainer, chunks = _mf_pieces(mesh, hot_tier=32, hot_sync_every=2,
                                 gathered=True, cold_budget=8, skew=True)
    return _lower_chunk_program(trainer, chunks)


def _mf_retier_pieces(mesh):
    """Adaptive (mapped + tracked) tier over the tiered-MF audit config:
    partial head H=32 of NI=64 under a Retierer, so the program carries
    the slot-map routes, the mapped reconcile, and the tracker's sketch
    ops."""
    from fps_tpu.tiering import Retierer

    trainer, chunks = _mf_pieces(mesh, hot_tier=32, hot_sync_every=2)
    trainer.retierer = Retierer(check_every=4)
    return trainer, chunks


def build_mf_retier(mesh) -> str:
    return _lower_chunk_program(*_mf_retier_pieces(mesh))


def rerank_byte_identity(mesh) -> bool:
    """THE recompile-freedom claim as a pinned contract: two different
    re-ranks of the same (H, table) must lower BYTE-IDENTICAL programs —
    the hot id membership rides as replicated slot-map/gid DATA, never
    as trace constants. A future change that bakes the ranking into the
    program (a fresh compile per re-rank) fails this audit."""
    trainer, chunks = _mf_retier_pieces(mesh)
    chunk = next(iter(chunks))
    t1 = trainer.lowered_chunk_text(chunk, "sync")
    # Re-rank to a disjoint hot id set of the same size (num_ids=64,
    # H=32: the complementary half) and lower again.
    trainer.retierer.hot_ids["item_factors"] = np.arange(
        32, 64, dtype=np.int64)
    t2 = trainer.lowered_chunk_text(chunk, "sync")
    return t1 == t2


def _mf_megastep_pieces(mesh, K: int):
    """Tiered partial-head MF (H=32 of 64, cold_budget=8, gathered cold
    routes) over the device-ingest path, fused into a K-chunk megastep —
    the program contains BOTH cold-route branches (the device-side
    overflow VOTE ``lax.cond``-selects per window), so the pinned census
    covers the compacted AND the static branch bodies plus the vote's
    verdict psum and the window reconcile."""
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK)
    trainer, store = online_mf(mesh, cfg, max_steps_per_call=STEPS)
    for name, spec in store.specs.items():
        store.specs[name] = dataclasses.replace(
            spec, hot_tier=32, cold_budget=8, dense_collectives=False)
    trainer.config = dataclasses.replace(trainer.config, hot_sync_every=2)
    data = synthetic_ratings(NU, NI, 2000, rank=3, seed=3)
    plan = DeviceEpochPlan(
        DeviceDataset(mesh, data), num_workers=num_workers_of(mesh),
        local_batch=LOCAL_BATCH, route_key="user", seed=11)
    return trainer, plan


def build_mf_megastep(mesh) -> str:
    trainer, plan = _mf_megastep_pieces(mesh, 2)
    return trainer.lowered_megastep_text(plan, chunks_per_dispatch=2)


def megastep_k_independence(mesh) -> bool:
    """THE megastep scaling claim as a pinned contract: collective count
    AND payload bytes must be IDENTICAL when K doubles — the per-step
    collectives live inside the scan body (one static occurrence
    whatever K is) and the boundary ticks move O(window) payload per
    window, so megastep collective cost scales with traffic, never with
    how many chunks are fused into the dispatch. A change that unrolls
    the segment loop (or adds a per-segment collective outside the scan
    body) fails this audit."""
    t2, p2 = _mf_megastep_pieces(mesh, 2)
    t4, p4 = _mf_megastep_pieces(mesh, 4)
    prof2 = collective_profile(
        t2.lowered_megastep_text(p2, chunks_per_dispatch=2))
    prof4 = collective_profile(
        t4.lowered_megastep_text(p4, chunks_per_dispatch=4))

    def census(prof):
        kinds: dict[str, list] = {}
        for c in prof:
            kinds.setdefault(c.kind, []).append(c.payload_bytes)
        return {k: sorted(v) for k, v in sorted(kinds.items())}

    return census(prof2) == census(prof4)


def build_logreg(mesh) -> str:
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )
    from fps_tpu.utils.datasets import synthetic_sparse_classification

    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, _ = logistic_regression(mesh, cfg)
    data = synthetic_sparse_classification(2000, NF, NNZ, seed=7)
    data = dict(data, label=(data["label"] > 0).astype(np.float32))
    chunks = multi_epoch_chunks(
        data, 1, num_workers=num_workers_of(mesh), local_batch=LOCAL_BATCH,
        steps_per_chunk=STEPS, seed=3)
    return _lower_chunk_program(trainer, chunks)


def build_w2v(mesh) -> str:
    from fps_tpu.models.word2vec import (
        W2VConfig,
        skipgram_chunks,
        word2vec,
    )

    rng = np.random.default_rng(5)
    tokens = rng.integers(0, VOCAB, 20_000, dtype=np.int32)
    uni = np.bincount(tokens, minlength=VOCAB).astype(np.float64)
    cfg = W2VConfig(vocab_size=VOCAB, dim=W2V_DIM, window=2, negatives=2,
                    subsample_t=None)
    trainer, _ = word2vec(mesh, cfg, uni)
    chunks = skipgram_chunks(
        tokens, uni, cfg, num_workers=num_workers_of(mesh),
        local_batch=LOCAL_BATCH, steps_per_chunk=STEPS, seed=9)
    return _lower_chunk_program(trainer, chunks)


def build_pa(mesh) -> str:
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.passive_aggressive import (
        PAConfig,
        passive_aggressive,
    )
    from fps_tpu.utils.datasets import synthetic_sparse_classification

    cfg = PAConfig(num_features=NF, variant="PA-I", C=1.0)
    trainer, _ = passive_aggressive(mesh, cfg)
    data = synthetic_sparse_classification(2000, NF, NNZ, seed=7)
    chunks = epoch_chunks(
        data, num_workers=num_workers_of(mesh), local_batch=LOCAL_BATCH,
        steps_per_chunk=STEPS, seed=3)
    return _lower_chunk_program(trainer, chunks)


def build_ials(mesh) -> str:
    """The iALS accumulate kernel — the solver's streaming hot path."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fps_tpu.core.store import rows_per_shard
    from fps_tpu.models.ials import (
        IALSConfig,
        IALSSolver,
        interaction_chunks,
    )
    from fps_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS
    from fps_tpu.utils.datasets import synthetic_implicit

    cfg = IALSConfig(num_users=NU, num_items=NI, rank=RANK)
    solver = IALSSolver(mesh, cfg)
    solver.init(jax.random.key(0))
    data = synthetic_implicit(NU, NI, 2000, seed=3)
    chunk = next(iter(interaction_chunks(
        data, num_workers=num_workers_of(mesh), local_batch=LOCAL_BATCH,
        steps_per_chunk=STEPS, seed=11)))
    sharding = NamedSharding(mesh, P(None, (DATA_AXIS, SHARD_AXIS)))
    dev = {
        "solve_ids": jax.device_put(np.asarray(chunk["user"]), sharding),
        "fixed_ids": jax.device_put(np.asarray(chunk["item"]), sharding),
        "rating": jax.device_put(np.asarray(chunk["rating"]), sharding),
        "weight": jax.device_put(np.asarray(chunk["weight"]), sharding),
    }
    rps = rows_per_shard(cfg.num_users, solver.num_shards)
    A = solver._zeros_acc(rps * solver.num_shards, RANK * RANK)
    b = solver._zeros_acc(rps * solver.num_shards, RANK)
    acc = solver._accumulate_fn()
    from fps_tpu.models.ials import ITEM_TABLE

    return acc.lower(solver.store.tables[ITEM_TABLE], A, b, dev).as_text()


BUILDERS = {
    "mf": build_mf,
    "streaming_mf": build_streaming_mf,
    "mf_tiered": build_mf_tiered,
    "mf_tiered_gathered": build_mf_tiered_gathered,
    "mf_tiered_compact": build_mf_tiered_compact,
    "mf_retier": build_mf_retier,
    "mf_megastep": build_mf_megastep,
    "logreg": build_logreg,
    "w2v": build_w2v,
    "pa": build_pa,
    "ials": build_ials,
}

_TIERED_ROWS = ("mf_tiered", "mf_tiered_gathered", "mf_tiered_compact",
                "mf_retier", "mf_megastep")


def diff_budgets(old_doc: dict, measured: dict) -> list[str]:
    """UNPINNED budget regressions of ``measured`` (``{program:
    {"collective_count": n, "collective_bytes": b}}``) against a prior
    audit JSON (``--out`` format). A program regresses when its measured
    collective count or payload bytes GREW versus the old certificate
    AND the growth is not covered by the current pinned ``BUDGETS`` row
    — i.e. someone changed the data plane without re-pinning, which is
    exactly the silent drift this gate exists to catch. Deliberate,
    re-pinned growth is reported by the caller but passes. Programs
    absent from either side are skipped (new rows cannot regress)."""
    problems = []
    old = old_doc.get("audit_programs", {})
    for name in sorted(measured):
        o = old.get(name)
        if not o:
            continue
        # Certificate JSON (--out format) nests the census under
        # "collectives": {"count": n, "bytes": b}.
        oc = o.get("collectives", o)
        old_n = oc.get("count", oc.get("collective_count", 0))
        old_b = oc.get("bytes", oc.get("collective_bytes", 0))
        cur_n = measured[name]["collective_count"]
        cur_b = measured[name]["collective_bytes"]
        if cur_n <= old_n and cur_b <= old_b:
            continue
        pinned = BUDGETS.get(name)
        if (pinned is None
                or cur_n > pinned["max_collectives"]
                or cur_b > pinned["max_collective_bytes"]):
            problems.append(
                f"{name}: measured {cur_n} collectives / {cur_b}B vs "
                f"{old_n} / {old_b}B in the reference audit, and the "
                "growth is NOT covered by the pinned budget — re-pin "
                "BUDGETS (and the docs table) if the change is "
                "deliberate")
    return problems


def contract_for(name: str) -> ProgramContract:
    budget = BUDGETS[name]
    tiered = name in _TIERED_ROWS
    # H=32 head rows x RANK f32 (+1 mean-count column headroom is not
    # needed: MF folds are sum) — the smallest tiered head's byte size.
    hot_bytes = 32 * RANK * 4 if tiered else 0
    return ProgramContract(
        name=f"audit/{name}",
        max_collectives=budget["max_collectives"],
        max_collective_bytes=budget["max_collective_bytes"],
        per_kind_max=budget["per_kind_max"],
        # Counts are pinned EXACT (the docstring's "not
        # ceilings-with-slack"): a removed collective or a new kind
        # fails the audit until the budget is re-pinned.
        exact_collectives=True,
        donated_tables=True,
        max_float_bits=32,
        require_shard_psum=tiered,
        hot_reconcile_bytes=hot_bytes,
        shard_group_size=8 if tiered else None,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="certify the example workloads' compiled programs "
                    "(fps_tpu.analysis)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the certificate JSON here (default: "
                         "stdout only)")
    ap.add_argument("--only", default=None,
                    help="comma-separated workload subset "
                         f"(default: all of {', '.join(BUILDERS)})")
    ap.add_argument("--measure", action="store_true",
                    help="print measured profiles without enforcing "
                         "budgets (for re-pinning after a deliberate "
                         "program change)")
    ap.add_argument("--diff", default=None, metavar="OLD.json",
                    help="also diff the measured profiles against a "
                         "prior audit JSON (--out format) and FAIL on "
                         "any unpinned budget regression: a program "
                         "whose collective count/bytes grew vs OLD "
                         "without the BUDGETS row being re-pinned. "
                         "Deliberate re-pinned growth is reported but "
                         "passes — the diff is the review artifact")
    args = ap.parse_args(argv)

    names = (args.only.split(",") if args.only else list(BUILDERS))
    unknown = [n for n in names if n not in BUILDERS]
    if unknown:
        ap.error(f"unknown workload(s): {', '.join(unknown)}")

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    certs = {}
    for name in names:
        text = BUILDERS[name](mesh)
        if args.measure:
            contract = ProgramContract(name=f"measure/{name}")
        else:
            contract = contract_for(name)
        cert = certify(text, contract, program=name)
        certs[name] = cert
        mark = "OK " if cert.ok else "FAIL"
        print(f"[{mark}] {name}: {cert.collective_count} collectives, "
              f"{cert.collective_bytes} bytes "
              f"{json.dumps(cert.per_kind())}", file=sys.stderr)
        for v in cert.violations:
            print(f"       [{v.pass_name}] {v.summary}", file=sys.stderr)

    rerank_identical = None
    if "mf_retier" in names:
        # The adaptive tier's recompile-freedom contract: two different
        # re-ranks of the same (H, table) lower byte-identical programs.
        rerank_identical = rerank_byte_identity(mesh)
        mark = "OK " if rerank_identical else "FAIL"
        print(f"[{mark}] mf_retier: re-rank byte-identity "
              f"({'identical' if rerank_identical else 'programs DIFFER'}"
              " across disjoint hot id sets)", file=sys.stderr)

    megastep_k_ind = None
    if "mf_megastep" in names:
        # The megastep scaling contract: collective census identical as
        # K doubles — megastep collective cost is O(traffic), not O(K).
        megastep_k_ind = megastep_k_independence(mesh)
        mark = "OK " if megastep_k_ind else "FAIL"
        verdict = ("census identical" if megastep_k_ind
                   else "census DIFFERS")
        print(f"[{mark}] mf_megastep: K-independence ({verdict} across "
              "K=2 vs K=4)", file=sys.stderr)

    diff_problems = []
    if args.diff:
        with open(args.diff, encoding="utf-8") as f:
            old_doc = json.load(f)
        measured = {
            n: {"collective_count": c.collective_count,
                "collective_bytes": c.collective_bytes}
            for n, c in certs.items()
        }
        diff_problems = diff_budgets(old_doc, measured)
        for n in sorted(measured):
            o = old_doc.get("audit_programs", {}).get(n)
            if not o:
                continue
            oc = o.get("collectives", o)
            old_pair = (oc.get("count", 0), oc.get("bytes", 0))
            cur_pair = (measured[n]["collective_count"],
                        measured[n]["collective_bytes"])
            if old_pair != cur_pair:
                print(f"[DIFF] {n}: {old_pair[0]}/{old_pair[1]}B -> "
                      f"{cur_pair[0]}/{cur_pair[1]}B", file=sys.stderr)
        for p in diff_problems:
            print(f"[FAIL] diff: {p}", file=sys.stderr)

    ok = (all(c.ok for c in certs.values())
          and rerank_identical is not False
          and megastep_k_ind is not False
          and not diff_problems)
    doc = {
        "audit_programs": {n: c.to_json() for n, c in certs.items()},
        "rerank_byte_identical": rerank_identical,
        "megastep_k_independent": megastep_k_ind,
        "ok": ok,
        "mesh": {"shard": 8, "data": 1},
        "scale": {"nu": NU, "ni": NI, "rank": RANK, "nf": NF,
                  "vocab": VOCAB, "local_batch": LOCAL_BATCH,
                  "steps_per_chunk": STEPS},
    }
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        print(f"wrote {args.out}", file=sys.stderr)
    print(json.dumps({
        "audit": {n: {"ok": c.ok, "collectives": c.collective_count,
                      "bytes": c.collective_bytes}
                  for n, c in certs.items()},
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
