"""On-chip route shoot-out at the LOGREG shape (round-5 kill-or-win).

The logreg workload is the last one far from its fused floor: a 1M-row
scalar table, B = 16384 examples x 26 sparse slots = 425,984 gathered /
scattered rows per step, Zipf(0.9) ids. This tool measures every candidate
route for that traffic with the dedup-safe chained-scan harness
(cf. bench_scatter.py):

  a. XLA gather + scatter on the full stream (the shipped route).
  b. dim-1 v2 full-table kernels at R in {131k, 262k, 524k, 1M} -- the
     measured v2 crossover that DIM1_MAX_ROWS=100k (a v1-margin guess)
     must be replaced with.
  c. head-only dim-1 kernel over table[:H] on the FULL stream (ids >= H
     masked to -1), H in {16k, 64k, 128k} -- the head half of a head/tail
     split; cost scales with ceil(H/128), not ceil(R/128).
  d. XLA gather/scatter on REDUCED column counts (the tail half: after an
     ingest-side head partition, only the non-head columns still pay the
     per-row-transaction XLA path).

Run on the TPU:  PYTHONPATH="/root/repo:$PYTHONPATH" python tools/bench_logreg_routes.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fps_tpu.ops.pallas_kernels import (
    gather_rows_dim1_pallas,
    scatter_add_dim1_pallas,
)

T = 256
R_FULL = 1_000_000
B_EX, NNZ = 16_384, 26
B = B_EX * NNZ
ALPHA = 0.9


def timeit(fn, *args):
    r = fn(*args)
    np.asarray(jax.tree.leaves(r)[0]).ravel()[0]
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        r = fn(*args)
        np.asarray(jax.tree.leaves(r)[0]).ravel()[0]
        best = min(best, time.perf_counter() - t0)
    return best / T * 1e6


def xla_scatter(tab, ids, deltas):
    safe = jnp.where((ids >= 0) & (ids < tab.shape[0]), ids, tab.shape[0])
    return tab.at[safe].add(deltas, mode="drop")


def xla_gather(tab, ids):
    keep = (ids >= 0) & (ids < tab.shape[0])
    v = jnp.take(tab, jnp.where(keep, ids, 0), axis=0)
    return jnp.where(keep[:, None], v, 0.0)


def scan_scatter(op):
    @jax.jit
    def f(tab, ids, deltas):
        def body(t, x):
            i, d = x
            return op(t, i, d), None

        return lax.scan(body, tab, (ids, deltas))[0]

    return f


def scan_gather(op):
    @jax.jit
    def f(tab, ids, _deltas):
        def body(t, x):
            i, _d = x
            return t + 1e-12 * jnp.sum(op(t, i)), None

        return lax.scan(body, tab, (ids, _deltas))[0]

    return f


def make_ids(R, B, T_, alpha=ALPHA, seed=0):
    rng = np.random.default_rng(seed)
    pop = 1.0 / np.arange(1, R + 1) ** alpha
    pop /= pop.sum()
    cdf = np.cumsum(pop)
    return np.searchsorted(cdf, rng.random((T_, B))).astype(np.int32)


def stage_a():
    rng = np.random.default_rng(1)
    ids_np = make_ids(R_FULL, B, T)
    ids = jnp.asarray(ids_np)
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B, 1)), jnp.float32)
    tab = jnp.asarray(rng.normal(0, 0.1, (R_FULL, 1)), jnp.float32)
    uniq = len(np.unique(ids_np[0]))
    print(f"logreg shape: R={R_FULL} B={B} ({B_EX}x{NNZ}) zipf({ALPHA}) "
          f"dup frac {1 - uniq / B:.3f}", flush=True)
    for H in (16_384, 65_536, 131_072):
        frac = float(np.mean(ids_np[0] < H))
        print(f"  head coverage H={H}: {frac:.3f}", flush=True)

    us = timeit(scan_scatter(xla_scatter), tab, ids, deltas)
    print(f"a. xla scatter  R=1M B={B}: {us / 1e3:8.3f} ms", flush=True)
    us = timeit(scan_gather(xla_gather), tab, ids, deltas)
    print(f"a. xla gather   R=1M B={B}: {us / 1e3:8.3f} ms", flush=True)


def stage_b(rs):
    rng = np.random.default_rng(1)
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B, 1)), jnp.float32)
    for R in rs:
        t2 = jnp.asarray(rng.normal(0, 0.1, (R, 1)), jnp.float32)
        i2 = jnp.asarray(make_ids(R, B, T, seed=2))
        us_xs = timeit(scan_scatter(xla_scatter), t2, i2, deltas)
        us_ds = timeit(
            scan_scatter(lambda t, i, d: scatter_add_dim1_pallas(
                t, i, d, row_tile=512, batch_tile=8192)),
            t2, i2, deltas)
        us_xg = timeit(scan_gather(xla_gather), t2, i2, deltas)
        us_dg = timeit(scan_gather(gather_rows_dim1_pallas), t2, i2, deltas)
        print(f"b. R={R:8d}: scatter xla {us_xs / 1e3:7.3f} "
              f"dim1 {us_ds / 1e3:7.3f} | gather xla {us_xg / 1e3:7.3f} "
              f"dim1 {us_dg / 1e3:7.3f} ms", flush=True)


def stage_c():
    rng = np.random.default_rng(1)
    ids = jnp.asarray(make_ids(R_FULL, B, T))
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B, 1)), jnp.float32)
    tab = jnp.asarray(rng.normal(0, 0.1, (R_FULL, 1)), jnp.float32)
    for H in (16_384, 65_536, 131_072):
        def head_scatter(t, i, d, H=H):
            im = jnp.where(i < H, i, -1)
            head = scatter_add_dim1_pallas(
                t[:H], im, d, row_tile=512, batch_tile=8192)
            return lax.dynamic_update_slice_in_dim(t, head, 0, axis=0)

        def head_gather(t, i, H=H):
            im = jnp.where(i < H, i, -1)
            return gather_rows_dim1_pallas(t[:H], im)

        us_s = timeit(scan_scatter(head_scatter), tab, ids, deltas)
        us_g = timeit(scan_gather(head_gather), tab, ids, deltas)
        print(f"c. head H={H:7d} full-B masked: scatter {us_s / 1e3:7.3f} "
              f"gather {us_g / 1e3:7.3f} ms", flush=True)


def stage_d():
    rng = np.random.default_rng(1)
    ids_np = make_ids(R_FULL, B, T)
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B, 1)), jnp.float32)
    tab = jnp.asarray(rng.normal(0, 0.1, (R_FULL, 1)), jnp.float32)
    for cols in (4, 8, 12, 16):
        Bt = B_EX * cols
        it = jnp.asarray(ids_np[:, :Bt])
        dt = deltas[:, :Bt]
        us_s = timeit(scan_scatter(xla_scatter), tab, it, dt)
        us_g = timeit(scan_gather(xla_gather), tab, it, dt)
        print(f"d. xla tail cols={cols:2d} (B={Bt:6d}): "
              f"scatter {us_s / 1e3:7.3f} gather {us_g / 1e3:7.3f} ms",
              flush=True)


def stage_pa_head():
    """Head-prefix deepening ceiling at the PA shape (round-5 kill-or-win
    on the head-prefix machinery): if the head-only kernel's cost on the
    full stream is already close to the full-table dim-1 kernel's, the
    maximum win ANY guaranteed-prefix scheme (per-dataset q, per-batch q,
    plan-level budgets) can deliver is their difference — the kernels are
    STREAM-bound at small rp, not head-size-bound."""
    R, B_pa = 47_236, 16_384 * 64
    H = 2_048
    rng = np.random.default_rng(3)
    tab = jnp.asarray(rng.normal(0, 0.1, (R, 1)), jnp.float32)
    ids = jnp.asarray(make_ids(R, B_pa, T, seed=4))
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B_pa, 1)), jnp.float32)

    us = timeit(scan_scatter(lambda t, i, d: scatter_add_dim1_pallas(
        t, i, d, row_tile=512, batch_tile=8192)), tab, ids, deltas)
    print(f"pa. full dim1 scatter R={R}: {us / 1e3:7.3f} ms", flush=True)
    us = timeit(scan_gather(gather_rows_dim1_pallas), tab, ids, deltas)
    print(f"pa. full dim1 gather  R={R}: {us / 1e3:7.3f} ms", flush=True)

    def head_scatter(t, i, d):
        im = jnp.where(i < H, i, -1)
        head = scatter_add_dim1_pallas(t[:H], im, d, row_tile=512,
                                       batch_tile=8192)
        return lax.dynamic_update_slice_in_dim(t, head, 0, axis=0)

    def head_gather(t, i):
        im = jnp.where(i < H, i, -1)
        return gather_rows_dim1_pallas(t[:H], im)

    us = timeit(scan_scatter(head_scatter), tab, ids, deltas)
    print(f"pa. head-only scatter H={H}: {us / 1e3:7.3f} ms", flush=True)
    us = timeit(scan_gather(head_gather), tab, ids, deltas)
    print(f"pa. head-only gather  H={H}: {us / 1e3:7.3f} ms", flush=True)


def stage_tune():
    """Batch-tile tuning shot for the head kernel at the logreg shape —
    is the stream-bound floor a tile-overhead artifact?"""
    rng = np.random.default_rng(1)
    ids = jnp.asarray(make_ids(R_FULL, B, T))
    deltas = jnp.asarray(rng.normal(0, 1e-4, (T, B, 1)), jnp.float32)
    tab = jnp.asarray(rng.normal(0, 0.1, (R_FULL, 1)), jnp.float32)
    H = 65_536
    for bt in (8_192, 16_384, 32_768):
        def head_scatter(t, i, d, bt=bt):
            im = jnp.where(i < H, i, -1)
            head = scatter_add_dim1_pallas(t[:H], im, d, row_tile=512,
                                           batch_tile=bt)
            return lax.dynamic_update_slice_in_dim(t, head, 0, axis=0)

        us = timeit(scan_scatter(head_scatter), tab, ids, deltas)
        print(f"t. head H={H} batch_tile={bt:6d}: scatter {us / 1e3:7.3f} ms",
              flush=True)


STAGES = {
    "a": stage_a,
    "b1": lambda: stage_b([131_072, 262_144]),
    "b2": lambda: stage_b([524_288, 1_000_000]),
    "c": stage_c,
    "d": stage_d,
    "pa_head": stage_pa_head,
    "tune": stage_tune,
}


if __name__ == "__main__":
    import sys

    for name in (sys.argv[1:] or list(STAGES)):
        STAGES[name]()
