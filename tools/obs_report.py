"""Render an ``--obs-dir`` telemetry directory into one run digest.

Reads every per-process ``events-p*.jsonl`` and ``journal-p*.jsonl``
under the directory (multi-host runs write one pair per process; they
join on ``run_id``) and prints a single JSON digest:

* run identity — run ids, config digest, processes, wall-clock span;
* progress — chunks/epochs/steps/examples, quarantined indices;
* **per-phase timings** — total/mean/max seconds per host phase
  (prefetch / ingest / place / dispatch / host_sync / checkpoint /
  callback — ``prefetch`` is the background pipeline's worker-thread
  time, i.e. host work OVERLAPPED with the phases beside it);
* **host pipeline** — chunks prefetched and the queue-depth gauge's
  last/max (the gauge samples after every put/get, so with any traffic
  the max is >= 1; a max STUCK at 1 means the driver drained each chunk
  the moment it landed — assembly is the bottleneck, a deeper queue
  won't help — while a max at the configured depth means the worker
  kept the buffer full: the device-bound good case);
* **per-table health totals** — nonfinite/norm/masked row counts;
* **hot tier** — two-tier storage hit rate (rows served by the
  replicated hot head over total pulled rows) and the last/max
  pending-delta gauge (parameter-plane staleness;
  `docs/performance.md` "Two-tier storage");
* **tiering** — adaptive-tiering activity (`fps_tpu.tiering`):
  re-ranks applied, promoted/demoted row totals, and the churn gauge
  (`docs/performance.md` "Adaptive tiering");
* **serve** — read-path tier (`fps_tpu.serve`): requests/rows served,
  exact p50/p99 request latency, the served step + step lag + the
  write→servable freshness SLO gauges, forward/backward swap counts, and
  rejected (CRC-failing) snapshot candidates (`docs/serving.md`);
* **incidents** — rollbacks, watchdog stalls (+ recoveries), guard
  escalations, health aborts, checkpoint fallbacks, checkpoint saves —
  plus, from the supervisor journal, `deadline_abort` events whose
  `stall_kind` is `source_stall` (a stalled `prefetch`-phase heartbeat:
  the SOURCE wedged while the driver waited on it, a distinct incident
  from a wedged driver) summarized as `source_stalls`;
* **analysis** — program-contract certification (`Trainer(audit=...)`,
  `fps_tpu.analysis`): programs certified clean, contract violations
  found at compile time, and each `analysis.contract_violation` event
  verbatim under `incidents` (`docs/analysis.md`).

Pure host tool: no jax import, safe to run on a login node against a
live or finished run directory.

Fleet mode (``--fleet DIR [DIR...]``) aggregates N per-host obs dirs
through ``fps_tpu/obs/fleet.py`` (loaded by file path, still jax-free):
windowed rollups (throughput, tiering hit rate, cold-route certification
rate, write→servable freshness, restart/fence counts) plus SLO burn-rate
evaluation, with each host's standard digest attached. ``--json`` pins
the machine-readable contract: compact strict JSON, non-finite floats
scrubbed to null, and a versioned ``schema`` field.

Usage:
  python tools/obs_report.py RUN_DIR [--pretty|--json]
  python tools/obs_report.py --fleet HOST_DIR... [--window-s S] [--json]
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import math
import os
import sys

# Event types surfaced verbatim (bounded lists) in the digest. The last
# five come from the run SUPERVISOR's journal (journal-supervisor.jsonl,
# written by tools/supervise.py into its --state-dir) — point this tool
# at a dir holding both and the digest narrates the whole supervised run.
_INCIDENT_EVENTS = (
    "rollback",
    "preset_skip",
    "stall",
    "stall_recovered",
    "guard_escalated",
    "health_abort",
    "poisoned_stream_abort",
    "checkpoint_fallback",
    "checkpoint_fenced",
    "checkpoint_resplit",
    "deadline_abort",
    "supervisor_restart",
    "attempt_first_signal",
    "chunk_quarantined",
    "heartbeat_rejected",
    "supervisor_give_up",
    "supervised_run_end",
    # Time-to-recovered SLO (ISSUE 20): synthesized by this tool when a
    # paired attempt_end -> attempt_first_signal gap exceeds the
    # --recovery-slo-s bound; also folded verbatim if a journal carries
    # one (tools/chaos_sweep.py keeps its own per-scenario bounds).
    "recovery_slo_breach",
    "analysis.contract_violation",
    # Runtime budget-drift detection (fps_tpu.obs.drift): measured
    # collective traffic departed from the AUDIT_r*.json pinned shape.
    "budget_drift",
    # Hostile-filesystem degradation (fps_tpu.core.retry + the async
    # writer's degraded mode): skipped publishes, aborted compactions,
    # and the backlog-drain marker after storage recovery.
    "checkpoint_degraded",
    "checkpoint_backlog_drained",
    "compaction_aborted",
    "leader_io_error",
    # Hostile-network survival (fps_tpu.serve.wire / serve.fleet): a
    # silent reader became an incident the supervisor can act on, and
    # torn frames were rejected loudly instead of decoded.
    "reader_wedged",
    "reader_restarted",
    "wire_torn_frame",
    # Pod coordination (journal-pod.jsonl, written into the pod dir by
    # the lease-holding member — point this tool at the pod dir and the
    # digest narrates the whole pod run).
    "lease_seized",
    "member_failed",
    "member_evicted",
    "member_readmitted",
    "pod_restart",
    "pod_quarantine",
    "pod_give_up",
    "pod_shutdown",
)

# Digest keys that must always be present (the smoke test asserts these —
# consumers can rely on the shape even for an empty run). The digest is
# versioned: DIGEST_SCHEMA_VERSION bumps whenever an existing field
# changes meaning (new fields may appear without a bump) — `--json`
# consumers (CI, fps_tpu/obs/fleet.py) key on it instead of scraping.
DIGEST_SCHEMA_VERSION = 1
REQUIRED_FIELDS = (
    "schema", "obs_dir", "run_ids", "processes", "chunks", "epochs",
    "steps", "examples", "phase_seconds", "health", "incidents",
    "checkpoint", "checkpoint_saves", "quarantined", "wall_span_s",
    "prefetch",
    "hot_tier", "megastep", "tiering", "source_stalls", "analysis",
    "serve", "pod", "net", "recovery",
)


def _seconds_stats(samples: list) -> dict:
    """Summary of one histogram's raw samples (n/total/mean/p99/max) —
    the checkpoint dump/capture split in the digest."""
    if not samples:
        return {"n": 0, "total_s": None, "mean_s": None,
                "p99_s": None, "max_s": None}
    s = sorted(samples)
    return {"n": len(s),
            "total_s": round(sum(s), 6),
            "mean_s": round(sum(s) / len(s), 6),
            "p99_s": round(_quantile(s, 0.99), 6),
            "max_s": round(s[-1], 6)}


def _quantile(sorted_vals: list, q: float):
    """Exact quantile over a sorted sample list (the ReadServer
    reservoir's index formula, so the two reports agree)."""
    n = len(sorted_vals)
    if not n:
        return None
    return sorted_vals[min(n - 1, int(q * (n - 1) + 0.5))]


def _read_jsonl(path: str):
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                # A torn final line (live run, killed writer) is expected;
                # everything before it is still a valid prefix.
                return


def render_digest(obs_dir: str, *, recovery_slo_s: float | None = None) -> dict:
    """Digest dict from an obs directory (see module docstring).

    ``recovery_slo_s`` enforces a time-to-recovered bound: every paired
    restart whose kill→first-signal gap exceeds it becomes a
    ``recovery_slo_breach`` incident, and the ``recovery`` section gains
    ``slo_s`` / ``breaches`` fields. ``None`` (default) reports without
    judging."""
    event_files = sorted(glob.glob(os.path.join(obs_dir, "events-p*.jsonl")))
    # journal-* (not journal-p*): also picks up journal-supervisor.jsonl
    # when the supervisor's --state-dir is (or is joined into) this dir.
    journal_files = sorted(
        glob.glob(os.path.join(obs_dir, "journal-*.jsonl")))
    if not event_files and not journal_files:
        raise FileNotFoundError(
            f"no events-p*.jsonl / journal-p*.jsonl under {obs_dir!r} — "
            "was the run started with --obs-dir (fps_tpu.obs.open_run)?"
        )

    counters: dict[str, float] = collections.defaultdict(float)
    gauges: dict[str, dict] = {}  # name -> {"last": v, "max": v}
    serve_latency: list[float] = []  # serve.request_seconds samples
    # Raw-speed split (ISSUE 20): what a save costs the TRAINING thread
    # (dump = enqueue) vs what the WRITER pays off-thread (capture).
    ckpt_seconds: dict[str, list[float]] = {
        "checkpoint.dump_seconds": [],
        "checkpoint.capture_seconds": [],
    }
    swap_directions: dict[str, int] = collections.defaultdict(int)
    phases: dict[str, dict] = {}
    health: dict[str, dict] = {}
    incidents: dict[str, list] = {k: [] for k in _INCIDENT_EVENTS}
    run_ids: set[str] = set()
    processes: set[int] = set()
    config_digests: set[str] = set()
    quarantined: list[int] = []
    t_min = t_max = None

    def see_time(t):
        nonlocal t_min, t_max
        if t is None:
            return
        t_min = t if t_min is None else min(t_min, t)
        t_max = t if t_max is None else max(t_max, t)

    # Events appear in BOTH the event log and the journal (one Recorder
    # emission fans out to every sink) — and after a crash the journal
    # (flushed per record) can hold incidents the event log's buffered
    # tail lost. Fold both sources, deduping on exact record content.
    seen_events: set[str] = set()
    # Supervisor recovery pairing (mirrors
    # fps_tpu.supervise.supervisor.recovery_times — this tool stays
    # import-free): attempt -> timestamp for each side of the pair.
    attempt_firsts: dict[int, float] = {}
    attempt_ends: dict[int, float] = {}

    def fold_event(rec):
        key = json.dumps(rec, sort_keys=True, default=str)
        if key in seen_events:
            return
        seen_events.add(key)
        et = rec.get("event")
        if et in incidents:
            incidents[et].append(
                {k: v for k, v in rec.items() if k != "kind"})
        if et in ("chunk", "epoch") and rec.get("quarantined"):
            quarantined.append(rec.get("index"))
        if (et in ("attempt_first_signal", "attempt_end")
                and rec.get("t") is not None
                and rec.get("attempt") is not None):
            try:
                a, t = int(rec["attempt"]), float(rec["t"])
            except (TypeError, ValueError):
                return
            if et == "attempt_end":
                attempt_ends[a] = max(attempt_ends.get(a, t), t)
            else:
                attempt_firsts.setdefault(a, t)  # first signal wins

    for rec in (r for p in event_files for r in _read_jsonl(p)):
        see_time(rec.get("t"))
        if rec.get("run_id"):
            run_ids.add(rec["run_id"])
        kind = rec.get("kind")
        if kind == "metric":
            name = rec.get("name", "")
            labels = rec.get("labels") or {}
            raw = rec.get("value", 0.0)
            # A null value is the strict-JSON spelling of a non-finite
            # sample (the serving watcher's orphaned-snapshot gauge).
            v = math.nan if raw is None else float(raw)
            if name == "driver.phase_seconds":
                ph = phases.setdefault(
                    labels.get("phase", "?"),
                    {"total_s": 0.0, "n": 0, "max_s": 0.0},
                )
                ph["total_s"] += v
                ph["n"] += 1
                ph["max_s"] = max(ph["max_s"], v)
            elif name.startswith("health.") and name.endswith("_rows"):
                table = labels.get("table", "?")
                tier = name[len("health."):-len("_rows")]
                health.setdefault(
                    table, {"nonfinite": 0, "norm": 0, "masked": 0}
                )[tier] += int(v)
            elif name == "serve.request_seconds":
                serve_latency.append(v)
            elif name in ckpt_seconds:
                ckpt_seconds[name].append(v)
            elif rec.get("mtype") == "counter":
                if name == "serve.swaps":
                    swap_directions[labels.get("direction", "?")] += int(v)
                counters[name] += v
            elif rec.get("mtype") == "gauge":
                # "last" by record TIMESTAMP, not file-iteration order —
                # a multi-process dir's files fold in name order.
                t = float(rec.get("t") or 0.0)
                g = gauges.setdefault(
                    name, {"last": v, "last_t": t, "max": v})
                if t >= g["last_t"]:
                    g["last"], g["last_t"] = v, t
                # Non-finite samples mark outages; they must not poison
                # the max (which would turn order-dependently NaN).
                if math.isfinite(v):
                    g["max"] = (v if not math.isfinite(g["max"])
                                else max(g["max"], v))
        elif kind == "event":
            fold_event(rec)

    # Journals: run identity + anything the event files missed (a process
    # may have died before its event sink flushed; journals flush per
    # record, so their incident trail survives a SIGKILL).
    started: set[str] = set()
    ended: set[str] = set()
    for rec in (r for p in journal_files for r in _read_jsonl(p)):
        see_time(rec.get("t"))
        if rec.get("run_id"):
            run_ids.add(rec["run_id"])
        fold_event(rec)
        if rec.get("event") == "run_start":
            started.add(rec.get("run_id"))
            if "process" in rec:
                processes.add(int(rec["process"]))
            if rec.get("config_digest"):
                config_digests.add(rec["config_digest"])
        elif rec.get("event") == "run_end":
            ended.add(rec.get("run_id"))

    for ph in phases.values():
        ph["total_s"] = round(ph["total_s"], 6)
        ph["mean_s"] = round(ph["total_s"] / max(ph["n"], 1), 6)
        ph["max_s"] = round(ph["max_s"], 6)

    # time_to_recovered_s per restart: the gap from an attempt's end to
    # the NEXT attempt's first liveness signal (kill -> first
    # post-restart dispatch) — the MTTR figure the chaos sweep records.
    recovery_times: list[float] = []
    for a in sorted(attempt_firsts):
        t_first = attempt_firsts[a]
        prior = [te for ae, te in attempt_ends.items()
                 if ae < a and te <= t_first]
        if prior:
            recovery_times.append(round(t_first - max(prior), 3))

    # Time-to-recovered SLO enforcement: every paired restart slower
    # than the bound becomes an incident, synthesized here next to any
    # recovery_slo_breach events a journal already carried.
    if recovery_slo_s is not None and recovery_slo_s > 0:
        for i, t in enumerate(recovery_times):
            if t > recovery_slo_s:
                incidents["recovery_slo_breach"].append({
                    "event": "recovery_slo_breach", "restart": i,
                    "time_to_recovered_s": t,
                    "slo_s": round(float(recovery_slo_s), 3),
                })

    digest = {
        "schema": DIGEST_SCHEMA_VERSION,
        "obs_dir": os.path.abspath(obs_dir),
        "run_ids": sorted(run_ids),
        "config_digests": sorted(config_digests),
        "processes": sorted(processes) or [0],
        "chunks": int(counters.get("driver.chunks", 0)),
        "epochs": int(counters.get("driver.epochs", 0)),
        "steps": int(counters.get("driver.steps", 0)),
        "examples": counters.get("driver.examples", 0.0),
        "phase_seconds": dict(sorted(phases.items())),
        # Host pipeline (fps_tpu.core.prefetch): the 'prefetch' entry in
        # phase_seconds is this worker's time, overlapped with the rest.
        "prefetch": {
            "chunks": int(counters.get("prefetch.chunks", 0)),
            "queue_depth_last": gauges.get(
                "prefetch.queue_depth", {}).get("last"),
            "queue_depth_max": gauges.get(
                "prefetch.queue_depth", {}).get("max"),
            # Adaptive depth (ISSUE 20): each +1 raise the stall-driven
            # sizing applied. 0 with a pinned max at the starting depth
            # means the fixed depth was already enough (or adaptation
            # was off); nonzero narrates how far the buffer grew.
            "depth_adjustments": int(
                counters.get("prefetch.depth_adjustments", 0)),
        },
        # Two-tier storage (labels fold across tables; the per-table
        # split lives in the raw event files if needed).
        "hot_tier": {
            "hot_rows": int(counters.get("hot_tier.hot_rows", 0)),
            "pulled_rows": int(counters.get("hot_tier.pulled_rows", 0)),
            "hit_rate": (
                round(counters["hot_tier.hot_rows"]
                      / counters["hot_tier.pulled_rows"], 4)
                if counters.get("hot_tier.pulled_rows") else None),
            "pending_delta_last": gauges.get(
                "hot_tier.pending_delta", {}).get("last"),
            "pending_delta_max": gauges.get(
                "hot_tier.pending_delta", {}).get("max"),
            # Payload-proportional cold routing (TableSpec.cold_budget):
            # per-chunk program selection + the device-side drop net
            # (nonzero cold_dropped = a certifier bug, not load).
            "compact_chunks": int(
                counters.get("cold_route.compact_chunks", 0)),
            "overflow_chunks": int(
                counters.get("cold_route.overflow_chunks", 0)),
            "cold_dropped": int(
                counters.get("hot_tier.cold_dropped", 0)),
        },
        # Device-resident megastep (fps_tpu.core.megastep): K-chunk
        # fused dispatches with in-graph boundaries, plus the
        # device-side overflow vote's window-level program selection.
        "megastep": {
            "windows": int(counters.get("megastep.windows", 0)),
            "chunks_per_dispatch": gauges.get(
                "megastep.chunks_per_dispatch", {}).get("last"),
            # Auto-K calibration (ISSUE 20): the K chosen by
            # chunks_per_dispatch="auto" (null when K was explicit).
            "auto_k": gauges.get("megastep.auto_k", {}).get("last"),
            "vote_compact_windows": int(
                counters.get("cold_route.vote_compact_windows", 0)),
            "vote_overflow_windows": int(
                counters.get("cold_route.vote_overflow_windows", 0)),
        },
        # Adaptive tiering (fps_tpu.tiering): online hot-set re-ranking
        # + auto-planner activity — re-rank/promotion totals (labels
        # fold across tables) and the churn gauge's last/max.
        "tiering": {
            "re_ranks": int(counters.get("tiering.re_ranks", 0)),
            "promoted_rows": int(
                counters.get("tiering.promoted_rows", 0)),
            "demoted_rows": int(
                counters.get("tiering.demoted_rows", 0)),
            "churn_last": gauges.get("tiering.churn", {}).get("last"),
            "churn_max": gauges.get("tiering.churn", {}).get("max"),
        },
        # Program contract auditor (fps_tpu.analysis): certification
        # totals; the per-violation events ride incidents verbatim.
        "analysis": {
            "certified_programs": int(
                counters.get("analysis.certified_programs", 0)),
            "contract_violations": int(
                counters.get("analysis.contract_violations", 0)),
            # Runtime budget drift (fps_tpu.obs.drift): the gauge's
            # last/max measured-vs-pinned byte ratio and how many
            # departure incidents fired (events ride incidents verbatim).
            "budget_drift_ratio_last": gauges.get(
                "analysis.budget_drift", {}).get("last"),
            "budget_drift_ratio_max": gauges.get(
                "analysis.budget_drift", {}).get("max"),
            "budget_drift_incidents": len(
                incidents.get("budget_drift", ())),
        },
        # Read-path serving tier (fps_tpu.serve; docs/serving.md): query
        # volume, exact request-latency quantiles over every recorded
        # sample, the freshness gauges (served step, step lag, the
        # write->servable SLO), and the swap trail — backward swaps mean
        # the trainer quarantined a served snapshot and readers rolled
        # back with it.
        "serve": {
            "requests": int(counters.get("serve.requests", 0)),
            "rows": int(counters.get("serve.rows", 0)),
            "latency_p50_s": _quantile(sorted(serve_latency), 0.5),
            "latency_p99_s": _quantile(sorted(serve_latency), 0.99),
            "snapshot_step_last": gauges.get(
                "serve.snapshot_step", {}).get("last"),
            "snapshot_lag_steps_last": gauges.get(
                "serve.snapshot_lag_steps", {}).get("last"),
            "write_to_servable_s_last": gauges.get(
                "serve.write_to_servable_s", {}).get("last"),
            "write_to_servable_s_max": gauges.get(
                "serve.write_to_servable_s", {}).get("max"),
            "swaps": dict(sorted(swap_directions.items())),
            "rejected_snapshots": int(
                counters.get("serve.rejected_snapshots", 0)),
            # Delta-snapshot chains + the step-fenced serving fleet
            # (ISSUE 14): publish-bytes proportionality on the write
            # side, the shared fence's last published step on the read
            # side (forward-monotone within a fencing epoch).
            "delta": {
                "delta_publishes": int(
                    counters.get("checkpoint.delta_publishes", 0)),
                "delta_bytes": int(
                    counters.get("checkpoint.delta_bytes", 0)),
                "compactions": int(
                    counters.get("checkpoint.compactions", 0)),
                "full_bytes_last": gauges.get(
                    "checkpoint.bytes", {}).get("last"),
            },
            "fence_step_last": gauges.get(
                "serve.fence_step", {}).get("last"),
            "fence_step_max": gauges.get(
                "serve.fence_step", {}).get("max"),
        },
        # Pod coordination (fps_tpu.supervise.pod): the control-plane
        # narrative folded from journal-pod.jsonl — lease churn, the
        # pod-wide decisions, membership changes, and the child-side
        # fence refusals / elastic re-splits from the run journals.
        "pod": {
            "lease_seizures": len(incidents.get("lease_seized", ())),
            "member_failures": len(incidents.get("member_failed", ())),
            "restarts": len(incidents.get("pod_restart", ())),
            "evictions": len(incidents.get("member_evicted", ())),
            "readmissions": len(incidents.get("member_readmitted", ())),
            "quarantines": len(incidents.get("pod_quarantine", ())),
            # The counter and the event fire together from _check_fence;
            # max() so a dir holding both sources doesn't double-count.
            "fenced_publishes": max(
                int(counters.get("checkpoint.fenced_publishes", 0)),
                len(incidents.get("checkpoint_fenced", ()))),
            "resplit_restores": int(
                counters.get("checkpoint.resplits", 0)),
            "heartbeat_rejected": len(
                incidents.get("heartbeat_rejected", ())),
            "completed": bool(incidents.get("pod_shutdown")),
            "gave_up": bool(incidents.get("pod_give_up")),
        },
        # Supervisor deadline aborts whose last heartbeat was a stalled
        # 'prefetch'-phase beat: the SOURCE wedged, not the driver.
        "source_stalls": sum(
            1 for e in incidents.get("deadline_abort", ())
            if e.get("stall_kind") == "source_stall"),
        # Supervised-restart MTTR evidence (attempt_first_signal events
        # ride incidents verbatim; this is their paired summary).
        "recovery": {
            "count": len(recovery_times),
            "times_s": recovery_times,
            "mean_s": (round(sum(recovery_times) / len(recovery_times), 3)
                       if recovery_times else None),
            "max_s": (round(max(recovery_times), 3)
                      if recovery_times else None),
            # Only meaningful when --recovery-slo-s was given: the bound
            # and how many paired restarts broke it (each breach also
            # rides incidents verbatim).
            "slo_s": (round(float(recovery_slo_s), 3)
                      if recovery_slo_s else None),
            "breaches": len(incidents.get("recovery_slo_breach", ())),
        },
        "health": dict(sorted(health.items())),
        "poisoned_chunks": int(counters.get("health.poisoned_chunks", 0)),
        "incidents": {k: v for k, v in incidents.items() if v},
        # Hostile-filesystem survival (fps_tpu.core.retry + degraded-
        # mode storage): retry traffic, skipped publishes + backlog
        # (recency spent to keep training alive through a brownout),
        # and read-plane polls that degraded to last-good state.
        "storage": {
            "retries": int(counters.get("storage.retries", 0)),
            "degraded_publishes": int(
                counters.get("storage.degraded_publishes", 0)),
            "publish_backlog_last": gauges.get(
                "checkpoint.publish_backlog", {}).get("last"),
            "publish_backlog_max": gauges.get(
                "checkpoint.publish_backlog", {}).get("max"),
            "poll_errors": int(counters.get("storage.poll_errors", 0)),
            "sidecar_skips": int(
                counters.get("storage.sidecar_skips", 0)),
            "compaction_aborts": int(
                counters.get("storage.compaction_aborts", 0)),
        },
        # Hostile-network survival (fps_tpu.serve.wire / serve.net;
        # docs/resilience.md "Hostile network"): retry/reconnect
        # traffic, frames the length/CRC gates rejected, requests shed
        # by admission control or abandoned on a dead deadline, and
        # per-reader liveness — a wedged reader is a reader_wedged
        # incident here, never a silent zero (BENCH_r14).
        "net": {
            "retries": int(counters.get("net.retries", 0)),
            "reconnects": int(counters.get("net.reconnects", 0)),
            "torn_frames": int(counters.get("net.torn_frames", 0)),
            "shed_requests": int(
                counters.get("net.shed_requests", 0)),
            "deadline_exceeded": int(
                counters.get("net.deadline_exceeded", 0)),
            "reader_heartbeat_age_s_last": gauges.get(
                "serve.reader_heartbeat_age_s", {}).get("last"),
            "reader_heartbeat_age_s_max": gauges.get(
                "serve.reader_heartbeat_age_s", {}).get("max"),
            "reader_wedged_incidents": len(
                incidents.get("reader_wedged", ())),
        },
        # Raw-speed split (ISSUE 20): dump_seconds is what a save costs
        # the TRAINING thread (deferred captures make this the enqueue
        # cost only); capture_seconds is the device->host materialization
        # the WRITER pays off-thread. dump collapsing toward zero while
        # capture stays flat is the off-thread capture working.
        "checkpoint": {
            "dump": _seconds_stats(
                ckpt_seconds["checkpoint.dump_seconds"]),
            "capture": _seconds_stats(
                ckpt_seconds["checkpoint.capture_seconds"]),
        },
        "checkpoint_saves": int(counters.get("checkpoint.saves", 0)),
        # Async writer: enqueued > saved means a write was still in
        # flight at the last flush — saves are the TRUE durability points.
        "checkpoint_enqueues": int(counters.get("checkpoint.enqueues", 0)),
        "checkpoint_fallbacks": int(
            counters.get("checkpoint.fallbacks", 0)),
        "watchdog_stalls": int(counters.get("watchdog.stalls", 0)),
        "rollbacks": int(counters.get("rollback.quarantined", 0)),
        "preset_skips": int(counters.get("rollback.preset_skipped", 0)),
        "quarantined": sorted(q for q in quarantined if q is not None),
        # Complete only when EVERY started run ended — a dir holding a
        # finished first run and a killed second run is not complete.
        "run_complete": bool(started) and started <= ended,
        # Append-mode sinks stack re-runs into the same files; counts and
        # phases above are then aggregates over all of them. Surfaced so
        # consumers don't mistake a 2-run dir for one double-sized run.
        "aggregated_runs": max(len(run_ids), 1),
        "wall_span_s": (round(t_max - t_min, 3)
                        if t_min is not None else None),
    }
    missing = [k for k in REQUIRED_FIELDS if k not in digest]
    assert not missing, f"digest contract violated: missing {missing}"
    return digest


# Strict JSON out: a NaN gauge (serving outage marker) prints as
# null, never the Python-only NaN token — the digest's consumers
# include jq and non-Python tooling. Mirrors
# fps_tpu.obs.sinks.scrub_nonfinite (this tool stays import-free).
def scrub(x):
    if isinstance(x, dict):
        return {k: scrub(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [scrub(v) for v in x]
    if isinstance(x, float) and not math.isfinite(x):
        return None
    return x


def digest_json(obs_dir: str, *, recovery_slo_s: float | None = None) -> dict:
    """The `--json` payload: the digest with every non-finite float
    scrubbed to null — the stable machine-readable schema
    (``DIGEST_SCHEMA_VERSION``) CI and ``fps_tpu/obs/fleet.py`` consume
    without scraping text."""
    return scrub(render_digest(obs_dir, recovery_slo_s=recovery_slo_s))


def _load_fleet():
    """fps_tpu/obs/fleet.py by FILE PATH (the tools/supervise.py
    pattern): importing the package would drag fps_tpu/__init__ — and
    with it jax — into a tool whose contract is running on login nodes."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "fps_tpu", "obs", "fleet.py")
    spec = importlib.util.spec_from_file_location("_fps_obs_fleet", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclasses resolve via sys.modules
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render fps_tpu --obs-dir telemetry into a run "
                    "digest (one dir) or a fleet rollup + SLO burn "
                    "report (--fleet, N dirs)")
    ap.add_argument("obs_dirs", nargs="+", metavar="OBS_DIR",
                    help="directory written by --obs-dir / "
                         "fps_tpu.obs.open_run (with --fleet: one per "
                         "host/member)")
    ap.add_argument("--fleet", action="store_true",
                    help="aggregate the dirs as one fleet: windowed "
                         "rollups (throughput, tiering hit rate, "
                         "cold-route certification rate, freshness, "
                         "restart/fence counts) + SLO burn rates "
                         "(fps_tpu.obs.fleet), with each host's "
                         "standard digest attached")
    ap.add_argument("--window-s", type=float, default=None,
                    help="fleet rollup window width in seconds "
                         "(default: span/6)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: compact strict JSON "
                         "with non-finite floats scrubbed to null and a "
                         "versioned 'schema' field — the contract for "
                         "CI and fleet consumers (the default output is "
                         "the same JSON; --json pins it and refuses "
                         "--pretty)")
    ap.add_argument("--pretty", action="store_true",
                    help="indent the JSON for humans")
    ap.add_argument("--recovery-slo-s", type=float, default=None,
                    metavar="S",
                    help="time-to-recovered bound: every paired restart "
                         "whose kill->first-signal gap exceeds S seconds "
                         "becomes a recovery_slo_breach incident and the "
                         "recovery section reports slo_s/breaches "
                         "(default: report without judging)")
    args = ap.parse_args(argv)
    if args.json and args.pretty:
        ap.error("--json is the compact machine form; drop --pretty")
    if not args.fleet and len(args.obs_dirs) > 1:
        ap.error("multiple OBS_DIRs need --fleet")

    if args.fleet:
        fleet = _load_fleet()
        def _digest_or_none(d):
            try:
                return render_digest(
                    d, recovery_slo_s=args.recovery_slo_s)
            except FileNotFoundError:
                return None

        out = fleet.fleet_digest(args.obs_dirs, window_s=args.window_s,
                                 digest_fn=_digest_or_none)
        # Multi-tenant pods (fps_tpu.tenancy): a dir holding a
        # tenants/ namespace gets a per-tenant rollup + SLO-burn +
        # recovery section — each tenant's burn rates are its own,
        # never a neighbor's (blast-radius isolation in telemetry).
        tenants = {}
        for d in args.obs_dirs:
            if os.path.isdir(os.path.join(d, fleet.TENANTS_DIRNAME)):
                td = fleet.tenant_fleet_digest(d, window_s=args.window_s)
                tenants.update(td["tenants"])
        if tenants:
            out["tenants"] = tenants
        if not out["rollup"]["windows"] and not tenants:
            print(f"no telemetry under {args.obs_dirs}", file=sys.stderr)
            return 2
    else:
        try:
            out = render_digest(args.obs_dirs[0],
                                recovery_slo_s=args.recovery_slo_s)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 2

    print(json.dumps(scrub(out), indent=2 if args.pretty else None,
                     allow_nan=False))
    return 0


if __name__ == "__main__":
    sys.exit(main())
