"""Ablation profile of the ML-20M MF hot step on the real chip.

Times a scan of T steps with components knocked out one at a time to see
where the per-step milliseconds go. Run from /root/repo:
    python scratch/prof_mf.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fps_tpu import ops

R_ITEMS = 26744
R_USERS = 138496
RANK = 10
B = 32768
T = 512
N = 20_000_263


def _fence(out):
    """Force completion with a host read of one element of every leaf."""
    leaves = jax.tree.leaves(out)
    for leaf in leaves:
        a = leaf
        while getattr(a, "ndim", 0) > 0:
            a = a[0]
        np.asarray(a)


def bench(name, fn, *args):
    # Warm-up (compile) + fence.
    _fence(fn(*args))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        _fence(fn(*args))
        times.append(time.perf_counter() - t0)
    per_step = min(times) / T * 1e6
    print(f"{name:40s} {per_step:9.1f} us/step")


def main():
    rng = np.random.default_rng(0)
    key = jax.random.key(0)
    items = jnp.asarray(rng.integers(0, R_ITEMS, (T, B)), jnp.int32)
    users = jnp.asarray(rng.integers(0, R_USERS, (T, B)), jnp.int32)
    ratings = jnp.asarray(rng.normal(0, 1, (T, B)), jnp.float32)
    qtab = jnp.asarray(rng.normal(0, 0.1, (R_ITEMS, RANK)), jnp.float32)
    ptab = jnp.asarray(rng.normal(0, 0.1, (R_USERS, RANK)), jnp.float32)
    packed = jnp.asarray(rng.integers(0, 2**30, (N, 3)), jnp.int32)
    queue_slots = jnp.asarray(rng.integers(0, N, (T, B)), jnp.int32)

    # 1. batch-build gather only: (N,3) packed matrix gather
    @jax.jit
    def build_only(packed, slots):
        def body(c, s):
            rows = jnp.take(packed, s, axis=0)
            return c + rows.sum(), None
        return lax.scan(body, jnp.int32(0), slots)[0]

    bench("batch build gather (N,3)", build_only, packed, queue_slots)

    # 2. pull gather only
    @jax.jit
    def pull_only(qtab, items):
        def body(c, ids):
            v = ops.gather_rows(qtab, ids)
            return c + v.sum(), None
        return lax.scan(body, jnp.float32(0), items)[0]

    bench("item gather (B,10)", pull_only, qtab, items)

    # 3. scatter-add only (sum combine)
    @jax.jit
    def scatter_only(qtab, items, ratings):
        def body(tab, x):
            ids, r = x
            tab = ops.scatter_add(tab, ids, r[:, None] * jnp.ones((1, RANK)))
            return tab, None
        return lax.scan(body, qtab, (items, ratings))[0]

    bench("item scatter-add sum (B,10)", scatter_only, qtab, items, ratings)

    # 4. mean-combine push path (segment_sum x2 + div + where)
    def mean_push(tab, ids, deltas):
        rps = tab.shape[0]
        summed = jax.ops.segment_sum(deltas, ids, num_segments=rps + 1)[:rps]
        counts = jax.ops.segment_sum(
            jnp.ones_like(ids, jnp.int32), ids, num_segments=rps + 1)[:rps]
        summed = summed / jnp.maximum(counts, 1)[:, None].astype(jnp.float32)
        touched = counts > 0
        return jnp.where(touched[:, None], tab + summed, tab)

    @jax.jit
    def mean_only(qtab, items, ratings):
        def body(tab, x):
            ids, r = x
            tab = mean_push(tab, ids, r[:, None] * jnp.ones((1, RANK)))
            return tab, None
        return lax.scan(body, qtab, (items, ratings))[0]

    bench("item mean-combine push (B,10)", mean_only, qtab, items, ratings)

    # 5. dedup (sort) + scatter unique
    @jax.jit
    def dedup_scatter(qtab, items, ratings):
        def body(tab, x):
            ids, r = x
            deltas = r[:, None] * jnp.ones((1, RANK))
            order = jnp.argsort(ids)
            sids = ids[order]
            sdel = deltas[order]
            seg_start = jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), sids[1:] != sids[:-1]])
            seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
            summed = jax.ops.segment_sum(sdel, seg_id, num_segments=B)
            uids = jnp.where(seg_start, sids, -1)
            u_first = jax.ops.segment_max(
                jnp.where(seg_start, sids, -1), seg_id, num_segments=B)
            tab = ops.scatter_add(tab, u_first, summed)
            return tab, None
        return lax.scan(body, qtab, (items, ratings))[0]

    bench("dedup(sort)+scatter (B,10)", dedup_scatter, qtab, items, ratings)

    # 6. user local: gather + scatter into (138k,10)
    @jax.jit
    def user_path(ptab, users, ratings):
        def body(tab, x):
            ids, r = x
            p = jnp.take(tab, ids, axis=0)
            tab = tab.at[ids].add(r[:, None] * p)
            return tab, None
        return lax.scan(body, ptab, (users, ratings))[0]

    bench("user gather+scatter (B,10)", user_path, ptab, users, ratings)

    # 7. dense math only
    @jax.jit
    def math_only(qtab, items, ratings, users):
        def body(c, x):
            ids, r, u = x
            q = jnp.take(qtab, ids, axis=0)
            p = jnp.take(qtab, jnp.minimum(u, R_ITEMS - 1), axis=0)
            pred = jnp.sum(p * q, axis=-1)
            err = (r - pred)
            dp = 0.05 * (err[:, None] * q - 0.01 * p)
            dq = 0.05 * (err[:, None] * p - 0.01 * q)
            return c + dp.sum() + dq.sum(), None
        return lax.scan(body, jnp.float32(0), (items, ratings, users))[0]

    bench("2 gathers + SGD math", math_only, qtab, items, ratings, users)

    # 8. full composite analog of the real step
    @jax.jit
    def full(qtab, ptab, items, users, ratings):
        def body(carry, x):
            qtab, ptab = carry
            ids, u, r = x
            q = ops.gather_rows(qtab, ids)
            p = jnp.take(ptab, u, axis=0)
            pred = jnp.sum(p * q, axis=-1)
            err = r - pred
            dp = 0.05 * (err[:, None] * q - 0.01 * p)
            dq = 0.05 * (err[:, None] * p - 0.01 * q)
            ptab = ptab.at[u].add(dp)
            qtab = mean_push(qtab, ids, dq)
            return (qtab, ptab), (jnp.sum(err * err), jnp.float32(B))
        (qtab, ptab), outs = lax.scan(body, (qtab, ptab),
                                      (items, users, ratings))
        return qtab, ptab, outs

    bench("full step analog", full, qtab, ptab, items, users, ratings)


if __name__ == "__main__":
    main()
