"""Chaos sweep: run the fault-injector matrix end-to-end and print a
one-line survival digest (bench.py-style compact JSON).

Scenarios (all deterministic — fps_tpu.testing.chaos; the training
harness is shared with tests/test_resilience.py via
fps_tpu.testing.workloads):

* ``nan_mask`` / ``inf_mask``  — NaN/Inf-poisoned chunk under guard="mask":
  survives iff every table stays finite, the health channel fired, and
  test accuracy stays within tolerance of the clean run.
* ``huge_norm_mask``           — finite norm-exploded deltas under a
  norm_limit guard: survives iff the norm tier fired and quality holds.
* ``observe_rollback``         — guard="observe" + RollbackPolicy:
  survives iff exactly the poisoned chunk is quarantined and the tables
  stay finite.
* ``ckpt_truncate`` / ``ckpt_bitflip`` — corrupt the newest of two
  snapshots: survives iff restore falls back to the older one.
* ``tmp_sweep``                — stale mid-write tmp file: survives iff a
  fresh Checkpointer sweeps it and restores normally.
* ``supervised``               — a SIGSTOP-wedged child under
  ``tools/supervise.py``: survives iff the supervisor deadline-aborts
  (SIGTERM→SIGKILL), restarts with backoff, the resumed run restores
  ``latest_valid_step`` (at most one chunk of lost work), no corrupt
  snapshot is ever selected, and the final weights are BIT-IDENTICAL to
  an unsupervised straight run.
* ``prefetch_kill``            — SIGKILL while the overlapped host
  pipeline's worker thread is assembling a chunk several indices ahead
  of the dispatch point (``--prefetch 2``): survives iff the supervisor
  restarts the child once, nothing is quarantined (one crash is not
  determinism evidence), and the resumed pipeline-on run reproduces a
  straight pipeline-on run bit-for-bit.
* ``serve_while_train``        — a concurrent ``fps_tpu.serve``
  ReadServer polls the supervised child's checkpoint dir while the child
  is SIGKILLed mid-run and a torn full-named snapshot candidate is
  planted: survives iff readers never observe a torn, CRC-failing, or
  backward-moving table, the torn candidate is rejected, the reader
  converges on the newest valid snapshot byte-for-byte, and a post-run
  quarantine of the served snapshot swaps the reader BACKWARD
  (``docs/serving.md``).
* ``hot_tier_kill``            — SIGKILL between hot-tier reconciles
  under the supervisor (two-tier storage on, ``--hot-tier``/
  ``--hot-sync-every``): survives iff the restart restores from the
  last reconciled snapshot (one canonical table — the flush-reconcile
  boundary invariant), re-splits the hot replica, replays exactly one
  chunk, quarantines nothing, and reproduces a straight tiered run's
  final weights bit-for-bit.
* ``retier_kill``              — SIGKILL between a hot-set re-rank and
  the next checkpoint with the ADAPTIVE tier on (``fps_tpu.tiering``:
  mapped hot set, device-side tracking, forced re-rank cadence,
  tracker sidecars): survives iff the restart restores the last
  reconciled snapshot AND the matching tracker sidecar, re-derives the
  replica/slot-map from both, quarantines nothing, and replays to
  final weights bit-identical to a straight adaptive run (i.e. the
  resumed re-rank decisions are the straight run's).
* ``reconcile_shard_kill``     — SIGKILL between a sharded
  (reduce-scatter) reconcile window and the next checkpoint, with a
  stateful Adagrad hot-tier fold on (``--hot-fold adagrad``: per-row
  optimizer state sharded over the replica axis, persisted as
  ``fold::`` checkpoint arrays): survives iff the restart restores the
  canonical tables AND the matching fold state (fold arrays present in
  the snapshot, canonical table bytes untouched), quarantines nothing,
  and replays to final weights bit-identical to a straight run — a
  zero-restarted Adagrad accumulator would diverge.

The digest also carries the clean run's program CERTIFICATE
(``fps_tpu.analysis``, ``docs/analysis.md``): the compiled logreg step
is audited against its derived contract, so a regression in collective
structure / donation / host-transfer freedom fails the sweep even when
every scenario still survives.

Run (CPU mesh, like the test suite):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PYTHONPATH=/root/repo python tools/chaos_sweep.py
"""

import glob
import json
import os
import sys

import numpy as np

import jax

from fps_tpu.core.checkpoint import Checkpointer
from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.resilience import GuardConfig, RollbackPolicy
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.testing import chaos
from fps_tpu.testing.workloads import (
    NF,
    accuracy,
    health_sum,
    logreg_chunks,
    logreg_data,
    run_logreg,
    weights,
)


def _finite(store):
    return bool(np.all(np.isfinite(weights(store))))


def program_certificate(trainer, chunks) -> dict:
    """Certify the exact compiled program the sweep's scenarios dispatch
    (fps_tpu.analysis) and return the certificate JSON for the digest —
    a regression in collective structure (an extra psum, a lost
    donation, a stray host callback) shows up here next to the survival
    booleans, even when every scenario still survives."""
    import dataclasses

    from fps_tpu.analysis import certify, contract_for_trainer

    hlo = trainer.lowered_chunk_text(chunks[0], "sync")
    # Pin the sweep program's collective structure exactly (counts, not
    # bytes — payload scales with the harness): the gathered logreg
    # route is one pull all_gather + one routed-push all_to_all, so an
    # extra psum (or a lost route) fails the sweep, as promised above.
    contract = dataclasses.replace(
        contract_for_trainer(trainer, "sync"),
        max_collectives=2,
        per_kind_max={"all_gather": 1, "all_to_all": 1},
        exact_collectives=True,
    )
    cert = certify(hlo, contract, program="chaos/logreg")
    return cert.to_json()


def _health_totals(metrics, tables=("weights",)):
    """Per-table health-counter totals over a run's metrics list — the
    digest's evidence that the guard actually saw the poison."""
    return {
        t: {kind: health_sum(metrics, t, kind)
            for kind in ("nonfinite", "norm", "masked")}
        for t in tables
    }


def poison_scenario(mesh, chunks, test, acc_clean, kind):
    poisoned = list(chaos.poison_chunks(iter(chunks), chunk_index=1,
                                        column="feat_vals", kind=kind,
                                        frac=0.5, seed=1))
    guard = (GuardConfig(mode="mask", norm_limit=100.0)
             if kind == "huge" else GuardConfig(mode="mask"))
    _, store, m = run_logreg(mesh, poisoned, guard=guard)
    tier = "norm" if kind == "huge" else "nonfinite"
    ok = (_finite(store) and health_sum(m, "weights", tier) > 0
          and abs(accuracy(store, test) - acc_clean) < 0.05)
    return ok, {"health": _health_totals(m)}


def rollback_scenario(mesh, chunks):
    poisoned = list(chaos.poison_chunks(iter(chunks), chunk_index=1,
                                        column="feat_vals", kind="nan",
                                        frac=0.5, seed=1))
    policy = RollbackPolicy()
    _, store, m = run_logreg(mesh, poisoned, guard="observe",
                             rollback=policy)
    ok = _finite(store) and policy.quarantined == [1]
    # Quarantined chunks contribute no metrics entry, so the health totals
    # here cover only the SURVIVING chunks (expected all-zero under
    # observe+rollback — the poison was dropped whole).
    return ok, {"health": _health_totals(m),
                "quarantined": list(policy.quarantined),
                "rollback_budget": policy.max_rollbacks}


def ckpt_scenario(tmpdir, mesh, chunks, mode):
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(mesh, cfg)
    tables, ls = trainer.init_state(jax.random.key(0))
    ckpt = Checkpointer(tmpdir, keep=2)
    for i, c in enumerate(chunks[:2]):
        tables, ls, _ = trainer.run_chunk(tables, ls, c, jax.random.key(i))
        ckpt.save(i + 1, store, None)
    want = weights(store).copy()
    if mode == "tmp_sweep":
        import time

        torn = os.path.join(tmpdir, "torn.tmp.npz")
        open(torn, "wb").write(b"PK\x03\x04x")
        past = time.time() - 2 * Checkpointer.TMP_SWEEP_AGE_S
        os.utime(torn, (past, past))  # crash leftover, not a live writer
        ckpt2 = Checkpointer(tmpdir, keep=2)
        _, step = ckpt2.restore_tables(store)
        return (step == 2 and not glob.glob(tmpdir + "/*.tmp.npz")
                and np.array_equal(weights(store), want))
    chaos.corrupt_latest_snapshot(tmpdir, mode)
    ok = Checkpointer(tmpdir, keep=2).latest_valid_step() == 1
    _, step = ckpt.restore_tables(store)
    return ok and step == 1 and _finite(store)


def supervised_scenario(tmpdir):
    """End-to-end supervisor survival: wedge a real training child with
    SIGSTOP mid-run; the supervisor must abort + restart it and the
    resumed run must reproduce the straight run bit-for-bit. One shared
    implementation with the slow test in tests/test_supervise.py
    (fps_tpu.testing.supervised_demo.run_supervised_scenario) so the two
    cannot drift."""
    from fps_tpu.testing.supervised_demo import run_supervised_scenario

    return run_supervised_scenario(tmpdir)


def main():
    import tempfile

    mesh = make_ps_mesh()
    train, test = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    trainer_clean, store_clean, _ = run_logreg(mesh, chunks)
    acc_clean = accuracy(store_clean, test)
    certificate = program_certificate(trainer_clean, chunks)

    results = {}
    detail = {}
    results["nan_mask"], detail["nan_mask"] = poison_scenario(
        mesh, chunks, test, acc_clean, "nan")
    results["inf_mask"], detail["inf_mask"] = poison_scenario(
        mesh, chunks, test, acc_clean, "inf")
    results["huge_norm_mask"], detail["huge_norm_mask"] = poison_scenario(
        mesh, chunks, test, acc_clean, "huge")
    results["observe_rollback"], detail["observe_rollback"] = (
        rollback_scenario(mesh, chunks))
    for mode in ("truncate", "bitflip", "tmp_sweep"):
        with tempfile.TemporaryDirectory() as d:
            results[f"ckpt_{mode}" if mode != "tmp_sweep" else mode] = (
                ckpt_scenario(d, mesh, chunks, mode))
    with tempfile.TemporaryDirectory() as d:
        results["supervised"], detail["supervised"] = supervised_scenario(d)
    with tempfile.TemporaryDirectory() as d:
        from fps_tpu.testing.supervised_demo import run_prefetch_kill_scenario

        results["prefetch_kill"], detail["prefetch_kill"] = (
            run_prefetch_kill_scenario(d))
    with tempfile.TemporaryDirectory() as d:
        from fps_tpu.testing.supervised_demo import run_hot_tier_kill_scenario

        results["hot_tier_kill"], detail["hot_tier_kill"] = (
            run_hot_tier_kill_scenario(d))
    with tempfile.TemporaryDirectory() as d:
        from fps_tpu.testing.supervised_demo import run_retier_kill_scenario

        results["retier_kill"], detail["retier_kill"] = (
            run_retier_kill_scenario(d))
    with tempfile.TemporaryDirectory() as d:
        from fps_tpu.testing.supervised_demo import (
            run_reconcile_shard_kill_scenario,
        )

        results["reconcile_shard_kill"], detail["reconcile_shard_kill"] = (
            run_reconcile_shard_kill_scenario(d))
    with tempfile.TemporaryDirectory() as d:
        from fps_tpu.testing.supervised_demo import (
            run_serve_while_train_scenario,
        )

        results["serve_while_train"], detail["serve_while_train"] = (
            run_serve_while_train_scenario(d))

    digest = {
        "chaos_sweep": results,
        "survived": sum(results.values()),
        "total": len(results),
        # Per-scenario evidence: per-table health-counter totals and the
        # rollback/quarantine record (survival booleans alone said WHETHER
        # we lived, not WHAT the defenses saw).
        "detail": detail,
        # The compiled program's contract certificate (fps_tpu.analysis):
        # collective structure regressions surface next to survival.
        "program_certificate": certificate,
        "mesh": dict(mesh.shape),
        "clean_test_acc": round(acc_clean, 4),
    }
    print(json.dumps(digest), flush=True)
    return 0 if (digest["survived"] == digest["total"]
                 and certificate["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
