"""Chaos sweep: run the fault-injector matrix end-to-end and print a
one-line survival digest (bench.py-style compact JSON).

Scenarios (all deterministic — fps_tpu.testing.chaos; the training
harness is shared with tests/test_resilience.py via
fps_tpu.testing.workloads):

* ``nan_mask`` / ``inf_mask``  — NaN/Inf-poisoned chunk under guard="mask":
  survives iff every table stays finite, the health channel fired, and
  test accuracy stays within tolerance of the clean run.
* ``huge_norm_mask``           — finite norm-exploded deltas under a
  norm_limit guard: survives iff the norm tier fired and quality holds.
* ``observe_rollback``         — guard="observe" + RollbackPolicy:
  survives iff exactly the poisoned chunk is quarantined and the tables
  stay finite.
* ``ckpt_truncate`` / ``ckpt_bitflip`` — corrupt the newest of two
  snapshots: survives iff restore falls back to the older one.
* ``tmp_sweep``                — stale mid-write tmp file: survives iff a
  fresh Checkpointer sweeps it and restores normally.
* ``supervised``               — a SIGSTOP-wedged child under
  ``tools/supervise.py``: survives iff the supervisor deadline-aborts
  (SIGTERM→SIGKILL), restarts with backoff, the resumed run restores
  ``latest_valid_step`` (at most one chunk of lost work), no corrupt
  snapshot is ever selected, and the final weights are BIT-IDENTICAL to
  an unsupervised straight run.
* ``prefetch_kill``            — SIGKILL while the overlapped host
  pipeline's worker thread is assembling a chunk several indices ahead
  of the dispatch point (``--prefetch 2``): survives iff the supervisor
  restarts the child once, nothing is quarantined (one crash is not
  determinism evidence), and the resumed pipeline-on run reproduces a
  straight pipeline-on run bit-for-bit.
* ``serve_while_train``        — a concurrent ``fps_tpu.serve``
  ReadServer polls the supervised child's checkpoint dir while the child
  is SIGKILLed mid-run and a torn full-named snapshot candidate is
  planted: survives iff readers never observe a torn, CRC-failing, or
  backward-moving table, the torn candidate is rejected, the reader
  converges on the newest valid snapshot byte-for-byte, and a post-run
  quarantine of the served snapshot swaps the reader BACKWARD
  (``docs/serving.md``).
* ``hot_tier_kill``            — SIGKILL between hot-tier reconciles
  under the supervisor (two-tier storage on, ``--hot-tier``/
  ``--hot-sync-every``): survives iff the restart restores from the
  last reconciled snapshot (one canonical table — the flush-reconcile
  boundary invariant), re-splits the hot replica, replays exactly one
  chunk, quarantines nothing, and reproduces a straight tiered run's
  final weights bit-for-bit.
* ``retier_kill``              — SIGKILL between a hot-set re-rank and
  the next checkpoint with the ADAPTIVE tier on (``fps_tpu.tiering``:
  mapped hot set, device-side tracking, forced re-rank cadence,
  tracker sidecars): survives iff the restart restores the last
  reconciled snapshot AND the matching tracker sidecar, re-derives the
  replica/slot-map from both, quarantines nothing, and replays to
  final weights bit-identical to a straight adaptive run (i.e. the
  resumed re-rank decisions are the straight run's).
* ``reconcile_shard_kill``     — SIGKILL between a sharded
  (reduce-scatter) reconcile window and the next checkpoint, with a
  stateful Adagrad hot-tier fold on (``--hot-fold adagrad``: per-row
  optimizer state sharded over the replica axis, persisted as
  ``fold::`` checkpoint arrays): survives iff the restart restores the
  canonical tables AND the matching fold state (fold arrays present in
  the snapshot, canonical table bytes untouched), quarantines nothing,
  and replays to final weights bit-identical to a straight run — a
  zero-restarted Adagrad accumulator would diverge.

* ``delta_chain_kill``         — delta-snapshot chains
  (``Checkpointer(delta=DeltaPolicy(...))``): a supervised child
  publishing one full + per-chunk deltas is SIGKILLed mid-chain, and a
  compaction victim is SIGKILLed at EVERY fold phase (pre-rename /
  pre-sweep / mid-sweep): survives iff every crash recovers to the last
  verified chain link (resume bit-identical; the delta encoding itself
  bit-identical to full snapshots) and a rerun compaction completes.
* ``fleet_fence``              — step-fenced serving fleet
  (``fps_tpu.serve.fleet``): N readers under quorum fencing over a
  SIGKILLed+restarted delta-publishing child, with one READER killed
  and restarted mid-swap: survives iff the fence stays forward-monotone,
  no reader ever answers a superseded step (restart included), delta
  chains hot-swap incrementally, and the fleet converges byte-identical
  to the resolved chain.

* ``pod_kill_one_host``        — pod of 3 member agents
  (``fps_tpu.supervise.pod``) over one shared pod dir; ONE member's
  child is SIGKILLed: survives iff the leader makes one pod-wide
  decision (coordinated abort + restart of ALL members from the common
  ``latest_valid_step``), nothing is quarantined or evicted, and every
  member finishes bit-identical to an uninterrupted run.
* ``pod_partition_coordinator`` — the lease HOLDER's member agent is
  SIGSTOPped: survives iff a follower seizes the expired lease (fencing
  epoch bump), fences every member dir, restarts the pod, the stale
  leader's orphan child is REFUSED by the fence when it next publishes
  (StaleEpochError in its log; no epoch-stale snapshot postdates the
  fence), and the released leader rejoins to a bit-identical finish.
* ``pod_flapping_member``      — one member's child crashes at the same
  chunk on every attempt: survives iff two coordinated restarts converge
  on a POD-WIDE quarantine of that chunk, EVERY member skips it (no host
  re-dispatches a chunk another host proved poisonous), and all members
  match a straight run carrying the same quarantine preset.
* ``pod_elastic_resize``       — a whole host dies (member agent + child
  SIGKILLed) and later returns: survives iff the leader evicts it (the
  pod re-plans at W-1), the survivors continue, the returning member is
  re-admitted at the next boundary from a SYNCED canonical snapshot, and
  every member finishes byte-identical to a straight W-host run — with
  zero torn or epoch-stale checkpoints published.

* ``storage_brownout``         — deterministic I/O faults
  (``fps_tpu.testing.faultfs``: transient EIO writes, slow fsyncs, a
  torn rename, EIO/stale/ENOENT reads, flaky scans) against a live
  training run + 2-reader quorum fleet: survives iff training never
  crashes and finishes BIT-identical to the fault-free run, at least
  one publish degrades (backlog raised, drained after recovery), the
  fleet serves last-good throughout with zero fence violations, and
  the read plane's degradation is counted (poll_errors), never a
  frozen reader.
* ``storage_blackout_recover`` — every snapshot write fails for a
  window covering three publishes' full retry budgets: survives iff
  training continues with a BOUNDED publish backlog (exactly the
  blacked-out publishes), the first landed publish drains it, the
  recovered directory's newest snapshot is bit-identical to the clean
  run's, and a fresh process resumes from it.
* ``enospc_compaction``        — ENOSPC through the LSM fold's whole
  retry budget: survives iff the fold aborts with the delta chain
  INTACT (still resolvable), ``storage.compaction_aborts`` counts it,
  and the next publish after recovery re-triggers a compaction that
  completes bit-exactly.
* ``slow_lease_near_ttl``      — the pod lease holder's renewal writes
  are slowed past TTL/2: survives iff the leader steps down CLEANLY
  before its record expires, stops renewing so the record lapses, a
  follower seizes with a strictly-higher fencing epoch, and the
  deposed leader stays out.

* ``tenant_poison_isolation``  — two tenants under one
  ``fps_tpu.tenancy.TenantManager``; tenant a's child poison-crashes at
  the same chunk every attempt: survives iff a's OWN supervisor
  quarantines it (2 restarts, chunk skipped) while tenant b finishes
  with zero restarts, BIT-IDENTICAL to its solo run, both fencing
  epochs untouched, and the post-run namespace audit clean.
* ``tenant_enospc_brownout``   — an ENOSPC faultfs schedule carried in
  tenant a's spec env (the only injection channel — per-tenant by
  construction) fails a run of its snapshot writes: survives iff a
  degrades (publishes skipped + counted in a's own telemetry) without
  restarting and still matches the fault-free solo weights, b sees zero
  degraded publishes and stays bit-identical, audit clean.
* ``tenant_reader_wedge``      — each tenant namespace runs its own
  heartbeating serving reader; a's reader is SIGSTOPped, detected
  wedged via a's own beacons, and restarted: survives iff b's reader
  never reads as wedged, b's serve fence bytes are untouched by the
  whole episode, the restarted reader catches up
  (``time_to_recovered_s``), both tenants' weights stay bit-identical
  to the clean run, audit clean.
* ``tenant_noisy_neighbor``    — a's flat access profile demands more
  replica budget than its weighted share; ``plan_tenants`` must grant
  b its FULL demand (plan knobs identical to b's solo plan) while only
  a's hot tier shrinks, then real children train at the arbitrated
  knobs: survives iff b is bit-identical to its solo run at those
  knobs and a still finishes cleanly, audit clean.

The digest also carries the clean run's program CERTIFICATE
(``fps_tpu.analysis``, ``docs/analysis.md``): the compiled logreg step
is audited against its derived contract, so a regression in collective
structure / donation / host-transfer freedom fails the sweep even when
every scenario still survives.

The pod scenarios additionally export their CAUSAL TRACE
(``tools/trace_export.py``: one merged Chrome/Perfetto span tree per pod
dir — ``pod_kill_one_host`` and ``pod_partition_coordinator`` fail
unless the coordinated restart is a single parent span whose per-host
attempt children all carry the fencing epoch) and a FLEET rollup + SLO
burn section (``fps_tpu.obs.fleet`` over the member obs dirs), lifted
into the digest's top-level ``fleet`` field.

``--only SCENARIO[,SCENARIO...]`` (repeatable; entries may be fnmatch
globs like ``tenant_*``) runs a subset so CI can shard the sweep; a red
run exits nonzero and names the failing scenarios on stderr (and in the
digest's ``failed`` list).

Run (CPU mesh, like the test suite):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PYTHONPATH=/root/repo python tools/chaos_sweep.py
"""

import glob
import json
import os
import sys

import numpy as np

import jax

from fps_tpu.core.checkpoint import Checkpointer
from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.resilience import GuardConfig, RollbackPolicy
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.testing import chaos
from fps_tpu.testing.workloads import (
    NF,
    accuracy,
    health_sum,
    logreg_chunks,
    logreg_data,
    run_logreg,
    weights,
)


# -- time-to-recovered SLOs ------------------------------------------------
# Seconds from the fault landing to the injected plane demonstrably
# recovered (the scenarios' own ``time_to_recovered_s`` measurement).
# A scenario that RECOVERS but recovers late is a failure: surviving a
# brownout by spending three minutes down is an outage with extra
# steps. The default is deliberately generous — CPU CI pays compiles
# and subprocess spawns a TPU pod never would — and per-scenario
# overrides loosen it further where recovery legitimately includes
# multi-child restarts or whole-tenant replays. ``--recovery-slo-s``
# rescales the default without touching the override ratios.
RECOVERY_SLO_DEFAULT_S = 60.0
RECOVERY_SLO_OVERRIDES_S = {
    # Pod-coordinated restarts: leader re-election + every member
    # replaying from the common verified step (N children, N compiles).
    "pod_kill_one_host": 120.0,
    "pod_partition_coordinator": 120.0,
    # Tenant scenarios restart/replay a whole tenant namespace (its own
    # supervisor, checkpoints, and serving reader) beside a healthy one.
    "tenant_enospc_brownout": 120.0,
    "tenant_reader_wedge": 120.0,
}


def recovery_slo_for(name: str, default_s: float | None = None) -> float:
    base = (RECOVERY_SLO_DEFAULT_S if default_s is None
            else float(default_s))
    scale = base / RECOVERY_SLO_DEFAULT_S
    return RECOVERY_SLO_OVERRIDES_S.get(name, RECOVERY_SLO_DEFAULT_S) * scale


def _finite(store):
    return bool(np.all(np.isfinite(weights(store))))


def program_certificate(trainer, chunks) -> dict:
    """Certify the exact compiled program the sweep's scenarios dispatch
    (fps_tpu.analysis) and return the certificate JSON for the digest —
    a regression in collective structure (an extra psum, a lost
    donation, a stray host callback) shows up here next to the survival
    booleans, even when every scenario still survives."""
    import dataclasses

    from fps_tpu.analysis import certify, contract_for_trainer

    hlo = trainer.lowered_chunk_text(chunks[0], "sync")
    # Pin the sweep program's collective structure exactly (counts, not
    # bytes — payload scales with the harness): the gathered logreg
    # route is one pull all_gather + one routed-push all_to_all, so an
    # extra psum (or a lost route) fails the sweep, as promised above.
    contract = dataclasses.replace(
        contract_for_trainer(trainer, "sync"),
        max_collectives=2,
        per_kind_max={"all_gather": 1, "all_to_all": 1},
        exact_collectives=True,
    )
    cert = certify(hlo, contract, program="chaos/logreg")
    return cert.to_json()


def _health_totals(metrics, tables=("weights",)):
    """Per-table health-counter totals over a run's metrics list — the
    digest's evidence that the guard actually saw the poison."""
    return {
        t: {kind: health_sum(metrics, t, kind)
            for kind in ("nonfinite", "norm", "masked")}
        for t in tables
    }


def poison_scenario(mesh, chunks, test, acc_clean, kind):
    poisoned = list(chaos.poison_chunks(iter(chunks), chunk_index=1,
                                        column="feat_vals", kind=kind,
                                        frac=0.5, seed=1))
    guard = (GuardConfig(mode="mask", norm_limit=100.0)
             if kind == "huge" else GuardConfig(mode="mask"))
    _, store, m = run_logreg(mesh, poisoned, guard=guard)
    tier = "norm" if kind == "huge" else "nonfinite"
    ok = (_finite(store) and health_sum(m, "weights", tier) > 0
          and abs(accuracy(store, test) - acc_clean) < 0.05)
    return ok, {"health": _health_totals(m)}


def rollback_scenario(mesh, chunks):
    poisoned = list(chaos.poison_chunks(iter(chunks), chunk_index=1,
                                        column="feat_vals", kind="nan",
                                        frac=0.5, seed=1))
    policy = RollbackPolicy()
    _, store, m = run_logreg(mesh, poisoned, guard="observe",
                             rollback=policy)
    ok = _finite(store) and policy.quarantined == [1]
    # Quarantined chunks contribute no metrics entry, so the health totals
    # here cover only the SURVIVING chunks (expected all-zero under
    # observe+rollback — the poison was dropped whole).
    return ok, {"health": _health_totals(m),
                "quarantined": list(policy.quarantined),
                "rollback_budget": policy.max_rollbacks}


def ckpt_scenario(tmpdir, mesh, chunks, mode):
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(mesh, cfg)
    tables, ls = trainer.init_state(jax.random.key(0))
    ckpt = Checkpointer(tmpdir, keep=2)
    for i, c in enumerate(chunks[:2]):
        tables, ls, _ = trainer.run_chunk(tables, ls, c, jax.random.key(i))
        ckpt.save(i + 1, store, None)
    want = weights(store).copy()
    if mode == "tmp_sweep":
        import time

        torn = os.path.join(tmpdir, "torn.tmp.npz")
        open(torn, "wb").write(b"PK\x03\x04x")
        past = time.time() - 2 * Checkpointer.TMP_SWEEP_AGE_S
        os.utime(torn, (past, past))  # crash leftover, not a live writer
        ckpt2 = Checkpointer(tmpdir, keep=2)
        _, step = ckpt2.restore_tables(store)
        return (step == 2 and not glob.glob(tmpdir + "/*.tmp.npz")
                and np.array_equal(weights(store), want))
    chaos.corrupt_latest_snapshot(tmpdir, mode)
    ok = Checkpointer(tmpdir, keep=2).latest_valid_step() == 1
    _, step = ckpt.restore_tables(store)
    return ok and step == 1 and _finite(store)


def supervised_scenario(tmpdir):
    """End-to-end supervisor survival: wedge a real training child with
    SIGSTOP mid-run; the supervisor must abort + restart it and the
    resumed run must reproduce the straight run bit-for-bit. One shared
    implementation with the slow test in tests/test_supervise.py
    (fps_tpu.testing.supervised_demo.run_supervised_scenario) so the two
    cannot drift."""
    from fps_tpu.testing.supervised_demo import run_supervised_scenario

    return run_supervised_scenario(tmpdir)


def _subprocess_scenario(fn_name,
                         module="fps_tpu.testing.supervised_demo"):
    """A scenario that lives in a testing module (supervised_demo by
    default; the multi-tenant ones in fps_tpu.testing.tenant_demo) and
    runs whole child processes — imported lazily, executed in a fresh
    tempdir."""
    import tempfile

    def run(_harness):
        import importlib

        demo = importlib.import_module(module)
        with tempfile.TemporaryDirectory() as d:
            return getattr(demo, fn_name)(d)

    return run


def _harness_scenarios():
    """Scenario registry: name -> callable(harness) -> (ok, detail|None).
    The in-process scenarios share one lazily-built logreg harness; the
    subprocess ones (supervised / pod) need none of it."""
    import tempfile

    def ckpt(mode):
        def run(h):
            with tempfile.TemporaryDirectory() as d:
                return ckpt_scenario(d, h["mesh"], h["chunks"], mode), None

        return run

    return {
        "nan_mask": lambda h: poison_scenario(
            h["mesh"], h["chunks"], h["test"], h["acc_clean"], "nan"),
        "inf_mask": lambda h: poison_scenario(
            h["mesh"], h["chunks"], h["test"], h["acc_clean"], "inf"),
        "huge_norm_mask": lambda h: poison_scenario(
            h["mesh"], h["chunks"], h["test"], h["acc_clean"], "huge"),
        "observe_rollback": lambda h: rollback_scenario(
            h["mesh"], h["chunks"]),
        "ckpt_truncate": ckpt("truncate"),
        "ckpt_bitflip": ckpt("bitflip"),
        "tmp_sweep": ckpt("tmp_sweep"),
        "supervised": lambda h: supervised_scenario_tmp(),
        "prefetch_kill": _subprocess_scenario("run_prefetch_kill_scenario"),
        "hot_tier_kill": _subprocess_scenario("run_hot_tier_kill_scenario"),
        "retier_kill": _subprocess_scenario("run_retier_kill_scenario"),
        "megastep_kill": _subprocess_scenario("run_megastep_kill_scenario"),
        "reconcile_shard_kill": _subprocess_scenario(
            "run_reconcile_shard_kill_scenario"),
        "serve_while_train": _subprocess_scenario(
            "run_serve_while_train_scenario"),
        # Delta-snapshot chains + the step-fenced serving fleet
        # (ISSUE 14; docs/resilience.md failure model rows, docs/
        # serving.md fleet sections).
        "delta_chain_kill": _subprocess_scenario(
            "run_delta_chain_kill_scenario"),
        "fleet_fence": _subprocess_scenario(
            "run_fleet_fence_scenario"),
        # Pod-level scenarios (fps_tpu.supervise.pod): N member agents
        # over one shared pod dir — one failure domain.
        "pod_kill_one_host": _subprocess_scenario(
            "run_pod_kill_one_host_scenario"),
        "pod_partition_coordinator": _subprocess_scenario(
            "run_pod_partition_coordinator_scenario"),
        "pod_flapping_member": _subprocess_scenario(
            "run_pod_flapping_member_scenario"),
        "pod_elastic_resize": _subprocess_scenario(
            "run_pod_elastic_resize_scenario"),
        # Hostile-filesystem scenarios (fps_tpu.testing.faultfs +
        # fps_tpu/core/retry.py; docs/resilience.md "Hostile
        # filesystem"): deterministic I/O fault injection against the
        # framework's own storage seams — ENOSPC/EIO/latency/torn
        # renames/stale reads — with training, compaction, the serving
        # fleet, and the pod lease all required to DEGRADE (retry,
        # skip, step down, serve last-good) instead of crashing or
        # wedging, and to recover bit-identically.
        "storage_brownout": _subprocess_scenario(
            "run_storage_brownout_scenario"),
        "storage_blackout_recover": _subprocess_scenario(
            "run_storage_blackout_recover_scenario"),
        "enospc_compaction": _subprocess_scenario(
            "run_enospc_compaction_scenario"),
        "slow_lease_near_ttl": _subprocess_scenario(
            "run_slow_lease_near_ttl_scenario"),
        # Hostile-network scenarios (fps_tpu.serve.wire +
        # fps_tpu.testing.faultnet; docs/resilience.md "Hostile
        # network"): deterministic wire-fault schedules against the
        # framed TCP plane — no torn frame is ever decoded, reconnects
        # dedupe through the replay cache (zero duplicate applies),
        # slow peers cost latency never integrity, deadlines bound
        # every request, and a SIGSTOPped reader becomes a
        # reader_wedged incident within the liveness timeout.
        "net_torn_frames": _subprocess_scenario(
            "run_net_torn_frames_scenario"),
        "net_reconnect_storm": _subprocess_scenario(
            "run_net_reconnect_storm_scenario"),
        "net_slow_peer": _subprocess_scenario(
            "run_net_slow_peer_scenario"),
        "net_partition_reader": _subprocess_scenario(
            "run_net_partition_reader_scenario"),
        # Batched read-plane scenarios (ISSUE 19: multi-lookup wire op
        # + admission control + the fleet autoscaler): a torn multi
        # frame is never partially applied (exactly-once across the
        # storm, batched == unbatched == binary bit-identical, BUSY
        # sheds whole batches retryably), and reader churn under the
        # autoscaler — scale-up, wedged-reader replacement, scale-down
        # — keeps the step fence monotone and the answers exact.
        "serve_batch_storm": _subprocess_scenario(
            "run_serve_batch_storm_scenario"),
        "autoscale_reader_churn": _subprocess_scenario(
            "run_autoscale_reader_churn_scenario"),
        # Multi-tenant blast-radius scenarios (fps_tpu.tenancy +
        # fps_tpu.testing.tenant_demo; docs/resilience.md "Multi-tenant
        # blast radius"): one tenant is faulted, and every NON-injected
        # tenant must finish bit-identical to its solo run with a clean
        # post-run namespace audit (zero cross-tenant writes) — the
        # per-scenario time_to_recovered_s and audit verdicts are lifted
        # into the digest's top-level maps.
        "tenant_poison_isolation": _subprocess_scenario(
            "run_tenant_poison_isolation_scenario",
            module="fps_tpu.testing.tenant_demo"),
        "tenant_enospc_brownout": _subprocess_scenario(
            "run_tenant_enospc_brownout_scenario",
            module="fps_tpu.testing.tenant_demo"),
        "tenant_reader_wedge": _subprocess_scenario(
            "run_tenant_reader_wedge_scenario",
            module="fps_tpu.testing.tenant_demo"),
        "tenant_noisy_neighbor": _subprocess_scenario(
            "run_tenant_noisy_neighbor_scenario",
            module="fps_tpu.testing.tenant_demo"),
    }


def supervised_scenario_tmp():
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        return supervised_scenario(d)


# Scenarios that need the shared in-process logreg harness (mesh, chunk
# stream, clean-run accuracy); everything else runs pure subprocesses.
_NEEDS_HARNESS = ("nan_mask", "inf_mask", "huge_norm_mask",
                  "observe_rollback", "ckpt_truncate", "ckpt_bitflip",
                  "tmp_sweep")


class _ScenarioTimeout(BaseException):
    """A scenario overran --timeout-s (raised from the SIGALRM handler
    so even a blocked subprocess wait unwinds). BaseException — the
    KeyboardInterrupt pattern — so a scenario's own broad `except
    Exception` recovery paths cannot swallow the timeout and leave the
    sweep unbounded with a disarmed timer."""


def _run_bounded(fn, harness, timeout_s: float):
    """Run one scenario under a wall-clock bound. SIGALRM (not a
    thread) so a scenario wedged inside a blocking syscall — the exact
    failure mode the flag exists for — is interrupted; 0 disables.
    Children a timed-out scenario leaks are the price of failing
    loudly instead of hanging CI."""
    if timeout_s <= 0:
        return fn(harness)
    import signal

    def on_alarm(_sig, _frame):
        raise _ScenarioTimeout()

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.setitimer(signal.ITIMER_REAL, timeout_s)
    try:
        return fn(harness)
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, old)


def main(argv=None):
    import argparse

    scenarios = _harness_scenarios()
    ap = argparse.ArgumentParser(
        description="fps_tpu chaos sweep: run the fault-injector matrix "
                    "and print a one-line survival digest")
    ap.add_argument("--only", action="append", default=[],
                    metavar="SCENARIO[,SCENARIO...]",
                    help="run only these scenarios (repeatable / "
                         "comma-separated; fnmatch globs like "
                         "'tenant_*' work) — lets CI shard the sweep; "
                         f"known: {', '.join(scenarios)}")
    ap.add_argument("--list", action="store_true",
                    help="print registered scenario names (one per "
                         "line) and exit — CI shards build their "
                         "--only sets from this instead of hardcoding")
    ap.add_argument("--timeout-s", type=float, default=0.0,
                    help="per-scenario wall-clock bound (0 = none): a "
                         "wedged scenario fails LOUDLY under its own "
                         "name instead of hanging the whole sweep "
                         "(SIGALRM-interrupted, so even a blocked "
                         "subprocess wait is bounded)")
    ap.add_argument("--recovery-slo-s", type=float, default=None,
                    metavar="S",
                    help="rescale the time-to-recovered SLO default "
                         f"(normally {RECOVERY_SLO_DEFAULT_S:.0f}s; "
                         "per-scenario overrides scale with it; 0 "
                         "disables SLO enforcement): a scenario that "
                         "recovers but recovers LATE fails the sweep "
                         "under its own name")
    ap.add_argument("--shard", default=None, metavar="K/N",
                    help="run shard K of N (1-based) over the --list "
                         "order, after --only filtering — CI splits "
                         "the sweep across jobs without hardcoding "
                         "scenario names")
    args = ap.parse_args(argv)
    if args.list:
        for name in scenarios:
            print(name)
        return 0
    selected = [s for arg in args.only for s in arg.split(",") if s]
    # Each --only entry may be an exact name or an fnmatch glob
    # (e.g. 'tenant_*', 'pod_*') — a pattern matching nothing is a
    # typo and fails loudly, same as an unknown exact name.
    import fnmatch

    unknown = sorted(pat for pat in selected
                     if not fnmatch.filter(scenarios, pat))
    if unknown:
        ap.error(f"unknown scenario(s)/pattern(s) {unknown}; "
                 f"known: {sorted(scenarios)}")
    names = [n for n in scenarios
             if not selected
             or any(fnmatch.fnmatch(n, pat) for pat in selected)]
    if args.shard:
        try:
            k, n_shards = (int(x) for x in args.shard.split("/"))
        except ValueError:
            ap.error(f"--shard wants K/N (e.g. 2/4), got {args.shard!r}")
        if not 1 <= k <= n_shards:
            ap.error(f"--shard K must be in [1, N], got {args.shard!r}")
        names = [nm for i, nm in enumerate(names)
                 if i % n_shards == k - 1]

    harness = None
    certificate = None
    if any(n in _NEEDS_HARNESS for n in names) or (not selected
                                                   and not args.shard):
        mesh = make_ps_mesh()
        train, test = logreg_data()
        chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
        trainer_clean, store_clean, _ = run_logreg(mesh, chunks)
        harness = {"mesh": mesh, "test": test, "chunks": chunks,
                   "acc_clean": accuracy(store_clean, test)}
        # The certificate rides the full sweep (and any shard that
        # builds the harness anyway): a collective-structure regression
        # fails the sweep even when every scenario survives.
        certificate = program_certificate(trainer_clean, chunks)

    results = {}
    detail = {}
    for name in names:
        try:
            out = _run_bounded(scenarios[name], harness, args.timeout_s)
        except _ScenarioTimeout:
            # The loud-failure contract: the wedged scenario is NAMED
            # in the digest and on stderr; the sweep moves on.
            print(f"chaos_sweep: scenario {name} timed out after "
                  f"{args.timeout_s}s", file=sys.stderr, flush=True)
            results[name] = False
            detail[name] = {"error": "timeout",
                            "timeout_s": args.timeout_s}
            continue
        ok, d = out if isinstance(out, tuple) else (out, None)
        results[name] = bool(ok)
        if d is not None:
            detail[name] = d

    # Time-to-recovered SLO: a scenario whose measured recovery latency
    # overruns its bound fails even though it recovered — late recovery
    # is an outage with extra steps. Enforced here (not inside the
    # scenarios) so the bounds stay in one place and obs_report can
    # read breaches off the digest.
    slo_enforced = (args.recovery_slo_s is None
                    or args.recovery_slo_s > 0)
    slo_breaches = {}
    if slo_enforced:
        for n, d in detail.items():
            t = (d.get("time_to_recovered_s")
                 if isinstance(d, dict) else None)
            if t is None:
                continue
            bound = recovery_slo_for(n, args.recovery_slo_s)
            if float(t) > bound:
                slo_breaches[n] = {"time_to_recovered_s": float(t),
                                   "slo_s": bound}
                results[n] = False
                print(f"chaos_sweep: scenario {n} recovered in "
                      f"{float(t):.1f}s, over its {bound:.1f}s SLO",
                      file=sys.stderr, flush=True)

    failed = sorted(n for n, ok in results.items() if not ok)
    cert_ok = certificate is None or certificate["ok"]
    digest = {
        "chaos_sweep": results,
        "survived": sum(results.values()),
        "total": len(results),
        # The names CI wants on a red run — also printed to stderr.
        "failed": failed,
        # Per-scenario evidence: per-table health-counter totals and the
        # rollback/quarantine record (survival booleans alone said WHETHER
        # we lived, not WHAT the defenses saw).
        "detail": detail,
        # The compiled program's contract certificate (fps_tpu.analysis):
        # collective structure regressions surface next to survival.
        "program_certificate": certificate,
        # Fleet rollup + SLO burn over the pod scenario's member obs
        # dirs (fps_tpu.obs.fleet, computed inside the scenario before
        # its tempdir is collected): the sweep's fleet-level telemetry
        # evidence — throughput, cold-route certification rate, restart
        # counts, and burn-rate verdicts ride the digest.
        "fleet": (detail.get("pod_kill_one_host") or {}).get("fleet"),
        # Per-scenario recovery latency (seconds from the fault landing
        # to the injected plane demonstrably recovered; null where the
        # scenario degrades in place instead of restarting) and the
        # multi-tenant scenarios' post-run namespace-audit verdicts —
        # obs_report's incident view and CI both read these off the
        # digest without digging through detail.
        "time_to_recovered_s": {
            n: d.get("time_to_recovered_s")
            for n, d in detail.items()
            if isinstance(d, dict) and "time_to_recovered_s" in d},
        # The SLO verdicts next to the measurements: the bound every
        # recovering scenario was held to and the ones that overran it
        # (breaches also flip the scenario into `failed`).
        "recovery_slo": {
            "default_s": (args.recovery_slo_s
                          if slo_enforced and args.recovery_slo_s
                          else RECOVERY_SLO_DEFAULT_S),
            "enforced": slo_enforced,
            "bounds_s": {
                n: recovery_slo_for(
                    n, args.recovery_slo_s if slo_enforced else None)
                for n, d in detail.items()
                if isinstance(d, dict) and "time_to_recovered_s" in d},
            "breaches": slo_breaches,
        },
        "namespace_audit": {
            n: d.get("namespace_audit")
            for n, d in detail.items()
            if isinstance(d, dict) and "namespace_audit" in d},
        "clean_test_acc": (round(harness["acc_clean"], 4)
                           if harness else None),
    }
    if harness:
        digest["mesh"] = dict(harness["mesh"].shape)
    print(json.dumps(digest), flush=True)
    if failed or not cert_ok:
        blame = list(failed) + ([] if cert_ok else ["program_certificate"])
        print(f"chaos_sweep: FAILED scenarios: {', '.join(blame)}",
              file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
