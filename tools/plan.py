"""Print an auto-tiering plan and its predicted collective-byte budget.

The CLI face of ``fps_tpu.tiering.planner`` (docs/performance.md
"Adaptive tiering"): given per-table geometries and an id-density
estimate — a synthetic Zipf profile (``--alpha``) or measured counts
from an ``.npz`` (``--counts``, arrays keyed by table name; e.g. the
per-id estimates a tracker sidecar's decayed sketch yields) — run
:func:`plan_tables` and print the per-table decision rows
(``hot_tier`` / ``hot_sync_every`` / dense route, with the planner's
reason strings).

Unless ``--no-lower``, the tool then LOWERS the plan: a generic
pull/push probe workload (:mod:`fps_tpu.tiering.probe`) is built over
the planned table specs on the 8-device CPU mesh, the exact per-chunk
program the driver would dispatch is lowered, and
``fps_tpu.analysis.collective_profile`` measures its collective count
and payload bytes — the predicted budget is a MEASURED program, not a
cost model. The untiered baseline program is profiled alongside so the
plan's collective savings are visible in one output.

Usage:
  python tools/plan.py --table item_factors:4096:16 --table users:100000:16 \
      [--alpha 1.2 | --counts COUNTS.npz] [--batch-rows 1024] \
      [--coverage 0.9] [--replica-budget-mb 64] [--max-sync-every 8] \
      [--shards 8] [--no-lower] [--json]

Like bench/audit_programs, re-execs itself into a cleaned 8-CPU-device
environment when lowering is requested and the current process cannot
see 8 devices.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def _parse_table(s: str):
    parts = s.split(":")
    if len(parts) != 3:
        raise argparse.ArgumentTypeError(
            f"--table wants name:num_ids:dim, got {s!r}")
    return parts[0], int(parts[1]), int(parts[2])


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        description="auto-tiering planner CLI (fps_tpu.tiering)")
    ap.add_argument("--table", action="append", required=True,
                    type=_parse_table, metavar="NAME:NUM_IDS:DIM",
                    help="one parameter table's geometry (repeatable)")
    ap.add_argument("--alpha", type=float, default=1.2,
                    help="synthetic Zipf skew for the density estimate "
                         "(ignored with --counts)")
    ap.add_argument("--counts", default=None, metavar="NPZ",
                    help="measured per-id counts, one array per table "
                         "name (overrides --alpha)")
    ap.add_argument("--batch-rows", type=int, default=1024,
                    help="pulled rows per step per table (the planner's "
                         "traffic unit)")
    ap.add_argument("--coverage", type=float, default=0.9,
                    help="traffic fraction a partial head must cover")
    ap.add_argument("--replica-budget-mb", type=float, default=64.0,
                    help="per-device replica memory budget per table")
    ap.add_argument("--max-sync-every", type=int, default=8,
                    help="reconcile-cadence ceiling (staleness bound)")
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--workers", type=int, default=8,
                    help="total worker devices (sizes the per-worker "
                         "compacted cold lane, planner.choose_cold_budget)")
    ap.add_argument("--no-lower", action="store_true",
                    help="plan only — skip lowering the probe program "
                         "(no jax devices needed)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output only")
    return ap


def _reexec_if_needed() -> None:
    spec = importlib.util.spec_from_file_location(
        "_fps_hostenv", os.path.join(_ROOT, "fps_tpu", "utils",
                                     "hostenv.py"))
    hostenv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hostenv)
    if hostenv.in_reexec():
        return
    env = hostenv.cpu_mesh_env(8)
    env["PYTHONPATH"] = os.pathsep.join(
        [_ROOT] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p])
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if not args.no_lower and argv is None:
        # Only the real CLI re-execs (importers own their device mesh).
        _reexec_if_needed()

    import numpy as np

    from fps_tpu.tiering.planner import TableDensity, plan_tables

    counts_by_name = {}
    if args.counts:
        with np.load(args.counts) as z:
            counts_by_name = {k: z[k].copy() for k in z.files}
    densities = []
    for name, num_ids, dim in args.table:
        if name in counts_by_name:
            c = np.asarray(counts_by_name[name], np.float64)
            if c.shape != (num_ids,):
                raise SystemExit(
                    f"--counts[{name}] shape {c.shape} != ({num_ids},)")
        else:
            c = 1.0 / np.arange(1, num_ids + 1) ** args.alpha
        densities.append(TableDensity(name, num_ids, dim, c))
    plans = plan_tables(
        densities,
        batch_rows_per_step=args.batch_rows,
        replica_budget_bytes=int(args.replica_budget_mb * (1 << 20)),
        coverage_target=args.coverage,
        max_sync_every=args.max_sync_every,
        num_shards=args.shards,
        num_workers=args.workers,
    )

    from fps_tpu.tiering.planner import global_sync_every

    out = {"plan": {n: p.to_json() for n, p in sorted(plans.items())},
           "hot_sync_every": global_sync_every(plans)}
    if not args.json:
        for name, p in sorted(plans.items()):
            print(f"{name}: hot_tier={p.hot_tier} "
                  f"hot_sync_every={p.hot_sync_every} dense={p.dense} "
                  f"cold_budget={p.cold_budget} "
                  f"coverage={p.coverage:.3f}\n    [{p.reason}]",
                  file=sys.stderr)

    if not args.no_lower:
        import jax

        from fps_tpu.analysis import collective_profile
        from fps_tpu.core.store import TableSpec
        from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
        from fps_tpu.tiering.probe import lowered_plan_text

        devs = jax.devices()
        nd, ns = default_mesh_shape(min(len(devs), 8))
        mesh = make_ps_mesh(num_shards=ns, num_data=nd,
                            devices=devs[:nd * ns])
        specs = {name: TableSpec(name, num_ids, dim)
                 for name, num_ids, dim in args.table}

        def profile(plans_arg, E):
            text = lowered_plan_text(mesh, specs, plans_arg,
                                     hot_sync_every=E)
            prof = collective_profile(text)
            return {"collectives": len(prof),
                    "bytes": sum(c.payload_bytes for c in prof)}

        out["predicted"] = profile(plans, global_sync_every(plans))
        out["untiered_baseline"] = profile({}, 1)
        out["mesh"] = dict(mesh.shape)
        if not args.json:
            print(f"predicted per-chunk collective budget: "
                  f"{out['predicted']['collectives']} collectives, "
                  f"{out['predicted']['bytes']} bytes "
                  f"(untiered baseline: "
                  f"{out['untiered_baseline']['collectives']} / "
                  f"{out['untiered_baseline']['bytes']})",
                  file=sys.stderr)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
