"""Staleness sweep: convergence vs (sync_every s, push_delay d) on MF,
SSP logreg, and word2vec, over an 8-worker mesh. Generates the table in
docs/STALENESS.md. The w2v column is a QUALITY metric (planted-synonym
nearest-neighbor partner recovery@5, chance 5/(2*V2)), not loss — stale
embeddings must still resolve the planted semantics.

Run (CPU mesh, like the test suite):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PYTHONPATH=/root/repo python tools/staleness_sweep.py
"""

import sys

import numpy as np

import jax

from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.ingest import multi_epoch_chunks
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
    predict_proba_host,
)
from fps_tpu.models.matrix_factorization import (
    MFConfig,
    online_mf,
    predict_host,
    rmse,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import (
    synthetic_ratings,
    synthetic_sparse_classification,
    train_test_split,
)


def mf_run(mesh, train, test, nu, ni, *, s, d, lr, epochs):
    W = num_workers_of(mesh)
    cfg = MFConfig(num_users=nu, num_items=ni, rank=4, learning_rate=lr,
                   reg=0.005)
    trainer, store = online_mf(mesh, cfg, sync_every=s, push_delay=d)
    t, l = trainer.init_state(jax.random.key(0))
    chunks = multi_epoch_chunks(
        train, epochs, num_workers=W, local_batch=32,
        steps_per_chunk=max(8, s or 0), route_key="user", sync_every=s,
        seed=11,
    )
    t, l, _ = trainer.fit_stream(t, l, chunks, jax.random.key(1))
    pred = predict_host(store, np.asarray(l), W, test["user"], test["item"])
    return rmse(pred, test["rating"])


def logreg_run(mesh, train, test, nf, *, s, d, lr, epochs):
    W = num_workers_of(mesh)
    cfg = LogRegConfig(num_features=nf, learning_rate=lr)
    trainer, store = logistic_regression(mesh, cfg, sync_every=s,
                                         push_delay=d)
    t, l = trainer.init_state(jax.random.key(0))
    chunks = multi_epoch_chunks(
        train, epochs, num_workers=W, local_batch=32,
        steps_per_chunk=max(8, s or 0), sync_every=s, seed=11,
    )
    t, l, _ = trainer.fit_stream(t, l, chunks, jax.random.key(1))
    p = predict_proba_host(store, test["feat_ids"], test["feat_vals"])
    return float(np.mean((p > 0.5) == (test["label"] > 0.5)))


def w2v_run(mesh, tokens, uni, V2, *, s, d, lr, epochs):
    from fps_tpu.models.word2vec import (
        W2VConfig, nearest_neighbors, skipgram_chunks, word2vec,
    )

    W = num_workers_of(mesh)
    cfg = W2VConfig(vocab_size=2 * V2, dim=16, window=3, negatives=4,
                    learning_rate=lr, subsample_t=None)
    trainer, store = word2vec(mesh, cfg, uni, sync_every=s, push_delay=d)
    t, l = trainer.init_state(jax.random.key(0))
    for e in range(epochs):
        chunks = skipgram_chunks(tokens, uni, cfg, num_workers=W,
                                 local_batch=64,
                                 steps_per_chunk=max(8, s or 0),
                                 sync_every=s, seed=11 + e)
        t, l, _ = trainer.fit_stream(t, l, chunks, jax.random.key(e))
    probes = np.argsort(-uni[:V2])[:40]
    ids, _ = nearest_neighbors(store, probes, k=5)
    partner = probes + V2
    return float(np.mean([partner[i] in ids[i] for i in range(len(probes))]))


def main():
    mesh = make_ps_mesh(num_shards=8, num_data=1)

    NU, NI = 96, 64
    mf_data = synthetic_ratings(NU, NI, 6000, rank=3, noise=0.05, seed=3)
    mf_train, mf_test = train_test_split(mf_data)

    NF = 4000
    lg_data = synthetic_sparse_classification(8000, NF, 8, seed=7,
                                              noise=0.05)
    lg_data["label"] = (lg_data["label"] > 0).astype(np.float32)
    lg_train, lg_test = train_test_split(lg_data)

    from fps_tpu.utils.datasets import synthetic_corpus

    V2 = 100
    wrng = np.random.default_rng(17)
    wbase = synthetic_corpus(V2, 40_000, num_topics=8, seed=0)
    wtokens = np.where(wrng.random(len(wbase)) < 0.5, wbase,
                       wbase + V2).astype(np.int32)
    wuni = np.bincount(wtokens, minlength=2 * V2).astype(np.float64)

    # (s, d, lr multiplier, epoch multiplier): the async-SGD recipe — scale
    # the learning rate down and the steps up with the TOTAL staleness.
    grid = [
        (None, 0, 1.0, 1),
        (1, 0, 1.0, 1),
        (4, 0, 1.0, 1),
        (4, 4, 0.5, 2),
        (16, 0, 0.5, 2),
        (16, 16, 0.25, 2),
        (64, 0, 0.25, 4),
        (64, 64, 1 / 16, 4),
    ]
    mf_lr0, mf_ep0 = 0.08, 3
    lg_lr0, lg_ep0 = 0.5, 3
    wv_lr0, wv_ep0 = 0.05, 4

    rows = []
    for s, d, lrm, epm in grid:
        r = mf_run(mesh, mf_train, mf_test, NU, NI, s=s, d=d,
                   lr=mf_lr0 * lrm, epochs=mf_ep0 * epm)
        a = logreg_run(mesh, lg_train, lg_test, NF, s=s, d=d,
                       lr=lg_lr0 * lrm, epochs=lg_ep0 * epm)
        w = w2v_run(mesh, wtokens, wuni, V2, s=s, d=d,
                    lr=wv_lr0 * lrm, epochs=wv_ep0 * epm)
        tag = "sync" if s is None else f"s={s}"
        rows.append((tag, d, lrm, epm, r, a, w))
        print(f"{tag:6s} d={d:3d} lr x{lrm:<5g} ep x{epm}: "
              f"MF test RMSE {r:.4f}   logreg test acc {a:.4f}   "
              f"w2v partner-rec@5 {w:.3f}",
              flush=True)

    print("\n| reads | push delay | lr scale | epochs scale | "
          "MF test RMSE | logreg test acc | w2v partner-rec@5 |")
    print("|---|---|---|---|---|---|---|")
    for tag, d, lrm, epm, r, a, w in rows:
        print(f"| {tag} | {d} | x{lrm:g} | x{epm} | {r:.4f} | {a:.4f} "
              f"| {w:.3f} |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
