"""Run the fps_tpu jax-hazard linter over the tree and report findings.

The CLI over :mod:`fps_tpu.analysis.lint` — the AST layer of the program
contract auditor (``docs/analysis.md``). Rules (FPS001–FPS006): late-
bound closures over loop variables, boolean branches on jnp predicates,
unsorted dict iteration inside compiled-fn builders, thread-starting
classes without a synchronization primitive, internal imports of the
``utils.profiling`` compat shim, and raw ``open()``/``np.load`` of
checkpoint files outside the CRC-verified readers.

CI contract: ``tests/test_lint.py`` runs this over ``fps_tpu/`` as a
tier-1 test expecting ZERO findings — a new hazard fails the suite with
the file:line and the rule's rationale. Suppress a deliberate exception
with ``# noqa: FPSNNN`` on the flagged line (the test suite's norm is
fixes, not suppressions).

No jax import: the linter module is loaded by file path (the
``tools/supervise.py`` pattern), so this runs on a login node in
milliseconds.

Usage:
  python tools/lint.py [PATHS...] [--json] [--select FPS003,FPS005]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_linter():
    """Load fps_tpu/analysis/lint.py WITHOUT importing the fps_tpu
    package (whose __init__ pulls jax)."""
    path = os.path.join(_ROOT, "fps_tpu", "analysis", "lint.py")
    spec = importlib.util.spec_from_file_location("_fps_lint", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fps_tpu jax-hazard source linter (fps_tpu.analysis)")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_ROOT, "fps_tpu")],
                    help="files/directories to lint (default: the "
                         "fps_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON line: findings + rule table")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to enable "
                         "(default: all)")
    ap.add_argument("--explain", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    lint = load_linter()
    if args.explain:
        for rule, why in sorted(lint.RULES.items()):
            print(f"{rule}: {why}")
        return 0
    select = (frozenset(args.select.split(",")) if args.select else None)
    findings = lint.lint_paths(args.paths, select=select)
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "count": len(findings),
            "rules": dict(sorted(lint.RULES.items())),
        }))
    else:
        for f in findings:
            print(f)
        print(f"{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
