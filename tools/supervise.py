"""Supervise a training command: deadline-abort + retry/backoff + quarantine.

The CLI over :class:`fps_tpu.supervise.RunSupervisor` — run a training
child under an external supervisor that aborts it when its heartbeat /
obs journal stalls (SIGTERM → SIGKILL on the process group), restarts it
with exponential backoff from ``latest_valid_step``, and quarantines
chunk/epoch indices that kill consecutive attempts (persisted in
``supervisor_state.json`` under ``--state-dir`` and exported to the child
via the ``FPS_TPU_SUPERVISOR_STATE`` env var).

The child signals progress by either

* running with ``--heartbeat``/``FPS_TPU_HEARTBEAT`` support (every
  example CLI beats per chunk when supervised — ``fps_tpu.examples.common``
  wires it automatically), or
* writing an obs run journal that the supervisor watches via ``--watch``
  (``--watch 'OBSDIR/journal-p*.jsonl'`` — the per-boundary flushes count
  as life).

Usage:
  python tools/supervise.py --state-dir CKPT_DIR [policy flags] -- CMD...

Pod mode (fps_tpu/supervise/pod.py — one failure domain for a
multi-host run): run one such process per host with a SHARED --pod-dir:

  python tools/supervise.py --pod-dir POD --pod-host h0 --pod-size 3 \
      [--elastic] [policy flags] -- CMD...

Members elect a leader over an atomic-rename lease; every
abort/restart/quarantine becomes one pod-wide, epoch-fenced decision
(coordinated restart from the COMMON latest_valid_step; the quarantine
set is merged and broadcast). '{host}' in CMD expands to the member's
host name; the member's state dir (and, by convention, its child's
checkpoint dir) is POD_DIR/HOST. See docs/resilience.md "Pod-level
coordination".

Prints the one-line JSON digest (attempts, restarts, deadline aborts,
quarantined indices, success) and exits 0 only on child success.

No jax import: the supervisor module is loaded by file path, so this
process stays a few-MB pure-python babysitter even when the child owns
every TPU chip on the host.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_supervise_module(name: str):
    """Load fps_tpu/supervise/<name>.py WITHOUT importing the fps_tpu
    package (whose __init__ pulls jax — the supervisor must never drag a
    TPU runtime into this process; same pattern as tests/conftest.py)."""
    path = os.path.join(_ROOT, "fps_tpu", "supervise", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_fps_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    # Registered BEFORE exec: dataclass creation resolves its module via
    # sys.modules on 3.10.
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_supervisor_module():
    return _load_supervise_module("supervisor")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Run a training command under the fps_tpu deadline-abort "
                    "supervisor",
        usage="%(prog)s [flags] -- CMD [ARG...]",
    )
    ap.add_argument("--state-dir", default=None,
                    help="directory for supervisor_state.json, heartbeat, "
                         "supervisor journal, and per-attempt child logs "
                         "(conventionally the checkpoint dir: quarantine "
                         "state lives next to the snapshots it protects). "
                         "Required unless running in pod mode, where the "
                         "member's state dir is POD_DIR/HOST")
    ap.add_argument("--stall-timeout-s", type=float, default=120.0,
                    help="liveness deadline between progress signals")
    ap.add_argument("--startup-grace-s", type=float, default=None,
                    help="deadline for the FIRST signal of each attempt "
                         "(covers interpreter + jax import + XLA compile; "
                         "default: --stall-timeout-s)")
    ap.add_argument("--wall-deadline-s", type=float, default=None,
                    help="whole-run budget across attempts and backoffs")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="retry budget (the first launch is free)")
    ap.add_argument("--backoff-base-s", type=float, default=1.0)
    ap.add_argument("--backoff-factor", type=float, default=2.0)
    ap.add_argument("--backoff-max-s", type=float, default=60.0)
    ap.add_argument("--term-grace-s", type=float, default=5.0,
                    help="seconds between SIGTERM and SIGKILL on abort")
    ap.add_argument("--poll-s", type=float, default=0.25)
    ap.add_argument("--quarantine-after", type=int, default=2,
                    help="consecutive same-index failures before that "
                         "chunk/epoch index is quarantined")
    ap.add_argument("--watch", action="append", default=[],
                    metavar="GLOB",
                    help="file glob whose growth also counts as liveness "
                         "(repeatable; e.g. 'OBSDIR/journal-p*.jsonl')")
    pod = ap.add_argument_group(
        "pod coordination (fps_tpu.supervise.pod)",
        "run this process as ONE member of a pod: all members share "
        "--pod-dir (a shared filesystem), elect a leader over an "
        "atomic-rename lease, and every abort/restart/quarantine becomes "
        "one pod-wide decision. '{host}' in the child command expands to "
        "--pod-host; the member's state dir (and, by convention, its "
        "child's checkpoint dir) is POD_DIR/HOST.")
    pod.add_argument("--pod-dir", default=None,
                     help="shared pod directory (lease, control, pod "
                          "state, per-member subdirs); enables pod mode "
                          "together with --pod-host")
    pod.add_argument("--pod-host", default=None,
                     help="this member's unique host name within the pod")
    pod.add_argument("--pod-size", type=int, default=1,
                     help="number of members forming the pod (the leader "
                          "waits for all of them before the first launch)")
    pod.add_argument("--elastic", action="store_true",
                     help="elastic membership: evict a member whose "
                          "failures exhaust --evict-after (the pod "
                          "re-plans at W-1) and re-admit it when it "
                          "returns")
    pod.add_argument("--lease-ttl-s", type=float, default=5.0,
                     help="leader lease expiry; any member may seize an "
                          "expired lease (fencing epoch bump)")
    pod.add_argument("--member-timeout-s", type=float, default=10.0,
                     help="member-beacon staleness before the leader "
                          "treats that host as unreachable")
    pod.add_argument("--evict-after", type=int, default=2,
                     help="consecutive member failures before eviction "
                          "(elastic pods)")
    pod.add_argument("--readmit-budget", type=int, default=2,
                     help="re-admissions allowed per evicted host")
    pod.add_argument("--rejoin-delay-s", type=float, default=0.5,
                     help="cooldown before an evicted member reports "
                          "ready again")
    ap.add_argument("--compilation-cache-dir", default=None,
                    help="persistent JAX compilation-cache directory "
                         "exported to every child attempt (and every "
                         "pod member) as JAX_COMPILATION_CACHE_DIR: a "
                         "restarted child reloads compiled programs "
                         "from disk instead of retracing, so "
                         "restart-to-first-dispatch (the digest's "
                         "restart_to_first_signal_s) stops paying the "
                         "compile on every recovery")
    ap.add_argument("--pretty", action="store_true",
                    help="indent the digest JSON")
    # Split at the first literal "--" BEFORE parsing: parse_known_args
    # would route a typo'd supervisor flag into the child command and fail
    # later with a raw Popen FileNotFoundError instead of a usage error.
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        cut = argv.index("--")
        argv, cmd = argv[:cut], argv[cut + 1:]
    else:
        cmd = []
    args = ap.parse_args(argv)
    if not cmd:
        ap.error("no child command given (append it after --)")
    if bool(args.pod_dir) != bool(args.pod_host):
        ap.error("--pod-dir and --pod-host must be given together")
    if not args.pod_dir and not args.state_dir:
        ap.error("--state-dir is required outside pod mode")

    extra_env = {}
    if args.compilation_cache_dir:
        cache_dir = os.path.abspath(args.compilation_cache_dir)
        os.makedirs(cache_dir, exist_ok=True)
        extra_env["JAX_COMPILATION_CACHE_DIR"] = cache_dir

    sup_mod = _load_supervisor_module()
    config = sup_mod.SupervisorConfig(
        stall_timeout_s=args.stall_timeout_s,
        startup_grace_s=args.startup_grace_s,
        wall_deadline_s=args.wall_deadline_s,
        max_restarts=args.max_restarts,
        backoff_base_s=args.backoff_base_s,
        backoff_factor=args.backoff_factor,
        backoff_max_s=args.backoff_max_s,
        term_grace_s=args.term_grace_s,
        poll_interval_s=args.poll_s,
        quarantine_after=args.quarantine_after,
    )
    if args.pod_dir:
        pod_mod = _load_supervise_module("pod")
        pod_config = pod_mod.PodConfig(
            pod_size=args.pod_size,
            elastic=args.elastic,
            lease_ttl_s=args.lease_ttl_s,
            member_timeout_s=args.member_timeout_s,
            max_restarts=args.max_restarts,
            evict_after=args.evict_after,
            readmit_budget=args.readmit_budget,
            rejoin_delay_s=args.rejoin_delay_s,
            member=config,
        )
        member = pod_mod.PodMember(
            cmd, pod_dir=args.pod_dir, host=args.pod_host,
            config=pod_config, watch=tuple(args.watch), env=extra_env,
        )
        digest = member.run()
    else:
        supervisor = sup_mod.RunSupervisor(
            cmd, state_dir=args.state_dir, config=config,
            watch=tuple(args.watch), env=extra_env,
        )
        digest = supervisor.run()
    print(json.dumps(digest, indent=2 if args.pretty else None), flush=True)
    return 0 if digest["success"] else 1


if __name__ == "__main__":
    sys.exit(main())
