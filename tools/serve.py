"""Serve a training run's snapshots to query traffic — no jax required.

The CLI over :mod:`fps_tpu.serve` (``docs/serving.md``): point it at a
run's ``--checkpoint-dir`` and it discovers, CRC-verifies, and mmaps the
newest snapshot (``SnapshotWatcher``), answers pull-by-id / scoring /
top-k queries over line-JSON TCP (``TcpServe``), and hot-swaps to every
newer snapshot the trainer publishes — including swapping BACKWARD when
the trainer quarantines the served one. Optionally tails the run's obs
journal (``--journal OBS_DIR``) so new publishes are picked up from
``checkpoint_saved`` events without directory re-stats.

Modes:

* default — serve forever: print one ``{"event": "serving", ...}`` JSON
  line with the bound host/port, then poll every ``--poll-s`` seconds.
* ``--once`` — poll once, print the served manifest (or an error), exit.
* ``--query JSON`` — client mode: connect to ``--host``/``--port``, send
  one request line, print the response. No server is started.

No jax import anywhere on these paths: the fps_tpu package roots are
stubbed (the ``tools/audit_programs.py --hlo`` pattern) so the serving
process stays a few-MB pure-python/numpy reader even on a host whose
training job owns every accelerator — and runs on machines with no
accelerator runtime installed at all (asserted by a jax-poisoned
subprocess test in ``tests/test_serve.py``).

Usage:
  python tools/serve.py CKPT_DIR [--journal OBS_DIR] [--port N]
  python tools/serve.py CKPT_DIR --once
  python tools/serve.py --query '{"op": "stats"}' --port N
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import types

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_serve():
    """Import ``fps_tpu.serve`` WITHOUT executing ``fps_tpu/__init__`` or
    ``fps_tpu/core/__init__`` (both pull jax): stub root packages whose
    ``__path__`` points at the real directories, then import the
    subpackage normally — serve, core.snapshot_format, and obs are all
    stdlib+numpy."""
    for name, sub in (("fps_tpu", ()), ("fps_tpu.core", ("core",))):
        if name not in sys.modules:
            stub = types.ModuleType(name)
            stub.__path__ = [os.path.join(_ROOT, "fps_tpu", *sub)]
            sys.modules[name] = stub
    return importlib.import_module("fps_tpu.serve")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Serve an fps_tpu run's snapshots over line-JSON TCP "
                    "(fps_tpu.serve; jax-free)")
    ap.add_argument("ckpt_dir", nargs="?", default=None,
                    help="the run's --checkpoint-dir (required unless "
                         "--query)")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="obs journal file or --obs-dir directory to tail "
                         "for checkpoint_saved events (the directory poll "
                         "stays on as the source of truth)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="TCP port (0 = ephemeral; the bound port is "
                         "printed in the 'serving' line)")
    ap.add_argument("--poll-s", type=float, default=0.5,
                    help="snapshot discovery poll interval")
    ap.add_argument("--max-polls", type=int, default=None,
                    help="stop after this many polls (tests; default: "
                         "run until interrupted)")
    ap.add_argument("--once", action="store_true",
                    help="poll once, print the served manifest, exit "
                         "(no TCP)")
    ap.add_argument("--query", default=None, metavar="JSON",
                    help="client mode: send one request line to "
                         "--host/--port and print the response")
    args = ap.parse_args(argv)

    if args.query is not None:
        serve = load_serve()
        if not args.port:
            ap.error("--query needs --port")
        with serve.JsonlClient(args.host, args.port) as client:
            print(json.dumps(client.request(json.loads(args.query))))
        return 0

    if args.ckpt_dir is None:
        ap.error("ckpt_dir is required (or use --query)")
    serve = load_serve()
    server, watcher = serve.ReadServer.over(args.ckpt_dir,
                                            journal=args.journal)
    if args.once:
        if watcher.current is None:
            print(json.dumps({"event": "no_snapshot",
                              "ckpt_dir": args.ckpt_dir,
                              "rejected": watcher.rejected}))
            return 1
        print(json.dumps({"event": "manifest",
                          **watcher.current.manifest(),
                          "rejected": watcher.rejected}))
        return 0

    with serve.TcpServe(server, host=args.host, port=args.port) as tcp:
        print(json.dumps({
            "event": "serving", "host": tcp.host, "port": tcp.port,
            "ckpt_dir": os.path.abspath(args.ckpt_dir),
            "step": None if watcher.current is None
            else watcher.current.step,
        }), flush=True)
        try:
            watcher.run(interval_s=args.poll_s, max_polls=args.max_polls)
        except KeyboardInterrupt:
            pass
    stats = server.stats()
    stats.update(swaps=dict(watcher.swaps), rejected=watcher.rejected,
                 write_to_servable_s=watcher.write_to_servable_s)
    print(json.dumps({"event": "served", **stats}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
