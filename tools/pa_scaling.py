"""Relative weak-scaling curve for passive-aggressive on the virtual mesh.

The single-chip PA-I headline now beats the measured native `ps` baseline
(BENCH r4), but the framework's structural case for PA on TPU has always
been data-parallel scale-out (BASELINE.md): per-example closed-form steps
with a tiny L2-resident model are the sequential loop's best case, while
the PS path amortizes per-row transactions across workers. This tool
MEASURES that claim's shape: examples/s vs W ∈ {1, 2, 4, 8} workers at a
FIXED per-worker batch (weak scaling — total work grows with W) on the
8-virtual-CPU-device mesh (the same fabric the test suite and the
multichip dryrun use; absolute CPU numbers are meaningless, the RELATIVE
curve is the artifact).

Run from /root/repo:  python tools/pa_scaling.py
Re-execs itself into a cleaned 8-device CPU subprocess when needed.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import numpy as np

# `python tools/pa_scaling.py` puts tools/ (not the repo root) on sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PER_WORKER_EX = 65_536
LOCAL_BATCH = 4_096
NF, NNZ = 47_236, 64


def run_curve(route: str):
    import dataclasses

    import jax

    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.passive_aggressive import (
        PAConfig, WEIGHT_TABLE, passive_aggressive,
    )
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_sparse_classification

    devs = jax.devices()
    results = []
    print(f"--- route: {route} ---", flush=True)
    for W in (1, 2, 4, 8):
        if W > len(devs):
            break
        mesh = make_ps_mesh(num_shards=W, num_data=1, devices=devs[:W])
        assert num_workers_of(mesh) == W
        nex = PER_WORKER_EX * W
        data = synthetic_sparse_classification(nex, NF, NNZ, seed=3,
                                               noise=0.05)
        cfg = PAConfig(num_features=NF, variant="PA-I", C=1.0)
        trainer, store = passive_aggressive(mesh, cfg,
                                            max_steps_per_call=8)
        if route != "auto":
            store.specs[WEIGHT_TABLE] = dataclasses.replace(
                store.specs[WEIGHT_TABLE],
                dense_collectives=(route == "dense"),
            )
        tables, ls = trainer.init_state(jax.random.key(0))
        ds = DeviceDataset(mesh, data)
        plan = DeviceEpochPlan(ds, num_workers=W, local_batch=LOCAL_BATCH,
                               seed=1)
        # warm (compile), then best-of-3 timed epochs
        tables, ls, _ = trainer.run_indexed(tables, ls, plan,
                                            jax.random.key(9))
        best = 1e9
        for r in range(3):
            t0 = time.perf_counter()
            tables, ls, m = trainer.run_indexed(tables, ls, plan,
                                                jax.random.key(1 + r))
            best = min(best, time.perf_counter() - t0)
        ex_s = nex / best
        results.append((W, ex_s))
        base = results[0][1]
        # All W virtual devices share the same host cores, so aggregate
        # ex/s CANNOT rise with W here; what the curve measures is TOTAL
        # WORK PER EXAMPLE (= base_rate / rate): flat aggregate rate at
        # W-fold work means per-example work is constant in W — the
        # property that turns into linear scale-out on physical chips.
        print(
            f"W={W}: {ex_s:12.0f} ex/s aggregate  "
            f"(x{ex_s / base:4.2f} of W=1)  "
            f"work/example x{base / ex_s:5.2f}",
            flush=True,
        )
    return results


def main():
    import jax

    from fps_tpu.utils.hostenv import cpu_mesh_env, reexec_count

    routes = sys.argv[1:] or ["dense", "gathered"]
    bad = [r for r in routes if r not in ("auto", "dense", "gathered")]
    if bad:
        raise SystemExit(f"unknown route(s) {bad!r} — choose from "
                         "auto / dense / gathered")
    if len(jax.devices()) >= 8:
        for route in routes:
            run_curve(route)
        return
    if reexec_count() >= 8:
        raise RuntimeError("re-exec failed to provide 8 devices")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = cpu_mesh_env(8)
    env["PYTHONPATH"] = os.pathsep.join(
        [root] + [p for p in env["PYTHONPATH"].split(os.pathsep) if p]
    )
    subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
        env=env, cwd=root, check=True,
    )


if __name__ == "__main__":
    main()
