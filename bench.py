"""Headline benchmarks vs a MEASURED sequential-baseline, on the real TPU.

BASELINE.json metric: "MovieLens-20M MF epoch time; text8 word2vec
words/sec/chip". The reference publishes no numbers (``"published": {}``)
and its Flink/JVM stack cannot run in this image, so every ``vs_baseline``
here is computed against a *measured, compiled* stand-in rather than a
guessed constant: ``fps_tpu/native/src/fps_native.cc`` implements the
reference's sequential per-record parameter-server hot loops (MF
pull→SGD→push, per-pair SGNS, per-feature sparse logreg) in C++ in two
modes, both strictly generous to the reference:

* ``ps``    — every pull request / pull response / push delta pays a real
  message hop (noinline memcpy through a bounded ring), the cheapest
  possible model of the reference's Flink operator hops (no JVM, no
  serialization framework, no network). ``vs_baseline`` is measured
  against THIS mode: same architecture, zero framework overhead.
* ``ideal`` — the fused sequential loop with direct array access, a floor
  no real deployment reaches. Reported alongside (``baseline`` field) for
  full honesty; on transaction-bound single-chip workloads (rank-10 MF,
  scalar-table logreg) it is genuinely competitive — see BASELINE.md's
  roofline discussion.

Default (no args) runs ALL workloads and prints one JSON line per
workload — w2v, logreg, ials first, the headline MF line LAST:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
vs_baseline > 1 means this framework is faster than the measured baseline.

* ``mf``     — ML-20M-scale MF **wall-clock to train-RMSE <= 0.12**
  (planted-structure noise floor ~0.1) vs the native loop's OWN measured
  time-to-the-same-target (it converges in fewer epochs — sequential SGD
  is the per-epoch gold standard — and pays that credit honestly).
* ``w2v``    — text8-scale SGNS words/sec/chip vs the native per-pair
  loop's words/sec on the same pair distribution.
* ``logreg`` — Criteo-scale SSP logreg examples/sec/chip vs the native
  per-example fan-out loop.
* ``ials``   — planted-implicit time to recall@20 >= 0.35 (plateau ~0.39;
  no reference baseline exists: iALS is a required extension the
  reference lacks).

Compile time is excluded everywhere via a warm-up pass on throwaway
state; each workload also prints a learning-evidence line on stderr
(NaN/flat = diverged — treat as failure regardless of speed).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def attach_phase_recorder(trainer):
    """Sink-less obs recorder on the trainer for the TIMED region: the
    per-workload JSON gains a ``phases`` breakdown (dispatch / host_sync /
    checkpoint seconds+counts), so a BENCH regression is attributable to
    a phase instead of one opaque wall-clock number. Aggregates-only (no
    sinks, no extra host syncs) — the recorder never changes the driver's
    sync behavior, so the measured numbers are unaffected."""
    from fps_tpu import obs

    rec = obs.Recorder(sinks=[])
    trainer.recorder = rec
    return rec


def phase_summary(rec):
    return {ph: {"s": round(v["s"], 4), "n": v["n"]}
            for ph, v in sorted(rec.phase_totals().items())}


# Driver-thread phases that serialize against dispatch — the host work
# the overlapped pipeline (fps_tpu.core.prefetch) moves off the critical
# path. 'prefetch' itself is worker-thread time and deliberately NOT in
# this sum: it overlaps the phases below. 'reconcile' is the two-tier
# re-split at run entry (once per run, host-side).
HOST_SERIAL_PHASES = ("ingest", "place", "host_sync", "checkpoint",
                      "callback", "reconcile")


# ---------------------------------------------------------------------------
# Cross-shard collective accounting (two-tier A/B evidence).
#
# The implementation grew into the static-analysis subsystem
# (fps_tpu.analysis — HloProgram model + contract pass suite);
# count_collectives is re-exported here for backward compatibility, and
# collective_profile is its structured form: one (kind, payload_bytes,
# replica_groups) entry per qualifying collective, so the A/B can report
# payload BYTES moved per chunk alongside the op count.
# ---------------------------------------------------------------------------

from fps_tpu.analysis import (  # noqa: F401  (count_collectives: re-export)
    collective_profile,
    count_collectives,
)


def host_pipeline_ab(trainer, init_state, make_chunks, *, depth=2):
    """A/B the fit_stream host pipeline on one workload.

    Runs the SAME chunk stream twice — background prefetch+place pipeline
    off, then on (fresh state each arm, shared compiled program) — and
    reports wall-clock, the per-phase breakdown, and the host-serial
    share of wall-clock for both arms, plus per-phase and overall overlap
    ratios. The BENCH trajectory's acceptance signal: host_serial_share
    must strictly drop from ``off`` to ``on`` (the chunks are
    bit-identical either way, so nothing else may move)."""
    import dataclasses

    import jax

    from fps_tpu import obs

    out = {"prefetch_depth": depth}
    base, base_rec = trainer.config, trainer.recorder
    try:
        for label, pf in (("off", 0), ("on", depth)):
            trainer.config = dataclasses.replace(base, prefetch=pf)
            rec = obs.Recorder(sinks=[])
            trainer.recorder = rec
            tables, ls = init_state()
            t0 = time.perf_counter()
            trainer.fit_stream(tables, ls, make_chunks(), jax.random.key(1))
            wall = time.perf_counter() - t0
            phases = {ph: round(v["s"], 4)
                      for ph, v in sorted(rec.phase_totals().items())}
            serial = sum(phases.get(ph, 0.0) for ph in HOST_SERIAL_PHASES)
            out[label] = {
                "wall_s": round(wall, 4),
                "host_serial_s": round(serial, 4),
                "host_serial_share": (round(serial / wall, 4) if wall
                                      else None),
                "phases": phases,
            }
    finally:
        trainer.config = base
        trainer.recorder = base_rec
    off, on = out["off"], out["on"]
    out["overlap_ratio"] = (
        round(1.0 - on["host_serial_s"] / off["host_serial_s"], 4)
        if off["host_serial_s"] > 0 else None)
    out["phase_overlap"] = {
        ph: round(1.0 - on["phases"].get(ph, 0.0) / v, 4)
        for ph, v in off["phases"].items()
        if ph in HOST_SERIAL_PHASES and v > 1e-9
    }
    out["speedup"] = (round(off["wall_s"] / on["wall_s"], 3)
                      if on["wall_s"] else None)
    return out


def first_last_real_step(metrics, key):
    """Per-example metric value at the first and last non-padding step of
    one epoch's metrics dict (trailing steps are weight-0 padding)."""
    vals = np.asarray(metrics[key])
    counts = np.asarray(metrics["n"])
    real = np.flatnonzero(counts > 0)
    if len(real) == 0:  # degenerate shard: every step was padding
        return float("nan"), float("nan")
    return (vals[real[0]] / counts[real[0]],
            vals[real[-1]] / counts[real[-1]])


def _time_to_target(per_epoch_s, curve, target):
    """Baseline time-to-target: median epoch seconds x epochs needed.
    The median (not the raw cumsum) makes the BASELINE's number robust to
    transient host contention from the preceding TPU workload — raw first
    -epoch spikes would inflate the baseline and flatter ``vs_baseline``.
    (Our own side always reports its raw measured wall-clock.) Returns
    ``(seconds, epochs)`` or ``(None, None)`` if the target is never hit."""
    import statistics

    for e, v in enumerate(curve):
        if v <= target:
            return statistics.median(per_epoch_s) * (e + 1), e + 1
    return None, None


def _rate_baseline(base_by_mode, kind, unit, our_rate, quality_by_mode):
    """Assemble the JSON ``baseline`` dict + ``vs_baseline`` for a
    rate-metric workload (logreg, pa) from per-mode measured rates, and
    print the per-mode stderr lines. Shared so the baseline JSON shape and
    report format cannot drift between workloads."""
    baseline = {"kind": "unavailable"}
    vs = None
    for label, rate in base_by_mode.items():
        if label == "ps":
            baseline = {"kind": kind, f"ps_{unit}_per_s": round(rate, 1)}
            vs = round(our_rate / rate, 2)
        else:
            baseline[f"ideal_{unit}_per_s"] = round(rate, 1)
        print(f"native baseline [{label}]: {1e9 / rate:.0f} ns/{unit[:-1]} "
              f"({rate / 1e6:.2f}M {unit}/s), "
              f"{quality_by_mode[label]}", file=sys.stderr)
    return baseline, vs


def _measure_native_modes(thunk):
    """Yield ``(label, result)`` for the ``ps`` then ``ideal`` native
    baseline modes, best-of-2 each: transient host contention from the
    preceding TPU dispatch must not inflate the baseline (min = least
    -contended, i.e. most favorable to the reference). Stops silently if
    the native library is unavailable (result None)."""
    for label, ps_mode in (("ps", True), ("ideal", False)):
        res = min((thunk(ps_mode) for _ in range(2)),
                  key=lambda r: r[0] if r else float("inf"))
        if res is None:
            return
        yield label, res


# ---------------------------------------------------------------------------
# Matrix factorization (headline)
# ---------------------------------------------------------------------------

def run_mf(args):
    import statistics

    import jax

    from fps_tpu import native
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import load_movielens

    data, nu, ni = load_movielens(args.movielens_path, args.scale)
    nr = len(data["user"])
    target = args.rmse_target
    LR, REG = 0.1, 0.01

    # MEASURED baseline FIRST, before any TPU work: the process is quiet
    # here, so the sequential loop gets its least-contended (most
    # favorable) timing window. The native loop runs the SAME ratings with
    # the SAME hyperparameters to the SAME target on its own online-RMSE
    # curve; per-epoch times are element-wise min'd over two runs
    # (host-contention noise on this shared VM swings single-run epochs by
    # ~1.5x). The baseline gets the same --max-epochs search budget as our
    # side — a stricter --rmse-target must not silently drop the
    # comparison by under-searching the baseline.
    baseline = {"kind": "unavailable"}
    base_tt = {}
    for label, ps_mode in (("ps", True), ("ideal", False)):
        # Early-stop schedule: at the shared lr the sequential loop reaches
        # the default target inside 3 epochs; only a stricter --rmse-target
        # pays for the full --max-epochs search (wall-clock matters — the
        # driver runs all five workloads in one bench invocation).
        for budget in (min(3, args.max_epochs), args.max_epochs):
            runs = [native.baseline_mf(
                data["user"], data["item"], data["rating"], nu, ni,
                rank=args.rank, lr=LR, reg=REG, seed=0,
                epochs=budget, ps_mode=ps_mode,
            ) for _ in range(2)]
            if any(r is None for r in runs):
                runs = None
                break
            curve = [m ** 0.5 for m in runs[0][1]]
            if any(r <= target for r in curve) or budget >= args.max_epochs:
                break
        if runs is None:
            break
        secs = [min(a, b) for a, b in zip(runs[0][0], runs[1][0])]
        tt, _ = _time_to_target(secs, curve, target)
        base_tt[label] = tt
        if label == "ps":
            baseline = {
                "kind": "measured native sequential PS loop (message-hop "
                        "mode); 'ideal' = fused-loop floor",
                "ps_time_to_target_s": round(tt, 3) if tt else None,
                "ps_epoch_s": round(float(np.median(secs)), 4),
            }
        else:
            baseline["ideal_time_to_target_s"] = round(tt, 3) if tt else None
            baseline["ideal_epoch_s"] = round(float(np.median(secs)), 4)
        print(f"native baseline [{label}]: epoch_s="
              f"{[round(s, 3) for s in secs]} rmse="
              f"{[round(r, 4) for r in curve]}", file=sys.stderr)

    devs = jax.devices()
    nd, ns = default_mesh_shape(len(devs))
    mesh = make_ps_mesh(num_shards=ns, num_data=nd)
    W = num_workers_of(mesh)

    # LR=0.1 is the shared operating point for BOTH systems (measured
    # sweep, round 3): at this noise floor it converges in 3 epochs for
    # ours AND the native sequential loop (vs 5 and 4 at the old 0.05),
    # stable across shuffle seeds; both sides always run the SAME
    # hyperparameters, so the comparison never rests on asymmetric tuning.
    cfg = MFConfig(num_users=nu, num_items=ni, rank=args.rank,
                   learning_rate=LR, reg=REG)
    # Per-id mean combine: at this batch size summed duplicate updates on
    # Zipfian-hot items diverge (the quality line below would show NaN);
    # mean-combine is the reference's combining-sender analog and learns
    # stably at any batch size.
    trainer, store = online_mf(mesh, cfg, combine="mean")
    dataset = DeviceDataset(mesh, data)  # one-time upload, outside the epoch
    plan = DeviceEpochPlan(
        dataset,
        num_workers=W,
        local_batch=args.local_batch,
        route_key="user",
        seed=1,
    )

    # Warm-up: compile + one full epoch on throwaway state (ingest is fused
    # into the jit, so the whole epoch — shuffle, batch gathers, training —
    # is ONE dispatch). The timed run below reuses the compiled program on
    # FRESH state: time-to-quality excludes one-time compilation.
    tables, local_state = trainer.init_state(jax.random.key(0))
    trainer.run_indexed(tables, local_state, plan, jax.random.key(9))

    tables, local_state = trainer.init_state(jax.random.key(0))
    rec = attach_phase_recorder(trainer)  # timed region only (post-warmup)
    epoch_times, rmse_curve = [], []
    # Speculative epoch pipelining: dispatch epoch e+1 BEFORE blocking on
    # epoch e's metrics, so the ~0.1-0.3 s per-epoch dispatch + sync round
    # trip overlaps device execution instead of serializing between
    # epochs. Epochs execute in order on the chip, so blocking on epoch
    # e's metrics returns exactly when e finishes — the recorded
    # time-to-target is unchanged in meaning, and the one speculative
    # epoch in flight at the stop point is simply discarded.
    t_start = time.perf_counter()
    t_prev = t_start
    pending = []  # device metrics dicts of not-yet-evaluated epochs

    def eval_oldest():
        """Block on the oldest pending epoch's (se, n) — ONE fetch round
        trip — and record its RMSE and wall time."""
        nonlocal t_prev
        md = pending.pop(0)
        se, n = jax.device_get((md["se"], md["n"]))
        rmse_e = float(np.sqrt(se.sum() / max(float(n.sum()), 1.0)))
        now = time.perf_counter()
        epoch_times.append(now - t_prev)
        t_prev = now
        rmse_curve.append(rmse_e)
        return rmse_e

    for e in range(args.max_epochs):
        tables, local_state, m = trainer.run_indexed(
            tables, local_state, plan, jax.random.key(1),
            epochs=1, start_epoch=e, as_numpy=False,
        )
        pending.append(m[0])
        if e == 0:
            continue  # keep one epoch in flight before evaluating
        if eval_oldest() <= target:
            break
    while pending and (not rmse_curve or rmse_curve[-1] > target):
        eval_oldest()
    total_s = sum(epoch_times)
    epochs = len(epoch_times)
    median_epoch = statistics.median(epoch_times)
    reached = rmse_curve[-1] <= target
    # Speculative pipelining: when the target is hit with an epoch still in
    # flight, that epoch's updates are already in `tables` — the post-loop
    # state reflects up to epochs+1 training passes, while timing/quality
    # cover exactly `epochs`. Only timing + rmse_curve are reported here;
    # anyone consuming the final state (export, extra eval) must account
    # for the extra pass — hence the explicit flag in the summary.
    state_extra_epochs = len(pending)

    vs = None
    if base_tt.get("ps") is not None and reached:
        vs = round(base_tt["ps"] / total_s, 2)

    # Host-pipeline A/B on the HOST-ingest path (fit_stream +
    # epoch_chunks): per-chunk numpy assembly + upload is exactly the
    # serial host work the overlapped pipeline hides, and the fused
    # run_indexed numbers above cannot show it. Bounded chunk budget so
    # the A/B stays a small fraction of the headline run.
    from itertools import islice

    from fps_tpu.core.ingest import epoch_chunks

    def ab_chunks(n=12):
        return islice(
            epoch_chunks(data, num_workers=W, local_batch=args.local_batch,
                         steps_per_chunk=8, route_key="user", seed=5),
            n)

    trainer.recorder = None  # keep the headline phases breakdown clean
    wt, wl = trainer.init_state(jax.random.key(7))
    trainer.fit_stream(wt, wl, ab_chunks(2), jax.random.key(8))  # compile
    host_pipeline = host_pipeline_ab(
        trainer, lambda: trainer.init_state(jax.random.key(0)), ab_chunks)

    print(
        "quality: per-epoch train RMSE "
        + " -> ".join(f"{r:.4f}" for r in rmse_curve)
        + (f" (reached <= {target})" if reached
           else f" (STOPPED at max_epochs={args.max_epochs} without "
                f"reaching {target})"),
        file=sys.stderr,
    )
    print(f"epoch times: {[round(t, 3) for t in epoch_times]} s "
          f"(median {median_epoch:.4f})", file=sys.stderr)

    return {
        "metric": f"ml{args.scale}_mf_time_to_rmse_{target}",
        "value": round(total_s, 4),
        "unit": "s",
        "vs_baseline": vs,
        "epochs": epochs,
        "median_epoch_s": round(median_epoch, 4),
        "final_train_rmse": round(rmse_curve[-1], 4),
        "reached": reached,
        "state_extra_epochs": state_extra_epochs,
        "phases": phase_summary(rec),
        "host_pipeline": host_pipeline,
        "baseline": baseline,
    }


# ---------------------------------------------------------------------------
# word2vec SGNS
# ---------------------------------------------------------------------------

def run_w2v(args):
    import jax

    from fps_tpu import native
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.word2vec import (
        W2VConfig, Word2VecDevicePlan, _keep_probs, word2vec_block,
    )
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import load_text8

    tokens, V, uni = load_text8(
        args.text8_path, vocab_size=50_000, num_tokens=args.num_tokens
    )
    devs = jax.devices()
    nd, ns = default_mesh_shape(len(devs))
    mesh = make_ps_mesh(num_shards=ns, num_data=nd)
    W = num_workers_of(mesh)

    cfg = W2VConfig(vocab_size=V, dim=args.dim, window=5, negatives=5)
    # Block-granularity worker: each block position's IN/OUT row is pulled
    # and pushed once per step (sparse row ops are per-transaction bound on
    # TPU — this is ~10x fewer transactions than per-pair pull/push).
    # Cap each dispatch well under the TPU runtime's per-dispatch deadline.
    trainer, store = word2vec_block(
        mesh, cfg, uni, args.block_len, max_steps_per_call=256
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    plan = Word2VecDevicePlan(
        tokens, uni, cfg, mesh, num_workers=W,
        block_len=args.block_len, seed=1, mode="block",
    )

    # MEASURED baseline FIRST (quiet pre-TPU window — host contention from
    # device dispatch must not inflate the baseline's per-pair cost):
    # native per-pair SGNS over a representative pair sample from the same
    # generator/distribution. Converted to words/s AFTER the epoch runs,
    # via the epoch's actual pair count.
    per_pair_ns = {}
    loss_by_mode = {}
    keep_p = _keep_probs(cfg, uni).astype(np.float32)
    sample = native.skipgram_pairs(
        np.ascontiguousarray(tokens[:2_000_000]), cfg.window, 3,
        keep_p=keep_p,
    )
    if sample is not None:
        c, x = sample
        m_pairs = min(len(c), 1_500_000)
        for label, (secs, loss) in _measure_native_modes(
            lambda m: native.baseline_w2v(
                c[:m_pairs], x[:m_pairs], uni, dim=cfg.dim,
                negatives=cfg.negatives, lr=cfg.learning_rate, ps_mode=m,
            )
        ):
            per_pair_ns[label] = secs / m_pairs
            loss_by_mode[label] = loss

    # Warm-up epoch: compiles the fused program.
    tables, ls, m = trainer.run_indexed(tables, ls, plan, jax.random.key(9))

    rec = attach_phase_recorder(trainer)  # timed region only (post-warmup)
    t0 = time.perf_counter()
    tables, ls, metrics = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1)
    )
    epoch_s = time.perf_counter() - t0
    words_s = len(tokens) / epoch_s / len(devs)  # per chip

    per0, per1 = first_last_real_step(metrics[0], "loss")
    print(
        f"quality: SGNS loss/pair step0 {per0:.4f} -> last-real-step "
        f"{per1:.4f} (epoch 2; init loss = (1+K)*log2 = "
        f"{0.6931 * (1 + cfg.negatives):.3f})",
        file=sys.stderr,
    )

    # metrics "n" counts PAIRS (the quality line above compares loss/n to
    # the (1+K)*log2 per-pair init loss), so no (1+K) rescale here.
    pairs = float(metrics[0]["n"].sum())
    baseline = {"kind": "unavailable"}
    vs = None
    for label, per_pair in per_pair_ns.items():
        base_words_s = len(tokens) / (pairs * per_pair)
        if label == "ps":
            baseline = {
                "kind": "measured native sequential per-pair SGNS "
                        "(message-hop mode); 'ideal' = fused floor",
                "ps_words_per_s": round(base_words_s, 1),
            }
            vs = round(words_s / base_words_s, 2)
        else:
            baseline["ideal_words_per_s"] = round(base_words_s, 1)
        print(f"native baseline [{label}]: {per_pair * 1e9:.0f} ns/pair"
              f" ({base_words_s / 1e3:.0f}k words/s), loss "
              f"{loss_by_mode[label]:.4f}", file=sys.stderr)

    return {
        "metric": "text8_w2v_words_per_sec_per_chip",
        "value": round(words_s, 1),
        "unit": "words/s",
        "vs_baseline": vs,
        "epoch_s": round(epoch_s, 3),
        "phases": phase_summary(rec),
        "baseline": baseline,
    }


# ---------------------------------------------------------------------------
# SSP logistic regression
# ---------------------------------------------------------------------------

def run_logreg(args):
    """Criteo-style bounded-staleness (SSP) logistic regression throughput."""
    import jax

    from fps_tpu import native
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.logistic_regression import (
        LogRegConfig, logistic_regression,
    )
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import (
        load_sparse, synthetic_sparse_classification,
    )

    NF, NNZ, NEX = 1_000_000, 39, 4_000_000  # Criteo-ish shape
    DENSE = 13  # Criteo's numeric columns, fixed-slot (id j at slot j)
    if args.input:
        from fps_tpu.utils.datasets import sniff_sparse_format

        fmt = sniff_sparse_format(args.input)  # sniff ONCE, pass through
        data, NF = load_sparse(args.input, fmt=fmt, num_features=NF)
        NEX, NNZ = data["feat_ids"].shape
        # Only the Criteo TSV loader guarantees the fixed-slot head.
        if fmt != "criteo":
            DENSE = 0
    else:
        data = synthetic_sparse_classification(NEX, NF, NNZ, seed=0,
                                               noise=0.05,
                                               dense_features=DENSE)
    data = dict(data, label=(data["label"] > 0).astype(np.float32))

    LR = 0.1
    # MEASURED baseline FIRST (quiet pre-TPU window): native per-example
    # fan-out loop on a sample of the same dataset (the reference pulls
    # and pushes each active feature individually — dense or not).
    m_ex = min(NEX, 500_000)
    base_ex_s = {}
    loss_by_mode = {}
    for label, (secs, loss) in _measure_native_modes(
        lambda m: native.baseline_logreg(
            data["feat_ids"][:m_ex], data["feat_vals"][:m_ex],
            data["label"][:m_ex], NF, lr=LR, ps_mode=m,
        )
    ):
        base_ex_s[label] = m_ex / secs
        loss_by_mode[label] = loss

    devs = jax.devices()
    nd, ns = default_mesh_shape(len(devs))
    mesh = make_ps_mesh(num_shards=ns, num_data=nd)
    W = num_workers_of(mesh)
    # dense_features: the 13 numeric weights ride one static pull and one
    # batch-combined push per step instead of 13 scatter rows per example
    # (the fixed-slot layout contract; see LogRegConfig).
    cfg = LogRegConfig(num_features=NF, learning_rate=LR,
                       dense_features=DENSE)
    trainer, store = logistic_regression(
        mesh, cfg, sync_every=8, max_steps_per_call=256
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    ds = DeviceDataset(mesh, data)
    plan = DeviceEpochPlan(
        ds, num_workers=W, local_batch=16384, sync_every=8, seed=1
    )

    tables, ls, _ = trainer.run_indexed(tables, ls, plan, jax.random.key(9))
    rec = attach_phase_recorder(trainer)  # timed region only (post-warmup)
    # Steady-state throughput over E back-to-back epochs (see run_pa).
    E = 2
    t0 = time.perf_counter()
    tables, ls, metrics = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1), epochs=E, as_numpy=False,
    )
    np.asarray(metrics[-1]["n"])
    epoch_s = (time.perf_counter() - t0) / E
    ex_s = NEX / epoch_s / len(devs)

    per0, _ = first_last_real_step(metrics[0], "logloss")
    _, per1 = first_last_real_step(metrics[-1], "logloss")
    print(
        f"quality: logloss step0 {per0:.4f} (epoch 2) -> last-real-step "
        f"{per1:.4f} (epoch {E + 1}; chance = 0.693)",
        file=sys.stderr,
    )

    # MEASURED baseline: native per-example fan-out loop on a sample of the
    # same dataset (the reference pulls/pushes each feature individually).
    baseline, vs = _rate_baseline(
        base_ex_s,
        "measured native sequential per-feature-fan-out logreg "
        "(message-hop mode); 'ideal' = fused floor",
        "examples", ex_s,
        {k: f"logloss {v:.4f}" for k, v in loss_by_mode.items()},
    )

    # Host-pipeline A/B on the host-ingest SSP path (see run_mf). A
    # smaller local batch keeps the per-chunk assembly cost (the thing
    # being overlapped) a sane fraction of each chunk.
    from itertools import islice

    from fps_tpu.core.ingest import epoch_chunks

    def ab_chunks(n=12):
        return islice(
            epoch_chunks(data, num_workers=W, local_batch=4096,
                         steps_per_chunk=8, sync_every=8, seed=5),
            n)

    trainer.recorder = None  # keep the headline phases breakdown clean
    wt, wl = trainer.init_state(jax.random.key(7))
    trainer.fit_stream(wt, wl, ab_chunks(2), jax.random.key(8))  # compile
    host_pipeline = host_pipeline_ab(
        trainer, lambda: trainer.init_state(jax.random.key(0)), ab_chunks)

    return {
        "metric": "criteo_ssp_logreg_examples_per_sec_per_chip",
        "value": round(ex_s, 1),
        "unit": "examples/s",
        "vs_baseline": vs,
        "epoch_s": round(epoch_s, 3),
        "steady_state_epochs": E,
        "phases": phase_summary(rec),
        "host_pipeline": host_pipeline,
        "baseline": baseline,
    }


# ---------------------------------------------------------------------------
# Passive-aggressive (RCV1-scale binary, PA-I)
# ---------------------------------------------------------------------------

def run_pa(args):
    """RCV1-scale binary passive-aggressive throughput (PA-I closed form)."""
    import jax

    from fps_tpu import native
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.passive_aggressive import (
        PAConfig, passive_aggressive,
    )
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import (
        load_sparse, synthetic_sparse_classification,
    )

    # RCV1 shape: 47236 features, ~76 nonzeros/doc, ~800k docs.
    NF, NNZ, NEX = 47_236, 64, 800_000
    if args.input:
        data, NF = load_sparse(args.input, num_features=NF)
        NEX, NNZ = data["feat_ids"].shape
    else:
        data = synthetic_sparse_classification(NEX, NF, NNZ, seed=3,
                                               noise=0.05)
    # PA (model and native baseline alike) requires labels in {-1,+1};
    # svmlight files commonly carry 0/1, which would pin the hinge at 1.0
    # for negative rows. (run_logreg's analog maps to {0,1} instead —
    # logloss wants probabilities, hinge wants signs.)
    data = dict(data, label=np.where(data["label"] > 0, 1.0,
                                     -1.0).astype(np.float32))

    C = 1.0
    # MEASURED baseline FIRST (quiet pre-TPU window).
    m_ex = min(NEX, 400_000)
    base_ex_s = {}
    quality = {}
    for label, res in _measure_native_modes(
        lambda m: native.baseline_pa(
            data["feat_ids"][:m_ex], data["feat_vals"][:m_ex],
            data["label"][:m_ex], NF, C=C, variant="PA-I", ps_mode=m,
        )
    ):
        secs, hinge, mist = res
        base_ex_s[label] = m_ex / secs
        quality[label] = (hinge, mist)

    # Multiclass baseline in the same quiet pre-TPU window (the 20-class
    # sequential closed-form loop, fps_baseline_pa_mc) on the SAME data the
    # TPU multiclass run will train on.
    from fps_tpu.utils.datasets import synthetic_sparse_multiclass

    NCLS, NEX_MC = 20, 200_000
    mdata = synthetic_sparse_multiclass(NEX_MC, NF, NCLS, NNZ, seed=5)
    mc_base_ex_s = {}
    mc_quality = {}
    for label, res in _measure_native_modes(
        lambda m: native.baseline_pa_mc(
            mdata["feat_ids"], mdata["feat_vals"], mdata["label"], NF, NCLS,
            C=C, variant="PA-I", ps_mode=m,
        )
    ):
        secs, hinge, mist = res
        mc_base_ex_s[label] = NEX_MC / secs
        mc_quality[label] = (hinge, mist)

    devs = jax.devices()
    nd, ns = default_mesh_shape(len(devs))
    mesh = make_ps_mesh(num_shards=ns, num_data=nd)
    W = num_workers_of(mesh)
    # Head-prefix routing (single-device meshes): frequency-sort each
    # example's slots so the first q columns carry ids < H, and the
    # guaranteed prefix rides head-only kernels — measured at ~15% of
    # the end-to-end headline (BASELINE.md round-5: 4.53M ex/s with the
    # machinery off vs 5.36M with it on). Equality-tested in
    # tests/test_passive_aggressive.py.
    HEAD = 2048
    q = 0
    if len(devs) == 1:
        from fps_tpu.utils.datasets import head_sort_slots

        data, q = head_sort_slots(data, HEAD)
    cfg = PAConfig(num_features=NF, variant="PA-I", C=C,
                   hot_features=HEAD if q else 0, head_prefix_cols=q)
    trainer, store = passive_aggressive(mesh, cfg, max_steps_per_call=256)
    tables, ls = trainer.init_state(jax.random.key(0))
    ds = DeviceDataset(mesh, data)
    plan = DeviceEpochPlan(ds, num_workers=W, local_batch=16384, seed=1)

    tables, ls, _ = trainer.run_indexed(tables, ls, plan, jax.random.key(9))
    rec = attach_phase_recorder(trainer)  # timed region only (post-warmup)
    # Steady-state throughput: E back-to-back epochs in one call, blocking
    # only on the final epoch's metrics — epochs queue on-device with no
    # host round trip between them, the same zero-per-pass-overhead
    # semantics the native baseline's tight loop gets. (Single-epoch
    # timing charged ~0.2 s of dispatch + metric-sync against a ~0.25 s
    # device epoch — measured ~90% of the device floor at E=4.)
    E = 4
    t0 = time.perf_counter()
    tables, ls, metrics = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1), epochs=E, as_numpy=False,
    )
    np.asarray(metrics[-1]["n"])  # fence on the last epoch
    epoch_s = (time.perf_counter() - t0) / E
    ex_s = NEX / epoch_s / len(devs)

    per0, _ = first_last_real_step(metrics[0], "mistakes")
    _, per1 = first_last_real_step(metrics[-1], "mistakes")
    print(
        f"quality: online mistake rate step0 {per0:.4f} (epoch 2) -> "
        f"last-real-step {per1:.4f} (epoch {E + 1}; chance = 0.5)",
        file=sys.stderr,
    )

    baseline, vs = _rate_baseline(
        base_ex_s,
        "measured native sequential per-feature-fan-out PA-I (message-hop "
        "mode); 'ideal' = fused floor. NOTE: at RCV1 scale the whole "
        "190 KB weight vector is L2-resident on the host core — the "
        "degenerate best case for the sequential loop",
        "examples", ex_s,
        {k: f"hinge {h:.4f}, mistakes {m:.4f}"
         for k, (h, m) in quality.items()},
    )

    # Multiclass PA (transformMulticlass parity, SURVEY §2 #9): a 20-class
    # RCV1-shaped run measured under the same roof, against its own
    # measured native sequential loop (fps_baseline_pa_mc, above).
    mcfg = PAConfig(num_features=NF, num_classes=NCLS, variant="PA-I", C=C)
    mtr, _ = passive_aggressive(mesh, mcfg, max_steps_per_call=256)
    mt, mls = mtr.init_state(jax.random.key(0))
    mds = DeviceDataset(mesh, mdata)
    mplan = DeviceEpochPlan(mds, num_workers=W, local_batch=16384, seed=1)
    mt, mls, _ = mtr.run_indexed(mt, mls, mplan, jax.random.key(9))
    E_MC = 2  # steady-state over 2 back-to-back epochs (as above)
    t0 = time.perf_counter()
    mt, mls, mm = mtr.run_indexed(mt, mls, mplan, jax.random.key(1),
                                  epochs=E_MC, as_numpy=False)
    np.asarray(mm[-1]["n"])
    mc_epoch_s = (time.perf_counter() - t0) / E_MC
    mc_ex_s = NEX_MC / mc_epoch_s / len(devs)
    m0, _ = first_last_real_step(mm[0], "mistakes")
    _, m1 = first_last_real_step(mm[-1], "mistakes")
    print(
        f"multiclass ({NCLS} classes): online mistake rate step0 {m0:.4f} "
        f"-> last-real-step {m1:.4f} (epoch {E_MC + 1}; "
        f"chance = {1 - 1 / NCLS:.2f})",
        file=sys.stderr,
    )
    mc_baseline, mc_vs = _rate_baseline(
        mc_base_ex_s,
        f"measured native sequential per-feature-fan-out {NCLS}-class PA-I "
        "(message-hop mode, num_classes-float row messages); 'ideal' = "
        "fused floor",
        "examples", mc_ex_s,
        {k: f"hinge {h:.4f}, mistakes {m:.4f}"
         for k, (h, m) in mc_quality.items()},
    )

    return {
        "metric": "rcv1_pa1_examples_per_sec_per_chip",
        "value": round(ex_s, 1),
        "unit": "examples/s",
        "vs_baseline": vs,
        "epoch_s": round(epoch_s, 3),
        "steady_state_epochs": E,
        "phases": phase_summary(rec),
        "baseline": baseline,
        "multiclass": {
            "num_classes": NCLS,
            "examples_per_sec_per_chip": round(mc_ex_s, 1),
            "epoch_s": round(mc_epoch_s, 3),
            "steady_state_epochs": E_MC,
            "mistake_rate_step0": round(float(m0), 4),
            "mistake_rate_last": round(float(m1), 4),
            "chance": round(1 - 1 / NCLS, 2),
            "baseline": mc_baseline,
            "vs_baseline": mc_vs,
        },
    }


# ---------------------------------------------------------------------------
# Two-tier storage A/B (zipf skew; replicated hot head vs sharded-only)
# ---------------------------------------------------------------------------

def _zipf_ratings(num_users, num_items, n, *, alpha=1.05, rank=3, seed=0):
    """Planted low-rank ratings whose ITEM stream is zipf-skewed with
    frequency-ranked ids (hottest first — the head convention every
    tier/hot_ids consumer assumes; real ML20M/text8/Criteo streams have
    exactly this shape)."""
    rng = np.random.default_rng(seed)
    p = 1.0 / np.arange(1, num_items + 1) ** alpha
    p /= p.sum()
    user = rng.integers(0, num_users, n).astype(np.int32)
    item = rng.choice(num_items, size=n, p=p).astype(np.int32)
    uf = rng.normal(0, 1.0 / rank ** 0.5, (num_users, rank))
    vf = rng.normal(0, 1.0 / rank ** 0.5, (num_items, rank))
    rating = ((uf[user] * vf[item]).sum(1)
              + rng.normal(0, 0.1, n)).astype(np.float32)
    return {"user": user, "item": item, "rating": rating}


def _reexec_workload_subprocess(workload: str):
    """Run ``--workload <name>`` in a cleaned 8-CPU-device subprocess
    (same pattern as ``__graft_entry__``'s dryrun re-exec): the tier
    A/Bs are specified over the 8-device mesh, and a single-chip TPU
    process cannot widen itself in-place."""
    import os
    import subprocess

    from fps_tpu.utils.hostenv import cpu_mesh_env, reexec_count

    if reexec_count() >= 8:
        raise RuntimeError(
            f"{workload} A/B needs 8 devices, still short after re-exec")
    root = os.path.dirname(os.path.abspath(__file__))
    env = cpu_mesh_env(8)
    env["PYTHONPATH"] = os.pathsep.join(
        [root] + [p for p in env["PYTHONPATH"].split(os.pathsep) if p]
    )
    r = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"),
         "--workload", workload],
        env=env, cwd=root, capture_output=True, text=True, timeout=1500,
    )
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError(
        f"{workload} re-exec produced no JSON; tail: "
        f"{(r.stdout + r.stderr)[-800:]}")


def _reexec_tiered_subprocess():
    return _reexec_workload_subprocess("tiered")


def split_route_bytes(profile, *, hot_rows, dim, num_shards,
                      counted=False, itemsize=4, sketch_bytes=0):
    """Attribute a tiered program's collective bytes per ROUTE: the
    window reconcile's reduce-scatter + all-gather pair (or the legacy /
    extremum all_reduce) is identified by its analytically-known payload
    (``ceil(H/S)*S`` padded head rows times the delta width — the count
    column under a counted combine), everything else is the cold
    pull/push routes. Separating the two makes the payload-proportional
    cold-routing win and the sharded-reconcile cost independently
    attributable in the A/B (one aggregate ratio conflates them)."""
    total = sum(c.payload_bytes for c in profile)
    tracking = 0
    if sketch_bytes:
        # The adaptive tier's end-of-call sketch-merge psum — its own
        # bucket (it is tracking overhead, neither a data route).
        for c in profile:
            if c.kind == "all_reduce" and c.payload_bytes == sketch_bytes:
                tracking += c.payload_bytes
                break
    if not hot_rows:
        return {"cold": total - tracking, "hot_reconcile": 0,
                "tracking": tracking}
    Hp = -(-hot_rows // num_shards) * num_shards
    dimp = dim + (1 if counted else 0)
    rs_bytes = Hp * dimp * itemsize
    ag_bytes = Hp * dim * itemsize
    # The data-axis psum of the owned slice (meshes with a data axis),
    # and the extremum pmax (full head + indicator column).
    slice_bytes = (Hp // num_shards) * dimp * itemsize
    ar_ok = (slice_bytes, Hp * (dim + 1) * itemsize)
    want = {"reduce_scatter": (rs_bytes,), "all_gather": (ag_bytes,),
            "all_reduce": ar_ok}
    reconcile = 0
    matched = {k: False for k in want}
    for c in profile:
        if (c.kind in want and not matched[c.kind]
                and c.payload_bytes in want[c.kind]):
            matched[c.kind] = True
            reconcile += c.payload_bytes
    return {"cold": total - reconcile - tracking,
            "hot_reconcile": reconcile, "tracking": tracking}


def run_tiered(args):
    """Zipf-skew two-tier A/B on the 8-device mesh: the same chunk
    stream trained four ways —

    * **off**  — untiered (per-step collective pull/push);
    * **on**   — full replication (the PR-5 headline: hot reads local,
      one sharded reconcile per ``hot_sync_every`` window);
    * **head** — PARTIAL hot head (H < num_ids) with the STATIC cold
      routes: the ROADMAP scaling cliff — even at a >0.9 hit rate the
      cold collectives still carry the full O(batch) payload;
    * **head_compact** — the same partial head with
      ``TableSpec.cold_budget``: cold ids compact into a bounded lane,
      so cold-route collective bytes track actual cold traffic.

    Reports per-chunk collective count and PER-ROUTE payload bytes (hot
    reconcile vs cold pull/push — :func:`split_route_bytes`) plus
    examples/s per arm, and an ``ssp`` sub-run: the ``head_compact``
    configuration under bounded staleness (``sync_every > 1``),
    measuring the compact/overflow certification rates there (the
    carried-over ROADMAP question; surfaced fleet-wide as
    ``cold_route_cert_rate`` in ``fps_tpu.obs.fleet`` rollups). Acceptance signals: strictly fewer collectives
    and no throughput regression for ``on`` vs ``off`` (PR 5), and a
    >= 3x cold-route byte reduction for ``head_compact`` vs ``head`` at
    a >= 0.9 hit rate (PR 10, pinned statically as the
    ``mf_tiered_compact`` audit budget)."""
    import dataclasses

    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh

    devs = jax.devices()
    if len(devs) < 8:
        return _reexec_tiered_subprocess()
    nd, ns = default_mesh_shape(8)
    mesh = make_ps_mesh(num_shards=ns, num_data=nd, devices=devs[:8])
    W = num_workers_of(mesh)

    NU, NI, RANK = 4096, 4096, 16
    E_SYNC = 4          # hot_sync_every: the parameter-plane SSP bound
    H_PART = 2048       # partial head: ~0.93 coverage at alpha 1.05
    COLD_BUDGET = 256   # per-worker cold lane (~3.5x expected cold rows)
    LOCAL_BATCH, SPC, CHUNKS = 1024, 8, 12
    data = _zipf_ratings(NU, NI, W * LOCAL_BATCH * SPC * CHUNKS, seed=0)

    def make_chunks(s=None):
        # s > 1 re-chunks the same stream for SSP mode (per-round batch
        # layout: extra leading rounds axis).
        return epoch_chunks(data, num_workers=W, local_batch=LOCAL_BATCH,
                            steps_per_chunk=SPC, route_key="user",
                            sync_every=s, seed=5)

    SSP_S = 2  # the ssp arm's bounded-staleness window (sync_every)
    out = {"hot_sync_every": E_SYNC, "hot_tier_rows": NI,
           "partial_head": H_PART, "cold_budget": COLD_BUDGET,
           "zipf_alpha": 1.05, "mesh": dict(mesh.shape)}
    rates = {}
    # (label, H, cold_budget, force_gathered, sync_every): the
    # partial-head arms force the gathered cold route
    # (dense_collectives=False) — the compaction story is about
    # embedding-scale tables whose cold route cannot afford table-sized
    # dense collectives; at this bench scale the item table would
    # otherwise auto-resolve dense. The "ssp" arm is the head_compact
    # configuration under BOUNDED STALENESS (the carried-over ROADMAP
    # question): the per-chunk host certification is mode-independent
    # (raw id streams, not staleness, decide the lane), and this arm
    # pins that with measured compact/overflow rates — surfaced
    # fleet-wide as cold_route_cert_rate in fps_tpu.obs.fleet rollups.
    arms = (("off", 0, 0, False, None), ("on", NI, 0, False, None),
            ("head", H_PART, 0, True, None),
            ("head_compact", H_PART, COLD_BUDGET, True, None),
            ("ssp", H_PART, COLD_BUDGET, True, SSP_S))
    for label, H, C, gathered, s in arms:
        cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK,
                       learning_rate=0.05)
        # Per-id mean combine: zipf-hot duplicate ids need the averaged
        # step (run_mf's reasoning) — and it exercises the tier's
        # windowed count-normalized reconcile.
        trainer, store = online_mf(mesh, cfg, combine="mean",
                                   sync_every=s)
        if H:
            store.specs["item_factors"] = dataclasses.replace(
                store.specs["item_factors"], hot_tier=H, cold_budget=C,
                **({"dense_collectives": False} if gathered else {}))
            trainer.config = dataclasses.replace(
                trainer.config, hot_sync_every=E_SYNC)
        from fps_tpu import obs

        # Static collective profile of the per-chunk program, split per
        # route (mean combine carries the count column -> counted=True).
        mode = "sync" if s is None else "ssp"
        hlo = trainer.lowered_chunk_text(next(make_chunks(s)), mode)
        profile = collective_profile(hlo)
        colls = len(profile)
        coll_bytes = sum(c.payload_bytes for c in profile)
        routes = split_route_bytes(
            profile, hot_rows=H, dim=RANK, num_shards=ns, counted=True)

        # Warm-up (compile), then timed run on fresh state with a fresh
        # recorder — the hit-rate counters must scope the timed pass
        # only, not the warm-up traffic.
        from itertools import islice

        tables, ls = trainer.init_state(jax.random.key(0))
        trainer.fit_stream(tables, ls, islice(make_chunks(s), 2),
                           jax.random.key(9))
        rec = obs.Recorder(sinks=[])
        trainer.recorder = rec
        tables, ls = trainer.init_state(jax.random.key(0))
        t0 = time.perf_counter()
        tables, ls, m = trainer.fit_stream(
            tables, ls, make_chunks(s), jax.random.key(1))
        wall = time.perf_counter() - t0
        n_ex = float(sum(np.asarray(mm["n"]).sum() for mm in m))
        se = float(sum(np.asarray(mm["se"]).sum() for mm in m))
        rates[label] = n_ex / wall
        arm = {
            "collectives_per_chunk": colls,
            # Payload bytes those collectives move per chunk program —
            # the structured profile's sum (fps_tpu.analysis), split by
            # ROUTE so the reconcile-sharding and cold-compaction wins
            # are separately attributable (the partial-head scaling
            # cliff is a BYTES story the bare count can't show).
            "collective_bytes_per_chunk": coll_bytes,
            "cold_bytes_per_chunk": routes["cold"],
            "hot_reconcile_bytes_per_chunk": routes["hot_reconcile"],
            "examples_per_sec": round(n_ex / wall, 1),
            "wall_s": round(wall, 4),
            "train_rmse": round((se / max(n_ex, 1.0)) ** 0.5, 4),
        }
        if s is not None:
            arm["sync_every"] = s
        if H:
            hr = rec.counter_value("hot_tier.hot_rows",
                                   table="item_factors")
            pr = rec.counter_value("hot_tier.pulled_rows",
                                   table="item_factors")
            # None under SSP by design: reads come from the round
            # snapshot, not the replica, so no pull counters flow
            # (driver fold docs).
            arm["hot_hit_rate"] = round(hr / pr, 4) if pr else None
        if C:
            arm["compact_chunks"] = int(
                rec.counter_value("cold_route.compact_chunks"))
            arm["overflow_chunks"] = int(rec.counter_value(
                "cold_route.overflow_chunks", table="item_factors"))
            arm["cold_dropped"] = int(rec.counter_value(
                "hot_tier.cold_dropped", table="item_factors"))
            total = arm["compact_chunks"] + arm["overflow_chunks"]
            arm["certification_rate"] = (
                round(arm["compact_chunks"] / total, 4) if total
                else None)
        out[label] = arm

    off, on = out["off"], out["on"]
    head, compact = out["head"], out["head_compact"]
    out["collectives_fewer"] = (on["collectives_per_chunk"]
                                < off["collectives_per_chunk"])
    # PER-ROUTE ratios (PR 10): the cold ratio isolates the compaction
    # win at the same head; the reconcile share shows what the sharded
    # window exchange costs against the cold traffic it absorbs.
    out["collective_bytes_ratio"] = {
        "cold_compact_vs_static": (
            round(compact["cold_bytes_per_chunk"]
                  / head["cold_bytes_per_chunk"], 4)
            if head["cold_bytes_per_chunk"] else None),
        "cold_head_vs_off": (
            round(head["cold_bytes_per_chunk"]
                  / off["cold_bytes_per_chunk"], 4)
            if off["cold_bytes_per_chunk"] else None),
        "total_on_vs_off": (
            round(on["collective_bytes_per_chunk"]
                  / off["collective_bytes_per_chunk"], 4)
            if off["collective_bytes_per_chunk"] else None),
    }
    ratio = out["collective_bytes_ratio"]["cold_compact_vs_static"]
    out["cold_bytes_reduction_x"] = (
        round(1.0 / ratio, 2) if ratio else None)
    out["speedup"] = round(rates["on"] / rates["off"], 3)
    out["speedup_compact_vs_head"] = round(
        rates["head_compact"] / rates["head"], 3)
    print(
        f"tiered A/B: collectives/chunk {off['collectives_per_chunk']} -> "
        f"{on['collectives_per_chunk']} "
        f"({off['collective_bytes_per_chunk']} -> "
        f"{on['collective_bytes_per_chunk']} bytes), examples/s "
        f"{off['examples_per_sec']:.0f} -> {on['examples_per_sec']:.0f}, "
        f"hot hit rate {on.get('hot_hit_rate')}; partial head "
        f"hit rate {head.get('hot_hit_rate')}, cold bytes/chunk "
        f"{head['cold_bytes_per_chunk']} -> "
        f"{compact['cold_bytes_per_chunk']} "
        f"({out['cold_bytes_reduction_x']}x, overflow "
        f"{compact.get('overflow_chunks')}, dropped "
        f"{compact.get('cold_dropped')}); SSP s={SSP_S} cert rate "
        f"{out['ssp']['certification_rate']} (overflow "
        f"{out['ssp']['overflow_chunks']})", file=sys.stderr)
    return {
        "metric": "zipf_mf_two_tier_examples_per_sec",
        "value": on["examples_per_sec"],
        "unit": "examples/s",
        # The A/B's own ratio: tier-on throughput over tier-off on the
        # same mesh/stream (no native-loop analog exists for this one).
        "vs_baseline": out["speedup"],
        **out,
    }


def _drifting_zipf_ratings(num_users, num_items, n, *, alpha=1.2, rank=3,
                           rotate_frac=0.5, shift=None, seed=0):
    """Planted low-rank ratings whose ITEM popularity RANKING rotates
    mid-stream: the first ``rotate_frac`` of examples draw item ids with
    Zipf rank = id (frequency-ranked, hottest first — the convention a
    static tier is specified against); the rest draw with rank =
    ``(id - shift) mod num_items``, so the hot head MOVES to ids around
    ``shift``. Stream order is temporal (feed with ``seed=None`` chunking
    so the drift survives ingest)."""
    rng = np.random.default_rng(seed)
    shift = num_items // 2 if shift is None else shift
    p = 1.0 / np.arange(1, num_items + 1) ** alpha
    p /= p.sum()
    n1 = int(n * rotate_frac)
    user = rng.integers(0, num_users, n).astype(np.int32)
    item1 = rng.choice(num_items, size=n1, p=p).astype(np.int32)
    item2 = ((rng.choice(num_items, size=n - n1, p=p) + shift)
             % num_items).astype(np.int32)
    item = np.concatenate([item1, item2])
    uf = rng.normal(0, 1.0 / rank ** 0.5, (num_users, rank))
    vf = rng.normal(0, 1.0 / rank ** 0.5, (num_items, rank))
    rating = ((uf[user] * vf[item]).sum(1)
              + rng.normal(0, 0.1, n)).astype(np.float32)
    return {"user": user, "item": item, "rating": rating}


def run_tiered_drift(args):
    """Drifting-Zipf adaptive-tiering A/B (fps_tpu.tiering;
    docs/performance.md "Adaptive tiering") on the 8-device mesh: the
    SAME drifting MF stream (item hot set rotates mid-run) trained
    three ways —

    * **static-oracle**: the best static config full knowledge buys
      under the replica budget (full item-table replication, E=4 — the
      PR 5 proven-win arm; drift-immune by construction);
    * **static-stale**: the PR 5-style hand-tuned partial head a user
      would pin from phase-1 frequencies (H=512, E=4) — after the
      rotation its replica serves ~nothing, and the program pays the
      full per-step collective complement it was meant to avoid;
    * **adaptive**: ``TrainerConfig.auto_tier`` — online tracking + the
      planner derive the config instead (it finds the item table fits
      the budget and fully replicates), with the Retierer's checks
      riding the run.

    Acceptance (ISSUE 9 / ROADMAP): adaptive examples/s within ~10% of
    the oracle and strictly above static-stale. A second sub-experiment
    (``rerank_recovery``) forces a PARTIAL mapped head under a tight
    replica budget and shows the re-ranker recovering the hot-tier HIT
    RATE after the rotation (static-stale's collapses), with ZERO
    recompiles across re-ranks — the online half of the NuPS story,
    which throughput alone cannot show (cold-route payloads are static
    shapes; the count win needs full replication).
    """
    import dataclasses

    import jax

    from fps_tpu import obs
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.tiering import Retierer

    devs = jax.devices()
    if len(devs) < 8:
        return _reexec_workload_subprocess("tiered_drift")
    nd, ns = default_mesh_shape(8)
    mesh = make_ps_mesh(num_shards=ns, num_data=nd, devices=devs[:8])
    W = num_workers_of(mesh)

    NU, NI, RANK = 4096, 4096, 16
    E_SYNC, H_STALE = 4, 512
    LOCAL_BATCH, SPC, CHUNKS = 1024, 8, 12
    data = _drifting_zipf_ratings(
        NU, NI, W * LOCAL_BATCH * SPC * CHUNKS, alpha=1.2, seed=0)

    def make_chunks():
        # seed=None: stream order preserved — the drift IS the workload.
        return epoch_chunks(data, num_workers=W, local_batch=LOCAL_BATCH,
                            steps_per_chunk=SPC, route_key="user",
                            seed=None)

    def make_trainer(arm):
        cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK,
                       learning_rate=0.05)
        trainer, store = online_mf(mesh, cfg, combine="mean")
        if arm == "oracle":
            store.specs["item_factors"] = dataclasses.replace(
                store.specs["item_factors"], hot_tier=NI)
            trainer.config = dataclasses.replace(
                trainer.config, hot_sync_every=E_SYNC)
        elif arm == "stale":
            store.specs["item_factors"] = dataclasses.replace(
                store.specs["item_factors"], hot_tier=H_STALE)
            trainer.config = dataclasses.replace(
                trainer.config, hot_sync_every=E_SYNC)
        else:  # adaptive: tracking + planner derive the knobs
            trainer.config = dataclasses.replace(
                trainer.config, auto_tier=True)
        return trainer, store

    out = {"mesh": dict(mesh.shape), "zipf_alpha": 1.2,
           "rotate_at_chunk": CHUNKS // 2, "hot_sync_every": E_SYNC,
           "stale_head": H_STALE, "num_items": NI}
    rates = {}
    from itertools import islice

    for arm in ("oracle", "stale", "adaptive"):
        trainer, store = make_trainer(arm)
        # Warm-up: compile — and for the adaptive arm, let the tracker
        # see enough traffic that the planner fires and its (one,
        # deliberate) recompile happens OUTSIDE the timed region; the
        # timed run then starts with the planned config via
        # on_run_entry, like any restarted production run would.
        tables, ls = trainer.init_state(jax.random.key(0))
        trainer.fit_stream(tables, ls, islice(make_chunks(), 6),
                           jax.random.key(9))
        hlo = trainer.lowered_chunk_text(next(make_chunks()), "sync")
        profile = collective_profile(hlo)
        rec = obs.Recorder(sinks=[])
        trainer.recorder = rec
        tables, ls = trainer.init_state(jax.random.key(0))
        t0 = time.perf_counter()
        tables, ls, m = trainer.fit_stream(
            tables, ls, make_chunks(), jax.random.key(1))
        wall = time.perf_counter() - t0
        n_ex = float(sum(np.asarray(mm["n"]).sum() for mm in m))
        se = float(sum(np.asarray(mm["se"]).sum() for mm in m))
        rates[arm] = n_ex / wall
        Hres = trainer._hot_tier_map().get("item_factors", 0)
        sketch_b = 0
        if trainer.retierer is not None:
            cm = trainer.retierer.spec
            sketch_b = cm.depth * cm.width * 4
        routes = split_route_bytes(
            profile, hot_rows=Hres, dim=RANK,
            num_shards=mesh.shape["shard"], counted=True,
            sketch_bytes=sketch_b)
        arm_out = {
            "collectives_per_chunk": len(profile),
            "collective_bytes_per_chunk": sum(
                c.payload_bytes for c in profile),
            # Per-route split (PR 10): cold pull/push vs the window
            # reconcile vs tracking overhead — the three optimizations
            # stay separately attributable.
            "cold_bytes_per_chunk": routes["cold"],
            "hot_reconcile_bytes_per_chunk": routes["hot_reconcile"],
            "tracking_bytes_per_chunk": routes["tracking"],
            "examples_per_sec": round(n_ex / wall, 1),
            "wall_s": round(wall, 4),
            "train_rmse": round((se / max(n_ex, 1.0)) ** 0.5, 4),
        }
        hr = rec.counter_value("hot_tier.hot_rows", table="item_factors")
        pr = rec.counter_value("hot_tier.pulled_rows",
                               table="item_factors")
        arm_out["hot_hit_rate"] = round(hr / pr, 4) if pr else None
        if arm == "adaptive":
            arm_out["planned"] = (
                {n: p.to_json() for n, p in
                 sorted(trainer.retierer.plans.items())}
                if trainer.retierer.plans else None)
        out[arm] = arm_out

    out["within_oracle"] = round(rates["adaptive"] / rates["oracle"], 4)
    out["above_stale"] = bool(rates["adaptive"] > rates["stale"])

    # -- re-rank recovery sub-experiment: tight replica budget forces a
    # PARTIAL mapped head; the hit rate around the rotation is the
    # online-management signal (throughput is program-identical between
    # these two arms — payload shapes are static).
    recovery = {}
    half = CHUNKS // 2
    for label in ("static", "adaptive"):
        cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK,
                       learning_rate=0.05)
        trainer, store = online_mf(mesh, cfg, combine="mean")
        store.specs["item_factors"] = dataclasses.replace(
            store.specs["item_factors"], hot_tier=H_STALE)
        trainer.config = dataclasses.replace(
            trainer.config, hot_sync_every=E_SYNC)
        if label == "adaptive":
            trainer.retierer = Retierer(check_every=2,
                                        churn_threshold=0.1)
        tables, ls = trainer.init_state(jax.random.key(0))
        phases = {}
        chunks = list(make_chunks())
        for phase, sl in (("phase1", chunks[:half]),
                          ("phase2", chunks[half:])):
            rec = obs.Recorder(sinks=[])
            trainer.recorder = rec
            start = 0 if phase == "phase1" else half
            tables, ls, _ = trainer.fit_stream(
                tables, ls, iter(sl), jax.random.key(1),
                start_step=start)
            hr = rec.counter_value("hot_tier.hot_rows",
                                   table="item_factors")
            pr = rec.counter_value("hot_tier.pulled_rows",
                                   table="item_factors")
            phases[phase] = round(hr / pr, 4) if pr else None
        entry = {"hit_rate_phase1": phases["phase1"],
                 "hit_rate_phase2": phases["phase2"]}
        if label == "adaptive":
            entry["re_ranks"] = trainer.retierer.re_ranks
            # Exactly ONE program across both phases and every re-rank:
            # the no-recompile contract, visible in the bench evidence.
            entry["recompiles_after_first"] = len(trainer._compiled) - 1
        recovery[label] = entry
    out["rerank_recovery"] = recovery

    print(
        "tiered_drift: examples/s oracle "
        f"{out['oracle']['examples_per_sec']:.0f} / stale "
        f"{out['stale']['examples_per_sec']:.0f} / adaptive "
        f"{out['adaptive']['examples_per_sec']:.0f} "
        f"(within_oracle {out['within_oracle']}, above_stale "
        f"{out['above_stale']}); recovery hit-rate phase2 static "
        f"{recovery['static']['hit_rate_phase2']} -> adaptive "
        f"{recovery['adaptive']['hit_rate_phase2']} with "
        f"{recovery['adaptive']['re_ranks']} re-ranks, "
        f"{recovery['adaptive']['recompiles_after_first']} recompiles",
        file=sys.stderr)
    return {
        "metric": "drifting_zipf_adaptive_tiering_examples_per_sec",
        "value": out["adaptive"]["examples_per_sec"],
        "unit": "examples/s",
        # The A/B's own ratio: adaptive throughput over the
        # static-oracle arm on the same mesh/stream (1.0 = the planner
        # gave up nothing vs hand-tuned omniscience).
        "vs_baseline": out["within_oracle"],
        **out,
    }


def _serve_ab_one(label, trainer, init_state, make_chunks,
                  make_query, *, queries_hint):
    """One serve-while-train A/B arm pair: train the same stream twice —
    checkpointing both times (the A/B isolates SERVING overhead, not
    checkpoint cost) — first bare, then with a SnapshotWatcher hot-swap
    loop and a query-load thread hammering the in-process ReadServer.
    Returns the per-model dict (train rates, queries/s, p50/p99 lookup
    latency, write→servable lag)."""
    import tempfile
    import threading

    import jax

    from fps_tpu.core.checkpoint import AsyncCheckpointer
    from fps_tpu.serve import NoSnapshotError, ReadServer, SnapshotWatcher

    def timed_fit(ckpt_dir):
        tables, ls = init_state()
        ckpt = AsyncCheckpointer(ckpt_dir, keep=3)
        t0 = time.perf_counter()
        tables, ls, m = trainer.fit_stream(
            tables, ls, make_chunks(), jax.random.key(1),
            checkpointer=ckpt, checkpoint_every=1)
        wall = time.perf_counter() - t0
        ckpt.close()
        n_ex = float(sum(np.asarray(mm["n"]).sum() for mm in m))
        return n_ex, wall

    # Warm-up (compile) on throwaway state, outside every timed region.
    from itertools import islice

    tables, ls = init_state()
    with tempfile.TemporaryDirectory() as d:
        ckpt = AsyncCheckpointer(d, keep=2)
        trainer.fit_stream(tables, ls, islice(make_chunks(), 2),
                           jax.random.key(9), checkpointer=ckpt,
                           checkpoint_every=1)
        ckpt.close()

    with tempfile.TemporaryDirectory() as d:
        n_ex, wall_off = timed_fit(d)
    rate_off = n_ex / wall_off

    with tempfile.TemporaryDirectory() as d:
        server = ReadServer()
        lags = []

        def on_swap(snap, _direction):
            server.swap_to(snap)
            if watcher.write_to_servable_s is not None:
                lags.append(watcher.write_to_servable_s)

        watcher = SnapshotWatcher(d, on_swap=on_swap)
        stop = threading.Event()
        qcount = [0]

        qerr = []

        def query_load():
            rng = np.random.default_rng(0)
            while not stop.is_set():
                try:
                    make_query(server, rng)
                except NoSnapshotError:
                    time.sleep(0.005)
                    continue
                except Exception as e:  # noqa: BLE001 — re-raised below
                    # A dead load generator must fail the workload, not
                    # publish queries_per_sec≈0 as a measurement.
                    qerr.append(e)
                    return
                qcount[0] += 1

        threads = [
            threading.Thread(target=watcher.run,
                             kwargs={"interval_s": 0.05, "stop": stop},
                             name="bench-serve-watcher", daemon=True),
            threading.Thread(target=query_load, name="bench-serve-load",
                             daemon=True),
        ]
        for t in threads:
            t.start()
        n_ex, wall_on = timed_fit(d)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        if qerr:
            raise RuntimeError(
                f"serve[{label}] query load died mid-run") from qerr[0]
        if qcount[0] == 0:
            # BENCH_r14 class of bug: a load generator that never got a
            # query through must FAIL the workload — a reported
            # queries_per_sec of 0.0 is a dead reader, not a rate.
            raise RuntimeError(
                f"serve[{label}] reader_dead: query load finished with "
                "0 queries served")
        if not any(t.is_alive() for t in threads):
            # Pick up the end-of-run flush's final snapshot — unless a
            # thread outlived its join timeout: poll() is
            # single-threaded by contract.
            watcher.poll()
    rate_on = n_ex / wall_on

    lat = server.latency_s() or {}
    lag_steps = None
    if watcher.current is not None and watcher.max_written_step is not None:
        lag_steps = watcher.max_written_step - watcher.current.step
    arm = {
        "train_examples_per_sec_off": round(rate_off, 1),
        "train_examples_per_sec_serving": round(rate_on, 1),
        "train_retention": round(rate_on / rate_off, 4),
        "queries_per_sec": round(qcount[0] / wall_on, 1),
        "queries": qcount[0],
        "latency_p50_s": lat.get("p50"),
        "latency_p99_s": lat.get("p99"),
        "write_to_servable_s_mean": (round(float(np.mean(lags)), 4)
                                     if lags else None),
        "write_to_servable_s_max": (round(float(np.max(lags)), 4)
                                    if lags else None),
        "snapshot_lag_steps_final": lag_steps,
        "swaps": dict(watcher.swaps),
        "rejected_snapshots": watcher.rejected,
        "rows_served": server.rows_served,
    }
    print(f"serve[{label}]: {arm['queries_per_sec']:.0f} q/s "
          f"(hint >= {queries_hint}), p50 {lat.get('p50')}, p99 "
          f"{lat.get('p99')}, write->servable mean "
          f"{arm['write_to_servable_s_mean']}s, train retention "
          f"{arm['train_retention']}", file=sys.stderr)
    return arm


def run_serve(args):
    """Serve-while-train A/B (fps_tpu.serve, docs/serving.md): MF and
    logreg trained with per-chunk async checkpoints while a
    SnapshotWatcher + in-process ReadServer answer a saturating query
    load — reports queries/s, p50/p99 lookup latency, and the
    write→servable freshness lag ALONGSIDE training throughput with and
    without the serving plane attached."""
    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_sparse_classification

    mesh = make_ps_mesh()
    W = num_workers_of(mesh)
    out = {"mesh": dict(mesh.shape)}

    # -- MF: pull + user×item top-k against the exported user factors.
    NU, NI, RANK = 2048, 2048, 8
    LOCAL_BATCH, SPC, CHUNKS = 512, 8, 10
    mf_data = _zipf_ratings(NU, NI, W * LOCAL_BATCH * SPC * CHUNKS, seed=0)
    mf_cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK,
                      learning_rate=0.05)
    mf_trainer, _mf_store = online_mf(mesh, mf_cfg)

    def mf_chunks():
        return epoch_chunks(mf_data, num_workers=W, local_batch=LOCAL_BATCH,
                            steps_per_chunk=SPC, route_key="user", seed=5)

    def mf_query(server, rng):
        if rng.integers(2):
            server.topk(rng.integers(0, NU, 8), k=10)
        else:
            server.pull("item_factors", rng.integers(0, NI, 256))

    out["mf"] = _serve_ab_one(
        "mf", mf_trainer,
        lambda: mf_trainer.init_state(jax.random.key(0)),
        mf_chunks, mf_query, queries_hint=100)

    # -- logreg: batched pull-by-id + sparse linear scoring.
    NF, NNZ = 1 << 14, 16
    lr_data = synthetic_sparse_classification(
        W * 256 * 8 * 10, NF, NNZ, seed=0)
    lr_data["label"] = (lr_data["label"] > 0).astype(np.float32)
    lr_cfg = LogRegConfig(num_features=NF, learning_rate=0.1)
    lr_trainer, _lr_store = logistic_regression(mesh, lr_cfg)

    def lr_chunks():
        return epoch_chunks(lr_data, num_workers=W, local_batch=256,
                            steps_per_chunk=8, seed=5)

    def lr_query(server, rng):
        if rng.integers(2):
            ids = rng.integers(0, NF, (64, NNZ))
            server.score_linear(ids, rng.normal(size=(64, NNZ)))
        else:
            server.pull("weights", rng.integers(0, NF, 256))

    out["logreg"] = _serve_ab_one(
        "logreg", lr_trainer,
        lambda: lr_trainer.init_state(jax.random.key(0)),
        lr_chunks, lr_query, queries_hint=100)

    qps = out["mf"]["queries_per_sec"] + out["logreg"]["queries_per_sec"]
    retention = min(out["mf"]["train_retention"],
                    out["logreg"]["train_retention"])
    return {
        "metric": "serve_while_train_queries_per_sec",
        "value": round(qps, 1),
        "unit": "queries/s",
        # The A/B's own ratio: training throughput retained while the
        # serving plane runs (1.0 = serving is free to the trainer).
        "vs_baseline": retention,
        **out,
    }


# ---------------------------------------------------------------------------
# iALS (required extension; no reference baseline exists)
# ---------------------------------------------------------------------------

def run_ials(args):
    import jax

    from fps_tpu.models.ials import (
        IALSConfig, IALSSolver, interaction_chunks, recall_at_k,
    )
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_implicit, train_test_split

    NU, NI, PER_USER, RANK = 32768, 16384, 64, 16
    TARGET = args.recall_target
    data = synthetic_implicit(NU, NI, PER_USER, rank=8, seed=0)
    train, test = train_test_split(data, test_frac=0.1, seed=1)

    devs = jax.devices()
    # iALS uses the shard axis only: fold ALL devices into it (a (ns, 1)
    # mesh over a subset would fail make_ps_mesh's full-cover check).
    mesh = make_ps_mesh(num_shards=len(devs), num_data=1)
    solver = IALSSolver(mesh, IALSConfig(num_users=NU, num_items=NI,
                                         rank=RANK, alpha=40.0, reg=0.1))

    def chunks():
        return interaction_chunks(train, num_workers=len(devs),
                                  local_batch=65536, steps_per_chunk=4,
                                  seed=0)

    # Warm-up epoch on throwaway state (compile), then re-init and time.
    solver.init(jax.random.key(99))
    solver.epoch(chunks)
    solver.init(jax.random.key(0))

    epoch_times, recalls = [], []
    for e in range(args.max_epochs):
        t0 = time.perf_counter()
        solver.epoch(chunks)
        epoch_times.append(time.perf_counter() - t0)
        r = recall_at_k(solver, test["user"][:2000], test["item"][:2000],
                        k=20, exclude=(train["user"], train["item"]))
        recalls.append(float(r))
        if r >= TARGET:
            break
    total_s = sum(epoch_times)
    reached = recalls[-1] >= TARGET

    print(
        "quality: per-epoch recall@20 "
        + " -> ".join(f"{r:.4f}" for r in recalls)
        + (f" (reached >= {TARGET})" if reached
           else f" (STOPPED at max_epochs={args.max_epochs})"),
        file=sys.stderr,
    )
    print(f"epoch times: {[round(t, 3) for t in epoch_times]} s",
          file=sys.stderr)

    return {
        "metric": f"implicit_ials_time_to_recall20_{TARGET}",
        "value": round(total_s, 4),
        "unit": "s",
        # iALS is a required extension BEYOND the reference's algorithm set
        # (SURVEY §6): there is no reference implementation to measure.
        "vs_baseline": None,
        "epochs": len(epoch_times),
        "final_recall_at_20": round(recalls[-1], 4),
        "reached": reached,
        "baseline": {"kind": "none — algorithm absent from the reference"},
    }


def run_megastep_ab(args):
    """Per-chunk dispatch vs K-chunk megastep on tiered MF (8-device
    mesh): the SAME tiered, cold-budgeted, device-ingested workload
    driven two ways —

    * **per_chunk** — ``run_indexed`` with ``max_steps_per_call`` = one
      chunk: every chunk pays Python dispatch, host key folding, and
      metric bookkeeping between compiled calls;
    * **megastep** — ``run_megastep`` fusing K of those chunks into ONE
      compiled program (``fps_tpu.core.megastep``): reconcile / sketch
      boundaries run in-graph and the device-side overflow VOTE selects
      the compacted cold routes per window (no host id stream exists on
      this path — the gap PR 10 left).

    Acceptance signals: megastep examples/s >= 1.3x per-chunk, final
    tables BIT-IDENTICAL across the two drivers, and the megastep
    program's collective census unchanged when K doubles (the
    O(traffic)-not-O(K) claim, also pinned statically by
    ``tools/audit_programs.py``'s ``mf_megastep`` rows)."""
    import dataclasses

    import jax

    from fps_tpu import obs
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh

    devs = jax.devices()
    if len(devs) < 8:
        return _reexec_workload_subprocess("megastep")
    nd, ns = default_mesh_shape(8)
    mesh = make_ps_mesh(num_shards=ns, num_data=nd, devices=devs[:8])
    W = num_workers_of(mesh)

    NU, NI, RANK = 4096, 4096, 16
    E_SYNC = 4
    H_PART = 2048
    COLD_BUDGET = 8  # ~3x the expected per-(step, worker) cold rows
    # Sized for the dispatch-bound regime the megastep targets: small
    # per-chunk compute (the TPU ratio — sub-ms steps behind a ~ms host
    # round-trip per dispatch), many chunks. The per-chunk arm then
    # pays ~CHUNKS host round-trips per epoch where the megastep pays
    # CHUNKS/K.
    LOCAL_BATCH, SPC, CHUNKS, K = 32, 2, 768, 16
    EPOCHS = 2
    data = _zipf_ratings(NU, NI, W * LOCAL_BATCH * SPC * CHUNKS, seed=0)

    def make_trainer():
        cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK,
                       learning_rate=0.05)
        trainer, store = online_mf(mesh, cfg, combine="mean",
                                   max_steps_per_call=SPC)
        store.specs["item_factors"] = dataclasses.replace(
            store.specs["item_factors"], hot_tier=H_PART,
            cold_budget=COLD_BUDGET, dense_collectives=False)
        trainer.config = dataclasses.replace(
            trainer.config, hot_sync_every=E_SYNC)
        plan = DeviceEpochPlan(
            DeviceDataset(mesh, data), num_workers=W,
            local_batch=LOCAL_BATCH, route_key="user", seed=5)
        return trainer, store, plan

    out = {"chunks_per_dispatch": K, "steps_per_chunk": SPC,
           "partial_head": H_PART, "cold_budget": COLD_BUDGET,
           "hot_sync_every": E_SYNC, "epochs": EPOCHS,
           "mesh": dict(mesh.shape)}
    finals = {}
    # Third arm (ISSUE 20): chunks_per_dispatch="auto" — the calibrated
    # K must land in the explicit arm's dispatch-amortized regime
    # (host_serial_share <= explicit K's) while staying bit-identical.
    for label in ("per_chunk", "megastep", "auto"):
        trainer, store, plan = make_trainer()

        def go(t, ls, key, epochs, _tr=trainer, _p=plan, _label=label):
            if _label == "per_chunk":
                return _tr.run_indexed(t, ls, _p, key, epochs=epochs)
            return _tr.run_megastep(
                t, ls, _p, key, epochs=epochs,
                chunks_per_dispatch=K if _label == "megastep" else "auto")

        # Warm-up pass (compile) on throwaway state, then the timed run
        # on fresh state with a fresh aggregates-only recorder.
        t0s, l0s = trainer.init_state(jax.random.key(0))
        go(t0s, l0s, jax.random.key(9), 1)
        rec = obs.Recorder(sinks=[])
        trainer.recorder = rec
        tables, ls = trainer.init_state(jax.random.key(0))
        t0 = time.perf_counter()
        tables, ls, m = go(tables, ls, jax.random.key(1), EPOCHS)
        wall = time.perf_counter() - t0
        n_ex = float(sum(np.asarray(mm["n"]).sum() for mm in m))
        phases = {ph: round(v["s"], 4)
                  for ph, v in sorted(rec.phase_totals().items())}
        serial = sum(phases.get(ph, 0.0) for ph in HOST_SERIAL_PHASES)
        arm_k = K
        if label == "auto":
            arm_k = max(int(rec.snapshot()["gauges"]["megastep.auto_k"]),
                        1)
        arm = {
            "examples_per_sec": round(n_ex / wall, 1),
            "wall_s": round(wall, 4),
            "host_serial_s": round(serial, 4),
            "host_serial_share": (round(serial / wall, 4) if wall
                                  else None),
            "dispatches": int(
                plan.calls_per_epoch(SPC) * EPOCHS
                if label == "per_chunk" else
                -(-plan.calls_per_epoch(SPC) // arm_k) * EPOCHS),
            "phases": phases,
        }
        if label == "auto":
            arm["chosen_k"] = arm_k
        if label == "megastep":
            arm["vote_compact_windows"] = int(
                rec.counter_value("cold_route.vote_compact_windows"))
            # Unlabeled since the phantom-window fix: ONE AND-ed verdict
            # per window, weighted by real (non-weight-0) segments.
            arm["vote_overflow_windows"] = int(rec.counter_value(
                "cold_route.vote_overflow_windows"))
            arm["cold_dropped"] = int(rec.counter_value(
                "hot_tier.cold_dropped", table="item_factors"))
            arm["windows"] = int(rec.counter_value("megastep.windows"))
            # Phantom-window fix (PR-13 carried-over item): the counter
            # must equal the REAL dispatched chunk count — the same
            # number the per-chunk arm dispatches — not M * K.
            arm["windows_match_dispatched"] = (
                arm["windows"]
                == plan.calls_per_epoch(SPC) * EPOCHS)
        finals[label] = {k: np.asarray(v) for k, v in store.tables.items()
                        if "::" not in k}
        out[label] = arm

    out["numerics_bit_identical"] = all(
        np.array_equal(finals["per_chunk"][k], finals[other][k])
        for other in ("megastep", "auto")
        for k in finals["per_chunk"])
    # ISSUE 20 acceptance: the calibrated K buys at least the explicit
    # K's dispatch amortization (shares are noisy at the 4th decimal —
    # judge with a hair of slack).
    out["auto_share_le_explicit"] = bool(
        out["auto"]["host_serial_share"] is not None
        and out["megastep"]["host_serial_share"] is not None
        and out["auto"]["host_serial_share"]
        <= out["megastep"]["host_serial_share"] + 0.005)
    # The O(traffic)-not-O(K) claim, measured on the lowered programs:
    # doubling K must leave the collective census byte-identical (the
    # per-step collectives live inside the scan body; boundary ticks
    # move O(window) bytes per window).
    trainer, _, plan = make_trainer()
    prof_k = collective_profile(trainer.lowered_megastep_text(
        plan, chunks_per_dispatch=2))
    trainer2, _, plan2 = make_trainer()
    prof_2k = collective_profile(trainer2.lowered_megastep_text(
        plan2, chunks_per_dispatch=4))
    census = [(sum(1 for c in p), sum(c.payload_bytes for c in p))
              for p in (prof_k, prof_2k)]
    out["collective_census_k2"] = {"count": census[0][0],
                                   "bytes": census[0][1]}
    out["collective_census_k4"] = {"count": census[1][0],
                                   "bytes": census[1][1]}
    out["collective_bytes_k_independent"] = census[0] == census[1]
    ratio = (out["megastep"]["examples_per_sec"]
             / out["per_chunk"]["examples_per_sec"]
             if out["per_chunk"]["examples_per_sec"] else None)
    out["speedup"] = round(ratio, 3) if ratio else None
    print(
        f"megastep A/B: examples/s "
        f"{out['per_chunk']['examples_per_sec']:.0f} -> "
        f"{out['megastep']['examples_per_sec']:.0f} "
        f"({out['speedup']}x at K={K}) -> "
        f"{out['auto']['examples_per_sec']:.0f} "
        f"(auto K={out['auto']['chosen_k']}), host_serial_share "
        f"{out['per_chunk']['host_serial_share']} -> "
        f"{out['megastep']['host_serial_share']} -> "
        f"{out['auto']['host_serial_share']} (auto<=explicit "
        f"{out['auto_share_le_explicit']}), bit-identical "
        f"{out['numerics_bit_identical']}, census K-independent "
        f"{out['collective_bytes_k_independent']} (vote compact "
        f"{out['megastep']['vote_compact_windows']} / overflow "
        f"{out['megastep']['vote_overflow_windows']}, dropped "
        f"{out['megastep']['cold_dropped']})", file=sys.stderr)
    return {
        "metric": "megastep_vs_per_chunk_examples_per_sec_ratio",
        "value": out["megastep"]["examples_per_sec"],
        "unit": "examples/s",
        "vs_baseline": out["speedup"],
        **out,
    }


def run_delta(args):
    """Delta-snapshot + serving-fleet A/B (ISSUE 14; docs/serving.md,
    docs/resilience.md) on the tiered zipf-MF workload at a ~0.94 hot
    hit rate: the same stream trained twice with per-chunk async
    checkpoints —

    * **full**  — every publication rewrites whole tables (the PR-7
      baseline: publish bytes and write→servable lag are O(table));
    * **delta** — ``DeltaPolicy`` chains: one full + row-sparse deltas
      sourced from the driver's touched-rows tracker, so publish bytes
      track rows actually touched since the last publication.

    A SnapshotWatcher tails each arm for write→servable lag; the delta
    arm additionally runs the step-fenced SERVING FLEET (N >= 3
    ``FleetReader``s under quorum fencing) with a per-reader query load,
    reporting p50/p99 pull latency under concurrent training.

    Acceptance: >= 3x fewer publish bytes than full snapshots, states
    bit-identical, and the fleet converged on one fenced step."""
    import dataclasses
    import tempfile
    import threading

    import jax

    from fps_tpu.core.checkpoint import AsyncCheckpointer, DeltaPolicy
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.serve import (
        NoSnapshotError,
        ServingFleet,
        SnapshotWatcher,
        scan_heartbeats,
    )

    devs = jax.devices()
    if len(devs) < 8:
        return _reexec_workload_subprocess("delta")
    nd, ns = default_mesh_shape(8)
    mesh = make_ps_mesh(num_shards=ns, num_data=nd, devices=devs[:8])
    W = num_workers_of(mesh)

    # Table large relative to per-chunk traffic (that is the regime the
    # delta encoding exists for); H = half the table gives the tiered
    # arm's ~0.94 hit rate at alpha 1.05 (run_tiered's coverage rule).
    NU, NI, RANK = 32768, 32768, 16
    H, E_SYNC = 12288, 4  # ~0.94 hot hit rate at alpha 1.05
    LOCAL_BATCH, SPC, CHUNKS = 256, 4, 10
    N_READERS = 3
    data = _zipf_ratings(NU, NI, W * LOCAL_BATCH * SPC * CHUNKS, seed=0)

    def make_chunks():
        return epoch_chunks(data, num_workers=W, local_batch=LOCAL_BATCH,
                            steps_per_chunk=SPC, route_key="user", seed=5)

    def make_trainer():
        from fps_tpu import obs

        cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK,
                       learning_rate=0.05)
        trainer, store = online_mf(mesh, cfg, combine="mean")
        store.specs["item_factors"] = dataclasses.replace(
            store.specs["item_factors"], hot_tier=H,
            dense_collectives=False)
        trainer.config = dataclasses.replace(trainer.config,
                                             hot_sync_every=E_SYNC)
        rec = obs.Recorder(sinks=[])
        trainer.recorder = rec
        return trainer, store, rec

    def run_arm(d, policy, *, fleet=None):
        trainer, store, rec = make_trainer()
        tables, ls = trainer.init_state(jax.random.key(0))
        ck = AsyncCheckpointer(d, keep=CHUNKS + 2, delta=policy)
        lags = []
        watcher = SnapshotWatcher(
            d, on_swap=lambda s, _dir: lags.append(
                watcher.write_to_servable_s))
        stop = threading.Event()
        threads = [threading.Thread(
            target=watcher.run, kwargs={"interval_s": 0.05, "stop": stop},
            name="bench-delta-watcher", daemon=True)]
        qcounts = [0] * (len(fleet.readers) if fleet is not None else 0)
        qerr = []
        if fleet is not None:
            fleet.start(interval_s=0.05)

            def load(idx, reader):
                rng = np.random.default_rng(idx)
                while not stop.is_set():
                    try:
                        reader.server.pull(
                            "item_factors", rng.integers(0, NI, 256))
                    except NoSnapshotError:
                        time.sleep(0.005)
                        continue
                    except Exception as e:  # noqa: BLE001 — re-raised
                        qerr.append(e)
                        return
                    qcounts[idx] += 1

            threads += [threading.Thread(
                target=load, args=(i, r), daemon=True,
                name=f"bench-delta-load-{i}")
                for i, r in enumerate(fleet.readers)]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        tables, ls, m = trainer.fit_stream(
            tables, ls, make_chunks(), jax.random.key(1),
            checkpointer=ck, checkpoint_every=1)
        wall = time.perf_counter() - t0
        ck.close()
        stop.set()
        if fleet is not None:
            fleet.stop()
        for t in threads:
            t.join(timeout=10.0)
        if qerr:
            raise RuntimeError("delta fleet query load died") from qerr[0]
        n_ex = float(sum(np.asarray(mm["n"]).sum() for mm in m))
        hr = rec.counter_value("hot_tier.hot_rows", table="item_factors")
        pr = rec.counter_value("hot_tier.pulled_rows",
                               table="item_factors")
        pubs = ck.full_publishes + ck.delta_publishes
        arm = {
            "examples_per_sec": round(n_ex / wall, 1),
            "publish_bytes_total": ck.publish_bytes_total,
            "publish_bytes_per_publication": (
                round(ck.publish_bytes_total / pubs) if pubs else None),
            "publications": pubs,
            "delta_publishes": ck.delta_publishes,
            "full_publishes": ck.full_publishes,
            "hot_hit_rate": round(hr / pr, 4) if pr else None,
            "write_to_servable_s_mean": (round(float(np.mean(lags)), 4)
                                         if lags else None),
            "write_to_servable_s_max": (round(float(np.max(lags)), 4)
                                        if lags else None),
        }
        final = store.lookup_host("item_factors", np.arange(NI))
        return arm, final, (qcounts, wall)

    # Warm-up (compile) outside every timed region.
    from itertools import islice

    trainer, _store, _rec = make_trainer()
    tables, ls = trainer.init_state(jax.random.key(9))
    with tempfile.TemporaryDirectory() as d:
        ck = AsyncCheckpointer(d, keep=2)
        trainer.fit_stream(tables, ls, islice(make_chunks(), 2),
                           jax.random.key(9), checkpointer=ck,
                           checkpoint_every=1)
        ck.close()

    policy = DeltaPolicy(full_every=CHUNKS + 4)
    with tempfile.TemporaryDirectory() as d:
        full_arm, full_state, _ = run_arm(d, None)
    # The lag/throughput A/B runs WITHOUT the fleet attached (same
    # topology as the full arm: one watcher) so write->servable compares
    # the PUBLISH paths, not GIL contention from the load generators.
    with tempfile.TemporaryDirectory() as d:
        delta_arm, delta_state, _ = run_arm(d, policy)
    # Fleet pass: same delta-publishing stream with N fence-coordinated
    # readers + per-reader query load hammering them mid-train.
    with tempfile.TemporaryDirectory() as d:
        fleet = ServingFleet(d, N_READERS, quorum=2)
        fleet_arm, _fleet_state, (qcounts, wall) = run_arm(
            d, policy, fleet=fleet)
        # Converge after the end-of-run flush (a reader mid-swap at
        # stop() catches up here; chain failures are retried).
        for _ in range(8):
            fleet.poll()
            if len({r.server._snap.step if r.server._snap else None
                    for r in fleet.readers}) == 1:
                break
        fleet_stats = fleet.stats()
        heartbeats = scan_heartbeats(d)
        # Silent-zero guard (BENCH_r14): a reader that served nothing,
        # or whose liveness beacon went stale relative to its peers, is
        # DEAD — fail the workload instead of averaging a zero into the
        # fleet rate. ("Stale" = older than the freshest beacon by more
        # than the liveness timeout; wall-clock ages don't apply here
        # because training has already stopped by the time we check.)
        from fps_tpu.serve.fleet import DEFAULT_LIVENESS_TIMEOUT_S
        newest_beat = max(
            (hb["t"] for hb in heartbeats.values()), default=None)
        dead = []
        for i, r in enumerate(fleet.readers):
            hb = heartbeats.get(r.reader_id)
            stale = (hb is None or (
                newest_beat is not None
                and newest_beat - hb["t"] > DEFAULT_LIVENESS_TIMEOUT_S))
            if qcounts[i] == 0 or stale:
                dead.append({"reader": r.reader_id,
                             "queries": qcounts[i],
                             "heartbeat": hb})
        if dead:
            raise RuntimeError(
                f"delta fleet reader_dead: {dead} — zero q/s or stale "
                "heartbeat means a wedged reader, not a slow one")

    ratio = (full_arm["publish_bytes_total"]
             / max(delta_arm["publish_bytes_total"], 1))
    readers = []
    for i, st in enumerate(fleet_stats):
        readers.append({
            "reader": st["reader"],
            "queries_per_sec": round(qcounts[i] / wall, 1),
            "latency_p50_s": st.get("latency_p50_s"),
            "latency_p99_s": st.get("latency_p99_s"),
            "final_step": st.get("step"),
            "fence": st.get("fence"),
            "chain_len": st.get("chain_len"),
        })
    fence_steps = {st.get("step") for st in fleet_stats}
    out = {
        "mesh": dict(mesh.shape), "hot_tier_rows": H,
        "hot_sync_every": E_SYNC, "zipf_alpha": 1.05,
        "table_rows": NI, "rank": RANK,
        "full": full_arm, "delta": delta_arm,
        "publish_bytes_reduction_x": round(ratio, 2),
        "states_bit_identical": bool(
            np.array_equal(full_state, delta_state)),
        "fleet": {
            "n_readers": N_READERS, "quorum": 2,
            "readers": readers,
            "converged_single_step": len(fence_steps) == 1,
            "queries_per_sec_total": round(sum(qcounts) / wall, 1),
            "heartbeat_beacons": len(heartbeats),
            "reader_dead": [],  # non-empty would have raised above
        },
    }
    print(
        f"delta A/B: publish bytes {full_arm['publish_bytes_total']} -> "
        f"{delta_arm['publish_bytes_total']} ({out['publish_bytes_reduction_x']}x"
        f" fewer; {delta_arm['delta_publishes']} deltas + "
        f"{delta_arm['full_publishes']} fulls), hit rate "
        f"{delta_arm['hot_hit_rate']}, write->servable mean "
        f"{full_arm['write_to_servable_s_mean']}s -> "
        f"{delta_arm['write_to_servable_s_mean']}s, fleet "
        f"{out['fleet']['queries_per_sec_total']:.0f} q/s over "
        f"{N_READERS} readers (p99 "
        f"{[r['latency_p99_s'] for r in readers]}), bit-identical "
        f"{out['states_bit_identical']}", file=sys.stderr)
    return {
        "metric": "delta_publish_bytes_reduction",
        "value": out["publish_bytes_reduction_x"],
        "unit": "x_fewer_bytes",
        # The A/B's own ratio mirrors the headline: full-arm publish
        # bytes over delta-arm publish bytes on the same stream.
        "vs_baseline": out["publish_bytes_reduction_x"],
        **out,
    }


def run_storage(args):
    """Hostile-filesystem brownout A/B (docs/resilience.md "Hostile
    filesystem"): the same logreg stream trained twice with per-chunk
    async publishes —

    * **clean**    — healthy storage;
    * **brownout** — ``fps_tpu.testing.faultfs`` injects a deterministic
      schedule against the snapshot plane: an EIO blackout window wide
      enough to exhaust the publish retry budget (the writer DEGRADES:
      skips the publish, raises checkpoint.publish_backlog) plus
      recurring slow-fsync latency, then recovery.

    Reported: training throughput retention (faulted/clean examples/s —
    the degradation must stay on the writer thread, not the training
    loop), the publish-backlog drain curve (rise through the blackout,
    cliff to 0 at the first landed publish), retry/degraded counts, and
    the headline invariant: final weights AND the final recovered
    snapshot's state are BIT-identical to the clean run's.

    ISSUE 20 (the raw-speed pass): both arms run the overlapped
    pipeline (``prefetch=2`` → boundary copies → ``save_deferred``) with
    ``when_full="degrade"`` — the device→host capture, the serialize,
    the fsync delays, AND the retry backoff all live on the writer
    thread, and a save arriving while the writer is wedged is skipped
    (recency spent, dispatch never stalled). The dump/capture second
    totals land in each arm: dump (what the TRAINING thread paid) must
    stay flat under brownout while capture absorbs the damage."""
    import dataclasses
    import tempfile
    import threading

    import jax

    from fps_tpu import obs
    from fps_tpu.core.checkpoint import AsyncCheckpointer
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import multi_epoch_chunks
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.testing import faultfs
    from fps_tpu.testing.faultfs import FaultRule

    from fps_tpu.utils.datasets import synthetic_sparse_classification

    mesh = make_ps_mesh()
    W = num_workers_of(mesh)
    NF, NNZ, EPOCHS = 2048, 16, 2
    data = synthetic_sparse_classification(120_000, NF, NNZ, seed=7,
                                           noise=0.05)
    data = dict(data, label=(data["label"] > 0).astype(np.float32))

    def make_chunks():
        return multi_epoch_chunks(data, EPOCHS, num_workers=W,
                                  local_batch=256, steps_per_chunk=8,
                                  seed=3)

    n_chunks = sum(1 for _ in make_chunks())
    # The blackout window: wide enough that one publish exhausts its
    # whole retry budget (4 attempts) and degrades, while the NEXT
    # publish fails twice and lands on its third attempt — both the
    # degrade and the retried-then-success paths are exercised.
    brownout_rules = [
        FaultRule("snapshot", "write", "errno", errno_name="EIO",
                  start=2, count=6),
        FaultRule("snapshot", "fsync", "delay", delay_s=0.01,
                  start=0, count=None, every=3),
    ]

    def run_arm(faulted: bool):
        cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
        trainer, store = logistic_regression(mesh, cfg)
        trainer.config = dataclasses.replace(trainer.config, prefetch=2)
        rec = obs.Recorder(sinks=[])
        trainer.recorder = rec
        # Checkpoint-layer telemetry (storage.retries, the backlog
        # gauge, checkpoint_degraded events) fires through the process
        # default, not the trainer's recorder.
        obs.events.set_default_recorder(rec)
        tables, ls = trainer.init_state(jax.random.key(0))
        fs = (faultfs.install(brownout_rules, seed=0)
              if faulted else None)
        curve = []  # (t_rel, backlog) drain-curve samples
        stop = threading.Event()
        with tempfile.TemporaryDirectory() as d:
            ck = AsyncCheckpointer(d, keep=n_chunks + 2,
                                   when_full="degrade")
            t0 = time.perf_counter()

            def sample():
                while not stop.is_set():
                    curve.append((round(time.perf_counter() - t0, 3),
                                  ck._publish_backlog))
                    stop.wait(0.02)

            sampler = threading.Thread(target=sample, daemon=True,
                                       name="bench-storage-sampler")
            sampler.start()
            try:
                tables, ls, m = trainer.fit_stream(
                    tables, ls, make_chunks(), jax.random.key(1),
                    checkpointer=ck, checkpoint_every=1)
                wall = time.perf_counter() - t0
                ck.flush()
            finally:
                stop.set()
                sampler.join(timeout=5.0)
                if fs is not None:
                    faultfs.uninstall()
                obs.events.set_default_recorder(None)
            curve.append((round(time.perf_counter() - t0, 3),
                          ck._publish_backlog))
            final_step = ck.latest_valid_step()
            _, snap_tables, _, _ = ck.read_snapshot(final_step)
            ck.close()
        n_ex = float(sum(np.asarray(mm["n"]).sum() for mm in m))
        # Downsample the curve: keep every change point (the drain
        # cliff) plus bounded padding.
        keep, last = [], None
        for t, b in curve:
            if b != last or len(keep) < 2:
                keep.append([t, int(b)])
                last = b
        hists = rec.snapshot()["histograms"]
        dump_h = hists.get("checkpoint.dump_seconds", {})
        cap_h = hists.get("checkpoint.capture_seconds", {})
        arm = {
            "examples_per_sec": round(n_ex / wall, 1),
            "wall_s": round(wall, 4),
            # The raw-speed split: dump = what each save cost the
            # TRAINING thread (an enqueue, with deferred capture);
            # capture = the device→host materialization the WRITER paid.
            "dump_seconds_total": round(dump_h.get("sum", 0.0), 6),
            "dump_count": int(dump_h.get("count", 0)),
            "capture_seconds_total": round(cap_h.get("sum", 0.0), 6),
            "capture_count": int(cap_h.get("count", 0)),
            "publishes_landed": ck.full_publishes + ck.delta_publishes,
            "degraded_publishes": ck.degraded_publishes,
            "retries": int(rec.counter_value("storage.retries",
                                             plane="checkpoint")),
            "backlog_final": ck._publish_backlog,
            "backlog_max": max((b for _, b in curve), default=0),
            "backlog_curve": keep[:40],
            "final_snapshot_step": final_step,
            "injected": (dict((f"{k[0]}/{k[1]}/{k[2]}", v) for k, v in
                              fs.injected_counts().items())
                         if fs is not None else None),
        }
        weights = store.lookup_host("weights", np.arange(NF))
        return arm, weights, snap_tables["weights"]

    # Warm-up (compile) outside the timed arms.
    from itertools import islice

    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    tw, sw = logistic_regression(mesh, cfg)
    t0s, l0s = tw.init_state(jax.random.key(9))
    tw.fit_stream(t0s, l0s, islice(make_chunks(), 2), jax.random.key(9))

    clean_arm, clean_w, clean_snap = run_arm(False)
    faulted_arm, faulted_w, faulted_snap = run_arm(True)
    retention = (faulted_arm["examples_per_sec"]
                 / clean_arm["examples_per_sec"]
                 if clean_arm["examples_per_sec"] else None)
    out = {
        "mesh": dict(mesh.shape), "chunks": n_chunks,
        "clean": clean_arm, "brownout": faulted_arm,
        "throughput_retention": (round(retention, 4)
                                 if retention else None),
        "weights_bit_identical": bool(
            np.array_equal(clean_w, faulted_w)),
        "recovered_snapshot_bit_identical": bool(
            np.array_equal(clean_snap, faulted_snap)),
        "backlog_drained": faulted_arm["backlog_final"] == 0,
    }
    print(
        f"storage brownout A/B: examples/s "
        f"{clean_arm['examples_per_sec']:.0f} -> "
        f"{faulted_arm['examples_per_sec']:.0f} (retention "
        f"{out['throughput_retention']}), degraded "
        f"{faulted_arm['degraded_publishes']} / retries "
        f"{faulted_arm['retries']}, backlog max "
        f"{faulted_arm['backlog_max']} drained "
        f"{out['backlog_drained']}, bit-identical "
        f"{out['weights_bit_identical']} (snapshot "
        f"{out['recovered_snapshot_bit_identical']}), dump_s "
        f"{clean_arm['dump_seconds_total']:.3f} -> "
        f"{faulted_arm['dump_seconds_total']:.3f} / capture_s "
        f"{clean_arm['capture_seconds_total']:.3f} -> "
        f"{faulted_arm['capture_seconds_total']:.3f}", file=sys.stderr)
    return {
        "metric": "storage_brownout_throughput_retention",
        "value": out["throughput_retention"],
        "unit": "x_retention",
        "vs_baseline": out["throughput_retention"],
        **out,
    }


def run_wire(args):
    """Hostile-network wire A/B (docs/resilience.md "Hostile network"):
    one fixed snapshot served over TCP three ways —

    * **legacy**   — raw line-JSON over a plain socket (the pre-wire
      protocol, still accepted by the dual-stack server for one
      release);
    * **framed**   — ``WireClient`` (versioned frames, CRC32, deadlines,
      bounded retry) at the SAME request sequence and load;
    * **brownout** — framed again, but under a deterministic
      ``fps_tpu.testing.faultnet`` schedule (refused reconnects,
      recurring mid-frame cuts, injected send latency) against an
      admission-limited server with hammer threads forcing BUSY sheds.

    Reported: framed-vs-legacy throughput ratio at equal load (framing
    must not cost throughput), shed-rate / retry / reconnect /
    torn-frame counts through the brownout, and RECOVERY BIT-IDENTITY:
    every brownout response byte-identical to the clean framed run's
    (retries and replays never corrupt or duplicate an answer)."""
    import threading

    from fps_tpu.serve import (
        ReadServer,
        ServableSnapshot,
        TcpServe,
        WireClient,
    )
    from fps_tpu.testing import faultnet
    from fps_tpu.testing.faultnet import NetFaultRule

    NROWS, RANK, N_REQ, N_WARM = 4096, 16, 300, 10
    rng = np.random.default_rng(0)
    tables = {"weights": rng.normal(
        size=(NROWS, RANK)).astype(np.float32)}

    def make_server():
        server = ReadServer()
        server.swap_to(ServableSnapshot(7, "bench-wire", tables, [],
                                        "none"))
        return server

    reqs = [{"op": "pull", "table": "weights",
             "ids": rng.integers(0, NROWS, 64).tolist()}
            for _ in range(N_REQ)]

    def drive(client):
        """Warm up, then time the fixed sequence; returns
        (queries_per_sec, [response dicts])."""
        for r in reqs[:N_WARM]:
            client.request(r)
        resps = []
        t0 = time.perf_counter()
        for r in reqs:
            resps.append(client.request(r))
        wall = time.perf_counter() - t0
        if not resps or any(not r.get("ok") for r in resps):
            raise RuntimeError("wire bench arm produced a failed or "
                               "empty response — that is an error, "
                               "not a rate")
        return round(N_REQ / wall, 1), resps

    class _LineClient:
        """The ACTUAL old protocol (JsonlClient is a framed shim now):
        one JSON object per line, raw socket."""

        def __init__(self, host, port):
            import socket

            self._sock = socket.create_connection((host, port),
                                                  timeout=10.0)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
            self._rfile = self._sock.makefile("rb")

        def request(self, req):
            self._sock.sendall(json.dumps(req).encode("utf-8") + b"\n")
            line = self._rfile.readline()
            if not line:
                raise ConnectionError("server closed the connection")
            return json.loads(line)

        def close(self):
            self._rfile.close()
            self._sock.close()

    # -- clean arms: legacy vs framed against one healthy server.
    # Interleaved rounds, median of the PAIRED per-round ratios:
    # absolute localhost throughput drifts far more run-to-run than
    # the few-percent protocol delta under measurement, but both arms
    # of one round share the same box conditions, so the paired ratio
    # is the stable quantity.
    N_ROUNDS = 5
    rounds = []
    legacy_resps = framed_resps = None
    with TcpServe(make_server()) as tcp:
        for _ in range(N_ROUNDS):
            legacy = _LineClient(tcp.host, tcp.port)
            lq, legacy_resps = drive(legacy)
            legacy.close()
            with WireClient(tcp.host, tcp.port) as wc:
                fq, framed_resps = drive(wc)
            rounds.append((fq / lq, lq, fq))
        clean_stats = tcp.wire_stats()
    rounds.sort()
    _, legacy_qps, framed_qps = rounds[len(rounds) // 2]

    # -- brownout arm: deterministic net faults on the measured client
    # ("client" stream; the hammer threads get their own peer class so
    # the schedule stays replayable) + admission-limited server.
    brownout_rules = [
        # The measured client's first two RECONNECT attempts are
        # refused (connect #0 is the constructor): a reconnect storm
        # that must back off and then resume under the same req_id.
        NetFaultRule("client", "connect", "refuse", start=1, count=2),
        # Recurring mid-frame cuts: torn frames the server must count
        # and never decode; the client reconnects and resends.
        NetFaultRule("client", "send", "cut", cut_bytes=6, start=10,
                     count=None, every=25),
        # Background send latency (congested path).
        NetFaultRule("client", "send", "delay", delay_s=0.001,
                     start=0, count=None, every=7),
    ]
    net = faultnet.install(brownout_rules, seed=0)
    try:
        with TcpServe(make_server()) as tcp:
            wc = WireClient(tcp.host, tcp.port, peer_class="client")
            brown_qps, brown_resps = drive(wc)
            wc.close()
            brown_stats = tcp.wire_stats()
    finally:
        faultnet.uninstall()

    # -- load-shed phase: an admission-limited server (max_inflight=1)
    # whose ONLY execution slot is wedged for a window — every request
    # arriving during the wedge is shed with a retryable BUSY that the
    # hammers' WireClients absorb through their retry budgets; after
    # the slot frees, the same clients recover and get served. Lost
    # WORK, never corruption (docs/STALENESS.md).
    server = make_server()
    with TcpServe(server, max_inflight=1) as tcp:
        stop = threading.Event()
        busy_counts = [0] * 3

        def hammer(idx):
            hc = WireClient(tcp.host, tcp.port, peer_class="hammer")
            while not stop.is_set():
                try:
                    hc.request(reqs[0])
                except Exception:  # noqa: BLE001 — shed work is lost work
                    continue
            busy_counts[idx] = hc.busy_rejections
            hc.close()

        hammers = [threading.Thread(target=hammer, args=(i,),
                                    daemon=True,
                                    name=f"bench-wire-hammer-{i}")
                   for i in range(3)]
        # Wedge the whole cost budget: full house, every request sheds.
        assert tcp.admission.try_admit(tcp.admission.max_cost)
        for t in hammers:
            t.start()
        time.sleep(0.5)
        tcp.admission.release(tcp.admission.max_cost)  # brownout lifts
        time.sleep(0.5)
        stop.set()
        for t in hammers:
            t.join(timeout=10.0)
        shed_stats = tcp.wire_stats()
        served = server.requests

    shed_rate = (shed_stats["shed_requests"]
                 / max(shed_stats["shed_requests"] + served, 1))
    out = {
        "rows": NROWS, "requests": N_REQ,
        "legacy": {"queries_per_sec": legacy_qps},
        "framed": {"queries_per_sec": framed_qps,
                   "wire_stats": clean_stats},
        "brownout": {
            "queries_per_sec": brown_qps,
            "client_retries": wc.retries,
            "client_reconnects": wc.reconnects,
            "wire_stats": brown_stats,
            "injected": dict((f"{k[0]}/{k[1]}/{k[2]}", v) for k, v in
                             net.injected_counts().items()),
        },
        "loadshed": {
            "shed_rate": round(shed_rate, 4),
            "shed_requests": shed_stats["shed_requests"],
            "served_requests": int(served),
            "client_busy_rejections": sum(busy_counts),
        },
        "framed_vs_legacy": round(framed_qps / legacy_qps, 4),
        "responses_bit_identical": bool(
            legacy_resps == framed_resps == brown_resps),
    }
    print(
        f"wire A/B: legacy {legacy_qps:.0f} q/s -> framed "
        f"{framed_qps:.0f} q/s ({out['framed_vs_legacy']}x); brownout "
        f"{brown_qps:.0f} q/s with {wc.retries} retries / "
        f"{wc.reconnects} reconnects / "
        f"{brown_stats['torn_frames']} torn frames; shed rate "
        f"{out['loadshed']['shed_rate']} "
        f"({shed_stats['shed_requests']} shed / {served} served), "
        f"responses bit-identical "
        f"{out['responses_bit_identical']}", file=sys.stderr)
    return {
        "metric": "wire_framed_vs_legacy_qps",
        "value": out["framed_vs_legacy"],
        "unit": "x_legacy_throughput",
        "vs_baseline": out["framed_vs_legacy"],
        **out,
    }


def run_serve_scale(args):
    """Closed-loop user-scale read-plane load (ISSUE 19's tentpole
    witness): a Zipf population of users pulls its feature bundles
    against a live autoscaled ServingFleet over the batched zero-copy
    wire, while a publisher keeps hot-swapping fresh snapshots under
    the load. Four measurements:

    * **unbatched** — the PR-16 shape (one frame per request, JSON
      responses): the p50/p99/p999 reference every batched number is
      judged against.
    * **batch curve** — per-frame latency + aggregate requests/s at
      batch sizes 1..512 over the binary multi path: the amortization
      curve ``docs/performance.md`` reprints.
    * **scaled run** — diurnal shape (ramp → flash crowd → cool) of
      closed-loop users against the whole fleet, the autoscaler
      evaluating live (its decisions reported), snapshots publishing
      throughout; the flash-crowd aggregate q/s is the headline, with
      the fence-lag freshness sampled continuously — a flash crowd
      must cost latency, never staleness.
    * **operating point** — the largest curve batch whose per-frame
      p99 stays within 2x the unbatched p99 (the acceptance bound).

    ``vs_baseline`` is the flash-crowd aggregate against BENCH_r14's
    3-reader fleet total (1477.5 q/s, the unbatched read plane)."""
    import os
    import tempfile
    import threading

    from fps_tpu.core import snapshot_format as fmt
    from fps_tpu.serve import (
        NoSnapshotError,
        ReadAutoscaler,
        ServingFleet,
        TcpServe,
        WireClient,
    )
    from fps_tpu.serve.wire import CAP_BIN, CAP_MULTI

    R14_FLEET_QPS = 1477.5
    NROWS, RANK, IDS_PER_REQ = 65536, 16, 16
    N_USERS = 100_000
    rng = np.random.default_rng(19)

    # Zipf user population: each request is one user's pull of its
    # (fixed) feature bundle, users drawn zipf so the head repeats —
    # the access pattern the warm caches and gathers actually see.
    user_rows = rng.integers(0, NROWS, size=(N_USERS, IDS_PER_REQ))
    zipf_users = (rng.zipf(1.2, size=1 << 14) - 1) % N_USERS
    req_pool = [{"op": "pull", "table": "emb",
                 "ids": user_rows[u].tolist()} for u in zipf_users]

    table = rng.normal(size=(NROWS, RANK)).astype(np.float32)
    ckpt_dir = tempfile.mkdtemp(prefix="fps-serve-scale-")
    published = [0]
    publish_lock = threading.Lock()

    def publish_next():
        with publish_lock:
            published[0] += 1
            step = published[0]
            # A few hot rows move per publish: real swaps, tiny deltas.
            table[rng.integers(0, NROWS, 64)] += 0.001
            arrays = {"table::emb": table,
                      "meta::ls_format": np.array("exported")}
            for k in list(arrays):
                arrays["meta::crc::" + k] = np.uint32(
                    fmt.array_crc32(arrays[k]))
            np.savez(fmt.snapshot_path(ckpt_dir, step), **arrays)
            return step

    publish_next()
    fleet = ServingFleet(ckpt_dir, 2)
    scaler = ReadAutoscaler(fleet, min_readers=2, max_readers=6,
                            latency_slo_s=0.002,
                            fence_lag_slo_steps=8.0, cooldown_s=0.5,
                            liveness_timeout_s=10.0)

    # One TcpServe per live reader, kept in sync with the autoscaler's
    # membership changes; workers round-robin the current set.
    serves: dict = {}
    serve_lock = threading.Lock()

    def sync_serves():
        with serve_lock:
            live = {r.reader_id: r for r in fleet.readers}
            for rid in [r for r in serves if r not in live]:
                serves.pop(rid).close()
            for rid, r in live.items():
                if rid not in serves:
                    serves[rid] = TcpServe(r.server).start()
            return list(serves.items())

    stop = threading.Event()
    active_n = [0]    # workers with idx < active_n[0] run (load shape)
    batch_n = [1]
    recording: list = [None]  # per-phase (latency_s, batch) sink
    N_WORKERS = 8

    def worker(idx):
        clients: dict = {}
        pos = idx * 1013
        while not stop.is_set():
            if idx >= active_n[0]:
                time.sleep(0.005)
                continue
            with serve_lock:
                targets = list(serves.items())
            if not targets:
                time.sleep(0.01)
                continue
            rid, tcp = targets[(pos // 7) % len(targets)]
            wc = clients.get(rid)
            if wc is None or wc.port != tcp.port:
                try:
                    clients[rid] = wc = WireClient(
                        tcp.host, tcp.port, caps=(CAP_MULTI, CAP_BIN))
                except OSError:
                    time.sleep(0.01)
                    continue
            B = batch_n[0]
            batch = [req_pool[(pos + j) % len(req_pool)]
                     for j in range(B)]
            pos += B
            t0 = time.perf_counter()
            try:
                if B == 1:
                    ok = wc.request(batch[0]).get("ok")
                else:
                    ok = all(r.get("ok") for r in wc.multi(batch))
            except Exception:  # noqa: BLE001 — churned reader: move on
                clients.pop(rid, None)
                continue
            dt = time.perf_counter() - t0
            sink = recording[0]
            if ok and sink is not None:
                sink.append((dt, B))
        for wc in clients.values():
            wc.close()

    def measure(n_active, B, seconds):
        """One closed-loop phase; returns (aggregate requests/s,
        per-frame latency percentiles, frames)."""
        sink: list = []
        batch_n[0] = B
        active_n[0] = n_active
        time.sleep(0.15)   # let the shape settle before recording
        recording[0] = sink
        time.sleep(seconds)
        recording[0] = None
        lat = np.array([d for d, _ in sink]) if sink else np.array([])
        reqs_done = sum(b for _, b in sink)
        pct = {p: (round(float(np.percentile(lat, q)), 6)
                   if lat.size else None)
               for p, q in (("p50", 50), ("p99", 99), ("p999", 99.9))}
        return round(reqs_done / seconds, 1), pct, len(sink)

    fence_trail: list = []

    def sample_fence():
        fence = fleet.readers[0].fence
        while not stop.is_set():
            f = fence.read()
            if f is not None:
                fence_trail.append(published[0] - f[1])
            time.sleep(0.02)

    out = {"rows": NROWS, "rank": RANK, "ids_per_request": IDS_PER_REQ,
           "users": N_USERS, "workers": N_WORKERS}
    workers = [threading.Thread(target=worker, args=(i,), daemon=True,
                                name=f"bench-scale-user{i}")
               for i in range(N_WORKERS)]
    fleet.start(interval_s=0.02)
    sync_serves()
    try:
        dl = time.monotonic() + 30.0
        while time.monotonic() < dl:
            try:
                if all(r.server.snapshot.step >= 1
                       for r in fleet.readers):
                    break
            except NoSnapshotError:
                pass
            time.sleep(0.02)
        for t in workers:
            t.start()

        # -- unbatched reference (the PR-16 shape, 4 users).
        measure(4, 1, 0.5)  # warm connections + caches off the record
        unb_qps, unb_pct, _ = measure(4, 1, 2.0)
        out["unbatched"] = {"queries_per_sec": unb_qps, **unb_pct}

        # -- batch-size/latency curve (1 user, binary multi).
        curve = []
        for B in (1, 8, 32, 128, 512):
            qps, pct, frames = measure(1, B, 1.0)
            curve.append({"batch": B, "queries_per_sec": qps,
                          "frames": frames, **pct})
        out["batch_curve"] = curve
        # Operating point: largest batch whose per-frame p99 holds
        # within 2x the unbatched p99.
        bound = 2.0 * (unb_pct["p99"] or float("inf"))
        oper = [c for c in curve
                if c["p99"] is not None and c["p99"] <= bound]
        oper_b = max((c["batch"] for c in oper), default=32)
        out["operating_batch"] = oper_b
        out["p99_bound_s"] = round(bound, 6)

        # -- scaled run: publisher + autoscaler live, diurnal shape.
        pub_stop = threading.Event()

        def publisher():
            while not pub_stop.is_set():
                publish_next()
                pub_stop.wait(0.3)

        def autoscale_loop():
            while not pub_stop.is_set():
                scaler.evaluate(newest_step=published[0])
                sync_serves()
                pub_stop.wait(0.2)

        sampler = threading.Thread(target=sample_fence, daemon=True)
        pub_t = threading.Thread(target=publisher, daemon=True)
        auto_t = threading.Thread(target=autoscale_loop, daemon=True)
        sampler.start()
        pub_t.start()
        auto_t.start()
        phases = {}
        flash_lag_start = None
        for name, n_active, seconds in (("ramp", 2, 1.5),
                                        ("flash", N_WORKERS, 2.5),
                                        ("cool", 2, 1.5)):
            if name == "flash":
                flash_lag_start = len(fence_trail)
            qps, pct, frames = measure(n_active, oper_b, seconds)
            phases[name] = {"queries_per_sec": qps, "frames": frames,
                            "active_users": n_active, **pct}
        flash_lags = fence_trail[flash_lag_start:len(fence_trail)]
        pub_stop.set()
        pub_t.join(timeout=10)
        auto_t.join(timeout=10)
        out["phases"] = phases
        out["published_steps"] = published[0]
        out["fence_lag_steps_max"] = (max(fence_trail)
                                      if fence_trail else None)
        out["flash_fence_lag_max"] = (max(flash_lags)
                                      if flash_lags else None)
        out["autoscale"] = {
            "final_fleet_size": len(fleet.readers),
            "actions": sorted({d["action"] for d in scaler.decisions
                               if d["action"] != "hold"}),
            "evaluations": len(scaler.decisions),
        }
    finally:
        stop.set()
        for t in workers:
            t.join(timeout=10)
        with serve_lock:
            for tcp in serves.values():
                tcp.close()
            serves.clear()
        fleet.stop()

    flash_qps = phases["flash"]["queries_per_sec"]
    oper_curve = next(c for c in curve if c["batch"] == oper_b)
    out["aggregate_queries_per_sec"] = flash_qps
    out["speedup_vs_r14_fleet"] = round(flash_qps / R14_FLEET_QPS, 2)
    out["p99_within_2x_unbatched"] = bool(
        oper_curve["p99"] is not None and oper_curve["p99"] <= bound)
    out["fence_slo_held_in_flash"] = bool(
        out["flash_fence_lag_max"] is not None
        and out["flash_fence_lag_max"] <= scaler.fence_lag_slo_steps)
    print(
        f"serve_scale: unbatched {unb_qps:.0f} q/s "
        f"(p99 {unb_pct['p99']}s) -> batch {oper_b} flash crowd "
        f"{flash_qps:.0f} q/s ({out['speedup_vs_r14_fleet']}x r14 "
        f"fleet), frame p99 {oper_curve['p99']}s "
        f"(bound {out['p99_bound_s']}s), flash fence lag max "
        f"{out['flash_fence_lag_max']} steps, fleet "
        f"{out['autoscale']['final_fleet_size']} readers "
        f"({out['autoscale']['actions']})", file=sys.stderr)
    return {
        "metric": "serve_scale_aggregate_qps",
        "value": flash_qps,
        "unit": "queries/s",
        "vs_baseline": out["speedup_vs_r14_fleet"],
        **out,
    }


def run_restart(args):
    """The cost of the restart ITSELF (ISSUE 20): wedge a real training
    child under ``tools/supervise.py`` twice — once with
    ``--compilation-cache-dir`` (a persistent XLA cache every attempt
    shares) and once without — and report the supervisor's
    ``restart_to_first_signal_s`` for both: seconds from the supervisor
    killing the wedged attempt to its replacement observably making
    progress. One supervised run per arm is the honest A/B: the FIRST
    attempt populates the cache, so the restarted attempt is the warm
    reader. On CPU the recompile is cheap and the arms sit close; on a
    real TPU recompilation dominates the restart, which is what the
    cache-dir flag exists to kill."""
    import os
    import subprocess
    import tempfile

    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=root,
               # Cache even sub-second CPU compiles so the with-cache
               # arm exercises the real read path (no-op without a
               # cache dir, so the cold arm is untouched).
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0",
               JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="-1")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    demo = [sys.executable, "-m", "fps_tpu.testing.supervised_demo",
            "--examples", "8000", "--epochs", "2"]

    def one_arm(workdir, cache_dir):
        sup_dir = os.path.join(workdir, "sup")
        cmd = [sys.executable,
               os.path.join(root, "tools", "supervise.py"),
               "--state-dir", sup_dir, "--stall-timeout-s", "10",
               "--startup-grace-s", "300", "--term-grace-s", "2",
               "--backoff-base-s", "0.2", "--max-restarts", "2",
               "--poll-s", "0.2"]
        if cache_dir is not None:
            cmd += ["--compilation-cache-dir", cache_dir]
        cmd += ["--", *demo, "--ckpt-dir", sup_dir,
                "--out", os.path.join(workdir, "out.npz"),
                "--wedge-at", "3", "--wedge-mode", "sigstop"]
        r = subprocess.run(cmd, env=env, cwd=root, capture_output=True,
                           text=True, timeout=600)
        try:
            digest = json.loads(r.stdout.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            return {"success": False,
                    "error": (r.stdout + r.stderr)[-500:]}
        rts = [round(float(t), 3) for t in
               digest.get("restart_to_first_signal_s") or []]
        return {"success": bool(digest.get("success")),
                "restarts": digest.get("restarts"),
                "restart_to_first_signal_s": rts,
                "worst_s": max(rts) if rts else None}

    with tempfile.TemporaryDirectory() as d:
        cache = os.path.join(d, "xla-cache")
        cold = one_arm(os.path.join(d, "cold"), None)
        warm = one_arm(os.path.join(d, "warm"), cache)
        cache_entries = sum(len(fs) for _, _, fs in os.walk(cache))

    cold_s, warm_s = cold.get("worst_s"), warm.get("worst_s")
    speedup = (round(cold_s / warm_s, 3)
               if cold_s and warm_s else None)
    print(f"restart: restart_to_first_signal_s "
          f"{cold_s} (no cache) -> {warm_s} "
          f"(--compilation-cache-dir, {cache_entries} cache entries), "
          f"ratio {speedup}", file=sys.stderr)
    return {
        "metric": "restart_to_first_signal_s",
        "value": warm_s,
        "unit": "s",
        "vs_baseline": speedup,
        "without_cache": cold,
        "with_cache": warm,
        "compilation_cache_entries": cache_entries,
    }


RUNNERS = {"mf": run_mf, "w2v": run_w2v, "logreg": run_logreg,
           "pa": run_pa, "ials": run_ials, "tiered": run_tiered,
           "tiered_drift": run_tiered_drift, "serve": run_serve,
           "megastep": run_megastep_ab, "delta": run_delta,
           "storage": run_storage, "wire": run_wire,
           "serve_scale": run_serve_scale, "restart": run_restart}


def compact_summary(results):
    """Digest for the driver-parsed FINAL stdout line.

    Per workload only {value, vs_baseline}, floats rounded to 4
    significant-ish decimals — no nested baseline dicts, no prose, no
    per-workload unit or metric string (the workload KEY names the row;
    the headline's metric/unit ride at top level. The serve workload
    already cost the units, and tiered_drift's eighth entry cost the
    metric copies — each shrink is what keeps the line inside the
    driver's bounded tail window) — so the whole line stays <=1000
    bytes (asserted in the contract test against worst-case verbose
    stubs). The headline (mf when present, else the last completed
    workload) is mirrored at top level for the driver's single-metric
    parse. Emitted CUMULATIVELY after every workload in all-mode: if
    the run is killed partway (the full bench is ~10+ min of mostly
    compilation on the tunnel), the final stdout line is still a
    parseable digest of everything that finished.
    """
    def rnd(v):
        return round(v, 4) if isinstance(v, float) else v

    digest = {
        name: {k: rnd(res.get(k)) for k in ("value", "vs_baseline")}
        for name, res in results.items()
    }
    head_name = "mf" if "mf" in digest else (
        list(digest)[-1] if digest else None)
    head = results.get(head_name, {}) if head_name else {}
    return {"metric": head.get("metric"), "value": rnd(head.get("value")),
            "unit": head.get("unit"),
            "vs_baseline": rnd(head.get("vs_baseline")),
            "workloads": digest}


def _enable_compilation_cache():
    """Persistent XLA compilation cache: the full 5-workload bench is
    ~10+ min of which compiles dominate; a warm cache (any earlier bench
    or example run in the same container) cuts that several-fold. Purely
    best-effort — unsupported flags or a read-only tmp must never break
    the bench."""
    import os

    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("FPS_TPU_JAX_CACHE",
                                         "/tmp/fps_tpu_jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - depends on jax build
        print(f"compilation cache unavailable: {e}", file=sys.stderr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="all",
                    choices=["all", "mf", "w2v", "logreg", "pa", "ials",
                             "tiered", "tiered_drift", "serve",
                             "megastep", "delta", "storage", "wire",
                             "serve_scale", "restart"])
    ap.add_argument("--scale", default="20m", choices=["100k", "1m", "20m"])
    ap.add_argument("--rank", type=int, default=10)
    ap.add_argument("--local-batch", type=int, default=32768)
    ap.add_argument("--movielens-path", default=None)
    ap.add_argument("--text8-path", default=None)
    ap.add_argument("--input", default=None,
                    help="real dataset file for --workload logreg "
                         "(Criteo TSV or svmlight; default: synthetic)")
    ap.add_argument("--num-tokens", type=int, default=17_000_000)
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--block-len", type=int, default=8192)
    ap.add_argument("--rmse-target", type=float, default=0.12,
                    help="mf workload: train to this train-RMSE "
                         "(planted-structure noise floor is ~0.1)")
    ap.add_argument("--recall-target", type=float, default=0.35,
                    help="ials workload: train to this recall@20 on the "
                         "held-out planted-implicit split (plateau ~0.39, "
                         "chance 20/16384 = 0.0012)")
    ap.add_argument("--max-epochs", type=int, default=8)
    args = ap.parse_args()
    _enable_compilation_cache()

    if args.workload == "all":
        # Headline (mf) LAST among the per-workload lines.
        order = ["w2v", "logreg", "pa", "ials", "tiered", "tiered_drift",
                 "serve", "megastep", "delta", "storage", "wire",
                 "serve_scale", "restart", "mf"]
    else:
        order = [args.workload]
    results = {}
    for name in order:
        print(f"--- workload: {name} ---", file=sys.stderr)
        results[name] = RUNNERS[name](args)
        print(json.dumps(results[name]), flush=True)
        if args.workload == "all" and name != order[-1]:
            # Cumulative digest after every non-final workload (see
            # compact_summary): a killed run's final line still certifies
            # what completed. The last workload's digest IS the final
            # line printed after the rich combined line below.
            print(json.dumps(compact_summary(results)), flush=True)

    if args.workload == "all":
        # Self-certifying artifact: the driver parses the FINAL line and
        # keeps only a bounded TAIL, so the last line must carry every
        # workload's result by itself AND fit the tail window. Round 3's
        # tail truncated mid-stream; round 4's single rich combined line
        # (nested baseline dicts, prose "kind" strings) was itself longer
        # than the window and BENCH_r04.json.parsed came back null. So:
        # the rich combined line goes out first, and the FINAL line is a
        # compact digest — per workload only {metric, value, unit,
        # vs_baseline}, floats rounded — size-asserted at <=1000 bytes by
        # tests/test_examples.py::test_bench_combined_summary_line_contract.
        # Top-level keys stay the mf headline for the driver's
        # metric/value/vs_baseline parse.
        mf = results["mf"]
        combined = {
            "metric": mf["metric"],
            "value": mf["value"],
            "unit": mf["unit"],
            "vs_baseline": mf["vs_baseline"],
            "workloads": results,
        }
        print(json.dumps(combined), flush=True)
        print(json.dumps(compact_summary(results)), flush=True)


if __name__ == "__main__":
    sys.exit(main())
