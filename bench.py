"""Headline benchmark: MovieLens-20M-scale online MF epoch time on TPU.

BASELINE.json metric: "MovieLens-20M MF epoch time" (the reference publishes
no numbers — ``"published": {}`` — so the baseline here is an *emulated*
Flink-CPU parameter server: a per-record pull/update/push loop in the style
of the reference's ``WorkerCoFlatMap``/``PSFlatMap`` hot path, measured on a
sample and extrapolated to the full epoch, then credited a generous JVM
speedup factor over CPython).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}
vs_baseline > 1 means this framework is faster than the emulated baseline.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def emulated_flink_cpu_epoch_s(data, num_ratings_full, rank, sample=60_000,
                               jvm_speedup=10.0):
    """Per-record PS loop (pull item vec -> SGD -> push delta), CPython,
    extrapolated to the full epoch and divided by an assumed JVM advantage."""
    users = data["user"][:sample]
    items = data["item"][:sample]
    ratings = data["rating"][:sample]
    num_users = int(users.max()) + 1
    num_items = int(items.max()) + 1
    rng = np.random.default_rng(0)
    P = rng.uniform(-0.1, 0.1, (num_users, rank))
    Q = rng.uniform(-0.1, 0.1, (num_items, rank))
    lr = 0.05
    t0 = time.perf_counter()
    for k in range(sample):
        u, i, r = users[k], items[k], ratings[k]
        q = Q[i]  # pull
        p = P[u]
        err = r - p @ q
        P[u] = p + lr * (err * q - 0.01 * p)
        Q[i] = q + lr * (err * p - 0.01 * q)  # push
    dt = time.perf_counter() - t0
    per_record = dt / sample
    return per_record * num_ratings_full / jvm_speedup


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", default="20m", choices=["100k", "1m", "20m"])
    ap.add_argument("--rank", type=int, default=10)
    ap.add_argument("--local-batch", type=int, default=131072)
    ap.add_argument("--movielens-path", default=None)
    args = ap.parse_args()

    import jax

    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import load_movielens

    data, nu, ni = load_movielens(args.movielens_path, args.scale)
    nr = len(data["user"])

    devs = jax.devices()
    nd, ns = default_mesh_shape(len(devs))
    mesh = make_ps_mesh(num_shards=ns, num_data=nd)
    W = num_workers_of(mesh)

    cfg = MFConfig(num_users=nu, num_items=ni, rank=args.rank,
                   learning_rate=0.05, reg=0.01)
    trainer, store = online_mf(mesh, cfg)
    tables, local_state = trainer.init_state(jax.random.key(0))

    dataset = DeviceDataset(mesh, data)  # one-time upload, outside the epoch
    plan = DeviceEpochPlan(
        dataset,
        num_workers=W,
        local_batch=args.local_batch,
        route_key="user",
        seed=1,
    )

    # Warm-up: compile + one full epoch (ingest is fused into the jit, so
    # the whole epoch — shuffle, batch gathers, training — is ONE dispatch).
    tables, local_state, _ = trainer.run_indexed(
        tables, local_state, plan, jax.random.key(9)
    )

    t0 = time.perf_counter()
    tables, local_state, metrics = trainer.run_indexed(
        tables, local_state, plan, jax.random.key(1)
    )
    epoch_s = time.perf_counter() - t0

    baseline_s = emulated_flink_cpu_epoch_s(data, nr, args.rank)

    print(json.dumps({
        "metric": f"ml{args.scale}_mf_epoch_time",
        "value": round(epoch_s, 4),
        "unit": "s",
        "vs_baseline": round(baseline_s / epoch_s, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
