"""Headline benchmark: MovieLens-20M-scale online MF time-to-quality on TPU.

BASELINE.json metric: "MovieLens-20M MF epoch time; text8 word2vec
words/sec/chip" (the reference publishes no numbers — ``"published": {}`` —
so the baseline here is an *emulated* Flink-CPU parameter server: a
per-record pull/update/push loop in the style of the reference's
``WorkerCoFlatMap``/``PSFlatMap`` hot path, measured on a sample and
extrapolated to the full epoch, then credited a generous JVM speedup factor
over CPython).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N, ...}
vs_baseline > 1 means this framework is faster than the emulated baseline.

``--workload mf`` (default) reports ML-20M MF **wall-clock to
train-RMSE <= 0.12** on the planted-structure set (noise floor ~0.1),
plus epoch count and the median epoch time — time-to-fixed-quality is the
firm cross-system comparison (a raw epoch time rewards configurations
that stream fast but learn slowly); compile time is excluded via a
warm-up epoch on throwaway state. ``--workload w2v`` reports text8-scale
word2vec SGNS words/sec/chip; ``--workload logreg`` reports Criteo-style
SSP logistic-regression examples/sec/chip.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def first_last_real_step(metrics, key):
    """Per-example metric value at the first and last non-padding step of
    one epoch's metrics dict (trailing steps are weight-0 padding)."""
    vals = np.asarray(metrics[key])
    counts = np.asarray(metrics["n"])
    real = np.flatnonzero(counts > 0)
    if len(real) == 0:  # degenerate shard: every step was padding
        return float("nan"), float("nan")
    return (vals[real[0]] / counts[real[0]],
            vals[real[-1]] / counts[real[-1]])


def emulated_flink_cpu_w2v_per_pair_s(uni, dim, negatives,
                                      sample_pairs=8_000, jvm_speedup=10.0):
    """Seconds per (center, context) pair for an emulated per-pair SGNS
    pull/update/push loop in CPython (credited a JVM speedup); the caller
    converts to words/sec via its own pair count."""
    V = len(uni)
    rng = np.random.default_rng(0)
    IN = rng.uniform(-0.5 / dim, 0.5 / dim, (V, dim))
    OUT = np.zeros((V, dim))
    p = uni.astype(np.float64) ** 0.75
    p /= p.sum()
    cdf = np.cumsum(p)
    centers = rng.integers(0, V, sample_pairs)
    contexts = rng.integers(0, V, sample_pairs)
    lr = 0.025
    t0 = time.perf_counter()
    for k in range(sample_pairs):
        c, x = centers[k], contexts[k]
        ids = [x] + list(np.searchsorted(cdf, rng.random(negatives)))
        v = IN[c]  # pull center
        dv = np.zeros(dim)
        for j, o in enumerate(ids):
            u = OUT[o]  # pull context/negative
            g = 1.0 / (1.0 + np.exp(-v @ u)) - (1.0 if j == 0 else 0.0)
            dv -= lr * g * u
            OUT[o] = u - lr * g * v  # push
        IN[c] = v + dv  # push
    per_pair = (time.perf_counter() - t0) / sample_pairs / jvm_speedup
    # pairs per epoch ~ 2 * E[half] * kept tokens; with subsample t=1e-4
    # and dynamic window this matches the TPU path's own pair count, so
    # compare on raw-token throughput instead of per-pair rates.
    return per_pair


def run_w2v(args):
    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.word2vec import (
        W2VConfig, Word2VecDevicePlan, word2vec_block,
    )
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import load_text8

    tokens, V, uni = load_text8(
        args.text8_path, vocab_size=50_000, num_tokens=args.num_tokens
    )
    devs = jax.devices()
    nd, ns = default_mesh_shape(len(devs))
    mesh = make_ps_mesh(num_shards=ns, num_data=nd)
    W = num_workers_of(mesh)

    cfg = W2VConfig(vocab_size=V, dim=args.dim, window=5, negatives=5)
    # Block-granularity worker: each block position's IN/OUT row is pulled
    # and pushed once per step (sparse row ops are per-transaction bound on
    # TPU — this is ~10x fewer transactions than per-pair pull/push).
    # Cap each dispatch well under the TPU runtime's per-dispatch deadline.
    trainer, store = word2vec_block(
        mesh, cfg, uni, args.block_len, max_steps_per_call=256
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    plan = Word2VecDevicePlan(
        tokens, uni, cfg, mesh, num_workers=W,
        block_len=args.block_len, seed=1, mode="block",
    )

    # Warm-up epoch: compiles the fused program.
    tables, ls, m = trainer.run_indexed(tables, ls, plan, jax.random.key(9))

    t0 = time.perf_counter()
    tables, ls, metrics = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1)
    )
    epoch_s = time.perf_counter() - t0
    words_s = len(tokens) / epoch_s / len(devs)  # per chip

    per0, per1 = first_last_real_step(metrics[0], "loss")
    print(
        f"quality: SGNS loss/pair step0 {per0:.4f} -> last-real-step "
        f"{per1:.4f} (epoch 2; init loss = (1+K)*log2 = "
        f"{0.6931 * (1 + cfg.negatives):.3f})",
        file=sys.stderr,
    )

    pairs = float(metrics[0]["n"].sum())
    per_pair_s = emulated_flink_cpu_w2v_per_pair_s(
        uni, cfg.dim, cfg.negatives
    )
    baseline_words_s = len(tokens) / (pairs * per_pair_s)

    print(json.dumps({
        "metric": "text8_w2v_words_per_sec_per_chip",
        "value": round(words_s, 1),
        "unit": "words/s",
        "vs_baseline": round(words_s / baseline_words_s, 2),
    }))


def emulated_flink_cpu_epoch_s(data, num_ratings_full, rank, sample=60_000,
                               jvm_speedup=10.0):
    """Per-record PS loop (pull item vec -> SGD -> push delta), CPython,
    extrapolated to the full epoch and divided by an assumed JVM advantage."""
    users = data["user"][:sample]
    items = data["item"][:sample]
    ratings = data["rating"][:sample]
    num_users = int(users.max()) + 1
    num_items = int(items.max()) + 1
    rng = np.random.default_rng(0)
    P = rng.uniform(-0.1, 0.1, (num_users, rank))
    Q = rng.uniform(-0.1, 0.1, (num_items, rank))
    lr = 0.05
    t0 = time.perf_counter()
    for k in range(sample):
        u, i, r = users[k], items[k], ratings[k]
        q = Q[i]  # pull
        p = P[u]
        err = r - p @ q
        P[u] = p + lr * (err * q - 0.01 * p)
        Q[i] = q + lr * (err * p - 0.01 * q)  # push
    dt = time.perf_counter() - t0
    per_record = dt / sample
    return per_record * num_ratings_full / jvm_speedup


def emulated_flink_cpu_logreg_per_example_s(num_features, nnz,
                                            sample=20_000, jvm_speedup=10.0):
    """Per-example sparse-logreg PS loop (pull active features -> sigmoid ->
    push per-feature deltas) in CPython, credited a JVM speedup."""
    rng = np.random.default_rng(0)
    w = np.zeros(num_features)
    fids = rng.integers(0, num_features, (sample, nnz))
    fvals = rng.normal(0, 1, (sample, nnz))
    ys = rng.integers(0, 2, sample).astype(np.float64)
    lr = 0.1
    t0 = time.perf_counter()
    for k in range(sample):
        ids, x, y = fids[k], fvals[k], ys[k]
        # One pull message per active feature (the reference's fan-out:
        # PA/logreg workers pull each feature id individually and reassemble
        # — SURVEY.md §3.4), then one push message per feature.
        z = 0.0
        for j in range(nnz):
            z += w[ids[j]] * x[j]
        p = 1.0 / (1.0 + np.exp(-z))
        g = (p - y) * lr
        for j in range(nnz):
            w[ids[j]] -= g * x[j]
    return (time.perf_counter() - t0) / sample / jvm_speedup


def run_logreg(args):
    """Criteo-style bounded-staleness (SSP) logistic regression throughput."""
    import jax

    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.logistic_regression import (
        LogRegConfig, logistic_regression,
    )
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import (
        load_sparse, synthetic_sparse_classification,
    )

    NF, NNZ, NEX = 1_000_000, 39, 4_000_000  # Criteo-ish shape
    if args.input:
        data, NF = load_sparse(args.input, num_features=NF)
        NEX, NNZ = data["feat_ids"].shape
    else:
        data = synthetic_sparse_classification(NEX, NF, NNZ, seed=0,
                                               noise=0.05)
    data = dict(data, label=(data["label"] > 0).astype(np.float32))

    devs = jax.devices()
    nd, ns = default_mesh_shape(len(devs))
    mesh = make_ps_mesh(num_shards=ns, num_data=nd)
    W = num_workers_of(mesh)
    cfg = LogRegConfig(num_features=NF, learning_rate=0.1)
    trainer, store = logistic_regression(
        mesh, cfg, sync_every=8, max_steps_per_call=256
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    ds = DeviceDataset(mesh, data)
    plan = DeviceEpochPlan(
        ds, num_workers=W, local_batch=16384, sync_every=8, seed=1
    )

    tables, ls, _ = trainer.run_indexed(tables, ls, plan, jax.random.key(9))
    t0 = time.perf_counter()
    tables, ls, metrics = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1)
    )
    epoch_s = time.perf_counter() - t0
    ex_s = NEX / epoch_s / len(devs)

    per0, per1 = first_last_real_step(metrics[0], "logloss")
    print(
        f"quality: logloss step0 {per0:.4f} -> last-real-step {per1:.4f} "
        f"(epoch 2; chance = 0.693)",
        file=sys.stderr,
    )

    per_ex = emulated_flink_cpu_logreg_per_example_s(NF, NNZ)
    print(json.dumps({
        "metric": "criteo_ssp_logreg_examples_per_sec_per_chip",
        "value": round(ex_s, 1),
        "unit": "examples/s",
        "vs_baseline": round(ex_s * per_ex, 2),
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="mf", choices=["mf", "w2v", "logreg"])
    ap.add_argument("--scale", default="20m", choices=["100k", "1m", "20m"])
    ap.add_argument("--rank", type=int, default=10)
    ap.add_argument("--local-batch", type=int, default=32768)
    ap.add_argument("--movielens-path", default=None)
    ap.add_argument("--text8-path", default=None)
    ap.add_argument("--input", default=None,
                    help="real dataset file for --workload logreg "
                         "(Criteo TSV or svmlight; default: synthetic)")
    ap.add_argument("--num-tokens", type=int, default=17_000_000)
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--block-len", type=int, default=8192)
    ap.add_argument("--rmse-target", type=float, default=0.12,
                    help="mf workload: train to this train-RMSE "
                         "(planted-structure noise floor is ~0.1)")
    ap.add_argument("--max-epochs", type=int, default=8)
    args = ap.parse_args()

    if args.workload == "w2v":
        return run_w2v(args)
    if args.workload == "logreg":
        return run_logreg(args)

    import statistics

    import jax

    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import default_mesh_shape, make_ps_mesh
    from fps_tpu.utils.datasets import load_movielens

    data, nu, ni = load_movielens(args.movielens_path, args.scale)
    nr = len(data["user"])

    devs = jax.devices()
    nd, ns = default_mesh_shape(len(devs))
    mesh = make_ps_mesh(num_shards=ns, num_data=nd)
    W = num_workers_of(mesh)

    cfg = MFConfig(num_users=nu, num_items=ni, rank=args.rank,
                   learning_rate=0.05, reg=0.01)
    # Per-id mean combine: at this batch size summed duplicate updates on
    # Zipfian-hot items diverge (the quality line below would show NaN);
    # mean-combine is the reference's combining-sender analog and learns
    # stably at any batch size.
    trainer, store = online_mf(mesh, cfg, combine="mean")
    dataset = DeviceDataset(mesh, data)  # one-time upload, outside the epoch
    plan = DeviceEpochPlan(
        dataset,
        num_workers=W,
        local_batch=args.local_batch,
        route_key="user",
        seed=1,
    )

    # Warm-up: compile + one full epoch on throwaway state (ingest is fused
    # into the jit, so the whole epoch — shuffle, batch gathers, training —
    # is ONE dispatch). The timed run below reuses the compiled program on
    # FRESH state: time-to-quality excludes one-time compilation.
    tables, local_state = trainer.init_state(jax.random.key(0))
    trainer.run_indexed(tables, local_state, plan, jax.random.key(9))

    # Headline: wall-clock (and epochs) to train-RMSE <= target on the
    # planted-structure set (noise floor ~0.1) — time-to-fixed-quality is
    # the firm cross-system comparison; raw epoch time alone rewards
    # configurations that stream fast but learn slowly.
    target = args.rmse_target
    tables, local_state = trainer.init_state(jax.random.key(0))
    epoch_times, rmse_curve = [], []
    for e in range(args.max_epochs):
        t0 = time.perf_counter()
        tables, local_state, m = trainer.run_indexed(
            tables, local_state, plan, jax.random.key(1),
            epochs=1, start_epoch=e,
        )
        epoch_times.append(time.perf_counter() - t0)
        rmse_e = float(np.sqrt(np.asarray(m[0]["se"]).sum()
                               / max(np.asarray(m[0]["n"]).sum(), 1.0)))
        rmse_curve.append(rmse_e)
        if rmse_e <= target:
            break
    total_s = sum(epoch_times)
    epochs = len(epoch_times)
    median_epoch = statistics.median(epoch_times)
    reached = rmse_curve[-1] <= target

    # Emulated reference cost for the SAME epoch count (the per-record
    # sequential loop converges at least as fast per epoch, so equal-epochs
    # is a conservative credit to the baseline).
    baseline_epoch_s = emulated_flink_cpu_epoch_s(data, nr, args.rank)
    baseline_total_s = baseline_epoch_s * epochs

    print(
        "quality: per-epoch train RMSE "
        + " -> ".join(f"{r:.4f}" for r in rmse_curve)
        + (f" (reached <= {target})" if reached
           else f" (STOPPED at max_epochs={args.max_epochs} without "
                f"reaching {target})"),
        file=sys.stderr,
    )
    print(
        f"epoch times: {[round(t, 3) for t in epoch_times]} s "
        f"(median {median_epoch:.4f}); emulated Flink-CPU epoch "
        f"{baseline_epoch_s:.1f}s",
        file=sys.stderr,
    )

    print(json.dumps({
        "metric": f"ml{args.scale}_mf_time_to_rmse_{target}",
        "value": round(total_s, 4),
        "unit": "s",
        "vs_baseline": round(baseline_total_s / total_s, 2),
        "epochs": epochs,
        "median_epoch_s": round(median_epoch, 4),
        "final_train_rmse": round(rmse_curve[-1], 4),
        "reached": reached,
    }))


if __name__ == "__main__":
    sys.exit(main())
