"""Unbounded streaming ingest + combinators + profiling tests."""

import numpy as np
import pytest

from fps_tpu.core.ingest import epoch_chunks, stream_chunks


def _source(n_batches, batch_n, seed=0, nnz=None):
    """Unbounded-style source: varying-size columnar batches."""
    rng = np.random.default_rng(seed)
    for b in range(n_batches):
        n = batch_n + (b % 3)  # varying lengths
        batch = {
            "user": rng.integers(0, 40, n).astype(np.int32),
            "item": rng.integers(0, 30, n).astype(np.int32),
            "rating": rng.normal(0, 1, n).astype(np.float32),
        }
        if nnz:
            batch["feat_ids"] = rng.integers(0, 100, (n, nnz)).astype(np.int32)
        yield batch


def _collect_real(chunks, key):
    """All real (weight 1) values of a column across chunks, any order."""
    vals = []
    for c in chunks:
        w = c["weight"].reshape(-1) > 0
        vals.append(c[key].reshape(-1, *c[key].shape[c["weight"].ndim:])[w])
    return np.concatenate(vals) if vals else np.array([])


def test_stream_chunks_conserves_examples_roundrobin():
    src = list(_source(10, 50))
    total = sum(len(b["user"]) for b in src)
    chunks = list(stream_chunks(iter(src), num_workers=4, local_batch=8,
                                steps_per_chunk=3))
    # Static shapes on every chunk.
    for c in chunks:
        assert c["user"].shape == (3, 32)
        assert c["weight"].shape == (3, 32)
    got = int(sum(c["weight"].sum() for c in chunks))
    assert got == total
    # Every rating value survives exactly once.
    want = np.sort(np.concatenate([b["rating"] for b in src]))
    have = np.sort(_collect_real(chunks, "rating"))
    np.testing.assert_allclose(have, want)


def test_stream_chunks_routing_and_multidim():
    src = list(_source(6, 40, seed=1, nnz=5))
    chunks = list(stream_chunks(iter(src), num_workers=4, local_batch=8,
                                steps_per_chunk=2, route_key="user"))
    W, LB = 4, 8
    for c in chunks:
        assert c["feat_ids"].shape == (2, 32, 5)
        # Routed: every real example sits in its owner's slot range.
        users = c["user"].reshape(2, W, LB)
        weight = c["weight"].reshape(2, W, LB)
        for w in range(W):
            real = weight[:, w, :] > 0
            assert np.all(users[:, w, :][real] % W == w)
    total = sum(len(b["user"]) for b in src)
    assert int(sum(c["weight"].sum() for c in chunks)) == total


def test_stream_chunks_ssp_shape():
    chunks = list(stream_chunks(_source(4, 64), num_workers=2, local_batch=4,
                                steps_per_chunk=4, sync_every=2))
    for c in chunks:
        assert c["user"].shape == (2, 2, 8)
    with pytest.raises(ValueError):
        next(stream_chunks(_source(1, 8), num_workers=2, local_batch=4,
                           steps_per_chunk=3, sync_every=2))


def test_stream_chunks_trains_mf(devices8):
    """stream_chunks output feeds the compiled driver directly."""
    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    trainer, store = online_mf(mesh, MFConfig(32, 24, rank=4), donate=False)
    data = synthetic_ratings(32, 24, 2048, seed=2)

    def src():
        for s in range(0, 2048, 256):
            yield {k: v[s : s + 256] for k, v in data.items()}

    chunks = stream_chunks(src(), num_workers=W, local_batch=16,
                           steps_per_chunk=4, route_key="user")
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, metrics = trainer.fit_stream(tables, ls, chunks,
                                             jax.random.key(1))
    n = sum(float(np.sum(m["n"])) for m in metrics)
    assert n == 2048.0


def test_combinators(devices8):
    import jax

    from fps_tpu.core.combinators import clip_pushes, scale_pushes, tap_outputs
    from fps_tpu.core.driver import Trainer, num_workers_of
    from fps_tpu.models.matrix_factorization import (
        MatrixFactorizationWorker,
        MFConfig,
        make_store,
    )
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=2, num_data=1, devices=devices8[:2])
    W = num_workers_of(mesh)
    cfg = MFConfig(num_users=16, num_items=12, rank=4)
    data = synthetic_ratings(16, 12, 256, seed=3)

    def run(wrap):
        store = make_store(mesh, cfg)
        logic = wrap(MatrixFactorizationWorker(cfg, W))
        trainer = Trainer(mesh, store, logic)
        chunk = next(epoch_chunks(data, num_workers=W, local_batch=8,
                                  steps_per_chunk=2, route_key="user"))
        tables, ls = trainer.init_state(jax.random.key(0))
        tables, ls, m = trainer.run_chunk(tables, ls, chunk, jax.random.key(1))
        return store, jax.tree.map(np.asarray, m)

    # tap_outputs adds push statistics to the metrics stream.
    _, m = run(tap_outputs)
    assert "push_norm/item_factors" in m and "push_count/item_factors" in m
    assert np.all(m["push_count/item_factors"] > 0)

    # clip_pushes with a tiny max_norm shrinks the push norms.
    _, m_clip = run(lambda l: tap_outputs(clip_pushes(l, 1e-3)))
    assert np.sum(m_clip["push_norm/item_factors"]) < np.sum(
        m["push_norm/item_factors"]
    )

    # scale_pushes(0) must leave the item table at its initialization.
    s0, _ = run(lambda l: scale_pushes(l, 0.0))
    s1, _ = run(lambda l: l)
    init_store = make_store(mesh, cfg)
    init_store.init(jax.random.fold_in(jax.random.key(0), 0))
    np.testing.assert_allclose(
        s0.dump_model("item_factors")[1],
        init_store.dump_model("item_factors")[1],
        rtol=1e-6,
    )
    assert not np.allclose(
        s1.dump_model("item_factors")[1], init_store.dump_model("item_factors")[1]
    )


def test_throughput_hook(devices8):
    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings
    from fps_tpu.utils.profiling import Throughput

    mesh = make_ps_mesh(num_shards=2, num_data=1, devices=devices8[:2])
    W = num_workers_of(mesh)
    trainer, _ = online_mf(mesh, MFConfig(16, 12, rank=4), donate=False)
    data = synthetic_ratings(16, 12, 512, seed=4)
    chunks = epoch_chunks(data, num_workers=W, local_batch=8,
                          steps_per_chunk=2, route_key="user")
    tables, ls = trainer.init_state(jax.random.key(0))
    tp = Throughput()
    trainer.fit_stream(tables, ls, chunks, jax.random.key(1), on_chunk=tp)
    s = tp.summary()
    assert s["chunks"] >= 2
    assert s["examples"] == 512.0
    assert s["examples_per_sec"] > 0


def test_trace_writes_profile(tmp_path, devices8):
    import jax
    import jax.numpy as jnp

    from fps_tpu.utils import profiling

    with profiling.trace(str(tmp_path)):
        jnp.sum(jnp.arange(1000.0)).block_until_ready()
    produced = list(tmp_path.rglob("*"))
    assert produced, "no trace files written"
