"""The step-fenced serving fleet + delta-aware read plane (ISSUE 14).

Contract under test (``docs/serving.md`` "Delta chains" / "The serving
fleet"):

* ``DeltaView``: row-overlay lookups agree with materialized patching,
  scalars/nd fancy indexing, merge later-wins;
* the delta-aware ``SnapshotWatcher``: incremental hot-swap (single and
  multi-delta catch-up), chains through ``*.corrupt`` bases never
  resolve, chain rejections are NOT pinned in the per-inode cache, and
  the poll-loop FileNotFoundError race (candidate swept between stat
  and open) is skipped, not raised and not counted as a rejection;
* ``StepFence``: quorum advancement, forward monotonicity, epoch-bumped
  rollback, reader-side max-observed clamping;
* ``FleetReader`` / ``ServingFleet``: readers swap only to the fence,
  a restarted reader never serves below the fence it booted on,
  quarantine rolls the whole fleet back coordinated, and the warm-row
  cache admits the hot-tier ranking without changing answers.

All jax-free below the fixtures (snapshots are handcrafted npz in the
checkpoint writer's exact layout, like ``tests/test_serve.py``).
"""

import os

import numpy as np
import pytest

from fps_tpu.core import snapshot_format as fmt
from fps_tpu.serve import (
    DeltaView,
    FleetReader,
    ServableSnapshot,
    ServingFleet,
    SnapshotWatcher,
    StepFence,
    tiering_hot_ids,
)


def write_full(dirpath, step, tables, *, ls=(), epoch=None):
    arrays = {f"table::{k}": np.asarray(v) for k, v in tables.items()}
    for i, leaf in enumerate(ls):
        arrays[f"ls::{i}"] = np.asarray(leaf)
    arrays["meta::ls_format"] = np.array("exported")
    if epoch is not None:
        arrays[fmt.POD_EPOCH_KEY] = np.int64(epoch)
    for k in list(arrays):
        arrays["meta::crc::" + k] = np.uint32(fmt.array_crc32(arrays[k]))
    os.makedirs(dirpath, exist_ok=True)
    np.savez(fmt.snapshot_path(dirpath, step), **arrays)
    return arrays


def write_delta(dirpath, step, base, rows_by_table, *, epoch=None,
                base_step=None):
    arrays = {fmt.BASE_STEP_KEY: np.int64(
        base if base_step is None else base_step)}
    arrays["meta::ls_format"] = np.array("exported")
    if epoch is not None:
        arrays[fmt.POD_EPOCH_KEY] = np.int64(epoch)
    for name, (ids, rows) in rows_by_table.items():
        arrays[fmt.DELTA_IDS_PREFIX + f"table::{name}"] = np.asarray(
            ids, np.int64)
        arrays[fmt.DELTA_ROWS_PREFIX + f"table::{name}"] = np.asarray(
            rows)
    for k in list(arrays):
        arrays["meta::crc::" + k] = np.uint32(fmt.array_crc32(arrays[k]))
    np.savez(fmt.delta_path(dirpath, step, base), **arrays)
    return arrays


def chain_dir(tmp_path, *, steps=4, nrows=64, dim=3, seed=0):
    """full@1 + deltas 2..steps; returns (dir, expected final table)."""
    d = str(tmp_path)
    rng = np.random.default_rng(seed)
    table = rng.normal(size=(nrows, dim)).astype(np.float32)
    write_full(d, 1, {"w": table})
    cur = table.copy()
    for step in range(2, steps + 1):
        ids = np.unique(rng.integers(0, nrows, 6))
        rows = (cur[ids] + step).astype(np.float32)
        cur[ids] = rows
        write_delta(d, step, step - 1, {"w": (ids, rows)})
    return d, cur


# ---------------------------------------------------------------------------
# DeltaView.
# ---------------------------------------------------------------------------

def test_delta_view_lookup_matches_materialized():
    rng = np.random.default_rng(0)
    base = rng.normal(size=(32, 4)).astype(np.float32)
    ids = np.array([2, 7, 30], np.int64)
    rows = rng.normal(size=(3, 4)).astype(np.float32)
    v = DeltaView(base, ids, rows)
    mat = base.copy()
    mat[ids] = rows
    np.testing.assert_array_equal(np.asarray(v), mat)
    idx = np.array([[0, 2, 7], [30, 30, 5]])
    np.testing.assert_array_equal(v[idx], mat[idx])
    np.testing.assert_array_equal(v[3], mat[3])  # scalar index
    np.testing.assert_array_equal(v[np.array([], np.int64)],
                                  mat[np.array([], np.int64)])
    assert v.shape == base.shape and v.dtype == base.dtype
    assert len(v) == 32 and v.overlay_rows == 3


def test_delta_view_validates_overlay():
    base = np.zeros((8, 2), np.float32)
    with pytest.raises(ValueError):
        DeltaView(base, [3, 1], np.zeros((2, 2)))  # unsorted
    with pytest.raises(ValueError):
        DeltaView(base, [1, 9], np.zeros((2, 2)))  # out of range
    with pytest.raises(ValueError):
        DeltaView(base, [1], np.zeros((2, 2)))  # length mismatch


# ---------------------------------------------------------------------------
# ServableSnapshot: chains, incremental swap, warm cache.
# ---------------------------------------------------------------------------

def test_open_chain_resolves_and_with_delta_increments(tmp_path):
    d, want = chain_dir(tmp_path, steps=4)
    snap = ServableSnapshot.open_chain(d, 4)
    assert snap.step == 4 and snap.chain_len == 4
    np.testing.assert_array_equal(snap.lookup("w", np.arange(64)), want)
    # Incremental: open the base full, then extend link by link.
    s = ServableSnapshot.open(fmt.snapshot_path(d, 1))
    for step in (2, 3, 4):
        s = s.with_delta(fmt.delta_path(d, step, step - 1))
    np.testing.assert_array_equal(s.lookup("w", np.arange(64)), want)
    assert s.chain_len == 4


def test_with_delta_refuses_wrong_base_and_stale_epoch(tmp_path):
    from fps_tpu.serve import SnapshotRejected

    d = str(tmp_path)
    write_full(d, 1, {"w": np.zeros((8, 2), np.float32)}, epoch=2)
    write_delta(d, 2, 1, {"w": ([0], np.ones((1, 2), np.float32))},
                epoch=2)
    write_delta(d, 3, 2, {"w": ([1], np.ones((1, 2), np.float32))},
                epoch=1)  # stale zombie
    snap = ServableSnapshot.open(fmt.snapshot_path(d, 1))
    assert snap.pod_epoch == 2
    snap2 = snap.with_delta(fmt.delta_path(d, 2, 1))
    with pytest.raises(SnapshotRejected, match="epoch"):
        snap2.with_delta(fmt.delta_path(d, 3, 2))
    with pytest.raises(SnapshotRejected, match="chains from"):
        snap.with_delta(fmt.delta_path(d, 3, 2))  # base mismatch


def test_warm_cache_admits_ranking_without_changing_answers(tmp_path):
    d, want = chain_dir(tmp_path, steps=3)
    snap = ServableSnapshot.open_chain(d, 3)
    warm = snap.warmed({"w": np.arange(10), "unknown": np.arange(4)})
    assert warm.warm_rows == 10
    np.testing.assert_array_equal(warm.lookup("w", np.arange(64)), want)
    # Admission from the adaptive tier's sidecar ranking.
    np.savez(os.path.join(d, "tiering-3.npz"),
             **{"hot::w": np.arange(5)})
    ids = tiering_hot_ids(d)
    np.testing.assert_array_equal(ids["w"], np.arange(5))
    assert tiering_hot_ids(str(tmp_path / "missing")) == {}


# ---------------------------------------------------------------------------
# Watcher: delta-aware discovery + the FNF poll race.
# ---------------------------------------------------------------------------

def test_watcher_swaps_incrementally_through_chain(tmp_path):
    d, want = chain_dir(tmp_path, steps=1)
    w = SnapshotWatcher(d)
    w.poll()
    assert w.current.step == 1
    rng = np.random.default_rng(9)
    cur = want.copy()
    for step in (2, 3):
        ids = np.unique(rng.integers(0, 64, 5))
        rows = (cur[ids] + step).astype(np.float32)
        cur[ids] = rows
        write_delta(d, step, step - 1, {"w": (ids, rows)})
        w.poll()
        assert w.current.step == step
        assert w.current.chain_len == step  # incremental, not re-opened
    np.testing.assert_array_equal(
        w.current.lookup("w", np.arange(64)), cur)
    # Multi-delta catch-up: two publishes land between polls — the swap
    # extends the served chain by BOTH links (no base re-open).
    for step in (4, 5):
        ids = np.array([step], np.int64)
        rows = (cur[ids] + step).astype(np.float32)
        cur[ids] = rows
        write_delta(d, step, step - 1, {"w": (ids, rows)})
    w.poll()
    assert w.current.step == 5 and w.current.chain_len == 5
    np.testing.assert_array_equal(
        w.current.lookup("w", np.arange(64)), cur)


def test_watcher_never_resolves_through_corrupt_base(tmp_path):
    """Satellite: a quarantined full's chained deltas are unservable —
    the reader must not resolve a chain through a ``*.corrupt`` base."""
    d, _ = chain_dir(tmp_path, steps=3)
    # Fresh watcher (no incremental state): base quarantined before the
    # first poll.
    os.replace(fmt.snapshot_path(d, 1), fmt.snapshot_path(d, 1)
               + ".corrupt")
    w = SnapshotWatcher(d)
    assert w.poll() is None and w.current is None
    # A later, independent full becomes servable; the orphaned deltas
    # never do.
    table = np.full((64, 3), 7.0, np.float32)
    write_full(d, 4, {"w": table})
    w.poll()
    assert w.current.step == 4
    np.testing.assert_array_equal(
        w.current.lookup("w", np.arange(64)), table)


def test_watcher_backward_swap_past_quarantined_chain_suffix(tmp_path):
    d, _ = chain_dir(tmp_path, steps=4)
    w = SnapshotWatcher(d)
    w.poll()
    assert w.current.step == 4
    served = w.current.lookup("w", np.arange(64)).copy()
    # The trainer quarantines deltas 3 and 4 (chain truncation): the
    # reader swaps BACKWARD to the surviving verified link.
    for s, b in ((4, 3), (3, 2)):
        p = fmt.delta_path(d, s, b)
        os.replace(p, p + ".corrupt")
    w.poll()
    assert w.current.step == 2
    assert w.swaps["backward"] == 1
    assert not np.array_equal(
        w.current.lookup("w", np.arange(64)), served)


def test_fnf_race_skipped_not_rejected(tmp_path):
    """Satellite regression: a candidate swept/renamed between the
    watcher's stat and its open must read as "gone, retry next poll" —
    no raise, no rejection verdict, and the step serves once it
    reappears."""
    d = str(tmp_path)
    w = SnapshotWatcher(d)
    # A journal-announced step whose file was already swept: candidates
    # include it, the file is gone.
    w._saved_events[5] = (fmt.snapshot_path(d, 5), 0.0)
    w.max_written_step = 5
    assert w.poll() is None
    assert w.rejected == 0 and w._rejected == {}
    # ServableSnapshot.open on a vanished path raises FileNotFoundError
    # (never a corruption verdict), with and without the CRC pass.
    with pytest.raises(FileNotFoundError):
        ServableSnapshot.open(fmt.snapshot_path(d, 5))
    with pytest.raises(FileNotFoundError):
        ServableSnapshot.open(fmt.snapshot_path(d, 5), verify=False)
    # The step re-published later serves normally.
    write_full(d, 5, {"w": np.ones((8, 2), np.float32)})
    w.poll()
    assert w.current.step == 5 and w.rejected == 0


def test_chain_rejection_not_pinned_in_cache(tmp_path):
    """A chain failure can be transient (link mid-quarantine/compaction
    when walked): it must be re-checked next poll, unlike a torn
    single-file candidate whose (inode, mtime) verdict is permanent."""
    d, want = chain_dir(tmp_path, steps=3)
    # Temporarily break the chain: move the mid link aside.
    link = fmt.delta_path(d, 2, 1)
    os.replace(link, link + ".hidden")
    w = SnapshotWatcher(d)
    w.poll()
    assert w.current.step == 1  # head 3 unservable, falls back
    # The head's verdict was NOT cached: restoring the link lifts it.
    os.replace(link + ".hidden", link)
    w.poll()
    assert w.current.step == 3
    np.testing.assert_array_equal(
        w.current.lookup("w", np.arange(64)), want)


# ---------------------------------------------------------------------------
# StepFence.
# ---------------------------------------------------------------------------

def test_fence_quorum_advance_and_monotonicity(tmp_path):
    d = str(tmp_path)
    f1, f2, f3 = (StepFence(d, f"r{i}") for i in range(3))
    assert f1.read() is None
    f1.ready(4)
    assert f1.advance(2) is None  # one reader ready: no quorum of 2
    f2.ready(3)
    assert f1.advance(2) == (0, 3)  # 2 readers at >= 3
    f3.ready(5)
    f1.ready(5)
    assert f2.advance(2) == (0, 5)
    # Forward-monotone: a stale advance attempt cannot regress.
    f2.ready(1)
    assert f3.advance(2) == (0, 5)
    # max_step caps at the advancing reader's own verified step.
    f1.ready(9)
    f2.ready(9)
    assert f3.advance(2, max_step=6) == (0, 6)


def test_fence_rollback_bumps_epoch(tmp_path):
    d = str(tmp_path)
    f1, f2 = StepFence(d, "a"), StepFence(d, "b")
    f1.ready(7)
    f2.ready(7)
    assert f1.advance(2) == (0, 7)
    assert f1.rollback(4) == (1, 4)
    # The lower step under the HIGHER epoch wins for every observer.
    assert f2.read() == (1, 4)
    # Within the new epoch, forward motion resumes.
    f1.ready(6)
    f2.ready(6)
    assert f2.advance(2) == (1, 6)


def test_fence_reader_clamps_regressed_file(tmp_path):
    import json

    d = str(tmp_path)
    f = StepFence(d, "a")
    f.ready(5)
    StepFence(d, "b").ready(5)
    assert f.advance(2) == (0, 5)
    # A racing stale write regresses the FILE; observers clamp to the
    # max (epoch, step) they have seen.
    with open(f.fence_path, "w", encoding="utf-8") as fh:
        json.dump({"epoch": 0, "step": 2}, fh)
    assert f.read() == (0, 5)


# ---------------------------------------------------------------------------
# FleetReader / ServingFleet.
# ---------------------------------------------------------------------------

def test_fleet_swaps_only_to_fence_and_converges(tmp_path):
    d, want = chain_dir(tmp_path, steps=3)
    fleet = ServingFleet(d, 3, quorum=2)
    for _ in range(3):
        fleet.poll()
    stats = fleet.stats()
    assert {s["step"] for s in stats} == {3}
    assert {tuple(s["fence"]) for s in stats} == {(0, 3)}
    for r in fleet.readers:
        _, got = r.server.pull("w", np.arange(64))
        np.testing.assert_array_equal(got, want)
        # Served trail is fence-monotone.
        assert all(b >= a for a, b in zip(r.served_steps,
                                          r.served_steps[1:]))


def test_restarted_reader_never_serves_below_fence(tmp_path):
    d, want = chain_dir(tmp_path, steps=4)
    fleet = ServingFleet(d, 3, quorum=2)
    for _ in range(3):
        fleet.poll()
    fence = fleet.readers[0].fence.read()
    assert fence == (0, 4)
    # Reader killed mid-swap: a fresh instance with the same id must
    # boot on the fence, not on whatever it last had mapped.
    nr = FleetReader(d, "r1", quorum=2)
    assert nr.server._snap is None  # serves NOTHING until fence-able
    nr.poll()
    assert nr.server._snap is not None
    assert nr.server._snap.step >= fence[1]
    assert nr.served_steps[0] >= fence[1]


def test_fleet_quarantine_rolls_back_coordinated(tmp_path):
    d, _ = chain_dir(tmp_path, steps=4)
    fleet = ServingFleet(d, 3, quorum=2)
    for _ in range(3):
        fleet.poll()
    assert {s["step"] for s in fleet.stats()} == {4}
    # The trainer quarantines the head links: chain truncation.
    for s, b in ((4, 3), (3, 2)):
        p = fmt.delta_path(d, s, b)
        os.replace(p, p + ".corrupt")
    for _ in range(4):
        fleet.poll()
    stats = fleet.stats()
    assert {s["step"] for s in stats} == {2}
    fence = fleet.readers[0].fence.read()
    assert fence[0] >= 1 and fence[1] == 2  # epoch-bumped rollback
    for r in fleet.readers:
        _, got = r.server.pull("w", [0, 1])
        assert np.all(np.isfinite(got))


def test_fleet_warm_cache_from_ranking(tmp_path):
    d, want = chain_dir(tmp_path, steps=2)
    np.savez(os.path.join(d, "tiering-2.npz"), **{"hot::w": np.arange(8)})
    fleet = ServingFleet(d, 2, quorum=1, warm_from="tiering")
    for _ in range(2):
        fleet.poll()
    stats = fleet.stats()
    assert all(s["warm_rows"] == 8 for s in stats)
    for r in fleet.readers:
        _, got = r.server.pull("w", np.arange(64))
        np.testing.assert_array_equal(got, want)


def test_fence_step_metric_emitted(tmp_path):
    from fps_tpu.obs import MemorySink, Recorder

    d, _ = chain_dir(tmp_path, steps=2)
    sink = MemorySink()
    rec = Recorder(sinks=[sink])
    reader = FleetReader(d, "r0", quorum=1, recorder=rec)
    reader.poll()
    vals = [r for r in sink.records
            if r.get("kind") == "metric"
            and r.get("name") == "serve.fence_step"]
    assert vals and vals[-1]["value"] == 2.0


@pytest.mark.slow
def test_fleet_fence_scenario_end_to_end(tmp_path):
    """The full chaos leg (shared with tools/chaos_sweep.py): N fenced
    readers over a SIGKILLed+restarted delta-publishing child, one
    reader killed and restarted mid-swap — fence monotone, no
    superseded answers, byte-identical convergence."""
    from fps_tpu.testing.supervised_demo import run_fleet_fence_scenario

    ok, detail = run_fleet_fence_scenario(str(tmp_path))
    assert ok, detail


def test_watcher_verify_false_broken_chain_no_raise(tmp_path):
    """poll() is documented never to raise on bad candidates — a broken
    chain (base swept with no *.corrupt marker) under verify=False must
    read as unservable, not as an escaped ChainError."""
    d, _ = chain_dir(tmp_path, steps=3)
    os.remove(fmt.snapshot_path(d, 1))
    w = SnapshotWatcher(d, verify=False)
    assert w.poll() is None and w.current is None
    wv = SnapshotWatcher(d)  # verify=True takes the rejection path
    assert wv.poll() is None and wv.current is None


def test_fence_ready_write_is_idempotent_per_step(tmp_path):
    d = str(tmp_path)
    f = StepFence(d, "a")
    f.ready(3)
    path = f._ready_path("a")
    ino = os.stat(path).st_ino
    f.ready(3)  # unchanged: no rewrite (no fsync churn per poll tick)
    assert os.stat(path).st_ino == ino
    f.ready(4)
    assert os.stat(path).st_ino != ino
    assert f.ready_steps() == {"a": 4}


def test_fence_read_repairs_regressed_file(tmp_path):
    import json

    d = str(tmp_path)
    f1, f2 = StepFence(d, "a"), StepFence(d, "b")
    f1.ready(7)
    f2.ready(7)
    assert f1.advance(2) == (0, 7)
    assert f1.rollback(4) == (1, 4)
    # A racing advance clobbers the rollback (last-writer-wins file).
    with open(f1.fence_path, "w", encoding="utf-8") as fh:
        json.dump({"epoch": 0, "step": 7}, fh)
    # The reader that observed the bump REPAIRS the file on read, so
    # peers that never saw (1, 4) converge to it instead of serving 7.
    assert f1.read() == (1, 4)
    assert StepFence(d, "c").read() == (1, 4)


def test_fleet_rollback_survives_clobbered_fence(tmp_path):
    """A forward advance racing the quarantine rollback may clobber the
    epoch bump in the fence FILE; the rollback is evidence-based and
    re-asserted every poll, so the fleet must still converge on the
    surviving step under a bumped epoch."""
    import json

    d, _ = chain_dir(tmp_path, steps=4)
    fleet = ServingFleet(d, 3, quorum=2)
    for _ in range(3):
        fleet.poll()
    assert {s["step"] for s in fleet.stats()} == {4}
    for s, b in ((4, 3), (3, 2)):
        p = fmt.delta_path(d, s, b)
        os.replace(p, p + ".corrupt")
    fleet.readers[0].poll()  # observes quarantine, proposes rollback
    # Simulate the racing writer: regress the fence file to the
    # quarantined step under the OLD epoch.
    with open(fleet.readers[0].fence.fence_path, "w",
              encoding="utf-8") as fh:
        json.dump({"epoch": 0, "step": 4}, fh)
    for _ in range(4):
        fleet.poll()
    stats = fleet.stats()
    assert {s["step"] for s in stats} == {2}
    fence = fleet.readers[2].fence.read()
    assert fence[0] >= 1 and fence[1] == 2


def test_incremental_swap_refuses_stale_base(tmp_path):
    """Quarantine -> rollback-replay re-publishes the served step with
    DIFFERENT content, then a delta chains on the NEW file. The
    incremental paths must detect that the served snapshot's mapped
    file is no longer the on-disk publication (src_id identity) and
    re-open the chain instead of overlaying the delta on stale maps."""
    d = str(tmp_path)
    old = np.zeros((16, 2), np.float32)
    write_full(d, 1, {"w": old})
    w = SnapshotWatcher(d)
    w.poll()
    assert w.current.step == 1
    # Atomic re-publish of step 1 with ROLLED-BACK (different) content.
    new = np.full((16, 2), 5.0, np.float32)
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    os.close(fd)
    os.remove(tmp)
    write_full(str(tmp_path / "stage"), 1, {"w": new})
    os.replace(fmt.snapshot_path(str(tmp_path / "stage"), 1),
               fmt.snapshot_path(d, 1))
    # A delta chained on the NEW step-1 file.
    ids = np.array([3], np.int64)
    rows = np.full((1, 2), 9.0, np.float32)
    write_delta(d, 2, 1, {"w": (ids, rows)})
    w.poll()
    assert w.current.step == 2
    want = new.copy()
    want[ids] = rows
    # Rows untouched by the delta must come from the RE-PUBLISHED base,
    # not the stale pre-quarantine maps.
    np.testing.assert_array_equal(
        w.current.lookup("w", np.arange(16)), want)
