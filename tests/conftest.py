"""Test harness: multi-device CPU mesh, mirroring the reference's strategy.

The reference tests distributed behavior with no cluster by running real
multi-subtask pipelines on Flink's local mini-cluster inside one JVM
(SURVEY.md §4). The TPU-native analog: 8 virtual CPU devices via
``--xla_force_host_platform_device_count=8`` so every collective in the
store/driver runs against a real 8-way mesh.

This container's sitecustomize eagerly registers the single-chip TPU (axon)
backend at interpreter start, *before* pytest loads — too late to choose the
CPU platform from inside this process. So on first import we re-exec pytest
in a cleaned environment (no sitecustomize on PYTHONPATH, JAX_PLATFORMS=cpu).
"""

import os
import sys

import pytest

_MARK = "_FPS_TPU_TEST_REEXEC"

# Repo root on sys.path so `import fps_tpu` works without an install step.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    if os.environ.get(_MARK) == "1":
        return
    env = dict(os.environ)
    env[_MARK] = "1"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    # Restore the real stdout/stderr fds before exec'ing, otherwise the new
    # process inherits pytest's capture temp-files and all output is lost.
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest"] + sys.argv[1:],
        env,
    )


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, (
        f"expected 8 virtual CPU devices, got {len(devs)} ({jax.default_backend()})"
    )
    return devs
