"""Test harness: multi-device CPU mesh, mirroring the reference's strategy.

The reference tests distributed behavior with no cluster by running real
multi-subtask pipelines on Flink's local mini-cluster inside one JVM
(SURVEY.md §4). The TPU-native analog: 8 virtual CPU devices via
``--xla_force_host_platform_device_count=8`` so every collective in the
store/driver runs against a real 8-way mesh.

This container's sitecustomize eagerly registers the single-chip TPU (axon)
backend at interpreter start, *before* pytest loads — too late to choose the
CPU platform from inside this process. So on first import we re-exec pytest
in a cleaned environment (no sitecustomize on PYTHONPATH, JAX_PLATFORMS=cpu).
"""

import importlib.util
import os
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Repo root on sys.path so `import fps_tpu` works without an install step.
sys.path.insert(0, _ROOT)


def _hostenv():
    # Load by file path, NOT `import fps_tpu...`: the package __init__ pulls
    # in jax, and the whole point of the re-exec is that jax must not be
    # imported in this dirty (sitecustomize'd) parent process.
    spec = importlib.util.spec_from_file_location(
        "_fps_hostenv", os.path.join(_ROOT, "fps_tpu", "utils", "hostenv.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def pytest_configure(config):
    # Registered before the re-exec so both processes know the marker:
    # tier-1 runs with ``-m 'not slow'``; chaos subprocess scenarios that
    # exceed its budget carry @pytest.mark.slow.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 budgeted run"
    )
    hostenv = _hostenv()
    if hostenv.in_reexec():
        return
    env = hostenv.cpu_mesh_env(8)
    # Restore the real stdout/stderr fds before exec'ing, otherwise the new
    # process inherits pytest's capture temp-files and all output is lost.
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest"] + sys.argv[1:],
        env,
    )


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, (
        f"expected 8 virtual CPU devices, got {len(devs)} ({jax.default_backend()})"
    )
    return devs
