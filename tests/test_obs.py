"""Telemetry subsystem (fps_tpu.obs): registry/recorder contracts, sinks,
phase timers, health alerting (monitor escalation + watchdog), run
journal, and the driver wiring.

Acceptance contract (ISSUE 2):

* a logreg run with telemetry attached produces phase timings, per-table
  health totals, and journal events (rendered end-to-end in
  tests/test_obs_report.py);
* HealthMonitor escalation observe→mask is exercised under chaos
  poisoning, and its abort tier raises PoisonedStreamError;
* recorder off ⇒ the compiled program is bit-identical to a
  recorder-attached build (telemetry is host-side only).
"""

import json
import os
import time

import numpy as np
import pytest

import jax

from fps_tpu import obs
from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of
from fps_tpu.core.resilience import GuardConfig, PoisonedStreamError
from fps_tpu.core.store import ParamStore, TableSpec
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
)
from fps_tpu.obs import events as obs_events
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.testing import chaos
from fps_tpu.testing.workloads import (
    NF,
    logreg_chunks as _logreg_chunks,
    logreg_data as _logreg_data,
    weights as _weights,
)


# ---------------------------------------------------------------------------
# Registry + recorder contracts (pure host, no mesh needed).
# ---------------------------------------------------------------------------

def test_metric_spec_validation():
    with pytest.raises(ValueError, match="kind"):
        obs.MetricSpec("x", "timer")
    with pytest.raises(ValueError, match="name"):
        obs.MetricSpec("a b", "counter")
    reg = obs.MetricsRegistry([obs.MetricSpec("x", "counter")])
    reg.register(obs.MetricSpec("x", "counter"))  # same spec: idempotent
    with pytest.raises(ValueError, match="already registered"):
        reg.register(obs.MetricSpec("x", "gauge"))
    with pytest.raises(KeyError, match="unregistered"):
        reg.get("nope")


def test_recorder_typed_leaves_and_aggregates():
    reg = obs.MetricsRegistry([
        obs.MetricSpec("c", "counter", labels=("table",)),
        obs.MetricSpec("g", "gauge"),
        obs.MetricSpec("h", "histogram"),
    ])
    sink = obs.MemorySink()
    rec = obs.Recorder(reg, sinks=[sink], run_id="r1")
    rec.inc("c", 2, table="a")
    rec.inc("c", 3, table="a")
    rec.inc("c", 1, table="b")
    rec.set("g", 7.5)
    for v in (0.1, 0.3):
        rec.observe("h", v)
    # Typed: wrong kind / unknown name / undeclared label all fail loudly.
    with pytest.raises(TypeError, match="counter"):
        rec.set("c", 1, table="a")
    with pytest.raises(KeyError):
        rec.inc("unknown")
    with pytest.raises(ValueError, match="undeclared"):
        rec.inc("c", 1, shard="a")
    with pytest.raises(ValueError, match="negative"):
        rec.inc("c", -1, table="a")

    assert rec.counter_value("c", table="a") == 5
    snap = rec.snapshot()
    assert snap["counters"]["c{table=b}"] == 1
    assert snap["gauges"]["g"] == 7.5
    h = snap["histograms"]["h"]
    assert h["count"] == 2 and abs(h["sum"] - 0.4) < 1e-9
    assert h["min"] == 0.1 and h["max"] == 0.3
    # Every sample reached the sink, stamped with the run id.
    ms = sink.metrics()
    assert len(ms) == 6 and all(m["run_id"] == "r1" for m in ms)


def test_memory_sink_ring_bound():
    sink = obs.MemorySink(capacity=3)
    for i in range(10):
        sink.write({"kind": "event", "event": "e", "i": i})
    assert [r["i"] for r in sink.records] == [7, 8, 9]


def test_jsonl_sink_roundtrip(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    sink = obs.JsonlSink(path, flush_every=1)
    sink.write({"kind": "metric", "name": "x", "value": np.float32(1.5)})
    sink.write({"kind": "event", "event": "e", "arr": np.arange(2)})
    sink.close()
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["value"] == 1.5  # numpy degraded to plain JSON
    assert lines[1]["arr"] == [0, 1]


def test_jsonl_sink_nonfinite_is_strict_json(tmp_path):
    """The serving watcher legitimately sets a NaN gauge (orphaned
    snapshot); the JSONL artifact must stay strict JSON — null, never
    the Python-only NaN/Infinity tokens strict parsers reject."""
    path = str(tmp_path / "ev.jsonl")
    sink = obs.JsonlSink(path, flush_every=1)
    sink.write({"kind": "metric", "name": "serve.snapshot_lag_steps",
                "mtype": "gauge", "value": float("nan")})
    sink.write({"kind": "metric", "name": "g", "mtype": "gauge",
                "value": np.float32("inf")})
    sink.write({"kind": "metric", "name": "ok", "value": 2.0})
    sink.close()
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    lines = [json.loads(l) for l in raw.splitlines()]
    assert lines[0]["value"] is None and lines[1]["value"] is None
    assert lines[2]["value"] == 2.0  # finite fast path untouched


def test_prometheus_sink_exposition(tmp_path):
    path = str(tmp_path / "m.prom")
    sink = obs.PrometheusSink(path)
    rec = obs.Recorder(sinks=[sink])
    rec.inc("health.nonfinite_rows", 4, table="weights")
    rec.set("checkpoint.bytes", 1024)
    rec.observe("driver.phase_seconds", 0.25, phase="dispatch")
    rec.flush()
    text = open(path).read()
    assert ('fps_tpu_health_nonfinite_rows{table="weights"} 4' in text)
    assert "# TYPE fps_tpu_health_nonfinite_rows counter" in text
    assert "fps_tpu_checkpoint_bytes 1024" in text
    assert ('fps_tpu_driver_phase_seconds_count{phase="dispatch"} 1'
            in text)
    assert ('fps_tpu_driver_phase_seconds_sum{phase="dispatch"} 0.25'
            in text)


def test_phase_timer_accumulates_and_records():
    rec = obs.Recorder(sinks=[])
    t = obs.PhaseTimer(rec)
    with t.phase("dispatch"):
        pass
    with t.phase("dispatch"):
        pass
    with t.phase("host_sync"):
        pass
    chunk = t.chunk_summary()
    assert set(chunk) == {"dispatch", "host_sync"}
    assert t.chunk_summary() == {}  # reset
    # Run-level totals live on the recorder, the single source of truth.
    assert rec.phase_totals()["dispatch"]["n"] == 2


def test_throughput_first_chunk_covers_construction_gap():
    """Satellite fix: auto-start on first observation used to record a
    zero-width first chunk; it must now measure from construction."""
    tp = obs.Throughput()
    time.sleep(0.05)
    tp(0, {"n": np.array([10.0])})
    assert tp.first_s is not None and tp.first_s >= 0.045
    tp(1, {"n": np.array([10.0])})
    s = tp.summary()
    # Keys stable (the documented contract).
    assert set(s) == {"chunks", "examples", "first_chunk_s", "steady_s",
                      "examples_per_sec"}
    assert s["chunks"] == 2 and s["examples"] == 20.0
    assert s["first_chunk_s"] >= 0.045
    # Explicit start() still overrides the construction origin.
    tp2 = obs.Throughput()
    time.sleep(0.02)
    tp2.start()
    tp2(0, {"n": np.array([1.0])})
    assert tp2.first_s < 0.02


# ---------------------------------------------------------------------------
# Health monitor + watchdog (pure policy).
# ---------------------------------------------------------------------------

def test_health_monitor_thresholds():
    m = obs.HealthMonitor(escalate_after_rows=10, abort_after_chunks=3)
    assert m.update(0, 0) == obs.HEALTH_OK
    assert m.update(1, 4) == obs.HEALTH_OK
    assert m.update(2, 7) == obs.HEALTH_ESCALATE  # 11 rows >= 10
    assert m.escalated_at == 2
    assert m.update(3, 5) == obs.HEALTH_ABORT  # 3rd poisoned chunk
    assert m.aborted_at == 3
    assert m.log == [(1, 4), (2, 7), (3, 5)]
    with pytest.raises(ValueError):
        obs.HealthMonitor(escalate_after_rows=0)


def test_step_watchdog_flags_and_recovers():
    sink = obs.MemorySink()
    rec = obs.Recorder(sinks=[sink])
    seen = []
    wd = obs.StepWatchdog(0.05, on_stall=seen.append, recorder=rec)
    with wd.watch("chunk", 3):
        time.sleep(0.15)
    assert len(wd.stalls) == 1
    assert wd.stalls[0]["index"] == 3
    assert wd.stalls[0]["elapsed_s"] >= 0.1  # recovery recorded real time
    assert seen and seen[0]["what"] == "chunk"
    assert rec.counter_value("watchdog.stalls") == 1
    assert [e["event"] for e in sink.events()] == ["stall",
                                                   "stall_recovered"]
    # Fast region: timer cancelled, nothing fires.
    with wd.watch("chunk", 4):
        pass
    time.sleep(0.08)
    assert len(wd.stalls) == 1


def test_watchdog_callback_exception_swallowed():
    wd = obs.StepWatchdog(0.02, on_stall=lambda info: 1 / 0)
    with wd.watch("chunk", 0):
        time.sleep(0.06)
    assert len(wd.stalls) == 1  # the run survived the broken callback


# ---------------------------------------------------------------------------
# Journal + open_run + process-default events.
# ---------------------------------------------------------------------------

def test_run_journal_keeps_events_only(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = obs.RunJournal(path, run_id="r9", meta={"process": 0})
    j.write({"kind": "metric", "name": "x", "value": 1})
    j.write({"kind": "event", "t": 1.0, "event": "chunk", "index": 0})
    j.close()
    j.close()  # idempotent
    recs = [json.loads(l) for l in open(path)]
    assert [r["event"] for r in recs] == ["run_start", "chunk", "run_end"]
    assert recs[0]["run_id"] == "r9" and recs[0]["process"] == 0


def test_config_digest_stable_and_discriminating():
    a = obs.config_digest({"lr": 0.1, "mesh": (1, 8)})
    assert a == obs.config_digest({"mesh": (1, 8), "lr": 0.1})  # order-free
    assert a != obs.config_digest({"lr": 0.2, "mesh": (1, 8)})
    assert obs.config_digest({"fn": open})  # non-JSON degrades, not raises


def test_open_run_writes_standard_files_and_installs(tmp_path):
    d = str(tmp_path / "obs")
    rec = obs.open_run(d, config={"x": 1}, meta={"workload": "t"})
    try:
        assert obs_events.get_default_recorder() is rec
        rec.inc("driver.chunks")
        obs_events.emit("rollback", index=2, total=1, budget=8)
        rec.flush()
    finally:
        rec.close()
    assert obs_events.get_default_recorder() is None  # uninstalled on close
    names = sorted(os.listdir(d))
    assert names == ["events-p0.jsonl", "journal-p0.jsonl",
                     "metrics-p0.prom"]
    journal = [json.loads(l) for l in
               open(os.path.join(d, "journal-p0.jsonl"))]
    assert journal[0]["event"] == "run_start"
    assert journal[0]["workload"] == "t"
    assert journal[0]["config_digest"] == obs.config_digest({"x": 1})
    assert [r["event"] for r in journal] == ["run_start", "rollback",
                                             "run_end"]
    # Base labels (process identity) ride every series.
    assert 'fps_tpu_driver_chunks{process="0"} 1' in open(
        os.path.join(d, "metrics-p0.prom")).read()


def test_default_recorder_scoped_and_noop():
    obs_events.emit("whatever")  # no recorder installed: silent no-op
    sink = obs.MemorySink()
    with obs_events.default_recorder(obs.Recorder(sinks=[sink])):
        obs_events.emit("rollback", index=1)
        obs_events.record_metric("inc", "rollback.quarantined", 1)
    assert obs_events.get_default_recorder() is None
    assert [e["event"] for e in sink.events()] == ["rollback"]
    assert sink.metrics("rollback.quarantined")


# ---------------------------------------------------------------------------
# Checkpoint + rollback event emission (the deep-layer trail).
# ---------------------------------------------------------------------------

def test_checkpoint_save_and_fallback_events(tmp_path, devices8):
    from fps_tpu.core.checkpoint import Checkpointer

    mesh = make_ps_mesh(num_shards=1, num_data=1, devices=devices8[:1])
    store = ParamStore(mesh, [TableSpec("t", 16, 2).zeros_init()])
    store.init(jax.random.key(0))
    sink = obs.MemorySink()
    rec = obs.Recorder(sinks=[sink])
    with obs_events.default_recorder(rec):
        ckpt = Checkpointer(str(tmp_path / "c"), keep=2)
        ckpt.save(1, store)
        ckpt.save(2, store)
        chaos.corrupt_latest_snapshot(str(tmp_path / "c"), "truncate")
        _, step = ckpt.restore_tables(store)
    assert step == 1
    saves = sink.events("checkpoint_saved")
    assert [e["step"] for e in saves] == [1, 2]
    assert all(e["bytes"] > 0 and e["seconds"] >= 0 for e in saves)
    # The saved event must carry the published path (and the byte size
    # above): the serving plane's SnapshotWatcher opens snapshots straight
    # from these fields, no directory re-stat on the hot path.
    from fps_tpu.core.checkpoint import SNAPSHOT_FMT

    assert [e["path"] for e in saves] == [
        str(tmp_path / "c" / SNAPSHOT_FMT.format(step=s)) for s in (1, 2)
    ]
    fb = sink.events("checkpoint_fallback")
    assert len(fb) == 1 and fb[0]["step"] == 2
    assert rec.counter_value("checkpoint.saves") == 2
    assert rec.counter_value("checkpoint.fallbacks") == 1


# ---------------------------------------------------------------------------
# Driver wiring (multi-device mesh).
# ---------------------------------------------------------------------------

def _poisoned_stream(W, kind="huge", idx=(1,), epochs=1, nchunks=None):
    train, _ = _logreg_data()
    clean = _logreg_chunks(train, W, epochs=epochs)
    if nchunks is not None:
        clean = clean[:nchunks]
    out = iter(clean)
    for i in sorted(idx):
        out = chaos.poison_chunks(out, chunk_index=i, column="feat_vals",
                                  kind=kind, frac=0.5, seed=1)
    return list(out)


def test_fit_stream_records_phases_health_and_events(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    chunks = _poisoned_stream(W, kind="nan", idx=(1,), nchunks=3)
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(mesh, cfg, guard="mask")
    sink = obs.MemorySink()
    rec = obs.Recorder(sinks=[sink])
    trainer.recorder = rec
    tables, ls = trainer.init_state(jax.random.key(0))
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                       on_chunk=lambda i, m: None)
    assert rec.counter_value("driver.chunks") == 3
    assert rec.counter_value("driver.examples") > 0
    assert rec.counter_value("health.nonfinite_rows", table="weights") > 0
    assert rec.counter_value("health.masked_rows", table="weights") > 0
    assert rec.counter_value("health.poisoned_chunks") == 1
    ev = sink.events("chunk")
    assert [e["index"] for e in ev] == [0, 1, 2]
    assert ev[1]["poison_rows"] > 0 and "poison_rows" not in ev[0]
    for e in ev:
        assert {"ingest", "place", "dispatch", "host_sync",
                "callback"} <= set(e["phases"])
    pt = rec.phase_totals()
    assert pt["dispatch"]["n"] == 3 and pt["dispatch"]["s"] > 0


def test_health_monitor_escalates_observe_to_mask(devices8):
    """ISSUE acceptance: chaos-poisoned stream under guard='observe' +
    HealthMonitor escalates to 'mask' after the row threshold. Paired
    with rollback (the production posture): the pre-escalation poisoned
    chunk is quarantined whole, the post-escalation one is ALSO masked
    in-step — its poison never reaches the fold even before the
    host-loop rollback decision lands."""
    from fps_tpu.core.resilience import RollbackPolicy

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    chunks = _poisoned_stream(W, kind="huge", idx=(1, 3), nchunks=5)
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(
        mesh, cfg, guard=GuardConfig(mode="observe", norm_limit=100.0))
    sink = obs.MemorySink()
    rec = obs.Recorder(sinks=[sink])
    monitor = obs.HealthMonitor(escalate_after_rows=1)
    policy = RollbackPolicy(max_rollbacks=4)
    tables, ls = trainer.init_state(jax.random.key(0))
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                       recorder=rec, health=monitor, rollback=policy)
    # Escalated exactly at the first poisoned chunk...
    assert monitor.escalated_at == 1
    from fps_tpu.core import resilience
    assert resilience.as_guard(trainer.config.guard).mode == "mask"
    esc = sink.events("guard_escalated")
    assert len(esc) == 1 and esc[0]["index"] == 1
    # ...chunk 1's poison was observed-only, chunk 3's was masked in-step
    # (mask mode still counts, so rollback quarantines both — documented
    # mask+rollback semantics).
    assert rec.counter_value("health.norm_rows", table="weights") > 0
    assert rec.counter_value("health.masked_rows", table="weights") > 0
    assert policy.quarantined == [1, 3]
    assert rec.counter_value("rollback.quarantined") == 2
    assert monitor.poisoned_chunks == 2
    assert np.all(np.isfinite(_weights(store)))


def test_health_monitor_abort_raises(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    chunks = _poisoned_stream(W, kind="nan", idx=(0, 1, 2), nchunks=3)
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, _ = logistic_regression(mesh, cfg, guard="mask")
    sink = obs.MemorySink()
    monitor = obs.HealthMonitor(abort_after_chunks=2)
    tables, ls = trainer.init_state(jax.random.key(0))
    with pytest.raises(PoisonedStreamError, match="health monitor abort"):
        trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                           recorder=obs.Recorder(sinks=[sink]),
                           health=monitor)
    assert monitor.poisoned_chunks == 2
    assert sink.events("health_abort")


def test_health_monitor_requires_guard(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, _ = logistic_regression(mesh, cfg)  # no guard
    tables, ls = trainer.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="health channel"):
        trainer.fit_stream(tables, ls, iter([]), jax.random.key(1),
                           health=obs.HealthMonitor())
    with pytest.raises(TypeError, match="HealthMonitor"):
        trainer.fit_stream(tables, ls, iter([]), jax.random.key(1),
                           health=object())


def test_watchdog_clean_run_no_stalls(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    train, _ = _logreg_data()
    chunks = _logreg_chunks(train, W, epochs=1)[:2]
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, _ = logistic_regression(mesh, cfg)
    wd = obs.StepWatchdog(120.0)
    tables, ls = trainer.init_state(jax.random.key(0))
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                       watchdog=wd)
    assert wd.stalls == []


def test_run_indexed_records_epochs(devices8):
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    data = synthetic_ratings(57, 31, 800, seed=0)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)
    trainer, store = online_mf(mesh, cfg, donate=False)
    sink = obs.MemorySink()
    rec = obs.Recorder(sinks=[sink])
    tables, ls = trainer.init_state(jax.random.key(0))
    plan = DeviceEpochPlan(DeviceDataset(mesh, data), num_workers=W,
                           local_batch=32, route_key="user", seed=5)
    trainer.run_indexed(tables, ls, plan, jax.random.key(1), epochs=2,
                        recorder=rec)
    assert rec.counter_value("driver.epochs") == 2
    assert rec.counter_value("driver.examples") == 1600.0
    ev = sink.events("epoch")
    assert [e["index"] for e in ev] == [0, 1]
    assert all("dispatch" in e["phases"] for e in ev)


def test_recorder_off_and_on_compile_identically(devices8):
    """ISSUE acceptance: the recorder is host-side only — attaching one
    must not change the traced program at all (bit-identical lowered
    text), unlike e.g. the guard which is part of the program."""
    from fps_tpu.parallel.mesh import host_to_sharded, key_to_replicated

    from fps_tpu.core.api import StepOutput, WorkerLogic

    class _Pusher(WorkerLogic):
        def pull_ids(self, batch):
            return {"t": batch["id"].astype(np.int32)}

        def step(self, batch, pulled, local_state, key):
            return StepOutput(
                pushes={"t": (batch["id"].astype(np.int32), batch["val"])},
                local_state=local_state, out={},
            )

    def lowered_text(recorder):
        mesh = make_ps_mesh(num_shards=1, num_data=1, devices=devices8[:1])
        store = ParamStore(mesh, [TableSpec("t", 16, 2).zeros_init()])
        trainer = Trainer(mesh, store, _Pusher(),
                          config=TrainerConfig(donate=False),
                          recorder=recorder)
        tables, ls = trainer.init_state(jax.random.key(0))
        chunk = {
            "id": np.zeros((1, 4), np.int32),
            "val": np.zeros((1, 4, 2), np.float32),
        }
        sharding = trainer._batch_sharding_for("sync")
        batches = jax.tree.map(lambda x: host_to_sharded(x, sharding), chunk)
        key = key_to_replicated(jax.random.key(1), mesh)
        return trainer._get_compiled("sync").lower(
            tables, ls, batches, key).as_text()

    assert lowered_text(None) == lowered_text(
        obs.Recorder(sinks=[obs.MemorySink()]))


# ---------------------------------------------------------------------------
# Registry completeness (ISSUE 12 satellite): every metric name the
# package emits has a spec — the silently-unregistered-metric class.
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = None  # compiled lazily below


def _emitted_metric_names():
    """AST scan of fps_tpu/ for metric emissions: ``<recv>.inc/set/
    observe("name", ...)`` calls, the ``events.record_metric(kind,
    "name", ...)`` indirection, and wrapper helpers (``_emit_metric`` /
    ``_inc``-style) — the first string argument shaped like a dotted
    metric name is the emission."""
    import ast
    import re

    name_re = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")
    emitters = {"inc", "set", "observe", "record_metric",
                "_emit_metric", "_obs_metric", "_inc", "_set",
                "_observe"}
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "fps_tpu")
    found = {}  # name -> first "path:line" site
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                leaf = (func.attr if isinstance(func, ast.Attribute)
                        else func.id if isinstance(func, ast.Name)
                        else None)
                if leaf not in emitters:
                    continue
                for arg in node.args:
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)
                            and name_re.match(arg.value)):
                        found.setdefault(
                            arg.value,
                            f"{os.path.relpath(path, root)}:"
                            f"{node.lineno}")
                        break
    return found


def test_every_emitted_metric_name_is_registered():
    """The silently-unregistered-metric class: an emission through the
    process-default path (events.record_metric) degrades to a logged
    DROP when its name has no spec — this scan fails the build instead,
    for every emission site anywhere in fps_tpu/."""
    emitted = _emitted_metric_names()
    # Non-vacuity: the scan must see the known emission styles — direct
    # recorder calls (driver), the process-default indirection
    # (checkpoint), and the serve-side _emit_metric wrapper.
    for expected in ("driver.chunks", "checkpoint.saves",
                     "serve.rejected_snapshots",
                     "analysis.budget_drift",
                     "analysis.certified_programs"):
        assert expected in emitted, f"scan lost {expected}"
    registry = obs.default_registry()
    unregistered = {name: site for name, site in sorted(emitted.items())
                    if name not in registry}
    assert not unregistered, (
        "metric(s) emitted without a MetricSpec in "
        f"obs/registry.py: {unregistered}")


def test_registry_scan_catches_a_seeded_unregistered_emission(tmp_path):
    """The scanner itself is not vacuous: a seeded emission of an
    unknown name would be caught by the same name-shape matcher."""
    import ast
    import re

    name_re = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")
    src = 'rec.inc("totally.unregistered_metric", 2, table="x")\n'
    call = ast.parse(src).body[0].value
    [arg] = [a for a in call.args if isinstance(a, ast.Constant)
             and isinstance(a.value, str) and name_re.match(a.value)]
    assert arg.value not in obs.default_registry()
