"""iALS tests: oracle equivalence of the sharded half-epoch solve, objective
descent, and ranking quality on planted-structure implicit data.

iALS is the BASELINE.json extension workload ("Implicit-feedback iALS
(MovieLens-20M)"); SURVEY.md §7 calls for a per-epoch sharded
normal-equation driver distinct from the streaming PS loop.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def mods():
    import jax

    from fps_tpu.models import ials
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_implicit

    return dict(jax=jax, ials=ials, make_ps_mesh=make_ps_mesh,
                synthetic_implicit=synthetic_implicit)


def _solver(mods, num_shards, nu, ni, rank, **cfg_kw):
    jax, ials = mods["jax"], mods["ials"]
    mesh = mods["make_ps_mesh"](num_shards=num_shards, num_data=1,
                                devices=jax.devices()[:num_shards])
    cfg = ials.IALSConfig(num_users=nu, num_items=ni, rank=rank, **cfg_kw)
    solver = ials.IALSSolver(mesh, cfg)
    solver.init(jax.random.key(0))
    return solver


def _numpy_half_epoch(U, V, users, items, ratings, alpha, reg, num_solve):
    """Dense-numpy oracle for one ALS half-step solving the U side."""
    k = V.shape[1]
    G = V.T @ V
    A = np.zeros((num_solve, k, k))
    b = np.zeros((num_solve, k))
    for u, i, r in zip(users, items, ratings):
        y = V[i]
        A[u] += alpha * r * np.outer(y, y)
        b[u] += (1.0 + alpha * r) * y
    out = np.zeros((num_solve, k))
    for u in range(num_solve):
        out[u] = np.linalg.solve(G + A[u] + reg * np.eye(k), b[u])
    return out


def test_half_epoch_matches_numpy_oracle(mods, devices8):
    """The sharded gram + accumulate + solve pipeline must equal dense ALS."""
    ials = mods["ials"]
    nu, ni, rank = 13, 9, 3  # deliberately not multiples of the shard count
    solver = _solver(mods, 4, nu, ni, rank, alpha=5.0, reg=0.3)
    data = mods["synthetic_implicit"](nu, ni, 7, rank=2, seed=1)

    U0, V0 = solver.factors()
    expected = _numpy_half_epoch(
        U0.astype(np.float64), V0.astype(np.float64),
        data["user"], data["item"], data["rating"],
        alpha=5.0, reg=0.3, num_solve=nu,
    )

    solver.half_epoch(
        "user",
        ials.interaction_chunks(data, num_workers=4, local_batch=4,
                                steps_per_chunk=2, seed=None),
    )
    U1, _ = solver.factors()
    np.testing.assert_allclose(U1, expected, rtol=2e-3, atol=2e-4)


def test_item_half_epoch_matches_numpy_oracle(mods, devices8):
    ials = mods["ials"]
    nu, ni, rank = 9, 14, 3
    solver = _solver(mods, 4, nu, ni, rank, alpha=3.0, reg=0.5)
    data = mods["synthetic_implicit"](nu, ni, 6, rank=2, seed=2)

    U0, V0 = solver.factors()
    expected = _numpy_half_epoch(
        V0.astype(np.float64), U0.astype(np.float64),
        data["item"], data["user"], data["rating"],
        alpha=3.0, reg=0.5, num_solve=ni,
    )
    solver.half_epoch(
        "item",
        ials.interaction_chunks(data, num_workers=4, local_batch=4,
                                steps_per_chunk=2, seed=None),
    )
    _, V1 = solver.factors()
    np.testing.assert_allclose(V1, expected, rtol=2e-3, atol=2e-4)


def test_objective_decreases_over_epochs(mods, devices8):
    ials = mods["ials"]
    nu, ni = 48, 32
    solver = _solver(mods, 8, nu, ni, rank=8, alpha=10.0, reg=0.5)
    data = mods["synthetic_implicit"](nu, ni, 12, rank=3, seed=3)

    def chunks():
        return ials.interaction_chunks(data, num_workers=8, local_batch=8,
                                       steps_per_chunk=2, seed=0)

    losses = [solver.weighted_loss(data["user"], data["item"], data["rating"])]
    for _ in range(3):
        solver.epoch(chunks)
        losses.append(
            solver.weighted_loss(data["user"], data["item"], data["rating"])
        )
    # ALS descends monotonically on the full objective; on the observed-term
    # estimate we still demand a big first drop and no blow-up after.
    assert losses[1] < 0.5 * losses[0], losses
    assert losses[-1] <= losses[1] * 1.05, losses


def test_recall_beats_random(mods, devices8):
    ials = mods["ials"]
    nu, ni = 40, 60
    data = mods["synthetic_implicit"](nu, ni, 20, rank=3, seed=4)
    # Hold out each user's last interaction.
    last = np.full(nu, -1)
    for idx, u in enumerate(data["user"]):
        last[u] = idx
    mask = np.zeros(len(data["user"]), bool)
    mask[last[last >= 0]] = True
    train = {k: v[~mask] for k, v in data.items()}
    hu, hi = data["user"][mask], data["item"][mask]

    solver = _solver(mods, 8, nu, ni, rank=8, alpha=10.0, reg=0.5)

    def chunks():
        return ials.interaction_chunks(train, num_workers=8, local_batch=8,
                                       steps_per_chunk=2, seed=0)

    for _ in range(3):
        solver.epoch(chunks)
    rec = ials.recall_at_k(solver, hu, hi, k=10,
                           exclude=(train["user"], train["item"]))
    # Random top-10 of 60 items ≈ 0.167; planted structure must beat it well.
    assert rec > 0.35, rec


def test_full_mesh_matches_shard_only_mesh(mods, devices8):
    """iALS over a (2, 4) data x shard mesh (stream split over ALL devices,
    pushes psum'd across the data axis) must solve the same factors as the
    1 x 8 shard-only mesh — closing the round-1 restriction that refused
    data-parallel meshes."""
    jax, ials = mods["jax"], mods["ials"]
    nu, ni, rank = 24, 18, 4
    data = mods["synthetic_implicit"](nu, ni, 9, rank=2, seed=6)

    def run(num_data, num_shards):
        mesh = mods["make_ps_mesh"](
            num_shards=num_shards, num_data=num_data,
            devices=jax.devices()[: num_data * num_shards],
        )
        cfg = ials.IALSConfig(num_users=nu, num_items=ni, rank=rank,
                              alpha=5.0, reg=0.3)
        solver = ials.IALSSolver(mesh, cfg)
        solver.init(jax.random.key(0))
        assert solver.num_workers == num_data * num_shards

        def chunks():
            return ials.interaction_chunks(
                data, num_workers=solver.num_workers, local_batch=4,
                steps_per_chunk=2, seed=0,
            )

        for _ in range(2):
            solver.epoch(chunks)
        return solver.factors()

    U_a, V_a = run(1, 8)
    U_b, V_b = run(2, 4)
    # Same normal equations accumulated in a different order: equal up to
    # float32 reassociation.
    np.testing.assert_allclose(U_a, U_b, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(V_a, V_b, rtol=5e-4, atol=5e-5)
