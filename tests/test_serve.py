"""The read-path serving tier (fps_tpu.serve + core/snapshot_format).

Contract under test (ISSUE 7, ``docs/serving.md``):

* the jax-free on-disk snapshot contract: zero-copy ``map_snapshot_arrays``
  views agree byte-for-byte with ``np.load``, and ``verify_snapshot_file``
  rejects exactly what the checkpoint layer's verified reader rejects
  (truncation, bit rot, garbage) — including on REAL ``Checkpointer``
  output, so the two planes cannot drift;
* ``SnapshotWatcher``: forward-monotone publication, torn-candidate
  rejection (cached per inode), journal tailing that survives truncation
  and file replacement (the supervisor restart path), and the BACKWARD
  swap when the trainer quarantines the served snapshot;
* ``ReadServer``: pull/score/topk numerics against plain-numpy references,
  and the hot-swap contract — an in-flight batched lookup completes on
  the snapshot it started on, and swap latency is a pointer flip
  independent of table size;
* the line-JSON TCP transport and the jax-free ``tools/serve.py`` CLI
  (jax poisoned in the subprocess — any import attempt raises).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from fps_tpu.core import snapshot_format as fmt
from fps_tpu.serve import (
    JsonlClient,
    NoSnapshotError,
    ReadServer,
    ServableSnapshot,
    SnapshotRejected,
    SnapshotWatcher,
    TcpServe,
)
from fps_tpu.serve import wire
from fps_tpu.serve.watcher import _JournalTail
from fps_tpu.testing import chaos

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_snapshot(dirpath, step, *, tables=None, ls=(),
                   ls_format="exported", seed=0):
    """Handcraft a snapshot in the checkpoint writer's exact npz layout
    (uncompressed members + per-array ``meta::crc`` tags). Returns the
    raw arrays for reference checks."""
    rng = np.random.default_rng(seed)
    if tables is None:
        tables = {"weights": rng.normal(size=(32, 3)).astype(np.float32),
                  "item_factors": rng.normal(size=(16, 4)).astype(
                      np.float32)}
    arrays = {f"table::{k}": np.asarray(v) for k, v in tables.items()}
    for i, leaf in enumerate(ls):
        arrays[f"ls::{i}"] = np.asarray(leaf)
    arrays["meta::ls_format"] = np.array(ls_format)
    for k in list(arrays):
        arrays["meta::crc::" + k] = np.uint32(fmt.array_crc32(arrays[k]))
    os.makedirs(dirpath, exist_ok=True)
    np.savez(fmt.snapshot_path(dirpath, step), **arrays)
    return arrays


def journal_append(path, records):
    with open(path, "a", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def saved_event(step, path, t=None):
    return {"kind": "event", "event": "checkpoint_saved", "step": step,
            "path": path, "t": time.time() if t is None else t,
            "bytes": os.path.getsize(path)}


# ---------------------------------------------------------------------------
# snapshot_format: the jax-free on-disk contract.
# ---------------------------------------------------------------------------

def test_map_snapshot_arrays_is_zero_copy_and_exact(tmp_path):
    d = str(tmp_path)
    ref = write_snapshot(d, 3, ls=[np.arange(12, dtype=np.float32)
                                   .reshape(4, 3)])
    path = fmt.snapshot_path(d, 3)
    mapped = fmt.map_snapshot_arrays(path)
    with np.load(path) as z:  # the ground truth the maps must equal
        for key, arr in mapped.items():
            assert isinstance(arr, np.memmap), key
            assert not arr.flags.writeable
            np.testing.assert_array_equal(np.asarray(arr), z[key])
    assert sorted(mapped) == ["ls::0", "table::item_factors",
                              "table::weights"]
    np.testing.assert_array_equal(mapped["table::weights"],
                                  ref["table::weights"])


def test_map_snapshot_arrays_rejects_compressed(tmp_path):
    path = str(tmp_path / "ckpt_000000000001.npz")
    np.savez_compressed(path, **{"table::t": np.zeros((4, 2), np.float32)})
    with pytest.raises(ValueError, match="compressed"):
        fmt.map_snapshot_arrays(path)


def test_verify_snapshot_file_catches_corruption(tmp_path):
    d = str(tmp_path)
    write_snapshot(d, 1, seed=1)
    path = fmt.snapshot_path(d, 1)
    assert fmt.verify_snapshot_file(path) == (True, None)

    chaos.bitflip_file(path, nflips=8, seed=0)
    ok, reason = fmt.verify_snapshot_file(path)
    assert not ok and reason

    write_snapshot(d, 2, seed=2)
    chaos.truncate_file(fmt.snapshot_path(d, 2), keep_frac=0.5)
    ok, reason = fmt.verify_snapshot_file(fmt.snapshot_path(d, 2))
    assert not ok
    assert fmt.verify_snapshot_file(str(tmp_path / "nope.npz")) == (
        False, "no such file")


def test_latest_valid_snapshot_skips_corrupt_newest(tmp_path):
    d = str(tmp_path)
    assert fmt.snapshot_steps(str(tmp_path / "missing")) == []
    assert fmt.latest_valid_snapshot(d) is None
    write_snapshot(d, 1, seed=1)
    write_snapshot(d, 5, seed=5)
    chaos.truncate_file(fmt.snapshot_path(d, 5))
    assert fmt.snapshot_steps(d) == [1, 5]
    assert fmt.latest_valid_snapshot(d) == (1, fmt.snapshot_path(d, 1))
    # Read-only: the corrupt file is left in place (trainer owns quarantine).
    assert os.path.exists(fmt.snapshot_path(d, 5))


def test_real_checkpointer_output_is_servable(tmp_path, devices8):
    """The two planes cannot drift: a REAL Checkpointer snapshot (CRC
    tags, exported local state) opens, verifies, and serves the exact
    table and local-state bytes the store holds."""
    import jax

    from fps_tpu.core.checkpoint import Checkpointer
    from fps_tpu.core.store import ParamStore, TableSpec
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=1, num_data=1, devices=devices8[:1])
    store = ParamStore(mesh, [TableSpec("t", 16, 2).zeros_init()])
    store.init(jax.random.key(0))
    ls = [np.arange(8, dtype=np.float32).reshape(4, 2)]
    ckpt = Checkpointer(str(tmp_path), keep=2)
    ckpt.save(7, store, ls, local_state_format="exported")

    snap = ServableSnapshot.open(fmt.snapshot_path(str(tmp_path), 7))
    assert snap.step == 7 and snap.local_state_format == "exported"
    np.testing.assert_array_equal(np.asarray(snap.table("t")),
                                  store.dump_model("t")[1])
    np.testing.assert_array_equal(np.asarray(snap.local_state[0]), ls[0])
    # And the serving-plane verifier agrees with the checkpoint layer's.
    assert ckpt.verify_snapshot(7)
    assert fmt.verify_snapshot_file(fmt.snapshot_path(str(tmp_path), 7))[0]


def test_snapshot_constants_are_shared_with_checkpoint_layer():
    from fps_tpu.core import checkpoint

    assert checkpoint.SNAPSHOT_RE is fmt.SNAPSHOT_RE
    assert checkpoint.SNAPSHOT_FMT is fmt.SNAPSHOT_FMT
    assert checkpoint._CRC_PREFIX == fmt.CRC_PREFIX
    assert checkpoint._IO_ERRORS == fmt.IO_ERRORS


# ---------------------------------------------------------------------------
# ServableSnapshot.
# ---------------------------------------------------------------------------

def test_servable_snapshot_rejects_torn_file(tmp_path):
    d = str(tmp_path)
    write_snapshot(d, 1)
    chaos.truncate_file(fmt.snapshot_path(d, 1))
    with pytest.raises(SnapshotRejected):
        ServableSnapshot.open(fmt.snapshot_path(d, 1))
    with pytest.raises(ValueError, match="naming contract"):
        ServableSnapshot.open(str(tmp_path / "model.npz"))


def test_servable_snapshot_lookup_contract(tmp_path):
    d = str(tmp_path)
    ref = write_snapshot(d, 2)
    snap = ServableSnapshot.open(fmt.snapshot_path(d, 2))
    out = snap.lookup("weights", [0, 5, -1])
    np.testing.assert_array_equal(out[0], ref["table::weights"][0])
    np.testing.assert_array_equal(out[2], np.zeros(3, np.float32))
    with pytest.raises(IndexError):
        snap.lookup("weights", [999])
    # Only -1 is the padding sentinel; other negatives are client bugs
    # and must not silently read as zero rows.
    with pytest.raises(IndexError, match="padding sentinel"):
        snap.lookup("weights", [-7, 3])
    with pytest.raises(KeyError, match="no table"):
        snap.table("nope")
    man = snap.manifest()
    assert man["step"] == 2
    assert man["tables"]["weights"]["shape"] == [32, 3]


# ---------------------------------------------------------------------------
# SnapshotWatcher: publication, rejection, rollback, journal tailing.
# ---------------------------------------------------------------------------

def test_watcher_forward_swaps_and_rejection_cache(tmp_path):
    d = str(tmp_path)
    server, watcher = ReadServer.over(d)
    assert watcher.current is None
    with pytest.raises(NoSnapshotError):
        server.pull("weights", [0])

    write_snapshot(d, 1, seed=1)
    assert watcher.poll().step == 1
    write_snapshot(d, 2, seed=2)
    assert watcher.poll().step == 2
    assert watcher.swaps == {"forward": 2, "backward": 0}

    # A torn candidate is rejected TWICE per inode — the second read
    # CONFIRMS the verdict (one failing open can be a transient stale
    # read on a hostile filesystem, not evidence about the durable
    # bytes) — then the cache pins it: no further re-verify churn, and
    # it is never served.
    with open(fmt.snapshot_path(d, 9), "wb") as f:
        f.write(b"PK\x03\x04junk")
    assert watcher.poll() is None
    assert watcher.poll() is None
    assert watcher.poll() is None
    assert watcher.rejected == 2
    assert server.snapshot.step == 2
    # An atomic RE-publish of the same step gets a fresh verdict.
    write_snapshot(d, 9, seed=9)
    assert watcher.poll().step == 9


def test_watcher_swaps_backward_past_quarantine(tmp_path):
    d = str(tmp_path)
    write_snapshot(d, 1, seed=1)
    write_snapshot(d, 2, seed=2)
    server, watcher = ReadServer.over(d)
    assert server.snapshot.step == 2
    # The trainer's on-disk quarantine verdict: *.corrupt rename.
    os.replace(fmt.snapshot_path(d, 2), fmt.snapshot_path(d, 2) + ".corrupt")
    watcher.poll()
    assert server.snapshot.step == 1
    assert watcher.swaps["backward"] == 1
    # In-flight maps on the quarantined snapshot would still be valid;
    # new requests answer from the surviving step.
    assert server.pull("weights", [0])[0] == 1


def test_watcher_serves_republished_step_after_quarantine(tmp_path):
    """The rollback-replay path: the trainer quarantines ckpt_N
    (*.corrupt sibling lingers), restores N-1, replays, and publishes a
    FRESH valid ckpt_N. The re-publish supersedes the quarantine verdict
    — readers must not stay pinned behind it until N+1 appears."""
    d = str(tmp_path)
    write_snapshot(d, 1, seed=1)
    write_snapshot(d, 2, seed=2)
    server, watcher = ReadServer.over(d)
    assert server.snapshot.step == 2
    os.replace(fmt.snapshot_path(d, 2), fmt.snapshot_path(d, 2) + ".corrupt")
    watcher.poll()
    assert server.snapshot.step == 1  # rolled back with the trainer
    ref = write_snapshot(d, 2, seed=22)  # the replayed re-publish
    assert watcher.poll().step == 2
    _, rows = server.pull("weights", [0])
    np.testing.assert_array_equal(rows[0], ref["table::weights"][0])
    # open->2, quarantine->1 (backward), re-publish->2 (forward again).
    assert watcher.swaps == {"forward": 2, "backward": 1}


def test_watcher_reopens_served_step_replaced_between_polls(tmp_path):
    """The quarantine→replay cycle can complete ENTIRELY between two
    polls: the watcher never sees the *.corrupt sibling, only the same
    step name atomically pointing at a fresh inode. Identity is (inode,
    mtime), not (step, exists) — readers must get the replayed bytes,
    not the stale mapping, and a torn re-publish must fall back."""
    d = str(tmp_path)
    write_snapshot(d, 1, seed=1)
    write_snapshot(d, 2, seed=2)
    server, watcher = ReadServer.over(d)
    assert server.snapshot.step == 2
    # Same step, fresh inode (np.savez writes a new file in place; give
    # the mtime a distinct value for coarse-clock filesystems).
    ref = write_snapshot(d, 2, seed=22)
    os.utime(fmt.snapshot_path(d, 2), ns=(1, 1))
    assert watcher.poll().step == 2
    _, rows = server.pull("weights", [0])
    np.testing.assert_array_equal(rows[0], ref["table::weights"][0])
    assert watcher.swaps == {"forward": 2, "backward": 0}
    # A TORN re-publish of the served step swaps backward instead.
    write_snapshot(d, 2, seed=222)
    chaos.truncate_file(fmt.snapshot_path(d, 2))
    watcher.poll()
    assert server.snapshot.step == 1
    assert watcher.swaps["backward"] == 1 and watcher.rejected == 1


def test_topk_rejects_negative_user_ids(tmp_path):
    """Negative user ids must error, not wrap to another user's rows."""
    d = str(tmp_path)
    write_snapshot(d, 1, ls=[np.random.default_rng(0).normal(
        size=(8, 4)).astype(np.float32)])
    server = ReadServer(ServableSnapshot.open(fmt.snapshot_path(d, 1)))
    with pytest.raises(IndexError, match="user ids"):
        server.topk([-1], k=2)
    with pytest.raises(IndexError, match="user ids"):
        server.topk([99], k=2)
    with pytest.raises(ValueError, match="k must be"):
        server.topk([0], k=0)


def test_watcher_swaps_backward_when_served_file_vanishes(tmp_path):
    """The served snapshot deleted WITHOUT a *.corrupt rename (operator
    cleanup, aggressive GC) while its step lingers in the journal's
    saved events: the watcher must still fall back to the surviving
    snapshot, not keep serving the unlinked inode forever."""
    from fps_tpu import obs

    d = str(tmp_path / "ckpt")
    jpath = str(tmp_path / "journal-p0.jsonl")
    write_snapshot(d, 1, seed=1)
    write_snapshot(d, 2, seed=2)
    rec = obs.Recorder(sinks=[])
    server, watcher = ReadServer.over(d, journal=jpath, recorder=rec)
    journal_append(jpath, [saved_event(1, fmt.snapshot_path(d, 1)),
                           saved_event(2, fmt.snapshot_path(d, 2))])
    watcher.poll()
    assert server.snapshot.step == 2

    os.remove(fmt.snapshot_path(d, 2))
    watcher.poll()
    assert server.snapshot.step == 1
    assert watcher.swaps["backward"] == 1

    # And when NOTHING survives, the stale-serving state is surfaced:
    # the lag gauge goes NaN while the mapped pages keep answering.
    os.remove(fmt.snapshot_path(d, 1))
    watcher.poll()
    assert server.snapshot.step == 1  # still answering from the old map
    assert np.isnan(rec.snapshot()["gauges"]["serve.snapshot_lag_steps"])


def test_watcher_journal_only_mode_needs_no_dir_scan(tmp_path):
    """checkpoint_saved events carry path/step/bytes (ISSUE 7 satellite):
    a journal-only watcher (poll_dir=False) publishes from the events
    alone, and a checkpoint_fallback event rolls it backward even though
    the file is still on disk."""
    d = str(tmp_path / "ckpt")
    jpath = str(tmp_path / "journal-p0.jsonl")
    write_snapshot(d, 1, seed=1)
    write_snapshot(d, 2, seed=2)
    server = ReadServer()
    watcher = SnapshotWatcher(
        d, journal=jpath, poll_dir=False,
        on_swap=lambda snap, _d: server.swap_to(snap))
    assert watcher.poll() is None  # journal not written yet

    journal_append(jpath, [saved_event(1, fmt.snapshot_path(d, 1)),
                           saved_event(2, fmt.snapshot_path(d, 2))])
    assert watcher.poll().step == 2
    assert watcher.max_written_step == 2

    journal_append(jpath, [{"kind": "event", "event": "checkpoint_fallback",
                            "step": 2, "t": time.time()}])
    watcher.poll()
    assert server.snapshot.step == 1
    assert watcher.swaps["backward"] == 1


def test_watcher_journal_dir_created_after_start(tmp_path):
    """A --journal pointing at an --obs-dir that does not exist YET
    (server started before the trainer) must begin consuming events once
    the directory and its journal-*.jsonl appear — and keep picking up
    journals that join later (multi-process runs add them)."""
    d = str(tmp_path / "ckpt")
    obs_dir = str(tmp_path / "obs")  # not created yet
    watcher = SnapshotWatcher(d, journal=obs_dir, poll_dir=False)
    assert watcher.poll() is None

    os.makedirs(obs_dir)
    write_snapshot(d, 1, seed=1)
    journal_append(os.path.join(obs_dir, "journal-p0.jsonl"),
                   [saved_event(1, fmt.snapshot_path(d, 1))])
    assert watcher.poll().step == 1
    # A journal file that joins later is tailed too.
    write_snapshot(d, 2, seed=2)
    journal_append(os.path.join(obs_dir, "journal-p1.jsonl"),
                   [saved_event(2, fmt.snapshot_path(d, 2))])
    assert watcher.poll().step == 2


def test_journal_tail_survives_truncation_and_rotation(tmp_path):
    """ISSUE 7 satellite: the tail must survive a journal truncated or
    replaced mid-tail (the supervisor restart path does exactly this),
    and buffer a torn final line until its newline arrives."""
    path = str(tmp_path / "journal-p0.jsonl")
    tail = _JournalTail(path)
    assert tail.read_new() == []  # not created yet

    journal_append(path, [{"a": 1}, {"a": 2}])
    assert [r["a"] for r in tail.read_new()] == [1, 2]

    # Torn final line: buffered, delivered once complete.
    with open(path, "a") as f:
        f.write('{"a": 3')
    assert tail.read_new() == []
    with open(path, "a") as f:
        f.write('}\n')
    assert [r["a"] for r in tail.read_new()] == [3]

    # Truncation in place: restart from the top (caller dedupes).
    open(path, "w").close()
    journal_append(path, [{"a": 4}])
    assert [r["a"] for r in tail.read_new()] == [4]

    # Rotation: a NEW file replaces the inode under the tailer.
    tmp = str(tmp_path / "new.jsonl")
    with open(tmp, "w") as f:
        f.write(json.dumps({"a": 5}) + "\n")
    os.replace(tmp, path)
    assert [r["a"] for r in tail.read_new()] == [5]

    # Deletion mid-tail: empty reads, then a recreated file reads fresh.
    os.remove(path)
    assert tail.read_new() == []
    journal_append(path, [{"a": 6}])
    assert [r["a"] for r in tail.read_new()] == [6]


def test_watcher_dedupes_replayed_journal_after_truncation(tmp_path):
    """A truncated+rewritten journal re-delivers old checkpoint_saved
    records; the watcher must treat steps as idempotent keys — no
    re-swap, no double counting."""
    d = str(tmp_path / "ckpt")
    jpath = str(tmp_path / "journal-p0.jsonl")
    write_snapshot(d, 1, seed=1)
    server, watcher = ReadServer.over(d, journal=jpath)
    journal_append(jpath, [saved_event(1, fmt.snapshot_path(d, 1))])
    watcher.poll()
    assert server.snapshot.step == 1 and watcher.swaps["forward"] == 1

    # Supervisor restart: journal truncated, the same event replayed.
    open(jpath, "w").close()
    journal_append(jpath, [saved_event(1, fmt.snapshot_path(d, 1))])
    assert watcher.poll() is None
    assert watcher.swaps == {"forward": 1, "backward": 0}


# ---------------------------------------------------------------------------
# ReadServer: numerics, hot swap, latency accounting.
# ---------------------------------------------------------------------------

def _two_snapshots(tmp_path):
    d = str(tmp_path)
    a = write_snapshot(d, 1, seed=1,
                       ls=[np.random.default_rng(1).normal(
                           size=(8, 4)).astype(np.float32)])
    b = write_snapshot(d, 2, seed=2,
                       ls=[np.random.default_rng(2).normal(
                           size=(8, 4)).astype(np.float32)])
    sa = ServableSnapshot.open(fmt.snapshot_path(d, 1))
    sb = ServableSnapshot.open(fmt.snapshot_path(d, 2))
    return a, b, sa, sb


def test_read_server_numerics_match_numpy(tmp_path):
    a, _, sa, _ = _two_snapshots(tmp_path)
    server = ReadServer(sa)

    step, vals = server.pull("weights", [[0, 1], [2, -1]])
    assert step == 1
    w = a["table::weights"]
    np.testing.assert_array_equal(vals[0], w[[0, 1]])
    np.testing.assert_array_equal(vals[1][1], np.zeros(3, np.float32))

    ids = np.array([[0, 2, 4], [1, 3, -1]])
    vs = np.array([[1.0, 0.5, 2.0], [1.0, 1.0, 3.0]], np.float32)
    step, p = server.score_linear(ids, vs, table="weights")
    live = ids >= 0
    logit = (np.where(live, w[np.where(live, ids, 0), 0], 0.0) * vs).sum(1)
    np.testing.assert_allclose(p, 1 / (1 + np.exp(-logit)), rtol=1e-6)
    _, margin = server.score_linear(ids, vs, table="weights", link="none")
    np.testing.assert_allclose(margin, logit, rtol=1e-6)

    users = np.array([0, 5])
    step, items, scores = server.topk(users, k=4)
    ref = a["ls::0"][users] @ a["table::item_factors"].T
    np.testing.assert_array_equal(items, np.argsort(-ref, axis=1)[:, :4])
    np.testing.assert_allclose(
        scores, np.take_along_axis(ref, items, axis=1), rtol=1e-6)

    stats = server.stats()
    assert stats["requests"] == 4 and stats["step"] == 1
    assert stats["latency_p99_s"] >= stats["latency_p50_s"] >= 0


def test_topk_requires_exported_local_state(tmp_path):
    d = str(tmp_path)
    write_snapshot(d, 1, ls=[np.zeros((4, 4), np.float32)], ls_format="raw")
    server = ReadServer(ServableSnapshot.open(fmt.snapshot_path(d, 1)))
    with pytest.raises(ValueError, match="EXPORTED"):
        server.topk([0], k=2)
    with pytest.raises(ValueError, match="no leaf"):
        d2 = str(tmp_path / "b")
        write_snapshot(d2, 1)
        ReadServer(ServableSnapshot.open(
            fmt.snapshot_path(d2, 1))).topk([0], k=2)


def test_hot_swap_is_atomic_for_in_flight_requests(tmp_path):
    """ISSUE acceptance: an in-flight batched lookup completes against
    the snapshot it started on; the swap lands for the NEXT request."""
    a, b, sa, sb = _two_snapshots(tmp_path)
    server = ReadServer(sa)
    entered, release = threading.Event(), threading.Event()
    orig = sa.lookup

    def slow_lookup(name, ids):
        entered.set()
        assert release.wait(10)
        return orig(name, ids)

    sa.lookup = slow_lookup
    result = {}

    def request():
        result["step"], result["vals"] = server.pull("weights", [0, 1])

    t = threading.Thread(target=request)
    t.start()
    assert entered.wait(10)
    server.swap_to(sb)  # swap WHILE the request is inside the lookup
    release.set()
    t.join(10)
    assert result["step"] == 1  # answered from the snapshot it started on
    np.testing.assert_array_equal(result["vals"],
                                  a["table::weights"][[0, 1]])
    assert server.pull("weights", [0, 1])[0] == 2  # next request: new snap


def test_swap_latency_independent_of_table_size(tmp_path):
    """ISSUE acceptance: the swap is a pointer flip — swapping in a
    snapshot with a table ~1000x larger costs the same O(ns) reference
    rebind (mmap: no bytes move). Bounded generously to stay
    timing-robust."""
    d = str(tmp_path)
    write_snapshot(d, 1, tables={"t": np.zeros((16, 4), np.float32)})
    big = np.zeros((1 << 20, 4), np.float32)  # 16 MB
    write_snapshot(d, 2, tables={"t": big})
    small = ServableSnapshot.open(fmt.snapshot_path(d, 1))
    bigsnap = ServableSnapshot.open(fmt.snapshot_path(d, 2))
    assert isinstance(bigsnap.table("t"), np.memmap)  # mapped, not read

    server = ReadServer(small)

    def best_of(snap, reps=2000):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            server.swap_to(snap)
            best = min(best, time.perf_counter() - t0)
        return best

    t_small, t_big = best_of(small), best_of(bigsnap)
    assert t_big < 1e-4, f"swap to 16MB-table snapshot took {t_big}s"
    assert t_big < 50 * max(t_small, 1e-7)


def test_serve_metrics_ride_the_default_registry(tmp_path):
    """Every serve.* leaf is declared in obs.default_registry: emitting
    through a schema-validating Recorder must not raise or drop."""
    from fps_tpu import obs

    d = str(tmp_path)
    write_snapshot(d, 1, ls=[np.zeros((8, 4), np.float32)])
    sink = obs.MemorySink()
    rec = obs.Recorder(sinks=[sink])
    server, watcher = ReadServer.over(d, recorder=rec)
    server.pull("weights", [0, 1, 2])
    server.topk([0], k=2)
    assert rec.counter_value("serve.requests", op="pull") == 1
    assert rec.counter_value("serve.requests", op="topk") == 1
    assert rec.counter_value("serve.rows") == 5
    assert rec.counter_value("serve.swaps", direction="forward") == 1
    snap = rec.snapshot()
    assert snap["gauges"]["serve.snapshot_step"] == 1.0
    assert snap["gauges"]["serve.snapshot_lag_steps"] == 0.0
    assert snap["gauges"]["serve.write_to_servable_s"] >= 0.0
    assert snap["histograms"]["serve.request_seconds{op=pull}"][
        "count"] == 1


# ---------------------------------------------------------------------------
# TCP transport (framed wire only; the PR-16 legacy dual stack is retired).
# ---------------------------------------------------------------------------

def test_tcp_round_trip_and_error_tolerance(tmp_path):
    d = str(tmp_path)
    ref = write_snapshot(d, 1, ls=[np.random.default_rng(0).normal(
        size=(8, 4)).astype(np.float32)])
    server, _ = ReadServer.over(d)
    with TcpServe(server) as tcp, JsonlClient(tcp.host, tcp.port) as c:
        r = c.request({"op": "pull", "table": "weights", "ids": [0, 1]})
        assert r["ok"] and r["step"] == 1
        np.testing.assert_allclose(np.asarray(r["values"], np.float32),
                                   ref["table::weights"][[0, 1]])
        # The connection survives garbage and bad requests.
        assert not c.request({"op": "nope"})["ok"]
        # ...including valid JSON that is not an object.
        assert not c.request([1, 2, 3])["ok"]
        r = c.request({"op": "pull", "table": "weights", "ids": [0]})
        assert r["ok"]  # same connection still answers
        r = c.request({"op": "pull", "table": "missing", "ids": [0]})
        assert not r["ok"] and "KeyError" in r["error"]
        r = c.request({"op": "stats"})
        assert r["ok"] and r["requests"] >= 1
    # The legacy line-JSON dual stack is RETIRED: a raw line-JSON peer
    # fails the first frame's magic gate and gets a counted OP_ERR +
    # dropped connection — never a silent hang, never a line reply.
    with TcpServe(server) as tcp:
        s = socket.create_connection((tcp.host, tcp.port), timeout=5.0)
        try:
            rf = s.makefile("rb")
            s.sendall(b"this is not json\n")
            fr = wire.read_frame(rf)
            assert fr.op == wire.OP_ERR
            assert not fr.json()["ok"]
            assert rf.read(1) == b""  # connection dropped after OP_ERR
        finally:
            s.close()
        assert tcp.wire_stats()["torn_frames"] == 1


def test_tcp_nonfinite_rows_serialize_as_strict_json(tmp_path):
    # Observe-mode guards publish snapshots that still hold non-finite
    # rows; the wire must stay strict JSON (null, never NaN/Infinity —
    # json.loads accepts the Python-only tokens, so assert on the raw
    # OP_RESP payload BYTES, not a parsed dict). Hand-rolled framed
    # conversation so the assertion sees the wire text.
    d = str(tmp_path)
    w = np.ones((4, 2), np.float32)
    w[1, 0], w[2, 1] = np.nan, np.inf
    write_snapshot(d, 1, tables={"weights": w})
    server, _ = ReadServer.over(d)
    with TcpServe(server) as tcp:
        s = socket.create_connection((tcp.host, tcp.port), timeout=5.0)
        try:
            rf = s.makefile("rb")
            wire.send_frame(s, wire.encode_frame(
                wire.OP_HELLO, 0, json.dumps(
                    {"versions": list(wire.SUPPORTED_VERSIONS),
                     "session": "nonfinite-test"}).encode()), "serve")
            assert wire.read_frame(rf).op == wire.OP_HELLO_OK
            req = {"op": "pull", "table": "weights", "ids": [0, 1, 2]}
            wire.send_frame(s, wire.encode_frame(
                wire.OP_REQ, 1, json.dumps({"q": req}).encode()), "serve")
            fr = wire.read_frame(rf)
        finally:
            s.close()
        assert fr.op == wire.OP_RESP
        raw = fr.payload.decode("utf-8")
        assert "NaN" not in raw and "Infinity" not in raw
        r = json.loads(raw)
        assert r["ok"] and r["values"][1][0] is None
        assert r["values"][2][1] is None
        assert r["values"][0] == [1.0, 1.0]


def test_tcp_no_snapshot_is_retryable():
    server = ReadServer()
    with TcpServe(server) as tcp, JsonlClient(tcp.host, tcp.port) as c:
        r = c.request({"op": "pull", "table": "t", "ids": [0]})
        assert not r["ok"] and r.get("retryable")


# ---------------------------------------------------------------------------
# tools/serve.py: the jax-free CLI.
# ---------------------------------------------------------------------------

def _poisoned_cli(args, tmp_path):
    """Run tools/serve.py in a subprocess with jax UNIMPORTABLE (poisoned
    in sys.modules) — the no-accelerator-runtime serving promise."""
    tool = os.path.join(ROOT, "tools", "serve.py")
    code = (
        "import sys, runpy\n"
        "sys.modules['jax'] = None\n"
        f"sys.argv = ['serve.py'] + {args!r}\n"
        f"runpy.run_path({tool!r}, run_name='__main__')\n"
    )
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          cwd=str(tmp_path))


def test_serve_cli_once_is_jax_free(tmp_path):
    d = str(tmp_path / "ckpt")
    write_snapshot(d, 4)
    with open(fmt.snapshot_path(d, 9), "wb") as f:
        f.write(b"PK\x03\x04junk")  # must be rejected, not served
    proc = _poisoned_cli([d, "--once"], tmp_path)
    assert proc.returncode == 0, proc.stderr
    man = json.loads(proc.stdout)
    assert man["event"] == "manifest" and man["step"] == 4
    assert man["rejected"] == 1
    assert man["tables"]["weights"]["shape"] == [32, 3]

    empty = _poisoned_cli([str(tmp_path / "empty"), "--once"], tmp_path)
    assert empty.returncode == 1
    assert json.loads(empty.stdout)["event"] == "no_snapshot"


def test_serve_cli_tcp_serves_queries(tmp_path):
    d = str(tmp_path / "ckpt")
    write_snapshot(d, 2, ls=[np.zeros((8, 4), np.float32)])
    tool = os.path.join(ROOT, "tools", "serve.py")
    proc = subprocess.Popen(
        [sys.executable, tool, d, "--max-polls", "40", "--poll-s", "0.1"],
        stdout=subprocess.PIPE, text=True, cwd=str(tmp_path))
    try:
        line = json.loads(proc.stdout.readline())
        assert line["event"] == "serving" and line["step"] == 2
        with JsonlClient(line["host"], line["port"]) as c:
            r = c.request({"op": "pull", "table": "weights", "ids": [0]})
            assert r["ok"] and r["step"] == 2
            r = c.request({"op": "topk", "users": [1], "k": 3})
            assert r["ok"] and len(r["items"][0]) == 3
        out, _ = proc.communicate(timeout=60)
        served = json.loads(out.strip().splitlines()[-1])
        assert served["event"] == "served" and served["requests"] == 2
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0


# ---------------------------------------------------------------------------
# Serve-while-train (the chaos scenario, full fidelity — slow tier).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_while_train_scenario(tmp_path):
    """The ISSUE 7 acceptance scenario end to end: a concurrent reader
    over a supervised, SIGKILLed, torn-candidate-injected training run
    never observes a torn, CRC-failing, or rolled-back-past table — and
    a post-run quarantine swaps it backward. One shared implementation
    with tools/chaos_sweep.py (fps_tpu.testing.supervised_demo)."""
    from fps_tpu.testing.supervised_demo import (
        run_serve_while_train_scenario,
    )

    ok, detail = run_serve_while_train_scenario(str(tmp_path))
    assert ok, json.dumps(detail, default=str)
