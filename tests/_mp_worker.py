"""Subprocess body for the multi-process distributed tests.

Usage: python _mp_worker.py <process_id> <num_processes> <port> <out_npz>
                            [scenario]

Initializes multi-controller JAX over a local gloo coordinator and trains
the standard tiny MF workload through the full framework path on a (2, 4)
global mesh. Scenarios:

* ``indexed``  (default) — device-resident ingest, fused indexed epochs,
  synchronous.
* ``host_sync`` — HOST ingest (`fit_stream` over numpy chunks placed via
  ``make_array_from_process_local_data``), synchronous.
* ``host_ssp``  — host ingest, SSP bounded staleness (sync_every=2).
* ``indexed_shard8`` — indexed ingest on a ``(data=1, shard=8)`` mesh, so
  the SHARD axis spans the process boundary: every pull's all_gather /
  psum_scatter, every push's shard-axis all_gather, ``dump_model``'s
  replication, and the checkpoint save's host transfer all move shard ROWS
  between the two OS processes (round-2 verdict: the one untested
  collective topology — every other scenario keeps shards process-local).

Every rank calls `dump_model` (a collective); rank 0 writes the table for
the parent test to compare against a single-process run. The shard8
scenario also checkpoints (every rank — the save's table dump is itself
a collective) and re-reads the snapshot to prove the cross-process
checkpoint path agrees with ``dump_model``.
"""

import sys


def main() -> int:
    pid, nproc, port, out = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    scenario = sys.argv[5] if len(sys.argv) > 5 else "indexed"

    from fps_tpu.parallel.mesh import init_distributed

    init_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )

    import numpy as np

    import jax

    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import multi_epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    if scenario == "indexed_shard8":
        mesh = make_ps_mesh(num_shards=8, num_data=1)
    else:
        mesh = make_ps_mesh(num_shards=4, num_data=2)
    W = num_workers_of(mesh)
    data = synthetic_ratings(57, 31, 2000, seed=0)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)
    sync_every = 2 if scenario == "host_ssp" else None
    trainer, store = online_mf(mesh, cfg, sync_every=sync_every)
    tables, ls = trainer.init_state(jax.random.key(0))

    if scenario in ("indexed", "indexed_shard8"):
        ds = DeviceDataset(mesh, data)
        plan = DeviceEpochPlan(
            ds, num_workers=W, local_batch=32, route_key="user", seed=5
        )
        tables, ls, metrics = trainer.run_indexed(
            tables, ls, plan, jax.random.key(1), epochs=2
        )
        n = sum(float(m["n"].sum()) for m in metrics)
    elif scenario in ("host_sync", "host_ssp"):
        # Host ingest: every process runs the identical deterministic chunk
        # iterator; run_chunk places the numpy leaves onto the global mesh.
        chunks = multi_epoch_chunks(
            data, 2, num_workers=W, local_batch=32, steps_per_chunk=4,
            route_key="user", sync_every=sync_every, seed=5,
        )
        tables, ls, metrics = trainer.fit_stream(
            tables, ls, chunks, jax.random.key(1)
        )
        n = sum(float(np.asarray(m["n"]).sum()) for m in metrics)
    else:
        raise SystemExit(f"unknown scenario {scenario!r}")
    assert n == 2 * 2000, n

    # dump_model replicates cross-host shards through a jitted identity — a
    # COLLECTIVE, so EVERY process must call it (on a topology where the
    # shard axis spans processes, a rank-0-only call deadlocks waiting for
    # the other processes' shards). Rank 0 alone writes the file.
    ids, values = store.dump_model("item_factors")

    if scenario == "indexed_shard8":
        # Cross-process checkpoint: every rank runs the collective table
        # dump inside save (atomic same-path writes race benignly), then
        # the re-read snapshot must agree with dump_model's host view.
        import os

        from fps_tpu.core.checkpoint import Checkpointer

        ck = Checkpointer(os.path.join(os.path.dirname(out), "ck_shard8"),
                          keep=1)
        ck.save(1, store, ls)
        _, snap_tables, _, _ = ck.read_snapshot(1)
        got = snap_tables["item_factors"]  # logical order, padding stripped
        host = store.lookup_host("item_factors", np.arange(31))
        assert np.array_equal(got, host), "checkpoint != dump view"

    if pid == 0:
        np.savez(out, item_factors=values)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
