"""Multi-tenant pods: blast-radius isolation (fps_tpu.tenancy).

Covers the tenancy plane end to end at tier-1 speed:

* TenantSpec / TenantPaths / validate_tenant_name / list_tenants /
  audit_namespaces unit behaviour;
* TenantManager machinery against the jax-free supervised stub
  (tests/_supervised_stub.py): manifests, seeded fences, placeholder
  resolution, env scoping, concurrent runs, and a poisoned tenant
  quarantining without touching its neighbor;
* per-tenant fencing-epoch isolation, plus property-style interleaving
  tests showing that serve-plane StepFence advances/rollbacks and pod
  fencing epochs never order across tenant namespaces;
* replica-budget arbitration (plan_tenants / arbitrate_replica_budget):
  under-demanders kept whole, weighted water-filling, noisy-neighbor
  knob isolation;
* the obs/fleet.py tenant rollup path: mirrored path constants,
  discover_tenants, apply_slo_overrides, tenant_fleet_digest.

The heavier proof — four chaos scenarios where the non-injected tenant
finishes bit-identical to its solo run — lives in
fps_tpu/testing/tenant_demo.py and runs under tools/chaos_sweep.py.
"""

from __future__ import annotations

import json
import os
import random
import sys

import pytest

from fps_tpu.obs import fleet as obs_fleet
from fps_tpu.serve.fleet import StepFence
from fps_tpu.supervise import supervisor as sup
from fps_tpu.supervise.supervisor import SupervisorConfig
from fps_tpu.tenancy import (
    CKPT_DIRNAME,
    MANIFEST_FILENAME,
    MANIFEST_SCHEMA_VERSION,
    OBS_DIRNAME,
    STATE_DIRNAME,
    TENANT_ENV,
    TENANTS_DIRNAME,
    TenantManager,
    TenantPaths,
    TenantSpec,
    audit_namespaces,
    list_tenants,
    tenants_root,
    validate_tenant_name,
)
from fps_tpu.tiering.planner import (
    TableDensity,
    arbitrate_replica_budget,
    plan_tables,
    plan_tenants,
)

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_STUB = os.path.join(_ROOT, "tests", "_supervised_stub.py")

_FAST = dict(stall_timeout_s=30.0, startup_grace_s=60.0,
             poll_interval_s=0.02, backoff_base_s=0.05, backoff_max_s=0.2,
             term_grace_s=1.0)


def _stub_spec(name, *extra, **kw):
    """A TenantSpec running the supervised stub inside its own ckpt
    namespace ({ckpt} doubles as the stub's --dir: heartbeats, fence
    checks, snapshots and result.json all land there)."""
    cmd = (sys.executable, _STUB, "--dir", "{ckpt}",
           "--chunks", "6", "--chunk-s", "0.02", *extra)
    return TenantSpec(name=name, cmd=cmd, **kw)


# ---------------------------------------------------------------------------
# TenantSpec validation


def test_spec_rejects_empty_cmd():
    with pytest.raises(ValueError):
        TenantSpec(name="a", cmd=())


def test_spec_rejects_nonpositive_weight():
    for w in (0, -1, -0.5):
        with pytest.raises(ValueError):
            TenantSpec(name="a", cmd=("true",), weight=w)


def test_spec_rejects_illegal_name():
    for name in ("", "Caps", "has space", "../escape", "a/b", "a" * 65):
        with pytest.raises(ValueError):
            TenantSpec(name=name, cmd=("true",))


def test_spec_coerces_sequences_to_tuples():
    spec = TenantSpec(name="a", cmd=["x", "y"], watch=["w"])
    assert spec.cmd == ("x", "y")
    assert spec.watch == ("w",)


# ---------------------------------------------------------------------------
# TenantPaths / validate_tenant_name / list_tenants


def test_tenant_paths_layout(tmp_path):
    root = str(tmp_path)
    tp = TenantPaths(root, "m1")
    assert tp.tenant_dir == os.path.join(tenants_root(root), "m1")
    assert tp.manifest_path == os.path.join(tp.tenant_dir, MANIFEST_FILENAME)
    assert tp.ckpt_dir == os.path.join(tp.tenant_dir, CKPT_DIRNAME)
    assert tp.obs_dir == os.path.join(tp.tenant_dir, OBS_DIRNAME)
    assert tp.state_dir == os.path.join(tp.tenant_dir, STATE_DIRNAME)
    assert not os.path.isdir(tp.tenant_dir)
    tp.ensure()
    tp.ensure()  # idempotent
    for d in (tp.ckpt_dir, tp.obs_dir, tp.state_dir):
        assert os.path.isdir(d)


def test_tenant_paths_owns(tmp_path):
    root = str(tmp_path)
    a, b = TenantPaths(root, "a"), TenantPaths(root, "b")
    assert a.owns(os.path.join(a.ckpt_dir, "snap.npz"))
    assert a.owns(a.manifest_path)
    assert not a.owns(os.path.join(b.state_dir, "journal.jsonl"))
    assert not a.owns(os.path.join(root, "loose.txt"))
    # Prefix tricks must not leak across namespaces.
    assert not a.owns(os.path.join(tenants_root(root), "a-evil", "x"))


def test_validate_tenant_name():
    assert validate_tenant_name("ok-name_9") == "ok-name_9"
    for bad in ("", "Caps", "..", "a/b", "-lead", "a" * 65):
        with pytest.raises(ValueError):
            validate_tenant_name(bad)


def test_list_tenants(tmp_path):
    root = str(tmp_path)
    assert list_tenants(root) == []
    for name in ("beta", "alpha"):
        TenantPaths(root, name).ensure()
    # Non-tenant clutter under tenants/ is ignored.
    os.makedirs(os.path.join(tenants_root(root), "NOT-A-TENANT!"),
                exist_ok=True)
    assert list_tenants(root) == ["alpha", "beta"]


# ---------------------------------------------------------------------------
# audit_namespaces


def test_audit_clean(tmp_path):
    root = str(tmp_path)
    for name in ("a", "b"):
        tp = TenantPaths(root, name).ensure()
        with open(os.path.join(tp.ckpt_dir, "snap.npz"), "w") as f:
            f.write("x")
    audit = audit_namespaces(root, ["a", "b"])
    assert audit["clean"] is True
    assert audit["violations"] == []
    assert audit["per_tenant"]["a"] >= 1
    assert audit["per_tenant"]["b"] >= 1


def test_audit_flags_cross_namespace_files(tmp_path):
    root = str(tmp_path)
    TenantPaths(root, "a").ensure()
    # 1) a file owned by no tenant at the root,
    with open(os.path.join(root, "loose.txt"), "w") as f:
        f.write("x")
    # 2) a file directly under tenants/ (between namespaces),
    with open(os.path.join(tenants_root(root), "stray.json"), "w") as f:
        f.write("{}")
    # 3) a whole namespace nobody declared.
    tp_ghost = TenantPaths(root, "ghost").ensure()
    with open(os.path.join(tp_ghost.ckpt_dir, "snap.npz"), "w") as f:
        f.write("x")
    audit = audit_namespaces(root, ["a"])
    assert audit["clean"] is False
    assert len(audit["violations"]) == 3


# ---------------------------------------------------------------------------
# TenantManager machinery (stub children)


def test_manager_rejects_duplicate_names(tmp_path):
    specs = [_stub_spec("a"), _stub_spec("a")]
    with pytest.raises(ValueError):
        TenantManager(str(tmp_path), specs)


def test_manager_prepare_manifests_and_fences(tmp_path):
    root = str(tmp_path)
    mgr = TenantManager(root, [
        _stub_spec("a", weight=2.0, seed=7, slo={"x": {"target": 0.5}}),
        _stub_spec("b"),
    ])
    mgr.prepare()
    with open(mgr.paths["a"].manifest_path, encoding="utf-8") as f:
        manifest = json.load(f)
    assert manifest["schema"] == MANIFEST_SCHEMA_VERSION
    assert manifest["name"] == "a"
    assert manifest["weight"] == 2.0
    assert manifest["seed"] == 7
    assert manifest["slo"] == {"x": {"target": 0.5}}
    assert mgr.fence_epoch("a") == 1
    assert mgr.fence_epoch("b") == 1
    # prepare() is idempotent and never regresses a fence.
    mgr.bump_fence("a")
    mgr.prepare()
    assert mgr.fence_epoch("a") == 2


def test_manager_resolves_placeholders_and_scopes_env(tmp_path):
    root = str(tmp_path)
    spec_a = TenantSpec(
        name="a",
        cmd=("prog", "{ckpt}", "{obs}", "{state}", "{out}", "{name}",
             "{root}"),
        env={"ONLY_A": "1"}, watch=("{state}/w.json",))
    spec_b = TenantSpec(name="b", cmd=("prog",))
    mgr = TenantManager(root, [spec_a, spec_b],
                        base_env={"SHARED": "yes"})
    mgr.prepare()
    sa, sb = mgr.supervisor("a"), mgr.supervisor("b")
    tp = mgr.paths["a"]
    assert sa.cmd == ["prog", tp.ckpt_dir, tp.obs_dir, tp.state_dir,
                      tp.out_path, "a", tp.root]
    assert sa.state_dir == tp.state_dir
    assert sa.env[TENANT_ENV] == "a"
    assert sb.env[TENANT_ENV] == "b"
    assert sa.env["SHARED"] == sb.env["SHARED"] == "yes"
    # Per-spec env never leaks into a neighbor's child.
    assert sa.env["ONLY_A"] == "1"
    assert "ONLY_A" not in sb.env
    # Watch paths resolve into the tenant's own namespace.
    assert list(sa.watch) == [os.path.join(tp.state_dir, "w.json")]


def test_manager_runs_tenants_concurrently(tmp_path):
    root = str(tmp_path)
    mgr = TenantManager(
        root, [_stub_spec("a"), _stub_spec("b")],
        config=SupervisorConfig(max_restarts=1, **_FAST))
    digests = mgr.run()
    assert sorted(digests) == ["a", "b"]
    for name in ("a", "b"):
        assert digests[name]["success"] is True
        assert digests[name]["restarts"] == 0
        result = os.path.join(mgr.paths[name].ckpt_dir, "result.json")
        with open(result, encoding="utf-8") as f:
            assert json.load(f)["done"] == 6
        assert os.path.isfile(mgr.journal_path(name))
    audit = audit_namespaces(root, ["a", "b"])
    assert audit["clean"] is True, audit["violations"]


def test_manager_poison_quarantined_neighbor_untouched(tmp_path):
    """Tier-1 version of the tenant_poison_isolation chaos scenario:
    tenant a crashes at chunk 3 until quarantined; b must finish with
    zero restarts and a clean shared namespace."""
    root = str(tmp_path)
    mgr = TenantManager(
        root, [_stub_spec("a", "--crash-at", "3"), _stub_spec("b")],
        config=SupervisorConfig(max_restarts=3, quarantine_after=2,
                                **_FAST))
    digests = mgr.run()
    assert digests["a"]["success"] is True
    assert digests["a"]["restarts"] == 2
    assert digests["a"]["quarantined"] == [3]
    assert digests["b"]["success"] is True
    assert digests["b"]["restarts"] == 0
    # b's journal shows no recovery events — the blast never reached it.
    assert sup.recovery_times(mgr.journal_path("b")) == []
    assert audit_namespaces(root, ["a", "b"])["clean"] is True


# ---------------------------------------------------------------------------
# Fencing-epoch isolation + property-style interleavings


def test_bump_fence_isolated(tmp_path):
    mgr = TenantManager(str(tmp_path),
                        [_stub_spec("a"), _stub_spec("b")])
    mgr.prepare()
    assert mgr.bump_fence("a") == 2
    assert mgr.bump_fence("a") == 3
    assert mgr.fence_epoch("b") == 1


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pod_fence_epochs_never_order_across_tenants(tmp_path, seed):
    """Property: an arbitrary interleaving of bump_fence calls across
    tenants leaves each tenant's epoch equal to 1 + its OWN bump count
    — neighbors' bumps are invisible to it."""
    names = ["a", "b", "c"]
    mgr = TenantManager(str(tmp_path), [_stub_spec(n) for n in names])
    mgr.prepare()
    rng = random.Random(seed)
    bumps = {n: 0 for n in names}
    for _ in range(30):
        n = rng.choice(names)
        got = mgr.bump_fence(n)
        bumps[n] += 1
        assert got == 1 + bumps[n]
    for n in names:
        assert mgr.fence_epoch(n) == 1 + bumps[n]


def _fence_ops(rng, n_ops):
    """A random but replayable StepFence op sequence: mostly forward
    advances, occasional epoch-bumping rollbacks."""
    ops, step = [], 0
    for _ in range(n_ops):
        if step > 0 and rng.random() < 0.3:
            step = rng.randrange(step)
            ops.append(("rollback", step))
        else:
            step += rng.randrange(1, 4)
            ops.append(("advance", step))
    return ops


def _apply_fence_op(fence, op, step):
    if op == "advance":
        fence.ready(step)
        return fence.advance(quorum=1, max_step=step)
    return fence.rollback(step)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_step_fence_trajectories_independent_across_tenants(tmp_path,
                                                            seed):
    """Property: interleaving serve-fence advances and rollbacks across
    tenant namespaces produces, for every tenant, the exact (epoch,
    step) trajectory of a solo replay of only ITS ops — fences never
    order across namespaces."""
    root = os.path.join(str(tmp_path), "shared")
    names = ["a", "b", "c"]
    ops = {n: _fence_ops(random.Random(seed * 101 + i), 12)
           for i, n in enumerate(names)}
    # Interleaved arm: one fence per tenant, ops merged in a random
    # global order that preserves each tenant's own op order.
    deck = [n for n in names for _ in ops[n]]
    random.Random(seed).shuffle(deck)
    fences = {n: StepFence(TenantPaths(root, n).ensure().ckpt_dir,
                           reader_id="r0") for n in names}
    cursor = {n: 0 for n in names}
    interleaved = {n: [] for n in names}
    for n in deck:
        op, step = ops[n][cursor[n]]
        cursor[n] += 1
        interleaved[n].append(_apply_fence_op(fences[n], op, step))
    # Solo arm: each tenant's ops replayed alone in a fresh root.
    for n in names:
        solo_dir = os.path.join(str(tmp_path), f"solo_{n}")
        solo = StepFence(solo_dir, reader_id="r0")
        solo_traj = [_apply_fence_op(solo, op, step)
                     for op, step in ops[n]]
        assert interleaved[n] == solo_traj, (
            f"tenant {n!r} fence trajectory diverged under interleaving")
    # The shared root stays cleanly partitioned.
    assert audit_namespaces(root, names)["clean"] is True


# ---------------------------------------------------------------------------
# Replica-budget arbitration


def test_arbitrate_under_demander_kept_whole():
    granted = arbitrate_replica_budget({"a": 10, "b": 1000}, 100)
    assert granted == {"a": 10, "b": 90}


def test_arbitrate_weighted_split_when_all_hungry():
    granted = arbitrate_replica_budget({"a": 1000, "b": 1000}, 90,
                                       weights={"a": 2.0, "b": 1.0})
    assert granted == {"a": 60, "b": 30}


def test_arbitrate_largest_remainder_deterministic():
    granted = arbitrate_replica_budget({"a": 100, "b": 100}, 101)
    assert granted == {"a": 51, "b": 50}


def test_arbitrate_work_conserving_and_bounded():
    demands = {"a": 7, "b": 0, "c": 400, "d": 55}
    total = 300
    granted = arbitrate_replica_budget(demands, total)
    assert sum(granted.values()) == min(total, sum(demands.values()))
    for n in demands:
        assert 0 <= granted[n] <= demands[n]
    assert granted["b"] == 0


def test_arbitrate_rejects_bad_inputs():
    with pytest.raises(ValueError):
        arbitrate_replica_budget({"a": 1}, -1)
    with pytest.raises(ValueError):
        arbitrate_replica_budget({"a": 1}, 10, weights={"a": 0})


def test_plan_tenants_noisy_neighbor_knob_isolation():
    """The arbitration leg of the tenant_noisy_neighbor chaos scenario,
    pinned as a unit test: a flat-density tenant demanding the whole
    budget cannot move a concentrated neighbor's knobs off its solo
    plan; only the noisy tenant's own hot tier shrinks."""
    nf, dim = 4096, 4
    dens_a = [TableDensity("weights", nf, dim, np.full(nf, 5.0))]
    counts_b = np.zeros(nf)
    counts_b[:64] = 1000.0
    dens_b = [TableDensity("weights", nf, dim, counts_b)]
    total = 48 * 1024
    plan_kw = dict(batch_rows_per_step=256, dense_table_bytes=1024)

    res = plan_tenants({"a": dens_a, "b": dens_b},
                       weights={"a": 1.0, "b": 1.0},
                       total_replica_budget_bytes=total, **plan_kw)
    solo_a = plan_tables(dens_a, replica_budget_bytes=total,
                         **plan_kw)["weights"]
    solo_b = plan_tables(dens_b, replica_budget_bytes=total,
                         **plan_kw)["weights"]

    # b under-demands its fair share: granted in full, knobs identical
    # to running solo on the whole budget.
    assert res["b"]["granted"] == res["b"]["demand"]
    assert res["b"]["plans"]["weights"].knobs() == solo_b.knobs()
    # a absorbs the entire shortfall: granted strictly less than its
    # demand, hot tier squeezed below solo but still serving.
    assert res["a"]["granted"] < res["a"]["demand"]
    assert res["a"]["granted"] == total - res["b"]["granted"]
    shared_hot = res["a"]["plans"]["weights"].hot_tier
    assert 0 < shared_hot < solo_a.hot_tier
    # Invariants the docstring promises.
    assert res["a"]["granted"] + res["b"]["granted"] <= total


# ---------------------------------------------------------------------------
# obs/fleet.py tenant rollups (stdlib mirror of the tenancy layout)


def test_fleet_constants_mirror_tenancy_paths():
    """fps_tpu/obs/fleet.py is loaded by file path on jax-free login
    nodes, so it re-declares the tenancy layout constants; this pin is
    the test its comment promises."""
    assert obs_fleet.TENANTS_DIRNAME == TENANTS_DIRNAME
    assert obs_fleet.TENANT_MANIFEST_FILENAME == MANIFEST_FILENAME
    assert obs_fleet.TENANT_OBS_DIRNAME == OBS_DIRNAME
    assert obs_fleet.TENANT_STATE_DIRNAME == STATE_DIRNAME
    assert obs_fleet.SUPERVISOR_JOURNAL_FILENAME == sup.JOURNAL_FILENAME


def _write_manifest(tp, manifest):
    with open(tp.manifest_path, "w", encoding="utf-8") as f:
        json.dump(manifest, f)


def test_discover_tenants(tmp_path):
    root = str(tmp_path)
    assert obs_fleet.discover_tenants(root) == {}
    tp_a = TenantPaths(root, "a").ensure()
    _write_manifest(tp_a, {"name": "a", "weight": 2.0})
    tp_b = TenantPaths(root, "b").ensure()
    with open(tp_b.manifest_path, "w", encoding="utf-8") as f:
        f.write('{"torn')  # torn manifest: tenant still reports
    TenantPaths(root, "c").ensure()  # no manifest at all: skipped
    found = obs_fleet.discover_tenants(root)
    assert sorted(found) == ["a", "b"]
    assert found["a"]["manifest"]["weight"] == 2.0
    assert found["a"]["obs_dir"] == tp_a.obs_dir
    assert found["a"]["state_dir"] == tp_a.state_dir
    assert found["b"]["manifest"] == {}


def test_apply_slo_overrides():
    slos = obs_fleet.DEFAULT_SLOS
    name = slos[0].name
    out = obs_fleet.apply_slo_overrides(slos, {name: {"target": 123.5}})
    assert out[0].target == 123.5
    assert out[0].objective == slos[0].objective
    assert out[1:] == tuple(slos[1:])
    # Unknown names and malformed values keep the defaults.
    assert obs_fleet.apply_slo_overrides(slos, {"nope": {"target": 1}}) \
        == tuple(slos)
    out = obs_fleet.apply_slo_overrides(slos, {name: {"target": "zzz"}})
    assert out[0].target == slos[0].target
    assert obs_fleet.apply_slo_overrides(slos, None) == tuple(slos)


def test_tenant_fleet_digest(tmp_path):
    root = str(tmp_path)
    slo_name = obs_fleet.DEFAULT_SLOS[0].name
    tp = TenantPaths(root, "a").ensure()
    _write_manifest(tp, {"name": "a", "weight": 2.5,
                         "slo": {slo_name: {"target": 9.0}}})
    # A minimal supervisor journal: attempt 1 died at t=10, attempt 2
    # first signaled at t=11.5 -> one recovery of 1.5s.
    journal = os.path.join(tp.state_dir, sup.JOURNAL_FILENAME)
    with open(journal, "w", encoding="utf-8") as f:
        for rec in ({"kind": "event", "event": "attempt_end",
                     "attempt": 1, "t": 10.0},
                    {"kind": "event", "event": "attempt_first_signal",
                     "attempt": 2, "t": 11.5}):
            f.write(json.dumps(rec) + "\n")
    TenantPaths(root, "b").ensure()
    _write_manifest(TenantPaths(root, "b"), {"name": "b"})

    digest = obs_fleet.tenant_fleet_digest(root)
    assert sorted(digest["tenants"]) == ["a", "b"]
    a = digest["tenants"]["a"]
    assert a["weight"] == 2.5
    assert a["slo_overrides"] == [slo_name]
    assert a["recovery"]["count"] == 1
    assert a["recovery"]["times_s"] == [1.5]
    assert a["recovery"]["max_s"] == 1.5
    # The per-tenant SLO override reached the burn evaluation.
    assert a["slo"][slo_name]["target"] == 9.0
    b = digest["tenants"]["b"]
    assert b["weight"] == 1.0
    assert b["recovery"]["count"] == 0
