"""Overlapped host pipeline (fps_tpu.core.prefetch + driver wiring).

The contracts under test, per docs/performance.md:

* prefetch on/off is BIT-identical — tables, metrics, and the compiled
  program (the pipeline is pure host plumbing);
* lag-by-one health sync (TrainerConfig.health_lag) is bit-identical to
  the immediate sync, including under quarantine (the poisoned chunk's
  successor is deterministically recomputed);
* worker-thread errors re-raise on the caller at the position they
  occurred, and EVERY exit path of fit_stream joins the worker thread
  (no leaks);
* overlapped boundary checkpoints hold the same state the inline saves
  would, and resume from them bit-identically.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np
import pytest

import jax

from fps_tpu.core.checkpoint import AsyncCheckpointer, Checkpointer
from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.ingest import multi_epoch_chunks
from fps_tpu.core.prefetch import ChunkPrefetcher, PlacedChunk
from fps_tpu.core.resilience import RollbackPolicy
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.testing import chaos
from fps_tpu.testing.workloads import (
    NF,
    logreg_chunks,
    logreg_data,
    weights,
)


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _no_prefetch_threads():
    return not any(
        t.name.startswith("fps-prefetch") for t in threading.enumerate()
    )


def _make_trainer(mesh, **cfg_over):
    trainer, store = logistic_regression(
        mesh, LogRegConfig(num_features=NF, learning_rate=0.5),
        guard=cfg_over.pop("guard", None),
        sync_every=cfg_over.pop("sync_every", None),
    )
    if cfg_over:
        trainer.config = dataclasses.replace(trainer.config, **cfg_over)
    return trainer, store


# ---------------------------------------------------------------------------
# ChunkPrefetcher unit contracts (no mesh needed).
# ---------------------------------------------------------------------------

def test_prefetcher_preserves_order_and_completes():
    items = [{"x": np.full(4, i)} for i in range(17)]
    pf = ChunkPrefetcher(iter(items), depth=3)
    got = list(pf)
    pf.close()
    assert len(got) == 17
    for i, c in enumerate(got):
        assert c["x"][0] == i
    assert _no_prefetch_threads()


def test_prefetcher_place_fn_wraps_and_runs_on_worker():
    worker_names = []

    def place(chunk):
        worker_names.append(threading.current_thread().name)
        return {k: v + 1 for k, v in chunk.items()}

    with ChunkPrefetcher(iter([{"x": np.arange(3)}] * 4), place,
                         depth=2) as pf:
        got = list(pf)
    assert all(isinstance(c, PlacedChunk) for c in got)
    assert np.array_equal(got[0].batches["x"], np.arange(3) + 1)
    assert set(worker_names) == {"fps-prefetch"}
    assert _no_prefetch_threads()


def test_prefetcher_error_propagates_at_position():
    def source():
        yield {"x": 0}
        yield {"x": 1}
        raise ValueError("poisoned source")

    pf = ChunkPrefetcher(source(), depth=2)
    assert next(pf)["x"] == 0
    assert next(pf)["x"] == 1
    with pytest.raises(ValueError, match="poisoned source"):
        next(pf)
    pf.close()
    assert _no_prefetch_threads()


def test_prefetcher_close_midstream_joins_thread():
    def endless():
        i = 0
        while True:
            yield {"x": i}
            i += 1

    pf = ChunkPrefetcher(endless(), depth=2)
    assert next(pf)["x"] == 0
    pf.close()
    assert _no_prefetch_threads()
    # Closed pipeline: close() is idempotent.
    pf.close()


def test_prefetcher_rejects_bad_depth():
    with pytest.raises(ValueError, match="depth"):
        ChunkPrefetcher(iter([]), depth=0)


def test_prefetcher_depth_bounds_queue():
    from fps_tpu import obs

    rec = obs.Recorder(sinks=[])
    # A consumer that never reads: the worker must stall at depth, not
    # drain the source.
    src = iter([{"x": i} for i in range(100)])
    pf = ChunkPrefetcher(src, depth=2, recorder=rec)
    deadline = time.time() + 5.0
    while (rec.snapshot()["gauges"].get("prefetch.queue_depth", 0) < 2
           and time.time() < deadline):
        time.sleep(0.01)
    pf.close()
    snap = rec.snapshot()
    assert snap["gauges"]["prefetch.queue_depth"] == 2
    # depth chunks buffered + at most one in flight when close() hit.
    assert snap["counters"]["prefetch.chunks"] <= 3
    assert _no_prefetch_threads()


# ---------------------------------------------------------------------------
# fit_stream integration: bit-identity.
# ---------------------------------------------------------------------------

def test_fit_stream_prefetch_bit_identical_sync(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)

    results = {}
    for pf in (0, 2):
        trainer, store = _make_trainer(mesh, prefetch=pf)
        tables, ls = trainer.init_state(jax.random.key(0))
        tables, ls, m = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1)
        )
        results[pf] = (weights(store), m)
        # The pipeline never adds a compiled program: one cache entry.
        assert len(trainer._compiled) == 1
    assert np.array_equal(results[0][0], results[2][0])
    assert _tree_equal(results[0][1], results[2][1])
    assert _no_prefetch_threads()


def test_fit_stream_prefetch_bit_identical_ssp(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = list(multi_epoch_chunks(
        train, 2, num_workers=num_workers_of(mesh), local_batch=32,
        steps_per_chunk=8, sync_every=4, seed=3,
    ))
    results = {}
    for pf in (0, 3):
        trainer, store = _make_trainer(mesh, sync_every=4, prefetch=pf)
        tables, ls = trainer.init_state(jax.random.key(0))
        tables, ls, m = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1)
        )
        results[pf] = (weights(store), m)
    assert np.array_equal(results[0][0], results[3][0])
    assert _tree_equal(results[0][1], results[3][1])


def test_compiled_hlo_unchanged_by_pipeline(devices8):
    """The pipeline is host plumbing: the lowered program text must be
    byte-identical whatever the prefetch/health_lag knobs say."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunk = logreg_chunks(train, num_workers_of(mesh), epochs=1)[0]

    def lowered(**cfg_over):
        trainer, _ = _make_trainer(mesh, **cfg_over)
        tables, ls = trainer.init_state(jax.random.key(0))
        batches = trainer._place_chunk(chunk, "sync")
        key = jax.random.key(1)
        return trainer._get_compiled("sync").lower(
            tables, ls, batches, key
        ).as_text()

    base = lowered()
    assert lowered(prefetch=2) == base
    assert lowered(prefetch=2, health_lag=1, metrics_drain_every=0) == base


# ---------------------------------------------------------------------------
# fit_stream integration: exits join the worker.
# ---------------------------------------------------------------------------

def test_on_chunk_raise_joins_prefetch_thread(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    trainer, _ = _make_trainer(mesh, prefetch=2)
    tables, ls = trainer.init_state(jax.random.key(0))
    baseline_threads = threading.active_count()

    def boom(i, metrics):
        if i == 1:
            raise RuntimeError("early stop")

    with pytest.raises(RuntimeError, match="early stop"):
        trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                           on_chunk=boom)
    assert _no_prefetch_threads()
    assert threading.active_count() <= baseline_threads


def test_raising_iterator_propagates_through_fit_stream(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)

    def source():
        yield chunks[0]
        yield chunks[1]
        raise OSError("stream tore")

    trainer, _ = _make_trainer(mesh, prefetch=2)
    tables, ls = trainer.init_state(jax.random.key(0))
    with pytest.raises(OSError, match="stream tore"):
        trainer.fit_stream(tables, ls, source(), jax.random.key(1))
    assert _no_prefetch_threads()


def test_health_abort_joins_prefetch_thread(devices8):
    from fps_tpu.core.resilience import PoisonedStreamError

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    poisoned = list(chaos.poison_chunks(
        iter(chunks), chunk_index=1, column="feat_vals", kind="nan",
        frac=0.5, seed=1))
    trainer, _ = _make_trainer(mesh, guard="observe", prefetch=2)
    tables, ls = trainer.init_state(jax.random.key(0))
    with pytest.raises(PoisonedStreamError):
        trainer.fit_stream(
            tables, ls, iter(poisoned), jax.random.key(1),
            rollback=RollbackPolicy(max_rollbacks=0),
        )
    assert _no_prefetch_threads()


# ---------------------------------------------------------------------------
# Lag-by-one health sync.
# ---------------------------------------------------------------------------

def _run_guarded(mesh, chunks, *, lag, prefetch=0, guard="observe",
                 rollback=None, checkpointer=None, checkpoint_every=0):
    trainer, store = _make_trainer(
        mesh, guard=guard, health_lag=lag, prefetch=prefetch)
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, m = trainer.fit_stream(
        tables, ls, iter(chunks), jax.random.key(1), rollback=rollback,
        checkpointer=checkpointer, checkpoint_every=checkpoint_every,
    )
    return store, m


def test_health_lag_bit_identical_clean_stream(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    s0, m0 = _run_guarded(mesh, chunks, lag=0, rollback=RollbackPolicy())
    s1, m1 = _run_guarded(mesh, chunks, lag=1, rollback=RollbackPolicy())
    assert np.array_equal(weights(s0), weights(s1))
    assert _tree_equal(m0, m1)


def test_health_lag_quarantine_recompute_identical(devices8):
    """A quarantined chunk under lag restores the pre-chunk snapshot and
    deterministically recomputes its successor — results must match the
    immediate-sync path bit for bit, with the same quarantine record."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    poisoned = list(chaos.poison_chunks(
        iter(chunks), chunk_index=1, column="feat_vals", kind="nan",
        frac=0.5, seed=1))

    runs = {}
    for name, (lag, pf) in {"lag0": (0, 0), "lag1": (1, 0),
                            "lag1_pf": (1, 2)}.items():
        pol = RollbackPolicy()
        store, m = _run_guarded(mesh, poisoned, lag=lag, prefetch=pf,
                                rollback=pol)
        runs[name] = (weights(store), m, pol.quarantined)

    w0, m0, q0 = runs["lag0"]
    assert q0 == [1]
    for name in ("lag1", "lag1_pf"):
        w, m, q = runs[name]
        assert q == [1], name
        assert np.array_equal(w0, w), name
        assert _tree_equal(m0, m), name
    assert _no_prefetch_threads()


# ---------------------------------------------------------------------------
# Overlapped boundary checkpoints.
# ---------------------------------------------------------------------------

def test_overlapped_checkpoint_snapshots_identical(tmp_path, devices8):
    """With the pipeline on, boundary saves dump from on-device boundary
    copies AFTER the next dispatch — the snapshots must still hold
    exactly the state the inline saves would have written."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)

    dirs = {}
    for name, pf in (("off", 0), ("on", 2)):
        d = tmp_path / name
        trainer, store = _make_trainer(mesh, prefetch=pf)
        tables, ls = trainer.init_state(jax.random.key(0))
        with AsyncCheckpointer(str(d)) as ckpt:
            trainer.fit_stream(
                tables, ls, iter(chunks), jax.random.key(1),
                checkpointer=ckpt, checkpoint_every=2,
            )
        dirs[name] = d

    off, on = Checkpointer(str(dirs["off"])), Checkpointer(str(dirs["on"]))
    assert off.steps() == on.steps() and off.steps()
    for step in off.steps():
        _, t_off, ls_off, _ = off.read_snapshot(step)
        _, t_on, ls_on, _ = on.read_snapshot(step)
        assert sorted(t_off) == sorted(t_on)
        for k in t_off:
            assert np.array_equal(t_off[k], t_on[k]), (step, k)
        assert len(ls_off) == len(ls_on)
        for a, b in zip(ls_off, ls_on):
            assert np.array_equal(a, b), step


def test_resume_from_overlapped_snapshot_bit_identical(tmp_path, devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)

    # Straight pipeline-on run.
    trainer, store = _make_trainer(mesh, prefetch=2)
    tables, ls = trainer.init_state(jax.random.key(0))
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1))
    want = weights(store)

    # Interrupted run: checkpoint every chunk, stop after chunk 1, resume.
    d = str(tmp_path / "ck")
    trainer, store = _make_trainer(mesh, prefetch=2)
    tables, ls = trainer.init_state(jax.random.key(0))

    class Stop(Exception):
        pass

    def stop_at(i, _m):
        if i == 1:
            raise Stop

    with Checkpointer(d) as ckpt:
        with pytest.raises(Stop):
            trainer.fit_stream(
                tables, ls, iter(chunks), jax.random.key(1),
                checkpointer=ckpt, checkpoint_every=1, on_chunk=stop_at,
            )
        start = ckpt.latest_valid_step()
        assert start and start >= 1
        tables, ls, start = trainer.restore_checkpoint(ckpt, ls)
        trainer.fit_stream(
            tables, ls, iter(chunks[start:]), jax.random.key(1),
            start_step=start,
        )
    assert np.array_equal(weights(store), want)
    assert _no_prefetch_threads()


# ---------------------------------------------------------------------------
# Satellites: metrics_drain_every knob, heartbeat sub-phase beats.
# ---------------------------------------------------------------------------

def test_metrics_drain_every_knob(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    results = []
    for de in (8, 2, 0):  # default cadence, tight cadence, never
        trainer, store = _make_trainer(mesh, metrics_drain_every=de)
        tables, ls = trainer.init_state(jax.random.key(0))
        _, _, m = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1)
        )
        # End-of-stream conversion happens regardless of cadence.
        assert all(isinstance(leaf, np.ndarray) for leaf in jax.tree.leaves(m))
        results.append((weights(store), m))
    for w, m in results[1:]:
        assert np.array_equal(results[0][0], w)
        assert _tree_equal(results[0][1], m)


def test_heartbeat_subphase_beats(tmp_path, devices8):
    """With a supervised heartbeat riding the recorder, the driver beats
    at sub-chunk boundaries with a phase field the supervisor parses."""
    import json

    from fps_tpu import obs
    from fps_tpu.supervise import child

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)
    hb_path = str(tmp_path / "hb.json")
    phases_seen = set()

    real_beat = child.Heartbeat.beat

    class SpyHeartbeat(child.Heartbeat):
        def beat(self, index=None, **fields):
            if "phase" in fields:
                phases_seen.add(fields["phase"])
            real_beat(self, index, **fields)

    hb = SpyHeartbeat(hb_path)
    rec = obs.Recorder(sinks=[child.HeartbeatSink(hb)])
    trainer, _ = _make_trainer(mesh, prefetch=2)
    trainer.recorder = rec
    tables, ls = trainer.init_state(jax.random.key(0))
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1))

    assert "dispatch" in phases_seen
    assert "prefetch" in phases_seen  # pipeline on: the wait boundary
    with open(hb_path, encoding="utf-8") as f:
        last = json.load(f)
    assert "index" in last and "phase" in last

    # The supervisor's reader surfaces the phase alongside the index.
    from fps_tpu.supervise.supervisor import RunSupervisor

    sup = RunSupervisor.__new__(RunSupervisor)
    sup.heartbeat_path = hb_path
    sup.host = None  # un-pinned: accept any host (schema hardening)
    sup._rejected_beats = set()
    mtime, idx, phase = sup._read_heartbeat()
    assert mtime is not None and idx is not None
    assert phase in phases_seen


def test_prefetch_queue_gauge_recorded(devices8):
    from fps_tpu import obs

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)
    rec = obs.Recorder(sinks=[])
    trainer, _ = _make_trainer(mesh, prefetch=2)
    trainer.recorder = rec
    tables, ls = trainer.init_state(jax.random.key(0))
    trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1))
    snap = rec.snapshot()
    assert snap["counters"]["prefetch.chunks"] == len(chunks)
    assert "prefetch.queue_depth" in snap["gauges"]
    assert "prefetch" in rec.phase_totals()


# ---------------------------------------------------------------------------
# Supervised chaos: SIGKILL mid-prefetch (subprocess-heavy -> slow tier).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sigkill_mid_prefetch_resumes_clean(tmp_path):
    from fps_tpu.testing.supervised_demo import run_prefetch_kill_scenario

    ok, detail = run_prefetch_kill_scenario(str(tmp_path))
    assert ok, detail


# ---------------------------------------------------------------------------
# Adaptive depth (max_depth): stall-driven raises, memory veto.
# ---------------------------------------------------------------------------

def test_prefetcher_adaptive_depth_raises_on_stalls():
    """A slow source against a fast consumer stalls the queue empty
    every window — depth climbs one chunk per window up to max_depth,
    each raise counted on prefetch.depth_adjustments."""
    from fps_tpu import obs

    def slow_src():
        for i in range(40):
            time.sleep(0.002)
            yield {"x": np.full(4, i)}

    rec = obs.Recorder(sinks=[])
    pf = ChunkPrefetcher(slow_src(), depth=2, max_depth=4,
                         mem_probe=lambda: 1 << 40, recorder=rec)
    got = list(pf)
    pf.close()
    assert len(got) == 40
    assert pf.depth == 4
    assert rec.counter_value("prefetch.depth_adjustments") == 2
    assert _no_prefetch_threads()


def test_prefetcher_adaptive_depth_memory_veto():
    """No raise when one more buffered chunk would push the buffer past
    the available-memory share — depth stays put, counter stays zero."""
    from fps_tpu import obs

    def slow_src():
        for i in range(24):
            time.sleep(0.002)
            yield {"x": np.zeros(1024, np.float32)}  # 4 KiB chunks

    rec = obs.Recorder(sinks=[])
    pf = ChunkPrefetcher(slow_src(), depth=2, max_depth=8,
                         mem_probe=lambda: 1024, recorder=rec)
    got = list(pf)
    pf.close()
    assert len(got) == 24
    assert pf.depth == 2
    assert rec.counter_value("prefetch.depth_adjustments") == 0


def test_prefetcher_fixed_depth_without_max():
    """max_depth=None (the default) keeps the PR-5 fixed-depth
    behavior exactly: stalls never move the depth."""
    from fps_tpu import obs

    def slow_src():
        for i in range(24):
            time.sleep(0.002)
            yield {"x": np.full(4, i)}

    rec = obs.Recorder(sinks=[])
    pf = ChunkPrefetcher(slow_src(), depth=2, recorder=rec)
    got = list(pf)
    pf.close()
    assert len(got) == 24
    assert pf.depth == 2
    assert rec.counter_value("prefetch.depth_adjustments") == 0


def test_prefetcher_rejects_bad_max_depth():
    with pytest.raises(ValueError, match="max_depth"):
        ChunkPrefetcher(iter([]), depth=3, max_depth=2)


def test_fit_stream_adaptive_prefetch_bit_identical(devices8):
    """prefetch_max on/off cannot change numerics — depth is pure host
    plumbing, whatever it adapts to."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)

    results = {}
    for pf_max in (0, 6):
        trainer, store = _make_trainer(mesh, prefetch=1,
                                       prefetch_max=pf_max)
        tables, ls = trainer.init_state(jax.random.key(0))
        tables, ls, m = trainer.fit_stream(
            tables, ls, iter(chunks), jax.random.key(1)
        )
        results[pf_max] = (weights(store), m)
        assert len(trainer._compiled) == 1
    assert np.array_equal(results[0][0], results[6][0])
    assert _tree_equal(results[0][1], results[6][1])
    assert _no_prefetch_threads()
