"""Device-resident ingest: coverage/routing invariants + indexed-epoch parity.

Mirrors the reference's test approach (assert invariants, not bitwise
outputs — SURVEY.md §4) on the 8-virtual-device CPU mesh: every example is
visited exactly once per epoch, keyed routing pins examples to the owning
worker, padding rows carry weight 0, and the fused index-fed epoch runner
(`Trainer.run_indexed`) produces the same tables as the chunked driver.
"""

import numpy as np
import pytest

import jax

from fps_tpu.core.device_ingest import (
    DeviceDataset,
    DeviceEpochPlan,
    device_epoch_chunks,
)
from fps_tpu.core.driver import num_workers_of
from fps_tpu.models.matrix_factorization import MFConfig, online_mf
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import synthetic_ratings


@pytest.fixture(scope="module")
def mesh(devices8):
    return make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])


@pytest.fixture(scope="module")
def data():
    d = synthetic_ratings(57, 31, 1003, seed=0)
    # distinct ratings so multiset comparison detects duplicates/misses
    d["rating"] = (np.arange(1003) * 0.001).astype(np.float32)
    return d


@pytest.fixture(scope="module")
def dataset(mesh, data):
    return DeviceDataset(mesh, data)


LOCAL_BATCH = 16


def _collect(chunks, W, route):
    """Gather (example ratings, routing violations) across all chunks."""
    seen = []
    for c in chunks:
        c = {k: np.asarray(v) for k, v in c.items()}
        wt = c["weight"].reshape(-1, W * LOCAL_BATCH)
        u = c["user"].reshape(-1, W * LOCAL_BATCH)
        r = c["rating"].reshape(-1, W * LOCAL_BATCH)
        mask = wt > 0
        seen.append(r[mask])
        if route:
            worker_of_slot = np.arange(W * LOCAL_BATCH) // LOCAL_BATCH
            assert (u[mask] % W == np.broadcast_to(
                worker_of_slot, u.shape)[mask]).all()
    return np.concatenate(seen)


@pytest.mark.parametrize("shuffle", [None, "interleave", "sort"])
@pytest.mark.parametrize("route", [None, "user"])
@pytest.mark.parametrize("sync_every", [None, 2])
def test_chunks_cover_every_example_once(dataset, data, shuffle, route,
                                         sync_every):
    W = 8
    chunks = device_epoch_chunks(
        dataset, num_workers=W, local_batch=LOCAL_BATCH, steps_per_chunk=4,
        route_key=route, sync_every=sync_every, seed=3, shuffle=shuffle,
    )
    seen = _collect(chunks, W, route)
    assert len(seen) == len(data["rating"])
    np.testing.assert_allclose(np.sort(seen), np.sort(data["rating"]))


def test_interleave_differs_by_epoch_and_mixes(dataset, data):
    W = 8
    orders = []
    for seed in (0, 1):
        chunks = device_epoch_chunks(
            dataset, num_workers=W, local_batch=LOCAL_BATCH,
            steps_per_chunk=4, route_key=None, seed=seed,
            shuffle="interleave",
        )
        orders.append(_collect(chunks, W, None))
    # same multiset, different order across epochs/seeds
    np.testing.assert_allclose(np.sort(orders[0]), np.sort(orders[1]))
    assert not np.array_equal(orders[0], orders[1])
    # and not stream order either
    stream = device_epoch_chunks(
        dataset, num_workers=W, local_batch=LOCAL_BATCH, steps_per_chunk=4,
        route_key=None, seed=0, shuffle=None,
    )
    assert not np.array_equal(orders[0], _collect(stream, W, None))


@pytest.mark.parametrize("sync_every", [None, 2])
def test_indexed_epoch_matches_chunked(mesh, dataset, data, sync_every):
    W = num_workers_of(mesh)
    cfg = MFConfig(num_users=57, num_items=31, rank=4)

    tr1, _ = online_mf(mesh, cfg, sync_every=sync_every)
    t1, l1 = tr1.init_state(jax.random.key(0))
    chunks = device_epoch_chunks(
        dataset, num_workers=W, local_batch=64, steps_per_chunk=4,
        route_key="user", seed=7, sync_every=sync_every, shuffle="interleave",
    )
    t1, l1, m1 = tr1.fit_stream(t1, l1, chunks, jax.random.key(1))

    tr2, _ = online_mf(mesh, cfg, sync_every=sync_every)
    t2, l2 = tr2.init_state(jax.random.key(0))
    plan = DeviceEpochPlan(
        dataset, num_workers=W, local_batch=64, route_key="user",
        shuffle="interleave", seed=7, sync_every=sync_every,
    )
    t2, l2, m2 = tr2.run_indexed(t2, l2, plan, jax.random.key(1))

    n1 = sum(float(m["n"].sum()) for m in m1)
    n2 = sum(float(m["n"].sum()) for m in m2)
    assert n1 == n2 == len(data["rating"])
    np.testing.assert_allclose(
        np.asarray(t1["item_factors"]), np.asarray(t2["item_factors"]),
        atol=1e-5,
    )


def test_indexed_multi_epoch_converges(mesh, dataset):
    """Loss falls over epochs through the fused runner (sanity: training
    actually happens, per-epoch shuffles differ)."""
    W = num_workers_of(mesh)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)
    tr, _ = online_mf(mesh, cfg)
    t, l = tr.init_state(jax.random.key(0))
    plan = DeviceEpochPlan(
        dataset, num_workers=W, local_batch=32, route_key="user", seed=5,
    )
    t, l, metrics = tr.run_indexed(t, l, plan, jax.random.key(1), epochs=4)
    rmse = [float(np.sqrt(m["se"].sum() / m["n"].sum())) for m in metrics]
    assert rmse[-1] < rmse[0] * 0.9, rmse


def test_indexed_sparse_workload_ssp(mesh):
    """DeviceEpochPlan handles 2-D columns (sparse feat_ids/feat_vals) and
    the SSP indexed runner: Criteo-style logreg trains through run_indexed
    with multi-call epochs."""
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
        predict_proba_host,
    )
    from fps_tpu.utils.datasets import (
        synthetic_sparse_classification,
        train_test_split,
    )

    NF = 400
    W = num_workers_of(mesh)
    d = synthetic_sparse_classification(6000, NF, 8, seed=7, noise=0.05)
    d = dict(d, label=(d["label"] > 0).astype(np.float32))
    train, test = train_test_split(d)
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(
        mesh, cfg, sync_every=4, max_steps_per_call=8
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    ds = DeviceDataset(mesh, train)
    plan = DeviceEpochPlan(
        ds, num_workers=W, local_batch=32, sync_every=4, seed=3
    )
    assert plan.steps_per_epoch > 8  # multi-call epochs exercised
    tables, ls, m = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1), epochs=6
    )
    # metrics sized exactly to the epoch, no phantom padded-call rows
    assert m[0]["n"].shape[0] == plan.steps_per_epoch
    p = predict_proba_host(store, test["feat_ids"], test["feat_vals"])
    acc = float(np.mean((p > 0.5) == (test["label"] > 0.5)))
    assert acc > 0.78, acc


def test_run_indexed_checkpoint_resume_bit_exact(mesh, dataset, tmp_path):
    """interrupt-at-epoch-2 + restore + continue == straight 4-epoch run,
    bit for bit (epoch shuffles and PRNG streams keyed by absolute epoch)."""
    from fps_tpu.core.checkpoint import Checkpointer

    W = num_workers_of(mesh)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)

    def fresh():
        tr, store = online_mf(mesh, cfg)
        t, l = tr.init_state(jax.random.key(0))
        plan = DeviceEpochPlan(
            dataset, num_workers=W, local_batch=32, route_key="user", seed=5
        )
        return tr, store, t, l, plan

    # straight run
    tr_a, store_a, t, l, plan = fresh()
    t_full, l_full, _ = tr_a.run_indexed(t, l, plan, jax.random.key(1),
                                         epochs=4)

    # interrupted run: 2 epochs + snapshot
    tr, store, t, l, plan = fresh()
    ck = Checkpointer(str(tmp_path))
    t2, l2, _ = tr.run_indexed(
        t, l, plan, jax.random.key(1), epochs=2,
        checkpointer=ck, checkpoint_every=2,
    )
    # resume from the snapshot in a fresh trainer (different init — the
    # restore must fully overwrite it)
    tr3, store3, t3, l3, plan3 = fresh()
    store3.tables = t3
    t3, l3, step = tr3.restore_checkpoint(ck, l3)
    assert step == 2
    t4, l4, _ = tr3.run_indexed(
        t3, l3, plan3, jax.random.key(1), epochs=2, start_epoch=2
    )
    # Compare real rows via dump_model / logical user order — restore
    # zero-fills padding rows (unreachable by any valid id), so raw
    # physical arrays may differ there.
    from fps_tpu.models.recommendation import mf_user_vectors

    _, v_full = store_a.dump_model("item_factors")
    _, v_resumed = store3.dump_model("item_factors")
    np.testing.assert_array_equal(v_full, v_resumed)
    users = np.arange(57)
    np.testing.assert_array_equal(
        mf_user_vectors(np.asarray(l_full), W, users),
        mf_user_vectors(np.asarray(l4), W, users),
    )


@pytest.mark.parametrize("shuffle", [None, "interleave"])
@pytest.mark.parametrize("route", [None, "user"])
def test_transposed_buffer_matches_gather_path(mesh, dataset, shuffle, route):
    """The transposed-epoch fast path (contiguous slices of a per-epoch
    relayout) must produce bit-identical batches to the gather path."""
    W = 8
    fast = DeviceEpochPlan(
        dataset, num_workers=W, local_batch=LOCAL_BATCH, route_key=route,
        shuffle=shuffle, seed=3, pack=True,
    )
    slow = DeviceEpochPlan(
        dataset, num_workers=W, local_batch=LOCAL_BATCH, route_key=route,
        shuffle=shuffle, seed=3, pack=False,
    )
    assert fast._tbuf_jit is not None  # fast path actually engaged
    assert fast.steps_per_epoch == slow.steps_per_epoch
    fast_at = jax.jit(fast.local_batch_at)
    slow_at = jax.jit(slow.local_batch_at)
    for epoch in (0, 1):
        fa, sa = fast.epoch_args(epoch), slow.epoch_args(epoch)
        assert "tbuf" in fa and "tbuf" not in sa
        for t in range(fast.steps_per_epoch):
            for w in range(W):
                bf = fast_at(fa, np.int32(w), np.int32(t))
                bs = slow_at(sa, np.int32(w), np.int32(t))
                assert set(bf) == set(bs)
                wf = np.asarray(bf["weight"])
                np.testing.assert_array_equal(wf, np.asarray(bs["weight"]))
                for k in bf:
                    if k == "weight":
                        continue
                    # padding slots may differ (zeros vs clamped reads);
                    # only real rows must agree
                    np.testing.assert_array_equal(
                        np.asarray(bf[k])[wf > 0], np.asarray(bs[k])[wf > 0]
                    )


def test_explicit_plan_kwarg_mismatch_raises(dataset):
    """Passing a plan plus disagreeing geometry kwargs must raise, not
    silently use the plan's geometry."""
    W = 8
    plan = DeviceEpochPlan(
        dataset, num_workers=W, local_batch=LOCAL_BATCH, route_key="user",
        seed=3,
    )
    # Validation is eager — it must fire at call time, not at first next().
    with pytest.raises(ValueError, match="local_batch"):
        device_epoch_chunks(
            dataset, num_workers=W, local_batch=LOCAL_BATCH * 2,
            steps_per_chunk=4, route_key="user", seed=3, plan=plan,
        )
    with pytest.raises(ValueError, match="route_key"):
        device_epoch_chunks(
            dataset, num_workers=W, local_batch=LOCAL_BATCH,
            steps_per_chunk=4, route_key=None, seed=3, plan=plan,
        )


def test_on_epoch_sees_live_store(mesh, dataset):
    """Under donate=True the pre-call table buffers are invalidated; the
    store must be repointed at the live arrays before on_epoch runs so
    per-epoch validation via store.lookup_host works."""
    W = num_workers_of(mesh)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)
    tr, store = online_mf(mesh, cfg)  # donate=True default
    t, l = tr.init_state(jax.random.key(0))
    plan = DeviceEpochPlan(
        dataset, num_workers=W, local_batch=32, route_key="user", seed=5
    )
    seen = []

    def on_epoch(e, metrics):
        # The natural per-epoch validation pattern: host read of the live
        # tables. Raises "array deleted" if the store still points at the
        # donated pre-call buffers.
        vals = store.lookup_host("item_factors", np.arange(5))
        assert np.isfinite(vals).all()
        seen.append(e)

    tr.run_indexed(t, l, plan, jax.random.key(1), epochs=2,
                   on_epoch=on_epoch)
    assert seen == [0, 1]


def test_packed_blowup_guard_falls_back(mesh):
    """Extreme routing skew (every example keyed to one worker) must skip
    the packed fast path (HBM blowup) and still train correctly."""
    W = num_workers_of(mesh)
    n = 257
    d = {"user": np.full(n, 0, np.int32),  # all route to worker 0
         "item": np.arange(n, dtype=np.int32) % 31,
         "rating": np.linspace(0, 1, n).astype(np.float32)}
    ds = DeviceDataset(mesh, d)
    assert ds.packed("user", W) is None  # blowup W*maxq/n = W > 2
    plan = DeviceEpochPlan(ds, num_workers=W, local_batch=16,
                           route_key="user", seed=0)
    assert "packed" not in plan.epoch_args(0)
    cfg = MFConfig(num_users=1, num_items=31, rank=4)
    tr, _ = online_mf(mesh, cfg)
    t, l = tr.init_state(jax.random.key(0))
    t, l, m = tr.run_indexed(t, l, plan, jax.random.key(1))
    assert sum(float(x["n"].sum()) for x in m) == n


def test_negative_seed_and_sort_key_shape(devices8):
    """epoch_args' host-side rng must accept negative seeds (SeedSequence
    rejects negative entropy) and fabricate sort key data sized for the
    active prng impl."""
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=2)
    ds = DeviceDataset(mesh, synthetic_ratings(32, 24, 512, seed=0))
    for shuffle in ("interleave", "sort"):
        plan = DeviceEpochPlan(ds, num_workers=8, local_batch=8,
                               shuffle=shuffle, seed=-3)
        args = plan.epoch_args(0)
        assert args is not None
        # deterministic per (seed, epoch)
        a0 = jax.tree.map(lambda x: np.asarray(x), plan.epoch_args(1))
        a1 = jax.tree.map(lambda x: np.asarray(x), plan.epoch_args(1))
        for x, y in zip(jax.tree.leaves(a0), jax.tree.leaves(a1)):
            np.testing.assert_array_equal(x, y)


def test_run_indexed_as_numpy_false_matches(mesh, dataset):
    """as_numpy=False returns DEVICE metrics (no blocking conversion) that
    are value-identical to the default host metrics of the same run."""
    W = num_workers_of(mesh)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)

    def run(as_numpy):
        tr, _ = online_mf(mesh, cfg, donate=False)
        t, l = tr.init_state(jax.random.key(0))
        plan = DeviceEpochPlan(
            dataset, num_workers=W, local_batch=32, route_key="user", seed=5,
        )
        return tr.run_indexed(t, l, plan, jax.random.key(1), epochs=2,
                              as_numpy=as_numpy)[2]

    host = run(True)
    dev = run(False)
    assert all(isinstance(x, np.ndarray)
               for m in host for x in jax.tree.leaves(m))
    assert all(isinstance(x, jax.Array)
               for m in dev for x in jax.tree.leaves(m))
    for mh, md in zip(host, dev):
        for kh, kd in zip(jax.tree.leaves(mh), jax.tree.leaves(md)):
            np.testing.assert_array_equal(kh, np.asarray(kd))
