"""Device-resident megastep (fps_tpu.core.megastep): bit-identity with
the per-chunk host loop, the device-side overflow vote, and the in-graph
tier tick.

The load-bearing contract: ``run_megastep`` fusing K chunks into one
compiled program must reproduce the per-chunk ``run_indexed`` loop
BIT-for-bit — tables, metrics, and checkpoints — across guard on/off,
tiered/untiered, SSP, and the cold_budget overflow-vote fallback. The
vote itself must mirror the host certifier (fit → compacted branch,
overflow/uncertifiable → the bit-identical static branch), and the
in-graph tick's arithmetic (decayed fold, top-H ranking) must match the
host tracker's exactly.
"""

import dataclasses

import numpy as np
import pytest

import jax

from fps_tpu import obs
from fps_tpu import sketch as sklib
from fps_tpu.core import resilience
from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.store import hot_key, ids_key, map_key
from fps_tpu.models.matrix_factorization import MFConfig, online_mf
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.tiering import MegastepTick, device_top_ids
from fps_tpu.tiering.retier import top_ids
from fps_tpu.utils.datasets import synthetic_ratings

NU, NI, RANK = 57, 31, 4
LOCAL_BATCH, T_CALL = 8, 4


@pytest.fixture(scope="module")
def mesh(devices8):
    return make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])


@pytest.fixture(scope="module")
def data():
    return synthetic_ratings(NU, NI, 1003, seed=0)


@pytest.fixture(scope="module")
def skewed_data():
    """Item stream concentrated on the leading head [0, 16) — certifies
    small cold budgets."""
    rng = np.random.default_rng(0)
    n = 1000
    item = np.where(rng.random(n) < 0.95, rng.integers(0, 16, n),
                    rng.integers(16, NI, n)).astype(np.int32)
    return {"user": rng.integers(0, NU, n).astype(np.int32),
            "item": item,
            "rating": rng.normal(size=n).astype(np.float32)}


def _make(mesh, data, *, hot_tier=0, cold_budget=0, hot_sync_every=1,
          sync_every=None, guard=None, negative_samples=0):
    cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK,
                   negative_samples=negative_samples)
    trainer, store = online_mf(mesh, cfg, sync_every=sync_every,
                               max_steps_per_call=T_CALL, guard=guard)
    if hot_tier:
        store.specs["item_factors"] = dataclasses.replace(
            store.specs["item_factors"], hot_tier=hot_tier,
            cold_budget=cold_budget, dense_collectives=False)
        trainer.config = dataclasses.replace(
            trainer.config, hot_sync_every=hot_sync_every)
    plan = DeviceEpochPlan(
        DeviceDataset(mesh, data), num_workers=num_workers_of(mesh),
        local_batch=LOCAL_BATCH, route_key="user", seed=3,
        sync_every=sync_every)
    return trainer, store, plan


def _epoch_concat(per_megastep, epochs):
    """Per-epoch metric trees from the per-megastep list (trimmed parts
    concatenate to exactly the epoch's rows)."""
    M = len(per_megastep) // epochs
    out = []
    for e in range(epochs):
        parts = [jax.tree.map(np.asarray, p)
                 for p in per_megastep[e * M:(e + 1) * M]]
        out.append(jax.tree.map(
            lambda *xs: np.concatenate(xs), *parts)
            if len(parts) > 1 else parts[0])
    return out


def _strip_vote_counters(tree):
    """Drop the megastep-only cold_dropped telemetry leaves (the
    compacted program's observability net — run_indexed's static
    program never traces them) so metric trees compare structurally."""
    if not isinstance(tree, dict):
        return tree
    out = {}
    for k, v in tree.items():
        if k == "cold_dropped":
            continue
        out[k] = _strip_vote_counters(v) if isinstance(v, dict) else v
    return out


def _assert_pair_identical(tr1, st1, m1, tr2, st2, m2, epochs,
                           strip_votes=False):
    for k in st1.tables:
        np.testing.assert_array_equal(
            np.asarray(st1.tables[k]), np.asarray(tr2.store.tables[k]),
            err_msg=f"table {k} diverged")
    mega = _epoch_concat(m2, epochs)
    for e in range(epochs):
        a = jax.tree.map(np.asarray, m1[e])
        b = mega[e]
        if strip_votes:
            b = _strip_vote_counters(b)
        la, ta = jax.tree.flatten(a)
        lb, tb = jax.tree.flatten(b)
        assert str(ta) == str(tb)
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(x, y)


def _run_pair(mesh, data, *, epochs=2, K=2, strip_votes=False,
              rec=None, **kw):
    tr1, st1, p1 = _make(mesh, data, **kw)
    tr2, st2, p2 = _make(mesh, data, **kw)
    t1, l1 = tr1.init_state(jax.random.key(0))
    t2, l2 = tr2.init_state(jax.random.key(0))
    t1, l1, m1 = tr1.run_indexed(t1, l1, p1, jax.random.key(1),
                                 epochs=epochs)
    t2, l2, m2 = tr2.run_megastep(t2, l2, p2, jax.random.key(1),
                                  epochs=epochs, chunks_per_dispatch=K,
                                  recorder=rec)
    _assert_pair_identical(tr1, st1, m1, tr2, st2, m2, epochs,
                           strip_votes=strip_votes)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    return tr2, st2, m2


# -- bit-identity with the per-chunk host loop ---------------------------


def test_megastep_matches_indexed_untiered(mesh, data):
    _run_pair(mesh, data)


def test_megastep_matches_indexed_guard_mask(mesh, data):
    _run_pair(mesh, data, guard="mask")


def test_megastep_matches_indexed_tiered_partial(mesh, data):
    _run_pair(mesh, data, hot_tier=16, hot_sync_every=2)


def test_megastep_matches_indexed_ssp(mesh, data):
    _run_pair(mesh, data, sync_every=2)


def test_megastep_uncertifiable_logic_stays_static(mesh, data):
    """A logic whose prepare synthesizes ids (negative sampling) cannot
    vote — every window runs the static routes, counted as overflow,
    still bit-identical to the per-chunk loop."""
    rec = obs.Recorder(sinks=[])
    _run_pair(mesh, data, hot_tier=16, hot_sync_every=2, cold_budget=4,
              negative_samples=2, rec=rec)
    assert rec.counter_value("cold_route.vote_compact_windows") == 0
    # One AND-ed verdict per window — unlabeled by design (the PR-13
    # per-table attribution multiply-counted the single verdict).
    assert rec.counter_value("cold_route.vote_overflow_windows") > 0


# -- the overflow vote ---------------------------------------------------


def test_vote_fits_runs_compacted_and_matches(mesh, skewed_data):
    rec = obs.Recorder(sinks=[])
    tr, st, m = _run_pair(mesh, skewed_data, hot_tier=16,
                          hot_sync_every=2, cold_budget=8,
                          strip_votes=True, rec=rec)
    assert rec.counter_value("cold_route.vote_compact_windows") > 0
    # The drop net: zero for every certified window, by construction.
    dropped = sum(
        float(np.sum(np.asarray(
            mm["hot_tier"]["item_factors"].get("cold_dropped", 0))))
        for mm in m)
    assert dropped == 0


def test_megastep_windows_counts_real_segments(mesh, data):
    """A trimmed final dispatch still runs K in-graph segments, but
    megastep.windows must count only the REAL (non-weight-0) ones —
    exactly the per-chunk dispatch count the bit-identity contract
    compares against (the PR-13 phantom-window fix)."""
    from fps_tpu.core.driver import calls_per_epoch_of

    rec = obs.Recorder(sinks=[])
    tr, _, _ = _run_pair(mesh, data, epochs=2, K=4, rec=rec)
    _, _, plan = _make(mesh, data)
    n_calls = calls_per_epoch_of(plan, tr._indexed_call_steps(plan))
    # Non-vacuity: K=4 must actually leave a trimmed final dispatch.
    assert n_calls % 4 != 0
    assert rec.counter_value("megastep.windows") == 2 * n_calls


def test_vote_totals_count_real_windows_only(mesh, skewed_data):
    """compact + overflow vote counters must sum to the REAL window
    count — phantom trailing segments of a trimmed dispatch voted
    in-graph but did no work and must not be attributed."""
    from fps_tpu.core.driver import calls_per_epoch_of

    rec = obs.Recorder(sinks=[])
    tr, _, _ = _run_pair(mesh, skewed_data, epochs=2, K=4, hot_tier=16,
                         hot_sync_every=2, cold_budget=8,
                         strip_votes=True, rec=rec)
    _, _, plan = _make(mesh, skewed_data, hot_tier=16, hot_sync_every=2,
                       cold_budget=8)
    n_calls = calls_per_epoch_of(plan, tr._indexed_call_steps(plan))
    assert n_calls % 4 != 0  # a trimmed dispatch exists
    total = (rec.counter_value("cold_route.vote_compact_windows")
             + rec.counter_value("cold_route.vote_overflow_windows"))
    assert total == 2 * n_calls
    assert rec.counter_value("megastep.windows") == 2 * n_calls


def test_vote_overflow_falls_back_bit_identical(mesh, skewed_data):
    rec = obs.Recorder(sinks=[])
    _run_pair(mesh, skewed_data, hot_tier=16, hot_sync_every=2,
              cold_budget=1, strip_votes=True, rec=rec)
    assert rec.counter_value("cold_route.vote_overflow_windows") > 0


# -- checkpoints ---------------------------------------------------------


def test_megastep_checkpoint_resume_bit_identical(mesh, data, tmp_path):
    from fps_tpu.core.checkpoint import Checkpointer

    kw = dict(hot_tier=16, hot_sync_every=2)
    # Straight run with boundary checkpoints.
    tr1, st1, p1 = _make(mesh, data, **kw)
    t1, l1 = tr1.init_state(jax.random.key(0))
    ck1 = Checkpointer(str(tmp_path / "straight"), keep=20)
    tr1.run_megastep(t1, l1, p1, jax.random.key(1), epochs=2,
                     chunks_per_dispatch=2, checkpointer=ck1,
                     checkpoint_every=1)
    # Interrupted run: stop after 3 megasteps, restore, resume.
    tr2, st2, p2 = _make(mesh, data, **kw)
    t2, l2 = tr2.init_state(jax.random.key(0))
    ck2 = Checkpointer(str(tmp_path / "resumed"), keep=20)
    tr2.run_megastep(t2, l2, p2, jax.random.key(1), epochs=1,
                     chunks_per_dispatch=2, checkpointer=ck2,
                     checkpoint_every=1)
    n_calls = p2.calls_per_epoch(T_CALL)
    M = -(-n_calls // 2)
    assert ck2.latest_valid_step() == M
    tr3, st3, p3 = _make(mesh, data, **kw)
    t3, l3 = tr3.init_state(jax.random.key(0))
    ck3 = Checkpointer(str(tmp_path / "resumed"), keep=20)
    t3, l3, _ = tr3.restore_checkpoint(ck3, l3)
    tr3.run_megastep(t3, l3, p3, jax.random.key(1), epochs=2,
                     chunks_per_dispatch=2, checkpointer=ck3,
                     checkpoint_every=1, start_megastep=M)
    # Logical rows bit-identical (the padding row of a restored table is
    # re-derived, not round-tripped — same as every other driver), and
    # every post-resume boundary checkpoint byte-compatible with the
    # straight run's.
    ids = np.arange(NI)
    np.testing.assert_array_equal(
        st1.lookup_host("item_factors", ids),
        st3.lookup_host("item_factors", ids),
        err_msg="resumed item_factors diverged from straight")
    np.testing.assert_array_equal(
        np.asarray(st1.tables[hot_key("item_factors")]),
        np.asarray(tr3.store.tables[hot_key("item_factors")]))
    for g in range(M, 2 * M + 1):
        _, va, la, _ = ck1.read_snapshot(g)
        _, vb, lb, _ = ck3.read_snapshot(g)
        for k in va:
            np.testing.assert_array_equal(
                np.asarray(va[k]), np.asarray(vb[k]),
                err_msg=f"checkpoint {g} table {k} diverged")
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(x, y)


# -- guard / rollback at megastep granularity ----------------------------


def test_megastep_quarantine_matches_preset_skip(mesh):
    """A poisoned megastep (NaN ratings in its chunks) is quarantined —
    pre-dispatch state restored, index recorded — and the result equals
    a fresh run that preset-skips the same megastep."""
    rng = np.random.default_rng(1)
    n = 1003
    d = {"user": rng.integers(0, NU, n).astype(np.int32),
         "item": rng.integers(0, NI, n).astype(np.int32),
         "rating": rng.normal(size=n).astype(np.float32)}
    # Poison a slab of the stream so one megastep's chunks see NaNs.
    d["rating"][100:160] = np.nan

    def go(rollback):
        tr, st, p = _make(mesh, d, guard="mask")
        t, ls = tr.init_state(jax.random.key(0))
        t, ls, m = tr.run_megastep(t, ls, p, jax.random.key(1),
                                   epochs=1, chunks_per_dispatch=2,
                                   rollback=rollback)
        return tr, st, rollback

    rb1 = resilience.RollbackPolicy()
    tr1, st1, rb1 = go(rb1)
    assert rb1.quarantined, "poison megastep was not quarantined"
    rb2 = resilience.RollbackPolicy(preset=frozenset(rb1.quarantined))
    tr2, st2, rb2 = go(rb2)
    assert sorted(rb2.skipped) == sorted(rb1.quarantined)
    for k in st1.tables:
        np.testing.assert_array_equal(np.asarray(st1.tables[k]),
                                      np.asarray(st2.tables[k]))


def test_health_by_segment_unit():
    metrics = {"health": {"t": {
        "nonfinite": np.array([0, 0, 3, 0, 0, 1, 0, 0]),
        "norm": np.array([0, 0, 0, 0, 0, 0, 0, 2]),
    }}}
    assert resilience.health_by_segment(metrics, 2, 4) == [3, 3]
    # Trimmed final megastep: missing trailing rows report 0.
    short = {"health": {"t": {"nonfinite": np.array([1, 0, 0])}}}
    assert resilience.health_by_segment(short, 2, 4) == [1, 0]
    assert resilience.health_by_segment({}, 3, 4) == [0, 0, 0]


# -- the in-graph tier tick ----------------------------------------------


def test_device_dcm_fold_matches_host():
    spec = sklib.DecayedCountMinSpec(depth=3, width=64, half_every=2)
    rng = np.random.default_rng(0)
    state = rng.random((3, 64)).astype(np.float32)
    window = rng.random((3, 64)).astype(np.float32)
    for tick in (0, 1, 2, 3, 4):
        host = sklib.dcm_fold(spec, state, window, tick)
        dev = jax.jit(
            lambda s, w, t: sklib.dcm_fold_traced(spec, s, w, t)
        )(state, window, np.int32(tick))
        np.testing.assert_array_equal(host, np.asarray(dev))


def test_device_top_ids_matches_host():
    rng = np.random.default_rng(0)
    # Heavy ties: a small value alphabet forces the id tie-break.
    est = rng.integers(0, 5, 200).astype(np.float32)
    for H in (1, 7, 50, 200):
        np.testing.assert_array_equal(
            top_ids(est, H),
            np.asarray(device_top_ids(est, H)).astype(np.int64))


def test_megastep_tick_reranks_deterministic(mesh):
    """E2E: a stream whose true head is NOT the static [0, H) must be
    re-ranked onto it by the in-graph tick; the replica stays consistent
    with the canonical table, host mirrors sync, and the whole run is
    deterministic."""
    rng = np.random.default_rng(0)
    n = 1200
    item = np.where(rng.random(n) < 0.9, rng.integers(15, NI, n),
                    rng.integers(0, 15, n)).astype(np.int32)
    d = {"user": rng.integers(0, NU, n).astype(np.int32), "item": item,
         "rating": rng.normal(size=n).astype(np.float32)}

    def go():
        tr, st, p = _make(mesh, d, hot_tier=16, hot_sync_every=2)
        tick = MegastepTick(check_every=1, churn_threshold=-1.0)
        t, ls = tr.init_state(jax.random.key(0))
        rec = obs.Recorder(sinks=[])
        t, ls, _ = tr.run_megastep(t, ls, p, jax.random.key(1),
                                   epochs=2, chunks_per_dispatch=2,
                                   tick=tick, recorder=rec)
        return tr, tick, rec, t

    tr, tick, rec, tables = go()
    gids = np.asarray(tr.store.tables[ids_key("item_factors")])
    # The sketched head found the hot ids (id 0 may ride along: padding
    # rows gather row 0, and the sketch counts them like the host
    # tracker does).
    assert len(set(gids.tolist()) & set(range(15, NI))) >= 14
    assert rec.counter_value("tiering.re_ranks",
                             table="item_factors") >= 1
    # Replica rows == canonical rows at the final hot ids (boundary
    # invariant survives in-graph re-derivation).
    np.testing.assert_array_equal(
        np.asarray(tr.store.tables[hot_key("item_factors")]),
        tr.store.lookup_host("item_factors", gids))
    # Slot map consistent with the gid order.
    smap = np.asarray(tr.store.tables[map_key("item_factors")])
    np.testing.assert_array_equal(smap[gids], np.arange(len(gids)))
    # Host mirrors synced at end of run.
    np.testing.assert_array_equal(tick.hot_ids["item_factors"], gids)
    assert tick.tick > 0
    # Determinism: an identical second run lands identical state.
    tr2, tick2, _, _ = go()
    np.testing.assert_array_equal(
        gids, np.asarray(tr2.store.tables[ids_key("item_factors")]))
    for k in tr.store.tables:
        np.testing.assert_array_equal(
            np.asarray(tr.store.tables[k]),
            np.asarray(tr2.store.tables[k]))


# -- validation ----------------------------------------------------------


def test_megastep_validations(mesh, data):
    tr, st, p = _make(mesh, data)
    t, ls = tr.init_state(jax.random.key(0))
    tr.config = dataclasses.replace(tr.config, push_delay=2)
    with pytest.raises(ValueError, match="push_delay"):
        tr.run_megastep(t, ls, p, jax.random.key(1))
    tr.config = dataclasses.replace(tr.config, push_delay=0,
                                    auto_tier=True)
    with pytest.raises(ValueError, match="auto_tier"):
        tr.run_megastep(t, ls, p, jax.random.key(1))
    tr.config = dataclasses.replace(tr.config, auto_tier=False)
    with pytest.raises(ValueError, match="chunks_per_dispatch"):
        tr.run_megastep(t, ls, p, jax.random.key(1),
                        chunks_per_dispatch=0)
    # A host Retierer has no in-graph boundary to run on.
    from fps_tpu.tiering import Retierer

    tr.retierer = Retierer()
    with pytest.raises(ValueError, match="MegastepTick"):
        tr.run_megastep(t, ls, p, jax.random.key(1))
    tr.retierer = None
    # A tick without a mapped table is a config error, loudly — and the
    # rejected tick must NOT stay attached as the trainer's retierer.
    with pytest.raises(ValueError, match="mapped tier"):
        tr.run_megastep(t, ls, p, jax.random.key(1),
                        tick=MegastepTick())
    assert tr.retierer is None
    # Tick cadence must divide the dispatch — both at the runner and at
    # the direct-builder entry point (lowered_megastep_text must raise,
    # never silently truncate the dispatch).
    tr2, st2, p2 = _make(mesh, data, hot_tier=16, hot_sync_every=2)
    t2, l2 = tr2.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="multiple"):
        tr2.run_megastep(t2, l2, p2, jax.random.key(1),
                         chunks_per_dispatch=3,
                         tick=MegastepTick(check_every=2))
    assert tr2.retierer is None
    with pytest.raises(ValueError, match="multiple"):
        tr2.lowered_megastep_text(p2, chunks_per_dispatch=3,
                                  tick=MegastepTick(check_every=2))


# -- chaos ---------------------------------------------------------------


@pytest.mark.slow
def test_megastep_kill_scenario(tmp_path):
    from fps_tpu.testing.supervised_demo import run_megastep_kill_scenario

    ok, detail = run_megastep_kill_scenario(str(tmp_path))
    assert ok, detail


# -- auto-K (chunks_per_dispatch="auto") ---------------------------------


def test_auto_k_derivation_fixed_points():
    """The pure derivation on fixed calibration traces: smallest K with
    h/(h+K*c) <= share, cadence-rounded, epoch- and max-capped."""
    from fps_tpu.core.autok import derive_chunks_per_dispatch as derive

    # h=1ms, c=1ms, s=0.05 -> ceil(0.95/0.05) = 19.
    assert derive(0.001, 0.001, target_share=0.05) == 19
    # Cadence rounds UP, never truncates a tick block.
    assert derive(0.001, 0.001, target_share=0.05, multiple_of=4) == 20
    # Dominant overhead hits the max-K cap (rounded DOWN to cadence).
    assert derive(0.1, 0.001, target_share=0.05, max_k=64) == 64
    assert derive(0.1, 0.001, target_share=0.05, max_k=62,
                  multiple_of=4) == 60
    # No measurable overhead: smallest legal K.
    assert derive(0.0, 0.001) == 1
    assert derive(0.0, 0.001, multiple_of=4) == 4
    # Dispatch-bound (c ~ 0): cap, not a crash.
    assert derive(0.001, 0.0, max_k=32) == 32
    # One epoch's calls bound the useful K (cadence-rounded up).
    assert derive(0.001, 0.001, n_calls=6) == 6
    assert derive(0.001, 0.001, n_calls=6, multiple_of=4) == 8
    with pytest.raises(ValueError, match="target_share"):
        derive(0.001, 0.001, target_share=1.5)


def test_auto_k_fixed_trace_bit_identical_to_flag(mesh, data, tmp_path,
                                                  monkeypatch):
    """On a FIXED calibration trace, "auto" picks the derived K and the
    run it drives is bit-identical to passing that K explicitly —
    tables, metrics, and every boundary checkpoint."""
    from fps_tpu.core import autok
    from fps_tpu.core.checkpoint import Checkpointer

    # wall(1 block) = h + c, wall(2 blocks) = h + 2c with h=0.2ms,
    # c=1ms -> derived K = ceil(0.0002*0.95/(0.05*0.001)) = 4.
    walls = iter([0.0012, 0.0022])
    monkeypatch.setattr(autok, "_measure_dispatch",
                        lambda *a, **kw: next(walls))
    K = 4

    tr1, st1, p1 = _make(mesh, data)
    t1, l1 = tr1.init_state(jax.random.key(0))
    ck1 = Checkpointer(str(tmp_path / "flag"), keep=20)
    rec1 = obs.Recorder(sinks=[])
    tr1.run_megastep(t1, l1, p1, jax.random.key(1), epochs=2,
                     chunks_per_dispatch=K, checkpointer=ck1,
                     checkpoint_every=1, recorder=rec1)

    tr2, st2, p2 = _make(mesh, data)
    t2, l2 = tr2.init_state(jax.random.key(0))
    ck2 = Checkpointer(str(tmp_path / "auto"), keep=20)
    rec2 = obs.Recorder(sinks=[])
    tr2.run_megastep(t2, l2, p2, jax.random.key(1), epochs=2,
                     chunks_per_dispatch="auto", checkpointer=ck2,
                     checkpoint_every=1, recorder=rec2)

    assert rec2.snapshot()["gauges"]["megastep.auto_k"] == K
    assert (rec2.snapshot()["gauges"]["megastep.chunks_per_dispatch"]
            == K)
    for k in st1.tables:
        np.testing.assert_array_equal(
            np.asarray(st1.tables[k]), np.asarray(st2.tables[k]),
            err_msg=f"table {k} diverged under auto-K")
    assert ck1.steps() == ck2.steps()
    for g in ck1.steps():
        _, va, la, _ = ck1.read_snapshot(g)
        _, vb, lb, _ = ck2.read_snapshot(g)
        assert sorted(va) == sorted(vb)
        for k in va:
            np.testing.assert_array_equal(
                np.asarray(va[k]), np.asarray(vb[k]),
                err_msg=f"checkpoint {g} table {k} diverged")
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(x, y)


def test_auto_k_live_calibration_runs(mesh, data):
    """The real (unmocked) calibration window: runs, records the gauge,
    and never perturbs the model state (the throwaway-copy contract) —
    the resulting tables still match the per-chunk host loop."""
    rec = obs.Recorder(sinks=[])
    tr1, st1, p1 = _make(mesh, data)
    t1, l1 = tr1.init_state(jax.random.key(0))
    t1, l1, m1 = tr1.run_indexed(t1, l1, p1, jax.random.key(1),
                                 epochs=1)
    tr2, st2, p2 = _make(mesh, data)
    t2, l2 = tr2.init_state(jax.random.key(0))
    tr2.run_megastep(t2, l2, p2, jax.random.key(1), epochs=1,
                     chunks_per_dispatch="auto", recorder=rec)
    chosen = rec.snapshot()["gauges"]["megastep.auto_k"]
    assert chosen >= 1
    for k in st1.tables:
        np.testing.assert_array_equal(
            np.asarray(st1.tables[k]), np.asarray(st2.tables[k]),
            err_msg=f"table {k} diverged under live auto-K")


def test_auto_k_rejects_unknown_string(mesh, data):
    tr, st, p = _make(mesh, data)
    t, ls = tr.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="'auto'"):
        tr.run_megastep(t, ls, p, jax.random.key(1),
                        chunks_per_dispatch="fastest")
