"""Pallas kernel parity tests (interpreter mode on the CPU mesh).

Oracle: numpy gather / np.add.at. Covers duplicates (Zipfian ids), drop
sentinels, ragged (non-tile-multiple) shapes, and the dispatcher's backend
switching — including a full MF training chunk run end-to-end with the
Pallas backend to prove the kernels compose inside shard_map + scan.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fps_tpu.ops as ops
from fps_tpu.ops.pallas_kernels import gather_rows_pallas, scatter_add_pallas


@pytest.fixture
def pallas_backend():
    prev = ops.get_backend()
    ops.set_backend("pallas")
    yield
    ops.set_backend(prev)


@pytest.mark.parametrize("R,D,B", [(64, 8, 32), (57, 5, 40), (8, 128, 256)])
def test_gather_parity(R, D, B):
    rng = np.random.default_rng(0)
    table = rng.normal(0, 1, (R, D)).astype(np.float32)
    ids = rng.integers(0, R, B).astype(np.int32)
    got = gather_rows_pallas(jnp.asarray(table), jnp.asarray(ids), interpret=True)
    np.testing.assert_array_equal(np.asarray(got), table[ids])


@pytest.mark.parametrize(
    "R,D,B,row_tile,batch_tile",
    [
        (64, 8, 100, 16, 32),   # ragged batch vs tile
        (57, 5, 40, 256, 2048),  # tiles larger than data
        (130, 3, 513, 64, 128),  # ragged rows vs tile
    ],
)
def test_scatter_add_parity(R, D, B, row_tile, batch_tile):
    rng = np.random.default_rng(1)
    table = rng.normal(0, 1, (R, D)).astype(np.float32)
    # Zipfian ids -> heavy duplication, plus drop sentinels -1 and R.
    ids = (rng.zipf(1.5, B) % R).astype(np.int32)
    ids[::7] = -1
    ids[3::11] = R
    deltas = rng.normal(0, 1, (B, D)).astype(np.float32)

    got = scatter_add_pallas(
        jnp.asarray(table), jnp.asarray(ids), jnp.asarray(deltas),
        row_tile=row_tile, batch_tile=batch_tile, interpret=True,
    )

    want = table.copy()
    keep = (ids >= 0) & (ids < R)
    np.add.at(want, ids[keep], deltas[keep])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("R,D,B,hot", [(64, 8, 100, 16), (130, 3, 513, 7),
                                       (57, 200, 64, 8)])
def test_scatter_add_hot_cold_split_parity(pallas_backend, R, D, B, hot):
    """scatter_add with hot_rows>0 (head via the lane-packed one-hot kernel,
    tail via XLA) must match the plain scatter semantics exactly: drops,
    duplicates, and head/tail boundary ids."""
    rng = np.random.default_rng(7)
    table = rng.normal(0, 1, (R, D)).astype(np.float32)
    ids = (rng.zipf(1.5, B) % R).astype(np.int32)  # heavy head duplication
    ids[::9] = -1
    ids[4::13] = R
    ids[1::17] = hot - 1  # boundary: last head row
    ids[2::17] = hot      # boundary: first tail row
    deltas = rng.normal(0, 1, (B, D)).astype(np.float32)

    got = np.asarray(ops.scatter_add(
        jnp.asarray(table), jnp.asarray(ids), jnp.asarray(deltas),
        hot_rows=hot,
    ))
    want = table.astype(np.float64).copy()
    keep = (ids >= 0) & (ids < R)
    np.add.at(want, ids[keep], deltas[keep].astype(np.float64))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_packed_scatter_parity():
    """The lane-packed kernel alone (pack = 128 // D logical rows per lane
    row, hi/lo bf16 split) vs the numpy oracle."""
    from fps_tpu.ops.pallas_kernels import scatter_add_packed_pallas

    rng = np.random.default_rng(8)
    for R, D, B in [(64, 8, 100), (53, 11, 513), (16, 130, 64), (512, 1, 700)]:
        table = rng.normal(0, 1, (R, D)).astype(np.float32)
        ids = (rng.zipf(1.5, B) % (R + 8) - 2).astype(np.int32)  # some oob
        deltas = rng.normal(0, 1, (B, D)).astype(np.float32)
        got = np.asarray(scatter_add_packed_pallas(
            jnp.asarray(table), jnp.asarray(ids), jnp.asarray(deltas),
            interpret=True,
        ))
        want = table.astype(np.float64).copy()
        keep = (ids >= 0) & (ids < R)
        np.add.at(want, ids[keep], deltas[keep].astype(np.float64))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4,
                                   err_msg=f"R={R} D={D} B={B}")


def test_dispatcher_backends():
    with pytest.raises(ValueError):
        ops.set_backend("cuda")
    assert ops.get_backend() in ("xla", "pallas", "auto")

    rng = np.random.default_rng(2)
    table = rng.normal(0, 1, (30, 4)).astype(np.float32)
    ids = rng.integers(-1, 31, 50).astype(np.int32)  # includes drop values
    deltas = rng.normal(0, 1, (50, 4)).astype(np.float32)
    keep = (ids >= 0) & (ids < 30)
    want = table.copy()
    np.add.at(want, ids[keep], deltas[keep])

    prev = ops.get_backend()
    try:
        results = {}
        for backend in ("xla", "pallas"):
            ops.set_backend(backend)
            results[backend] = np.asarray(
                ops.scatter_add(jnp.asarray(table), jnp.asarray(ids),
                                jnp.asarray(deltas))
            )
            gids = np.clip(ids, 0, 29)
            g = np.asarray(ops.gather_rows(jnp.asarray(table), jnp.asarray(gids)))
            np.testing.assert_array_equal(g, table[gids])
        for backend, got in results.items():
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                       err_msg=f"backend={backend}")
    finally:
        ops.set_backend(prev)


def test_gather_oob_zero_rows_on_every_backend():
    """Padding ids (-1) must read as zero rows identically on all backends."""
    rng = np.random.default_rng(4)
    table = rng.normal(0, 1, (20, 70)).astype(np.float32)  # D>=64: pallas path
    ids = np.array([-1, 3, 20, 0, -1], np.int32)
    prev = ops.get_backend()
    try:
        outs = {}
        for backend in ("xla", "pallas"):
            ops.set_backend(backend)
            outs[backend] = np.asarray(
                ops.gather_rows(jnp.asarray(table), jnp.asarray(ids))
            )
        want = np.stack([
            np.zeros(70), table[3], np.zeros(70), table[0], np.zeros(70)
        ]).astype(np.float32)
        for backend, got in outs.items():
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=0,
                                       err_msg=f"backend={backend}")
    finally:
        ops.set_backend(prev)


def test_set_backend_takes_effect_on_compiled_trainer(devices8):
    """set_backend() after a chunk has compiled must retrace, not silently
    reuse the old backend's executable (Trainer keys its cache on it)."""
    import fps_tpu.ops as ops_mod
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=2, num_data=1, devices=devices8[:2])
    trainer, store = online_mf(mesh, MFConfig(16, 12, rank=4), donate=False)
    data = synthetic_ratings(16, 12, 128, seed=5)
    chunk = next(epoch_chunks(data, num_workers=num_workers_of(mesh),
                              local_batch=8, steps_per_chunk=2,
                              route_key="user"))
    tables, ls = trainer.init_state(jax.random.key(0))
    prev = ops_mod.get_backend()
    try:
        ops_mod.set_backend("xla")
        trainer.run_chunk(tables, ls, chunk, jax.random.key(1))
        assert any(k[:2] == ("sync", "xla") for k in trainer._compiled)
        ops_mod.set_backend("pallas")
        trainer.run_chunk(tables, ls, chunk, jax.random.key(1))
        assert any(k[:2] == ("sync", "pallas") for k in trainer._compiled)
    finally:
        ops_mod.set_backend(prev)


def test_mf_chunk_runs_with_pallas_backend(devices8, pallas_backend):
    """Full compiled training chunk (shard_map + scan + collectives) with the
    Pallas kernels in the pull/push hot path, vs the XLA backend result."""
    import fps_tpu.ops as ops_mod
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    cfg = MFConfig(num_users=32, num_items=24, rank=4)
    data = synthetic_ratings(32, 24, 512, seed=3)

    def run_one():
        trainer, store = online_mf(mesh, cfg, donate=False)
        W = num_workers_of(mesh)
        chunk = next(epoch_chunks(data, num_workers=W, local_batch=16,
                                  steps_per_chunk=4, route_key="user"))
        tables, ls = trainer.init_state(jax.random.key(0))
        tables, ls, m = trainer.run_chunk(tables, ls, chunk, jax.random.key(1))
        return np.asarray(tables["item_factors"])

    got = run_one()
    ops_mod.set_backend("xla")
    want = run_one()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_whole_shard_packed_scatter_matches_xla(devices8):
    """hot_rows >= R routes the ENTIRE scatter through the packed MXU
    kernel (no tail scatter); result must match the XLA scatter within
    the bf16 hi+lo limb tolerance, including drops and duplicates."""
    from fps_tpu import ops

    rng = np.random.default_rng(3)
    R, D, B = 96, 8, 512
    tab = jnp.asarray(rng.normal(0, 0.1, (R, D)), jnp.float32)
    ids = jnp.asarray(rng.integers(-1, R + 2, B), jnp.int32)  # drops both ends
    deltas = jnp.asarray(rng.normal(0, 1e-2, (B, D)), jnp.float32)

    want = np.asarray(ops.scatter_add(tab, ids, deltas))  # hot_rows=0: XLA
    old = ops.get_backend()
    ops.set_backend("pallas")
    try:
        got = np.asarray(ops.scatter_add(tab, ids, deltas, hot_rows=R))
    finally:
        ops.set_backend(old)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-5)


def test_hot_ids_auto_resolution(devices8):
    """hot_ids="auto" enables whole-shard packed routing exactly when the
    per-shard slice is at or below the measured crossover."""
    from fps_tpu.core.api import ServerLogic, StepOutput, WorkerLogic
    from fps_tpu.core.driver import Trainer
    from fps_tpu.core.store import ParamStore, TableSpec, rows_per_shard
    from fps_tpu.ops import packed_crossover_rows
    from fps_tpu.parallel.mesh import make_ps_mesh

    class Noop(WorkerLogic):
        def pull_ids(self, batch):
            return {}

        def step(self, batch, pulled, local_state, key):
            return StepOutput(pushes={}, local_state=local_state, out={})

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    thin = TableSpec("thin", 8 * 1024, 10, hot_ids="auto").zeros_init()
    fat = TableSpec("fat", 8 * 65536, 10, hot_ids="auto").zeros_init()
    head = TableSpec("head", 8 * 65536, 10, hot_ids=4096).zeros_init()
    store = ParamStore(mesh, [thin, fat, head])
    tr = Trainer(mesh, store, Noop(), server_logic=ServerLogic())

    assert rows_per_shard(8 * 1024, 8) <= packed_crossover_rows(10)
    assert tr._resolve_hot_rows(store.specs["thin"]) == 1024  # whole shard
    assert tr._resolve_hot_rows(store.specs["fat"]) == 0      # above cutover
    assert tr._resolve_hot_rows(store.specs["head"]) == 512   # ceil(4096/8)

    # Any other string must fail loudly at the right altitude, not as a
    # cryptic TypeError inside the jitted push.
    bad = TableSpec("bad", 100, 4, hot_ids="Auto").zeros_init()
    store2 = ParamStore(mesh, [bad])
    tr2 = Trainer(mesh, store2, Noop(), server_logic=ServerLogic())
    with pytest.raises(ValueError, match="hot_ids"):
        tr2._resolve_hot_rows(store2.specs["bad"])


def test_hot_ids_auto_trains_equivalently(devices8, monkeypatch):
    """End-to-end: a Trainer with hot_ids="auto" on a thin 8-shard table
    (auto -> whole-shard packed routing) trains to the same result as the
    exact XLA path within the packed kernel's bf16 hi+lo tolerance — AND
    the packed kernel is asserted to actually be on the traced path (a
    route that never fires would vacuously pass the equality check)."""
    from fps_tpu.core.api import ServerLogic, StepOutput, WorkerLogic
    from fps_tpu.core.driver import Trainer, TrainerConfig
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.core.store import ParamStore, TableSpec
    from fps_tpu.parallel.mesh import make_ps_mesh

    class Pusher(WorkerLogic):
        def pull_ids(self, batch):
            return {"t": batch["id"].astype(jnp.int32)}

        def step(self, batch, pulled, local_state, key):
            ids = jnp.where(batch["weight"] > 0,
                            batch["id"].astype(jnp.int32), -1)
            # pulled-dependent delta: exercises gather AND scatter
            deltas = (0.5 * batch["val"][:, None]
                      - 0.1 * pulled["t"]).astype(jnp.float32)
            return StepOutput(pushes={"t": (ids, deltas)},
                              local_state=local_state, out={})

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    R, D = 512, 4  # 64 rows/shard, far below the crossover -> auto packs
    rng = np.random.default_rng(9)
    n = 1024
    data = {"id": rng.integers(0, R, n).astype(np.int32),
            "val": rng.normal(0, 1, n).astype(np.float32)}
    chunks = list(epoch_chunks(data, num_workers=8, local_batch=32,
                               steps_per_chunk=2, seed=1))

    # Mean combine = word2vec's SHIPPED server logic; non-"sum" combines
    # always take the gathered route (the dense-collective route would
    # otherwise claim every small additive table and bypass hot_rows —
    # which is exactly where hot_ids="auto" spent two rounds dark).
    def run(hot):
        store = ParamStore(
            mesh, [TableSpec("t", R, D, hot_ids=hot).zeros_init()])
        tr = Trainer(mesh, store, Pusher(),
                     server_logic=ServerLogic(combine="mean"),
                     config=TrainerConfig(donate=False))
        t, ls = tr.init_state(jax.random.key(0))
        for c in chunks:
            t, ls, _ = tr.run_chunk(t, ls, c, jax.random.key(1))
        return store.dump_model("t")[1]

    from fps_tpu import ops
    from fps_tpu.ops import pallas_kernels

    # Count packed-kernel invocations at TRACE time (scatter_add imports it
    # per call, so patching the module attribute intercepts the route).
    calls = {"packed": 0}
    real_packed = pallas_kernels.scatter_add_packed_pallas

    def counting_packed(*args, **kwargs):
        calls["packed"] += 1
        return real_packed(*args, **kwargs)

    monkeypatch.setattr(pallas_kernels, "scatter_add_packed_pallas",
                        counting_packed)
    old = ops.get_backend()
    ops.set_backend("pallas")  # interpret-mode kernels on the CPU mesh
    try:
        got_auto = run("auto")
        assert calls["packed"] > 0, (
            "auto never routed through the packed kernel")
        # The negative claim must run INSIDE the pallas window too: with
        # the backend restored to CPU "auto", every packed route is off
        # regardless of hot_ids and the assert would be vacuous.
        calls["packed"] = 0
        want = run(0)
        assert calls["packed"] == 0  # hot_ids=0 must NOT take packed route
    finally:
        ops.set_backend(old)
    np.testing.assert_allclose(got_auto, want, rtol=3e-3, atol=3e-5)
    assert np.abs(want).sum() > 0  # the workload actually moved the table


# ---------------------------------------------------------------------------
# Dim-1 (scalar table) lane-packed kernels — the PA/logreg weight-vector
# shape, where XLA pays ~8 ns per scalar moved (measured dedup-safe on
# chip: dim1 kernels 2.8 ms vs XLA 7.7/8.2 ms at R=47k, B=2^20).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("R,B", [(1000, 5000), (128, 300), (47_236, 4096),
                                 (130, 513)])
def test_dim1_scatter_parity(R, B):
    from fps_tpu.ops.pallas_kernels import scatter_add_dim1_pallas

    rng = np.random.default_rng(1)
    table = rng.normal(0, 1, (R, 1)).astype(np.float32)
    # include drop sentinels and out-of-range ids
    ids = rng.integers(-3, R + 200, B).astype(np.int32)
    deltas = rng.normal(0, 1, (B, 1)).astype(np.float32)
    ref = table.copy()
    keep = (ids >= 0) & (ids < R)
    np.add.at(ref[:, 0], ids[keep], deltas[keep, 0])
    got = np.asarray(scatter_add_dim1_pallas(
        jnp.asarray(table), jnp.asarray(ids), jnp.asarray(deltas),
        interpret=True,
    ))
    # hi+lo bf16 contract: ~16 mantissa bits per delta.
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("R,B", [(1000, 5000), (128, 300), (47_236, 4096)])
def test_dim1_gather_parity(R, B):
    from fps_tpu.ops.pallas_kernels import gather_rows_dim1_pallas

    rng = np.random.default_rng(2)
    table = rng.normal(0, 1, (R, 1)).astype(np.float32)
    ids = rng.integers(-3, R + 200, B).astype(np.int32)
    ref = np.where(((ids >= 0) & (ids < R))[:, None],
                   table[np.clip(ids, 0, R - 1)], 0.0)
    got = np.asarray(gather_rows_dim1_pallas(
        jnp.asarray(table), jnp.asarray(ids), interpret=True))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_dim1_routing_conditions(pallas_backend):
    """_route_dim1: only scalar tables below the measured row cap at large
    batch route to the dim-1 kernels; everything else keeps its path."""
    assert ops._route_dim1(47_236, 1, 1 << 20)
    assert not ops._route_dim1(47_236, 2, 1 << 20)      # not scalar
    assert not ops._route_dim1(1_000_000, 1, 1 << 20)   # row cap
    assert not ops._route_dim1(47_236, 1, 1024)         # batch floor
    prev = ops.get_backend()
    ops.set_backend("xla")
    try:
        assert not ops._route_dim1(47_236, 1, 1 << 20)  # forced xla
    finally:
        ops.set_backend(prev)


def test_dim1_routed_scatter_and_gather_through_dispatcher(pallas_backend):
    """The dispatcher-level ops with a routed dim-1 shape must match the
    XLA backend to the hi+lo precision contract."""
    rng = np.random.default_rng(3)
    R, B = 9_000, 16_384
    table = rng.normal(0, 1, (R, 1)).astype(np.float32)
    ids = rng.integers(-1, R, B).astype(np.int32)
    deltas = rng.normal(0, 1e-2, (B, 1)).astype(np.float32)
    assert ops._route_dim1(R, 1, B)

    got_s = np.asarray(ops.scatter_add(jnp.asarray(table), jnp.asarray(ids),
                                       jnp.asarray(deltas)))
    ref_s = table.copy()
    keep = ids >= 0
    np.add.at(ref_s[:, 0], ids[keep], deltas[keep, 0])
    np.testing.assert_allclose(got_s, ref_s, rtol=2e-4, atol=2e-4)

    got_g = np.asarray(ops.gather_rows(jnp.asarray(table), jnp.asarray(ids)))
    ref_g = np.where((ids >= 0)[:, None], table[np.clip(ids, 0, None)], 0.0)
    np.testing.assert_allclose(got_g, ref_g, rtol=2e-4, atol=2e-4)


def test_gather_exact_overrides_lossy_routes(pallas_backend):
    """``exact=True`` must take the bit-exact XLA gather even on shapes the
    dim-1 hi+lo-bf16 route would claim — the read-only escape hatch that
    keeps eval/export pulls out of training's precision concession."""
    rng = np.random.default_rng(7)
    R, B = 9_000, 16_384
    # Values with >16 significant mantissa bits so the hi+lo bf16 pair
    # visibly diverges from the exact read.
    table = (rng.normal(0, 1, (R, 1)) * (1 + 1e-7)).astype(np.float32)
    ids = rng.integers(-1, R, B).astype(np.int32)
    assert ops._route_dim1(R, 1, B)

    ref = np.where((ids >= 0)[:, None], table[np.clip(ids, 0, None)], 0.0)
    got_exact = np.asarray(
        ops.gather_rows(jnp.asarray(table), jnp.asarray(ids), exact=True))
    # Bit-exact, not just close.
    np.testing.assert_array_equal(got_exact, ref)

    # Sanity: the routed (non-exact) read on this shape is NOT bit-exact
    # under the forced-pallas backend, which is the whole reason the
    # override exists.
    got_routed = np.asarray(
        ops.gather_rows(jnp.asarray(table), jnp.asarray(ids)))
    assert not np.array_equal(got_routed, ref)
    np.testing.assert_allclose(got_routed, ref, rtol=2e-4, atol=2e-4)


def test_pull_exact_plumbs_through_both_routes(devices8):
    """store.pull(exact=True) must produce bit-exact reads on both the
    gathered and dense collective routes."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from fps_tpu.core.store import SHARD_AXIS, pull
    from fps_tpu.parallel.mesh import make_ps_mesh

    prev = ops.get_backend()
    ops.set_backend("pallas")
    try:
        mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
        S, R = 4, 36_000
        rps = R // S
        rng = np.random.default_rng(11)
        full = (rng.normal(0, 1, (R, 1)) * (1 + 1e-7)).astype(np.float32)
        # owner-major physical layout: shard s holds ids with id % S == s
        shards = np.stack([full[s::S, 0] for s in range(S)])  # (S, rps)
        ids = rng.integers(0, R, 16_384).astype(np.int32)

        for dense in (False, True):
            def f(local, i):
                return pull(local.reshape(-1)[:, None], i, num_shards=S,
                            dense=dense, exact=True)

            got = jax.jit(shard_map(
                f, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P()), out_specs=P(SHARD_AXIS),
            ))(jnp.asarray(shards), jnp.asarray(ids))
            # One (B, 1) answer block per shard-position worker; every
            # worker asked for the same ids, so each must be bit-exact.
            for blk in np.split(np.asarray(got), S):
                np.testing.assert_array_equal(
                    blk, full[ids], err_msg=f"dense={dense}")
    finally:
        ops.set_backend(prev)


@pytest.mark.parametrize("R,H,B,q", [(47_236, 2048, 12_288, 8192),
                                     (9_000, 1024, 6_000, 2048)])
def test_head_prefix_scatter_and_gather_parity(pallas_backend, R, H, B, q):
    """head_prefix routing: ids[:q] in [0, H) ∪ {-1} ride the head-only
    kernel; results match plain numpy to the hi+lo contract."""
    rng = np.random.default_rng(7)
    table = rng.normal(0, 1, (R, 1)).astype(np.float32)
    head_ids = rng.integers(0, H, q).astype(np.int32)
    head_ids[::11] = -1  # dropped slots inside the guaranteed prefix
    tail_ids = rng.integers(-1, R, B - q).astype(np.int32)
    ids = np.concatenate([head_ids, tail_ids])
    deltas = rng.normal(0, 1, (B, 1)).astype(np.float32)
    assert ops._route_head_prefix(R, 1, q, H, np.float32)

    got = np.asarray(ops.scatter_add(
        jnp.asarray(table), jnp.asarray(ids), jnp.asarray(deltas),
        hot_rows=H, head_prefix=q,
    ))
    ref = table.copy()
    keep = ids >= 0
    np.add.at(ref[:, 0], ids[keep], deltas[keep, 0])
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    got_g = np.asarray(ops.gather_rows(
        jnp.asarray(table), jnp.asarray(ids), hot_rows=H, head_prefix=q))
    ref_g = np.where(keep[:, None], table[np.clip(ids, 0, None)], 0.0)
    np.testing.assert_allclose(got_g, ref_g, rtol=2e-4, atol=2e-4)


def test_head_prefix_routing_conditions(pallas_backend):
    f32 = np.float32
    assert ops._route_head_prefix(47_236, 1, 8192, 2048, f32)
    assert not ops._route_head_prefix(47_236, 1, 1024, 2048, f32)  # short
    assert not ops._route_head_prefix(47_236, 2, 8192, 2048, f32)  # D!=1
    assert not ops._route_head_prefix(47_236, 1, 8192, 0, f32)     # no head
    assert not ops._route_head_prefix(4_096, 1, 8192, 2048, f32)   # H~R
