"""Mesh construction (fps_tpu.parallel.mesh): shape factoring and the
non-divisible error paths — previously only exercised implicitly through
the example CLIs."""

from __future__ import annotations

import pytest

from fps_tpu.parallel.mesh import (
    DATA_AXIS,
    SHARD_AXIS,
    default_mesh_shape,
    make_ps_mesh,
)


@pytest.mark.parametrize("n, want", [
    (1, (1, 1)),
    (2, (1, 2)),
    (4, (2, 2)),
    (6, (2, 3)),
    (7, (1, 7)),      # prime: all devices onto the shard axis
    (8, (2, 4)),
    (12, (3, 4)),
    (16, (4, 4)),
    (24, (4, 6)),
])
def test_default_mesh_shape_factoring(n, want):
    assert default_mesh_shape(n) == want


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 12, 30, 64, 100])
def test_default_mesh_shape_invariants(n):
    """Covers the full factorization contract: the shape covers every
    device and the shard axis (HBM, the scarce resource) never gets the
    smaller side."""
    d, s = default_mesh_shape(n)
    assert d * s == n
    assert s >= d >= 1


def test_make_ps_mesh_shapes_and_axes(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8)
    assert mesh.axis_names == (DATA_AXIS, SHARD_AXIS)
    assert mesh.shape == {DATA_AXIS: 2, SHARD_AXIS: 4}
    # num_shards defaulted from the device count.
    mesh = make_ps_mesh(num_data=2, devices=devices8)
    assert mesh.shape[SHARD_AXIS] == 4


def test_make_ps_mesh_non_divisible_raises(devices8):
    with pytest.raises(ValueError, match="not divisible"):
        make_ps_mesh(num_data=3, devices=devices8)


def test_make_ps_mesh_non_covering_raises(devices8):
    with pytest.raises(ValueError, match="does not cover"):
        make_ps_mesh(num_shards=3, num_data=2, devices=devices8)
    with pytest.raises(ValueError, match="does not cover"):
        make_ps_mesh(num_shards=16, num_data=1, devices=devices8)
