"""tools/obs_report.py smoke (ISSUE 2 acceptance + CI satellite): a
2-chunk logreg `fit_stream` run with --obs-dir produces a JSONL event log
+ run journal that the report tool renders into a digest with per-phase
timings, per-table health totals, and incident events."""

import importlib.util
import json
import os

import pytest


def _load_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "obs_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_digest_from_logreg_run(devices8, capsys, tmp_path):
    from fps_tpu.examples import logreg_ssp

    obs_dir = str(tmp_path / "obs")
    rc = logreg_ssp.main([
        "--epochs", "1", "--local-batch", "32", "--steps-per-chunk", "4",
        "--num-examples", "2000", "--num-features", "500",
        "--sync-every", "2", "--guard", "observe",
        "--checkpoint-dir", str(tmp_path / "ck"), "--checkpoint-every", "2",
        "--obs-dir", obs_dir, "--obs-watchdog-s", "300",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    events = [json.loads(l) for l in out.splitlines()]
    assert any(e["event"] == "obs" and e["dir"] == obs_dir for e in events)

    report = _load_report()
    digest = report.render_digest(obs_dir)
    # Required shape (REQUIRED_FIELDS is the tool's own contract).
    for field in report.REQUIRED_FIELDS:
        assert field in digest, field
    assert digest["chunks"] == 2
    assert digest["examples"] > 0
    assert digest["run_complete"] is True
    assert len(digest["run_ids"]) == 1 and digest["processes"] == [0]
    # Per-phase timings: every driver phase observed, with real time.
    for phase in ("ingest", "place", "dispatch", "host_sync", "checkpoint"):
        assert phase in digest["phase_seconds"], phase
        assert digest["phase_seconds"][phase]["n"] >= 1
    assert digest["phase_seconds"]["dispatch"]["total_s"] > 0
    # Per-table health totals: the guard watched (clean run => zeros).
    assert digest["health"] == {
        "weights": {"nonfinite": 0, "norm": 0, "masked": 0}
    }
    assert digest["checkpoint_saves"] >= 1
    assert digest["watchdog_stalls"] == 0 and digest["incidents"] == {}
    assert digest["wall_span_s"] >= 0

    # main() prints the digest as one JSON line.
    assert report.main([obs_dir]) == 0
    line = capsys.readouterr().out.strip()
    assert json.loads(line)["chunks"] == 2

    # --json: the pinned machine contract — identical payload, compact,
    # versioned schema field, strict JSON (digest_json is the importable
    # form fps_tpu/obs/fleet.py consumers use).
    assert report.main([obs_dir, "--json"]) == 0
    machine = json.loads(capsys.readouterr().out.strip())
    assert machine["schema"] == report.DIGEST_SCHEMA_VERSION
    assert machine == report.digest_json(obs_dir)
    # The causal-trace anchor rides the journal: the run_start carries
    # trace/span ids (fps_tpu.obs.trace) without perturbing the digest.
    journal = os.path.join(obs_dir, "journal-p0.jsonl")
    start = json.loads(open(journal).readline())
    assert start["event"] == "run_start" and start["span_id"]


def test_obs_report_surfaces_incidents(tmp_path):
    """Rollback / stall / escalation / checkpoint-fallback events written
    by a run land in the digest's incident lists (synthetic event files —
    the report tool is a pure JSONL consumer)."""
    report = _load_report()
    d = str(tmp_path)
    with open(os.path.join(d, "events-p0.jsonl"), "w") as f:
        for rec in [
            {"kind": "metric", "t": 1.0, "name": "driver.chunks",
             "mtype": "counter", "value": 1},
            {"kind": "metric", "t": 1.2, "name": "rollback.quarantined",
             "mtype": "counter", "value": 1},
            {"kind": "event", "t": 1.2, "event": "rollback", "index": 4,
             "total": 1, "budget": 8},
            {"kind": "event", "t": 1.3, "event": "chunk", "index": 4,
             "quarantined": True, "phases": {}},
            {"kind": "event", "t": 1.4, "event": "stall", "what": "chunk",
             "index": 5, "deadline_s": 2.0},
            {"kind": "event", "t": 1.5, "event": "guard_escalated",
             "index": 5, "what": "chunk", "poison_rows": 12},
            {"kind": "event", "t": 1.6, "event": "checkpoint_fallback",
             "step": 3, "error": "boom"},
            "garbage that is not json",  # torn tail line must not break it
        ]:
            f.write(rec if isinstance(rec, str) else json.dumps(rec))
            f.write("\n")
    # Journal holds: a duplicate of the rollback (same record fanned to
    # both sinks — must dedupe) plus a stall the buffered event sink LOST
    # (SIGKILL before flush) — must still surface in the digest.
    with open(os.path.join(d, "journal-p0.jsonl"), "w") as f:
        for rec in [
            {"kind": "event", "t": 0.5, "event": "run_start",
             "run_id": "r", "process": 0},
            {"kind": "event", "t": 1.2, "event": "rollback", "index": 4,
             "total": 1, "budget": 8},
            {"kind": "event", "t": 1.7, "event": "stall", "what": "chunk",
             "index": 9, "deadline_s": 2.0},
        ]:
            f.write(json.dumps(rec) + "\n")
    digest = report.render_digest(d)
    assert digest["quarantined"] == [4]
    assert digest["rollbacks"] == 1
    assert [i["index"] for i in digest["incidents"]["rollback"]] == [4]
    # The journal-only stall survived; the duplicated rollback didn't fork.
    assert sorted(i["index"] for i in digest["incidents"]["stall"]) == [5, 9]
    assert digest["incidents"]["guard_escalated"][0]["poison_rows"] == 12
    assert digest["incidents"]["checkpoint_fallback"][0]["step"] == 3
    assert digest["run_complete"] is False  # no journal run_end


def test_obs_report_empty_dir_errors(tmp_path):
    report = _load_report()
    with pytest.raises(FileNotFoundError):
        report.render_digest(str(tmp_path))
    assert report.main([str(tmp_path)]) == 2
