"""tools/obs_report.py smoke (ISSUE 2 acceptance + CI satellite): a
2-chunk logreg `fit_stream` run with --obs-dir produces a JSONL event log
+ run journal that the report tool renders into a digest with per-phase
timings, per-table health totals, and incident events."""

import importlib.util
import json
import os

import pytest


def _load_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "obs_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_digest_from_logreg_run(devices8, capsys, tmp_path):
    from fps_tpu.examples import logreg_ssp

    obs_dir = str(tmp_path / "obs")
    rc = logreg_ssp.main([
        "--epochs", "1", "--local-batch", "32", "--steps-per-chunk", "4",
        "--num-examples", "2000", "--num-features", "500",
        "--sync-every", "2", "--guard", "observe",
        "--checkpoint-dir", str(tmp_path / "ck"), "--checkpoint-every", "2",
        "--obs-dir", obs_dir, "--obs-watchdog-s", "300",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    events = [json.loads(l) for l in out.splitlines()]
    assert any(e["event"] == "obs" and e["dir"] == obs_dir for e in events)

    report = _load_report()
    digest = report.render_digest(obs_dir)
    # Required shape (REQUIRED_FIELDS is the tool's own contract).
    for field in report.REQUIRED_FIELDS:
        assert field in digest, field
    assert digest["chunks"] == 2
    assert digest["examples"] > 0
    assert digest["run_complete"] is True
    assert len(digest["run_ids"]) == 1 and digest["processes"] == [0]
    # Per-phase timings: every driver phase observed, with real time.
    for phase in ("ingest", "place", "dispatch", "host_sync", "checkpoint"):
        assert phase in digest["phase_seconds"], phase
        assert digest["phase_seconds"][phase]["n"] >= 1
    assert digest["phase_seconds"]["dispatch"]["total_s"] > 0
    # Per-table health totals: the guard watched (clean run => zeros).
    assert digest["health"] == {
        "weights": {"nonfinite": 0, "norm": 0, "masked": 0}
    }
    assert digest["checkpoint_saves"] >= 1
    assert digest["watchdog_stalls"] == 0 and digest["incidents"] == {}
    assert digest["wall_span_s"] >= 0

    # main() prints the digest as one JSON line.
    assert report.main([obs_dir]) == 0
    line = capsys.readouterr().out.strip()
    assert json.loads(line)["chunks"] == 2

    # --json: the pinned machine contract — identical payload, compact,
    # versioned schema field, strict JSON (digest_json is the importable
    # form fps_tpu/obs/fleet.py consumers use).
    assert report.main([obs_dir, "--json"]) == 0
    machine = json.loads(capsys.readouterr().out.strip())
    assert machine["schema"] == report.DIGEST_SCHEMA_VERSION
    assert machine == report.digest_json(obs_dir)
    # The causal-trace anchor rides the journal: the run_start carries
    # trace/span ids (fps_tpu.obs.trace) without perturbing the digest.
    journal = os.path.join(obs_dir, "journal-p0.jsonl")
    start = json.loads(open(journal).readline())
    assert start["event"] == "run_start" and start["span_id"]


def test_obs_report_surfaces_incidents(tmp_path):
    """Rollback / stall / escalation / checkpoint-fallback events written
    by a run land in the digest's incident lists (synthetic event files —
    the report tool is a pure JSONL consumer)."""
    report = _load_report()
    d = str(tmp_path)
    with open(os.path.join(d, "events-p0.jsonl"), "w") as f:
        for rec in [
            {"kind": "metric", "t": 1.0, "name": "driver.chunks",
             "mtype": "counter", "value": 1},
            {"kind": "metric", "t": 1.2, "name": "rollback.quarantined",
             "mtype": "counter", "value": 1},
            {"kind": "event", "t": 1.2, "event": "rollback", "index": 4,
             "total": 1, "budget": 8},
            {"kind": "event", "t": 1.3, "event": "chunk", "index": 4,
             "quarantined": True, "phases": {}},
            {"kind": "event", "t": 1.4, "event": "stall", "what": "chunk",
             "index": 5, "deadline_s": 2.0},
            {"kind": "event", "t": 1.5, "event": "guard_escalated",
             "index": 5, "what": "chunk", "poison_rows": 12},
            {"kind": "event", "t": 1.6, "event": "checkpoint_fallback",
             "step": 3, "error": "boom"},
            "garbage that is not json",  # torn tail line must not break it
        ]:
            f.write(rec if isinstance(rec, str) else json.dumps(rec))
            f.write("\n")
    # Journal holds: a duplicate of the rollback (same record fanned to
    # both sinks — must dedupe) plus a stall the buffered event sink LOST
    # (SIGKILL before flush) — must still surface in the digest.
    with open(os.path.join(d, "journal-p0.jsonl"), "w") as f:
        for rec in [
            {"kind": "event", "t": 0.5, "event": "run_start",
             "run_id": "r", "process": 0},
            {"kind": "event", "t": 1.2, "event": "rollback", "index": 4,
             "total": 1, "budget": 8},
            {"kind": "event", "t": 1.7, "event": "stall", "what": "chunk",
             "index": 9, "deadline_s": 2.0},
        ]:
            f.write(json.dumps(rec) + "\n")
    digest = report.render_digest(d)
    assert digest["quarantined"] == [4]
    assert digest["rollbacks"] == 1
    assert [i["index"] for i in digest["incidents"]["rollback"]] == [4]
    # The journal-only stall survived; the duplicated rollback didn't fork.
    assert sorted(i["index"] for i in digest["incidents"]["stall"]) == [5, 9]
    assert digest["incidents"]["guard_escalated"][0]["poison_rows"] == 12
    assert digest["incidents"]["checkpoint_fallback"][0]["step"] == 3
    assert digest["run_complete"] is False  # no journal run_end


def test_obs_report_raw_speed_sections(tmp_path):
    """ISSUE 20 telemetry lands in the digest: the checkpoint
    dump/capture split, the auto-K gauge, and the adaptive-prefetch
    raise counter (synthetic event files — pure JSONL consumer)."""
    report = _load_report()
    d = str(tmp_path)
    with open(os.path.join(d, "events-p0.jsonl"), "w") as f:
        for rec in [
            {"kind": "metric", "t": 1.0, "name": "checkpoint.dump_seconds",
             "mtype": "histogram", "value": 0.001},
            {"kind": "metric", "t": 1.1, "name": "checkpoint.dump_seconds",
             "mtype": "histogram", "value": 0.003},
            {"kind": "metric", "t": 1.2,
             "name": "checkpoint.capture_seconds",
             "mtype": "histogram", "value": 0.05},
            {"kind": "metric", "t": 1.3, "name": "megastep.auto_k",
             "mtype": "gauge", "value": 12.0},
            {"kind": "metric", "t": 1.4,
             "name": "prefetch.depth_adjustments",
             "mtype": "counter", "value": 3},
        ]:
            f.write(json.dumps(rec) + "\n")
    digest = report.render_digest(d)
    ck = digest["checkpoint"]
    assert ck["dump"]["n"] == 2
    assert ck["dump"]["total_s"] == pytest.approx(0.004)
    assert ck["dump"]["max_s"] == pytest.approx(0.003)
    assert ck["capture"] == {"n": 1, "total_s": 0.05, "mean_s": 0.05,
                             "p99_s": 0.05, "max_s": 0.05}
    assert digest["megastep"]["auto_k"] == 12.0
    assert digest["prefetch"]["depth_adjustments"] == 3
    # No samples at all still yields the full shape (nulls, n=0).
    assert report._seconds_stats([]) == {
        "n": 0, "total_s": None, "mean_s": None, "p99_s": None,
        "max_s": None}


def test_obs_report_recovery_slo_breach(tmp_path):
    """--recovery-slo-s turns a late paired restart into a
    recovery_slo_breach incident and annotates the recovery section;
    without the flag the same dir reports without judging."""
    report = _load_report()
    d = str(tmp_path)
    with open(os.path.join(d, "journal-supervisor.jsonl"), "w") as f:
        for rec in [
            # Attempt 0 dies at t=10; attempt 1 first signal at t=18
            # (recovery 8s). Attempt 1 dies at t=30; attempt 2 first
            # signal at t=90 (recovery 60s — over a 20s bound).
            {"kind": "event", "t": 10.0, "event": "attempt_end",
             "attempt": 0},
            {"kind": "event", "t": 18.0, "event": "attempt_first_signal",
             "attempt": 1},
            {"kind": "event", "t": 30.0, "event": "attempt_end",
             "attempt": 1},
            {"kind": "event", "t": 90.0, "event": "attempt_first_signal",
             "attempt": 2},
        ]:
            f.write(json.dumps(rec) + "\n")

    plain = report.render_digest(d)
    assert plain["recovery"]["times_s"] == [8.0, 60.0]
    assert plain["recovery"]["slo_s"] is None
    assert plain["recovery"]["breaches"] == 0
    assert "recovery_slo_breach" not in plain["incidents"]

    judged = report.render_digest(d, recovery_slo_s=20.0)
    assert judged["recovery"]["slo_s"] == 20.0
    assert judged["recovery"]["breaches"] == 1
    [breach] = judged["incidents"]["recovery_slo_breach"]
    assert breach["time_to_recovered_s"] == 60.0
    assert breach["slo_s"] == 20.0

    # The CLI spelling reaches the same path.
    assert report.main([d, "--recovery-slo-s", "20"]) == 0


def test_obs_report_empty_dir_errors(tmp_path):
    report = _load_report()
    with pytest.raises(FileNotFoundError):
        report.render_digest(str(tmp_path))
    assert report.main([str(tmp_path)]) == 2
