"""jax-free training stand-in for the supervisor's tier-1 smoke tests.

Simulates the supervised-child contract at ~100x real speed: beats the
supervisor heartbeat per "chunk", persists its progress ("checkpoint")
after each chunk, resumes from it on restart, honors the carried
quarantine set, and can misbehave on demand:

* ``--wedge-at K``  — on the FIRST attempt only (marker file), stop
  beating at chunk K and sleep forever: the deadline-abort path.
* ``--wedge-mode sigstop`` — same, but SIGSTOP the whole process instead
  (the queued-SIGTERM case: only the supervisor's SIGKILL escalation can
  clear it).
* ``--crash-at K`` — exit(3) at chunk K on EVERY attempt whose quarantine
  set does not contain K: the deterministic-poison crash loop the
  supervisor must break by quarantining K.

Usage: python _supervised_stub.py --dir D --chunks N [flags]
Writes ``result.json`` ({"done": N, "ran": [...]}) into --dir on success.

Loads fps_tpu/supervise/child.py by file path (no fps_tpu package import,
so no jax) — the same trick tools/supervise.py uses for the parent side.
"""

import argparse
import importlib.util
import json
import os
import signal
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_child_module():
    path = os.path.join(_ROOT, "fps_tpu", "supervise", "child.py")
    spec = importlib.util.spec_from_file_location("_fps_child", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # 3.10 needs the registration pre-exec
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--chunks", type=int, default=8)
    ap.add_argument("--chunk-s", type=float, default=0.05)
    ap.add_argument("--wedge-at", type=int, default=None)
    ap.add_argument("--wedge-mode", default="sleep",
                    choices=["sleep", "sigstop"])
    ap.add_argument("--wedge-always", action="store_true",
                    help="wedge on EVERY attempt (no marker) — the "
                         "unrecoverable-hang case for wall-deadline tests")
    ap.add_argument("--trap-term", action="store_true",
                    help="install a SIGTERM handler that exits 0 (a "
                         "graceful-shutdown child): an ABORTED attempt "
                         "ending rc=0 must still not count as success")
    ap.add_argument("--crash-at", type=int, default=None)
    args = ap.parse_args()

    if args.trap_term:
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))

    child = _load_child_module()
    hb = child.from_env()
    quarantined = child.quarantined_from_env()
    os.makedirs(args.dir, exist_ok=True)
    progress_path = os.path.join(args.dir, "progress.json")
    marker = os.path.join(args.dir, "wedge.done")

    start = 0
    try:
        with open(progress_path, encoding="utf-8") as f:
            start = int(json.load(f)["next"])
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        pass

    ran = []
    for i in range(start, args.chunks):
        if hb is not None:
            hb.beat(index=i, attempt=child.attempt_from_env())
        if i in quarantined:
            continue  # carried quarantine: consume the index, skip the work
        if args.crash_at is not None and i == args.crash_at:
            print(f"stub: deterministic crash at chunk {i}", flush=True)
            return 3
        if args.wedge_at is not None and i == args.wedge_at \
                and (args.wedge_always or not os.path.exists(marker)):
            open(marker, "w").close()  # wedge once; the restart proceeds
            print(f"stub: wedging ({args.wedge_mode}) at chunk {i}",
                  flush=True)
            if args.wedge_mode == "sigstop":
                os.kill(os.getpid(), signal.SIGSTOP)
            while True:  # sleep-forever wedge (also post-SIGCONT fallthrough)
                time.sleep(3600)
        time.sleep(args.chunk_s)
        ran.append(i)
        with open(progress_path, "w", encoding="utf-8") as f:
            json.dump({"next": i + 1}, f)  # the stub's "checkpoint"

    with open(os.path.join(args.dir, "result.json"), "w",
              encoding="utf-8") as f:
        json.dump({"done": args.chunks, "ran": ran,
                   "attempt": child.attempt_from_env()}, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
