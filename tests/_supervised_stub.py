"""jax-free training stand-in for the supervisor's tier-1 smoke tests.

Simulates the supervised-child contract at ~100x real speed: beats the
supervisor heartbeat per "chunk", persists its progress ("checkpoint")
after each chunk, resumes from it on restart, honors the carried
quarantine set, and can misbehave on demand:

* ``--wedge-at K``  — on the FIRST attempt only (marker file), stop
  beating at chunk K and sleep forever: the deadline-abort path.
* ``--wedge-mode sigstop`` — same, but SIGSTOP the whole process instead
  (the queued-SIGTERM case: only the supervisor's SIGKILL escalation can
  clear it).
* ``--crash-at K`` — exit(3) at chunk K on EVERY attempt whose quarantine
  set does not contain K: the deterministic-poison crash loop the
  supervisor must break by quarantining K.
* ``--crash-until-file F`` — exit(3) at startup (before any beat) until
  ``F`` exists: the flapping member the elastic pod must EVICT and, once
  the operator clears the fault (touches F), re-admit.
* ``--misbehave-host H`` — only misbehave when this process runs as pod
  member ``H`` (``FPS_TPU_POD_HOST``): one shared pod command template
  can then poison exactly one member.

Pod contract (``fps_tpu/supervise/pod.py``): besides ``progress.json``
the stub publishes tiny zip "snapshots" named like real checkpoints
(``ckpt_%012d.npz`` — zipfile members carry CRCs, so the stdlib-only pod
coordinator verifies them exactly like real npz snapshots), resumes from
the pod-commanded common step (``FPS_TPU_POD_STEP``), and refuses to
publish behind a pod fence (``pod_fence.json`` vs ``FPS_TPU_POD_EPOCH``)
— exiting 9 with a ``stale epoch`` marker, the stub-speed analog of
``fps_tpu.core.checkpoint``'s ``StaleEpochError``.

Usage: python _supervised_stub.py --dir D --chunks N [flags]
Writes ``result.json`` ({"done": N, "ran": [...]}) into --dir on success.

Loads fps_tpu/supervise/child.py by file path (no fps_tpu package import,
so no jax) — the same trick tools/supervise.py uses for the parent side.
"""

import argparse
import importlib.util
import json
import os
import signal
import sys
import time
import zipfile

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_child_module():
    path = os.path.join(_ROOT, "fps_tpu", "supervise", "child.py")
    spec = importlib.util.spec_from_file_location("_fps_child", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # 3.10 needs the registration pre-exec
    spec.loader.exec_module(mod)
    return mod


def _publish_snapshot(child, directory: str, step: int, epoch,
                      keep: int = 3) -> None:
    """Checkpoint-shaped publish: fence check, tmp write, atomic rename,
    keep-N retention — the control-plane surface of a real save."""
    ok, min_epoch = child.fence_allows(directory, epoch)
    if not ok:
        print(f"stub: stale epoch {epoch} < fence {min_epoch}, "
              "refusing to publish", flush=True)
        sys.exit(9)
    name = f"ckpt_{step:012d}.npz"
    tmp = os.path.join(directory, name + ".stub.tmp")
    with zipfile.ZipFile(tmp, "w") as z:
        z.writestr("progress.json",
                   json.dumps({"step": step, "epoch": epoch}))
    os.replace(tmp, os.path.join(directory, name))
    steps = sorted(
        int(f[5:17]) for f in os.listdir(directory)
        if f.startswith("ckpt_") and f.endswith(".npz") and len(f) == 21
    )
    for s in steps[:-keep]:
        try:
            os.remove(os.path.join(directory, f"ckpt_{s:012d}.npz"))
        except OSError:
            pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--chunks", type=int, default=8)
    ap.add_argument("--chunk-s", type=float, default=0.05)
    ap.add_argument("--wedge-at", type=int, default=None)
    ap.add_argument("--wedge-mode", default="sleep",
                    choices=["sleep", "sigstop"])
    ap.add_argument("--wedge-always", action="store_true",
                    help="wedge on EVERY attempt (no marker) — the "
                         "unrecoverable-hang case for wall-deadline tests")
    ap.add_argument("--trap-term", action="store_true",
                    help="install a SIGTERM handler that exits 0 (a "
                         "graceful-shutdown child): an ABORTED attempt "
                         "ending rc=0 must still not count as success")
    ap.add_argument("--crash-at", type=int, default=None)
    ap.add_argument("--crash-until-file", default=None,
                    help="exit(3) at startup until this file exists")
    ap.add_argument("--misbehave-host", default=None,
                    help="apply wedge/crash flags only when running as "
                         "this pod member")
    args = ap.parse_args()

    if args.trap_term:
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))

    child = _load_child_module()
    hb = child.from_env()
    quarantined = child.quarantined_from_env()
    pod = child.pod_env()
    os.makedirs(args.dir, exist_ok=True)
    progress_path = os.path.join(args.dir, "progress.json")
    marker = os.path.join(args.dir, "wedge.done")

    misbehave = (args.misbehave_host is None
                 or pod["host"] == args.misbehave_host)
    if (misbehave and args.crash_until_file is not None
            and not os.path.exists(args.crash_until_file)):
        print("stub: crash-until-file fault active, dying at startup",
              flush=True)
        return 3

    start = 0
    if pod["step"] is not None:
        # Pod-commanded common restart step: every member resumes HERE,
        # not from its own (possibly different) local progress.
        start = pod["step"]
    else:
        try:
            with open(progress_path, encoding="utf-8") as f:
                start = int(json.load(f)["next"])
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            pass

    ran = []
    for i in range(start, args.chunks):
        if hb is not None:
            hb.beat(index=i, attempt=child.attempt_from_env())
        if i in quarantined:
            continue  # carried quarantine: consume the index, skip the work
        if misbehave and args.crash_at is not None and i == args.crash_at:
            print(f"stub: deterministic crash at chunk {i}", flush=True)
            return 3
        if misbehave and args.wedge_at is not None and i == args.wedge_at \
                and (args.wedge_always or not os.path.exists(marker)):
            open(marker, "w").close()  # wedge once; the restart proceeds
            print(f"stub: wedging ({args.wedge_mode}) at chunk {i}",
                  flush=True)
            if args.wedge_mode == "sigstop":
                os.kill(os.getpid(), signal.SIGSTOP)
            while True:  # sleep-forever wedge (also post-SIGCONT fallthrough)
                time.sleep(3600)
        time.sleep(args.chunk_s)
        ran.append(i)
        with open(progress_path, "w", encoding="utf-8") as f:
            json.dump({"next": i + 1}, f)  # the stub's "checkpoint"
        _publish_snapshot(child, args.dir, i + 1, pod["epoch"])

    with open(os.path.join(args.dir, "result.json"), "w",
              encoding="utf-8") as f:
        json.dump({"done": args.chunks, "ran": ran,
                   "attempt": child.attempt_from_env(),
                   "pod": pod}, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
