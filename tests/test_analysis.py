"""The program contract auditor: parser, pass suite, Trainer hook.

Two altitudes of evidence:

* **Seeded mutations** — a toy StableHLO module (written in the exact
  textual forms jax 0.4.x emits, sampled from a real lowered MF step)
  is deliberately broken one contract at a time — extra psum, un-donated
  table, widened dtype, host callback, missing reconcile psum — and the
  corresponding pass (and ONLY that pass) must report the break. No pass
  is allowed to be vacuous.
* **Real programs** — the MF step program lowered on the 8-device mesh
  must parse non-vacuously (donated args seen, result_info paths seen,
  the 2-collective data plane profiled) and certify clean; the Trainer
  ``audit=`` hook must certify at compile time, report through the
  recorder, and raise in strict mode when the contract is violated.
"""

import dataclasses
import json

import numpy as np
import pytest

from fps_tpu.analysis import (
    Certificate,
    CollectiveBudget,
    ContractViolationError,
    DonationAudit,
    DtypeDriftDetector,
    HloProgram,
    HostTransferDetector,
    ProgramAuditor,
    ProgramContract,
    ReplicaConsistency,
    Violation,
    as_auditor,
    certify,
    collective_profile,
    contract_for_trainer,
    count_collectives,
)
from fps_tpu.analysis.hlo import float_widths, tensor_bytes

# ---------------------------------------------------------------------------
# Toy program: the textual forms are verbatim jax 0.4.x StableHLO (one
# donated table arg -> "[0]['tab']" result, one 2048B gathered pull, one
# 2048B routed push, one scalar metric psum, one singleton-group psum).
# ---------------------------------------------------------------------------

GROUPS_1X8 = "dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>"
GROUPS_8X1 = ("dense<[[0], [1], [2], [3], [4], [5], [6], [7]]> "
              ": tensor<8x1xi64>")

TOY = f'''module @jit_step attributes {{mhlo.num_partitions = 8 : i32}} {{
  func.func public @main(%arg0: tensor<64x8xf32> {{jax.buffer_donor = true, mhlo.sharding = "{{devices=[8,1]<=[8]}}"}}, %arg1: tensor<4x32xi32> {{mhlo.sharding = "{{devices=[1,8]<=[8]}}"}}, %arg2: tensor<4x32xf32> {{mhlo.sharding = "{{devices=[1,8]<=[8]}}"}}) -> (tensor<64x8xf32> {{jax.result_info = "[0]['tab']"}}, tensor<4xf32> {{jax.result_info = "[2]['n']"}}) {{
    %0 = stablehlo.custom_call @Sharding(%arg0) {{backend_config = "", mhlo.sharding = "{{devices=[8,1]<=[8]}}"}} : (tensor<64x8xf32>) -> tensor<64x8xf32>
    %1 = stablehlo.custom_call @SPMDFullToShardShape(%0) {{backend_config = "", mhlo.sharding = "{{manual}}"}} : (tensor<64x8xf32>) -> tensor<8x8xf32>
    %2 = "stablehlo.all_gather"(%1) <{{all_gather_dim = 0 : i64, channel_handle = #stablehlo.channel_handle<handle = 1, type = 1>, replica_groups = {GROUPS_1X8}, use_global_device_ids}}> : (tensor<8x8xf32>) -> tensor<64x8xf32>
    %3 = "stablehlo.all_to_all"(%2) <{{channel_handle = #stablehlo.channel_handle<handle = 2, type = 1>, concat_dimension = 0 : i64, replica_groups = {GROUPS_1X8}, split_count = 8 : i64, split_dimension = 0 : i64}}> : (tensor<8x8x8xf32>) -> tensor<8x8x8xf32>
    %4 = "stablehlo.all_reduce"(%3) <{{channel_handle = #stablehlo.channel_handle<handle = 3, type = 1>, replica_groups = {GROUPS_1X8}, use_global_device_ids}}> ({{
    ^bb0(%arg6: tensor<f32>, %arg7: tensor<f32>):
      %90 = stablehlo.add %arg6, %arg7 : tensor<f32>
      stablehlo.return %90 : tensor<f32>
    }}) : (tensor<f32>) -> tensor<f32>
    %5 = "stablehlo.all_reduce"(%4) <{{channel_handle = #stablehlo.channel_handle<handle = 4, type = 1>, replica_groups = {GROUPS_8X1}, use_global_device_ids}}> ({{
    ^bb0(%arg6: tensor<f32>, %arg7: tensor<f32>):
      %91 = stablehlo.add %arg6, %arg7 : tensor<f32>
      stablehlo.return %91 : tensor<f32>
    }}) : (tensor<f32>) -> tensor<f32>
    %6 = stablehlo.add %2, %2 : tensor<64x8xf32>
    return %6, %arg2 : tensor<64x8xf32>, tensor<4xf32>
  }}
}}
'''

# The reconcile psum (region-carrying all_reduce, 2048B payload on the
# closing line) — inserted by mutations that need a big psum present.
RECONCILE_PSUM = f'''    %7 = "stablehlo.all_reduce"(%6) <{{channel_handle = #stablehlo.channel_handle<handle = 5, type = 1>, replica_groups = {GROUPS_1X8}, use_global_device_ids}}> ({{
    ^bb0(%arg6: tensor<f32>, %arg7: tensor<f32>):
      %92 = stablehlo.add %arg6, %arg7 : tensor<f32>
      stablehlo.return %92 : tensor<f32>
    }}) : (tensor<64x8xf32>) -> tensor<64x8xf32>
'''

MARK = "    %6 = stablehlo.add"

# The base contract the unmutated toy satisfies exactly.
BASE = ProgramContract(
    name="toy", max_collectives=2, max_collective_bytes=4096,
    per_kind_max={"all_gather": 1, "all_to_all": 1},
    donated_tables=True, max_float_bits=32,
)


def _insert(extra: str) -> str:
    assert MARK in TOY
    return TOY.replace(MARK, extra + MARK)


def _pass_names(cert: Certificate) -> set:
    return {v.pass_name for v in cert.violations}


# ---------------------------------------------------------------------------
# Parser.
# ---------------------------------------------------------------------------


def test_tensor_bytes_and_float_widths():
    assert tensor_bytes("(tensor<8x8xf32>) -> tensor<64x8xf32>") == 2048
    assert tensor_bytes("tensor<4xi32>") == 16
    assert tensor_bytes("tensor<f32>") == 0  # scalar: below accounting
    assert float_widths("(tensor<8xbf16>) -> tensor<8xf32>") == [16, 32]
    assert float_widths("tensor<4xf64>") == [64]
    assert float_widths("tensor<4xi32>") == []


def test_toy_parses_ops_args_results():
    prog = HloProgram.from_text(TOY)
    kinds = [op.kind for op in prog.ops]
    assert kinds.count("custom_call") == 2
    assert kinds.count("all_gather") == 1
    assert kinds.count("all_reduce") == 2
    # @main metadata: the donated table arg and both result paths.
    assert len(prog.args) == 3
    assert prog.args[0].donated and not prog.args[1].donated
    assert [r.info for r in prog.results] == ["[0]['tab']", "[2]['n']"]
    # Replica groups parse into id tuples; the 8x1 form is 8 singletons.
    ag = prog.by_kind("all_gather")[0]
    assert ag.replica_groups == ((0, 1, 2, 3, 4, 5, 6, 7),)
    assert ag.group_size == 8
    assert prog.by_kind("all_reduce")[1].group_size == 1


def test_arg_attrs_survive_quoted_braces():
    """mhlo.sharding's quoted value contains '}' — attributes sorted
    after it (tf.aliasing_output, the donation marker some jax versions
    emit instead of jax.buffer_donor) must still be seen; a naive
    [^}]* attr match truncates inside the quote and reports a
    correctly-donated program as un-donated."""
    sig = (
        'func.func public @main('
        '%arg0: tensor<64x8xf32> {mhlo.sharding = '
        '"{devices=[8,1]<=[8]}", tf.aliasing_output = 0 : i32}, '
        '%arg1: tensor<4x32xi32> {mhlo.sharding = '
        '"{devices=[1,8]<=[8]}"}) -> '
        '(tensor<64x8xf32> {mhlo.sharding = "{devices=[8,1]<=[8]}", '
        'jax.result_info = "[0][\'tab\']"}) {'
    )
    args, results = HloProgram._parse_main(sig)
    assert [a.index for a in args] == [0, 1]
    assert args[0].donated and "tf.aliasing_output" in args[0].attrs
    assert not args[1].donated
    # Result attrs after a quoted-brace sharding are also still read.
    assert results[0].info == "[0]['tab']"


def test_collective_profile_thresholds():
    # 2 data-plane collectives: the scalar psum is sub-threshold, the
    # singleton-group psum is excluded regardless of payload.
    prof = collective_profile(TOY)
    assert [(c.kind, c.payload_bytes) for c in prof] == [
        ("all_gather", 2048), ("all_to_all", 2048)]
    assert count_collectives(TOY) == 2
    # min_bytes=0 admits the scalar psum but still not the singleton.
    assert count_collectives(TOY, min_bytes=0) == 3


def test_region_payload_from_closing_line():
    # The reconcile psum's op line names only the replica-groups
    # constant; its 2048B payload sits on the region's closing line.
    prog = HloProgram.from_text(_insert(RECONCILE_PSUM))
    big = [op for op in prog.by_kind("all_reduce")
           if op.payload_bytes >= 1024]
    assert len(big) == 1 and big[0].payload_bytes == 2048


def test_count_collectives_reexported_from_bench():
    import bench

    assert bench.count_collectives is count_collectives
    assert bench.collective_profile is collective_profile


# ---------------------------------------------------------------------------
# Seeded mutations: each break is caught by exactly the pass that owns it.
# ---------------------------------------------------------------------------


def test_toy_certifies_clean_under_base_contract():
    cert = certify(TOY, BASE, program="toy")
    assert cert.ok, [v.summary for v in cert.violations]
    assert cert.collective_count == 2
    assert cert.collective_bytes == 4096


def test_mutation_extra_psum_breaks_collective_budget():
    cert = certify(_insert(RECONCILE_PSUM), BASE)
    assert not cert.ok
    assert _pass_names(cert) == {"collective_budget"}
    # Both the count (3 > 2) and the byte (6144 > 4096) budgets fire.
    assert len(cert.violations) == 2
    assert cert.collective_count == 3


def test_mutation_per_kind_budget():
    contract = ProgramContract(per_kind_max={"all_gather": 0})
    cert = certify(TOY, contract)
    assert _pass_names(cert) == {"collective_budget"}
    assert "all_gather" in cert.violations[0].summary


def test_mutation_removed_collective_breaks_exact_budget():
    """Pinned-exact budgets (the audit tool's re-pinning workflow) fail
    on a REMOVED collective too, where a plain ceiling is blind."""
    mutated = "\n".join(l for l in TOY.splitlines()
                        if "all_to_all" not in l)
    exact = dataclasses.replace(BASE, exact_collectives=True)
    cert = certify(mutated, exact, program="mutant")
    assert not cert.ok
    assert _pass_names(cert) == {"collective_budget"}
    # Total count (1 != 2) and the all_to_all per-kind pin (0 < 1).
    assert any("differ from the pinned budget" in v.summary
               for v in cert.violations)
    assert any("fall short of the pinned per-kind" in v.summary
               for v in cert.violations)
    # The ceiling form of the same contract passes the mutant: exactly
    # the gap exact_collectives closes.
    assert certify(mutated, BASE, program="mutant").ok
    # And the unmutated program still certifies clean under exact pins.
    assert certify(TOY, exact, program="clean").ok


def test_mutation_unpinned_kind_breaks_exact_budget():
    """Under exact pins a NEW collective kind fails even when the total
    count cap alone would admit it."""
    mutated = TOY.replace('"stablehlo.all_to_all"',
                          '"stablehlo.collective_permute"')
    exact = dataclasses.replace(BASE, exact_collectives=True)
    cert = certify(mutated, exact, program="mutant")
    assert not cert.ok
    assert any("not in the pinned per-kind budget" in v.summary
               for v in cert.violations)


def test_mutation_undonate_breaks_donation():
    cert = certify(TOY.replace("jax.buffer_donor = true, ", ""), BASE)
    assert not cert.ok
    assert _pass_names(cert) == {"donation"}
    assert "'tab'" in cert.violations[0].summary


def test_mutation_widening_convert_breaks_dtype_drift():
    extra = ("    %9 = stablehlo.convert %2 : (tensor<64x8xbf16>) -> "
             "tensor<64x8xf32>\n")
    cert = certify(_insert(extra), BASE)
    assert not cert.ok
    assert _pass_names(cert) == {"dtype_drift"}
    assert "f16->f32" in cert.violations[0].summary


def test_mutation_f64_op_breaks_dtype_drift():
    extra = "    %9 = stablehlo.add %2, %2 : tensor<64x8xf64>\n"
    cert = certify(_insert(extra), BASE)
    assert not cert.ok
    assert _pass_names(cert) == {"dtype_drift"}
    assert "wider than f32" in cert.violations[0].summary


def test_mutation_host_callback_breaks_host_transfer():
    extra = ('    %9 = stablehlo.custom_call @xla_python_cpu_callback(%2) '
             '{api_version = 2 : i32} : (tensor<64x8xf32>) -> '
             'tensor<64x8xf32>\n')
    cert = certify(_insert(extra), BASE)
    assert not cert.ok
    assert _pass_names(cert) == {"host_transfer"}
    assert "xla_python_cpu_callback" in cert.violations[0].summary
    # The same callback certifies clean when the contract declares it.
    import dataclasses

    allowed = dataclasses.replace(
        BASE, allow_host_transfers=("xla_python_cpu_callback",))
    assert certify(_insert(extra), allowed).ok


def test_mutation_infeed_breaks_host_transfer():
    extra = ('    %9 = "stablehlo.infeed"(%2) : (!stablehlo.token) -> '
             '(tensor<4xf32>, !stablehlo.token)\n')
    cert = certify(_insert(extra), BASE)
    assert _pass_names(cert) == {"host_transfer"}
    assert "infeed" in cert.violations[0].summary


def test_mutation_missing_reconcile_psum_breaks_replica_consistency():
    import dataclasses

    tiered = dataclasses.replace(
        BASE, require_shard_psum=True, hot_reconcile_bytes=1024,
        shard_group_size=8)
    # The plain toy claims tiering but has no big shard-axis psum.
    cert = certify(TOY, tiered)
    assert not cert.ok
    assert _pass_names(cert) == {"replica_consistency"}
    # With the reconcile psum present the SAME contract certifies —
    # modulo the count budget the extra op now exceeds, which is
    # collective_budget's finding, not replica_consistency's.
    tiered3 = dataclasses.replace(
        tiered, max_collectives=3, max_collective_bytes=8192,
        per_kind_max={"all_gather": 1, "all_to_all": 1, "all_reduce": 1})
    assert certify(_insert(RECONCILE_PSUM), tiered3).ok
    # A psum on the WRONG axis (singleton groups) does not satisfy it:
    # the toy's 8x1 psum is group_size 1.
    assert not certify(TOY, tiered).ok


RECONCILE_RS = f'''    %7 = "stablehlo.reduce_scatter"(%6) <{{channel_handle = #stablehlo.channel_handle<handle = 5, type = 1>, replica_groups = {GROUPS_1X8}, scatter_dimension = 0 : i64, use_global_device_ids}}> ({{
    ^bb0(%arg6: tensor<f32>, %arg7: tensor<f32>):
      %92 = stablehlo.add %arg6, %arg7 : tensor<f32>
      stablehlo.return %92 : tensor<f32>
    }}) : (tensor<64x8xf32>) -> tensor<8x8xf32>
'''


def test_sharded_reconcile_rs_satisfies_replica_consistency():
    """PR 10: the window reconcile lowers a reduce-scatter (each replica
    applies its 1/S slice) — ReplicaConsistency accepts it in place of
    the legacy full-head psum, with the same group-size and payload
    gates."""
    import dataclasses

    tiered = dataclasses.replace(
        BASE, require_shard_psum=True, hot_reconcile_bytes=1024,
        shard_group_size=8, max_collectives=3,
        max_collective_bytes=8192,
        per_kind_max={"all_gather": 1, "all_to_all": 1,
                      "reduce_scatter": 1})
    assert certify(_insert(RECONCILE_RS), tiered).ok
    # An undersized reduce_scatter does not satisfy the reconcile bound.
    small = dataclasses.replace(tiered, hot_reconcile_bytes=1 << 20)
    cert = certify(_insert(RECONCILE_RS), small)
    assert not cert.ok
    assert "replica_consistency" in _pass_names(cert)


def test_audit_diff_budgets_gate():
    """tools/audit_programs.py --diff: growth vs the reference audit
    fails iff it is NOT covered by the current pinned budget (an
    unpinned regression); re-pinned growth and shrinkage pass."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "_audit_programs", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "audit_programs.py"))
    ap = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ap)

    old = {"audit_programs": {
        "mf": {"collectives": {"count": 2, "bytes": 4096}},
        "mf_tiered": {"collectives": {"count": 3, "bytes": 5120}},
        "ghost": {"collectives": {"count": 1, "bytes": 64}},
    }}
    pinned_mf = ap.BUDGETS["mf"]
    # Unchanged + shrunk: clean.
    assert ap.diff_budgets(old, {
        "mf": {"collective_count": 2, "collective_bytes": 4096},
    }) == []
    assert ap.diff_budgets(old, {
        "mf": {"collective_count": 1, "collective_bytes": 2048},
    }) == []
    # Growth covered by the CURRENT pin (mf_tiered was deliberately
    # re-pinned this PR to its sharded-reconcile census): passes.
    cur = {"mf_tiered": {
        "collective_count": ap.BUDGETS["mf_tiered"]["max_collectives"],
        "collective_bytes":
            ap.BUDGETS["mf_tiered"]["max_collective_bytes"]}}
    assert ap.diff_budgets(old, cur) == []
    # Unpinned growth: fails, naming the program.
    bad = {"mf": {"collective_count": pinned_mf["max_collectives"] + 1,
                  "collective_bytes": 999999}}
    problems = ap.diff_budgets(old, bad)
    assert len(problems) == 1 and problems[0].startswith("mf:")
    # Programs absent from the old audit (new rows) never regress.
    assert ap.diff_budgets(old, {
        "brand_new": {"collective_count": 99,
                      "collective_bytes": 1 << 30}}) == []


def test_every_default_pass_has_a_mutation():
    """Meta-test: the suite above covers every registered pass."""
    from fps_tpu.analysis import DEFAULT_PASSES

    assert {p.name for p in DEFAULT_PASSES} == {
        "collective_budget", "host_transfer", "donation", "dtype_drift",
        "replica_consistency"}
    assert {type(p) for p in DEFAULT_PASSES} == {
        CollectiveBudget, HostTransferDetector, DonationAudit,
        DtypeDriftDetector, ReplicaConsistency}


# ---------------------------------------------------------------------------
# Certificates, auditor, normalization.
# ---------------------------------------------------------------------------


def test_certificate_json_roundtrip():
    cert = certify(TOY, BASE, program="toy")
    doc = cert.to_json()
    assert doc["ok"] is True and doc["program"] == "toy"
    assert doc["collectives"]["count"] == 2
    assert doc["collectives"]["per_kind"]["all_gather"]["bytes"] == 2048
    assert doc["contract"]["max_collectives"] == 2
    json.dumps(doc)  # must be serializable as-is


def test_violation_json():
    v = Violation(pass_name="donation", summary="s", op_kind="", line=3)
    assert v.to_json() == {"pass_name": "donation", "summary": "s",
                           "op_kind": "", "line": 3}


class _FakeRecorder:
    def __init__(self):
        self.incs, self.events = [], []

    def inc(self, name, value=1.0, **labels):
        self.incs.append((name, value, labels))

    def event(self, etype, **fields):
        self.events.append((etype, fields))


def test_auditor_records_certified_and_violations():
    rec = _FakeRecorder()
    auditor = ProgramAuditor(contract=BASE, recorder=rec)
    cert = auditor.certify("toy/clean", TOY)
    assert cert.ok
    assert ("analysis.certified_programs", 1.0, {}) in rec.incs
    bad = auditor.certify("toy/bad", _insert(RECONCILE_PSUM))
    assert not bad.ok
    rules = [labels["rule"] for name, _, labels in rec.incs
             if name == "analysis.contract_violations"]
    assert rules == ["collective_budget", "collective_budget"]
    etypes = [e for e, _ in rec.events]
    assert etypes == ["analysis.contract_violation"] * 2
    assert rec.events[0][1]["program"] == "toy/bad"
    assert auditor.certificates == [cert, bad]


def test_auditor_strict_raises_with_certificate():
    auditor = ProgramAuditor(contract=BASE, strict=True,
                             recorder=_FakeRecorder())
    with pytest.raises(ContractViolationError) as ei:
        auditor.certify("toy/bad", _insert(RECONCILE_PSUM))
    assert ei.value.certificate.program == "toy/bad"
    assert "collective_budget" in str(ei.value)


def test_as_auditor_normalization():
    auditor = ProgramAuditor()
    assert as_auditor(auditor) is auditor
    assert as_auditor(BASE).contract is BASE
    assert as_auditor(True).strict is False
    assert as_auditor("strict").strict is True
    # None and False mean disabled, so boolean flags wire straight
    # through Trainer(audit=...).
    assert as_auditor(None) is None
    assert as_auditor(False) is None
    with pytest.raises(TypeError):
        as_auditor(17)


# ---------------------------------------------------------------------------
# Real programs: the Trainer hook and contract_for_trainer.
# ---------------------------------------------------------------------------

NU, NI, RANK = 96, 64, 4


def _mf_run(mesh, *, audit=None, chunks_n=2):
    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import multi_epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    cfg = MFConfig(num_users=NU, num_items=NI, rank=RANK)
    trainer, store = online_mf(mesh, cfg)
    trainer.audit = audit
    data = synthetic_ratings(NU, NI, 1500, rank=3, seed=3)
    chunks = list(multi_epoch_chunks(
        data, 1, num_workers=num_workers_of(mesh), local_batch=32,
        steps_per_chunk=4, route_key="user", seed=11))[:chunks_n]
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, m = trainer.fit_stream(tables, ls, iter(chunks),
                                       jax.random.key(1))
    return trainer, store, m


@pytest.fixture(scope="module")
def mf_hlo(devices8):
    """One lowered MF step program on the 8-device mesh."""
    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import multi_epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    trainer, _ = online_mf(mesh, MFConfig(num_users=NU, num_items=NI,
                                          rank=RANK))
    data = synthetic_ratings(NU, NI, 1500, rank=3, seed=3)
    chunk = next(iter(multi_epoch_chunks(
        data, 1, num_workers=num_workers_of(mesh), local_batch=32,
        steps_per_chunk=4, route_key="user", seed=11)))
    placed = trainer._place_chunk(chunk)
    tables, ls = trainer.init_state(jax.random.key(0))
    fn = trainer._get_compiled("sync")
    return trainer, fn.lower(tables, ls, placed,
                             jax.random.key(1)).as_text()


def test_real_mf_program_parses_nonvacuously(mf_hlo):
    """Guard against parser rot: if a jax upgrade changes the textual
    form, these assertions fail loudly instead of every pass silently
    passing on an empty model."""
    _, hlo = mf_hlo
    prog = HloProgram.from_text(hlo)
    assert len(prog.ops) > 50
    assert sum(a.donated for a in prog.args) >= 1
    assert any(r.info.startswith("[0]") for r in prog.results)
    # The untiered MF data plane: one gathered pull + one routed push.
    assert [c.kind for c in prog.profile()] == ["all_gather", "all_to_all"]


def test_real_mf_program_certifies_clean(mf_hlo):
    trainer, hlo = mf_hlo
    cert = certify(hlo, contract_for_trainer(trainer, "sync"),
                   program="mf/sync")
    assert cert.ok, [v.summary for v in cert.violations]


def test_contract_for_trainer_untiered(mf_hlo):
    trainer, _ = mf_hlo
    c = contract_for_trainer(trainer, "sync")
    assert c.donated_tables is True
    assert c.max_float_bits == 32
    assert c.require_shard_psum is False and c.shard_group_size is None


def test_contract_for_trainer_tiered(devices8):
    import dataclasses

    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    trainer, store = online_mf(mesh, MFConfig(num_users=NU, num_items=NI,
                                              rank=RANK))
    store.specs["item_factors"] = dataclasses.replace(
        store.specs["item_factors"], hot_tier=32)
    trainer.config = dataclasses.replace(trainer.config, hot_sync_every=2)
    c = contract_for_trainer(trainer, "sync")
    assert c.require_shard_psum is True
    assert c.hot_reconcile_bytes == 32 * RANK * 4
    assert c.shard_group_size == 8


def test_trainer_audit_certifies_at_compile_time(devices8):
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    trainer, _, m = _mf_run(mesh, audit=True)
    auditor = trainer.audit
    assert isinstance(auditor, ProgramAuditor)
    # One program compiled for the whole stream -> exactly one
    # certificate, clean under the derived contract.
    assert [c.program for c in auditor.certificates] == ["chunk/sync"]
    assert auditor.certificates[0].ok
    assert len(m) == 2  # the run itself was untouched


def test_trainer_audit_reports_violations_through_recorder(devices8):
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    rec = _FakeRecorder()
    impossible = ProgramContract(name="impossible", max_collectives=0)
    trainer, _, _ = _mf_run(mesh, audit=ProgramAuditor(
        contract=impossible, recorder=rec))
    assert not trainer.audit.certificates[0].ok
    assert any(n == "analysis.contract_violations" for n, _, _ in rec.incs)
    assert rec.events and rec.events[0][0] == "analysis.contract_violation"


def test_trainer_audit_strict_raises(devices8):
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    impossible = ProgramContract(name="impossible", max_collectives=0)
    with pytest.raises(ContractViolationError):
        _mf_run(mesh, audit=ProgramAuditor(contract=impossible,
                                           strict=True))


def test_trainer_audit_off_is_passthrough(devices8):
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    trainer, _, _ = _mf_run(mesh, audit=None)
    assert trainer.audit is None
    # The cached compiled fn is the bare jitted callable (no wrapper).
    (fn,) = trainer._compiled.values()
    assert not getattr(fn, "_fps_audited", False)


def test_trainer_audit_numerics_unchanged(devices8):
    """Certification is host-side only: the audited run's tables are
    bit-identical to the unaudited run's."""
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    _, store_a, _ = _mf_run(mesh, audit=True)
    _, store_b, _ = _mf_run(mesh, audit=None)
    a = np.asarray(store_a.tables["item_factors"])
    b = np.asarray(store_b.tables["item_factors"])
    assert np.array_equal(a, b)


def test_trainer_audit_false_disables(devices8):
    """A boolean flag wired straight through: audit=False at
    construction normalizes to None; assigned after construction it
    still certifies nothing (and doesn't die on the first dispatch)."""
    from fps_tpu.core.driver import Trainer
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    trainer, _ = online_mf(mesh, MFConfig(num_users=NU, num_items=NI,
                                          rank=RANK))
    assert Trainer(mesh, trainer.store, trainer.logic,
                   trainer.server_logic, config=trainer.config,
                   audit=False).audit is None
    # Late assignment bypasses ctor normalization; the run must still
    # complete with nothing certified.
    trainer2, _, m = _mf_run(mesh, audit=False)
    assert len(m) == 2
    assert not isinstance(trainer2.audit, ProgramAuditor)


def test_trainer_audit_bad_value_fails_at_construction(devices8):
    """A typo'd audit= value raises at Trainer construction, not on the
    first compiled dispatch mid-run."""
    from fps_tpu.core.driver import Trainer
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    trainer, _ = online_mf(mesh, MFConfig(num_users=NU, num_items=NI,
                                          rank=RANK))
    with pytest.raises(TypeError, match="audit"):
        Trainer(mesh, trainer.store, trainer.logic, trainer.server_logic,
                config=trainer.config, audit="strictt")


def test_lowered_chunk_text_is_certifiable(devices8):
    """Trainer.lowered_chunk_text — the shared entry the analysis tools
    (audit_programs, chaos_sweep's certificate, bench's tiered A/B)
    lower through — produces the dispatched program: parses
    non-vacuously and certifies clean under the trainer's own derived
    contract."""
    import jax

    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import multi_epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    trainer, _ = online_mf(mesh, MFConfig(num_users=NU, num_items=NI,
                                          rank=RANK))
    data = synthetic_ratings(NU, NI, 1500, rank=3, seed=3)
    chunk = next(iter(multi_epoch_chunks(
        data, 1, num_workers=num_workers_of(mesh), local_batch=32,
        steps_per_chunk=4, route_key="user", seed=11)))
    text = trainer.lowered_chunk_text(chunk)
    prog = HloProgram.from_text(text)
    assert len(prog.ops) > 50 and any(a.donated for a in prog.args)
    assert collective_profile(text)
    cert = certify(text, contract_for_trainer(trainer, "sync"),
                   program="helper/sync")
    assert cert.ok, cert.violations
    # Read-only on the trainer: certifying AFTER a run (chaos_sweep's
    # order is run -> certificate -> read the store) must not clobber
    # the trained weights store.init writes in place.
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, _ = trainer.fit_stream(tables, ls, iter([chunk]),
                                       jax.random.key(1))
    trained = {k: np.asarray(v) for k, v in trainer.store.tables.items()}
    trainer.lowered_chunk_text(chunk)
    for k, v in trained.items():
        assert np.array_equal(np.asarray(trainer.store.tables[k]), v), k


def test_audit_programs_offline_hlo_is_jax_free(tmp_path):
    """tools/audit_programs.py --hlo profiles a saved dump with jax
    unimportable — the login-node workflow the analysis docstrings
    promise (jax is poisoned in sys.modules, so any import attempt
    raises)."""
    import os
    import subprocess
    import sys

    dump = tmp_path / "toy.hlo.txt"
    dump.write_text(TOY)
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "audit_programs.py")
    code = (
        "import sys, runpy\n"
        "sys.modules['jax'] = None\n"
        f"sys.argv = ['audit_programs.py', '--hlo', {str(dump)!r}]\n"
        f"runpy.run_path({tool!r}, run_name='__main__')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    entry = out[str(dump)]
    assert entry["collectives"] == 2
    assert entry["bytes"] == 4096
    assert {p["kind"] for p in entry["profile"]} == {"all_gather",
                                                     "all_to_all"}


@pytest.mark.slow
def test_audit_programs_importable_without_reexec():
    """Importing the module (to reuse BUDGETS/builders) must not
    execve-replace the importing process — only the CLI re-execs."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = ("import sys; sys.path.insert(0, 'tools'); "
            "import audit_programs; "
            "print('IMPORT_OK', len(audit_programs.BUDGETS))")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=root, capture_output=True,
        text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    # 11 pinned rows (mf_megastep joined the PR-10 census of 10 when
    # the fused dispatch got its own budget).
    assert "IMPORT_OK 11" in proc.stdout
