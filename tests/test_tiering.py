"""Adaptive tiering (fps_tpu.tiering): online hot-set re-ranking + the
auto-tiering planner.

The contracts under test, per docs/performance.md "Adaptive tiering":

* **mapped == static on the identity ranking** — the adaptive tier with
  hot set ``[0, H)`` trains bit-identically to PR 5's static head (the
  slot-map machinery changes routing representation, not semantics);
* **re-ranks NEVER recompile** — the hot membership rides as replicated
  slot-map/gid DATA; the compile cache is keyed on H only (asserted on
  the cache itself AND on the program-build count);
* **the flush-reconcile invariant survives re-ranks** — at any boundary
  the replica is a pure projection of the canonical table's CURRENT hot
  ids, and checkpoints stay canonical (one table per spec, restorable
  by an untiered trainer);
* **sidecar resume is bit-identical** — a run resumed from checkpoint +
  tracker sidecar replays the straight run's re-rank decisions exactly;
* the planner derives (H, E, dense) from densities, and the fold
  resolution REPORTS (warns) instead of silently disengaging.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fps_tpu.core.api import ServerLogic
from fps_tpu.core.checkpoint import Checkpointer
from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.store import (
    hot_key,
    hot_slot_map,
    lookup_hot_slots,
    sketch_key,
)
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.testing.workloads import (
    NF,
    logreg_chunks,
    logreg_data,
    weights,
)
from fps_tpu.tiering import Retierer, TableDensity, plan_tables
from fps_tpu import sketch as sk


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _make_trainer(mesh, *, hot_tier=0, hot_sync_every=1, retierer=None,
                  **cfg_over):
    trainer, store = logistic_regression(
        mesh, LogRegConfig(num_features=NF, learning_rate=0.5))
    if hot_tier:
        for name, spec in store.specs.items():
            store.specs[name] = dataclasses.replace(
                spec, hot_tier=min(hot_tier, spec.num_ids))
    trainer.config = dataclasses.replace(
        trainer.config, hot_sync_every=hot_sync_every, **cfg_over)
    trainer.retierer = retierer
    return trainer, store


def _fit(trainer, chunks, **kw):
    tables, ls = trainer.init_state(jax.random.key(0))
    return trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                              **kw)


# ---------------------------------------------------------------------------
# Mapped tier semantics.
# ---------------------------------------------------------------------------

def test_mapped_identity_ranking_matches_static_head(devices8):
    """The adaptive (slot-mapped) tier with hot set [0, H) must train to
    the same values as the static id<H tier — the mapped routing is a
    representation change, not a semantics change. (Not asserted at the
    HLO level: the mapped reconcile scatters where the static one
    slice-adds; value equality is the contract.)"""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)

    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3)
    _fit(trainer, chunks)
    w_static = weights(store)

    # check_every > len(chunks): the Retierer engages the mapped routes
    # but never re-ranks, so the hot set stays the identity head.
    rt = Retierer(check_every=100)
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3,
                                   retierer=rt)
    tables, _, _ = _fit(trainer, chunks)
    w_mapped = weights(store)
    assert np.array_equal(w_static, w_mapped)
    # Boundary invariant, mapped flavor: replica == canonical rows of
    # the CURRENT hot ids.
    gids = rt.hot_ids_for("weights", 64)
    assert np.array_equal(np.asarray(tables[hot_key("weights")]),
                          store.lookup_host("weights", gids))


def test_retierer_on_disengaged_tier_lowers_untiered_program(devices8):
    """Attaching a Retierer must not perturb programs whose tier the
    resolution disengages: exact mode (hot_sync_every=1) and
    untiered specs both lower BYTE-IDENTICAL text to the plain untiered
    trainer — tracking is gated on the RESOLVED tier, not the raw spec,
    so no orphan sketch ops ride a program nothing will consume."""
    from fps_tpu.parallel.mesh import key_to_replicated

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)

    def lowered(**kw):
        trainer, _ = _make_trainer(mesh, **kw)
        tables, ls = trainer.init_state(jax.random.key(0))
        tables = trainer._attach_hot(tables)
        batches = trainer._place_chunk(chunks[0], "sync")
        key = key_to_replicated(jax.random.key(1), mesh)
        return trainer._get_compiled("sync").lower(
            tables, ls, batches, key).as_text()

    base = lowered()
    assert lowered(hot_tier=64, hot_sync_every=1,
                   retierer=Retierer(check_every=2)) == base
    assert lowered(retierer=Retierer(check_every=2)) == base


def test_rerank_zero_recompiles_and_boundary_invariant(devices8):
    """Forced re-ranks must (a) actually fire, (b) hit the SAME compiled
    program — zero recompiles, counted on both the compile cache and the
    program-build calls — and (c) keep the replica a projection of the
    canonical rows of whatever ids are currently hot. Two identical runs
    stay bit-identical (the re-rank schedule is deterministic)."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    results = []
    for _ in range(2):
        rt = Retierer(check_every=2, churn_threshold=-1.0)
        trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3,
                                       retierer=rt)
        builds = []
        orig = type(trainer)._build_chunk_fn

        def counting(self, mode, *args, _orig=orig, _b=builds, **kw):
            _b.append(mode)
            return _orig(self, mode, *args, **kw)

        trainer._build_chunk_fn = counting.__get__(trainer)
        tables, _, m = _fit(trainer, chunks)
        assert rt.re_ranks >= 1
        assert len(trainer._compiled) == 1, "re-rank recompiled"
        assert builds == ["sync"], f"program rebuilt: {builds}"
        gids = rt.hot_ids_for("weights", 64)
        assert np.array_equal(np.asarray(tables[hot_key("weights")]),
                              store.lookup_host("weights", gids))
        results.append((weights(store), m, gids.copy()))
    assert np.array_equal(results[0][0], results[1][0])
    assert np.array_equal(results[0][2], results[1][2])
    assert _tree_equal(results[0][1], results[1][1])


def test_rerank_checkpoints_stay_canonical(tmp_path, devices8):
    """A checkpoint written by a re-ranked run is one canonical table in
    logical id order — no aux entries, restorable by a plain UNTIERED
    trainer, equal to the run's own host view."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    rt = Retierer(check_every=2, churn_threshold=-1.0)
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3,
                                   retierer=rt)
    d = str(tmp_path / "ck")
    with Checkpointer(d) as ckpt:
        _fit(trainer, chunks, checkpointer=ckpt, checkpoint_every=1)
        assert rt.re_ranks >= 1
        want = weights(store)

        untiered, ustore = _make_trainer(mesh)
        tables, ls = untiered.init_state(jax.random.key(0))
        tables, ls, step = untiered.restore_checkpoint(ckpt, ls)
        assert not any("::" in k for k in tables)
        assert np.array_equal(weights(ustore), want)


def test_sidecar_resume_bit_identical(tmp_path, devices8):
    """Kill-free, in-process version of the retier_kill chaos scenario:
    a run resumed from (checkpoint, tracker sidecar) replays the
    straight adaptive run's re-rank decisions and final weights
    bit-for-bit."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    stop_at = 3

    def adaptive_trainer(state_dir):
        rt = Retierer(check_every=2, churn_threshold=-1.0,
                      state_dir=state_dir)
        return _make_trainer(mesh, hot_tier=64, hot_sync_every=3,
                             retierer=rt)

    d1 = str(tmp_path / "straight")
    trainer, store = adaptive_trainer(d1)
    _fit(trainer, chunks)
    want = weights(store)
    want_gids = trainer.retierer.hot_ids_for("weights", 64).copy()

    class Stop(Exception):
        pass

    def stop(i, _m):
        if i == stop_at:
            raise Stop

    d2 = str(tmp_path / "resumed")
    trainer, store = adaptive_trainer(d2)
    tables, ls = trainer.init_state(jax.random.key(0))
    with Checkpointer(d2) as ckpt:
        with pytest.raises(Stop):
            trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                               checkpointer=ckpt, checkpoint_every=1,
                               on_chunk=stop)
        # Fresh trainer + fresh Retierer, like a restarted process.
        trainer, store = adaptive_trainer(d2)
        tables, ls = trainer.init_state(jax.random.key(0))
        tables, ls, start = trainer.restore_checkpoint(ckpt, ls)
        assert trainer.retierer.restore(start) is True
        trainer.fit_stream(tables, ls, iter(chunks[start:]),
                           jax.random.key(1), start_step=start)
    assert np.array_equal(weights(store), want)
    assert np.array_equal(trainer.retierer.hot_ids_for("weights", 64),
                          want_gids)


def test_device_tracking_matches_host_counts(devices8):
    """The device-side window sketch (updated inside the compiled step,
    psum-merged across the mesh) must equal a HOST cm_update over the
    chunk's live pulled ids under the SAME per-table hashing spec — the
    seed-agreement contract between tracker halves."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)
    rt = Retierer(check_every=100)  # never folds: window keeps raw sums
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3,
                                   retierer=rt)
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, _ = trainer.fit_stream(tables, ls, iter(chunks[:1]),
                                       jax.random.key(1))
    win = np.asarray(tables[sketch_key("weights")])
    spec = rt._table_cm("weights")
    ids = chunks[0]["feat_ids"].reshape(-1)
    live = (np.repeat(chunks[0]["weight"].reshape(-1),
                      chunks[0]["feat_ids"].shape[-1]) > 0)
    host = sk.cm_update(spec, sk.cm_init(spec),
                        jnp.asarray(np.where(live, ids, -1).astype(
                            np.int32)))
    np.testing.assert_allclose(win, np.asarray(host))


# ---------------------------------------------------------------------------
# Store-level mapped primitives.
# ---------------------------------------------------------------------------

def test_hot_slot_map_contract():
    m = hot_slot_map(10, np.array([7, 2, 9]))
    assert m.shape == (11,)
    assert m[7] == 0 and m[2] == 1 and m[9] == 2
    assert m[10] == -1 and m[0] == -1
    slots = np.asarray(lookup_hot_slots(
        jnp.asarray(m), jnp.asarray(np.array([2, -1, 0, 9], np.int32))))
    assert slots.tolist() == [1, -1, -1, 2]
    with pytest.raises(ValueError, match="duplicates"):
        hot_slot_map(10, np.array([1, 1]))
    with pytest.raises(ValueError, match="outside"):
        hot_slot_map(10, np.array([10]))


def test_rows_replica_requires_valid_ids(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    _, store = _make_trainer(mesh)
    store.init(jax.random.key(0))
    rep = np.asarray(store.rows_replica("weights", np.array([5, 3, 380])))
    assert np.array_equal(rep,
                          store.lookup_host("weights",
                                            np.array([5, 3, 380])))
    with pytest.raises(ValueError, match="subset"):
        store.rows_replica("weights", np.array([NF]))
    with pytest.raises(ValueError, match="subset"):
        store.rows_replica("weights", np.array([], np.int64))


# ---------------------------------------------------------------------------
# Resolution policy: the fold gap reports instead of silently falling back.
# ---------------------------------------------------------------------------

def test_fold_resolution_warns_not_silent(devices8):
    # PR 10 moved max/min onto the tier (windowed extremum buffer), so
    # the demotion — and its warning — is down to the per-push folds:
    # a callable combine and apply_fn.
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=4)
    trainer.server_logic["weights"] = ServerLogic(
        combine=lambda summed, counts: summed)
    with pytest.warns(UserWarning, match="gathered route"):
        assert trainer._resolve_hot_tier(store.specs["weights"]) == 0
    # Once per table per trainer — resolution runs per compile AND per
    # chunk via _attach_hot, so a repeat must stay silent.
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        assert trainer._resolve_hot_tier(store.specs["weights"]) == 0

    # max/min no longer demote: the tier engages.
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=4)
    trainer.server_logic["weights"] = ServerLogic(combine="max")
    with _w.catch_warnings():
        _w.simplefilter("error")
        assert trainer._resolve_hot_tier(store.specs["weights"]) == 64

    # apply_fn trips the same report.
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=4)
    trainer.server_logic["weights"] = ServerLogic(
        apply_fn=lambda cur, d: cur + d)
    with pytest.warns(UserWarning, match="apply_fn"):
        assert trainer._resolve_hot_tier(store.specs["weights"]) == 0


# ---------------------------------------------------------------------------
# Planner.
# ---------------------------------------------------------------------------

def _zipf_density(name, num_ids, dim, alpha=1.2):
    return TableDensity(name, num_ids, dim,
                        1.0 / np.arange(1, num_ids + 1) ** alpha)


def test_planner_full_replication_under_budget():
    plans = plan_tables([_zipf_density("t", 1024, 8)],
                        batch_rows_per_step=256)
    p = plans["t"]
    assert p.hot_tier == 1024 and p.hot_sync_every >= 2
    assert "full replication" in p.reason


def test_planner_partial_head_respects_budget_and_coverage():
    # 1M ids x dim 16 x 4B = 64MB > a 1MB budget -> partial head.
    plans = plan_tables([_zipf_density("t", 1 << 20, 16, alpha=1.4)],
                        batch_rows_per_step=4096,
                        replica_budget_bytes=1 << 20)
    p = plans["t"]
    budget_rows = (1 << 20) // (16 * 4)
    assert 0 < p.hot_tier <= budget_rows
    assert 2 <= p.hot_sync_every <= 8
    assert p.coverage >= 0.5


def test_planner_flat_distribution_stays_untiered():
    flat = TableDensity("t", 1 << 16, 16, np.ones(1 << 16))
    plans = plan_tables([flat], batch_rows_per_step=4096,
                        replica_budget_bytes=1 << 18)
    assert plans["t"].hot_tier == 0 and plans["t"].hot_sync_every == 1
    assert "flat" in plans["t"].reason


def test_planner_no_evidence_stays_untiered_and_global_e():
    from fps_tpu.tiering import global_sync_every

    empty = TableDensity("a", 64, 4, np.zeros(64))
    hot = _zipf_density("b", 64, 4)
    plans = plan_tables([empty, hot], batch_rows_per_step=64)
    assert plans["a"].hot_tier == 0
    assert plans["b"].hot_tier == 64
    assert global_sync_every(plans) == plans["b"].hot_sync_every
    assert global_sync_every({"a": plans["a"]}) == 1


def test_planner_cold_budget_for_partial_heads():
    from fps_tpu.tiering.planner import choose_cold_budget

    # Partial head on a non-dense table: the plan carries a compacted
    # cold lane sized to the UNCOVERED traffic (margined, multiple of 8).
    plans = plan_tables([_zipf_density("t", 1 << 20, 16, alpha=1.4)],
                        batch_rows_per_step=4096,
                        replica_budget_bytes=1 << 20,
                        num_workers=8)
    p = plans["t"]
    assert 0 < p.hot_tier < (1 << 20)
    assert p.cold_budget == choose_cold_budget(
        p.coverage, 4096, num_workers=8)
    assert p.cold_budget % 8 == 0
    assert "compacted cold lane" in p.reason
    # Full replication: no cold route, no lane.
    plans = plan_tables([_zipf_density("t", 1024, 8)],
                        batch_rows_per_step=256, num_workers=8)
    assert plans["t"].cold_budget == 0
    # Low coverage: a lane as wide as the batch buys nothing -> 0.
    assert choose_cold_budget(0.1, 4096, num_workers=8) == 0
    # knobs() compares the compile-affecting fields only.
    a = plans["t"]
    b = dataclasses.replace(a, coverage=0.123, reason="different")
    assert a.knobs() == b.knobs()
    assert a.knobs() != dataclasses.replace(a, cold_budget=8).knobs()


def test_replan_unchanged_noop_changed_recompiles_once(devices8):
    """Periodic RE-planning (Retierer.replan_every): an unchanged plan
    is a strict no-op — zero recompiles, counted on the compile cache
    AND the program-build calls; a changed plan (here: the replica
    budget collapses, forcing full replication -> partial head)
    recompiles exactly once."""
    from fps_tpu import obs

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=4)
    rt = Retierer(auto_plan=True, warmup_checks=1, check_every=1,
                  replan_every=1)
    trainer, store = _make_trainer(mesh, retierer=rt)
    builds = []
    orig = type(trainer)._build_chunk_fn

    def counting(self, mode, *args, _orig=orig, _b=builds, **kw):
        _b.append(mode)
        return _orig(self, mode, *args, **kw)

    trainer._build_chunk_fn = counting.__get__(trainer)
    rec = obs.Recorder(sinks=[])
    trainer.recorder = rec

    # Phase 1: warmup program + the planned program = 2 builds; every
    # boundary after the plan re-plans with UNCHANGED knobs (stationary
    # stream) — zero further builds.
    _fit(trainer, chunks)
    assert rt.planned
    n_initial = len(builds)
    assert n_initial == 2, builds
    assert rec.counter_value("tiering.replans", changed="false") >= 1
    assert rec.counter_value("tiering.replans", changed="true") == 0
    plan_before = {n: p.knobs() for n, p in rt.plans.items()}

    # Phase 2: collapse the replica budget — the next re-plan must land
    # a DIFFERENT plan (partial head) with exactly one recompile.
    rt.plan_kwargs["replica_budget_bytes"] = 64 * 4  # 64 rows of dim 1
    tables, ls = trainer.init_state(jax.random.key(0))
    trainer.fit_stream(tables, ls, iter(chunks[:2]), jax.random.key(2))
    assert rec.counter_value("tiering.replans", changed="true") == 1
    assert {n: p.knobs() for n, p in rt.plans.items()} != plan_before
    assert store.specs["weights"].hot_tier < NF
    assert len(builds) == n_initial + 1, builds

    # Phase 3: further boundaries with the (new) stationary plan are
    # no-ops again.
    n_after = len(builds)
    trainer.fit_stream(trainer.store.tables, ls, iter(chunks[2:4]),
                       jax.random.key(3), start_step=2)
    assert len(builds) == n_after, builds
    assert np.isfinite(weights(store)).all()


def test_plan_application_preserves_fold_state(devices8):
    """Applying (or re-applying) a plan strips the DERIVABLE aux entries
    (replica, slot maps, sketches — re-split from the canonical table)
    but must KEEP ::fold optimizer state: it is not a projection of the
    canonical table, and zeroing a live Adagrad accumulator mid-run
    would silently change step sizes."""
    from fps_tpu.tiering.planner import TierPlan

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    trainer, store = _make_trainer(mesh, hot_tier=NF, hot_sync_every=3)
    trainer.server_logic["weights"] = dataclasses.replace(
        trainer.server_logic["weights"], hot_fold="adagrad")
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)
    tables, _, _ = _fit(trainer, chunks)
    state_before = np.asarray(tables["weights::fold"])
    assert np.any(state_before != 0)  # the run really accumulated state

    # Install a plan that keeps the table's knobs (full replication,
    # same E): the strip must preserve the live fold state verbatim —
    # a dropped entry would be re-derived as ZEROS by _attach_hot.
    rt = Retierer()
    trainer.retierer = rt
    plans = {"weights": TierPlan(NF, 3, False, 1.0, "test")}
    out = rt._install_plans(trainer, dict(tables), plans, {}, None,
                            what="test")
    assert "weights::fold" in out
    assert np.array_equal(np.asarray(out["weights::fold"]), state_before)
    # The derivable kinds were genuinely stripped + re-derived (the
    # replica is a projection, so re-derivation is value-identical).
    assert hot_key("weights") in out


def test_planner_validates_density():
    with pytest.raises(ValueError, match="shape"):
        TableDensity("t", 8, 4, np.zeros(9))
    with pytest.raises(ValueError, match="negative"):
        TableDensity("t", 2, 4, np.array([-1.0, 1.0]))


def test_top_ids_matches_full_sort_with_ties():
    from fps_tpu.tiering.retier import top_ids

    rng = np.random.default_rng(0)
    # Heavy ties: small integer counts force the tie-break to matter.
    est = rng.integers(0, 5, 1000).astype(np.float64)
    for H in (1, 7, 64, 999, 1000, 1500):
        full = np.lexsort((np.arange(len(est)), -est))[:min(H, len(est))]
        np.testing.assert_array_equal(top_ids(est, H), full)


def test_sidecar_sweep_keeps_checkpointed_steps(tmp_path):
    from fps_tpu.core import snapshot_format as fmt

    rt = Retierer(state_dir=str(tmp_path), keep=2)
    # A published snapshot at step 2: its sidecar must survive the sweep
    # even once newer sidecars push it past `keep` — that is the step a
    # supervised resume will restore.
    open(fmt.snapshot_path(str(tmp_path), 2), "wb").close()
    for step in range(1, 7):
        rt._save_sidecar(step, {})
    from fps_tpu.tiering import sidecar_path

    import os

    left = sorted(f for f in os.listdir(tmp_path)
                  if f.startswith("tiering-"))
    assert os.path.basename(sidecar_path(str(tmp_path), 2)) in left
    assert os.path.basename(sidecar_path(str(tmp_path), 6)) in left
    assert os.path.basename(sidecar_path(str(tmp_path), 5)) in left
    assert len(left) == 3  # newest 2 + the checkpointed step


def test_auto_tier_push_delay_rejected_at_run_entry(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)
    trainer, _ = _make_trainer(mesh, auto_tier=True, push_delay=2)
    tables, ls = trainer.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="auto_tier and push_delay"):
        trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1))


# ---------------------------------------------------------------------------
# Auto-tier end to end + probe lowering.
# ---------------------------------------------------------------------------

def test_auto_tier_plans_and_trains(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    trainer, store = _make_trainer(mesh, auto_tier=True)
    _fit(trainer, chunks)
    rt = trainer.retierer
    assert rt is not None and rt.planned
    assert "weights" in rt.plans
    # The plan landed on the live spec/config.
    assert store.specs["weights"].hot_tier == rt.plans["weights"].hot_tier
    assert np.isfinite(weights(store)).all()


def test_probe_plan_lowering_and_rerank_identity(devices8):
    """The probe program lowers with the plan's routes, and two
    different hot id sets lower BYTE-IDENTICAL text (the unit-level
    recompile-freedom check; tools/audit_programs.py pins the same
    claim on the MF workload)."""
    from fps_tpu.analysis import collective_profile
    from fps_tpu.core.store import TableSpec
    from fps_tpu.tiering import TierPlan, lowered_plan_text

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    specs = {"t": TableSpec("t", 256, 8)}
    plans = {"t": TierPlan(64, 2, False, 0.9, "test")}
    rt1 = Retierer()
    text1 = lowered_plan_text(mesh, specs, plans, hot_sync_every=2,
                              retierer=rt1)
    assert collective_profile(text1, 0)
    rt2 = Retierer()
    rt2.hot_ids["t"] = np.arange(64, 128, dtype=np.int64)
    text2 = lowered_plan_text(mesh, specs, plans, hot_sync_every=2,
                              retierer=rt2)
    assert text1 == text2


# ---------------------------------------------------------------------------
# Chaos: SIGKILL between re-rank and re-split (slow; shared with the sweep).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_retier_kill_resumes_bit_identical(tmp_path):
    from fps_tpu.testing.supervised_demo import run_retier_kill_scenario

    ok, detail = run_retier_kill_scenario(str(tmp_path))
    assert ok, detail
