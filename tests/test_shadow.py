"""Shadow serving: the promotion gate between publish and serve.

Contract under test (``fps_tpu/serve/shadow.py`` + docs/serving.md
"Shadow serving" / docs/STALENESS.md):

* ``ShadowGate``: no approvals -> None; approvals are forward-monotone
  (a stale approve() is a no-op);
* a ``shadow=True`` FleetReader serves NOTHING until the first
  promotion, then never past the approved step — a held publication is
  invisible to the fleet (lost freshness, never wrong answers);
* ``ShadowScorer``: bootstrap-promotes the first candidate, holds a
  regression (``new < old + min_delta``), re-judges only NEWER
  candidates after a hold, and a recovered candidate promotes the gate
  straight past the held step.

Snapshots are handcrafted npz in the checkpoint writer's layout, same
as tests/test_serve_fleet.py — everything here is jax-free.
"""

import os

import numpy as np

from fps_tpu.core import snapshot_format as fmt
from fps_tpu.serve import FleetReader
from fps_tpu.serve.shadow import GATE_NAME, ShadowGate, ShadowScorer


def write_full(dirpath, step, tables):
    arrays = {f"table::{k}": np.asarray(v) for k, v in tables.items()}
    arrays["meta::ls_format"] = np.array("exported")
    for k in list(arrays):
        arrays["meta::crc::" + k] = np.uint32(fmt.array_crc32(arrays[k]))
    os.makedirs(dirpath, exist_ok=True)
    np.savez(fmt.snapshot_path(dirpath, step), **arrays)


def _table(seed, nrows=16, dim=3):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(nrows, dim)).astype(np.float32)


def _scorer(d, scores, **kw):
    """A scorer whose judgment is a fixed step->score lookup."""
    return ShadowScorer(d, lambda snap: scores[snap.step], **kw)


# ---------------------------------------------------------------------------
# ShadowGate


def test_gate_empty_then_forward_monotone(tmp_path):
    gate = ShadowGate(str(tmp_path))
    assert gate.approved_step() is None
    gate.approve(3, score_new=0.9)
    assert gate.approved_step() == 3
    # Stale approvals no-op; newer ones advance.
    gate.approve(2)
    assert gate.approved_step() == 3
    gate.approve(5)
    assert gate.approved_step() == 5
    rec = gate.read_record()
    assert rec["approved_step"] == 5
    assert os.path.basename(gate.path) == GATE_NAME


def test_gate_garbage_record_reads_as_unapproved(tmp_path):
    gate = ShadowGate(str(tmp_path))
    os.makedirs(gate.dir, exist_ok=True)
    with open(gate.path, "w", encoding="utf-8") as f:
        f.write('{"not_a_step": 1}')
    assert gate.approved_step() is None


# ---------------------------------------------------------------------------
# Gated FleetReader


def test_gated_reader_serves_nothing_before_first_approval(tmp_path):
    d = str(tmp_path)
    write_full(d, 1, {"w": _table(0)})
    reader = FleetReader(d, "r0", quorum=1, shadow=True)
    for _ in range(3):
        reader.poll()
    # Verified and candidate-ready, but the gate has never approved.
    assert reader.server._snap is None
    assert reader.fence.read() is None
    ShadowGate(d).approve(1)
    reader.poll()
    assert reader.server._snap.step == 1


def test_gated_reader_capped_at_approved_step(tmp_path):
    d = str(tmp_path)
    write_full(d, 1, {"w": _table(0)})
    write_full(d, 2, {"w": _table(1)})
    ShadowGate(d).approve(1)
    reader = FleetReader(d, "r0", quorum=1, shadow=True)
    for _ in range(3):
        reader.poll()
    # The unapproved step 2 is published and verified, yet invisible:
    # readiness and the fence both stop at the approved step.
    assert reader.server._snap.step == 1
    assert reader.fence.read() == (0, 1)
    ShadowGate(d).approve(2)
    for _ in range(2):
        reader.poll()
    assert reader.server._snap.step == 2


# ---------------------------------------------------------------------------
# ShadowScorer


def test_scorer_bootstrap_promotes_first_candidate(tmp_path):
    d = str(tmp_path)
    write_full(d, 1, {"w": _table(0)})
    scorer = _scorer(d, {1: 1.0})
    rec = scorer.poll()
    assert rec["decision"] == "promoted"
    assert rec["prev_approved"] is None
    assert rec["score_old"] is None
    assert scorer.gate.approved_step() == 1
    assert scorer.promotions == 1
    # Nothing new: the next poll judges nothing.
    assert scorer.poll() is None


def test_scorer_holds_regression_and_skips_rejudging_it(tmp_path):
    d = str(tmp_path)
    write_full(d, 1, {"w": _table(0)})
    scorer = _scorer(d, {1: 1.0, 2: 0.5})
    assert scorer.poll()["decision"] == "promoted"
    write_full(d, 2, {"w": _table(1)})
    rec = scorer.poll()
    assert rec == {"step": 2, "prev_approved": 1, "score_new": 0.5,
                   "score_old": 1.0, "decision": "held"}
    assert scorer.gate.approved_step() == 1
    assert scorer.holds == 1
    # The held step is judged once; only a NEWER candidate re-opens
    # the question.
    assert scorer.poll() is None
    assert scorer.holds == 1


def test_recovery_promotes_past_held_step(tmp_path):
    d = str(tmp_path)
    write_full(d, 1, {"w": _table(0)})
    scorer = _scorer(d, {1: 1.0, 2: 0.5, 3: 1.1})
    scorer.poll()
    write_full(d, 2, {"w": _table(1)})
    assert scorer.poll()["decision"] == "held"
    write_full(d, 3, {"w": _table(2)})
    rec = scorer.poll()
    assert rec["decision"] == "promoted"
    assert rec["step"] == 3
    # The gate jumps 1 -> 3: the regressed step 2 is never served.
    assert scorer.gate.approved_step() == 3
    assert scorer.promotions == 2


def test_min_delta_tolerates_small_noise(tmp_path):
    d = str(tmp_path)
    write_full(d, 1, {"w": _table(0)})
    # Default bar (-0.02): candidate may be slightly worse and still
    # promote — freshness is worth a little noise.
    scorer = _scorer(d, {1: 1.0, 2: 0.99})
    scorer.poll()
    write_full(d, 2, {"w": _table(1)})
    assert scorer.poll()["decision"] == "promoted"
    assert scorer.gate.approved_step() == 2


def test_unopenable_approved_snapshot_cannot_hold_the_gate(tmp_path):
    d = str(tmp_path)
    write_full(d, 1, {"w": _table(0)})
    scorer = _scorer(d, {1: 1.0, 2: 0.1})
    scorer.poll()
    # The approved snapshot vanishes (pruned/quarantined): a regressed
    # candidate must still promote — there is nothing left to compare
    # against, and an unservable approval must not wedge the tenant.
    os.remove(fmt.snapshot_path(d, 1))
    write_full(d, 2, {"w": _table(1)})
    rec = scorer.poll()
    assert rec["decision"] == "promoted"
    assert rec["score_old"] is None
    assert scorer.gate.approved_step() == 2
