"""Payload-proportional cold routing (``TableSpec.cold_budget``).

The contracts under test, per docs/performance.md "Payload-proportional
routing":

* **compaction is exact** — for a chunk stream whose every batch fits
  the lane, the compacted program produces the same tables and metrics
  as the static cold routes (the lane carries the same cold ids/deltas,
  zeros removed);
* **overflow falls back bit-identically** — a chunk whose cold ids
  exceed the budget dispatches the STATIC program (the exact
  ``cold_budget=0`` program, same compile-cache entry), counts a
  ``cold_route.overflow_chunks`` metric, and never drops an update;
* **the compacted program is strictly smaller** — cold-route collective
  payload scales with the lane, not the batch (pinned exactly in
  ``tools/audit_programs.py`` as ``mf_tiered_compact`` vs
  ``mf_tiered_gathered``);
* the device-side ``hot_tier.cold_dropped`` net stays zero for every
  host-certified chunk.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.ingest import epoch_chunks, per_worker_cold_counts
from fps_tpu.core.store import compact_cold
from fps_tpu.models.matrix_factorization import MFConfig, online_mf
from fps_tpu.parallel.mesh import make_ps_mesh

NU, NI, RANK = 48, 32, 4
H = 16  # partial head


def _make_trainer(mesh, *, cold_budget=0, combine="sum"):
    trainer, store = online_mf(
        mesh, MFConfig(num_users=NU, num_items=NI, rank=RANK),
        combine=combine)
    store.specs["item_factors"] = dataclasses.replace(
        store.specs["item_factors"], hot_tier=H,
        dense_collectives=False, cold_budget=cold_budget)
    trainer.config = dataclasses.replace(trainer.config, hot_sync_every=2)
    return trainer, store


def _data(n, *, p_cold, seed=0):
    """Ratings whose item stream is hot-heavy: cold fraction p_cold."""
    rng = np.random.default_rng(seed)
    item = np.where(rng.random(n) < p_cold,
                    rng.integers(H, NI, n),
                    rng.integers(0, H, n)).astype(np.int32)
    return {"user": rng.integers(0, NU, n).astype(np.int32),
            "item": item,
            "rating": rng.normal(0, 1, n).astype(np.float32)}


def _chunks(data, W, *, local_batch=8, spc=4, seed=5):
    return list(epoch_chunks(data, num_workers=W, local_batch=local_batch,
                             steps_per_chunk=spc, route_key="user",
                             seed=seed))


def _fit(trainer, chunks, rec=None):
    trainer.recorder = rec
    tables, ls = trainer.init_state(jax.random.key(0))
    return trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1))


# ---------------------------------------------------------------------------
# Unit: the device-side lane packer and the host-side certifier.
# ---------------------------------------------------------------------------

def test_compact_cold_packs_order_preserving_and_drops_overflow():
    ids = jnp.asarray([-1, 5, -1, 9, 3, -1, 7], jnp.int32)
    deltas = jnp.arange(14, dtype=jnp.float32).reshape(7, 2)
    lane_ids, lane_deltas, pos, over = compact_cold(ids, deltas, budget=4)
    assert lane_ids.shape == (4,)
    assert np.array_equal(np.asarray(lane_ids), [5, 9, 3, 7])
    assert np.array_equal(np.asarray(lane_deltas),
                          np.asarray(deltas)[[1, 3, 4, 6]])
    # pos maps batch slots to lane positions; masked slots are -1.
    assert np.array_equal(np.asarray(pos), [-1, 0, -1, 1, 2, -1, 3])
    assert int(over) == 0

    # Overflow: live entries beyond the lane are dropped and counted.
    lane_ids, _, pos, over = compact_cold(ids, None, budget=2)
    assert np.array_equal(np.asarray(lane_ids), [5, 9])
    assert np.array_equal(np.asarray(pos), [-1, 0, -1, 1, -1, -1, -1])
    assert int(over) == 2


def test_per_worker_cold_counts_static_and_member():
    # 2 steps x (2 workers * 3 local): worker-major batch layout.
    ids = np.array([[0, 1, 9, 2, 8, 7],
                    [9, 9, 9, -1, 0, 8]])
    counts = per_worker_cold_counts(ids, 2, hot_head=8)
    assert counts.shape == (2, 2)
    # worker 0 step 0: {9}; worker 1 step 0: {8}; step 1: {9,9,9} / {8}
    # (-1 is padding, never cold).
    assert np.array_equal(counts, [[1, 1], [3, 1]])
    # Membership form (adaptive tier): hot set {0, 9}.
    member = np.zeros(11, bool)
    member[[0, 9]] = True
    counts = per_worker_cold_counts(ids, 2, hot_member=member)
    assert np.array_equal(counts, [[1, 3], [0, 1]])
    with pytest.raises(ValueError, match="divisible"):
        per_worker_cold_counts(ids, 4)


# ---------------------------------------------------------------------------
# Resolution: where the compacted route engages.
# ---------------------------------------------------------------------------

def test_cold_compact_resolution_policy(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    trainer, store = _make_trainer(mesh, cold_budget=4)
    assert trainer._cold_compact_map() == {"item_factors": 4}
    # Full replication: no cold route to compact.
    store.specs["item_factors"] = dataclasses.replace(
        store.specs["item_factors"], hot_tier=NI)
    assert trainer._cold_compact_map() == {}
    # Dense route: table-sized collectives regardless of the lane.
    store.specs["item_factors"] = dataclasses.replace(
        store.specs["item_factors"], hot_tier=H, dense_collectives=True)
    assert trainer._cold_compact_map() == {}
    # Tier off (exact mode): nothing engages.
    trainer2, _ = _make_trainer(mesh, cold_budget=4)
    trainer2.config = dataclasses.replace(trainer2.config,
                                          hot_sync_every=1)
    assert trainer2._cold_compact_map() == {}


# ---------------------------------------------------------------------------
# The exactness + fallback contracts.
# ---------------------------------------------------------------------------

def test_compacted_chunks_match_static_and_overflow_falls_back(devices8):
    """One stream, three trainers: static (cold_budget=0), compacted
    with a generous lane (every chunk certifies), compacted with a lane
    of 0 < C < cold traffic (every chunk overflows). The generous arm
    matches static numerically through the compacted program; the
    overflow arm IS the static program — tables and metrics equal to
    cold_budget=0 bit for bit, nothing dropped."""
    from fps_tpu import obs

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    data = _data(W * 8 * 4 * 3, p_cold=0.2)
    chunks = _chunks(data, W)
    # Every batch's per-worker cold count, so the lane choices below are
    # provably on the right side of the certifier.
    counts = np.concatenate([
        per_worker_cold_counts(c["item"], W, hot_head=H).reshape(-1)
        for c in chunks])
    assert counts.max() > 1  # the stream really has cold traffic

    runs = {}
    for label, C in (("static", 0), ("fits", int(counts.max())),
                     ("overflows", 1)):
        trainer, store = _make_trainer(mesh, cold_budget=C)
        rec = obs.Recorder(sinks=[])
        tables, _, m = _fit(trainer, chunks, rec)
        runs[label] = (store.dump_model("item_factors")[1], m, rec,
                       trainer)

    static_vals, static_m, _, _ = runs["static"]

    vals, m, rec, trainer = runs["fits"]
    assert int(rec.counter_value("cold_route.compact_chunks")) == len(
        chunks)
    assert rec.counter_value("cold_route.overflow_chunks",
                             table="item_factors") == 0
    assert rec.counter_value("hot_tier.cold_dropped",
                             table="item_factors") == 0
    # The compacted program is a DIFFERENT cache entry...
    assert len(trainer._compiled) == 1
    # ...whose result matches the static route exactly: the lane carries
    # the same cold ids/deltas in the same order, zeros removed.
    assert np.array_equal(vals, static_vals)

    vals, m, rec, trainer = runs["overflows"]
    over = int(rec.counter_value("cold_route.overflow_chunks",
                                 table="item_factors"))
    fit = int(rec.counter_value("cold_route.compact_chunks"))
    # Every chunk was adjudicated; the zero-weight-padded trailing chunk
    # may legitimately fit a 1-wide lane, every full chunk overflows.
    assert fit + over == len(chunks)
    assert over >= len(chunks) - 1
    # Fallback is the cold_budget=0 program (and the rare fitting chunk
    # takes the exact compacted route): BIT-identical everything.
    assert np.array_equal(vals, static_vals)
    assert all(
        np.array_equal(np.asarray(a["se"]), np.asarray(b["se"]))
        and np.array_equal(np.asarray(a["n"]), np.asarray(b["n"]))
        for a, b in zip(m, static_m))


def test_mixed_stream_dispatches_both_programs(devices8):
    """A stream with fitting AND overflowing chunks uses two compiled
    programs (compact + static fallback) and still matches the all-
    static run exactly."""
    from fps_tpu import obs

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    hot = _data(W * 8 * 4, p_cold=0.0, seed=1)     # all-hot chunk
    cold = _data(W * 8 * 4, p_cold=0.9, seed=2)    # cold-heavy chunk
    data = {k: np.concatenate([hot[k], cold[k]]) for k in hot}
    # seed=None: preserve stream order so the hot half lands (mostly) in
    # the first chunk and the cold half later (a shuffle would mix them
    # and make every chunk overflow).
    chunks = _chunks(data, W, seed=None)
    assert len(chunks) >= 2
    # Lane sized to exactly fit the first chunk: skew routing leaks a
    # few cold examples into it, so size from the measured counts and
    # assert a later chunk really exceeds the lane.
    per_chunk = [int(per_worker_cold_counts(
        c["item"], W, hot_head=H).max()) for c in chunks]
    lane = per_chunk[0]
    assert max(per_chunk[1:]) > lane

    trainer, store = _make_trainer(mesh, cold_budget=lane)
    rec = obs.Recorder(sinks=[])
    _fit(trainer, chunks, rec)
    vals = store.dump_model("item_factors")[1]
    assert int(rec.counter_value("cold_route.compact_chunks")) >= 1
    assert int(rec.counter_value("cold_route.overflow_chunks",
                                 table="item_factors")) >= 1
    assert len(trainer._compiled) == 2  # compact + static fallback

    static, sstore = _make_trainer(mesh, cold_budget=0)
    _fit(static, chunks)
    assert np.array_equal(vals, sstore.dump_model("item_factors")[1])


def test_uncertifiable_logic_stays_static(devices8):
    """A logic whose prepare() synthesizes ids (MF negative sampling)
    reports pulled_ids_host=None — every chunk falls back to the static
    program and nothing breaks."""
    from fps_tpu import obs

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    trainer, store = online_mf(
        mesh, MFConfig(num_users=NU, num_items=NI, rank=RANK,
                       negative_samples=1))
    store.specs["item_factors"] = dataclasses.replace(
        store.specs["item_factors"], hot_tier=H,
        dense_collectives=False, cold_budget=8)
    trainer.config = dataclasses.replace(trainer.config, hot_sync_every=2)
    assert trainer.logic.pulled_ids_host(
        {"item": np.zeros(4, np.int32)}) is None
    data = _data(W * 8 * 4, p_cold=0.1)
    rec = obs.Recorder(sinks=[])
    _fit(trainer, _chunks(data, W), rec)
    assert int(rec.counter_value("cold_route.compact_chunks")) == 0
    assert int(rec.counter_value("cold_route.overflow_chunks",
                                 table="item_factors")) >= 1
    assert np.isfinite(store.dump_model("item_factors")[1]).all()


def test_compacted_program_smaller_and_prefetch_identical(devices8):
    """The compacted program's cold-route collective payload is strictly
    smaller than the static program's, and prefetch on/off dispatches
    the same certified programs with identical results (certification
    rides the PlacedChunk's retained host ids)."""
    from fps_tpu.analysis import collective_profile

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    data = _data(W * 8 * 4 * 2, p_cold=0.1)
    chunks = _chunks(data, W)
    lane = int(max(per_worker_cold_counts(
        c["item"], W, hot_head=H).max() for c in chunks))

    trainer, store = _make_trainer(mesh, cold_budget=lane)
    hlo_c = trainer.lowered_chunk_text(chunks[0], "sync")
    static, _ = _make_trainer(mesh, cold_budget=0)
    hlo_s = static.lowered_chunk_text(chunks[0], "sync")
    # Test-scale payloads sit below the default 1KB data-plane
    # threshold — lower it so the comparison sees the routes at all.
    bytes_c = sum(c.payload_bytes for c in collective_profile(hlo_c, 64))
    bytes_s = sum(c.payload_bytes for c in collective_profile(hlo_s, 64))
    assert bytes_c < bytes_s

    tables, _, _ = _fit(trainer, chunks)
    want = store.dump_model("item_factors")[1]

    pf_trainer, pf_store = _make_trainer(mesh, cold_budget=lane)
    pf_trainer.config = dataclasses.replace(pf_trainer.config, prefetch=2)
    from fps_tpu import obs

    rec = obs.Recorder(sinks=[])
    _fit(pf_trainer, chunks, rec)
    assert int(rec.counter_value("cold_route.compact_chunks")) == len(
        chunks)
    assert np.array_equal(pf_store.dump_model("item_factors")[1], want)
