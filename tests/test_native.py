"""Native (C++) ingest tests: build, parse parity, skip-gram semantics.

The toolchain (g++) is part of the supported environment, so these tests
require the native library to build; the ``available() is False`` fallback
path is covered separately by forcing the numpy branch.
"""

import numpy as np
import pytest

from fps_tpu import native


@pytest.fixture(scope="module")
def lib():
    assert native.available(), "g++ toolchain expected in this environment"
    return native


def test_parse_ratings_formats(lib, tmp_path):
    # ML-100K style: tab-separated ints with timestamp.
    p1 = tmp_path / "u.data"
    p1.write_text("1\t10\t3\t881250949\n2\t20\t5\t891717742\n3\t30\t1\t878887116\n")
    u, i, r = lib.parse_ratings(str(p1))
    np.testing.assert_array_equal(u, [1, 2, 3])
    np.testing.assert_array_equal(i, [10, 20, 30])
    np.testing.assert_allclose(r, [3.0, 5.0, 1.0])

    # ML-20M style: csv with header and float ratings.
    p2 = tmp_path / "ratings.csv"
    p2.write_text("userId,movieId,rating,timestamp\n1,2,3.5,1112486027\n7,8,4.0,1112484676\n")
    u, i, r = lib.parse_ratings(str(p2))
    np.testing.assert_array_equal(u, [1, 7])
    np.testing.assert_array_equal(i, [2, 8])
    np.testing.assert_allclose(r, [3.5, 4.0])

    assert lib.parse_ratings(str(tmp_path / "missing")) is None

    # Corrupted data lines must raise, not silently truncate.
    p3 = tmp_path / "bad.data"
    p3.write_text("1\t2\t3\n4\tgarbage\n5\t6\t1\n")
    with pytest.raises(ValueError, match="malformed"):
        lib.parse_ratings(str(p3))

    # A quoted-field csv must raise too — every line is non-digit-leading,
    # so nothing may be silently skipped as a "header".
    p4 = tmp_path / "quoted.csv"
    p4.write_text('"userId","movieId","rating"\n' + "".join(
        f'"{k}","{k+1}","3.5"\n' for k in range(20)))
    with pytest.raises(ValueError, match="malformed"):
        lib.parse_ratings(str(p4))

    # Non-digit garbage after data has started is malformed, not a header.
    p5 = tmp_path / "midfile.data"
    p5.write_text("1\t2\t3\noops line\n5\t6\t1\n")
    with pytest.raises(ValueError, match="malformed"):
        lib.parse_ratings(str(p5))

    # '#' comments are valid anywhere, including a long preamble.
    p6 = tmp_path / "commented.data"
    p6.write_text("".join(f"# preamble {k}\n" for k in range(10))
                  + "1\t2\t3\n# interlude\n4\t5\t2\n")
    u, i, r = lib.parse_ratings(str(p6))
    np.testing.assert_array_equal(u, [1, 4])
    np.testing.assert_allclose(r, [3.0, 2.0])


def test_parse_ratings_matches_loadtxt(lib, tmp_path):
    rng = np.random.default_rng(0)
    n = 5000
    rows = np.stack([
        rng.integers(1, 944, n),
        rng.integers(1, 1683, n),
        rng.integers(1, 6, n),
        rng.integers(0, 10**9, n),
    ], axis=1)
    p = tmp_path / "big.data"
    np.savetxt(p, rows, fmt="%d", delimiter="\t")
    u, i, r = lib.parse_ratings(str(p))
    raw = np.loadtxt(p, dtype=np.int64)
    np.testing.assert_array_equal(u, raw[:, 0])
    np.testing.assert_array_equal(i, raw[:, 1])
    np.testing.assert_allclose(r, raw[:, 2].astype(np.float32))


def test_load_movielens_uses_native(lib, tmp_path):
    from fps_tpu.utils.datasets import load_movielens

    p = tmp_path / "u.data"
    p.write_text("1\t1\t5\t0\n2\t2\t3\t0\n943\t1682\t1\t0\n")
    data, nu, ni = load_movielens(str(p))
    assert (nu, ni) == (943, 1682)
    np.testing.assert_array_equal(data["user"], [0, 1, 942])
    np.testing.assert_allclose(data["rating"], [5.0, 3.0, 1.0])


def test_skipgram_window1_exact(lib):
    """window=1, no subsampling: exactly the adjacent bidirectional pairs."""
    tokens = np.array([4, 7, 2, 9], np.int32)
    c, x = lib.skipgram_pairs(tokens, window=1, seed=0)
    want_c = [4, 7, 7, 2, 2, 9]
    want_x = [7, 4, 2, 7, 9, 2]
    np.testing.assert_array_equal(c, want_c)
    np.testing.assert_array_equal(x, want_x)


def test_skipgram_dynamic_window_validity_and_determinism(lib):
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 50, 2000).astype(np.int32)
    c1, x1 = lib.skipgram_pairs(tokens, window=5, seed=42)
    c2, x2 = lib.skipgram_pairs(tokens, window=5, seed=42)
    np.testing.assert_array_equal(c1, c2)  # deterministic per seed
    c3, _ = lib.skipgram_pairs(tokens, window=5, seed=43)
    assert len(c3) != len(c1) or not np.array_equal(c1, c3)

    # Without subsampling the kept sequence is the input: each emitted pair
    # must occur somewhere in the stream within `window` positions.
    within = set()
    for t in range(len(tokens)):
        for d in range(1, 6):
            if t + d < len(tokens):
                within.add((int(tokens[t]), int(tokens[t + d])))
                within.add((int(tokens[t + d]), int(tokens[t])))
    assert all((int(a), int(b)) in within for a, b in zip(c1[:500], x1[:500]))
    # Expected count: sum over positions of 2*E[half] ≈ 2 * (w+1)/2 * n.
    expect = 2 * (5 + 1) / 2 * len(tokens)
    assert 0.8 * expect < len(c1) < 1.2 * expect


def test_skipgram_subsampling_drops_frequent(lib):
    tokens = np.zeros(5000, np.int32)  # all the same, maximally frequent
    tokens[::10] = 1
    keep_p = np.array([0.05, 1.0], np.float32)
    c, x = lib.skipgram_pairs(tokens, window=2, seed=7, keep_p=keep_p)
    kept0 = np.sum(c == 0) / max(len(c), 1)
    # token 0 is 90% of the stream but should be heavily subsampled away
    assert kept0 < 0.6
    c_all, _ = lib.skipgram_pairs(tokens, window=2, seed=7)
    assert len(c) < len(c_all) / 2


def test_skipgram_chunks_native_vs_numpy_stream(devices8):
    """Both generator paths feed identical-shape chunks and train."""
    from fps_tpu.models.word2vec import W2VConfig, skipgram_chunks

    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 100, 30_000).astype(np.int32)
    uni = np.bincount(tokens, minlength=100).astype(np.float64)
    cfg = W2VConfig(vocab_size=100, dim=8, window=3, negatives=2)

    counts = {}
    for mode in (True, False):
        chunks = list(skipgram_chunks(
            tokens, uni, cfg, num_workers=4, local_batch=64,
            steps_per_chunk=2, seed=3, use_native=mode,
        ))
        for ch in chunks:
            assert ch["center"].shape == (2, 256)
        counts[mode] = sum(float(ch["weight"].sum()) for ch in chunks)
    # Same sampling scheme, different RNG draws: totals within 10%.
    assert abs(counts[True] - counts[False]) / counts[False] < 0.1


def test_parse_ratings_crlf_and_blank_lines(lib, tmp_path):
    """Windows line endings and blank lines (including mid-file and
    trailing) parse cleanly — a bare CR blank line must not count as
    malformed."""
    p = tmp_path / "crlf.csv"
    p.write_bytes(b"userId,movieId,rating\r\n1,2,3.5\r\n\r\n4,5,2.0\r\n\r\n")
    u, i, r = lib.parse_ratings(str(p))
    np.testing.assert_array_equal(u, [1, 4])
    np.testing.assert_array_equal(i, [2, 5])
    np.testing.assert_allclose(r, [3.5, 2.0])


def test_baseline_mf_learns_and_modes_agree(lib):
    """The measured-baseline MF loop must actually train (bench.py's
    equal-target credit depends on it), be deterministic per seed, and the
    message-structured mode must be semantically identical to the fused
    loop (the ring only adds cost, never changes updates)."""
    rng = np.random.default_rng(0)
    nu, ni, rank, n = 300, 200, 4, 20000
    P = rng.normal(0, 0.5, (nu, rank))
    Q = rng.normal(0, 0.5, (ni, rank))
    u = rng.integers(0, nu, n).astype(np.int32)
    i = rng.integers(0, ni, n).astype(np.int32)
    r = (np.sum(P[u] * Q[i], 1) + 0.05 * rng.normal(size=n)).astype(
        np.float32)
    secs_ps, mse_ps = lib.baseline_mf(u, i, r, nu, ni, rank=rank, lr=0.1,
                                      epochs=10, ps_mode=True)
    secs_id, mse_id = lib.baseline_mf(u, i, r, nu, ni, rank=rank, lr=0.1,
                                      epochs=10, ps_mode=False)
    assert mse_ps[-1] < 0.5 * mse_ps[0]          # it learns
    np.testing.assert_allclose(mse_ps, mse_id, rtol=1e-6)  # same semantics
    assert all(s > 0 for s in secs_ps + secs_id)
    # deterministic per seed
    _, mse2 = lib.baseline_mf(u, i, r, nu, ni, rank=rank, lr=0.1, epochs=10,
                              ps_mode=True)
    np.testing.assert_array_equal(mse_ps, mse2)


def test_baseline_w2v_learns_and_modes_agree(lib):
    rng = np.random.default_rng(1)
    V, dim, n = 500, 16, 30000
    # planted co-occurrence: context = center + small offset mod V
    c = rng.integers(0, V, n).astype(np.int32)
    x = ((c + rng.integers(1, 4, n)) % V).astype(np.int32)
    uni = np.bincount(c, minlength=V).astype(np.float64) + 1
    s_ps, loss_ps = lib.baseline_w2v(c, x, uni, dim=dim, negatives=3,
                                     ps_mode=True)
    s_id, loss_id = lib.baseline_w2v(c, x, uni, dim=dim, negatives=3,
                                     ps_mode=False)
    assert loss_ps < 0.6931  # below chance (sigmoid at 0)
    assert abs(loss_ps - loss_id) < 1e-6
    assert s_ps > 0 and s_id > 0


def test_baseline_logreg_learns_and_modes_agree(lib):
    rng = np.random.default_rng(2)
    nf, nnz, n = 5000, 8, 40000
    ids = rng.integers(0, nf, (n, nnz)).astype(np.int32)
    vals = rng.normal(0, 1, (n, nnz)).astype(np.float32)
    w_true = rng.normal(0, 1, nf)
    y = ((vals * w_true[ids]).sum(1) > 0).astype(np.float32)
    s_ps, ll_ps = lib.baseline_logreg(ids, vals, y, nf, ps_mode=True)
    s_id, ll_id = lib.baseline_logreg(ids, vals, y, nf, ps_mode=False)
    assert ll_ps < 0.6        # well below chance logloss 0.693
    assert abs(ll_ps - ll_id) < 1e-6
    assert s_ps > 0 and s_id > 0


def test_baseline_pa_learns_and_modes_agree(lib):
    rng = np.random.default_rng(4)
    nf, nnz, n = 3000, 8, 30000
    ids = rng.integers(0, nf, (n, nnz)).astype(np.int32)
    vals = rng.normal(0, 1, (n, nnz)).astype(np.float32)
    w_true = rng.normal(0, 1, nf)
    y = np.where((vals * w_true[ids]).sum(1) > 0, 1.0, -1.0).astype(
        np.float32)
    s_ps, h_ps, m_ps = lib.baseline_pa(ids, vals, y, nf, ps_mode=True)
    s_id, h_id, m_id = lib.baseline_pa(ids, vals, y, nf, ps_mode=False)
    assert m_ps < 0.35          # online mistakes well below chance 0.5
    assert abs(h_ps - h_id) < 1e-6 and abs(m_ps - m_id) < 1e-9
    assert s_ps > 0 and s_id > 0


def test_baseline_pa_mc_learns_and_modes_agree(lib):
    rng = np.random.default_rng(5)
    nf, nnz, n, nc = 3000, 8, 30000, 6
    ids = rng.integers(0, nf, (n, nnz)).astype(np.int32)
    vals = rng.normal(0, 1, (n, nnz)).astype(np.float32)
    # Planted per-class weights: label = argmax of true class scores.
    w_true = rng.normal(0, 1, (nf, nc))
    scores = np.einsum("bn,bnc->bc", vals, w_true[ids])
    y = np.argmax(scores, axis=-1).astype(np.int32)
    s_ps, h_ps, m_ps = lib.baseline_pa_mc(ids, vals, y, nf, nc, ps_mode=True)
    s_id, h_id, m_id = lib.baseline_pa_mc(ids, vals, y, nf, nc, ps_mode=False)
    chance = 1.0 - 1.0 / nc
    assert m_ps < chance - 0.2    # online mistakes well below chance
    assert abs(h_ps - h_id) < 1e-6 and abs(m_ps - m_id) < 1e-9
    assert s_ps > 0 and s_id > 0


def test_baseline_pa_mc_data_bugs_raise(lib):
    """Data bugs must raise ValueError on the Python side — only
    environment failures (library unavailable / allocation) may map to the
    silent-None baseline drop (ADVICE round 5 low #3)."""
    ids = np.zeros((4, 2), np.int32)
    vals = np.ones((4, 2), np.float32)
    y = np.array([0, 1, 2, 3], np.int32)

    with pytest.raises(ValueError, match="num_classes"):
        lib.baseline_pa_mc(ids, vals, y, 10, 2)  # binary belongs to baseline_pa
    with pytest.raises(ValueError, match="num_classes"):
        lib.baseline_pa_mc(ids, vals, y, 10, lib.PA_MC_MAX_CLASSES + 1)
    with pytest.raises(ValueError, match="labels"):
        lib.baseline_pa_mc(ids, vals, np.array([0, 1, 2, 4], np.int32), 10, 4)
    with pytest.raises(ValueError, match="labels"):
        lib.baseline_pa_mc(ids, vals, np.array([-1, 1, 2, 3], np.int32), 10, 4)

    # Valid data with the library present: a real measurement, not None.
    r = lib.baseline_pa_mc(ids, vals, y, 10, 4)
    assert r is not None and len(r) == 3


def test_baseline_pa_mc_none_reserved_for_env_failure(monkeypatch):
    """With the library unavailable, VALID data returns None (the bench
    drops the baseline) while bad data still raises — the two failure
    classes stay distinguishable."""
    from fps_tpu import native as mod

    monkeypatch.setattr(mod, "_load", lambda: None)
    ids = np.zeros((4, 2), np.int32)
    vals = np.ones((4, 2), np.float32)
    y = np.array([0, 1, 2, 3], np.int32)
    assert mod.baseline_pa_mc(ids, vals, y, 10, 4) is None
    with pytest.raises(ValueError, match="labels"):
        mod.baseline_pa_mc(ids, vals, np.array([9, 9, 9, 9], np.int32), 10, 4)
