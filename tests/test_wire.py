"""Hostile-network survival (docs/resilience.md "Hostile network"):
versioned length-prefixed framing (``fps_tpu.serve.wire``), the
retryable/fatal network-exception split (``classify_net``),
seed-replayable wire fault injection (``fps_tpu.testing.faultnet``),
server-side admission control / deadline enforcement / idempotent
replay (``fps_tpu.serve.net``), and per-reader liveness beacons
(``fps_tpu.serve.fleet``).

The satellite acceptance contract (ISSUE 16):

* framing round-trips arbitrary payloads; EVERY single-byte truncation
  of a valid frame is rejected with the failing layer named — a torn
  frame is never decoded;
* the ``classify_net`` table is exact (timeouts / connection lifecycle
  / transient errnos retry; protocol violations are fatal);
* faultnet schedules are deterministic and replayable (same seed, same
  op stream, same evidence trail);
* a reconnecting client resending an in-flight request id is deduped —
  the server executes once and replays the cached response.
"""

import errno
import json
import os
import socket
import time

import numpy as np
import pytest

from fps_tpu.core import retry as retry_mod
from fps_tpu.core.retry import (
    DEFAULT_NET_RETRY,
    RETRYABLE_NET_ERRNOS,
    classify_net,
    classify_path,
    net_fault_check,
)
from fps_tpu.serve import wire
from fps_tpu.serve.fleet import (
    DEFAULT_LIVENESS_TIMEOUT_S,
    FleetReader,
    liveness_check,
    scan_heartbeats,
)
from fps_tpu.serve.net import JsonlClient, TcpServe, handle_request
from fps_tpu.serve.server import ReadServer
from fps_tpu.serve.snapshot import ServableSnapshot
from fps_tpu.serve.wire import (
    MAGIC,
    MAX_PAYLOAD,
    OP_ERR,
    OP_HELLO,
    OP_HELLO_OK,
    OP_REQ,
    OP_RESP,
    PROTO_VERSION,
    FrameTooLargeError,
    ProtocolVersionError,
    ServerBusyError,
    TornFrameError,
    WireClient,
    decode_frame,
    encode_frame,
)
from fps_tpu.testing import faultnet
from fps_tpu.testing.faultnet import FaultNet, NetFaultRule


@pytest.fixture(autouse=True)
def _clean_injector():
    """Every test leaves the process net injector uninstalled — a
    leaked schedule would fault unrelated tests' sockets."""
    yield
    faultnet.uninstall()


# ---------------------------------------------------------------------------
# Framing units.
# ---------------------------------------------------------------------------


def test_frame_roundtrip_random_payloads():
    rng = np.random.default_rng(0)
    payloads = [b"", b"{}", bytes(rng.integers(0, 256, 1, np.uint8)),
                bytes(rng.integers(0, 256, 4096, np.uint8)),
                json.dumps({"op": "pull", "ids": list(range(64))},
                           ).encode()]
    for i, payload in enumerate(payloads):
        data = encode_frame(OP_REQ, i + 1, payload)
        fr = decode_frame(data)
        assert fr.op == OP_REQ
        assert fr.req_id == i + 1
        assert fr.payload == payload
        assert fr.version == PROTO_VERSION


def test_every_single_byte_truncation_rejected():
    data = encode_frame(OP_RESP, 7, b'{"ok": true}')
    # Zero bytes is a CLEAN EOF at a frame boundary, not a torn frame.
    assert wire.read_frame(__import__("io").BytesIO(b"")) is None
    for n in range(1, len(data)):
        with pytest.raises(TornFrameError) as e:
            decode_frame(data[:n])
        # The failing layer is named (header / payload / crc trailer).
        assert "torn frame" in str(e.value), n


def test_bad_magic_rejected():
    data = encode_frame(OP_REQ, 1, b"{}")
    with pytest.raises(TornFrameError, match="bad magic"):
        decode_frame(b"XXXX" + data[4:])


def test_unknown_version_rejected():
    data = encode_frame(OP_REQ, 1, b"{}", version=99)
    with pytest.raises(ProtocolVersionError, match="99"):
        decode_frame(data)


def test_flipped_payload_byte_fails_crc():
    data = bytearray(encode_frame(OP_REQ, 1, b'{"op": "stats"}'))
    data[wire._HEADER.size + 3] ^= 0xFF
    with pytest.raises(TornFrameError, match="crc mismatch"):
        decode_frame(bytes(data))


def test_oversized_length_prefix_rejected_before_allocation():
    # A corrupt length prefix must reject WITHOUT reading the payload.
    head = wire._HEADER.pack(MAGIC, PROTO_VERSION, OP_REQ, 0, 1,
                             MAX_PAYLOAD + 1)
    with pytest.raises(FrameTooLargeError):
        decode_frame(head)
    with pytest.raises(FrameTooLargeError):
        encode_frame(OP_REQ, 1, b"x" * (MAX_PAYLOAD + 1))


def test_torn_frame_is_a_connection_error():
    # The retry loop treats a torn frame as "the connection is garbage":
    # reconnect-and-resend, which classify_net already blesses.
    assert issubclass(TornFrameError, ConnectionError)
    assert classify_net(TornFrameError("x")) == "retryable"


# ---------------------------------------------------------------------------
# classify_net + the wire retry policy.
# ---------------------------------------------------------------------------


def test_classify_net_table_exact():
    retryable = [TimeoutError("t"), ConnectionResetError("r"),
                 ConnectionRefusedError("c"), BrokenPipeError("b"),
                 EOFError("e"), ConnectionError("closed"),
                 OSError(errno.EHOSTUNREACH, "x")]
    for err in retryable:
        assert classify_net(err) == "retryable", err
    fatal = [OSError(errno.EACCES, "x"), OSError("no errno"),
             ValueError("v"), ProtocolVersionError("p"),
             FrameTooLargeError("f")]
    for err in fatal:
        assert classify_net(err) == "fatal", err
    for code in sorted(RETRYABLE_NET_ERRNOS):
        assert classify_net(OSError(code, "x")) == "retryable", code


def test_default_net_retry_tighter_than_storage():
    # A query client must degrade in seconds, not inherit the storage
    # plane's patience.
    assert DEFAULT_NET_RETRY.retries == 5
    assert DEFAULT_NET_RETRY.deadline_s <= 5.0
    assert DEFAULT_NET_RETRY.max_backoff_s <= 0.5
    seq = [DEFAULT_NET_RETRY.backoff_s(i) for i in range(6)]
    assert seq == [DEFAULT_NET_RETRY.backoff_s(i) for i in range(6)]


# ---------------------------------------------------------------------------
# faultnet: schedule semantics, determinism, env contract.
# ---------------------------------------------------------------------------


def test_faultnet_env_mirror():
    assert faultnet.FAULTNET_ENV == retry_mod.FAULTNET_ENV


def test_rule_validation_rejects_illegal_combos():
    with pytest.raises(ValueError):
        NetFaultRule("serve", "recv", "cut")       # cut is send-only
    with pytest.raises(ValueError):
        NetFaultRule("serve", "send", "refuse")    # refuse is connect
    with pytest.raises(ValueError):
        NetFaultRule("serve", "connect", "drop")   # drop is accept-only
    with pytest.raises(ValueError):
        NetFaultRule("serve", "*", "cut")          # '*' only for delay
    with pytest.raises(ValueError):
        NetFaultRule("serve", "send", "nonsense")
    with pytest.raises(ValueError):
        NetFaultRule("serve", "send", "cut", every=0)
    with pytest.raises(ValueError):
        NetFaultRule("serve", "send", "cut", prob=0.0)
    NetFaultRule("*", "*", "delay", delay_s=0.001)  # legal wildcard


def test_rule_window_semantics():
    # count is the WINDOW WIDTH [start, start+count), not a fire count:
    # start=2, count=9, every=3 fires at n = 2, 5, 8.
    r = NetFaultRule("c", "send", "cut", start=2, count=9, every=3)
    fired = [n for n in range(20) if r.matches("c", "send", n, seed=0)]
    assert fired == [2, 5, 8]
    forever = NetFaultRule("c", "send", "cut", start=1, count=None,
                           every=4)
    fired = [n for n in range(14) if forever.matches("c", "send", n, 0)]
    assert fired == [1, 5, 9, 13]
    assert not r.matches("other", "send", 2, 0)  # class targeted
    assert not r.matches("c", "recv", 2, 0)      # op targeted


def _drive(net: FaultNet, n: int = 40):
    """A synthetic deterministic op stream over two peer classes."""
    for i in range(n):
        for cls in ("client", "serve"):
            for op in ("connect", "send", "recv"):
                try:
                    net.check(op, cls)
                except (ConnectionError, TimeoutError, OSError):
                    pass


def test_faultnet_same_seed_same_trail():
    rules = [NetFaultRule("client", "connect", "refuse", start=3,
                          count=None, every=5, prob=0.6),
             NetFaultRule("serve", "send", "cut", start=0, count=20,
                          every=4),
             NetFaultRule("*", "*", "delay", delay_s=0.0, start=10,
                          count=None, every=7, prob=0.4)]
    a = FaultNet(rules, seed=7, sleep=lambda s: None)
    b = FaultNet(rules, seed=7, sleep=lambda s: None)
    _drive(a)
    _drive(b)
    assert a.trail() == b.trail()
    assert a.trail(), "schedule fired nothing — test is vacuous"
    c = FaultNet(rules, seed=8, sleep=lambda s: None)
    _drive(c)
    assert c.trail() != a.trail()  # distinct seeds desynchronize prob


def test_faultnet_quiesce_heals_but_keeps_evidence():
    net = FaultNet([NetFaultRule("c", "connect", "refuse", start=0,
                                 count=None)], seed=0)
    with pytest.raises(ConnectionRefusedError):
        net.check("connect", "c")
    net.quiesce()
    assert net.check("connect", "c") is None  # healed
    assert net.injected_counts() == {("c", "connect", "refuse"): 1}


def test_spec_roundtrip_string_and_file(tmp_path):
    rules = [NetFaultRule("serve", "send", "trickle", chunk=3,
                          delay_s=0.001, start=1, count=None, every=2)]
    net = FaultNet(rules, seed=5)
    again = FaultNet.from_spec(net.to_spec())
    assert again.rules == net.rules and again.seed == 5
    p = tmp_path / "schedule.json"
    p.write_text(net.to_spec(), encoding="utf-8")
    from_file = FaultNet.from_spec(str(p))
    assert from_file.rules == net.rules and from_file.seed == 5


def test_env_self_install(tmp_path, monkeypatch):
    """A process launched with FPS_TPU_FAULTNET self-installs the
    schedule at the first seam crossing — no imports required of it."""
    net = FaultNet([NetFaultRule("client", "connect", "refuse",
                                 start=0, count=1)], seed=0)
    monkeypatch.setenv(retry_mod.FAULTNET_ENV, net.to_spec())
    monkeypatch.setattr(retry_mod, "_net_injector", None)
    monkeypatch.setattr(retry_mod, "_net_env_checked", False)
    try:
        with pytest.raises(ConnectionRefusedError):
            net_fault_check("connect", "client")
        assert net_fault_check("connect", "client") is None  # count=1
        assert net_fault_check("send", "serve") is None  # other stream
    finally:
        retry_mod.remove_net_injector()
        monkeypatch.setattr(retry_mod, "_net_env_checked", False)


def test_cut_and_trickle_directives():
    net = FaultNet([NetFaultRule("c", "send", "cut", cut_bytes=6,
                                 start=0, count=1),
                    NetFaultRule("c", "send", "trickle", chunk=2,
                                 delay_s=0.0, start=1, count=1)],
                   seed=0)
    assert net.check("send", "c") == ("cut", 6)
    assert net.check("send", "c") == ("trickle", 2, 0.0)
    assert net.check("send", "c") is None


# ---------------------------------------------------------------------------
# End-to-end: WireClient <-> TcpServe.
# ---------------------------------------------------------------------------


def _snapshot():
    rng = np.random.default_rng(3)
    tables = {"weights": rng.normal(size=(64, 4)).astype(np.float32)}
    return ServableSnapshot(11, "test-wire", tables, [], "none")


def _tcp(**kw):
    server = ReadServer()
    server.swap_to(_snapshot())
    return server, TcpServe(server, **kw).start()


def _raw_conn(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=5.0)
    return s, s.makefile("rb")


def test_wire_client_roundtrip_matches_handle_request():
    server, tcp = _tcp()
    try:
        with WireClient("127.0.0.1", tcp.port,
                        peer_class="client") as c:
            assert c.version == PROTO_VERSION
            req = {"op": "pull", "table": "weights", "ids": [1, 5, 9]}
            got = c.request(req)
            want = handle_request(server, req)
            assert got == json.loads(json.dumps(want))
            assert c.request({"op": "stats"})["ok"]
            # Application-level errors return unchanged, NOT retried.
            bad = c.request({"op": "bogus"})
            assert not bad["ok"] and c.retries == 0
        assert tcp.wire_stats()["framed_conns"] == 1
    finally:
        tcp.close()


def test_legacy_line_json_is_rejected_with_op_err():
    # The PR-16 dual stack is retired: a raw line-JSON peer fails the
    # first frame's magic gate, is counted as a torn frame, answered
    # with one OP_ERR frame, and dropped — never served a line reply.
    server, tcp = _tcp()
    try:
        sock, rfile = _raw_conn(tcp.port)
        try:
            sock.sendall(json.dumps(
                {"op": "pull", "table": "weights",
                 "ids": [0]}).encode() + b"\n")
            fr = wire.read_frame(rfile)
            assert fr.op == OP_ERR and not fr.json()["ok"]
            assert rfile.read(1) == b""  # dropped after the OP_ERR
        finally:
            sock.close()
        stats = tcp.wire_stats()
        assert stats["torn_frames"] == 1
        assert stats["framed_conns"] == 1  # every conn is framed now
    finally:
        tcp.close()


def test_jsonl_client_is_a_framed_shim():
    server, tcp = _tcp()
    try:
        with JsonlClient("127.0.0.1", tcp.port) as c:
            assert c.request({"op": "stats"})["ok"]
        # The compat shim speaks the FRAMED wire, not line-JSON.
        assert tcp.wire_stats()["framed_conns"] == 1
        assert tcp.wire_stats()["torn_frames"] == 0
    finally:
        tcp.close()


def test_replay_cache_is_byte_bounded_with_lru_eviction_order():
    """The (session, req_id) replay cache evicts by BYTES, oldest-touched
    first: cache cost is response-size-dependent, and a 16 MiB-response
    tenant must not be able to hold unbounded memory behind a generous
    entry cap. Pins the eviction order, the byte accounting, and the
    replay_evictions counter."""
    server = ReadServer()
    tcp = TcpServe(server, replay_cache=1024,
                   replay_cache_bytes=100).start()
    try:
        put, get = tcp._replay_put, tcp._replay_get
        put(("s", 1), b"a" * 40)
        put(("s", 2), b"b" * 40)
        assert tcp.replay_bytes() == 80
        assert tcp.wire_stats()["replay_evictions"] == 0
        # Touch 1 so 2 becomes the LRU victim.
        assert get(("s", 1)) == b"a" * 40
        put(("s", 3), b"c" * 40)  # 120 > 100: evicts exactly (s, 2)
        assert get(("s", 2)) is None
        assert get(("s", 1)) == b"a" * 40
        assert get(("s", 3)) == b"c" * 40
        assert tcp.replay_bytes() == 80
        assert tcp.wire_stats()["replay_evictions"] == 1
        # Re-putting a key replaces its bytes, never double-counts.
        put(("s", 1), b"A" * 10)
        assert tcp.replay_bytes() == 50
        # An entry bigger than the whole budget flushes everything
        # older — but NEVER itself: the just-executed response is in
        # flight (a reconnecting client may resend its req_id, and a
        # replay miss means a duplicate execution), so the newest entry
        # survives even when it alone exceeds the byte bound.
        put(("s", 4), b"x" * 101)
        assert get(("s", 4)) == b"x" * 101
        assert tcp.replay_bytes() == 101
        assert tcp.wire_stats()["replay_evictions"] == 3
    finally:
        tcp.close()


def test_dedupe_on_reconnect_executes_once():
    """Server response frame cut mid-send -> client sees a torn frame,
    reconnects, resends the SAME req_id -> server replays the cached
    response instead of executing twice."""
    server, tcp = _tcp()
    try:
        # serve/send stream: n=0 HELLO_OK, n=1 first response (cut),
        # n=2 HELLO_OK on reconnect, n=3 cached replay.
        faultnet.install([NetFaultRule("serve", "send", "cut",
                                       cut_bytes=5, start=1, count=1)],
                         seed=0)
        executed_before = server.requests
        with WireClient("127.0.0.1", tcp.port,
                        peer_class="client") as c:
            resp = c.request({"op": "pull", "table": "weights",
                              "ids": [2, 3]})
            assert resp["ok"]
            assert c.reconnects == 1 and c.retries >= 1
        stats = tcp.wire_stats()
        assert stats["dedup_replays"] == 1
        assert server.requests == executed_before + 1  # at-most-once
    finally:
        tcp.close()


def test_busy_shed_is_retryable_and_bounded():
    server, tcp = _tcp(max_inflight=1)
    try:
        # Wedge the whole cost budget: every request sheds.
        assert tcp.admission.try_admit(tcp.admission.max_cost)
        try:
            c = WireClient("127.0.0.1", tcp.port, peer_class="client",
                           deadline_s=0.3)
            with pytest.raises(ServerBusyError):
                c.request({"op": "stats"})
            assert c.busy_rejections >= 1
            assert c.deadline_exceeded == 1
            assert c.reconnects == 0  # BUSY never drops the connection
            assert tcp.wire_stats()["shed_requests"] >= 1
        finally:
            tcp.admission.release(tcp.admission.max_cost)
        # The budget freed: the SAME client recovers on its next request.
        assert c.request({"op": "stats"})["ok"]
        c.close()
    finally:
        tcp.close()


def test_dead_on_arrival_deadline_not_executed():
    server, tcp = _tcp()
    try:
        executed_before = server.requests
        sock, rfile = _raw_conn(tcp.port)
        try:
            def _send(op, req_id, obj):
                sock.sendall(encode_frame(op, req_id, json.dumps(
                    obj).encode()))

            _send(OP_HELLO, 0, {"versions": [PROTO_VERSION],
                                "session": "doa"})
            assert wire.read_frame(rfile).op == OP_HELLO_OK
            _send(OP_REQ, 1, {"d": 0.0, "q": {"op": "pull",
                                              "table": "weights",
                                              "ids": [0]}})
            fr = wire.read_frame(rfile)
            assert fr.op == OP_RESP and fr.req_id == 1
            resp = fr.json()
            assert resp["deadline_exceeded"] and resp["retryable"]
        finally:
            sock.close()
        assert tcp.wire_stats()["deadline_exceeded"] == 1
        assert server.requests == executed_before  # never executed
    finally:
        tcp.close()


def test_version_negotiation_rejects_loudly():
    server, tcp = _tcp()
    try:
        sock, rfile = _raw_conn(tcp.port)
        try:
            sock.sendall(encode_frame(OP_HELLO, 0, json.dumps(
                {"versions": [99], "session": "v99"}).encode()))
            fr = wire.read_frame(rfile)
            assert fr.op == OP_ERR
            body = fr.json()
            assert "no common protocol version" in body["error"]
            assert body["supported"] == list(wire.SUPPORTED_VERSIONS)
        finally:
            sock.close()
    finally:
        tcp.close()


def test_garbage_after_magic_byte_counted_as_torn():
    server, tcp = _tcp()
    try:
        sock, _ = _raw_conn(tcp.port)
        try:
            # First byte routes to the framed path; the rest is junk.
            sock.sendall(MAGIC[:1] + b"garbage-not-a-frame")
            sock.shutdown(socket.SHUT_WR)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if tcp.wire_stats()["torn_frames"]:
                    break
                time.sleep(0.01)
        finally:
            sock.close()
        assert tcp.wire_stats()["torn_frames"] == 1
        assert tcp.wire_stats()["framed_conns"] == 1
    finally:
        tcp.close()


def test_client_retries_through_injected_resets():
    server, tcp = _tcp()
    try:
        # connect #0 is the constructor (no-retry by contract); faults
        # start at #1 so only request-path reconnects are faulted.
        faultnet.install([NetFaultRule("client", "send", "cut",
                                       cut_bytes=4, start=2, count=5,
                                       every=2)], seed=0)
        with WireClient("127.0.0.1", tcp.port,
                        peer_class="client") as c:
            for i in range(4):
                assert c.request({"op": "stats"})["ok"], i
            assert c.retries >= 1 and c.reconnects >= 1
    finally:
        tcp.close()


# ---------------------------------------------------------------------------
# Per-reader liveness beacons.
# ---------------------------------------------------------------------------


def test_reader_heartbeat_beacon_on_poll(tmp_path):
    d = str(tmp_path)
    r = FleetReader(d, "r0", heartbeat_interval_s=0.0)
    r.poll()  # nothing servable yet — the beacon still beats
    beats = scan_heartbeats(d)
    assert set(beats) == {"r0"}
    assert beats["r0"]["polls"] == 1 and beats["r0"]["step"] is None
    assert beats["r0"]["age_s"] < DEFAULT_LIVENESS_TIMEOUT_S
    assert os.path.exists(r.heartbeat_path)


def test_liveness_check_fresh_stale_and_missing(tmp_path):
    d = str(tmp_path)
    r = FleetReader(d, "r0", heartbeat_interval_s=0.0)
    r.poll()
    fresh = liveness_check(d)
    assert fresh["wedged"] == [] and "r0" in fresh["ages"]
    # Judged 10s in the future the same beacon is stale -> wedged.
    stale = liveness_check(d, timeout_s=5.0, now=time.time() + 10.0)
    assert stale["wedged"] == ["r0"]
    # An expected reader that never wrote a beacon is wedged too —
    # a reader that never came up must not be a silent absence.
    ghost = liveness_check(d, expected=["r0", "ghost"])
    assert ghost["wedged"] == ["ghost"]
    assert ghost["ages"]["ghost"] is None


def test_liveness_check_empty_dir(tmp_path):
    rep = liveness_check(str(tmp_path))
    assert rep == {"ages": {}, "wedged": []}


def test_heartbeat_path_class_is_liveness():
    assert classify_path("/ckpt/fleet/heartbeat_r0.json") == "liveness"
    assert classify_path("/ckpt/fleet/ready_r0.json") != "liveness"
