"""Sketch module tests: estimator guarantees + device-side/mergeable use.

Mirrors the upstream sketch module's purpose (co-occurrence similarity from
a stream) with convergence-style assertions, per the test strategy of
asserting invariants rather than exact values (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fps_tpu import sketch as sk


def zipf_stream(n, vocab, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.zipf(1.3, n) % vocab).astype(np.int32)


def test_count_min_overestimates_and_is_accurate_for_heavy_hitters():
    spec = sk.CountMinSpec(depth=4, width=2048, seed=1)
    ids = zipf_stream(20_000, 500)
    s = sk.cm_update(spec, sk.cm_init(spec), jnp.asarray(ids))
    true = np.bincount(ids, minlength=500).astype(np.float32)
    probe = np.arange(500, dtype=np.int32)
    est = np.asarray(sk.cm_query(spec, s, jnp.asarray(probe)))
    assert np.all(est >= true - 1e-4)  # never underestimates
    heavy = np.argsort(-true)[:20]
    np.testing.assert_allclose(est[heavy], true[heavy], rtol=0.05)


def test_count_min_drops_negative_ids_and_merges():
    spec = sk.CountMinSpec(depth=3, width=256, seed=2)
    ids = np.array([5, -1, 5, 7, -1], np.int32)
    s = sk.cm_update(spec, sk.cm_init(spec), jnp.asarray(ids))
    est = np.asarray(sk.cm_query(spec, s, jnp.asarray(np.array([5, 7], np.int32))))
    assert est[0] == 2.0 and est[1] == 1.0
    # merge of two half-streams == one full stream
    s1 = sk.cm_update(spec, sk.cm_init(spec), jnp.asarray(ids[:3]))
    s2 = sk.cm_update(spec, sk.cm_init(spec), jnp.asarray(ids[3:]))
    np.testing.assert_allclose(np.asarray(sk.merge(s1, s2)), np.asarray(s))


def test_decayed_cm_halves_on_schedule_and_queries_like_cm():
    """The fold schedule is exact: a constant window stream's state is a
    closed-form geometric sum, and a single-window state queries exactly
    like the plain count-min (same hashing via spec.cm())."""
    spec = sk.DecayedCountMinSpec(depth=3, width=256, seed=2, half_every=2)
    ids = np.array([5, 5, 7], np.int32)
    win = np.asarray(sk.cm_update(spec.cm(), sk.cm_init(spec.cm()),
                                  jnp.asarray(ids)))
    st = sk.dcm_init(spec)
    for t in range(4):  # folds at ticks 0..3, halvings before ticks 2
        st = sk.dcm_fold(spec, st, win, t)
    # weights per window (oldest->newest): 1/2, 1/2, 1, 1 -> total 3x
    np.testing.assert_allclose(st, 3.0 * win)
    est = np.asarray(sk.dcm_query(spec, win,
                                  jnp.asarray(np.array([5, 7], np.int32))))
    assert est[0] == 2.0 and est[1] == 1.0


def test_decayed_cm_decay_merge_commute():
    """Linearity contract: folding the elementwise-MERGED windows of two
    substreams equals merging the separately folded states — the psum
    merge and the halve-on-schedule decay commute."""
    spec = sk.DecayedCountMinSpec(depth=4, width=512, seed=3, half_every=3)
    rng = np.random.default_rng(0)
    wins_a, wins_b = [], []
    for _ in range(7):
        for wins in (wins_a, wins_b):
            ids = rng.integers(0, 300, 200).astype(np.int32)
            wins.append(np.asarray(sk.cm_update(
                spec.cm(), sk.cm_init(spec.cm()), jnp.asarray(ids))))
    merged_then_fold = sk.dcm_init(spec)
    fold_a = sk.dcm_init(spec)
    fold_b = sk.dcm_init(spec)
    for t, (wa, wb) in enumerate(zip(wins_a, wins_b)):
        merged_then_fold = sk.dcm_fold(
            spec, merged_then_fold, np.asarray(sk.merge(wa, wb)), t)
        fold_a = sk.dcm_fold(spec, fold_a, wa, t)
        fold_b = sk.dcm_fold(spec, fold_b, wb, t)
    np.testing.assert_array_equal(merged_then_fold,
                                  np.asarray(sk.merge(fold_a, fold_b)))


def test_decayed_cm_forgets_stale_hot_set():
    """Drift regression: after the hot set rotates, the decayed ranking
    follows the NEW head within a few half-lives while the undecayed
    count-min stays pinned to the stale one."""
    spec = sk.DecayedCountMinSpec(depth=4, width=2048, seed=4, half_every=2)
    vocab, probe = 400, np.arange(400, dtype=np.int32)
    rng = np.random.default_rng(5)

    def window(shift):
        ids = ((rng.zipf(1.5, 4000) + shift) % vocab).astype(np.int32)
        return np.asarray(sk.cm_update(spec.cm(), sk.cm_init(spec.cm()),
                                       jnp.asarray(ids)))

    decayed = sk.dcm_init(spec)
    flat = sk.dcm_init(spec)
    tick = 0
    for _ in range(8):  # phase 1: head near id 0
        w = window(0)
        decayed = sk.dcm_fold(spec, decayed, w, tick)
        flat = flat + w
        tick += 1
    for _ in range(8):  # phase 2: head rotates to id 200
        w = window(200)
        decayed = sk.dcm_fold(spec, decayed, w, tick)
        flat = flat + w
        tick += 1
    top_decayed = np.argsort(-np.asarray(sk.dcm_query(
        spec, decayed, jnp.asarray(probe))))[:10]
    top_flat = np.argsort(-np.asarray(sk.dcm_query(
        spec, flat, jnp.asarray(probe))))[:10]
    new_head = set(range(200, 210))
    assert len(new_head & set(top_decayed.tolist())) >= 7
    # The undecayed fold still ranks the stale phase-1 head comparably —
    # the failure mode the decay exists to fix.
    assert len(new_head & set(top_flat.tolist())) < 7


def test_decayed_cm_rejects_bad_schedule():
    import pytest

    with pytest.raises(ValueError, match="half_every"):
        sk.DecayedCountMinSpec(half_every=0)
    spec = sk.DecayedCountMinSpec()
    with pytest.raises(ValueError, match="tick"):
        sk.dcm_fold(spec, sk.dcm_init(spec), sk.dcm_init(spec), -1)


def test_tug_of_war_inner_product_estimates_cooccurrence_similarity():
    """Two context-frequency vectors; the sketch inner product must track the
    true inner product — the co-occurrence similarity use case."""
    spec = sk.TugOfWarSpec(depth=9, width=4096, seed=3)
    rng = np.random.default_rng(4)
    vocab = 1000
    # word A and word B share contexts; word C does not.
    base = (rng.zipf(1.4, 8000) % vocab).astype(np.int32)
    ctx_a = base[:6000]
    ctx_b = np.concatenate([base[2000:6000], (rng.zipf(1.4, 2000) % vocab).astype(np.int32)])
    ctx_c = ((rng.zipf(1.4, 6000) + 350) % vocab).astype(np.int32)

    sketches = {}
    for name, ctx in [("a", ctx_a), ("b", ctx_b), ("c", ctx_c)]:
        sketches[name] = sk.tow_update(spec, sk.tow_init(spec), jnp.asarray(ctx))

    def true_inner(x, y):
        fx = np.bincount(x, minlength=vocab).astype(np.float64)
        fy = np.bincount(y, minlength=vocab).astype(np.float64)
        return float(fx @ fy)

    est_ab = float(sk.tow_inner(sketches["a"], sketches["b"]))
    est_ac = float(sk.tow_inner(sketches["a"], sketches["c"]))
    true_ab = true_inner(ctx_a, ctx_b)
    true_ac = true_inner(ctx_a, ctx_c)
    assert abs(est_ab - true_ab) / true_ab < 0.15
    assert est_ab > est_ac  # similar words stay more similar than dissimilar


def test_tug_of_war_point_query_unbiased():
    spec = sk.TugOfWarSpec(depth=7, width=2048, seed=5)
    ids = zipf_stream(10_000, 300, seed=6)
    s = sk.tow_update(spec, sk.tow_init(spec), jnp.asarray(ids))
    true = np.bincount(ids, minlength=300).astype(np.float32)
    heavy = np.argsort(-true)[:10].astype(np.int32)
    est = np.asarray(sk.tow_query(spec, s, jnp.asarray(heavy)))
    np.testing.assert_allclose(est, true[heavy], rtol=0.1, atol=5)


def test_bucket_hash_covers_large_widths():
    """Widths above 2^16 must actually use the full table (regression: a
    fixed 16-bit shift once capped every sketch at 65536 slots)."""
    from fps_tpu.sketch import _bucket, _hash_constants

    a, b = _hash_constants(0, 2)
    ids = jnp.asarray(np.arange(200_000, dtype=np.int32))
    cols = np.asarray(_bucket(ids, jnp.asarray(a), jnp.asarray(b), 1 << 20))
    assert cols.max() >= (1 << 16), "buckets capped below width"
    # occupancy close to the balls-in-bins expectation (~17.4% for 2e5 balls
    # into 2^20 bins per row)
    frac = len(np.unique(cols[0])) / (1 << 20)
    assert 0.12 < frac < 0.25


def test_bloom_filter_no_false_negatives():
    spec = sk.BloomSpec(num_hashes=4, num_bits=1 << 14, seed=7)
    rng = np.random.default_rng(8)
    members = rng.choice(100_000, 500, replace=False).astype(np.int32)
    bits = sk.bloom_add(spec, sk.bloom_init(spec), jnp.asarray(members))
    assert bool(np.all(sk.bloom_contains(spec, bits, jnp.asarray(members))))
    # false positive rate is low at this load factor
    non = np.setdiff1d(np.arange(100_000, 200_000), members)[:5000].astype(np.int32)
    fp = float(np.mean(np.asarray(sk.bloom_contains(spec, bits, jnp.asarray(non)))))
    assert fp < 0.02
    # negative ids are dropped, not inserted
    bits2 = sk.bloom_add(spec, sk.bloom_init(spec), jnp.asarray(np.array([-1], np.int32)))
    assert float(jnp.sum(bits2)) == 0.0


def test_sketch_inside_compiled_step_and_psum_merge(devices8):
    """Sketches are device state: update inside a jitted shard_map step and
    merge across workers with psum — the distributed substream pattern."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fps_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS, make_ps_mesh

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8)
    spec = sk.CountMinSpec(depth=3, width=512, seed=9)
    ids = zipf_stream(8 * 1000, 200, seed=10)

    def device_fn(local_ids):
        s = sk.cm_update(spec, sk.cm_init(spec), local_ids)
        return jax.lax.psum(jax.lax.psum(s, SHARD_AXIS), DATA_AXIS)

    fn = jax.jit(jax.shard_map(
        device_fn, mesh=mesh,
        in_specs=P((DATA_AXIS, SHARD_AXIS)), out_specs=P(),
        check_vma=False,
    ))
    merged = fn(jax.device_put(
        jnp.asarray(ids), NamedSharding(mesh, P((DATA_AXIS, SHARD_AXIS)))
    ))
    single = sk.cm_update(spec, sk.cm_init(spec), jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(merged), np.asarray(single))


def test_tow_update_rows_matches_per_row_updates():
    """The batched multi-sketch scatter must equal P independent
    tow_update calls with per-row masks (drop semantics included)."""
    spec = sk.TugOfWarSpec(depth=3, width=64, seed=11)
    rng = np.random.default_rng(0)
    B, P = 200, 4
    ids = rng.integers(-1, 500, B).astype(np.int32)
    rows = rng.integers(-1, P, B).astype(np.int32)
    vals = rng.random(B).astype(np.float32)

    stack = sk.tow_update_rows(
        spec, jnp.zeros((P, spec.depth, spec.width), jnp.float32),
        jnp.asarray(rows), jnp.asarray(ids), jnp.asarray(vals),
    )
    for p in range(P):
        ref = sk.tow_update(spec, sk.tow_init(spec), jnp.asarray(ids),
                            jnp.asarray(np.where(rows == p, vals, 0.0)))
        np.testing.assert_allclose(np.asarray(stack[p]), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)
