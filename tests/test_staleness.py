"""Staleness semantics: SSP bounded reads + delayed (in-flight) pushes.

The reference is asynchronous by construction: workers read values that may
be stale AND their pushes are in flight on the network (SURVEY.md §2.2).
``TrainerConfig.sync_every`` bounds read staleness; ``push_delay`` delays
write visibility — together they bracket free-running asynchrony. These
tests pin (a) the delivery invariant (delayed pushes lose nothing and
double-apply nothing) and (b) graceful convergence degradation as the
staleness knobs grow toward the async limit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fps_tpu.core.api import StepOutput, WorkerLogic
from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of
from fps_tpu.core.ingest import multi_epoch_chunks
from fps_tpu.core.store import ParamStore, TableSpec
from fps_tpu.models.matrix_factorization import (
    MFConfig,
    online_mf,
    predict_host,
    rmse,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import synthetic_ratings, train_test_split


class _ConstantPusher(WorkerLogic):
    """Pushes delta == batch value to id == batch id — read-independent, so
    any correct delivery schedule must produce identical final tables."""

    def pull_ids(self, batch):
        return {"t": batch["id"].astype(jnp.int32)}

    def step(self, batch, pulled, local_state, key):
        ids = jnp.where(batch["weight"] > 0, batch["id"].astype(jnp.int32), -1)
        deltas = batch["val"][:, None].astype(jnp.float32)
        out = {"n": jnp.sum(batch["weight"]).astype(jnp.float32)}
        return StepOutput(pushes={"t": (ids, deltas)},
                          local_state=local_state, out=out)


@pytest.mark.parametrize("sync_every", [None, 2])
@pytest.mark.parametrize("delay", [1, 3, 8])
def test_push_delay_delivers_exactly_once(devices8, sync_every, delay):
    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)
    rng = np.random.default_rng(0)
    n = 1000
    data = {
        "id": rng.integers(0, 37, n).astype(np.int32),
        "val": rng.normal(0, 1, n).astype(np.float32),
    }

    def run(d):
        store = ParamStore(mesh, [TableSpec("t", 37, 1).zeros_init()])
        trainer = Trainer(
            mesh, store, _ConstantPusher(),
            config=TrainerConfig(sync_every=sync_every, push_delay=d,
                                 donate=False),
        )
        tables, ls = trainer.init_state(jax.random.key(0))
        chunks = multi_epoch_chunks(
            data, 2, num_workers=W, local_batch=16, steps_per_chunk=4,
            sync_every=sync_every, seed=3,
        )
        tables, ls, m = trainer.fit_stream(tables, ls, chunks,
                                           jax.random.key(1))
        return store.dump_model("t")[1]

    base = run(0)
    got = run(delay)
    # Every push delivered exactly once (order is irrelevant for the
    # additive fold up to fp rounding).
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-5)


def test_staleness_sweep_degrades_gracefully(devices8):
    """MF convergence vs (sync_every, push_delay): quality may degrade as
    the knobs grow toward the async limit, but must degrade gracefully —
    every configuration still learns on the planted low-rank set."""
    mesh = make_ps_mesh(num_shards=8, num_data=1, devices=devices8[:8])
    W = num_workers_of(mesh)
    NU, NI, NR = 96, 64, 6000
    data = synthetic_ratings(NU, NI, NR, rank=3, noise=0.05, seed=3)
    train, test = train_test_split(data)

    def run(sync_every, delay, lr, epochs):
        cfg = MFConfig(num_users=NU, num_items=NI, rank=4,
                       learning_rate=lr, reg=0.005)
        trainer, store = online_mf(mesh, cfg, sync_every=sync_every,
                                   push_delay=delay)
        tables, ls = trainer.init_state(jax.random.key(0))
        chunks = multi_epoch_chunks(
            train, epochs, num_workers=W, local_batch=32,
            steps_per_chunk=max(8, sync_every or 0),
            route_key="user", sync_every=sync_every, seed=11,
        )
        tables, ls, _ = trainer.fit_stream(tables, ls, chunks,
                                           jax.random.key(1))
        pred = predict_host(store, np.asarray(ls), W, test["user"],
                            test["item"])
        return rmse(pred, test["rating"])

    # The async-SGD stability recipe: the stable learning rate shrinks with
    # the total staleness (read lag + write delay), and the cost of
    # asynchrony is paid in steps-to-quality, not in reachable quality.
    results = {
        ("sync", 0): run(None, 0, lr=0.08, epochs=3),
        ("s=4", 0): run(4, 0, lr=0.08, epochs=3),
        ("s=4", 4): run(4, 4, lr=0.04, epochs=6),
        ("s=16", 16): run(16, 16, lr=0.02, epochs=6),
    }
    # Untrained predicts ~0 -> RMSE near the rating std (~0.6); every
    # staleness configuration must clearly beat that.
    for k, v in results.items():
        assert v < 0.42, (k, v, results)
    # Read-stale + write-delayed at the scaled lr reaches (near-)sync
    # quality — degradation is graceful, not a cliff.
    assert results[("s=4", 4)] < results[("sync", 0)] * 1.35 + 0.05, results


class _PaddingProbe(WorkerLogic):
    """Pulls a fixed id vector whose tail is -1 padding and reports the
    max |value| read through those padding slots — must be 0 on every
    pull route (the zero-row contract for drop-sentinel ids)."""

    def __init__(self, num_rows):
        self.num_rows = num_rows

    def pull_ids(self, batch):
        ids = batch["id"].astype(jnp.int32)
        # Second half of every batch is -1 padding.
        half = ids.shape[0] // 2
        ids = ids.at[half:].set(-1)
        return {"t": ids}

    def step(self, batch, pulled, local_state, key):
        half = batch["id"].shape[0] // 2
        pad_max = jnp.max(jnp.abs(pulled["t"][half:]))
        out = {"pad_max": pad_max}
        ids = jnp.full_like(batch["id"], -1, dtype=jnp.int32)
        deltas = jnp.zeros((ids.shape[0], 1), jnp.float32)
        return StepOutput(pushes={"t": (ids, deltas)},
                          local_state=local_state, out=out)


def test_ssp_snapshot_pull_zeroes_padding_ids(devices8):
    """The SSP snapshot pull must honor the -1 zero-row contract when
    num_shards > 1: id_to_phys's floor-mod would wrap -1 onto the live
    physical row (S-1)*rps-1, silently reading a real parameter. The
    table is all-ones, so any wrap shows up as pad_max == 1."""
    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)
    R = 40
    store = ParamStore(
        mesh,
        [TableSpec("t", R, 1,
                   init_fn=lambda key, ids: jnp.ones(
                       (ids.shape[0], 1), jnp.float32))],
    )
    trainer = Trainer(
        mesh, store, _PaddingProbe(R),
        config=TrainerConfig(sync_every=2, donate=False),
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    data = {"id": rng.integers(0, R, 256).astype(np.int32)}
    chunks = multi_epoch_chunks(
        data, 1, num_workers=W, local_batch=16, steps_per_chunk=4,
        sync_every=2, seed=3,
    )
    _, _, metrics = trainer.fit_stream(tables, ls, chunks, jax.random.key(1))
    for m in metrics:
        assert float(np.max(m["pad_max"])) == 0.0
