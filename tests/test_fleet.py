"""Fleet rollups + SLO burn (fps_tpu.obs.fleet, obs_report --fleet).

Synthetic per-host obs dirs (the aggregator is a pure JSONL consumer)
pin the windowing math, the fleet signals (throughput, tiering hit rate,
cold-route certification rate, freshness, restart/fence counts), the SLO
burn-rate semantics, and the ``tools/obs_report.py --fleet`` CLI.
"""

import importlib.util
import json
import os

import pytest

from fps_tpu.obs.fleet import (
    DEFAULT_SLOS,
    SLO,
    evaluate_slos,
    fleet_digest,
    host_series,
    rollup,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(_ROOT, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _metric(t, name, value, mtype="counter", **labels):
    rec = {"kind": "metric", "t": t, "name": name, "mtype": mtype,
           "value": value}
    if labels:
        rec["labels"] = labels
    return rec


def _event(t, etype, **fields):
    return {"kind": "event", "t": t, "event": etype, **fields}


def _write(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def _host_dir(tmp_path, name, *, t0, chunks=4, restart_at=None):
    """One host's synthetic trail: per-chunk counter increments 10s
    apart, a freshness gauge, and optionally a supervisor restart."""
    d = str(tmp_path / name)
    events = []
    journal = [_event(t0, "run_start", run_id=name + "-run")]
    for i in range(chunks):
        t = t0 + 10.0 * i
        events += [
            _metric(t, "driver.chunks", 1),
            _metric(t, "driver.examples", 1000),
            _metric(t, "hot_tier.hot_rows", 90, table="item"),
            _metric(t, "hot_tier.pulled_rows", 100, table="item"),
            _metric(t, "cold_route.compact_chunks", 1),
            _metric(t, "serve.write_to_servable_s", 2.0 + i,
                    mtype="gauge"),
        ]
        if i == chunks - 1:
            events.append(_metric(t, "cold_route.overflow_chunks", 1,
                                  table="item"))
    if restart_at is not None:
        journal.append(_event(t0 + restart_at, "supervisor_restart",
                              attempt=1))
    _write(os.path.join(d, "events-p0.jsonl"), events)
    _write(os.path.join(d, "journal-supervisor.jsonl"), journal)
    return d


def test_host_series_and_totals(tmp_path):
    d = _host_dir(tmp_path, "h0", t0=1000.0)
    s = host_series(d)
    assert sum(v for _, v in s["counters"]["driver.examples"]) == 4000
    assert len(s["samples"]["serve.write_to_servable_s"]) == 4

    roll = rollup([d], num_windows=1)
    tot = roll["totals"]
    assert tot["examples"] == 4000
    assert tot["chunks"] == 4
    assert tot["hot_hit_rate"] == pytest.approx(0.9)
    # 4 compact + 1 overflow chunk-samples -> 0.8 certification.
    assert tot["cold_route_cert_rate"] == pytest.approx(0.8)
    assert tot["freshness_s_max"] == pytest.approx(5.0)
    assert tot["restarts"] == 0


def test_rollup_windows_split_and_fold_hosts(tmp_path):
    d0 = _host_dir(tmp_path, "h0", t0=1000.0)
    d1 = _host_dir(tmp_path, "h1", t0=1000.0, restart_at=15.0)
    roll = rollup([d0, d1], window_s=20.0)
    assert roll["hosts"] == ["h0", "h1"]
    assert roll["window_s"] == 20.0
    # Span 0..30s -> two 20s windows.
    assert len(roll["windows"]) == 2
    w0, w1 = roll["windows"]
    # Window 0 holds chunk samples at t=0s,10s from BOTH hosts.
    assert w0["examples"] == 4000 and w1["examples"] == 4000
    assert w0["restarts"] == 1 and w1["restarts"] == 0
    assert w0["examples_per_sec"] == pytest.approx(200.0)
    # The totals row folds both hosts across the whole span.
    assert roll["totals"]["examples"] == 8000
    assert roll["totals"]["restarts"] == 1


def test_rollup_empty_dirs(tmp_path):
    d = str(tmp_path / "empty")
    os.makedirs(d)
    roll = rollup([d])
    assert roll["windows"] == [] and roll["totals"] is None
    digest = fleet_digest([d])
    assert digest["slo"] == {s.name: pytest.approx(
        digest["slo"][s.name]) for s in DEFAULT_SLOS}  # shape only
    for v in digest["slo"].values():
        assert v["windows_evaluated"] == 0 and v["ok"]


def test_slo_semantics_and_burn_rate():
    slo = SLO("fresh", "freshness_s_max", "<=", 10.0, objective=0.9)
    assert slo.good(5.0) and not slo.good(11.0) and slo.good(None) is None
    with pytest.raises(ValueError):
        SLO("bad", "x", "==", 1.0)
    with pytest.raises(ValueError):
        SLO("bad", "x", ">=", 1.0, objective=1.5)

    windows = [{"freshness_s_max": v} for v in (5.0, 12.0, None, 5.0,
                                                 5.0)]
    out = evaluate_slos({"windows": windows}, [slo])["fresh"]
    # 4 evaluated, 1 bad -> bad_fraction 0.25; error budget 0.1 ->
    # burn 2.5: the objective is being missed 2.5x faster than allowed.
    assert out["windows_evaluated"] == 4
    assert out["bad_windows"] == 1
    assert out["bad_fraction"] == pytest.approx(0.25)
    assert out["burn_rate"] == pytest.approx(2.5)
    assert out["ok"] is False

    clean = evaluate_slos(
        {"windows": [{"freshness_s_max": 1.0}] * 10}, [slo])["fresh"]
    assert clean["ok"] and clean["burn_rate"] == 0.0


def test_fleet_digest_slo_burn_on_synthetic_fleet(tmp_path):
    d0 = _host_dir(tmp_path, "h0", t0=1000.0)
    d1 = _host_dir(tmp_path, "h1", t0=1000.0, restart_at=5.0)
    digest = fleet_digest([d0, d1], window_s=8.0)
    assert digest["schema"] == 1
    slo = digest["slo"]
    assert set(slo) == {s.name for s in DEFAULT_SLOS}
    # Certification dips below 0.9 only in the overflow window.
    cert = slo["cold_route_certification"]
    assert cert["windows_evaluated"] >= 3 and cert["bad_windows"] == 1
    # One restart window out of 4 at objective 0.75 -> burn 1.0 (ok:
    # the budget is exactly spent, not overspent).
    rst = slo["restart_quiet"]
    assert rst["windows_evaluated"] == 4
    assert rst["bad_windows"] == 1 and rst["ok"]
    assert slo["budget_drift_quiet"]["bad_windows"] == 0


def test_obs_report_fleet_cli(tmp_path, capsys):
    report = _load_report()
    d0 = _host_dir(tmp_path, "h0", t0=1000.0)
    d1 = _host_dir(tmp_path, "h1", t0=1000.0)
    assert report.main(["--fleet", d0, d1, "--window-s", "20",
                        "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["schema"] == 1
    assert out["rollup"]["hosts"] == ["h0", "h1"]
    assert out["rollup"]["totals"]["examples"] == 8000
    assert set(out["slo"]) == {s.name for s in DEFAULT_SLOS}
    # Host digests ride along (the member dirs hold supervisor journals
    # only -> the standard digest still renders, with zero chunks... or
    # None when a dir has no digestible files at all).
    assert set(out["host_digests"]) == {"h0", "h1"}
    assert out["host_digests"]["h0"]["schema"] == 1

    # Multiple dirs without --fleet is an error, as is --json --pretty.
    with pytest.raises(SystemExit):
        report.main([d0, d1])
    with pytest.raises(SystemExit):
        report.main([d0, "--json", "--pretty"])
    # Empty fleet: loud exit 2.
    empty = str(tmp_path / "none")
    os.makedirs(empty)
    assert report.main(["--fleet", empty]) == 2
