"""Two-tier hot storage (TableSpec.hot_tier + TrainerConfig.hot_sync_every).

The contracts under test, per docs/performance.md "Two-tier storage":

* **exact mode is provably free** — with ``hot_sync_every=1`` (or the
  tier off) the driver lowers the IDENTICAL untiered program; tables,
  metrics, and checkpoint BYTES are bit-identical on MF, logreg, and
  w2v;
* **tiered runs keep one canonical table** — every compiled call ends
  with a flush reconcile, so at any boundary the replicated hot head is
  a pure projection of the sharded table (checkpoints need no special
  casing; restore re-splits);
* **full replication statically elides the collective routes** — a
  fully-hot table's per-chunk program carries no pull/push
  all_gather/all_to_all at all, only the windowed reconcile psum;
* resilience composes: rollback quarantines restore replica+table as a
  unit, checkpoint resume is bit-identical to a straight tiered run.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np
import pytest

import jax

from fps_tpu.core.checkpoint import Checkpointer
from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.resilience import RollbackPolicy
from fps_tpu.core.store import (
    TableSpec,
    hot_key,
    id_to_phys,
    rows_per_shard,
)
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
)
from fps_tpu.parallel.mesh import key_to_replicated, make_ps_mesh
from fps_tpu.testing import chaos
from fps_tpu.testing.workloads import (
    NF,
    logreg_chunks,
    logreg_data,
    weights,
)


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _make_trainer(mesh, *, hot_tier=0, hot_sync_every=1, sync_every=None,
                  guard=None, **cfg_over):
    trainer, store = logistic_regression(
        mesh, LogRegConfig(num_features=NF, learning_rate=0.5),
        guard=guard, sync_every=sync_every,
    )
    if hot_tier:
        for name, spec in store.specs.items():
            store.specs[name] = dataclasses.replace(
                spec, hot_tier=min(hot_tier, spec.num_ids))
    cfg_over["hot_sync_every"] = hot_sync_every
    trainer.config = dataclasses.replace(trainer.config, **cfg_over)
    return trainer, store


def _fit(trainer, chunks, **kw):
    tables, ls = trainer.init_state(jax.random.key(0))
    return trainer.fit_stream(tables, ls, iter(chunks), jax.random.key(1),
                              **kw)


# ---------------------------------------------------------------------------
# Exact mode: hot_sync_every=1 is bit-identical to the untiered path.
# ---------------------------------------------------------------------------

def test_exact_mode_bit_identical_logreg_with_checkpoint_bytes(
        tmp_path, devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    runs = {}
    for name, (H, E) in {"untiered": (0, 1), "exact": (64, 1)}.items():
        trainer, store = _make_trainer(mesh, hot_tier=H, hot_sync_every=E)
        d = tmp_path / name
        with Checkpointer(str(d)) as ckpt:
            _, _, m = _fit(trainer, chunks, checkpointer=ckpt,
                           checkpoint_every=2)
        runs[name] = (weights(store), m, d)
    w0, m0, d0 = runs["untiered"]
    w1, m1, d1 = runs["exact"]
    assert np.array_equal(w0, w1)
    assert _tree_equal(m0, m1)
    # Checkpoint BYTES identical: one canonical table per spec either way.
    files0 = sorted(p.name for p in d0.iterdir() if p.suffix == ".npz")
    files1 = sorted(p.name for p in d1.iterdir() if p.suffix == ".npz")
    assert files0 == files1 and files0
    for f in files0:
        assert (d0 / f).read_bytes() == (d1 / f).read_bytes(), f


def test_exact_mode_bit_identical_mf_indexed(devices8):
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    data = synthetic_ratings(48, 32, 64 * W, rank=3, seed=0)
    runs = {}
    for name, H in (("untiered", 0), ("exact", 12)):
        trainer, store = online_mf(
            mesh, MFConfig(num_users=48, num_items=32, rank=4))
        if H:
            store.specs["item_factors"] = dataclasses.replace(
                store.specs["item_factors"], hot_tier=H)
        # hot_sync_every stays 1: the exact mode.
        ds = DeviceDataset(mesh, data)
        plan = DeviceEpochPlan(ds, num_workers=W, local_batch=8,
                               route_key="user")
        tables, ls = trainer.init_state(jax.random.key(0))
        tables, ls, m = trainer.run_indexed(tables, ls, plan,
                                            jax.random.key(3))
        runs[name] = (store.dump_model("item_factors")[1], m)
    assert np.array_equal(runs["untiered"][0], runs["exact"][0])
    assert _tree_equal(runs["untiered"][1], runs["exact"][1])


def test_exact_mode_bit_identical_w2v(devices8):
    from fps_tpu.models.word2vec import (
        W2VConfig, Word2VecDevicePlan, word2vec_block,
    )
    from fps_tpu.utils.datasets import synthetic_corpus

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    tokens = synthetic_corpus(40, 1500, seed=0)
    uni = np.bincount(tokens, minlength=40).astype(np.float64)
    cfg = W2VConfig(vocab_size=40, dim=8, window=2, negatives=2,
                    subsample_t=None)
    runs = {}
    for name, H in (("untiered", 0), ("exact", 10)):
        trainer, store = word2vec_block(mesh, cfg, uni, 16,
                                        max_steps_per_call=8)
        if H:
            for t in ("in_embeddings", "out_embeddings"):
                store.specs[t] = dataclasses.replace(
                    store.specs[t], hot_tier=H)
        plan = Word2VecDevicePlan(tokens, uni, cfg, mesh, num_workers=W,
                                  block_len=16, seed=0, mode="block")
        tables, ls = trainer.init_state(jax.random.key(0))
        tables, ls, m = trainer.run_indexed(tables, ls, plan,
                                            jax.random.key(4))
        runs[name] = (store.dump_model("in_embeddings")[1], m)
    assert np.array_equal(runs["untiered"][0], runs["exact"][0])
    assert _tree_equal(runs["untiered"][1], runs["exact"][1])


def test_lowered_hlo_unchanged_when_tier_disengaged(devices8):
    """Adding the tier machinery must not perturb the untiered program:
    tier off, exact mode (H set, E=1), and E set with H=0 all lower to
    byte-identical text — the zero-cost claim, proven at the same
    altitude as tests/test_prefetch.py."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)

    def lowered(**kw):
        trainer, _ = _make_trainer(mesh, **kw)
        tables, ls = trainer.init_state(jax.random.key(0))
        tables = trainer._attach_hot(tables)
        batches = trainer._place_chunk(chunks[0], "sync")
        key = key_to_replicated(jax.random.key(1), mesh)
        return trainer._get_compiled("sync").lower(
            tables, ls, batches, key).as_text()

    base = lowered()
    assert lowered(hot_tier=64, hot_sync_every=1) == base
    assert lowered(hot_tier=0, hot_sync_every=4) == base


# ---------------------------------------------------------------------------
# Engaged tier: canonical-table invariant, routing, determinism.
# ---------------------------------------------------------------------------

def test_tiered_sync_invariant_and_determinism(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    results = []
    for _ in range(2):
        trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3)
        tables, _, m = _fit(trainer, chunks)
        results.append((weights(store), m))
        # Boundary invariant: the replica is a pure projection of the
        # canonical table's head rows after every compiled call.
        assert hot_key("weights") in tables
        rep = np.asarray(tables[hot_key("weights")])
        assert np.array_equal(rep, store.lookup_host("weights",
                                                     np.arange(64)))
        assert np.isfinite(results[-1][0]).all()
        # Telemetry channel: per-chunk hit counts ride the out stream.
        assert "hot_tier" in m[0]
        hot = np.sum(np.asarray(m[0]["hot_tier"]["weights"]["hot_rows"]))
        pulled = np.sum(
            np.asarray(m[0]["hot_tier"]["weights"]["pulled_rows"]))
        assert 0 < hot <= pulled
    assert np.array_equal(results[0][0], results[1][0])
    assert _tree_equal(results[0][1], results[1][1])


def test_tiered_full_replication_elides_collective_routes(devices8):
    """H >= num_ids: the pull/push collective routes must be statically
    GONE from the per-chunk program — the NuPS replicate-the-hot-table
    regime and the source of the bench A/B's strictly-fewer-collectives
    win. What remains is the SHARDED window reconcile (PR 10): one
    reduce-scatter + one re-broadcast all-gather per window, plus scalar
    metric reductions."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)

    def lowered(**kw):
        trainer, _ = _make_trainer(mesh, **kw)
        tables, ls = trainer.init_state(jax.random.key(0))
        tables = trainer._attach_hot(tables)
        batches = trainer._place_chunk(chunks[0], "sync")
        key = key_to_replicated(jax.random.key(1), mesh)
        return trainer._get_compiled("sync").lower(
            tables, ls, batches, key).as_text()

    pat = re.compile(r"stablehlo\.(all_to_all|collective_permute)")
    off_text = lowered()
    on_text = lowered(hot_tier=NF, hot_sync_every=4)
    n_off = len(pat.findall(off_text))
    n_on = len(pat.findall(on_text))
    assert n_off > 0  # the untiered program really pays data collectives
    assert n_on == 0, f"tiered program still carries {n_on} route ops"
    # The reconcile is the sharded RS+AG pair — present in the tiered
    # program, absent untiered (the untiered push rides all_to_all).
    assert "stablehlo.reduce_scatter" in on_text
    assert "stablehlo.reduce_scatter" not in off_text
    # The only all_gathers left are the reconcile re-broadcasts — the
    # pull/push gather routes (which dominate the untiered count) are
    # statically gone.
    n_ag_on = len(re.findall(r"stablehlo\.all_gather", on_text))
    n_rs_on = len(re.findall(r"stablehlo\.reduce_scatter", on_text))
    assert n_ag_on == n_rs_on, (
        f"{n_ag_on} all_gathers vs {n_rs_on} reconcile reduce_scatters "
        "— a gather route survived full replication")


def test_tiered_ssp_runs_and_reconciles_per_round(devices8):
    from fps_tpu.core.ingest import multi_epoch_chunks

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = list(multi_epoch_chunks(
        train, 2, num_workers=num_workers_of(mesh), local_batch=32,
        steps_per_chunk=8, sync_every=4, seed=3))
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=2,
                                   sync_every=4)
    tables, _, m = _fit(trainer, chunks)
    w = weights(store)
    assert np.isfinite(w).all()
    rep = np.asarray(tables[hot_key("weights")])
    assert np.array_equal(rep, store.lookup_host("weights", np.arange(64)))


def test_tiered_mean_combine_windowed_reconcile(devices8):
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    trainer, store = online_mf(
        mesh, MFConfig(num_users=32, num_items=24, rank=4), combine="mean")
    store.specs["item_factors"] = dataclasses.replace(
        store.specs["item_factors"], hot_tier=24)
    trainer.config = dataclasses.replace(trainer.config, hot_sync_every=3)
    data = synthetic_ratings(32, 24, 64 * W, rank=3, seed=0)
    chunk = next(epoch_chunks(data, num_workers=W, local_batch=8,
                              steps_per_chunk=4, route_key="user"))
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, m = trainer.run_chunk(tables, ls, chunk, jax.random.key(2))
    vals = store.dump_model("item_factors")[1]
    assert np.isfinite(vals).all()
    rep = np.asarray(tables[hot_key("item_factors")])
    assert np.array_equal(rep, store.lookup_host("item_factors",
                                                 np.arange(24)))


# ---------------------------------------------------------------------------
# Resilience composition: rollback, checkpoint resume.
# ---------------------------------------------------------------------------

def test_tiered_rollback_quarantines_and_restores_unit(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    poisoned = list(chaos.poison_chunks(
        iter(chunks), chunk_index=1, column="feat_vals", kind="nan",
        frac=0.5, seed=1))
    pol = RollbackPolicy()
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3,
                                   guard="observe")
    tables, _, _ = _fit(trainer, poisoned, rollback=pol)
    assert pol.quarantined == [1]
    w = weights(store)
    assert np.isfinite(w).all()
    # The rollback restored replica + canonical table as one unit: the
    # projection invariant still holds at the end of the stream.
    rep = np.asarray(tables[hot_key("weights")])
    assert np.array_equal(rep, store.lookup_host("weights", np.arange(64)))


def test_tiered_checkpoint_resume_bit_identical(tmp_path, devices8):
    """A checkpoint written under the tier is one canonical table;
    restore re-splits the replica and the resumed run reproduces the
    straight tiered run bit-for-bit."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)

    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3)
    _fit(trainer, chunks)
    want = weights(store)

    d = str(tmp_path / "ck")
    trainer, store = _make_trainer(mesh, hot_tier=64, hot_sync_every=3)
    tables, ls = trainer.init_state(jax.random.key(0))

    class Stop(Exception):
        pass

    def stop_at(i, _m):
        if i == 1:
            raise Stop

    with Checkpointer(d) as ckpt:
        with pytest.raises(Stop):
            trainer.fit_stream(
                tables, ls, iter(chunks), jax.random.key(1),
                checkpointer=ckpt, checkpoint_every=1, on_chunk=stop_at,
            )
        start = ckpt.latest_valid_step()
        assert start and start >= 1
        tables, ls, start = trainer.restore_checkpoint(ckpt, ls)
        # restore hands back the canonical (cold-only) table set; the
        # run entry re-splits it.
        assert not any(k.endswith("::hot") for k in tables)
        trainer.fit_stream(
            tables, ls, iter(chunks[start:]), jax.random.key(1),
            start_step=start,
        )
    assert np.array_equal(weights(store), want)


# ---------------------------------------------------------------------------
# Telemetry: recorder counters + gauge.
# ---------------------------------------------------------------------------

def test_hot_tier_recorder_counters(devices8):
    from fps_tpu import obs

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)
    trainer, _ = _make_trainer(mesh, hot_tier=64, hot_sync_every=3)
    rec = obs.Recorder(sinks=[])
    trainer.recorder = rec
    _fit(trainer, chunks)
    hot = rec.counter_value("hot_tier.hot_rows", table="weights")
    pulled = rec.counter_value("hot_tier.pulled_rows", table="weights")
    assert 0 < hot <= pulled
    snap = rec.snapshot()
    assert any(k.startswith("hot_tier.pending_delta")
               for k in snap["gauges"])


# ---------------------------------------------------------------------------
# Resolution policy + satellite error paths (direct unit tests).
# ---------------------------------------------------------------------------

def _unit_trainer(devices8, **spec_over):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    trainer, store = _make_trainer(mesh)
    spec = store.specs["weights"]
    if spec_over:
        spec = dataclasses.replace(spec, **spec_over)
        store.specs["weights"] = spec
    return trainer, spec


def test_resolve_hot_rows_bad_string_raises(devices8):
    trainer, spec = _unit_trainer(devices8, hot_ids="asuto")
    with pytest.raises(ValueError, match="asuto"):
        trainer._resolve_hot_rows(spec)


def test_resolve_dense_bad_string_raises(devices8):
    trainer, spec = _unit_trainer(devices8, dense_collectives="yes")
    with pytest.raises(ValueError, match="yes"):
        trainer._resolve_dense(spec)


def test_resolve_hot_tier_bad_values_raise(devices8):
    trainer, spec = _unit_trainer(devices8, hot_tier="asuto")
    with pytest.raises(ValueError, match="asuto"):
        trainer._resolve_hot_tier(spec)
    trainer, spec = _unit_trainer(devices8, hot_tier=-1)
    with pytest.raises(ValueError, match="-1"):
        trainer._resolve_hot_tier(spec)


def test_resolve_hot_tier_policy(devices8):
    """The tier engages exactly where it can win and stay correct."""
    trainer, spec = _unit_trainer(devices8, hot_tier=64)
    assert trainer._resolve_hot_tier(spec) == 0  # E=1: exact mode
    trainer.config = dataclasses.replace(trainer.config, hot_sync_every=4)
    assert trainer._resolve_hot_tier(spec) == 64
    # Over-asked H clamps to the table.
    big = dataclasses.replace(spec, hot_tier=10 * NF)
    assert trainer._resolve_hot_tier(big) == NF
    # Single-device mesh: nothing to save.
    mesh1 = make_ps_mesh(num_shards=1, num_data=1, devices=devices8[:1])
    tr1, store1 = _make_trainer(mesh1, hot_tier=64, hot_sync_every=4)
    assert tr1._resolve_hot_tier(store1.specs["weights"]) == 0
    # The max/min combines now ride the tier too (PR 10: windowed
    # extremum pending buffer); only per-push folds (apply_fn / callable
    # combine) keep the gathered route.
    from fps_tpu.core.api import ServerLogic
    trainer.server_logic["weights"] = ServerLogic(combine="max")
    assert trainer._resolve_hot_tier(spec) == 64
    trainer.server_logic["weights"] = ServerLogic(
        apply_fn=lambda rows, delta: rows + delta)
    assert trainer._resolve_hot_tier(spec) == 0
    trainer.server_logic["weights"] = ServerLogic(
        combine=lambda summed, counts: summed)
    assert trainer._resolve_hot_tier(spec) == 0


def test_hot_tier_push_delay_rejected(devices8):
    trainer, _ = _unit_trainer(devices8, hot_tier=64)
    trainer.config = dataclasses.replace(
        trainer.config, hot_sync_every=4, push_delay=2)
    with pytest.raises(ValueError, match="push_delay"):
        trainer._hot_tier_map()


def test_owner_major_head_layout_invariant(devices8):
    """Global id h lives in local row ``h // S`` on shard ``h % S`` —
    pinned directly against per-id-deterministic init values, and the
    derived head replica matches the canonical head rows."""
    from fps_tpu.core.store import ParamStore

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    S, NIDS, H = 4, 10, 7

    def init(key, ids):
        return jax.numpy.stack(
            [ids.astype(np.float32), ids.astype(np.float32) * 10.0], axis=1)

    store = ParamStore(mesh, [TableSpec("t", NIDS, 2, init_fn=init,
                                        hot_tier=H)])
    store.init(jax.random.key(0))
    rps = rows_per_shard(NIDS, S)
    full = store._host_table("t")  # physical (owner-major) layout
    for h in range(NIDS):
        phys = (h % S) * rps + h // S
        assert phys == int(id_to_phys(np.int32(h), S, rps))
        assert np.array_equal(full[phys], [h, 10.0 * h]), h
    # Shard s's block holds exactly the ids congruent to s (mod S).
    for s in range(S):
        block = full[s * rps:(s + 1) * rps]
        for j in range(rps):
            gid = j * S + s
            if gid < NIDS:
                assert block[j][0] == gid
    rep = np.asarray(store.head_replica("t", H))
    assert rep.shape == (H, 2)
    assert np.array_equal(rep, store.lookup_host("t", np.arange(H)))
    with pytest.raises(ValueError, match="hot_rows"):
        store.head_replica("t", NIDS + 1)


# ---------------------------------------------------------------------------
# Sharded reconcile + stateful hot folds (PR 10).
# ---------------------------------------------------------------------------

def test_sharded_reconcile_lowers_rs_ag_not_psum(devices8):
    """The window reconcile is the reduce-scatter -> owned-slice apply ->
    all-gather exchange (arXiv:2004.13336), not a full-head all_reduce:
    the tiered program carries the RS, and its byte payload is the
    padded head, not the batch."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)
    trainer, _ = _make_trainer(mesh, hot_tier=64, hot_sync_every=4)
    hlo = trainer.lowered_chunk_text(chunks[0], "sync")
    from fps_tpu.analysis import collective_profile

    prof = collective_profile(hlo, 64)
    kinds = {c.kind for c in prof}
    assert "reduce_scatter" in kinds
    # H=64, dim=1 (logreg weights), f32 accumulator, padded to S=4.
    assert any(c.kind == "reduce_scatter" and c.payload_bytes == 64 * 4
               for c in prof)


def _fold_trainer(mesh, *, fold="adagrad", H=NF, E=3, combine="sum"):
    trainer, store = _make_trainer(mesh, hot_tier=H, hot_sync_every=E)
    trainer.server_logic["weights"] = dataclasses.replace(
        trainer.server_logic["weights"], combine=combine, hot_fold=fold)
    return trainer, store


def test_hot_fold_validation(devices8):
    from fps_tpu.core.api import HotFold

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    # Partial head: the fold would fork semantics between head and tail.
    trainer, store = _fold_trainer(mesh, H=64)
    with pytest.raises(ValueError, match="PARTIAL head"):
        trainer._hot_tier_map()
    # Tier disengaged (exact mode): a silently-dropped optimizer is an
    # error, not a fallback.
    trainer, store = _fold_trainer(mesh, H=NF, E=1)
    with pytest.raises(ValueError, match="resolve ON"):
        trainer._hot_tier_map()
    # Extremum combine cannot feed a delta-sum fold.
    trainer, store = _fold_trainer(mesh, combine="max")
    with pytest.raises(ValueError, match="'sum'/'mean'"):
        trainer._hot_tier_map()
    # Typo'd kind fails at construction, not first dispatch.
    with pytest.raises(ValueError, match="adagrid"):
        HotFold(kind="adagrid")
    # The happy path resolves with the fold attached.
    trainer, store = _fold_trainer(mesh)
    assert trainer._hot_tier_map() == {"weights": NF}
    assert trainer._hot_fold_map()["weights"].kind == "adagrad"


@pytest.mark.parametrize("fold", ["adagrad", "adam"])
def test_hot_fold_runs_deterministic_and_state_sharded(devices8, fold):
    """A stateful hot-fold run is deterministic, keeps the projection
    invariant, carries its state SHARDED (never replicated) under the
    ::fold aux key, and actually changes the trajectory vs the plain
    additive fold (the state is load-bearing)."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)
    results = []
    for _ in range(2):
        trainer, store = _fold_trainer(mesh, fold=fold)
        tables, _, m = _fit(trainer, chunks)
        results.append((weights(store), m))
        state = tables["weights::fold"]
        from fps_tpu.core.api import HotFold
        from fps_tpu.core.store import hot_fold_state_shape

        assert tuple(state.shape) == hot_fold_state_shape(
            HotFold(kind=fold), NF, 1, 4)
        # Sharded over the shard axis — each device holds 1/S rows.
        assert len(state.sharding.device_set) == 4
        shard_rows = {(s.index[0].start, s.index[0].stop)
                      for s in state.addressable_shards}
        assert len(shard_rows) == 4, "fold state is replicated, not sharded"
        assert np.isfinite(results[-1][0]).all()
        rep = np.asarray(tables[hot_key("weights")])
        assert np.array_equal(rep, store.lookup_host("weights",
                                                     np.arange(NF)))
    assert np.array_equal(results[0][0], results[1][0])
    assert _tree_equal(results[0][1], results[1][1])
    plain, pstore = _make_trainer(mesh, hot_tier=NF, hot_sync_every=3)
    _fit(plain, chunks)
    assert not np.array_equal(weights(pstore), results[0][0])


def test_hot_fold_checkpoint_resume_bit_identical_and_canonical(
        tmp_path, devices8):
    """Fold state rides the snapshot as fold:: arrays: resume replays
    bit-identically, while the canonical table bytes stay restorable by
    an UNTIERED trainer (which drops the fold kind)."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=2)

    trainer, store = _fold_trainer(mesh)
    _fit(trainer, chunks)
    want = weights(store)

    d = str(tmp_path / "ck")
    trainer, store = _fold_trainer(mesh)
    tables, ls = trainer.init_state(jax.random.key(0))

    class Stop(Exception):
        pass

    def stop_at(i, _m):
        if i == 1:
            raise Stop

    with Checkpointer(d) as ckpt:
        with pytest.raises(Stop):
            trainer.fit_stream(
                tables, ls, iter(chunks), jax.random.key(1),
                checkpointer=ckpt, checkpoint_every=1, on_chunk=stop_at,
            )
        # The snapshot carries the state under its own kind.
        import glob as _g
        import os as _os
        snaps = sorted(_g.glob(_os.path.join(d, "ckpt_*.npz")))
        with np.load(snaps[-1]) as z:
            assert any(k.startswith("fold::") for k in z.files)
            assert "table::weights" in z.files
        tables, ls, start = trainer.restore_checkpoint(ckpt, ls)
        assert "weights::fold" in tables  # restored, not re-zeroed
        trainer.fit_stream(
            tables, ls, iter(chunks[start:]), jax.random.key(1),
            start_step=start,
        )
        assert np.array_equal(weights(store), want)

        # Untiered restore: fold arrays are skipped, canonical tables
        # load clean.
        untiered, ustore = _make_trainer(mesh)
        utables, uls = untiered.init_state(jax.random.key(0))
        utables, uls, _ = untiered.restore_checkpoint(ckpt, uls)
        assert not any("::" in k for k in untiered._attach_hot(utables))
        assert np.isfinite(weights(ustore)).all()


def test_max_min_combine_rides_the_tier(devices8):
    """max/min server combines now engage the tier (windowed extremum
    pending buffer, pmax/pmin reconcile): deterministic runs, the
    projection invariant holds, and the reconcile lowers an all_reduce
    (extremum cannot reduce-scatter)."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    train, _ = logreg_data()
    chunks = logreg_chunks(train, num_workers_of(mesh), epochs=1)
    for combine in ("max", "min"):
        results = []
        for _ in range(2):
            trainer, store = _make_trainer(mesh, hot_tier=NF,
                                           hot_sync_every=3)
            trainer.server_logic["weights"] = dataclasses.replace(
                trainer.server_logic["weights"], combine=combine)
            assert trainer._hot_tier_map() == {"weights": NF}
            tables, _, m = _fit(trainer, chunks)
            w = weights(store)
            assert np.isfinite(w).all()
            rep = np.asarray(tables[hot_key("weights")])
            assert np.array_equal(
                rep, store.lookup_host("weights", np.arange(NF)))
            results.append(w)
        assert np.array_equal(results[0], results[1])
    # The extremum reconcile is a pmax/pmin all_reduce sized to the
    # head (+ indicator column), not a reduce-scatter.
    hlo = trainer.lowered_chunk_text(chunks[0], "sync")
    from fps_tpu.analysis import collective_profile

    prof = collective_profile(hlo, 64)
    assert any(c.kind == "all_reduce"
               and c.payload_bytes == NF * 2 * 4 for c in prof)


# ---------------------------------------------------------------------------
# Chaos: SIGKILL between reconciles under the supervisor (slow tier).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sigkill_between_reconciles_resumes_bit_identical(tmp_path):
    from fps_tpu.testing.supervised_demo import run_hot_tier_kill_scenario

    ok, detail = run_hot_tier_kill_scenario(str(tmp_path))
    assert ok, detail


@pytest.mark.slow
def test_reconcile_shard_kill_restores_fold_state_bit_identical(tmp_path):
    """SIGKILL between a reduce-scatter window and the next checkpoint
    with the Adagrad hot fold on: the restart restores canonical tables
    AND the sharded fold state (fold:: snapshot arrays) and replays
    bit-identically under the supervisor."""
    from fps_tpu.testing.supervised_demo import (
        run_reconcile_shard_kill_scenario,
    )

    ok, detail = run_reconcile_shard_kill_scenario(str(tmp_path))
    assert ok, detail
