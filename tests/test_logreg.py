"""Bounded-staleness SGD logistic regression: SSP converges close to sync,
and staleness actually changes the trajectory (proving reads are stale)."""

import jax
import pytest
import numpy as np

from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.ingest import multi_epoch_chunks
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
    predict_proba_host,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import (
    synthetic_sparse_classification,
    train_test_split,
)

NF, NNZ = 400, 8


def run_logreg(mesh, sync_every, epochs=4, lr=0.5):
    data = synthetic_sparse_classification(6000, NF, NNZ, seed=7, noise=0.05)
    data = dict(data, label=((data["label"] > 0).astype(np.float32)))  # {0,1}
    train, test = train_test_split(data)
    cfg = LogRegConfig(num_features=NF, learning_rate=lr)
    trainer, store = logistic_regression(mesh, cfg, sync_every=sync_every)
    tables, ls = trainer.init_state(jax.random.key(0))
    W = num_workers_of(mesh)
    chunks = multi_epoch_chunks(
        train, epochs, num_workers=W, local_batch=32, steps_per_chunk=8,
        sync_every=sync_every, seed=3,
    )
    tables, ls, m = trainer.fit_stream(tables, ls, chunks, jax.random.key(1))
    logloss = np.concatenate([x["logloss"] for x in m])
    n = np.concatenate([x["n"] for x in m])
    p = predict_proba_host(store, test["feat_ids"], test["feat_vals"])
    acc = float(np.mean((p > 0.5) == (test["label"] > 0.5)))
    return logloss, n, acc, store


def test_logreg_sync_converges(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    logloss, n, acc, _ = run_logreg(mesh, sync_every=None)
    q = len(logloss) // 4
    assert logloss[-q:].sum() / n[-q:].sum() < 0.693  # below chance
    assert acc > 0.8, acc


def test_logreg_ssp_converges(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    _, _, acc_ssp, _ = run_logreg(mesh, sync_every=4, epochs=6)
    assert acc_ssp > 0.78, acc_ssp


def test_ssp_staleness_changes_trajectory(devices8):
    """SSP reads must actually be stale: with a planted difference between
    s=2 and sync, final weights differ (else the snapshot path is dead
    code), yet both learn."""
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    _, _, acc_sync, store_sync = run_logreg(mesh, sync_every=None, epochs=3)
    _, _, acc_ssp, store_ssp = run_logreg(mesh, sync_every=4, epochs=3)
    w_sync = store_sync.lookup_host("weights", np.arange(NF))
    w_ssp = store_ssp.lookup_host("weights", np.arange(NF))
    assert not np.allclose(w_sync, w_ssp)
    assert acc_sync > 0.72 and acc_ssp > 0.72


def test_logreg_adagrad_converges_and_keeps_state_in_table(devices8):
    """optimizer='adagrad': the server fold keeps per-coordinate accumulator
    state in table column 1; training converges and the accumulator is
    non-negative and grows only for touched features."""
    import jax
    import numpy as np

    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
        predict_proba_host,
    )
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import (
        synthetic_sparse_classification,
        train_test_split,
    )

    mesh = make_ps_mesh(num_shards=8, num_data=1)
    W = num_workers_of(mesh)
    data = synthetic_sparse_classification(6000, NF, NNZ, seed=7, noise=0.05)
    data = dict(data, label=(data["label"] > 0).astype(np.float32))
    train, test = train_test_split(data)
    cfg = LogRegConfig(num_features=NF, learning_rate=0.3,
                       optimizer="adagrad")
    trainer, store = logistic_regression(mesh, cfg)
    tables, ls = trainer.init_state(jax.random.key(0))
    ds = DeviceDataset(mesh, train)
    plan = DeviceEpochPlan(ds, num_workers=W, local_batch=32, seed=3)
    tables, ls, m = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1), epochs=4
    )
    p = predict_proba_host(store, test["feat_ids"], test["feat_vals"])
    acc = float(np.mean((p > 0.5) == (test["label"] > 0.5)))
    assert acc > 0.8, acc
    rows = store.lookup_host("weights", np.arange(NF))
    assert rows.shape == (NF, 2)
    assert (rows[:, 1] >= 0).all()  # accumulator is a sum of squares
    assert (rows[:, 1] > 0).sum() > NF // 2  # most features were touched


@pytest.mark.parametrize("optimizer", ["sgd", "adagrad"])
def test_dense_head_matches_scatter_path(devices8, optimizer):
    """dense_features=d (fixed-slot numeric head pulled/pushed densely)
    must train to the SAME weights as the all-scatter path on the same
    structured data — the head deltas are just pre-combined on the worker,
    so the additive fold sees identical per-id sums (up to f32
    reassociation)."""
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.models.logistic_regression import (
        LogRegConfig, logistic_regression,
    )

    NF, NNZ, D, NEX = 2000, 8, 3, 2048
    data = synthetic_sparse_classification(NEX, NF, NNZ, seed=5, noise=0.05,
                                           dense_features=D)
    # fixed-slot contract holds in the generator
    np.testing.assert_array_equal(
        data["feat_ids"][:, :D], np.broadcast_to(np.arange(D), (NEX, D)))
    data = dict(data, label=(data["label"] > 0).astype(np.float32))

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)

    def run(dense):
        cfg = LogRegConfig(num_features=NF, learning_rate=0.3,
                           optimizer=optimizer, dense_features=dense)
        trainer, store = logistic_regression(mesh, cfg, donate=False)
        tables, ls = trainer.init_state(jax.random.key(0))
        ds = DeviceDataset(mesh, data)
        plan = DeviceEpochPlan(ds, num_workers=W, local_batch=64, seed=2)
        tables, ls, m = trainer.run_indexed(tables, ls, plan,
                                            jax.random.key(1), epochs=2)
        lls = [float(mm["logloss"].sum() / mm["n"].sum()) for mm in m]
        return store.dump_model("weights")[1], lls

    w_dense, ll_dense = run(D)
    w_flat, ll_flat = run(0)
    np.testing.assert_allclose(w_dense, w_flat, rtol=2e-4, atol=2e-6)
    assert ll_dense[-1] < ll_dense[0]  # it learns
    np.testing.assert_allclose(ll_dense, ll_flat, rtol=1e-4)
