"""The jax-hazard source linter: per-rule seeded sources + the CI gate.

Every rule gets a positive (flagged) and a negative (clean) seed so no
rule is vacuous, ``# noqa`` suppression is honored, and — the actual CI
contract — the whole ``fps_tpu`` package lints to ZERO findings, so any
new hazard fails tier-1 with its file:line and rationale.
"""

import json
import os
import subprocess
import sys
import textwrap

from fps_tpu.analysis.lint import RULES, lint_paths, lint_source

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(src):
    return [f.rule for f in lint_source(textwrap.dedent(src))]


# ---------------------------------------------------------------------------
# FPS001 — late-bound closure over a loop variable.
# ---------------------------------------------------------------------------


def test_fps001_flags_loop_closure():
    src = """
    def build(tables):
        fns = []
        for name in tables:
            fns.append(lambda: step(name))
        return fns
    """
    assert rules_of(src) == ["FPS001"]


def test_fps001_default_arg_binding_is_clean():
    src = """
    def build(tables):
        fns = []
        for name in tables:
            fns.append(lambda _n=name: step(_n))
        return fns
    """
    assert rules_of(src) == []


def test_fps001_def_inside_loop():
    src = """
    for epoch in range(3):
        def thunk():
            return source(epoch)
        run(thunk)
    """
    assert rules_of(src) == ["FPS001"]


def test_fps001_rebound_in_body_is_clean():
    # The closure assigns the name itself — no free capture.
    src = """
    for i in range(3):
        def thunk():
            i = 0
            return i
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# FPS002 — boolean branch on a jnp predicate.
# ---------------------------------------------------------------------------


def test_fps002_flags_if_on_jnp_any():
    src = """
    def check(x):
        if jnp.any(jnp.isnan(x)):
            raise ValueError
    """
    assert rules_of(src) == ["FPS002"]


def test_fps002_flags_while_and_assert():
    src = """
    def run(x):
        while jnp.all(x > 0):
            x = step(x)
        assert jnp.isfinite(x)
    """
    assert rules_of(src) == ["FPS002", "FPS002"]


def test_fps002_np_predicates_are_clean():
    src = """
    def check(x):
        if np.any(np.isnan(x)):
            raise ValueError
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# FPS003 — unsorted dict iteration inside a compiled-fn builder.
# ---------------------------------------------------------------------------


def test_fps003_flags_items_in_builder():
    src = """
    def build_fn(tables):
        def step(carry, batch):
            out = {n: f(t) for n, t in tables.items()}
            return carry, out
        return lax.scan(step, tables, None)
    """
    assert rules_of(src) == ["FPS003"]


def test_fps003_sorted_items_is_clean():
    src = """
    def build_fn(tables):
        def step(carry, batch):
            out = {n: f(t) for n, t in sorted(tables.items())}
            return carry, out
        return lax.scan(step, tables, None)
    """
    assert rules_of(src) == []


def test_fps003_for_statement_in_builder():
    src = """
    def build_fn(tables):
        acc = []
        for n, t in tables.items():
            acc.append(t)
        return lax.scan(make_step(acc), tables, None)
    """
    assert rules_of(src) == ["FPS003"]


def test_fps003_outside_builder_is_clean():
    # No scan/fori/while/shard_map in the subtree: host-side dict
    # iteration is fine (ingest, reporting, checkpointing).
    src = """
    def summarize(metrics):
        return {k: sum(v) for k, v in metrics.items()}
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# FPS004 — thread-starting class without synchronization.
# ---------------------------------------------------------------------------


def test_fps004_flags_unsynchronized_thread_class():
    src = """
    class Worker:
        def start(self):
            self.t = threading.Thread(target=self.run)
            self.t.start()
    """
    assert rules_of(src) == ["FPS004"]


def test_fps004_lock_is_clean():
    src = """
    class Worker:
        def __init__(self):
            self.lock = threading.Lock()
        def start(self):
            self.t = threading.Thread(target=self.run)
    """
    assert rules_of(src) == []


def test_fps004_docstring_note_is_clean():
    src = '''
    class Worker:
        """Background dumper.

        thread-safety: the worker owns all state after start().
        """
        def start(self):
            self.t = threading.Thread(target=self.run)
    '''
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# FPS005 — internal import of the utils.profiling shim.
# ---------------------------------------------------------------------------


def test_fps005_flags_shim_import():
    assert rules_of("from fps_tpu.utils.profiling import trace") == [
        "FPS005"]
    assert rules_of("import fps_tpu.utils.profiling") == ["FPS005"]
    assert rules_of("from fps_tpu.utils import profiling") == ["FPS005"]


def test_fps005_obs_import_is_clean():
    assert rules_of("from fps_tpu.obs import trace") == []


def test_fps005_shim_itself_is_exempt():
    src = "import fps_tpu.utils.profiling"
    path = os.path.join("fps_tpu", "utils", "profiling.py")
    assert [f.rule for f in lint_source(src, path)] == []


# ---------------------------------------------------------------------------
# FPS006 — raw open()/np.load of checkpoint/snapshot paths.
# ---------------------------------------------------------------------------


def test_fps006_flags_raw_snapshot_reads():
    assert rules_of("z = np.load(ckpt_path)") == ["FPS006"]
    assert rules_of("f = open(snapshot_file, 'rb')") == ["FPS006"]
    assert rules_of("z = numpy.load(run.ckpt_dir)") == ["FPS006"]
    # The token may sit in a string literal (a hardcoded path).
    assert rules_of("z = np.load('out/ckpt_000000000001.npz')") == [
        "FPS006"]


def test_fps006_generic_paths_and_other_calls_are_clean():
    assert rules_of("z = np.load(path)") == []
    assert rules_of("f = open(out_file, 'wb')") == []
    # Non-read calls never flag, even on flavored names.
    assert rules_of("os.remove(ckpt_path)") == []


def test_fps006_sanctioned_readers_are_exempt():
    src = "z = np.load(snapshot_path)"
    for path in (
        os.path.join("fps_tpu", "core", "checkpoint.py"),
        os.path.join("fps_tpu", "core", "snapshot_format.py"),
        os.path.join("fps_tpu", "serve", "snapshot.py"),
    ):
        assert [f.rule for f in lint_source(src, path)] == [], path
    assert [f.rule for f in lint_source(
        src, os.path.join("fps_tpu", "testing", "chaos.py"))] == ["FPS006"]


# ---------------------------------------------------------------------------
# FPS007 — host clock calls inside compiled-fn builder subtrees.
# ---------------------------------------------------------------------------


def test_fps007_flags_host_clock_in_builder():
    src = """
    import time
    from jax import lax

    def build():
        def step(c, x):
            t = time.perf_counter()
            return c, t
        return lax.scan(step, 0, None)
    """
    assert rules_of(src) == ["FPS007"]
    # Every clock spelling flags, bare imports included — `from time
    # import time; time()` too.
    for call in ("time.time()", "time.monotonic()", "perf_counter()",
                 "time()"):
        one = f"""
        from time import perf_counter
        import time
        from jax import lax

        def build():
            def step(c, x):
                return c, {call}
            return lax.scan(step, 0, None)
        """
        assert rules_of(one) == ["FPS007"], call


def test_fps007_outside_builder_is_clean():
    # No trace trigger anywhere: the timing module's own PhaseTimer
    # pattern stays legal.
    src = """
    import time

    def phase():
        t0 = time.perf_counter()
        return time.perf_counter() - t0
    """
    assert rules_of(src) == []
    # Non-clock time.* calls inside a builder stay legal too.
    src2 = """
    import time
    from jax import lax

    def build():
        def step(c, x):
            return c, x
        time.sleep(0)
        return lax.scan(step, 0, None)
    """
    assert rules_of(src2) == []


def test_fps007_noqa_and_explain():
    src = """
    import time
    from jax import lax

    def build():
        t = time.time()  # noqa: FPS007
        return lax.scan(lambda c, x: (c, x), 0, None)
    """
    assert rules_of(src) == []
    assert "FPS007" in RULES and "PhaseTimer" in RULES["FPS007"]


# ---------------------------------------------------------------------------
# FPS008 — raw socket use outside the wire plane (fps_tpu/serve/).
# ---------------------------------------------------------------------------


def test_fps008_flags_raw_sockets():
    assert rules_of("s = socket.socket()") == ["FPS008"]
    assert rules_of(
        "s = socket.create_connection((h, p))") == ["FPS008"]
    assert rules_of(
        "from socket import create_connection\n"
        "s = create_connection((h, p))") == ["FPS008"]


def test_fps008_wire_plane_is_exempt():
    src = "s = socket.create_connection((h, p))"
    for path in (os.path.join("fps_tpu", "serve", "wire.py"),
                 os.path.join("fps_tpu", "serve", "net.py")):
        assert [f.rule for f in lint_source(src, path)] == [], path
    # Anywhere else in the package flags — every caller goes through
    # WireClient (deadlines, bounded retry, idempotent reconnect).
    assert [f.rule for f in lint_source(
        src, os.path.join("fps_tpu", "core", "driver.py"))] == ["FPS008"]


def test_fps008_other_socket_calls_are_clean():
    # Non-constructor socket.* helpers don't flag: the rule targets
    # connection creation, not constants or address utilities.
    assert rules_of("fam = socket.AF_INET") == []
    assert rules_of("name = socket.gethostname()") == []
    assert rules_of("s = socket.socket()  # noqa: FPS008") == []


# ---------------------------------------------------------------------------
# FPS009 — hand-spelled tenant-namespace literals outside the path helper.
# ---------------------------------------------------------------------------


def test_fps009_flags_hand_spelled_tenant_paths():
    assert rules_of(
        'p = os.path.join(root, "tenants", name, "ckpt")') == ["FPS009"]
    assert rules_of('f = open(d + "/tenants/a/tenant.json")') == ["FPS009"]
    assert rules_of('os.makedirs(f"{root}/tenants/{n}/obs")') == ["FPS009"]
    # A nested path call flags at BOTH call sites (outer glob + inner
    # join each see the literal) — loud is right for this hazard.
    assert rules_of(
        'hits = glob.glob(os.path.join(r, "tenants", "*"))'
    ) == ["FPS009", "FPS009"]


def test_fps009_helper_and_mirrored_constants_are_exempt():
    src = 'p = os.path.join(root, "tenants", name)'
    # The sanctioned helper owns the layout.
    assert [f.rule for f in lint_source(
        src, os.path.join("fps_tpu", "tenancy", "paths.py"))] == []
    # Everywhere else in the package flags.
    assert [f.rule for f in lint_source(
        src, os.path.join("fps_tpu", "obs", "fleet.py"))] == ["FPS009"]
    # A mirrored Name constant (the stdlib-only login-node pattern) is
    # the sanctioned alternative — the rule keys on string literals.
    assert rules_of(
        'TENANTS_DIRNAME = "tenants"\n'
        "p = os.path.join(root, TENANTS_DIRNAME)") == []


def test_fps009_generic_paths_and_noqa_are_clean():
    assert rules_of('p = os.path.join(root, "ckpt", name)') == []
    assert rules_of('msg = "tenants must not collide"') == []
    assert rules_of(
        'p = os.path.join(r, "tenants")  # noqa: FPS009') == []


# ---------------------------------------------------------------------------
# FPS010 — whole-table materialization in the serve hot path.
# ---------------------------------------------------------------------------

SERVE_PATH = os.path.join("fps_tpu", "serve", "hot.py")


def serve_rules(src, path=SERVE_PATH):
    return [f.rule for f in lint_source(textwrap.dedent(src), path)]


def test_fps010_flags_table_materialization_in_serve():
    assert serve_rules('q = np.asarray(snap.table("items"))') == [
        "FPS010"]
    assert serve_rules('q = np.array(snap.tables["w"])') == ["FPS010"]
    assert serve_rules('q = np.ascontiguousarray(view.base)') == [
        "FPS010"]
    assert serve_rules('q = snap.tables["w"].copy()') == ["FPS010"]


def test_fps010_tracks_table_aliases():
    src = """
    t = snap.table(name)
    u = t
    dense = np.asarray(u)
    """
    assert serve_rules(src) == ["FPS010"]


def test_fps010_gather_results_are_clean():
    # A SUBSCRIPT of a table view is the request-bounded gather result —
    # materializing it is the point, not the hazard.
    src = """
    t = snap.table(name)
    rows = np.ascontiguousarray(t[ids])
    """
    assert serve_rules(src) == []


def test_fps010_materialize_seam_and_array_dunder_are_exempt():
    src = """
    def materialize(table):
        return np.asarray(snap.table(name))

    class DeltaView:
        def __array__(self, dtype=None):
            return self.base.copy()
    """
    assert serve_rules(src) == []


def test_fps010_outside_serve_and_noqa_are_clean():
    assert rules_of('q = np.asarray(snap.table("items"))') == []
    assert serve_rules(
        'q = np.asarray(snap.table("i"))  # noqa: FPS010') == []


def test_fps010_serve_package_is_clean():
    """The tentpole's zero-copy guarantee as a standing gate: the whole
    serve package answers off mapped pages — any new whole-table
    materialization in the hot path fails here with file:line."""
    findings = lint_paths([os.path.join(ROOT, "fps_tpu", "serve")],
                          select={"FPS010"})
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# Machinery: noqa, syntax errors, file walking, the CI gate.
# ---------------------------------------------------------------------------


def test_noqa_suppresses_exactly_that_rule():
    src = "from fps_tpu.utils.profiling import trace  # noqa: FPS005"
    assert lint_source(src) == []
    other = "from fps_tpu.utils.profiling import trace  # noqa: FPS001"
    assert [f.rule for f in lint_source(other)] == ["FPS005"]


def test_syntax_error_reports_fps000():
    findings = lint_source("def broken(:\n")
    assert [f.rule for f in findings] == ["FPS000"]


def test_findings_carry_location_and_str():
    f = lint_source("import fps_tpu.utils.profiling", "x.py")[0]
    assert (f.path, f.line) == ("x.py", 1)
    assert str(f).startswith("x.py:1: FPS005")
    assert f.to_json()["rule"] == "FPS005"


def test_lint_paths_walks_and_selects(tmp_path):
    (tmp_path / "a.py").write_text("import fps_tpu.utils.profiling\n")
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "b.py").write_text(
        "def f(x):\n    if jnp.any(x):\n        pass\n")
    (sub / "noise.txt").write_text("not python")
    found = lint_paths([str(tmp_path)])
    assert sorted(f.rule for f in found) == ["FPS002", "FPS005"]
    only = lint_paths([str(tmp_path)], select={"FPS005"})
    assert [f.rule for f in only] == ["FPS005"]


def test_rule_table_is_complete():
    assert set(RULES) == {"FPS001", "FPS002", "FPS003", "FPS004", "FPS005",
                          "FPS006", "FPS007", "FPS008", "FPS009", "FPS010",
                          "FPS011"}


def test_package_lints_clean():
    """THE CI gate: zero findings over the whole fps_tpu package. A new
    hazard anywhere in the tree fails here with file:line + rationale
    (fix it, or — deliberately — suppress with `# noqa: FPSNNN`)."""
    findings = lint_paths([os.path.join(ROOT, "fps_tpu")])
    assert findings == [], "\n".join(
        [""] + [f"{f}  [{RULES.get(f.rule, '?')}]" for f in findings])


def test_cli_json_and_exit_codes(tmp_path):
    (tmp_path / "bad.py").write_text("import fps_tpu.utils.profiling\n")
    env = dict(os.environ, PYTHONPATH=ROOT)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "lint.py"),
         str(tmp_path), "--json"],
        capture_output=True, text=True, env=env, timeout=60)
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["count"] == 1
    assert doc["findings"][0]["rule"] == "FPS005"
    (tmp_path / "bad.py").write_text("x = 1\n")
    r2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "lint.py"),
         str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=60)
    assert r2.returncode == 0


def test_cli_explain(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "lint.py"),
         "--explain"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    for rule in RULES:
        assert rule in r.stdout


# ---------------------------------------------------------------------------
# FPS011 — blocking host work in the training-thread scope.
# ---------------------------------------------------------------------------

DRIVER_PATH = os.path.join("fps_tpu", "core", "driver.py")
MEGASTEP_PATH = os.path.join("fps_tpu", "core", "megastep.py")


def hot_rules(src, path=DRIVER_PATH):
    return [f.rule for f in lint_source(textwrap.dedent(src), path)]


def test_fps011_flags_blocking_calls_in_training_scope():
    for path in (DRIVER_PATH, MEGASTEP_PATH):
        assert hot_rules("time.sleep(0.1)", path) == ["FPS011"], path
        assert hot_rules("os.fsync(fd)", path) == ["FPS011"], path
        assert hot_rules("x = jax.device_get(t)", path) == [
            "FPS011"], path
        assert hot_rules("out.block_until_ready()", path) == [
            "FPS011"], path
    # `from time import sleep` / `from os import fsync` bare forms.
    assert hot_rules("sleep(0.1)") == ["FPS011"]
    assert hot_rules("fsync(fd)") == ["FPS011"]
    assert hot_rules("jax.block_until_ready(out)") == ["FPS011"]


def test_fps011_scope_is_the_training_files_only():
    for path in (os.path.join("fps_tpu", "core", "checkpoint.py"),
                 os.path.join("fps_tpu", "core", "autok.py"),
                 os.path.join("fps_tpu", "tiering", "retier.py"),
                 os.path.join("tools", "bench_helper.py")):
        assert hot_rules("time.sleep(0.1)", path) == [], path
        assert hot_rules("out.block_until_ready()", path) == [], path


def test_fps011_writer_seam_functions_are_exempt():
    src = """
    def _writer_loop(self):
        time.sleep(backoff)
        os.fsync(fd)

    def _run_capture(collect):
        jax.device_get(collect())

    def _sidecar_retry_loop(self):
        time.sleep(d)
    """
    assert hot_rules(src) == []


def test_fps011_non_seam_functions_still_flagged():
    src = """
    def fit_stream(self):
        time.sleep(0.1)
    """
    assert hot_rules(src) == ["FPS011"]
    # A method named like a random helper gets no exemption.
    assert hot_rules("""
    def _dispatch(self):
        out.block_until_ready()
    """) == ["FPS011"]


def test_fps011_noqa_and_unrelated_calls_clean():
    assert hot_rules("time.sleep(0.1)  # noqa: FPS011") == []
    # Method chains that merely END in a scoped bare name are not the
    # stdlib calls the rule targets.
    assert hot_rules("self.sleep(0.1)") == []
    assert hot_rules("clock.monotonic()") == []


def test_fps011_training_files_are_clean_in_tree():
    """The contract the rule enforces holds for the shipped tree: zero
    findings over the scoped files (capture/retry moved off-thread)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = [os.path.join(repo, "fps_tpu", "core", "driver.py"),
             os.path.join(repo, "fps_tpu", "core", "megastep.py")]
    assert [str(f) for f in lint_paths(files, select={"FPS011"})] == []
