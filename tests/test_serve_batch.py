"""The batched zero-copy read plane (ISSUE 19).

Contract under test (``docs/serving.md`` "Batched wire protocol" /
"The read autoscaler", ``docs/performance.md`` "Read-plane
throughput"):

* HELLO capability negotiation: the server grants the INTERSECTION of
  offered and supported caps; an un-granted ``multi`` falls back to
  sequential single requests (old peers keep working, PROTO_VERSION
  unchanged);
* the binary response path (``CAP_BIN``): row segments ride the frame
  as raw buffers decoded by ``np.frombuffer`` — dtype/shape exact,
  zero copies on either side;
* header-only CRC (``CAP_CRC_LIGHT``): negotiated sessions skip the
  payload CRC pass above the size threshold — and an unnegotiated
  crc-light frame is rejected as torn (no unilateral integrity
  opt-out);
* ``multi``: one frame, many lookups — per-item failures ride inside
  their entry and never fail siblings; the server merges same-table
  members into one fancy-index gather; a reconnect-resent multi frame
  replays EXACTLY once from the (session, req_id) cache;
* the coalescer: concurrently-queued requests merge into shared
  batches (answers unchanged), and an idle server never waits;
* zero-copy: serving batched pulls never materializes O(table) bytes
  per request (tracemalloc-bounded);
* admission control: per-op cost weights, multi = sum of members, an
  idle server always admits, and the AIMD latency governor shrinks /
  regrows the limit against its target;
* the ReadAutoscaler: scale-up on latency burn with a fresh fence,
  the fence-lag veto (publish-bound holds), cooldown gating, and
  scale-down to ``min_readers``.
"""

import json
import os
import threading
import time
import tracemalloc

import numpy as np
import pytest

from fps_tpu.core import snapshot_format as fmt
from fps_tpu.serve import (
    AdmissionController,
    CoalesceConfig,
    ReadAutoscaler,
    ReadServer,
    ServableSnapshot,
    ServingFleet,
    TcpServe,
    WireClient,
)
from fps_tpu.serve.admission import DEFAULT_COST_WEIGHTS
from fps_tpu.serve.net import handle_request, handle_request_segs
from fps_tpu.serve.wire import (
    CAP_BIN,
    CAP_CRC_LIGHT,
    CAP_MULTI,
    CRC_LIGHT_THRESHOLD,
    FLAG_CRC_LIGHT,
    OP_RESP,
    SUPPORTED_CAPS,
    TornFrameError,
    decode_bin_response,
    encode_frame_parts,
    pack_bin_payload,
    read_frame,
)
from fps_tpu.testing import faultnet
from fps_tpu.testing.faultnet import NetFaultRule


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    faultnet.uninstall()


def _snapshot(nrows=64, rank=4, step=11):
    rng = np.random.default_rng(3)
    tables = {"weights": rng.normal(
        size=(nrows, rank)).astype(np.float32)}
    return ServableSnapshot(step, "test-batch", tables, [], "none")


def _tcp(**kw):
    server = ReadServer()
    server.swap_to(_snapshot())
    return server, TcpServe(server, **kw).start()


# ---------------------------------------------------------------------------
# Capability negotiation
# ---------------------------------------------------------------------------

def test_hello_caps_granted_is_the_intersection():
    server, tcp = _tcp(caps=(CAP_MULTI, CAP_BIN))
    try:
        with WireClient("127.0.0.1", tcp.port,
                        caps=SUPPORTED_CAPS) as c:
            # Client offered all three; server supports two.
            assert c.caps == {CAP_MULTI, CAP_BIN}
        with WireClient("127.0.0.1", tcp.port, caps=()) as c:
            # Client offered nothing: PR-16 peer, nothing granted.
            assert c.caps == set()
    finally:
        tcp.close()


def test_multi_not_negotiated_falls_back_sequential():
    server, tcp = _tcp(caps=())  # a server predating multi
    try:
        reqs = [{"op": "pull", "table": "weights", "ids": [i, i + 1]}
                for i in range(4)]
        with WireClient("127.0.0.1", tcp.port) as c:
            assert CAP_MULTI not in c.caps
            got = c.multi(reqs)
        assert [r["values"] for r in got] == [
            handle_request(server, r)["values"] for r in reqs]
        # Four single frames, zero multi frames: the fallback is the
        # PR-16 shape, not a rejected batch.
        assert tcp.wire_stats()["multi_frames"] == 0
    finally:
        tcp.close()


# ---------------------------------------------------------------------------
# Binary (zero-copy) responses + header-only CRC
# ---------------------------------------------------------------------------

def test_bin_payload_roundtrip_is_exact_and_zero_copy():
    rng = np.random.default_rng(0)
    segs = [rng.normal(size=(16, 8)).astype(np.float32),
            rng.integers(0, 1 << 40, 5).astype(np.int64)]
    resp = {"ok": True, "step": 3,
            "values": {"__seg__": 0}, "items": {"__seg__": 1}}
    parts = pack_bin_payload(resp, segs)
    payload = b"".join(bytes(p) for p in parts)
    out = decode_bin_response(payload)
    assert out["ok"] and out["step"] == 3
    assert np.array_equal(out["values"], segs[0])
    assert out["values"].dtype == np.float32
    assert np.array_equal(out["items"], segs[1])
    assert out["items"].dtype == np.int64
    # np.frombuffer views, not copies: the arrays alias the payload.
    assert out["values"].base is not None
    assert out["items"].base is not None


def test_bin_multi_over_tcp_matches_json():
    server, tcp = _tcp()
    try:
        reqs = [{"op": "pull", "table": "weights",
                 "ids": [1, 5, 9, 13]},
                {"op": "score", "table": "weights",
                 "feat_ids": [[1, 2], [3, 4]],
                 "feat_vals": [[1.0, 2.0], [0.5, -1.0]]}]
        with WireClient("127.0.0.1", tcp.port) as cj:
            want = cj.multi(reqs)
        with WireClient("127.0.0.1", tcp.port,
                        caps=(CAP_MULTI, CAP_BIN)) as cb:
            got = cb.multi(reqs)
        assert np.array_equal(
            np.asarray(want[0]["values"], np.float32),
            got[0]["values"])
        assert np.allclose(
            np.asarray(want[1]["scores"]), got[1]["scores"])
        assert tcp.wire_stats()["bin_responses"] >= 1
    finally:
        tcp.close()


def test_crc_light_negotiated_above_threshold_only():
    # A pull big enough that its binary response crosses the
    # threshold: 64KiB / (4 bytes * 4 cols) = 4096 rows.
    server = ReadServer()
    server.swap_to(_snapshot(nrows=8192))
    tcp = TcpServe(server).start()
    big = {"op": "pull", "table": "weights",
           "ids": np.arange(8192).tolist()}
    small = {"op": "pull", "table": "weights", "ids": [1, 2, 3]}
    try:
        with WireClient("127.0.0.1", tcp.port,
                        caps=(CAP_MULTI, CAP_BIN, CAP_CRC_LIGHT)) as c:
            assert CAP_CRC_LIGHT in c.caps
            got_small = c.request(small)
            assert tcp.wire_stats()["crc_light_frames"] == 0
            got_big = c.request(big)
            assert tcp.wire_stats()["crc_light_frames"] == 1
        assert np.array_equal(
            got_big["values"],
            server.snapshot.lookup("weights", np.arange(8192)))
        assert np.array_equal(
            np.asarray(got_small["values"]),
            server.snapshot.lookup("weights", [1, 2, 3]))
        # Without the cap offered: same big response, full CRC.
        with WireClient("127.0.0.1", tcp.port,
                        caps=(CAP_MULTI, CAP_BIN)) as c:
            c.request(big)
        assert tcp.wire_stats()["crc_light_frames"] == 1
    finally:
        tcp.close()


def test_unnegotiated_crc_light_frame_rejected_as_torn():
    import io

    payload = json.dumps({"ok": True}).encode()
    parts = encode_frame_parts(OP_RESP, 1, [payload], crc_light=True)
    raw = b"".join(bytes(p) for p in parts)
    fr = read_frame(io.BytesIO(raw), allow_crc_light=True)
    assert fr.flags & FLAG_CRC_LIGHT and fr.json()["ok"]
    with pytest.raises(TornFrameError):
        read_frame(io.BytesIO(raw), allow_crc_light=False)


def test_crc_light_threshold_is_meaningfully_large():
    # The "small responses stay fully guarded" contract only means
    # something while the threshold dwarfs a typical single lookup.
    assert CRC_LIGHT_THRESHOLD >= 16 << 10


# ---------------------------------------------------------------------------
# multi: one frame, many lookups
# ---------------------------------------------------------------------------

def test_multi_roundtrip_with_per_item_errors():
    server, tcp = _tcp()
    try:
        reqs = [
            {"op": "pull", "table": "weights", "ids": [0, 2]},
            {"op": "pull", "table": "nope", "ids": [0]},     # bad table
            {"op": "stats"},
            {"op": "bogus"},                                 # bad op
            {"op": "pull", "table": "weights", "ids": [63]},
        ]
        with WireClient("127.0.0.1", tcp.port) as c:
            got = c.multi(reqs)
        assert len(got) == len(reqs)
        assert got[0]["ok"] and got[4]["ok"]     # siblings unharmed
        assert not got[1]["ok"] and "nope" in got[1]["error"]
        assert got[2]["ok"] and "requests" in got[2]
        assert not got[3]["ok"]
        assert got[0]["values"] == handle_request(
            server, reqs[0])["values"]
        assert tcp.wire_stats()["multi_frames"] == 1
    finally:
        tcp.close()


def test_server_multi_merges_same_table_pulls_into_one_batch():
    server = ReadServer()
    server.swap_to(_snapshot())
    calls = [("pull", {"table": "weights", "ids": [i, i + 3]})
             for i in range(8)]
    before = server.batches
    results = server.multi(calls)
    assert server.batches == before + 1       # ONE merged execution
    assert server.batched_requests >= 8
    for (kind, payload), (step, values) in zip(calls, results):
        assert step == 11
        assert np.array_equal(
            values, server.snapshot.lookup("weights", payload["ids"]))


def test_server_multi_isolates_per_item_failures():
    server = ReadServer()
    server.swap_to(_snapshot())
    results = server.multi([
        ("pull", {"table": "weights", "ids": [1]}),
        ("pull", {"table": "weights", "ids": [9999]}),  # out of range
        ("pull", {"table": "weights", "ids": [2]}),
    ])
    assert isinstance(results[1], Exception)
    assert np.array_equal(
        results[0][1], server.snapshot.lookup("weights", [1]))
    assert np.array_equal(
        results[2][1], server.snapshot.lookup("weights", [2]))


def test_multi_replayed_exactly_once_after_reconnect():
    # S3's chaos half at unit scale: the server's FIRST response send
    # after the handshake is cut mid-frame — the whole multi executed,
    # its response died on the wire, and the client's resend must be
    # answered from the replay cache WITHOUT re-executing any member.
    # serve send occurrences are 0-based: #0 is the HELLO response,
    # #1 the first data response — cut that one.
    rules = [NetFaultRule("serve", "send", "cut", cut_bytes=4,
                          start=1, count=1)]
    reqs = [{"op": "pull", "table": "weights", "ids": [i]}
            for i in range(6)]
    net = faultnet.install(rules, seed=0)
    try:
        server, tcp = _tcp()
        try:
            with WireClient("127.0.0.1", tcp.port,
                            peer_class="client") as c:
                got = c.multi(reqs)
                assert c.reconnects == 1
            stats = tcp.wire_stats()
            executed = server.requests
        finally:
            tcp.close()
    finally:
        faultnet.uninstall()
    assert [r["values"] for r in got] == [
        handle_request(server, r)["values"] for r in reqs]
    # Exactly once: 6 member executions total, the resend a cache hit.
    assert executed == len(reqs)
    assert stats["dedup_replays"] == 1
    # The resend is answered from the replay cache BEFORE dispatch, so
    # only the original execution counts as a multi frame.
    assert stats["multi_frames"] == 1


# ---------------------------------------------------------------------------
# The coalescer
# ---------------------------------------------------------------------------

def test_coalescer_merges_concurrent_pulls_answers_unchanged():
    server = ReadServer(coalesce=CoalesceConfig(max_batch=64,
                                                max_delay_s=0.002))
    snap = _snapshot()
    server.swap_to(snap)
    N_THREADS, N_REQ = 8, 30
    errors: list = []

    def client(idx):
        rng = np.random.default_rng(idx)
        try:
            for _ in range(N_REQ):
                ids = rng.integers(0, 64, 4)
                step, values = server.pull("weights", ids)
                if step != 11 or not np.array_equal(
                        values, snap.lookup("weights", ids)):
                    errors.append((idx, ids))
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append((idx, repr(e)))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    total = N_THREADS * N_REQ
    assert server.requests == total
    # Batching actually happened: fewer executions than requests.
    assert 1 <= server.batches < total
    assert server.batched_requests == total


def test_coalescer_idle_server_never_waits():
    server = ReadServer(coalesce=CoalesceConfig(max_batch=64,
                                                max_delay_s=0.25))
    server.swap_to(_snapshot())
    t0 = time.perf_counter()
    step, values = server.pull("weights", [1, 2, 3])
    elapsed = time.perf_counter() - t0
    assert step == 11 and values.shape == (3, 4)
    # max_delay only applies while another batch is EXECUTING; an idle
    # server answers immediately (far under the 250ms knob).
    assert elapsed < 0.2


def test_coalescer_per_item_errors_do_not_fail_siblings():
    server = ReadServer(coalesce=CoalesceConfig(max_batch=64))
    snap = _snapshot()
    server.swap_to(snap)
    results: dict = {}
    barrier = threading.Barrier(3)

    def go(name, ids):
        barrier.wait()
        try:
            results[name] = server.pull("weights", ids)
        except Exception as e:  # noqa: BLE001 — asserted below
            results[name] = e

    threads = [threading.Thread(target=go, args=(n, ids)) for n, ids in
               (("good_a", [1, 2]), ("bad", [9999]), ("good_b", [3]))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert isinstance(results["bad"], Exception)
    assert np.array_equal(results["good_a"][1],
                          snap.lookup("weights", [1, 2]))
    assert np.array_equal(results["good_b"][1],
                          snap.lookup("weights", [3]))


# ---------------------------------------------------------------------------
# Zero-copy: no O(table) allocation per request
# ---------------------------------------------------------------------------

def test_batched_pulls_never_materialize_the_table():
    # A table far larger than any legitimate per-request allocation:
    # 1M rows x 16 float32 = 64 MiB. Serving batched pulls (including
    # the segment/binary encode path) must allocate O(batch), never
    # O(table) — the FPS010 lint is the static half of this contract.
    NROWS, RANK = 1 << 20, 16
    table = np.zeros((NROWS, RANK), np.float32)
    table_bytes = table.nbytes
    server = ReadServer()
    server.swap_to(ServableSnapshot(5, "big", {"emb": table}, [],
                                    "none"))
    ids = np.arange(0, NROWS, NROWS // 256).tolist()
    req = {"op": "multi",
           "reqs": [{"op": "pull", "table": "emb", "ids": ids}
                    for _ in range(4)]}
    handle_request_segs(server, req)  # warm allocator pools
    tracemalloc.start()
    try:
        for _ in range(8):
            resp, segs = handle_request_segs(server, req)
            parts = pack_bin_payload(resp, segs)
            assert sum(getattr(p, "nbytes", None) or len(p)
                       for p in parts) < table_bytes // 64
        _cur, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    # Peak transient allocation stays orders of magnitude under the
    # table: one full .copy()/np.asarray() of it would blow this.
    assert peak < table_bytes // 8, (
        f"peak {peak} bytes vs table {table_bytes} — something "
        f"materialized O(table) per request")


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------

def test_admission_cost_weights_and_multi_sums():
    adm = AdmissionController(max_cost=16.0)
    assert adm.cost_of({"op": "pull"}) == DEFAULT_COST_WEIGHTS["pull"]
    assert adm.cost_of({"op": "topk"}) == DEFAULT_COST_WEIGHTS["topk"]
    assert adm.cost_of({"op": "stats"}) == DEFAULT_COST_WEIGHTS["stats"]
    multi = {"op": "multi",
             "reqs": [{"op": "pull"}] * 5 + [{"op": "topk"}]}
    assert adm.cost_of(multi) == 5 * 1.0 + 8.0
    assert adm.cost_of("garbage") == 1.0


def test_admission_idle_always_admits_busy_sheds():
    adm = AdmissionController(max_cost=8.0)
    # One request larger than the whole budget: admitted while idle
    # (degrade to serial, never starve).
    assert adm.try_admit(100.0)
    assert not adm.try_admit(1.0)       # budget wedged: shed
    assert adm.stats()["rejected"] == 1
    adm.release(100.0)
    assert adm.try_admit(4.0) and adm.try_admit(4.0)
    assert not adm.try_admit(1.0)       # 8 + 1 > 8
    adm.release(4.0)
    assert adm.try_admit(1.0)


def test_admission_aimd_governor_tracks_latency_target():
    adm = AdmissionController(max_cost=64.0, target_latency_s=0.010,
                              min_limit_fraction=0.125)
    # Sustained over-target completions: multiplicative decrease down
    # to the floor, never below it.
    for _ in range(200):
        assert adm.try_admit(1.0) or True
        adm.release(1.0, latency_s=0.100)
    assert adm.limit() == pytest.approx(64.0 * 0.125)
    # Recovery: under-target completions regrow additively to the cap.
    for _ in range(200):
        adm.release(0.0, latency_s=0.001)
    assert adm.limit() == pytest.approx(64.0)


def test_tcp_serve_exposes_admission_stats():
    server, tcp = _tcp()
    try:
        with WireClient("127.0.0.1", tcp.port) as c:
            c.request({"op": "pull", "table": "weights", "ids": [1]})
        stats = tcp.wire_stats()["admission"]
        assert stats["admitted"] >= 1 and stats["rejected"] == 0
        assert stats["max_cost"] == 64.0
    finally:
        tcp.close()


# ---------------------------------------------------------------------------
# The ReadAutoscaler (unit scale; the chaos scenario covers churn)
# ---------------------------------------------------------------------------

def _write_full(dirpath, step, tables):
    arrays = {f"table::{k}": np.asarray(v) for k, v in tables.items()}
    arrays["meta::ls_format"] = np.array("exported")
    for k in list(arrays):
        arrays["meta::crc::" + k] = np.uint32(fmt.array_crc32(arrays[k]))
    os.makedirs(dirpath, exist_ok=True)
    np.savez(fmt.snapshot_path(dirpath, step), **arrays)


def _converged_fleet(tmp_path, n_readers=1, **scaler_kw):
    d = str(tmp_path)
    table = np.arange(32, dtype=np.float32).reshape(8, 4)
    _write_full(d, 1, {"w": table})
    fleet = ServingFleet(d, n_readers)
    for _ in range(3):
        fleet.poll()   # verify + fence + heartbeat, no threads
    assert all(r.stats()["step"] == 1 for r in fleet.readers)
    return fleet, ReadAutoscaler(fleet, **scaler_kw)


def test_autoscaler_scale_up_cooldown_and_lag_veto(tmp_path):
    fleet, scaler = _converged_fleet(
        tmp_path, 1, min_readers=1, max_readers=3,
        latency_slo_s=0.010, fence_lag_slo_steps=4.0, cooldown_s=5.0)
    for _ in range(20):
        fleet.readers[0].server.latency.add(0.050)  # p99 over SLO

    d1 = scaler.evaluate(newest_step=1, now=100.0)
    assert d1["action"] == "scale_up" and d1["fleet_size"] == 2
    assert fleet.quorum == 2    # majority follows membership

    # Cooldown gates the next action even though p99 still burns.
    d2 = scaler.evaluate(newest_step=1, now=101.0)
    assert d2["action"] == "hold"

    # Fence-lag veto: latency burn with a STALE fence is publish-bound
    # — another reader won't help, hold instead of thrash.
    d3 = scaler.evaluate(newest_step=100, now=200.0)
    assert d3["action"] == "hold"
    assert "publish-bound" in d3["reason"]
    assert len(fleet.readers) == 2

    # Decisions are journaled with their evidence.
    assert [d["action"] for d in scaler.decisions] == [
        "scale_up", "hold", "hold"]
    assert d1["worst_p99_s"] == pytest.approx(0.050)


def test_autoscaler_scale_down_to_min_then_holds(tmp_path):
    fleet, scaler = _converged_fleet(
        tmp_path, 2, min_readers=1, max_readers=3,
        latency_slo_s=1.0, scale_down_fraction=0.25, cooldown_s=0.0)
    for r in fleet.readers:
        for _ in range(20):
            r.server.latency.add(0.001)   # way under 25% of the SLO

    d1 = scaler.evaluate(newest_step=1, now=10.0)
    assert d1["action"] == "scale_down" and d1["fleet_size"] == 1
    d2 = scaler.evaluate(newest_step=1, now=20.0)
    assert d2["action"] == "hold"         # never below min_readers
    assert len(fleet.readers) == 1
    assert fleet.quorum == 1


def test_fleet_dynamic_membership_requorum(tmp_path):
    fleet, _scaler = _converged_fleet(tmp_path, 3, min_readers=1)
    assert fleet.quorum == 2
    r = fleet.add_reader()
    assert len(fleet.readers) == 4 and fleet.quorum == 3
    assert fleet.remove_reader(r.reader_id)
    assert len(fleet.readers) == 3 and fleet.quorum == 2
    # The last reader is never removable.
    for rid in [x.reader_id for x in fleet.readers[1:]]:
        assert fleet.remove_reader(rid)
    assert not fleet.remove_reader(fleet.readers[0].reader_id)
    assert len(fleet.readers) == 1
