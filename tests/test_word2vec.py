"""word2vec SGNS: loss falls during streaming training; negatives sampled
on-device; both tables updated through the collective pull/push path."""

import jax
import numpy as np

from fps_tpu.core.driver import num_workers_of
from fps_tpu.models.word2vec import (
    IN_TABLE,
    OUT_TABLE,
    W2VConfig,
    skipgram_chunks,
    word2vec,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import synthetic_corpus

V = 300


def train_w2v(mesh, sync_every=None, epochs=2, dim=16):
    tokens = synthetic_corpus(V, 60_000, num_topics=8, seed=0)
    uni = np.bincount(tokens, minlength=V).astype(np.float64)
    cfg = W2VConfig(vocab_size=V, dim=dim, window=3, negatives=4,
                    learning_rate=0.05, subsample_t=None)
    trainer, store = word2vec(mesh, cfg, uni, sync_every=sync_every)
    tables, ls = trainer.init_state(jax.random.key(0))
    W = num_workers_of(mesh)
    all_m = []
    for e in range(epochs):
        chunks = skipgram_chunks(
            tokens, uni, cfg, num_workers=W, local_batch=64,
            steps_per_chunk=8, sync_every=sync_every, seed=e,
        )
        tables, ls, m = trainer.fit_stream(
            tables, ls, chunks, jax.random.fold_in(jax.random.key(1), e)
        )
        all_m.extend(m)
    loss = np.concatenate([m["loss"] for m in all_m])
    n = np.concatenate([m["n"] for m in all_m])
    return store, loss, n


def test_w2v_loss_decreases(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    store, loss, n = train_w2v(mesh)
    steps = len(loss)
    early = loss[: steps // 5].sum() / n[: steps // 5].sum()
    late = loss[-steps // 5 :].sum() / n[-steps // 5 :].sum()
    # Initial loss ~ (1+K)*log 2 ≈ 3.47 with K=4; must drop clearly.
    assert late < early * 0.8, (early, late)
    # Input table moved away from init; output table moved away from zero.
    in_emb = store.lookup_host(IN_TABLE, np.arange(V))
    out_emb = store.lookup_host(OUT_TABLE, np.arange(V))
    assert float(np.abs(out_emb).max()) > 0.01
    assert float(np.linalg.norm(in_emb, axis=1).max()) > 0.1


def test_w2v_ssp_matches_shape(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=2)
    store, loss, n = train_w2v(mesh, sync_every=4, epochs=1)
    assert len(loss) > 0 and np.all(np.isfinite(loss))
    early = loss[: len(loss) // 4].sum() / n[: len(loss) // 4].sum()
    late = loss[-len(loss) // 4 :].sum() / n[-len(loss) // 4 :].sum()
    assert late < early, (early, late)


def test_skipgram_chunks_static_shapes():
    tokens = synthetic_corpus(50, 5000, seed=1)
    uni = np.bincount(tokens, minlength=50).astype(np.float64)
    cfg = W2VConfig(vocab_size=50, window=2, subsample_t=None)
    shapes = set()
    total_w = 0.0
    for chunk in skipgram_chunks(tokens, uni, cfg, num_workers=4,
                                 local_batch=8, steps_per_chunk=4):
        shapes.add(chunk["center"].shape)
        assert chunk["center"].shape == chunk["context"].shape
        total_w += chunk["weight"].sum()
    assert len(shapes) == 1  # every chunk identical shape
    # pair count ≈ 2 * E[min(half,d) coverage] — just sanity-bound it.
    assert total_w > 2 * 0.9 * len(tokens)


def test_cooccurrence_sketch_tap_tracks_exact(devices8):
    """The tug-of-war step_tap riding the training loop must reproduce the
    exact co-occurrence inner products among probe words (computed from the
    identical pair stream) up to the sketch's variance: high rank agreement
    across probe pairs and bounded error on the diagonal (F2 norms)."""
    from fps_tpu.models.word2vec import (
        accumulate_sketch_taps,
        cooccurrence_sketch_tap,
        sketch_similarity,
    )
    from fps_tpu.sketch import TugOfWarSpec

    V2 = 80
    tokens = synthetic_corpus(V2, 20_000, num_topics=4, seed=5)
    uni = np.bincount(tokens, minlength=V2).astype(np.float64)
    cfg = W2VConfig(vocab_size=V2, dim=8, window=2, negatives=2,
                    subsample_t=None)
    probe = np.argsort(-uni)[:6].astype(np.int32)  # 6 most frequent words
    spec = TugOfWarSpec(depth=5, width=512, seed=7)

    mesh = make_ps_mesh(num_shards=4, num_data=2)
    W = num_workers_of(mesh)
    trainer, store = word2vec(
        mesh, cfg, uni, step_tap=cooccurrence_sketch_tap(spec, probe)
    )
    tables, ls = trainer.init_state(jax.random.key(0))
    chunk_args = dict(num_workers=W, local_batch=64, steps_per_chunk=4,
                      seed=3)
    tables, ls, m = trainer.fit_stream(
        tables, ls, skipgram_chunks(tokens, uni, cfg, **chunk_args),
        jax.random.key(1),
    )
    sketches = accumulate_sketch_taps(m)
    est = sketch_similarity(sketches)

    # Exact co-occurrence from the IDENTICAL (deterministic) pair stream.
    C = np.zeros((len(probe), V2), np.float64)
    for chunk in skipgram_chunks(tokens, uni, cfg, **chunk_args):
        c = chunk["center"].reshape(-1)
        x = chunk["context"].reshape(-1)
        w = chunk["weight"].reshape(-1)
        for p, pid in enumerate(probe):
            sel = (c == pid) & (w > 0)
            np.add.at(C[p], x[sel], w[sel])
    exact = C @ C.T

    # Diagonal (second-moment) estimates: unbiased, variance O(F2^2/width).
    rel = np.abs(np.diag(est) - np.diag(exact)) / np.maximum(
        np.diag(exact), 1.0
    )
    assert np.median(rel) < 0.15, (np.diag(est), np.diag(exact))
    # Off-diagonal similarity structure: strong rank agreement.
    iu = np.triu_indices(len(probe), k=1)
    r = np.corrcoef(est[iu], exact[iu])[0, 1]
    assert r > 0.9, (r, est[iu], exact[iu])


def test_w2v_push_delay_guardrail_warns(devices8):
    """docs/STALENESS.md finding #5: large push_delay (the measured collapse
    regime for SGNS under the lr-downscale recipe) must raise a runtime
    warning; small/zero push_delay must not."""
    import warnings

    import pytest

    tokens = synthetic_corpus(50, 2000, seed=0)
    uni = np.bincount(tokens, minlength=50).astype(np.float64)
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    cfg_down = W2VConfig(vocab_size=50, dim=8, learning_rate=0.00625,
                         subsample_t=None)
    with pytest.warns(UserWarning, match="push_delay=16.*downscaled"):
        word2vec(mesh, cfg_down, uni, push_delay=16)
    with pytest.warns(UserWarning, match="push_delay=16"):
        word2vec(mesh, W2VConfig(vocab_size=50, dim=8, subsample_t=None),
                 uni, push_delay=16)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        word2vec(mesh, W2VConfig(vocab_size=50, dim=8, subsample_t=None),
                 uni, push_delay=4)


def test_w2v_hot_words_literal_validated(devices8):
    """A typo'd hot_words literal must fail with the altitude-correct
    ValueError at store construction, not a TypeError inside min()."""
    import pytest

    from fps_tpu.models.word2vec import W2VConfig, make_store
    from fps_tpu.parallel.mesh import make_ps_mesh

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    with pytest.raises(ValueError, match="hot_words"):
        make_store(mesh, W2VConfig(vocab_size=64, dim=8, hot_words="Auto"))
