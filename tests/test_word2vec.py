"""word2vec SGNS: loss falls during streaming training; negatives sampled
on-device; both tables updated through the collective pull/push path."""

import jax
import numpy as np

from fps_tpu.core.driver import num_workers_of
from fps_tpu.models.word2vec import (
    IN_TABLE,
    OUT_TABLE,
    W2VConfig,
    skipgram_chunks,
    word2vec,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import synthetic_corpus

V = 300


def train_w2v(mesh, sync_every=None, epochs=2, dim=16):
    tokens = synthetic_corpus(V, 60_000, num_topics=8, seed=0)
    uni = np.bincount(tokens, minlength=V).astype(np.float64)
    cfg = W2VConfig(vocab_size=V, dim=dim, window=3, negatives=4,
                    learning_rate=0.05, subsample_t=None)
    trainer, store = word2vec(mesh, cfg, uni, sync_every=sync_every)
    tables, ls = trainer.init_state(jax.random.key(0))
    W = num_workers_of(mesh)
    all_m = []
    for e in range(epochs):
        chunks = skipgram_chunks(
            tokens, uni, cfg, num_workers=W, local_batch=64,
            steps_per_chunk=8, sync_every=sync_every, seed=e,
        )
        tables, ls, m = trainer.fit_stream(
            tables, ls, chunks, jax.random.fold_in(jax.random.key(1), e)
        )
        all_m.extend(m)
    loss = np.concatenate([m["loss"] for m in all_m])
    n = np.concatenate([m["n"] for m in all_m])
    return store, loss, n


def test_w2v_loss_decreases(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    store, loss, n = train_w2v(mesh)
    steps = len(loss)
    early = loss[: steps // 5].sum() / n[: steps // 5].sum()
    late = loss[-steps // 5 :].sum() / n[-steps // 5 :].sum()
    # Initial loss ~ (1+K)*log 2 ≈ 3.47 with K=4; must drop clearly.
    assert late < early * 0.8, (early, late)
    # Input table moved away from init; output table moved away from zero.
    in_emb = store.lookup_host(IN_TABLE, np.arange(V))
    out_emb = store.lookup_host(OUT_TABLE, np.arange(V))
    assert float(np.abs(out_emb).max()) > 0.01
    assert float(np.linalg.norm(in_emb, axis=1).max()) > 0.1


def test_w2v_ssp_matches_shape(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=2)
    store, loss, n = train_w2v(mesh, sync_every=4, epochs=1)
    assert len(loss) > 0 and np.all(np.isfinite(loss))
    early = loss[: len(loss) // 4].sum() / n[: len(loss) // 4].sum()
    late = loss[-len(loss) // 4 :].sum() / n[-len(loss) // 4 :].sum()
    assert late < early, (early, late)


def test_skipgram_chunks_static_shapes():
    tokens = synthetic_corpus(50, 5000, seed=1)
    uni = np.bincount(tokens, minlength=50).astype(np.float64)
    cfg = W2VConfig(vocab_size=50, window=2, subsample_t=None)
    shapes = set()
    total_w = 0.0
    for chunk in skipgram_chunks(tokens, uni, cfg, num_workers=4,
                                 local_batch=8, steps_per_chunk=4):
        shapes.add(chunk["center"].shape)
        assert chunk["center"].shape == chunk["context"].shape
        total_w += chunk["weight"].sum()
    assert len(shapes) == 1  # every chunk identical shape
    # pair count ≈ 2 * E[min(half,d) coverage] — just sanity-bound it.
    assert total_w > 2 * 0.9 * len(tokens)
