"""Crash-safe delta-snapshot chains (ISSUE 14).

Contract under test (``docs/resilience.md`` failure model,
``docs/serving.md`` "Delta chains", ``docs/STALENESS.md`` publish-cadence
row):

* the jax-free chain layer in ``core/snapshot_format``: publication
  discovery (full-wins at a shared step), chain walking, per-link
  CRC + ``meta::base_step`` cross-link + fencing-epoch monotonicity
  verification, and pure-numpy chain resolution;
* ``Checkpointer(delta=DeltaPolicy(...))``: delta saves restore
  BIT-identically to the fulls they stand in for (tracker-sourced
  touched ids and the exact row-diff fallback agree), structural
  surprises publish fulls, the chain plan re-anchors across restarts,
  and the pod fence is re-read on EVERY publish in a chain;
* recovery semantics: a torn/CRC-failing/epoch-stale link truncates the
  chain back to the last verified link; quarantining a full quarantines
  every delta chained on it; retention GC never deletes a live chain's
  link;
* LSM-style compaction: the fold is bit-exact, shadows its chain head,
  sweeps folded deltas, and leaves a recoverable chain when killed at
  any phase (the chaos scenario runs the real SIGKILLs; here the
  phases are simulated in-process);
* the driver path: ``fit_stream`` with a delta checkpointer publishes
  deltas sourced from ``WorkerLogic.pulled_ids_host`` and resumes from
  a mid-chain state bit-identically.
"""

import os

import numpy as np
import pytest

from fps_tpu.core import snapshot_format as fmt
from fps_tpu.core.checkpoint import (
    AsyncCheckpointer,
    Checkpointer,
    DeltaPolicy,
    TouchedRowsTracker,
    load_rows,
)
from fps_tpu.core.resilience import SnapshotCorruptionError
from fps_tpu.testing import chaos


def _store(jax, mesh, *, num_ids=256, dim=4, name="w"):
    from fps_tpu.core.store import ParamStore, TableSpec

    store = ParamStore(mesh, [TableSpec(name, num_ids=num_ids, dim=dim)])
    store.init(jax.random.key(0))
    return store


def _touch(store, name, ids, val):
    ids = np.asarray(ids)
    rows = store.lookup_host(name, ids)
    load_rows(store, name, ids, rows + val)


@pytest.fixture
def jx(devices8):
    import jax

    return jax


@pytest.fixture
def mesh(jx):
    from fps_tpu.parallel.mesh import make_ps_mesh

    return make_ps_mesh()


def _chain(dirpath, jx, mesh, *, steps=4, policy=None, seed=3):
    """A store + checkpointer with one full and ``steps - 1`` deltas;
    returns (store, checkpointer, expected_final_table)."""
    store = _store(jx, mesh)
    ck = Checkpointer(dirpath, keep=30,
                      delta=policy or DeltaPolicy(full_every=50))
    ck.save(1, store, None)
    rng = np.random.default_rng(seed)
    for step in range(2, steps + 1):
        ids = np.unique(rng.integers(0, 256, 12))
        _touch(store, "w", ids, float(step))
        ck.save(step, store, None, touched_rows={"w": ids})
    return store, ck, store.lookup_host("w", np.arange(256)).copy()


# ---------------------------------------------------------------------------
# snapshot_format: the jax-free chain layer.
# ---------------------------------------------------------------------------

def test_publications_and_chain_members(tmp_path, jx, mesh):
    d = str(tmp_path)
    _chain(d, jx, mesh, steps=4)
    pubs = fmt.publications(d)
    assert sorted(pubs) == [1, 2, 3, 4]
    assert pubs[1].kind == "full" and pubs[1].base is None
    assert pubs[3].kind == "delta" and pubs[3].base == 2
    members = fmt.chain_members(pubs, 4)
    assert [(p.step, p.kind) for p in members] == [
        (1, "full"), (2, "delta"), (3, "delta"), (4, "delta")]
    # full-wins: a full at a delta's step shadows the delta.
    Checkpointer(d, keep=30, delta=DeltaPolicy()).compact()
    pubs = fmt.publications(d)
    assert pubs[4].kind == "full"
    assert [p.step for p in fmt.chain_members(pubs, 4)] == [4]


def test_chain_members_broken_base_raises(tmp_path, jx, mesh):
    d = str(tmp_path)
    _chain(d, jx, mesh, steps=3)
    os.remove(fmt.delta_path(d, 2, 1))
    pubs = fmt.publications(d)
    with pytest.raises(fmt.ChainError) as ei:
        fmt.chain_members(pubs, 3)
    assert ei.value.step == 3  # the link whose base is gone


def test_verify_chain_and_resolution(tmp_path, jx, mesh):
    d = str(tmp_path)
    store, _, want = _chain(d, jx, mesh, steps=4)
    ok, reason, failing = fmt.verify_chain(d, 4)
    assert ok and reason is None and failing is None
    step, members = fmt.latest_valid_chain(d)
    assert step == 4
    entries = fmt.resolve_chain_entries(members)
    np.testing.assert_array_equal(entries["table::w"], want)
    # Corrupting a mid-chain link fails verification AT that link and
    # truncates latest_valid_chain to the last verified head.
    chaos.bitflip_file(fmt.delta_path(d, 3, 2), nflips=8, seed=0)
    ok, reason, failing = fmt.verify_chain(d, 4)
    assert not ok and failing == 3
    assert fmt.latest_valid_chain(d)[0] == 2


def test_verify_chain_epoch_staleness(tmp_path, jx, mesh):
    """A delta carrying an OLDER fencing epoch than an earlier link is a
    stale zombie's publish: chain verification refuses at that link."""
    d = str(tmp_path)
    store = _store(jx, mesh)
    ck2 = Checkpointer(d, keep=30, fence_epoch=2,
                       delta=DeltaPolicy(full_every=50))
    ck2.save(1, store, None)
    _touch(store, "w", [3], 1.0)
    ck2.save(2, store, None, touched_rows={"w": np.array([3])})
    # Forge an epoch-1 delta chaining on the epoch-2 head (the fence
    # file itself is absent, so only the READ side can catch this).
    entries = {
        fmt.BASE_STEP_KEY: np.int64(2),
        fmt.POD_EPOCH_KEY: np.int64(1),
        fmt.DELTA_IDS_PREFIX + "table::w": np.array([5], np.int64),
        fmt.DELTA_ROWS_PREFIX + "table::w": np.zeros((1, 4), np.float32),
    }
    arrays = dict(entries)
    for k in list(arrays):
        arrays[fmt.CRC_PREFIX + k] = np.uint32(fmt.array_crc32(arrays[k]))
    np.savez(fmt.delta_path(d, 3, 2), **arrays)
    ok, reason, failing = fmt.verify_chain(d, 3)
    assert not ok and failing == 3 and "epoch" in reason
    assert fmt.latest_valid_chain(d)[0] == 2
    # The checkpoint reader refuses it the same way (auto-resolve
    # quarantines the stale link and lands on the verified prefix).
    step, tables, _, _ = Checkpointer(d, keep=30).read_snapshot()
    assert step == 2
    assert os.path.exists(fmt.delta_path(d, 3, 2) + ".corrupt")


# ---------------------------------------------------------------------------
# Checkpointer: delta planning + restore identity.
# ---------------------------------------------------------------------------

def test_delta_restore_bit_identical(tmp_path, jx, mesh):
    d = str(tmp_path)
    store, ck, want = _chain(d, jx, mesh, steps=5)
    assert ck.delta_publishes == 4 and ck.full_publishes == 1
    store2 = _store(jx, mesh)
    _, step = Checkpointer(d, keep=30).restore_tables(store2)
    assert step == 5
    np.testing.assert_array_equal(
        store2.lookup_host("w", np.arange(256)), want)


def test_tracker_sourced_equals_diff_fallback(tmp_path, jx, mesh):
    """touched_rows is a SUPERSET hint: the published state must be
    identical whether the tracker supplies ids or the exact row compare
    runs (and a superset only costs bytes, never correctness)."""
    d1, d2, d3 = (str(tmp_path / s) for s in ("a", "b", "c"))
    for d, touched in ((d1, "ids"), (d2, None), (d3, "superset")):
        store = _store(jx, mesh)
        ck = Checkpointer(d, keep=30, delta=DeltaPolicy(full_every=50))
        ck.save(1, store, None)
        ids = np.array([7, 9, 100])
        _touch(store, "w", ids, 2.0)
        tr = {"ids": {"w": ids}, None: None,
              "superset": {"w": np.arange(0, 200)}}[touched]
        ck.save(2, store, None, touched_rows=tr)
    states = []
    for d in (d1, d2, d3):
        s = _store(jx, mesh)
        Checkpointer(d, keep=30).restore_tables(s)
        states.append(s.lookup_host("w", np.arange(256)))
    np.testing.assert_array_equal(states[0], states[1])
    np.testing.assert_array_equal(states[0], states[2])
    # The diff fallback writes exactly the changed rows; the tracker
    # path writes its (3-row) superset too — both strictly smaller than
    # a full.
    assert fmt.publications(d2)[2].kind == "delta"
    assert fmt.publications(d1)[2].kind == "delta"


def test_full_published_when_delta_not_smaller(tmp_path, jx, mesh):
    """Touching every row (or an unknown touched set on a tiny table)
    makes the delta encoding >= the full: the planner must publish a
    full, not a pointless delta."""
    d = str(tmp_path)
    store = _store(jx, mesh)
    ck = Checkpointer(d, keep=30, delta=DeltaPolicy(full_every=50))
    ck.save(1, store, None)
    _touch(store, "w", np.arange(256), 1.0)
    ck.save(2, store, None, touched_rows={"w": np.arange(256)})
    assert fmt.publications(d)[2].kind == "full"
    assert ck.delta_publishes == 0


def test_full_every_bounds_chain_length(tmp_path, jx, mesh):
    d = str(tmp_path)
    store = _store(jx, mesh)
    ck = Checkpointer(d, keep=30, delta=DeltaPolicy(full_every=3))
    ck.save(1, store, None)
    for step in range(2, 8):
        _touch(store, "w", [step], 1.0)
        ck.save(step, store, None, touched_rows={"w": np.array([step])})
    kinds = [fmt.publications(d)[s].kind for s in range(1, 8)]
    # full, d, d, full, d, d, full — at most full_every-1 deltas/chain.
    assert kinds == ["full", "delta", "delta", "full", "delta", "delta",
                     "full"]


def test_chain_reanchors_across_restart(tmp_path, jx, mesh):
    """A fresh Checkpointer (new process) continues the on-disk chain
    after read_snapshot instead of restarting with a full."""
    d = str(tmp_path)
    store, _, want = _chain(d, jx, mesh, steps=3)
    store2 = _store(jx, mesh)
    ck2 = Checkpointer(d, keep=30, delta=DeltaPolicy(full_every=50))
    ck2.restore_tables(store2)
    _touch(store2, "w", [11], 5.0)
    path = ck2.save(4, store2, None, touched_rows={"w": np.array([11])})
    assert os.path.basename(path) == os.path.basename(
        fmt.delta_path(d, 4, 3))
    s3 = _store(jx, mesh)
    Checkpointer(d, keep=30).restore_tables(s3)
    np.testing.assert_array_equal(
        s3.lookup_host("w", np.arange(256)),
        store2.lookup_host("w", np.arange(256)))


def test_quarantined_full_cascades_to_chained_deltas(tmp_path, jx, mesh):
    """Satellite: quarantining a full must quarantine every delta
    chained on it — no reader may resolve a chain through a *.corrupt
    base, and latest_valid_step knows delta files."""
    d = str(tmp_path)
    store, ck, _ = _chain(d, jx, mesh, steps=4)
    # Corrupt the chain's BASE full: every chained step is unservable
    # (their state is defined in terms of the bad link).
    chaos.bitflip_file(fmt.snapshot_path(d, 1), nflips=8, seed=1)
    assert Checkpointer(d, keep=30).latest_valid_step() is None
    ck3 = Checkpointer(d, keep=30)
    with pytest.raises(SnapshotCorruptionError):
        ck3.read_snapshot(step=4)  # explicit pin: raises, no fallback
    # Auto-resolve walks 4 -> trips on the corrupt base -> quarantines
    # the full AND every delta chained on it -> nothing survives.
    with pytest.raises(FileNotFoundError):
        ck3.read_snapshot(step=None)
    names = sorted(os.listdir(d))
    assert fmt.SNAPSHOT_FMT.format(step=1) + ".corrupt" in names
    for s, b in ((2, 1), (3, 2), (4, 3)):
        assert os.path.basename(
            fmt.delta_path(d, s, b)) + ".corrupt" in names
    # No live chain resolves through the corrupt base anymore.
    assert fmt.publications(d) == {}
    assert fmt.latest_valid_chain(d) is None


def test_corrupt_midchain_truncates_to_last_verified(tmp_path, jx, mesh):
    d = str(tmp_path)
    store, ck, _ = _chain(d, jx, mesh, steps=5)
    chaos.truncate_file(fmt.delta_path(d, 4, 3))
    assert Checkpointer(d, keep=30).latest_valid_step() == 3
    step, tables, _, _ = Checkpointer(d, keep=30).read_snapshot()
    assert step == 3  # truncation: lost recency, never corruption
    # The failing link and its descendant are quarantined; the prefix
    # survives untouched.
    assert os.path.exists(fmt.delta_path(d, 2, 1))
    assert os.path.exists(fmt.delta_path(d, 4, 3) + ".corrupt")
    assert os.path.exists(fmt.delta_path(d, 5, 4) + ".corrupt")


def test_gc_protects_live_chain_links(tmp_path, jx, mesh):
    """keep=2 on a 5-link chain: every link of the newest heads'
    back-chains survives GC (deleting the base full would orphan every
    delta)."""
    d = str(tmp_path)
    store = _store(jx, mesh)
    ck = Checkpointer(d, keep=2, delta=DeltaPolicy(full_every=50))
    ck.save(1, store, None)
    for step in range(2, 6):
        _touch(store, "w", [step], 1.0)
        ck.save(step, store, None, touched_rows={"w": np.array([step])})
    assert sorted(fmt.publications(d)) == [1, 2, 3, 4, 5]
    s2 = _store(jx, mesh)
    _, step = Checkpointer(d, keep=2).restore_tables(s2)
    assert step == 5


def test_compaction_folds_and_sweeps(tmp_path, jx, mesh):
    d = str(tmp_path)
    store, ck, want = _chain(d, jx, mesh, steps=5)
    path = ck.compact()
    assert os.path.basename(path) == fmt.SNAPSHOT_FMT.format(step=5)
    assert ck.compactions == 1
    pubs = fmt.publications(d)
    # Folded deltas swept; the base full kept for redundancy (keep>=2).
    assert [(s, pubs[s].kind) for s in sorted(pubs)] == [
        (1, "full"), (5, "full")]
    s2 = _store(jx, mesh)
    _, step = Checkpointer(d, keep=30).restore_tables(s2)
    assert step == 5
    np.testing.assert_array_equal(
        s2.lookup_host("w", np.arange(256)), want)
    # Nothing to fold on a full head.
    assert Checkpointer(d, keep=30, delta=DeltaPolicy()).compact() is None


def test_compaction_phase_crashes_recoverable(tmp_path, jx, mesh):
    """The in-process twin of the chaos scenario's SIGKILL legs: abort
    compaction at each phase and assert the directory still resolves to
    the same state (and a rerun compaction completes)."""
    class _Stop(Exception):
        pass

    for phase in ("precommit", "published", "swept_one"):
        d = str(tmp_path / phase)
        store, ck, want = _chain(d, jx, mesh, steps=5)
        ck._compact_phase_hook = (
            lambda p, _ph=phase: (_ for _ in ()).throw(_Stop())
            if p == _ph else None)
        with pytest.raises(_Stop):
            ck.compact()
        step, members = fmt.latest_valid_chain(d)
        assert step == 5, phase
        np.testing.assert_array_equal(
            fmt.resolve_chain_entries(members)["table::w"], want)
        ck2 = Checkpointer(d, keep=30, delta=DeltaPolicy())
        ck2.compact()
        step2, members2 = fmt.latest_valid_chain(d)
        assert step2 == 5 and members2[-1].kind == "full", phase
        np.testing.assert_array_equal(
            fmt.resolve_chain_entries(members2)["table::w"], want)


def test_auto_compaction_via_policy(tmp_path, jx, mesh):
    d = str(tmp_path)
    store = _store(jx, mesh)
    ck = Checkpointer(d, keep=30,
                      delta=DeltaPolicy(full_every=50, compact_every=3))
    ck.save(1, store, None)
    for step in range(2, 9):
        _touch(store, "w", [step], 1.0)
        ck.save(step, store, None, touched_rows={"w": np.array([step])})
    assert ck.compactions >= 1
    step, members = fmt.latest_valid_chain(d)
    assert step == 8
    # The live chain stays short: compaction keeps folding it.
    assert sum(1 for p in members if p.kind == "delta") <= 3
    s2 = _store(jx, mesh)
    Checkpointer(d, keep=30).restore_tables(s2)
    np.testing.assert_array_equal(
        s2.lookup_host("w", np.arange(256)),
        store.lookup_host("w", np.arange(256)))


# ---------------------------------------------------------------------------
# Fence re-read on EVERY publish in a chain (satellite).
# ---------------------------------------------------------------------------

def _drop_fence(dirpath, min_epoch):
    import json

    with open(os.path.join(dirpath, "pod_fence.json"), "w",
              encoding="utf-8") as f:
        json.dump({"min_epoch": min_epoch}, f)


@pytest.mark.parametrize("async_writer", [False, True])
def test_fence_refuses_midchain_delta(tmp_path, jx, mesh, async_writer):
    """A fence landing MID-CHAIN must refuse the next delta publish with
    StaleEpochError — the fence is re-read on every publish, full or
    delta, sync or async."""
    from fps_tpu.supervise.child import StaleEpochError

    d = str(tmp_path)
    store = _store(jx, mesh)
    cls = AsyncCheckpointer if async_writer else Checkpointer
    ck = cls(d, keep=30, fence_epoch=1, delta=DeltaPolicy(full_every=50))
    try:
        ck.save(1, store, None)
        _touch(store, "w", [3], 1.0)
        ck.save(2, store, None, touched_rows={"w": np.array([3])})
        ck.flush()
        assert fmt.publications(d)[2].kind == "delta"
        _drop_fence(d, 2)  # the pod moved on: this writer is a zombie
        _touch(store, "w", [4], 1.0)
        with pytest.raises((StaleEpochError, RuntimeError)) as ei:
            ck.save(3, store, None, touched_rows={"w": np.array([4])})
            ck.flush()
        if not isinstance(ei.value, StaleEpochError):
            # Async path wraps the writer-thread error.
            assert isinstance(ei.value.__cause__, StaleEpochError)
        # Nothing stale landed; the chain still resolves to step 2.
        assert fmt.latest_valid_chain(d)[0] == 2
    finally:
        try:
            ck.close()
        except RuntimeError:
            pass  # the surfaced fence error re-raises on close


def test_epochless_writer_refused_by_fenced_dir_midchain(tmp_path, jx,
                                                         mesh):
    from fps_tpu.supervise.child import StaleEpochError

    d = str(tmp_path)
    store = _store(jx, mesh)
    ck = Checkpointer(d, keep=30, delta=DeltaPolicy(full_every=50))
    ck.save(1, store, None)
    _touch(store, "w", [3], 1.0)
    ck.save(2, store, None, touched_rows={"w": np.array([3])})
    _drop_fence(d, 1)
    _touch(store, "w", [4], 1.0)
    with pytest.raises(StaleEpochError):
        ck.save(3, store, None, touched_rows={"w": np.array([4])})
    assert fmt.latest_valid_chain(d)[0] == 2


def test_fenced_delta_carries_epoch_stamp(tmp_path, jx, mesh):
    d = str(tmp_path)
    store = _store(jx, mesh)
    ck = Checkpointer(d, keep=30, fence_epoch=3,
                      delta=DeltaPolicy(full_every=50))
    ck.save(1, store, None)
    _touch(store, "w", [3], 1.0)
    ck.save(2, store, None, touched_rows={"w": np.array([3])})
    meta = fmt.read_pub_meta(fmt.delta_path(d, 2, 1))
    assert meta["base_step"] == 1 and meta["pod_epoch"] == 3


# ---------------------------------------------------------------------------
# TouchedRowsTracker.
# ---------------------------------------------------------------------------

def test_touched_tracker_capture_commit():
    tr = TouchedRowsTracker(["a", "b"])
    tr.observe({"a": np.array([3, 1, 3])})
    tr.observe({"a": np.array([5]), "b": np.array([2])})
    ids, marker = tr.capture()
    # 'b' was absent from the first observation: unknown (diff fallback).
    np.testing.assert_array_equal(ids["a"], [1, 3, 5])
    assert ids["b"] is None
    # Capture is non-destructive: re-capture sees the same prefix.
    ids2, marker2 = tr.capture()
    np.testing.assert_array_equal(ids2["a"], [1, 3, 5])
    tr.commit(marker2)
    ids3, _ = tr.capture()
    assert len(ids3["a"]) == 0
    # An uncertifiable chunk poisons every table in its prefix.
    tr.observe(None)
    tr.observe({"a": np.array([9]), "b": np.array([9])})
    ids4, _ = tr.capture()
    assert ids4["a"] is None and ids4["b"] is None


# ---------------------------------------------------------------------------
# Driver path: deltas from the pulled-id stream + resume identity.
# ---------------------------------------------------------------------------

def _sparse_logreg(jx, mesh):
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.logistic_regression import (
        LogRegConfig,
        logistic_regression,
    )
    from fps_tpu.utils.datasets import synthetic_sparse_classification

    W = num_workers_of(mesh)
    NF = 1 << 14
    data = synthetic_sparse_classification(W * 32 * 4 * 5, NF, 8, seed=0)
    data["label"] = (data["label"] > 0).astype(np.float32)
    chunks = list(epoch_chunks(data, num_workers=W, local_batch=32,
                               steps_per_chunk=4, seed=5))
    cfg = LogRegConfig(num_features=NF, learning_rate=0.1)
    return cfg, chunks, NF, logistic_regression


def test_fit_stream_publishes_tracker_sourced_deltas(tmp_path, jx, mesh):
    cfg, chunks, NF, factory = _sparse_logreg(jx, mesh)

    def run(d, policy, stop_at=None, start=0):
        trainer, store = factory(mesh, cfg)
        tables, ls = trainer.init_state(jx.random.key(0))
        ck = AsyncCheckpointer(d, keep=30, delta=policy)
        if start:
            tables, ls, start = trainer.restore_checkpoint(ck, ls)
        trainer.fit_stream(tables, ls, iter(chunks[start:stop_at]),
                           jx.random.key(1), checkpointer=ck,
                           checkpoint_every=1, start_step=start)
        ck.close()
        return (store.lookup_host("weights", np.arange(NF)),
                ck.delta_publishes, ck.publish_bytes_total)

    d_full = str(tmp_path / "full")
    d_delta = str(tmp_path / "delta")
    d_res = str(tmp_path / "resume")
    w_full, _, full_bytes = run(d_full, None)
    w_delta, deltas, delta_bytes = run(d_delta,
                                       DeltaPolicy(full_every=50))
    assert deltas >= 3  # the tracker-sourced chain actually engaged
    assert delta_bytes < full_bytes  # publish bytes track touched rows
    np.testing.assert_array_equal(w_full, w_delta)
    # Crash-resume mid-chain: stop after 2 chunks, restart from the
    # chain, finish — bit-identical to the uninterrupted run.
    run(d_res, DeltaPolicy(full_every=50), stop_at=2)
    w_res, _, _ = run(d_res, DeltaPolicy(full_every=50), start=1)
    np.testing.assert_array_equal(w_res, w_full)


def test_delta_metric_specs_registered():
    from fps_tpu.obs.registry import default_registry

    reg = default_registry()
    for name in ("checkpoint.delta_publishes", "checkpoint.delta_bytes",
                 "checkpoint.compactions", "serve.fence_step"):
        assert reg.get(name) is not None, name


@pytest.mark.slow
def test_delta_chain_kill_scenario_end_to_end(tmp_path):
    """The full chaos leg (shared with tools/chaos_sweep.py so the two
    cannot drift): SIGKILL mid-chain under the supervisor + SIGKILL at
    every compaction phase — recovery to the last verified link,
    bit-identical resume."""
    from fps_tpu.testing.supervised_demo import (
        run_delta_chain_kill_scenario,
    )

    ok, detail = run_delta_chain_kill_scenario(str(tmp_path))
    assert ok, detail


def test_orphan_delta_never_published_after_failed_base(tmp_path, jx, mesh,
                                                        monkeypatch):
    """A delta planned while its base's BACKGROUND write was in flight
    must never land if that write fails — the writer refuses the orphan
    (broken chain heads never reach disk) and the caller sees the
    error; the next save publishes a full."""
    import threading

    import fps_tpu.core.checkpoint as ckmod

    d = str(tmp_path)
    store = _store(jx, mesh)
    ck = AsyncCheckpointer(d, keep=30, delta=DeltaPolicy(full_every=50))
    ck.save(1, store, None)
    ck.flush()
    real = ckmod._atomic_savez
    gate = threading.Event()
    state = {"fails": 0}

    def failing(path, arrays, precommit=None):
        if state["fails"] == 0:
            state["fails"] = 1
            gate.wait(10)  # hold until the NEXT save is enqueued
            raise OSError("disk full")
        return real(path, arrays, precommit)

    monkeypatch.setattr(ckmod, "_atomic_savez", failing)
    _touch(store, "w", [3], 1.0)
    ck.save(2, store, None, touched_rows={"w": np.array([3])})
    _touch(store, "w", [4], 1.0)
    ck.save(3, store, None, touched_rows={"w": np.array([4])})
    gate.set()  # write(2) now fails; queued delta(3, base 2) is refused
    with pytest.raises(RuntimeError):
        ck.flush()
    assert set(fmt.publications(d)) == {1}  # no orphan on disk
    # Recovery: the chain plan reset — the next save is a clean FULL.
    _touch(store, "w", [5], 1.0)
    ck.save(4, store, None, touched_rows={"w": np.array([5])})
    ck.close()
    assert fmt.publications(d)[4].kind == "full"
    s2 = _store(jx, mesh)
    _, step = Checkpointer(d, keep=30).restore_tables(s2)
    assert step == 4
    np.testing.assert_array_equal(
        s2.lookup_host("w", np.arange(256)),
        store.lookup_host("w", np.arange(256)))


def test_compaction_credits_chain_plan(tmp_path, jx, mesh):
    """compact() credits the folded deltas back to the publisher's
    chain-length plan: auto-compaction must not cause premature
    full_every fulls against an already-folded chain."""
    d = str(tmp_path)
    store = _store(jx, mesh)
    ck = Checkpointer(d, keep=30,
                      delta=DeltaPolicy(full_every=6, compact_every=3))
    ck.save(1, store, None)
    for step in range(2, 12):
        _touch(store, "w", [step], 1.0)
        ck.save(step, store, None, touched_rows={"w": np.array([step])})
    # Every post-base publication stayed a delta (compaction kept the
    # live chain under full_every; without the credit, steps 6/11 would
    # have been whole-table fulls).
    assert ck.delta_publishes == 10 and ck.full_publishes == 1
    assert ck.compactions >= 2
