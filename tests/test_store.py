"""Core store tests: pull answers every request, pushes accumulate.

Mirrors the reference's core test intent (SURVEY.md §4: "a core test driving
FlinkParameterServer.transform with trivial logic asserting every pull gets
answered and pushes accumulate"), on a real 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from fps_tpu.core.store import (
    ParamStore,
    TableSpec,
    id_to_phys,
    phys_to_id,
    pull,
    pull_local,
    push,
    rows_per_shard,
)
from fps_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS, make_ps_mesh


def reference_table(num_ids, dim, num_shards):
    """Dense global table in owner-major physical layout + the id->row map."""
    rps = rows_per_shard(num_ids, num_shards)
    total = rps * num_shards
    phys = np.arange(total)
    ids = phys_to_id(phys, num_shards, rps)
    vals = (ids[:, None] * 10.0 + np.arange(dim)[None, :]).astype(np.float32)
    return vals, rps


def test_phys_id_roundtrip():
    for num_shards in (1, 3, 8):
        ids = np.arange(100)
        rps = rows_per_shard(100, num_shards)
        phys = id_to_phys(ids, num_shards, rps)
        back = phys_to_id(phys, num_shards, rps)
        np.testing.assert_array_equal(back, ids)
        assert len(np.unique(np.asarray(phys))) == 100


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4)])
def test_pull_returns_requested_rows(devices8, mesh_shape):
    mesh = make_ps_mesh(num_shards=mesh_shape[1], num_data=mesh_shape[0])
    S = mesh_shape[1]
    num_ids, dim, B = 103, 7, 16
    table, rps = reference_table(num_ids, dim, S)
    table_dev = jax.device_put(
        jnp.asarray(table), NamedSharding(mesh, P(SHARD_AXIS, None))
    )
    W = mesh_shape[0] * mesh_shape[1]
    rng = np.random.default_rng(0)
    ids = rng.integers(0, num_ids, (W * B,)).astype(np.int32)
    ids_dev = jax.device_put(
        jnp.asarray(ids), NamedSharding(mesh, P((DATA_AXIS, SHARD_AXIS)))
    )

    out = jax.jit(
        jax.shard_map(
            lambda t, i: pull(t, i, num_shards=S),
            mesh=mesh,
            in_specs=(P(SHARD_AXIS, None), P((DATA_AXIS, SHARD_AXIS))),
            out_specs=P((DATA_AXIS, SHARD_AXIS)),
            check_vma=False,
        )
    )(table_dev, ids_dev)

    expected = (ids[:, None] * 10.0 + np.arange(dim)[None, :]).astype(np.float32)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4)])
def test_push_accumulates_including_duplicates(devices8, mesh_shape):
    mesh = make_ps_mesh(num_shards=mesh_shape[1], num_data=mesh_shape[0])
    D, S = mesh_shape
    W = D * S
    num_ids, dim, B = 50, 4, 12
    rps = rows_per_shard(num_ids, S)
    table = np.zeros((rps * S, dim), np.float32)
    table_dev = jax.device_put(
        jnp.asarray(table), NamedSharding(mesh, P(SHARD_AXIS, None))
    )
    rng = np.random.default_rng(1)
    ids = rng.integers(0, num_ids, (W * B,)).astype(np.int32)
    deltas = rng.normal(0, 1, (W * B, dim)).astype(np.float32)

    out = jax.jit(
        jax.shard_map(
            lambda t, i, d: push(
                t, i, d, num_shards=S,
                data_axis=DATA_AXIS if D > 1 else None,
            ),
            mesh=mesh,
            in_specs=(
                P(SHARD_AXIS, None),
                P((DATA_AXIS, SHARD_AXIS)),
                P((DATA_AXIS, SHARD_AXIS), None),
            ),
            out_specs=P(SHARD_AXIS, None),
            check_vma=False,
        )
    )(table_dev, jnp.asarray(ids), jnp.asarray(deltas))

    expected = np.zeros((rps * S, dim), np.float32)
    phys = np.asarray(id_to_phys(ids, S, rps))
    np.testing.assert_array_equal(
        np.asarray(phys_to_id(np.arange(rps * S), S, rps))[phys], ids
    )
    np.add.at(expected, phys, deltas)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5, atol=1e-5)


def test_push_general_apply_fn_sees_combined_delta(devices8):
    """Non-additive folds get the batch-summed delta once per id, and
    padding pushes (id -1) are dropped entirely."""
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    S, num_ids, dim = 8, 24, 3
    rps = rows_per_shard(num_ids, S)
    base = np.ones((rps * S, dim), np.float32)
    ids = np.array([5] * 8 + list(range(7)) + [-1], np.int32)  # dup-heavy + pad
    deltas = np.ones((16, dim), np.float32)

    # apply_fn: param * 2 + delta  (checks it runs once per touched row).
    out = jax.jit(
        jax.shard_map(
            lambda t, i, d: push(
                t, i, d, num_shards=S, data_axis=None,
                apply_fn=lambda rows, delta: rows * 2 + delta,
            ),
            mesh=mesh,
            in_specs=(P(SHARD_AXIS, None), P((DATA_AXIS, SHARD_AXIS)),
                      P((DATA_AXIS, SHARD_AXIS), None)),
            out_specs=P(SHARD_AXIS, None),
            check_vma=False,
        )
    )(
        jax.device_put(jnp.asarray(base), NamedSharding(mesh, P(SHARD_AXIS, None))),
        jnp.asarray(ids),
        jnp.asarray(deltas),
    )
    out = np.asarray(out)
    phys5 = int(id_to_phys(np.int32(5), S, rps))
    # id 5: touched, combined delta = 8 (+1 from the range part? id 5 also in range)
    total5 = 8.0 + 1.0
    assert out[phys5] == pytest.approx(np.full(dim, 1 * 2 + total5))
    phys3 = int(id_to_phys(np.int32(3), S, rps))
    assert out[phys3] == pytest.approx(np.full(dim, 1 * 2 + 1.0))
    # Untouched id stays exactly as it was.
    phys20 = int(id_to_phys(np.int32(20), S, rps))
    assert out[phys20] == pytest.approx(np.ones(dim))


def test_pull_local_reads_own_rows(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    W = 8
    num_ids, dim = 40, 5
    rps = rows_per_shard(num_ids, W)
    table, _ = reference_table(num_ids, dim, W)
    # Each worker asks only for ids it owns (id % W == worker).
    ids = np.stack([np.arange(w, w + 2 * W, W) for w in range(W)]).astype(np.int32)
    ids_flat = ids.reshape(-1)

    out = jax.jit(
        jax.shard_map(
            lambda t, i: pull_local(t, i, num_shards=W),
            mesh=mesh,
            in_specs=(P((DATA_AXIS, SHARD_AXIS), None), P((DATA_AXIS, SHARD_AXIS))),
            out_specs=P((DATA_AXIS, SHARD_AXIS)),
            check_vma=False,
        )
    )(
        jax.device_put(
            jnp.asarray(table),
            NamedSharding(mesh, P((DATA_AXIS, SHARD_AXIS), None)),
        ),
        jnp.asarray(ids_flat),
    )
    expected = (ids_flat[:, None] * 10.0 + np.arange(dim)[None, :]).astype(np.float32)
    np.testing.assert_allclose(np.asarray(out), expected)


def test_param_store_init_deterministic_across_shardings(devices8):
    """Same key -> same per-id values regardless of shard count (the
    reference's id-seeded reproducible initialization)."""
    spec = TableSpec(name="t", num_ids=37, dim=4)
    vals = {}
    for S in (1, 2, 8):
        mesh = make_ps_mesh(num_shards=S, num_data=8 // S if S < 8 else 1)
        store = ParamStore(mesh, [spec])
        store.init(jax.random.key(7))
        ids = np.arange(37)
        vals[S] = store.lookup_host("t", ids)
    np.testing.assert_allclose(vals[1], vals[2], rtol=1e-6)
    np.testing.assert_allclose(vals[1], vals[8], rtol=1e-6)


@pytest.mark.parametrize("trial", range(6))
def test_pull_push_matches_numpy_model_randomized(devices8, trial):
    """Property test: for random table/mesh/batch geometries (duplicates,
    padding ids, both combine modes), a pull followed by a push through the
    collective path matches a pure-numpy model of the PS semantics."""
    rng = np.random.default_rng(100 + trial)
    nd, ns = [(1, 8), (2, 4), (4, 2), (1, 4), (2, 2), (8, 1)][trial]
    devs = jax.devices()[: nd * ns]
    mesh = make_ps_mesh(num_shards=ns, num_data=nd, devices=devs)
    num_ids = int(rng.integers(3, 200))
    dim = int(rng.integers(1, 17))
    B_local = int(rng.integers(1, 33))
    combine = ["sum", "mean"][trial % 2]
    W = nd * ns

    rps = rows_per_shard(num_ids, ns)
    vals, _ = reference_table(num_ids, dim, ns)
    # ~20% padding ids (-1) for the push; pulls use valid ids only.
    pull_ids_h = rng.integers(0, num_ids, (W, B_local)).astype(np.int32)
    push_ids_h = pull_ids_h.copy()
    drop = rng.random((W, B_local)) < 0.2
    push_ids_h[drop] = -1
    deltas_h = rng.normal(0, 1, (W, B_local, dim)).astype(np.float32)

    table = jax.device_put(
        jnp.asarray(vals), NamedSharding(mesh, P(SHARD_AXIS, None))
    )
    bsh = NamedSharding(mesh, P((DATA_AXIS, SHARD_AXIS)))
    pids = jax.device_put(pull_ids_h.reshape(-1), bsh)
    qids = jax.device_put(push_ids_h.reshape(-1), bsh)
    dls = jax.device_put(
        deltas_h.reshape(-1, dim),
        NamedSharding(mesh, P((DATA_AXIS, SHARD_AXIS), None)),
    )

    def dev(table, pids, qids, dls):
        got = pull(table, pids, num_shards=ns)
        new = push(table, qids, dls, num_shards=ns,
                   data_axis=DATA_AXIS if nd > 1 else None,
                   combine=combine,
                   apply_fn=None if combine == "sum" else lambda r, d: r + d)
        return got, new

    got, new = jax.jit(jax.shard_map(
        dev, mesh=mesh,
        in_specs=(P(SHARD_AXIS, None), P((DATA_AXIS, SHARD_AXIS)),
                  P((DATA_AXIS, SHARD_AXIS)),
                  P((DATA_AXIS, SHARD_AXIS), None)),
        out_specs=(P((DATA_AXIS, SHARD_AXIS), None), P(SHARD_AXIS, None)),
        check_vma=False,
    ))(table, pids, qids, dls)

    # numpy model: pull = row lookup; push = per-id combined fold.
    phys = np.asarray(id_to_phys(pull_ids_h.reshape(-1), ns, rps))
    np.testing.assert_allclose(np.asarray(got), vals[phys], atol=1e-5)

    expect = vals.copy()
    flat_ids = push_ids_h.reshape(-1)
    flat_d = deltas_h.reshape(-1, dim)
    for i in np.unique(flat_ids):
        if i < 0:
            continue
        sel = flat_ids == i
        agg = flat_d[sel].sum(0)
        if combine == "mean":
            agg = agg / sel.sum()
        expect[np.asarray(id_to_phys(np.int64(i), ns, rps))] += agg
    np.testing.assert_allclose(np.asarray(new), expect, atol=1e-4)


# ---------------------------------------------------------------------------
# User-pluggable push-combine strategies (the reference's combining senders).
# ---------------------------------------------------------------------------

def test_push_combine_strategies_through_trainer(devices8):
    """"max" and a user-supplied callable combine run through the FULL
    Trainer path (shard_map + scan + collectives) and match a numpy oracle
    applied per step over the global batch."""
    import jax.numpy as jnp

    from fps_tpu.core.api import ServerLogic, StepOutput, WorkerLogic
    from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of
    from fps_tpu.core.ingest import epoch_chunks

    class Pusher(WorkerLogic):
        def pull_ids(self, batch):
            return {"t": batch["id"].astype(jnp.int32)}

        def step(self, batch, pulled, local_state, key):
            ids = jnp.where(batch["weight"] > 0,
                            batch["id"].astype(jnp.int32), -1)
            deltas = batch["val"][:, None].astype(jnp.float32)
            return StepOutput(pushes={"t": (ids, deltas)},
                              local_state=local_state,
                              out={"n": jnp.sum(batch["weight"])})

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)
    R = 23
    rng = np.random.default_rng(4)
    n = 768
    data = {
        "id": rng.integers(0, R, n).astype(np.int32),  # heavy duplication
        "val": rng.normal(0, 1, n).astype(np.float32),
    }

    def clipped_mean(summed, counts):
        # custom strategy: count-normalized step, clipped to [-0.5, 0.5]
        return jnp.clip(summed / jnp.maximum(counts, 1.0)[:, None],
                        -0.5, 0.5)

    def np_combine(mode, vals):
        if mode == "max":
            return vals.max()
        return np.clip(vals.mean(), -0.5, 0.5)

    for mode, combine in [("max", "max"), ("clip", clipped_mean)]:
        store = ParamStore(mesh, [TableSpec("t", R, 1).zeros_init()])
        trainer = Trainer(mesh, store, Pusher(),
                          server_logic=ServerLogic(combine=combine),
                          config=TrainerConfig(donate=False))
        tables, ls = trainer.init_state(jax.random.key(0))
        chunks = list(epoch_chunks(data, num_workers=W, local_batch=16,
                                   steps_per_chunk=4, seed=7))
        # Oracle: per global step, fold each id's pushes with the strategy,
        # then add (the default apply).
        want = np.zeros(R, np.float64)
        for c in chunks:
            ids_c = np.asarray(c["id"]).reshape(-1, W * 16)
            val_c = np.asarray(c["val"]).reshape(-1, W * 16)
            wt_c = np.asarray(c["weight"]).reshape(-1, W * 16)
            for t in range(ids_c.shape[0]):
                m = wt_c[t] > 0
                for i in np.unique(ids_c[t][m]):
                    vals = val_c[t][m][ids_c[t][m] == i]
                    want[i] += np_combine(mode, vals.astype(np.float64))
        tables, ls, _ = trainer.fit_stream(tables, ls, iter(chunks),
                                           jax.random.key(1))
        got = store.dump_model("t")[1][:, 0]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5,
                                   err_msg=mode)


def test_push_combine_min_and_validation(devices8):
    """"min" fold matches its oracle; unknown modes raise at trace time."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from fps_tpu.core.store import push
    from fps_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    R = 13
    rng = np.random.default_rng(5)
    B = 32  # per worker
    ids = rng.integers(-1, R, (8, B)).astype(np.int32)  # some dropped
    deltas = rng.normal(0, 1, (8, B, 2)).astype(np.float32)

    store = ParamStore(mesh, [TableSpec("t", R, 2).zeros_init()])
    tables = store.init(jax.random.key(0))

    def dev(tab, i, d):
        return push(tab, i, d, num_shards=4, combine="min")

    f = jax.jit(jax.shard_map(
        dev, mesh=mesh,
        in_specs=(P(SHARD_AXIS, None), P((DATA_AXIS, SHARD_AXIS)),
                  P((DATA_AXIS, SHARD_AXIS))),
        out_specs=P(SHARD_AXIS, None), check_vma=False,
    ))
    got = np.asarray(f(tables["t"], jnp.asarray(ids.reshape(-1)),
                       jnp.asarray(deltas.reshape(-1, 2))))
    want = np.zeros((R, 2))
    flat_i, flat_d = ids.reshape(-1), deltas.reshape(-1, 2)
    for i in range(R):
        m = flat_i == i
        if m.any():
            want[i] = flat_d[m].min(axis=0)
    # physical rows: owner-major cyclic over 4 shards
    from fps_tpu.core.store import id_to_phys, rows_per_shard
    rps = rows_per_shard(R, 4)
    phys = np.asarray(id_to_phys(np.arange(R), 4, rps))
    np.testing.assert_allclose(got[phys], want, rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="combine"):
        jax.shard_map(
            lambda t, i, d: push(t, i, d, num_shards=4, combine="median"),
            mesh=mesh,
            in_specs=(P(SHARD_AXIS, None), P((DATA_AXIS, SHARD_AXIS)),
                      P((DATA_AXIS, SHARD_AXIS))),
            out_specs=P(SHARD_AXIS, None), check_vma=False,
        )(tables["t"], jnp.asarray(ids.reshape(-1)),
          jnp.asarray(deltas.reshape(-1, 2)))


def test_push_combine_mean_float64_precision(devices8):
    """A float64 table must fold duplicate pushes in float64: deltas that
    differ only below f32 precision (2^-40) must survive a mean-combine.
    Regression for the hard-coded f32 accumulator (round-2 advice)."""
    import contextlib

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from fps_tpu.core.store import id_to_phys, push, rows_per_shard
    from fps_tpu.parallel.mesh import DATA_AXIS, SHARD_AXIS

    @contextlib.contextmanager
    def x64():
        old = jax.config.jax_enable_x64
        jax.config.update("jax_enable_x64", True)
        try:
            yield
        finally:
            jax.config.update("jax_enable_x64", old)

    with x64():
        mesh = make_ps_mesh(num_shards=2, num_data=1, devices=devices8[:2])
        R = 4
        eps = 2.0 ** -40  # representable in f64, vanishes in f32 (1+eps==1)
        ids = np.array([1, 1, 1, 1], np.int32)
        deltas = np.array(
            [[1.0], [1.0 + eps], [1.0 + 2 * eps], [1.0 + 3 * eps]],
            np.float64,
        )
        store = ParamStore(
            mesh, [TableSpec("t", R, 1, dtype=jnp.float64).zeros_init()]
        )
        tables = store.init(jax.random.key(0))

        f = jax.jit(jax.shard_map(
            lambda t, i, d: push(t, i, d, num_shards=2, combine="mean"),
            mesh=mesh,
            in_specs=(P(SHARD_AXIS, None), P((DATA_AXIS, SHARD_AXIS)),
                      P((DATA_AXIS, SHARD_AXIS))),
            out_specs=P(SHARD_AXIS, None), check_vma=False,
        ))
        got = np.asarray(f(tables["t"], jnp.asarray(ids),
                           jnp.asarray(deltas)))
        assert got.dtype == np.float64
        rps = rows_per_shard(R, 2)
        phys = int(np.asarray(id_to_phys(np.array([1]), 2, rps))[0])
        want = 1.0 + 1.5 * eps  # exact f64 mean of the four deltas
        # An f32 accumulator would return exactly 1.0 here.
        assert got[phys, 0] == pytest.approx(want, abs=eps / 8)
        assert got[phys, 0] != 1.0

        # Extremum fold sentinel must sit beyond the ACCUMULATOR dtype's
        # range: an f32-range fill (-3e38) would swallow an f64 delta of
        # -1e39 (max(-3e38, -1e39) = -3e38 — wrong value committed).
        g = jax.jit(jax.shard_map(
            lambda t, i, d: push(t, i, d, num_shards=2, combine="max"),
            mesh=mesh,
            in_specs=(P(SHARD_AXIS, None), P((DATA_AXIS, SHARD_AXIS)),
                      P((DATA_AXIS, SHARD_AXIS))),
            out_specs=P(SHARD_AXIS, None), check_vma=False,
        ))
        big = np.array([[-1.0e39], [-2.0e39], [0.0], [0.0]], np.float64)
        ids2 = np.array([1, 1, -1, -1], np.int32)  # two dropped slots
        got2 = np.asarray(g(tables["t"], jnp.asarray(ids2),
                            jnp.asarray(big)))
        assert got2[phys, 0] == pytest.approx(-1.0e39, rel=1e-12)


def test_server_logic_swap_recompiles(devices8):
    """Swapping trainer.server_logic after a compile must MISS the compile
    cache (combine is baked into the program as a constant): the next
    chunk must fold with the new strategy, not the shadowed old one."""
    import jax.numpy as jnp

    from fps_tpu.core.api import ServerLogic, StepOutput, WorkerLogic
    from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of
    from fps_tpu.core.ingest import epoch_chunks

    class Pusher(WorkerLogic):
        def pull_ids(self, batch):
            return {"t": batch["id"].astype(jnp.int32)}

        def step(self, batch, pulled, local_state, key):
            ids = jnp.where(batch["weight"] > 0,
                            batch["id"].astype(jnp.int32), -1)
            return StepOutput(pushes={"t": (ids, batch["val"][:, None])},
                              local_state=local_state, out={})

    mesh = make_ps_mesh(num_shards=4, num_data=2, devices=devices8[:8])
    W = num_workers_of(mesh)
    rng = np.random.default_rng(0)
    n = 128
    data = {"id": rng.integers(0, 7, n).astype(np.int32),
            "val": rng.normal(0, 1, n).astype(np.float32)}
    chunk = next(epoch_chunks(data, num_workers=W, local_batch=16,
                              steps_per_chunk=1, seed=3))

    def fold(combine):
        store = ParamStore(mesh, [TableSpec("t", 7, 1).zeros_init()])
        tr = Trainer(mesh, store, Pusher(),
                     server_logic=ServerLogic(combine=combine),
                     config=TrainerConfig(donate=False))
        t, ls = tr.init_state(jax.random.key(0))
        return tr, store, t, ls

    tr, store, t, ls = fold("sum")
    t, ls, _ = tr.run_chunk(t, ls, chunk, jax.random.key(1))
    got_sum = store.dump_model("t")[1].copy()

    # Swap the logic on the SAME trainer; rerun the same chunk on fresh
    # state. Without server_logic in the cache key this silently reuses
    # the sum program.
    from fps_tpu.core.api import ServerLogic as SL
    tr.server_logic = {"t": SL(combine="mean")}
    t2, ls2 = tr.init_state(jax.random.key(0))
    t2, ls2, _ = tr.run_chunk(t2, ls2, chunk, jax.random.key(1))
    got_swapped = store.dump_model("t")[1]

    # Oracle: a trainer built with mean from the start.
    tr3, store3, t3, ls3 = fold("mean")
    t3, ls3, _ = tr3.run_chunk(t3, ls3, chunk, jax.random.key(1))
    got_mean = store3.dump_model("t")[1]

    np.testing.assert_array_equal(got_swapped, got_mean)
    assert not np.array_equal(got_sum, got_mean)  # the swap matters


# ---------------------------------------------------------------------------
# Dense collective route (replicate-on-read / dense-reduce-on-write): the
# small-table path where per-worker row transactions are O(B) instead of
# the gathered route's O(W*B) per shard. Same results, different comms.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (8, 1)])
def test_pull_dense_matches_gathered(devices8, mesh_shape):
    mesh = make_ps_mesh(num_shards=mesh_shape[1], num_data=mesh_shape[0])
    S = mesh_shape[1]
    num_ids, dim, B = 103, 7, 16
    table, rps = reference_table(num_ids, dim, S)
    table_dev = jax.device_put(
        jnp.asarray(table), NamedSharding(mesh, P(SHARD_AXIS, None))
    )
    W = mesh_shape[0] * mesh_shape[1]
    rng = np.random.default_rng(3)
    # include -1 drop slots: both routes must read them as zero rows
    ids = rng.integers(0, num_ids, (W * B,)).astype(np.int32)
    ids[:: 7] = -1
    ids_dev = jax.device_put(
        jnp.asarray(ids), NamedSharding(mesh, P((DATA_AXIS, SHARD_AXIS)))
    )

    def run(dense):
        return jax.jit(
            jax.shard_map(
                lambda t, i: pull(t, i, num_shards=S, dense=dense),
                mesh=mesh,
                in_specs=(P(SHARD_AXIS, None), P((DATA_AXIS, SHARD_AXIS))),
                out_specs=P((DATA_AXIS, SHARD_AXIS)),
                check_vma=False,
            )
        )(table_dev, ids_dev)

    expected = np.where(
        (ids >= 0)[:, None],
        (ids[:, None] * 10.0 + np.arange(dim)[None, :]),
        0.0,
    ).astype(np.float32)
    np.testing.assert_allclose(np.asarray(run(True)), expected, rtol=1e-6)
    # both routes read -1 slots as zero rows (gather_rows drop contract)
    np.testing.assert_allclose(np.asarray(run(False)), expected, rtol=1e-6)


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (8, 1)])
def test_push_dense_matches_gathered(devices8, mesh_shape):
    mesh = make_ps_mesh(num_shards=mesh_shape[1], num_data=mesh_shape[0])
    D, S = mesh_shape
    W = D * S
    num_ids, dim, B = 50, 4, 12
    rps = rows_per_shard(num_ids, S)
    table = np.zeros((rps * S, dim), np.float32)
    table_dev = jax.device_put(
        jnp.asarray(table), NamedSharding(mesh, P(SHARD_AXIS, None))
    )
    rng = np.random.default_rng(4)
    ids = rng.integers(0, num_ids, (W * B,)).astype(np.int32)
    ids[::5] = -1  # dropped pushes
    deltas = rng.normal(0, 1, (W * B, dim)).astype(np.float32)

    def run(dense):
        return jax.jit(
            jax.shard_map(
                lambda t, i, d: push(
                    t, i, d, num_shards=S,
                    data_axis=DATA_AXIS if D > 1 else None,
                    dense=dense,
                ),
                mesh=mesh,
                in_specs=(
                    P(SHARD_AXIS, None),
                    P((DATA_AXIS, SHARD_AXIS)),
                    P((DATA_AXIS, SHARD_AXIS), None),
                ),
                out_specs=P(SHARD_AXIS, None),
                check_vma=False,
            )
        )(table_dev, jnp.asarray(ids), jnp.asarray(deltas))

    expected = np.zeros((rps * S, dim), np.float32)
    keep = ids >= 0
    phys = np.asarray(id_to_phys(ids[keep], S, rps))
    np.add.at(expected, phys, deltas[keep])
    np.testing.assert_allclose(np.asarray(run(True)), expected,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(run(False)), expected,
                               rtol=1e-5, atol=1e-5)


def test_dense_route_trains_pa_equivalently(devices8):
    """End-to-end: a PA run with forced dense collectives matches the
    gathered route to f32 reassociation tolerance, on a mesh with both a
    data axis and a shard axis."""
    import dataclasses as _dc

    import importlib

    # the models package re-exports a same-named factory FUNCTION that
    # shadows the submodule attribute `import ... as` resolves through
    pa_mod = importlib.import_module("fps_tpu.models.passive_aggressive")
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of
    from fps_tpu.utils.datasets import synthetic_sparse_classification

    mesh = make_ps_mesh(num_shards=4, num_data=2)
    W = num_workers_of(mesh)
    data = synthetic_sparse_classification(W * 64 * 4, 300, 10, seed=6)

    def run(dense):
        cfg = pa_mod.PAConfig(num_features=300, variant="PA-I", C=1.0)
        store = pa_mod.make_store(mesh, cfg)
        store.specs[pa_mod.WEIGHT_TABLE] = _dc.replace(
            store.specs[pa_mod.WEIGHT_TABLE], dense_collectives=dense
        )
        trainer = Trainer(mesh, store, pa_mod.PassiveAggressiveWorker(cfg),
                          config=TrainerConfig(donate=False))
        tables, ls = trainer.init_state(jax.random.key(0))
        ds = DeviceDataset(mesh, data)
        plan = DeviceEpochPlan(ds, num_workers=W, local_batch=64, seed=2)
        tables, ls, m = trainer.run_indexed(tables, ls, plan,
                                            jax.random.key(1), epochs=2)
        return np.asarray(store.dump_model(pa_mod.WEIGHT_TABLE)[1]), m

    w_dense, m_dense = run(True)
    w_gathered, m_gathered = run(False)
    assert np.abs(w_dense).max() > 0  # it actually trained
    np.testing.assert_allclose(w_dense, w_gathered, rtol=2e-4, atol=1e-6)
