"""End-to-end online MF: convergence, determinism, sync vs SSP.

Mirrors the reference's algorithm tests (SURVEY.md §4): stream a small
dataset through the full pipeline and assert convergence-style properties,
not exact values — plus a determinism test the asynchronous reference could
never have.
"""

import jax
import numpy as np
import pytest

from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.ingest import epoch_chunks, multi_epoch_chunks
from fps_tpu.models.matrix_factorization import (
    MFConfig,
    online_mf,
    predict_host,
    rmse,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import synthetic_ratings, train_test_split

NU, NI, NR, RANK = 96, 64, 6000, 4


def run_mf(mesh, sync_every=None, epochs=3, seed=3):
    cfg = MFConfig(
        num_users=NU, num_items=NI, rank=RANK, learning_rate=0.08, reg=0.005
    )
    trainer, store = online_mf(mesh, cfg, sync_every=sync_every)
    data = synthetic_ratings(NU, NI, NR, rank=3, noise=0.05, seed=seed)
    train, test = train_test_split(data)

    tables, local_state = trainer.init_state(jax.random.key(0))
    W = num_workers_of(mesh)
    chunks = multi_epoch_chunks(
        train,
        epochs,
        num_workers=W,
        local_batch=32,
        steps_per_chunk=8,
        route_key="user",
        sync_every=sync_every,
        seed=11,
    )
    tables, local_state, metrics = trainer.fit_stream(
        tables, local_state, chunks, jax.random.key(1)
    )

    se = np.concatenate([m["se"] for m in metrics])
    n = np.concatenate([m["n"] for m in metrics])
    train_rmse_curve = np.sqrt(se.sum() / n.sum())

    pred = predict_host(
        store, np.asarray(local_state), W, test["user"], test["item"]
    )
    return float(train_rmse_curve), rmse(pred, test["rating"]), n


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4)])
def test_mf_converges_sync(devices8, mesh_shape):
    mesh = make_ps_mesh(num_shards=mesh_shape[1], num_data=mesh_shape[0])
    _, test_rmse, n = run_mf(mesh)
    # Planted rank-3 structure with sigma=0.05 noise; untrained predicts ~0
    # giving RMSE near the rating std (~0.6). Learning must beat 0.35.
    assert test_rmse < 0.35, f"test RMSE {test_rmse}"
    # Every real example was processed exactly once per epoch.
    assert int(np.sum(n)) == 3 * int(0.9 * NR)


def test_mf_converges_ssp(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    _, test_rmse, _ = run_mf(mesh, sync_every=4)
    assert test_rmse < 0.4, f"SSP test RMSE {test_rmse}"


def test_mf_sync_deterministic(devices8):
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    r1 = run_mf(mesh, epochs=1)
    r2 = run_mf(mesh, epochs=1)
    assert r1[0] == r2[0]
    assert r1[1] == r2[1]
