"""Checkpoint/export/warm-start tests (SURVEY.md §5 checkpoint row).

The reference's only persistence is the close()-time model stream plus
transformWithModelLoad warm start; these tests cover that parity surface and
the periodic-snapshot resume the rebuild adds on top.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jaxmods():
    import jax

    from fps_tpu.core import checkpoint as ck
    from fps_tpu.core.driver import Trainer, num_workers_of
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    return dict(
        jax=jax, ck=ck, Trainer=Trainer, num_workers_of=num_workers_of,
        epoch_chunks=epoch_chunks, MFConfig=MFConfig, online_mf=online_mf,
        make_ps_mesh=make_ps_mesh, synthetic_ratings=synthetic_ratings,
    )


def _mf(jaxmods, num_shards, num_data=1, num_users=32, num_items=24, rank=4):
    jax = jaxmods["jax"]
    mesh = jaxmods["make_ps_mesh"](
        num_shards=num_shards, num_data=num_data,
        devices=jax.devices()[: num_shards * num_data],
    )
    cfg = jaxmods["MFConfig"](num_users=num_users, num_items=num_items, rank=rank)
    trainer, store = jaxmods["online_mf"](mesh, cfg, donate=False)
    return mesh, cfg, trainer, store


def _chunks(jaxmods, data, W, seed=0):
    return list(
        jaxmods["epoch_chunks"](
            data, num_workers=W, local_batch=8, steps_per_chunk=2,
            route_key="user", seed=seed,
        )
    )


def test_export_load_roundtrip(tmp_path, jaxmods, devices8):
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, cfg, trainer, store = _mf(jaxmods, num_shards=4)
    store.init(jax.random.key(0))
    path = str(tmp_path / "model.npz")
    ck.export_model(store, path)

    saved = ck.load_saved_model(path)
    assert set(saved) == {"item_factors"}
    assert saved["item_factors"].shape == (cfg.num_items, cfg.rank)
    _, values = store.dump_model("item_factors")
    np.testing.assert_array_equal(saved["item_factors"], values)


def test_warm_start_across_shard_counts(tmp_path, jaxmods, devices8):
    """A model exported from a 4-shard store loads into a 2-shard store."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, _, store4 = _mf(jaxmods, num_shards=4)
    store4.init(jax.random.key(7))
    path = str(tmp_path / "model.npz")
    ck.export_model(store4, path)

    _, _, _, store2 = _mf(jaxmods, num_shards=2)
    store2.init(jax.random.key(99))  # different init — must be overwritten
    ck.load_model(store2, path, strict=True)

    _, v4 = store4.dump_model("item_factors")
    _, v2 = store2.dump_model("item_factors")
    np.testing.assert_allclose(v2, v4, rtol=1e-6)


def test_load_rows_subset(jaxmods, devices8):
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, cfg, _, store = _mf(jaxmods, num_shards=4)
    store.init(jax.random.key(0))
    _, before = store.dump_model("item_factors")

    ids = np.array([0, 5, 13])
    new = np.full((3, cfg.rank), 42.0, np.float32)
    ck.load_rows(store, "item_factors", ids, new)

    _, after = store.dump_model("item_factors")
    np.testing.assert_array_equal(after[ids], new)
    mask = np.ones(cfg.num_items, bool)
    mask[ids] = False
    np.testing.assert_array_equal(after[mask], before[mask])


def test_load_rows_validates(jaxmods, devices8):
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, cfg, _, store = _mf(jaxmods, num_shards=2)
    store.init(jax.random.key(0))
    with pytest.raises(ValueError):
        ck.load_rows(store, "item_factors", np.array([cfg.num_items]),
                     np.zeros((1, cfg.rank), np.float32))
    with pytest.raises(ValueError):
        ck.load_model(store, {"item_factors": np.zeros((3, 3), np.float32)})


def test_checkpoint_resume_bit_exact(tmp_path, jaxmods, devices8):
    """Train 4 chunks straight vs. 2 chunks → snapshot → restore → 2 chunks:
    identical tables and local state (sync mode is deterministic)."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    W = 4

    data = jaxmods["synthetic_ratings"](32, 24, 4 * W * 8 * 2, seed=3)
    chunks = _chunks(jaxmods, data, W)[:4]
    assert len(chunks) == 4
    key = jax.random.key(5)

    # Straight-through run.
    _, _, trainerA, storeA = _mf(jaxmods, num_shards=4)
    tabA, lsA = trainerA.init_state(jax.random.key(1))
    for i, c in enumerate(chunks):
        tabA, lsA, _ = trainerA.run_chunk(tabA, lsA, c, jax.random.fold_in(key, i))

    # Interrupted run with snapshot at chunk 2.
    _, _, trainerB, storeB = _mf(jaxmods, num_shards=4)
    tabB, lsB = trainerB.init_state(jax.random.key(1))
    for i, c in enumerate(chunks[:2]):
        tabB, lsB, _ = trainerB.run_chunk(tabB, lsB, c, jax.random.fold_in(key, i))
    ckpt = ck.Checkpointer(str(tmp_path / "ckpts"))
    ckpt.save(2, storeB, lsB)

    # Fresh process analog: new trainer/store, restore, continue.
    _, _, trainerC, storeC = _mf(jaxmods, num_shards=4)
    tabC, lsC = trainerC.init_state(jax.random.key(1234))  # different init
    storeC.tables = tabC
    tabC, lsC, step = ckpt.restore(storeC, lsC)
    assert step == 2
    for i, c in enumerate(chunks[2:], start=2):
        tabC, lsC, _ = trainerC.run_chunk(tabC, lsC, c, jax.random.fold_in(key, i))

    for name in storeA.specs:
        _, vA = storeA.dump_model(name)
        _, vC = storeC.dump_model(name)
        np.testing.assert_array_equal(vA, vC)
    np.testing.assert_array_equal(np.asarray(lsA), np.asarray(lsC))


def test_checkpointer_gc_and_latest(tmp_path, jaxmods, devices8):
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, _, store = _mf(jaxmods, num_shards=2)
    store.init(jax.random.key(0))
    ckpt = ck.Checkpointer(str(tmp_path / "c"), keep=2)
    for s in (1, 2, 3):
        ckpt.save(s, store, None)
    assert ckpt.steps() == [2, 3]
    assert ckpt.latest_step() == 3
    tables, ls, step = ckpt.restore(store, None)
    assert step == 3 and ls is None


def test_fit_stream_resume_matches_straight_run(tmp_path, jaxmods, devices8):
    """fit_stream with start_step continues the PRNG stream and snapshot
    numbering: interrupted+resumed == straight-through, bit for bit."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    W = 4
    data = jaxmods["synthetic_ratings"](32, 24, 4 * W * 8 * 2, seed=3)
    chunks = _chunks(jaxmods, data, W)[:4]
    key = jax.random.key(5)

    _, _, trainerA, storeA = _mf(jaxmods, num_shards=4)
    tabA, lsA = trainerA.init_state(jax.random.key(1))
    tabA, lsA, _ = trainerA.fit_stream(tabA, lsA, chunks, key)

    _, _, trainerB, storeB = _mf(jaxmods, num_shards=4)
    tabB, lsB = trainerB.init_state(jax.random.key(1))
    ckpt = ck.Checkpointer(str(tmp_path / "c"))
    trainerB.fit_stream(tabB, lsB, chunks[:2], key,
                        checkpointer=ckpt, checkpoint_every=2)

    _, _, trainerC, storeC = _mf(jaxmods, num_shards=4)
    tabC, lsC = trainerC.init_state(jax.random.key(77))
    storeC.tables = tabC
    # Trainer-level restore: fit_stream saved the logic's EXPORTED (logical
    # user order) local state, which import_local_state re-lays-out.
    tabC, lsC, step = trainerC.restore_checkpoint(ckpt, lsC)
    assert step == 2
    trainerC.fit_stream(tabC, lsC, chunks[2:], key,
                        checkpointer=ckpt, checkpoint_every=2,
                        start_step=step)
    assert ckpt.latest_step() == 4

    for name in storeA.specs:
        np.testing.assert_array_equal(
            storeA.dump_model(name)[1], storeC.dump_model(name)[1]
        )


def test_fit_stream_checkpoints(tmp_path, jaxmods, devices8):
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    W = 4
    data = jaxmods["synthetic_ratings"](32, 24, 4 * W * 8 * 2, seed=3)
    chunks = _chunks(jaxmods, data, W)
    _, _, trainer, store = _mf(jaxmods, num_shards=4)
    tables, ls = trainer.init_state(jax.random.key(1))
    ckpt = ck.Checkpointer(str(tmp_path / "c"))
    trainer.fit_stream(tables, ls, chunks, jax.random.key(2),
                       checkpointer=ckpt, checkpoint_every=2)
    assert ckpt.latest_step() == len(chunks)


def test_elastic_worker_count_restore(tmp_path, jaxmods, devices8):
    """A checkpoint taken on an 8-worker mesh resumes on a 4-worker mesh:
    tables reshard (as before) AND the MF user factors re-lay-out through
    the logic's export/import (logical user order), closing the round-1
    worker-count pinning. The restored model must predict identically."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    from fps_tpu.models.matrix_factorization import predict_host
    from fps_tpu.parallel.mesh import make_ps_mesh

    data = jaxmods["synthetic_ratings"](32, 24, 4 * 8 * 8, seed=3)
    chunks8 = _chunks(jaxmods, data, 8)[:2]

    # Train at W=8 (1x8 mesh) and snapshot through the trainer path.
    _, cfgA, trainerA, storeA = _mf(jaxmods, num_shards=8)
    tabA, lsA = trainerA.init_state(jax.random.key(1))
    tabA, lsA, _ = trainerA.fit_stream(
        tabA, lsA, chunks8, jax.random.key(5),
        checkpointer=ck.Checkpointer(str(tmp_path / "el")),
        checkpoint_every=2,
    )
    ckpt = ck.Checkpointer(str(tmp_path / "el"))
    predA = predict_host(storeA, np.asarray(lsA), 8, data["user"],
                         data["item"])

    # Resume at W=4 (1x4 mesh over half the devices).
    mesh4 = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    from fps_tpu.models.matrix_factorization import online_mf

    trainerB, storeB = online_mf(mesh4, cfgA)
    tabB, lsB = trainerB.init_state(jax.random.key(999))  # different init
    storeB.tables = tabB
    tabB, lsB, step = trainerB.restore_checkpoint(ckpt, lsB)
    assert step == 2
    predB = predict_host(storeB, np.asarray(lsB), 4, data["user"],
                         data["item"])
    np.testing.assert_allclose(predA, predB, rtol=1e-6, atol=1e-6)

    # And training continues from the restored state without error.
    chunks4 = _chunks(jaxmods, data, 4)[:1]
    tabB, lsB, m = trainerB.fit_stream(tabB, lsB, chunks4, jax.random.key(6))
    assert float(np.asarray(m[0]["n"]).sum()) > 0


def test_raw_restore_of_exported_snapshot_fails_loudly(tmp_path, jaxmods,
                                                       devices8):
    """Trainer-path snapshots tag local state as 'exported'; the raw
    Checkpointer.restore must refuse them rather than silently permuting
    state when shapes coincide (nu divisible by W makes logical and device
    layouts the same shape)."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    data = jaxmods["synthetic_ratings"](32, 24, 4 * 4 * 8, seed=3)
    chunks = _chunks(jaxmods, data, 4)[:2]
    _, _, trainer, store = _mf(jaxmods, num_shards=4)
    tab, ls = trainer.init_state(jax.random.key(1))
    ckpt = ck.Checkpointer(str(tmp_path / "x"))
    trainer.fit_stream(tab, ls, chunks, jax.random.key(5),
                       checkpointer=ckpt, checkpoint_every=2)
    assert ckpt.local_state_format(2) == "exported"
    with pytest.raises(ValueError, match="EXPORTED"):
        ckpt.restore(store, ls)


# ---------------------------------------------------------------------------
# Snapshot integrity + fallback restore (the keep>=2 redundancy contract).
# ---------------------------------------------------------------------------

def _two_snapshots(tmp_path, jaxmods, *, keep=2):
    """Train 2 chunks, snapshotting after each: returns (ckpt, store,
    trainer, per-step host dumps) so tests can corrupt the newest and
    check the fallback lands exactly on the older state."""
    jax, ck = jaxmods["jax"], jaxmods["ck"]
    W = 4
    data = jaxmods["synthetic_ratings"](32, 24, 4 * W * 8 * 2, seed=3)
    chunks = _chunks(jaxmods, data, W)[:2]
    _, _, trainer, store = _mf(jaxmods, num_shards=4)
    tab, ls = trainer.init_state(jax.random.key(1))
    ckpt = ck.Checkpointer(str(tmp_path / "c"), keep=keep)
    key = jax.random.key(5)
    dumps = {}
    for i, c in enumerate(chunks):
        tab, ls, _ = trainer.run_chunk(tab, ls, c, jax.random.fold_in(key, i))
        ckpt.save(i + 1, store, ls)
        dumps[i + 1] = store.dump_model("item_factors")[1].copy()
    return ckpt, store, trainer, ls, dumps


@pytest.mark.parametrize("corruption", ["truncate", "bitflip"])
def test_corrupt_newest_snapshot_falls_back(tmp_path, jaxmods, devices8,
                                            corruption):
    """keep=2 is a REAL redundancy contract: truncating or bit-flipping the
    newest snapshot makes the raw restore path recover the previous one,
    bit-for-bit, quarantining the bad file out of the rotation."""
    from fps_tpu.testing import chaos

    ckpt, store, _, ls, dumps = _two_snapshots(tmp_path, jaxmods)
    assert ckpt.steps() == [1, 2]
    assert ckpt.verify_snapshot(2) and ckpt.latest_valid_step() == 2

    kw = {"seed": 7} if corruption == "bitflip" else {}
    bad = chaos.corrupt_latest_snapshot(ckpt.dir, corruption, **kw)
    assert ckpt.latest_valid_step() == 1
    assert not ckpt.verify_snapshot(2)

    tables, ls2, step = ckpt.restore(store, ls)
    assert step == 1
    np.testing.assert_array_equal(store.dump_model("item_factors")[1],
                                  dumps[1])
    # The corrupt file left the rotation but survives for forensics.
    assert ckpt.steps() == [1]
    assert not np.any([p.endswith("ckpt_%012d.npz" % 2)
                       for p in chaos.snapshot_paths(ckpt.dir)])
    import os
    assert os.path.exists(bad + ".corrupt")


def test_corrupt_newest_trainer_restore_falls_back(tmp_path, jaxmods,
                                                   devices8):
    """Trainer.restore_checkpoint (the exported-local-state path) rides the
    same verified read: corruption of the newest snapshot falls back too."""
    from fps_tpu.testing import chaos

    jax, ck = jaxmods["jax"], jaxmods["ck"]
    W = 4
    data = jaxmods["synthetic_ratings"](32, 24, 4 * W * 8 * 2, seed=3)
    chunks = _chunks(jaxmods, data, W)[:4]
    _, _, trainer, store = _mf(jaxmods, num_shards=4)
    tab, ls = trainer.init_state(jax.random.key(1))
    ckpt = ck.Checkpointer(str(tmp_path / "c"), keep=2)
    trainer.fit_stream(tab, ls, chunks, jax.random.key(5),
                       checkpointer=ckpt, checkpoint_every=2)
    assert ckpt.steps() == [2, 4]

    chaos.corrupt_latest_snapshot(ckpt.dir, "bitflip", seed=3)

    _, _, trainerC, storeC = _mf(jaxmods, num_shards=4)
    tabC, lsC = trainerC.init_state(jax.random.key(77))
    storeC.tables = tabC
    tabC, lsC, step = trainerC.restore_checkpoint(ckpt, lsC)
    assert step == 2


def test_explicit_step_corruption_raises(tmp_path, jaxmods, devices8):
    """Pinning step= must surface SnapshotCorruptionError, not silently
    answer with a different snapshot."""
    from fps_tpu.core.resilience import SnapshotCorruptionError
    from fps_tpu.testing import chaos

    ckpt, store, _, ls, _ = _two_snapshots(tmp_path, jaxmods)
    chaos.corrupt_latest_snapshot(ckpt.dir, "truncate")
    with pytest.raises(SnapshotCorruptionError):
        ckpt.read_snapshot(2)
    # Explicit-step failure must NOT quarantine (the caller may want the
    # bytes for forensics).
    assert 2 in ckpt.steps()


def test_metadata_accessors_share_fallback(tmp_path, jaxmods, devices8):
    """raw_local_state/local_state_format ride the verified read: with the
    newest snapshot corrupted they fall back like restore does, instead of
    leaking a raw zipfile error."""
    from fps_tpu.testing import chaos

    ckpt, _, _, ls, _ = _two_snapshots(tmp_path, jaxmods)
    chaos.corrupt_latest_snapshot(ckpt.dir, "truncate")
    assert ckpt.local_state_format() == "raw"  # fell back to step 1
    assert len(ckpt.raw_local_state()) == len(
        __import__("jax").tree.flatten(ls)[0]
    )
    assert ckpt.steps() == [1]


def test_stale_tmp_files_swept_on_init(tmp_path, jaxmods, devices8):
    """Crash leftovers (old tmp files) are swept; a FRESH tmp file — a
    concurrent writer's in-flight save — is left alone."""
    import os
    import time

    ck = jaxmods["ck"]
    d = tmp_path / "c"
    d.mkdir()
    stale, live = d / "abc123.tmp.npz", d / "def456.tmp.npz"
    for f in (stale, live):
        f.write_bytes(b"PK\x03\x04partial")
    past = time.time() - 2 * ck.Checkpointer.TMP_SWEEP_AGE_S
    os.utime(stale, (past, past))
    ck.Checkpointer(str(d), keep=2)
    assert not stale.exists()
    assert live.exists()


def _run_kill_worker(mode, ckdir, out):
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "_kill_resume_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = root
    return subprocess.run(
        [sys.executable, worker, mode, ckdir, out],
        env=env, cwd=root, capture_output=True, text=True, timeout=300,
    )


@pytest.mark.slow
def test_corrupt_snapshot_fresh_process_resume_matches_straight(tmp_path):
    """END-TO-END extension of the kill-resume contract: after the SIGKILL,
    the newest surviving snapshot is bit-flipped on disk — a fresh process
    must fall back to the older one and STILL reproduce the straight run
    bit-for-bit (epochs 1..4 replayed from step 1)."""
    import glob
    import signal

    from fps_tpu.testing import chaos

    ckdir = str(tmp_path / "roll")
    straight = str(tmp_path / "straight.npz")
    resumed = str(tmp_path / "resumed.npz")

    r = _run_kill_worker("straight", ckdir, straight)
    assert r.returncode == 0, r.stdout + r.stderr
    v = _run_kill_worker("victim", ckdir, "-")
    assert v.returncode == -signal.SIGKILL, v.stdout + v.stderr

    chaos.corrupt_latest_snapshot(ckdir, "bitflip", seed=11)

    r2 = _run_kill_worker("resume-any", ckdir, resumed)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert glob.glob(ckdir + "/*.corrupt"), "bad snapshot not quarantined"

    a, b = np.load(straight), np.load(resumed)
    np.testing.assert_array_equal(a["item_factors"], b["item_factors"])
    np.testing.assert_array_equal(a["user_factors"], b["user_factors"])


@pytest.mark.slow
def test_midwrite_crash_tmp_cleanup_and_resume(tmp_path):
    """Dying MID-checkpoint-write (partial .tmp.npz on disk, step 3 never
    lands) must not confuse recovery: the tmp leftover is swept, snapshots
    1/2 restore, and the resumed run matches the straight run."""
    import glob
    import signal

    ckdir = str(tmp_path / "roll")
    straight = str(tmp_path / "straight.npz")
    resumed = str(tmp_path / "resumed.npz")

    r = _run_kill_worker("straight", ckdir, straight)
    assert r.returncode == 0, r.stdout + r.stderr
    v = _run_kill_worker("victim-midwrite", ckdir, "-")
    assert v.returncode == -signal.SIGKILL, v.stdout + v.stderr

    # The torn write left its partial tmp file; snapshots 1 and 2 intact.
    torn = glob.glob(ckdir + "/*.tmp.npz")
    assert torn, "expected a torn tmp file"
    steps = sorted(int(p[-16:-4]) for p in glob.glob(ckdir + "/ckpt_*.npz"))
    assert steps == [1, 2]

    # Age the leftover past the live-writer grace window (a real resume
    # happens well after the crash; the sweep must not touch FRESH tmp
    # files, which could be a concurrent writer's in-flight save).
    import os
    import time

    from fps_tpu.core.checkpoint import Checkpointer

    past = time.time() - 2 * Checkpointer.TMP_SWEEP_AGE_S
    for p in torn:
        os.utime(p, (past, past))

    r2 = _run_kill_worker("resume-any", ckdir, resumed)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert glob.glob(ckdir + "/*.tmp.npz") == [], "tmp file not swept"

    a, b = np.load(straight), np.load(resumed)
    np.testing.assert_array_equal(a["item_factors"], b["item_factors"])
    np.testing.assert_array_equal(a["user_factors"], b["user_factors"])


@pytest.mark.slow
def test_async_writer_sigkill_midwrite_never_publishes_torn(tmp_path):
    """ISSUE 3 kill/resume contract under the ASYNC writer: SIGKILL
    landing mid-background-write (inside the writer thread's serialize,
    partial tmp on disk) publishes nothing — latest_valid_step stays
    monotone at 2 — and a fresh process resumes to the straight run's
    exact state."""
    import glob
    import os
    import signal
    import time

    from fps_tpu.core.checkpoint import Checkpointer

    ckdir = str(tmp_path / "roll")
    straight = str(tmp_path / "straight.npz")
    resumed = str(tmp_path / "resumed.npz")

    r = _run_kill_worker("straight", ckdir, straight)
    assert r.returncode == 0, r.stdout + r.stderr
    v = _run_kill_worker("victim-async-midwrite", ckdir, "-")
    assert v.returncode == -signal.SIGKILL, v.stdout + v.stderr

    # Nothing torn was ever published: steps 1/2 intact and verified,
    # step 3 only exists as tmp litter (the kill-site evidence).
    ck = Checkpointer.__new__(Checkpointer)  # skip the sweeping __init__
    ck.dir, ck.keep = ckdir, 2
    assert ck.steps() == [1, 2]
    assert ck.latest_valid_step() == 2
    assert glob.glob(ckdir + "/*.tmp.npz"), "expected the torn tmp file"

    # Age the leftover past the live-writer grace window, then resume.
    past = time.time() - 2 * Checkpointer.TMP_SWEEP_AGE_S
    for p in glob.glob(ckdir + "/*.tmp.npz"):
        os.utime(p, (past, past))
    r2 = _run_kill_worker("resume-any", ckdir, resumed)
    assert r2.returncode == 0, r2.stdout + r2.stderr

    a, b = np.load(straight), np.load(resumed)
    np.testing.assert_array_equal(a["item_factors"], b["item_factors"])
    np.testing.assert_array_equal(a["user_factors"], b["user_factors"])


@pytest.mark.slow
def test_deferred_capture_sigkill_midcapture_resumes(tmp_path):
    """ISSUE 20 crash window of the WRITER-side capture: fit_stream with
    prefetch routes saves through save_deferred over a delta chain, and
    SIGKILL lands inside the writer's step-3 device→host capture — after
    steps 1 (full) + 2 (delta) published, before step 3 touched disk.
    Nothing torn exists (the capture never reached serialize), the chain
    restores to step 2, and a fresh process resumes to the straight
    run's exact state: a kill mid-capture loses at most the boundary
    being captured, never served or recovered bytes."""
    import glob
    import os
    import signal

    from fps_tpu.core.checkpoint import Checkpointer, DeltaPolicy
    from fps_tpu.core.snapshot_format import delta_path

    ckdir = str(tmp_path / "roll")
    straight = str(tmp_path / "straight.npz")
    resumed = str(tmp_path / "resumed.npz")

    r = _run_kill_worker("straight-stream", ckdir, straight)
    assert r.returncode == 0, r.stdout + r.stderr
    v = _run_kill_worker("victim-capture-kill", ckdir, "-")
    assert v.returncode == -signal.SIGKILL, v.stdout + v.stderr

    # The chain the kill left behind: full 1 + delta 2(<-1), step 3
    # absent entirely — no tmp litter, because the capture died before
    # any serialize started.
    ck = Checkpointer(ckdir, keep=8, delta=DeltaPolicy(full_every=50))
    assert ck.steps() == [1, 2]
    assert ck.latest_valid_step() == 2
    assert os.path.exists(delta_path(ckdir, 2, 1))
    assert glob.glob(ckdir + "/*.tmp.npz") == []

    r2 = _run_kill_worker("resume-stream", ckdir, resumed)
    assert r2.returncode == 0, r2.stdout + r2.stderr

    a, b = np.load(straight), np.load(resumed)
    np.testing.assert_array_equal(a["item_factors"], b["item_factors"])
    np.testing.assert_array_equal(a["user_factors"], b["user_factors"])


def test_sigkill_and_fresh_process_resume(tmp_path):
    """END-TO-END crash recovery: a training process is SIGKILLed mid-run
    (epoch 3 trained, not yet checkpointed), and a FRESH OS process
    restores the rolling snapshot and continues — final tables AND
    worker-local state must be bit-identical to an uninterrupted run.
    Same-process restore tests can't prove the PRNG/shuffle continuity
    claims survive a real process boundary; this does."""
    import signal

    ckdir = str(tmp_path / "roll")
    straight = str(tmp_path / "straight.npz")
    resumed = str(tmp_path / "resumed.npz")

    r = _run_kill_worker("straight", ckdir, straight)
    assert r.returncode == 0, r.stdout + r.stderr

    v = _run_kill_worker("victim", ckdir, "-")
    assert v.returncode == -signal.SIGKILL, (
        f"victim should die by SIGKILL, got rc={v.returncode}:\n"
        f"{v.stdout}{v.stderr}")
    # Rolling retention (keep=2) after the kill: snapshots 1 and 2 survive,
    # epoch 3's work is lost — exactly the crash window.
    ck = __import__("fps_tpu.core.checkpoint",
                    fromlist=["Checkpointer"]).Checkpointer(ckdir, keep=2)
    assert ck.steps() == [1, 2]

    r2 = _run_kill_worker("resume", ckdir, resumed)
    assert r2.returncode == 0, r2.stdout + r2.stderr

    a, b = np.load(straight), np.load(resumed)
    np.testing.assert_array_equal(a["item_factors"], b["item_factors"])
    np.testing.assert_array_equal(a["user_factors"], b["user_factors"])


# ---------------------------------------------------------------------------
# Pod fencing (fps_tpu.supervise.pod contract at the checkpoint layer).
# ---------------------------------------------------------------------------

def test_fenced_publish_refused(tmp_path, jaxmods, devices8):
    """A writer whose fencing epoch predates the dir's pod fence must
    REFUSE to publish (StaleEpochError), leaving the snapshot trail
    untouched; a writer at-or-above the fence publishes normally, and an
    epoch-less writer is refused by any fenced dir (a pre-pod zombie
    must not leak state into a pod attempt)."""
    import pytest as _pytest

    from fps_tpu.supervise.child import StaleEpochError, write_fence

    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, _, store = _mf(jaxmods, num_shards=2)
    store.init(jax.random.key(0))
    d = str(tmp_path / "c")

    fenced = ck.Checkpointer(d, fence_epoch=2)
    fenced.save(1, store, None)
    write_fence(d, 3, 1)
    with _pytest.raises(StaleEpochError):
        fenced.save(2, store, None)
    assert fenced.steps() == [1]  # nothing published behind the fence

    ok = ck.Checkpointer(d, fence_epoch=3)
    ok.save(2, store, None)
    assert ok.steps() == [1, 2]

    epochless = ck.Checkpointer(d)
    with _pytest.raises(StaleEpochError):
        epochless.save(3, store, None)
    assert ok.steps() == [1, 2]


def test_fenced_async_writer_surfaces_on_caller(tmp_path, jaxmods, devices8):
    """The async writer hits the fence on its background thread; the
    refusal must re-raise on the caller (flush/close), chained from the
    StaleEpochError, and never publish a torn or stale snapshot."""
    import pytest as _pytest

    from fps_tpu.supervise.child import StaleEpochError, write_fence

    jax, ck = jaxmods["jax"], jaxmods["ck"]
    _, _, _, store = _mf(jaxmods, num_shards=2)
    store.init(jax.random.key(0))
    d = str(tmp_path / "a")

    ac = ck.AsyncCheckpointer(d, fence_epoch=1)
    ac.save(1, store, None)
    ac.flush()
    write_fence(d, 5, 1)
    ac.save(2, store, None)  # accepted; the WRITER will be refused
    with _pytest.raises(RuntimeError) as ei:
        ac.flush()
    cause = ei.value.__cause__
    assert isinstance(cause, StaleEpochError), cause
    assert ck.Checkpointer(d, fence_epoch=5).steps() == [1]
    ac.close()  # error re-raises ONCE (already consumed): clean close


def test_fence_epoch_from_env(monkeypatch):
    from fps_tpu.core.checkpoint import fence_epoch_from_env
    from fps_tpu.supervise.child import POD_EPOCH_ENV

    monkeypatch.delenv(POD_EPOCH_ENV, raising=False)
    assert fence_epoch_from_env() is None
    monkeypatch.setenv(POD_EPOCH_ENV, "7")
    assert fence_epoch_from_env() == 7


# ---------------------------------------------------------------------------
# Mesh-shape-independent restore: the explicit elastic re-split path.
# ---------------------------------------------------------------------------

def test_resplit_restore_bit_identical_at_w_minus_and_plus_one(
        tmp_path, jaxmods, devices8):
    """A checkpoint written at W=3 shards restores BIT-IDENTICALLY at
    W-1=2 and W+1=4 shards through the explicit re-split path: the
    restore detects the recorded mesh-shape change, emits the
    checkpoint_resplit event + counter, and asserts the re-laid-out
    tables round-trip to the snapshot's exact logical bytes — the
    invariant the pod's elastic W->W-1->W re-planning stands on."""
    import jax

    from fps_tpu import obs
    from fps_tpu.obs import events as obs_events
    from fps_tpu.obs.sinks import MemorySink

    ck = jaxmods["ck"]
    _, cfg, trainerA, storeA = _mf(jaxmods, num_shards=3)
    tabA, lsA = trainerA.init_state(jax.random.key(1))
    data = jaxmods["synthetic_ratings"](32, 24, 3 * 8 * 4, seed=3)
    chunks = _chunks(jaxmods, data, 3)[:2]
    tabA, lsA, _ = trainerA.fit_stream(
        tabA, lsA, chunks, jax.random.key(5),
        checkpointer=ck.Checkpointer(str(tmp_path / "w3")),
        checkpoint_every=2)
    want = {n: storeA.dump_model(n)[1] for n in storeA.specs}

    for shards in (2, 4):  # W-1 and W+1
        sink = MemorySink()
        rec = obs.Recorder(sinks=[sink])
        _, _, trainerB, storeB = _mf(jaxmods, num_shards=shards)
        tabB, lsB = trainerB.init_state(jax.random.key(99))
        storeB.tables = tabB
        with obs_events.default_recorder(rec):
            tabB, lsB, step = trainerB.restore_checkpoint(
                ck.Checkpointer(str(tmp_path / "w3")), lsB)
        assert step == 2
        for n, v in want.items():
            np.testing.assert_array_equal(storeB.dump_model(n)[1], v)
        events = [r for r in sink.records
                  if r.get("event") == "checkpoint_resplit"]
        assert len(events) == 1, events
        assert events[0]["from_shape"] == {"data": 1, "shard": 3}
        assert events[0]["to_shape"] == {"data": 1, "shard": shards}


def test_same_shape_restore_emits_no_resplit(tmp_path, jaxmods, devices8):
    """The re-split path (and its extra per-table round-trip dump) stays
    OFF the common same-mesh restore."""
    import jax

    from fps_tpu import obs
    from fps_tpu.obs import events as obs_events
    from fps_tpu.obs.sinks import MemorySink

    ck = jaxmods["ck"]
    _, _, _, store = _mf(jaxmods, num_shards=2)
    store.init(jax.random.key(0))
    ckpt = ck.Checkpointer(str(tmp_path / "s"))
    ckpt.save(1, store, None)
    sink = MemorySink()
    with obs_events.default_recorder(obs.Recorder(sinks=[sink])):
        ckpt.restore_tables(store)
    assert not [r for r in sink.records
                if r.get("event") == "checkpoint_resplit"]
