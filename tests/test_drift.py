"""Budget-drift detection (fps_tpu.obs.drift).

ISSUE 12 acceptance: the detector folds the LIVE data plane (the lowered
program a tiered MF run actually dispatches, weighted by its dispatch
counters) against the budgets pinned in ``AUDIT_r10.json`` — a clean run
stays quiet (gauge 1.0, zero incidents) while a seeded budget mutation
(pinned bytes halved) fires an ``analysis.budget_drift`` incident that
``tools/obs_report.py`` surfaces.
"""

import copy
import importlib.util
import json
import math
import os
import sys

import pytest

from fps_tpu import obs
from fps_tpu.obs.drift import (
    BudgetDriftDetector,
    load_pinned_budgets,
    profile_budget,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_AUDIT = os.path.join(_ROOT, "AUDIT_r10.json")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


# -- pinned-budget loading ----------------------------------------------


def test_load_pinned_budgets_from_audit_r10():
    pinned = load_pinned_budgets(_AUDIT)
    # The r10 census: every pinned program row loads with its exact
    # totals and per-kind split.
    assert {"mf", "mf_tiered", "mf_tiered_compact", "logreg",
            "w2v"} <= set(pinned)
    mt = pinned["mf_tiered"]
    assert mt["count"] == 4 and mt["bytes"] == 6144
    assert mt["per_kind"]["reduce_scatter"] == {"count": 1,
                                                "bytes": 1024}
    lr = pinned["logreg"]
    assert lr["count"] == 2 and lr["bytes"] == 3200


# -- detector unit semantics --------------------------------------------


def _pinned_one(bytes_=1000, count=2, per_kind=None):
    return {"p": {"count": count, "bytes": bytes_,
                  "per_kind": per_kind or {"all_gather":
                                           {"count": 2,
                                            "bytes": bytes_}}}}


def test_profile_budget_normalizes_shapes():
    class C:
        kind, payload_bytes = "all_gather", 512

    for profile in ([C(), C()],
                    [("all_gather", 512), ("all_gather", 512)],
                    [{"kind": "all_gather", "payload_bytes": 512}] * 2):
        b = profile_budget(profile)
        assert b == {"count": 2, "bytes": 1024,
                     "per_kind": {"all_gather": {"count": 2,
                                                 "bytes": 1024}}}


def test_detector_quiet_within_tolerance():
    det = BudgetDriftDetector(_pinned_one(), byte_rel_tol=0.05)
    det.observe("p", [("all_gather", 500), ("all_gather", 510)])
    [r] = det.evaluate(emit=False)
    assert r.ok and r.byte_ratio == pytest.approx(1.01)


def test_detector_flags_bytes_count_and_new_kind():
    det = BudgetDriftDetector(_pinned_one(), byte_rel_tol=0.05)
    det.observe("p", [("all_gather", 2000), ("all_gather", 2000),
                      ("psum", 64)])
    [r] = det.evaluate(emit=False)
    assert not r.ok
    blob = " ".join(r.reasons)
    assert "bytes" in blob and "count 3 vs pinned 2" in blob
    assert "unpinned collective kind 'psum'" in blob
    assert r.byte_ratio == pytest.approx(4064 / 1000)


def test_detector_unpinned_program_policy():
    det = BudgetDriftDetector({}, allow_unpinned=True)
    det.observe("new", [("all_gather", 64)])
    [r] = det.evaluate(emit=False)
    assert r.ok and r.byte_ratio is None
    strict = BudgetDriftDetector({}, allow_unpinned=False)
    strict.observe("new", [("all_gather", 64)])
    [r] = strict.evaluate(emit=False)
    assert not r.ok and "no pinned budget" in r.reasons[0]


def test_detector_zero_chunk_observation_never_fires():
    """chunks=0 moved no traffic: the report documents the (drifted)
    ratio but evaluate() must not turn it into an incident."""
    mem = obs.MemorySink()
    rec = obs.Recorder(sinks=[mem])
    det = BudgetDriftDetector(_pinned_one(), recorder=rec)
    det.observe("p", [("all_gather", 2000)], chunks=0)
    [r] = det.evaluate()
    assert r.ok and r.byte_ratio == pytest.approx(2.0)
    assert mem.events("budget_drift") == []


def test_detector_validates_args():
    with pytest.raises(ValueError):
        BudgetDriftDetector({}, byte_rel_tol=-1)
    det = BudgetDriftDetector({})
    with pytest.raises(ValueError):
        det.observe("p")  # neither profile nor budget
    with pytest.raises(ValueError):
        det.observe("p", [("a", 1)], budget={"count": 1, "bytes": 1,
                                             "per_kind": {}})


def test_emissions_ride_registry_and_obs_report(tmp_path):
    """The gauge/incident telemetry validates against the default
    registry, lands in an obs dir, and surfaces in the digest's analysis
    section + incidents."""
    d = str(tmp_path / "obs")
    rec = obs.open_run(d, config=None, install=False)
    det = BudgetDriftDetector(_pinned_one(), recorder=rec)
    det.observe("p", [("all_gather", 500), ("all_gather", 500)],
                chunks=3)
    det.observe("p", [("all_gather", 2000)], chunks=1)
    reports = det.evaluate()
    assert [r.ok for r in reports] == [True, False]
    rec.close()

    snap_gauges = rec.snapshot()["gauges"]
    assert snap_gauges["analysis.budget_drift{program=p}"] == 2.0

    report = _load_tool("obs_report")
    digest = report.render_digest(d)
    assert digest["analysis"]["budget_drift_incidents"] == 1
    assert digest["analysis"]["budget_drift_ratio_max"] == 2.0
    [incident] = digest["incidents"]["budget_drift"]
    assert incident["program"] == "p" and incident["chunks"] == 1
    assert "collective bytes 2000 vs pinned 1000" in incident["reasons"][0]
    # Strict JSON all the way out (the --json contract).
    json.loads(json.dumps(report.digest_json(d), allow_nan=False))


# -- acceptance: live tiered MF vs AUDIT_r10.json ------------------------


@pytest.fixture(scope="module")
def mf_tiered_live(devices8):
    """The audit harness's exact mf_tiered configuration, RUN live for
    two chunks with a recorder: (collective profile of the dispatched
    program, chunks dispatched, recorder)."""
    import jax

    from fps_tpu.analysis import collective_profile
    from fps_tpu.parallel.mesh import make_ps_mesh

    audit = _load_tool("audit_programs")
    mesh = make_ps_mesh(num_shards=8, num_data=1, devices=devices8[:8])
    trainer, chunks = audit._mf_pieces(mesh, hot_tier=32,
                                       hot_sync_every=2)
    chunks = list(chunks)
    hlo = trainer.lowered_chunk_text(chunks[0], "sync")
    rec = obs.Recorder(sinks=[obs.MemorySink()])
    trainer.recorder = rec
    tables, ls = trainer.init_state(jax.random.key(0))
    trainer.fit_stream(tables, ls, iter(chunks[:2]), jax.random.key(1))
    return collective_profile(hlo), rec


def test_clean_tiered_mf_run_stays_quiet(mf_tiered_live):
    profile, rec = mf_tiered_live
    chunks = int(rec.counter_value("driver.chunks"))
    assert chunks == 2  # the live dispatch weight, from the data plane
    det = BudgetDriftDetector(load_pinned_budgets(_AUDIT), recorder=rec)
    det.observe("mf_tiered", profile, chunks=chunks)
    [r] = det.evaluate()
    assert r.ok and r.byte_ratio == pytest.approx(1.0)
    assert r.measured_bytes == 6144 and r.measured_count == 4
    # Quiet means QUIET: the gauge reads 1.0 and no incident event fired.
    assert rec.snapshot()["gauges"][
        "analysis.budget_drift{program=mf_tiered}"] == 1.0
    assert rec.sinks[0].events("budget_drift") == []


def test_seeded_budget_mutation_flags_incident(mf_tiered_live):
    """Halve the pinned bytes (the ISSUE's seeded mutation): the same
    live program now measures 2x the certified budget — the detector
    must flag it as an analysis.budget_drift incident."""
    profile, _ = mf_tiered_live
    pinned = copy.deepcopy(load_pinned_budgets(_AUDIT))
    pinned["mf_tiered"]["bytes"] //= 2
    mem = obs.MemorySink()
    rec = obs.Recorder(sinks=[mem])
    det = BudgetDriftDetector(pinned, recorder=rec)
    det.observe("mf_tiered", profile, chunks=2)
    [r] = det.evaluate()
    assert not r.ok
    assert r.byte_ratio == pytest.approx(2.0)
    [event] = mem.events("budget_drift")
    assert event["program"] == "mf_tiered"
    assert math.isclose(event["byte_ratio"], 2.0)
    assert any("bytes" in reason for reason in event["reasons"])
