"""Smoke tests for the L5 CLI entrypoints (the reference's example jobs).

Each entrypoint runs in-process on a tiny synthetic workload and must emit a
"done" event with a sane quality metric — the analog of the reference's
example jobs being runnable end-to-end on the local mini-cluster.
"""

import json

import pytest


def run_main(module, argv, capsys):
    rc = module.main(argv)
    assert rc == 0
    events = [json.loads(line) for line in capsys.readouterr().out.splitlines()]
    by_event = {}
    for e in events:
        by_event.setdefault(e["event"], []).append(e)
    assert "done" in by_event, f"no done event in {events}"
    return by_event


TINY = ["--epochs", "1", "--local-batch", "32", "--steps-per-chunk", "4"]


def test_mf_entrypoint(devices8, capsys, tmp_path):
    from fps_tpu.examples import mf

    export = str(tmp_path / "mf.npz")
    ev = run_main(
        mf,
        TINY + ["--scale", "100k", "--rank", "4", "--topk", "3",
                "--export", export],
        capsys,
    )
    assert ev["done"][0]["test_rmse"] < 2.0
    assert len(ev["topk"][0]["items"]) == 3
    assert ev["export"][0]["path"] == export

    # Warm start from the exported model must load cleanly.
    ev2 = run_main(
        mf, TINY + ["--scale", "100k", "--rank", "4", "--warm-start", export],
        capsys,
    )
    assert "warm_start" in ev2


def test_pa_entrypoints(devices8, capsys):
    from fps_tpu.examples import passive_aggressive as pa

    ev = run_main(
        pa, TINY + ["--num-examples", "4000", "--num-features", "500"], capsys
    )
    assert ev["done"][0]["test_accuracy"] > 0.6

    ev = run_main(
        pa,
        TINY + ["--num-examples", "4000", "--num-features", "500",
                "--num-classes", "4"],
        capsys,
    )
    assert ev["done"][0]["test_accuracy"] > 0.4


def test_word2vec_entrypoint(devices8, capsys):
    from fps_tpu.examples import word2vec as w2v

    ev = run_main(
        w2v,
        TINY + ["--vocab-size", "200", "--num-tokens", "20000", "--dim", "16"],
        capsys,
    )
    assert ev["done"][0]["pairs_per_sec"] > 0
    assert len(ev["neighbors"]) == 4


def test_logreg_entrypoint(devices8, capsys, tmp_path):
    from fps_tpu.examples import logreg_ssp

    ckdir = tmp_path / "ck"
    ev = run_main(
        logreg_ssp,
        TINY + ["--num-examples", "4000", "--num-features", "2000",
                "--sync-every", "2", "--checkpoint-dir", str(ckdir),
                "--checkpoint-every", "2"],
        capsys,
    )
    assert ev["done"][0]["test_accuracy"] > 0.6
    # --checkpoint-dir must actually produce snapshots (incl. end-of-stream).
    snaps = sorted(ckdir.glob("ckpt_*.npz"))
    assert snaps, "no checkpoints written despite --checkpoint-dir"


def test_ials_entrypoint(devices8, capsys):
    from fps_tpu.examples import ials

    ev = run_main(
        ials,
        TINY + ["--num-users", "64", "--num-items", "48", "--per-user", "10",
                "--rank", "4", "--epochs", "2"],
        capsys,
    )
    assert ev["done"][0]["recall_at_10"] > 0.0


def test_streaming_mf_entrypoint(devices8, capsys):
    from fps_tpu.examples import streaming_mf

    # bounded source: stops by exhaustion
    ev = run_main(
        streaming_mf,
        ["--local-batch", "32", "--steps-per-chunk", "4",
         "--num-users", "60", "--num-items", "40", "--rank", "4",
         "--max-records", "20000", "--source-batch", "1024"],
        capsys,
    )
    assert ev["done"][0]["stopped_by"] == "stream_exhausted"
    assert ev["done"][0]["records_seen"] == 20000.0
    # chunk RMSE falls over the stream
    rmses = [c["train_rmse"] for c in ev["chunk"]]
    assert rmses[-1] < rmses[0]

    # unbounded source: stops by convergence target
    ev = run_main(
        streaming_mf,
        ["--local-batch", "32", "--steps-per-chunk", "4",
         "--num-users", "60", "--num-items", "40", "--rank", "4",
         "--max-records", "0", "--target-rmse", "0.3",
         "--source-batch", "1024"],
        capsys,
    )
    assert ev["done"][0]["stopped_by"] == "target_rmse"


def test_pa_real_input_svmlight(devices8, capsys, tmp_path):
    """--input on a real svmlight file trains and evaluates (VERDICT round-1
    gap: the flag was accepted but ignored)."""
    import numpy as np

    from fps_tpu.examples import passive_aggressive as pa

    rng = np.random.default_rng(0)
    NF, N = 60, 2000
    w = rng.normal(0, 1, NF)
    lines = []
    for _ in range(N):
        ids = np.sort(rng.choice(NF, 8, replace=False)) + 1
        vals = rng.normal(0, 1, 8)
        y = 1 if (w[ids - 1] @ vals) > 0 else -1
        lines.append(f"{y:+d} " + " ".join(
            f"{i}:{v:.4f}" for i, v in zip(ids, vals)))
    path = tmp_path / "rcv1.svm"
    path.write_text("\n".join(lines) + "\n")

    ev = run_main(
        pa, ["--epochs", "3", "--local-batch", "32", "--steps-per-chunk", "4",
             "--input", str(path)], capsys,
    )
    assert ev["done"][0]["test_accuracy"] > 0.8


def test_logreg_real_input_criteo(devices8, capsys, tmp_path):
    """--input on a Criteo-format TSV trains through the SSP path with the
    AdaGrad fold (dense numeric columns make plain SGD oscillate under
    staleness)."""
    import numpy as np

    from fps_tpu.examples import logreg_ssp

    rng = np.random.default_rng(0)
    lines = []
    for _ in range(2000):
        x = rng.integers(0, 100, 13)
        c0 = rng.choice(["aaaa", "bbbb", "cccc", "dddd"])
        label = int(c0 in ("aaaa", "bbbb")) if rng.random() > 0.05 else \
            int(rng.random() > 0.5)
        cats = [c0] + [format(int(v), "06x")
                       for v in rng.integers(0, 1000, 25)]
        lines.append("\t".join([str(label)] + [str(v) for v in x] + cats))
    path = tmp_path / "criteo.tsv"
    path.write_text("\n".join(lines) + "\n")

    ev = run_main(
        logreg_ssp,
        ["--epochs", "12", "--local-batch", "32", "--steps-per-chunk", "8",
         "--input", str(path), "--optimizer", "adagrad"],
        capsys,
    )
    assert ev["done"][0]["test_accuracy"] > 0.8


def test_bench_combined_summary_line_contract(capsys):
    """The driver parses bench.py's FINAL stdout line and keeps a bounded
    tail. Round 4 proved the binding constraint is SIZE, not shape: the
    rich combined line (nested baseline dicts, prose) overran the tail
    window and BENCH_r04.json.parsed was null. The final line must be a
    compact digest — per workload only {metric, value, unit, vs_baseline}
    — and must stay under a hard byte budget; the rich combined line
    rides immediately above it."""
    import importlib.util
    import json
    import os
    import sys as _sys

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    for name in bench.RUNNERS:
        # Realistically verbose stub results: long metric names, full
        # nested baseline dicts with prose "kind" strings, unrounded
        # floats — the exact payload class that overran the round-4 tail.
        bench.RUNNERS[name] = (lambda n: lambda args: {
            "metric": f"synthetic_{n}_examples_per_sec_per_chip_headline",
            "value": 5355285.333333333, "unit": "examples/s",
            "vs_baseline": None if n == "ials" else 5.302187123,
            "epoch_s": 0.1492837465,
            "baseline": {"kind": "measured native sequential loop "
                                 "(message-hop mode); 'ideal' = fused "
                                 "floor — long prose annotation " * 3,
                         "ps_examples_per_s": 1010333.7123,
                         "ideal_examples_per_s": 8836468.0123},
        })(name)
    argv, _sys.argv = _sys.argv, ["bench.py"]
    try:
        bench.main()
    finally:
        _sys.argv = argv
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    # (N-1) x (per-workload line + cumulative digest) + final workload +
    # rich combined + final digest (the last workload's digest IS the
    # final line): a killed run's final stdout line is ALWAYS a digest of
    # what completed.
    n_workloads = len(bench.RUNNERS)
    assert len(lines) == 2 * (n_workloads - 1) + 3

    final = lines[-1]
    # The driver keeps a bounded tail; the final line must fit it with
    # margin even with every workload present. 1000 bytes is the budget.
    assert len(final.encode("utf-8")) <= 1000, len(final)
    digest = json.loads(final)
    assert {"metric", "value", "unit", "vs_baseline"} <= digest.keys()
    assert set(digest["workloads"]) == set(bench.RUNNERS)
    assert digest["unit"] == "examples/s"
    for name, res in digest["workloads"].items():
        # Per workload only {value, vs_baseline}: the workload key names
        # the row, the headline metric/unit ride at top level (each
        # dropped copy bought byte budget as the workload count grew).
        assert set(res) == {"value", "vs_baseline"}
        # floats rounded: json round-trip stays short
        assert res["value"] == 5355285.3333
    assert digest["metric"] == "synthetic_mf_examples_per_sec_per_chip_headline"
    assert digest["vs_baseline"] == digest["workloads"]["mf"]["vs_baseline"]

    # Every cumulative digest (odd positions) is parseable, in budget, and
    # mirrors a headline even before mf completes (kill-resilience): the
    # fallback must track the LAST completed workload, not a stale one.
    order = ["w2v", "logreg", "pa", "ials", "mf"]
    for seen, i in enumerate((1, 3, 5, 7), start=1):
        d = json.loads(lines[i])
        assert len(lines[i].encode("utf-8")) <= 1000
        assert len(d["workloads"]) == seen
        assert d["metric"] == (
            f"synthetic_{order[seen - 1]}_examples_per_sec_per_chip_headline")

    # The rich combined line still precedes the final digest with the
    # full results.
    rich = json.loads(lines[-2])
    assert set(rich["workloads"]) == set(bench.RUNNERS)
    assert "baseline" in rich["workloads"]["mf"]
