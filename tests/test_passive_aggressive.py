"""Passive-aggressive classifier: online mistake rate falls, held-out
accuracy beats chance by a wide margin (binary + multiclass), on the full
sparse fan-out path (many pulls per example)."""

import jax
import numpy as np
import pytest

from fps_tpu.core.driver import num_workers_of
from fps_tpu.core.ingest import epoch_chunks
from fps_tpu.models.passive_aggressive import (
    PAConfig,
    passive_aggressive,
    predict_host,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.utils.datasets import (
    synthetic_sparse_classification,
    synthetic_sparse_multiclass,
    train_test_split,
)

NF, NNZ = 500, 10


def run_pa(mesh, cfg, data, epochs=4, local_batch=16):
    trainer, store = passive_aggressive(mesh, cfg)
    train, test = train_test_split(data)
    tables, ls = trainer.init_state(jax.random.key(0))
    W = num_workers_of(mesh)
    key = jax.random.key(1)
    metrics = []
    for e in range(epochs):
        chunks = epoch_chunks(
            train, num_workers=W, local_batch=local_batch, steps_per_chunk=8, seed=e
        )
        tables, ls, m = trainer.fit_stream(tables, ls, chunks, jax.random.fold_in(key, e))
        metrics.extend(m)
    mistakes = np.concatenate([m["mistakes"] for m in metrics])
    n = np.concatenate([m["n"] for m in metrics])
    pred = predict_host(store, test["feat_ids"], test["feat_vals"], cfg.num_classes)
    acc = float(np.mean(pred == test["label"]))
    return mistakes, n, acc


@pytest.mark.parametrize("variant", ["PA", "PA-I", "PA-II"])
def test_pa_binary_learns(devices8, variant):
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    data = synthetic_sparse_classification(6000, NF, NNZ, seed=2, noise=0.05)
    cfg = PAConfig(num_features=NF, variant=variant, C=1.0)
    mistakes, n, acc = run_pa(mesh, cfg, data)
    # Online mistake rate in the last quarter well below the first quarter.
    q = len(mistakes) // 4
    early = mistakes[:q].sum() / n[:q].sum()
    late = mistakes[-q:].sum() / n[-q:].sum()
    assert late < early * 0.7, (early, late)
    assert acc > 0.8, acc


def test_pa_multiclass_learns(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=2)
    data = synthetic_sparse_multiclass(6000, NF, 5, NNZ, seed=3)
    cfg = PAConfig(num_features=NF, num_classes=5, variant="PA-I", C=1.0)
    _, _, acc = run_pa(mesh, cfg, data, epochs=6)
    assert acc > 0.55, acc  # chance = 0.2


def test_pa_weights_stay_zero_without_data(devices8):
    """Features never touched keep exactly their init (zero) — pushes of
    padding rows must not leak (the reference's SimplePSLogic only updates
    pushed ids)."""
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    data = synthetic_sparse_classification(200, NF, NNZ, seed=4)
    # restrict features to the low half of the id space
    data["feat_ids"] = data["feat_ids"] % (NF // 2)
    cfg = PAConfig(num_features=NF, variant="PA-I")
    trainer, store = passive_aggressive(mesh, cfg)
    tables, ls = trainer.init_state(jax.random.key(0))
    W = num_workers_of(mesh)
    chunks = epoch_chunks(
        data, num_workers=W, local_batch=16, steps_per_chunk=4, seed=0
    )
    tables, ls, _ = trainer.fit_stream(tables, ls, chunks, jax.random.key(1))
    untouched = store.lookup_host(
        "weights", np.arange(NF // 2, NF)
    )
    np.testing.assert_array_equal(untouched, 0.0)


def test_head_sort_slots_contract():
    """head_sort_slots: per-example multiset preserved, head ids first,
    q = min head count."""
    from fps_tpu.utils.datasets import head_sort_slots

    data = synthetic_sparse_classification(500, NF, NNZ, seed=7)
    H = 50
    data2, q = head_sort_slots(data, H)
    ids, ids2 = data["feat_ids"], data2["feat_ids"]
    # multiset of (id, val) pairs preserved per example
    for b in (0, 123, 499):
        a = sorted(zip(data["feat_ids"][b], data["feat_vals"][b]))
        c = sorted(zip(data2["feat_ids"][b], data2["feat_vals"][b]))
        assert a == c
    head_counts = (ids < H).sum(axis=1)
    assert q == int(head_counts.min())
    # first q columns are head ids in EVERY example
    assert (ids2[:, :q] < H).all()
    # within each example, no head id after a tail id
    is_tail = ids2 >= H
    assert (np.diff(is_tail.astype(int), axis=1) >= 0).all()


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_head_prefix_training_matches_plain(devices8, backend):
    """PA with head-prefix routing (sorted slots + nnz-major flatten +
    head-only kernels) must train to the same weights as the plain
    row-major path on the same sorted data — the hint is routing only."""
    import fps_tpu.ops as ops_mod
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.utils.datasets import head_sort_slots

    H = 64
    data = synthetic_sparse_classification(4096, NF, NNZ, seed=9,
                                           noise=0.05)
    data, q = head_sort_slots(data, H)
    assert q >= 1
    mesh = make_ps_mesh(num_shards=1, num_data=1, devices=jax.devices()[:1])

    prev = ops_mod.get_backend()
    ops_mod.set_backend(backend)
    try:
        def run(head):
            cfg = PAConfig(num_features=NF, variant="PA-I", C=1.0,
                           hot_features=H if head else 0,
                           head_prefix_cols=q if head else 0)
            trainer, store = passive_aggressive(mesh, cfg, donate=False)
            tables, ls = trainer.init_state(jax.random.key(0))
            ds = DeviceDataset(mesh, data)
            plan = DeviceEpochPlan(ds, num_workers=1, local_batch=2048,
                                   seed=3)
            tables, ls, m = trainer.run_indexed(tables, ls, plan,
                                                jax.random.key(1), epochs=2)
            return (np.asarray(store.dump_model("weights")[1]),
                    float(np.sum(m[-1]["mistakes"])))

        w_head, mk_head = run(True)
        w_plain, mk_plain = run(False)
    finally:
        ops_mod.set_backend(prev)

    assert np.abs(w_plain).max() > 0
    np.testing.assert_allclose(w_head, w_plain, rtol=3e-4, atol=3e-4)
