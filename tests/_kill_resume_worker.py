"""Subprocess body for the SIGKILL-and-resume end-to-end test.

Usage: python _kill_resume_worker.py <mode> <ckdir> <out_npz>

Modes (all on one process with 8 virtual CPU devices, (2, 4) mesh):

* ``straight`` — train 4 indexed epochs uninterrupted, dump the model.
* ``victim``   — train with a rolling Checkpointer (keep=2,
  checkpoint_every=1) and SIGKILL OURSELVES from the ``on_epoch`` callback
  after epoch 3's training but BEFORE its checkpoint lands: the process
  dies mid-run with no atexit/flush, losing epoch 3's work — the crash the
  reference's Flink-era checkpointing cannot survive on iterative streams.
* ``resume``   — FRESH process: restore the latest snapshot (epoch 2),
  continue with ``start_epoch=2`` for the remaining 2 epochs, dump the
  model. The parent asserts straight == resumed bit-for-bit, which is only
  possible if the per-epoch shuffle (``plan.epoch_args(e)``) and PRNG
  stream (``fold_in(key, e)``) genuinely continue across the process
  boundary (driver.py's resume contract).

Chaos extensions (fps_tpu.testing.chaos; tests/test_checkpoint.py and
tests/test_resilience.py):

* ``victim-midwrite`` — like ``victim``, but dies DURING epoch 3's
  checkpoint write, leaving a partial ``.tmp.npz`` in the directory (the
  torn-write window of ``_atomic_savez``): snapshots 1 and 2 stay intact,
  step 3 never lands.
* ``victim-async-midwrite`` — checkpoints through the
  ``AsyncCheckpointer`` and SIGKILLs from INSIDE the background writer
  while step 3's serialize is underway (partial tmp on disk, rename
  never reached): the async writer's atomicity contract — a kill
  mid-background-write publishes nothing torn, ``latest_valid_step``
  stays 2, and resume-any still reproduces the straight run.
* ``resume-any`` — FRESH process: restore whatever the newest *intact*
  snapshot is (fallback path — the parent may have corrupted the newest
  file first), continue to 4 total epochs, dump the model. The parent
  still asserts bit-identity with ``straight``, extending the kill-resume
  contract to corrupted/torn snapshots.

Deferred-capture chain extensions (ISSUE 20; tests/test_checkpoint.py
``test_deferred_capture_sigkill_midcapture_resumes``): ``fit_stream``
with ``prefetch=2`` routes saves through ``save_deferred`` — the
device→host capture runs on the WRITER thread over a delta chain
(``DeltaPolicy(full_every=50)``):

* ``straight-stream`` — 6 chunks of ``fit_stream``, no checkpointer.
* ``victim-capture-kill`` — same stream, AsyncCheckpointer + delta
  chain + ``prefetch=2``, and SIGKILL from INSIDE the writer's THIRD
  ``_run_capture`` call: the crash lands mid-device→host-capture, after
  steps 1 (full) and 2 (delta) published but before step 3 touched disk.
* ``resume-stream`` — FRESH process: restore through the delta chain
  (full 1 + delta 2), continue with ``start_step=2``, dump. The parent
  asserts bit-identity with ``straight-stream`` — a kill mid-capture
  loses at most the boundary being captured, never recovered bytes.
"""

import os
import signal
import sys


def main() -> int:
    mode, ckdir, out = sys.argv[1], sys.argv[2], sys.argv[3]

    import numpy as np

    import jax

    from fps_tpu.core.checkpoint import Checkpointer
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.core.driver import num_workers_of
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.parallel.mesh import make_ps_mesh
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=2)
    W = num_workers_of(mesh)
    data = synthetic_ratings(57, 31, 2000, seed=0)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)
    trainer, store = online_mf(mesh, cfg)
    tables, ls = trainer.init_state(jax.random.key(0))
    ds = DeviceDataset(mesh, data)
    plan = DeviceEpochPlan(ds, num_workers=W, local_batch=32,
                           route_key="user", seed=5)
    key = jax.random.key(1)

    from fps_tpu.models.recommendation import mf_user_vectors

    def dump(path):
        # Local state compared in LOGICAL user order: physical padding
        # slots (users >= 57 on this worker layout) are dead state — never
        # routed, never observable — and the exported-checkpoint roundtrip
        # does not preserve them (import zero-fills), by design.
        np.savez(path, item_factors=store.dump_model("item_factors")[1],
                 user_factors=mf_user_vectors(np.asarray(ls), W,
                                              np.arange(57)))

    if mode == "straight":
        tables, ls, _ = trainer.run_indexed(tables, ls, plan, key, epochs=4)
        dump(out)
        return 0

    if mode in ("straight-stream", "victim-capture-kill", "resume-stream"):
        import dataclasses

        from fps_tpu.core import checkpoint as ck_mod
        from fps_tpu.core.ingest import epoch_chunks

        # A user table big enough that a per-boundary touched-row delta
        # is genuinely smaller than a full dump (the planner falls back
        # to a full when the delta wouldn't save bytes).
        NU, NI = 1024, 64
        cfg2 = MFConfig(num_users=NU, num_items=NI, rank=4,
                        learning_rate=0.1)
        trainer2, store2 = online_mf(mesh, cfg2)
        # prefetch=2 turns on the overlapped pipeline: boundary copies +
        # writer-side capture (save_deferred) — the layer under test.
        trainer2.config = dataclasses.replace(trainer2.config, prefetch=2)
        tables, ls = trainer2.init_state(jax.random.key(0))
        data2 = synthetic_ratings(NU, NI, 2000, seed=0)
        chunks = list(epoch_chunks(data2, num_workers=W, local_batch=32,
                                   steps_per_chunk=2, route_key="user",
                                   seed=0))[:6]
        skey = jax.random.key(7)

        def dump_stream(path):
            np.savez(path,
                     item_factors=store2.dump_model("item_factors")[1],
                     user_factors=mf_user_vectors(np.asarray(ls), W,
                                                  np.arange(NU)))

        if mode == "straight-stream":
            tables, ls, _ = trainer2.fit_stream(tables, ls, chunks, skey)
            dump_stream(out)
            return 0

        ackpt = ck_mod.AsyncCheckpointer(
            ckdir, keep=8, delta=ck_mod.DeltaPolicy(full_every=50))

        if mode == "victim-capture-kill":
            real_capture = ck_mod._run_capture
            calls = {"n": 0}

            def dying_capture(collect):
                calls["n"] += 1
                if calls["n"] == 3:
                    # Step 3's WRITER-side device→host capture: die
                    # before a single byte of it reaches disk.
                    os.kill(os.getpid(), signal.SIGKILL)
                return real_capture(collect)

            ck_mod._run_capture = dying_capture
            trainer2.fit_stream(tables, ls, chunks, skey,
                                checkpointer=ackpt, checkpoint_every=1)
            raise AssertionError("victim-capture-kill must never get here")

        # resume-stream: a fresh process restores through the delta
        # chain and continues the same stream from the same boundary.
        tables, ls, step = trainer2.restore_checkpoint(ackpt, ls)
        assert step == 2, step
        tables, ls, _ = trainer2.fit_stream(
            tables, ls, chunks[step:], skey, checkpointer=ackpt,
            checkpoint_every=1, start_step=step)
        ackpt.close()
        dump_stream(out)
        return 0

    ckpt = Checkpointer(ckdir, keep=2)

    if mode == "victim":
        def die_mid_run(e, _metrics):
            if e == 2:  # epoch 3 trained; its checkpoint has NOT landed yet
                os.kill(os.getpid(), signal.SIGKILL)

        trainer.run_indexed(tables, ls, plan, key, epochs=4,
                            checkpointer=ckpt, checkpoint_every=1,
                            on_epoch=die_mid_run)
        raise AssertionError("victim must never get here")

    if mode == "victim-midwrite":
        from fps_tpu.testing import chaos

        real_save = ckpt.save

        def dying_save(step, store_, local_state_=None, **kw):
            if step == 3:
                # Partial tmp file hits the disk, then SIGKILL — the torn
                # window between mkstemp and os.replace in _atomic_savez.
                chaos.partial_write_then_kill(ckdir)
            return real_save(step, store_, local_state_, **kw)

        ckpt.save = dying_save
        trainer.run_indexed(tables, ls, plan, key, epochs=4,
                            checkpointer=ckpt, checkpoint_every=1)
        raise AssertionError("victim-midwrite must never get here")

    if mode == "victim-async-midwrite":
        from fps_tpu.core import checkpoint as ck_mod
        from fps_tpu.testing import chaos

        ackpt = ck_mod.AsyncCheckpointer(ckdir, keep=2)
        real_savez = ck_mod._atomic_savez

        def dying_savez(path, arrays, precommit=None):
            if path.endswith(ck_mod.SNAPSHOT_FMT.format(step=3)):
                # Step 3's BACKGROUND write: partial tmp hits the disk,
                # then SIGKILL — from the writer thread itself, i.e. the
                # kill lands mid-serialize with the rename never reached.
                chaos.partial_write_then_kill(ckdir)
            return real_savez(path, arrays, precommit)

        ck_mod._atomic_savez = dying_savez
        trainer.run_indexed(tables, ls, plan, key, epochs=4,
                            checkpointer=ackpt, checkpoint_every=1)
        raise AssertionError("victim-async-midwrite must never get here")

    if mode in ("resume", "resume-any"):
        if mode == "resume":
            # The plain kill window: snapshot 2 must be the survivor.
            assert ckpt.latest_valid_step() == 2
        tables, ls, step = trainer.restore_checkpoint(ckpt, ls)
        tables, ls, _ = trainer.run_indexed(tables, ls, plan, key,
                                            epochs=4 - step,
                                            start_epoch=step)
        dump(out)
        return 0

    raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    raise SystemExit(main())
