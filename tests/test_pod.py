"""Pod-level coordination (fps_tpu/supervise/pod.py + tools/supervise.py).

Tier-1 keeps the pod protocol honest at stub speed: N member agents
(the REAL CLI, one subprocess each) over one shared pod dir, each
supervising a jax-free stub child (``tests/_supervised_stub.py``) that
beats, publishes zip "snapshots" shaped like real checkpoints, honors
the pod-commanded common restart step, and refuses to publish behind a
pod fence. The real-jax versions of these scenarios live in
``fps_tpu.testing.supervised_demo`` (run by ``tools/chaos_sweep.py`` and
the slow tests below).
"""

import json
import os
import signal
import subprocess
import sys
import time
import zipfile

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_STUB = os.path.join(_ROOT, "tests", "_supervised_stub.py")
_CLI = os.path.join(_ROOT, "tools", "supervise.py")

HOSTS = ("h0", "h1", "h2")


def _member_cmd(pod_dir, host, pod_size, *flags, child=()):
    return [
        sys.executable, _CLI, "--pod-dir", str(pod_dir), "--pod-host",
        host, "--pod-size", str(pod_size),
        "--stall-timeout-s", "1.2", "--startup-grace-s", "15",
        "--term-grace-s", "0.4", "--backoff-base-s", "0.1",
        "--backoff-max-s", "0.5", "--max-restarts", "6",
        "--poll-s", "0.1", "--lease-ttl-s", "1.0",
        "--member-timeout-s", "3.0", *flags,
        "--", sys.executable, _STUB,
        "--dir", os.path.join(str(pod_dir), "{host}"), *child,
    ]


def _launch(pod_dir, *flags, hosts=HOSTS, child=()):
    return {
        h: subprocess.Popen(
            _member_cmd(pod_dir, h, len(hosts), *flags, child=child),
            cwd=_ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for h in hosts
    }


def _collect(procs, timeout=120):
    out = {}
    deadline = time.monotonic() + timeout
    for h, p in procs.items():
        try:
            stdout, _ = p.communicate(
                timeout=max(5, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            stdout, _ = p.communicate()
        digest = None
        try:
            digest = json.loads(stdout.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            pass
        out[h] = (p.returncode, digest, stdout[-1500:])
    return out


def _result(pod_dir, host):
    with open(os.path.join(str(pod_dir), host, "result.json"),
              encoding="utf-8") as f:
        return json.load(f)


def _read_json(path):
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


# ---------------------------------------------------------------------------
# End-to-end member protocol (subprocess, stub children).
# ---------------------------------------------------------------------------

def test_pod_clean_run_elects_one_leader(tmp_path):
    """A fault-free pod: every member succeeds, exactly one leader term
    is ever held, zero restarts, and every stub finishes all chunks."""
    res = _collect(_launch(tmp_path / "pod",
                           child=("--chunks", "6", "--chunk-s", "0.05")))
    assert all(rc == 0 and d["success"] for rc, d, _ in res.values()), res
    assert sum(d["leader_terms"] for _, d, _ in res.values()) == 1
    assert all(d["pod"]["restarts"] == 0 for _, d, _ in res.values())
    for h in HOSTS:
        assert _result(tmp_path / "pod", h)["done"] == 6


def test_pod_wedged_member_one_coordinated_abort(tmp_path):
    """One member's child SIGSTOPs mid-run: the stall becomes ONE
    pod-wide decision — every member digest shows the same single
    coordinated restart, nothing is quarantined, and the pod journal
    narrates the abort (member_failed -> fence_written -> pod_restart)."""
    res = _collect(_launch(
        tmp_path / "pod",
        child=("--chunks", "6", "--chunk-s", "0.05", "--wedge-at", "3",
               "--wedge-mode", "sigstop", "--misbehave-host", "h1")))
    assert all(rc == 0 and d["success"] for rc, d, _ in res.values()), res
    assert all(d["pod"]["restarts"] == 1 for _, d, _ in res.values())
    assert all(d["pod"]["quarantined"] == [] for _, d, _ in res.values())
    events = [json.loads(line)["event"] for line in
              open(tmp_path / "pod" / "journal-pod.jsonl")]
    for expected in ("pod_start", "member_failed", "fence_written",
                     "pod_restart", "pod_shutdown"):
        assert expected in events, events


def test_pod_quarantine_broadcast(tmp_path):
    """A chunk that crashes ONE member on every attempt is quarantined
    POD-WIDE after two coordinated restarts: every member's stub — the
    never-crashing ones included — skips it, so no host re-dispatches a
    chunk another host proved poisonous."""
    res = _collect(_launch(
        tmp_path / "pod",
        child=("--chunks", "8", "--chunk-s", "0.05", "--crash-at", "5",
               "--misbehave-host", "h1")))
    assert all(rc == 0 and d["success"] for rc, d, _ in res.values()), res
    assert all(d["pod"]["quarantined"] == [5]
               for _, d, _ in res.values())
    assert all(d["pod"]["restarts"] == 2 for _, d, _ in res.values())
    for h in HOSTS:
        assert 5 not in _result(tmp_path / "pod", h)["ran"], h
    # The broadcast rides the pod state file through the child env
    # contract (STATE_ENV -> pod_state.json).
    state = _read_json(tmp_path / "pod" / "pod_state.json")
    assert state["quarantined"] == [5]


def test_pod_elastic_eviction_and_readmission(tmp_path):
    """Elastic membership at stub speed: one member's child dies at
    startup (index-less — never quarantinable) until evicted at W-1;
    the fault then clears, the member reports ready, and the leader
    re-admits it (snapshot sync + restart at W). Every member finishes."""
    fixed = tmp_path / "fixed"
    procs = _launch(
        tmp_path / "pod", "--elastic", "--evict-after", "2",
        "--rejoin-delay-s", "0.5",
        child=("--chunks", "10", "--chunk-s", "0.15",
               "--crash-until-file", str(fixed),
               "--misbehave-host", "h2"))
    # Clear the fault the moment the eviction lands (world drops to 2).
    deadline = time.monotonic() + 60
    saw_world2 = False
    while time.monotonic() < deadline:
        ctl = _read_json(tmp_path / "pod" / "pod_control.json")
        if ctl and ctl.get("action") == "run" and ctl.get("world") == 2:
            saw_world2 = True
            open(fixed, "w").close()
            break
        time.sleep(0.05)
    res = _collect(procs)
    assert saw_world2, [r[2] for r in res.values()]
    assert all(rc == 0 and d["success"] for rc, d, _ in res.values()), res
    assert all(d["pod"]["readmissions"] == 1 for _, d, _ in res.values())
    assert all(d["pod"]["world"] == 3 for _, d, _ in res.values())
    assert all(d["pod"]["evicted"] == [] for _, d, _ in res.values())
    for h in HOSTS:
        assert _result(tmp_path / "pod", h)["done"] == 10
    events = [json.loads(line)["event"] for line in
              open(tmp_path / "pod" / "journal-pod.jsonl")]
    for expected in ("member_evicted", "member_readmitted"):
        assert expected in events, events


def test_pod_partition_seizure_and_fencing(tmp_path):
    """The lease holder's member agent is SIGSTOPped: a follower seizes
    the lease (epoch bump), fences every member dir, and restarts the
    pod — and the stale leader's ORPHANED stub child is refused by the
    fence on its next publish (exit 9, 'stale epoch' in its log). On
    SIGCONT the deposed leader rejoins and the pod completes."""
    procs = _launch(tmp_path / "pod", "--lease-ttl-s", "0.6",
                    "--member-timeout-s", "1.2",
                    child=("--chunks", "40", "--chunk-s", "0.25"))
    lease_path = tmp_path / "pod" / "pod_lease.json"
    deadline = time.monotonic() + 60
    leader = None
    try:
        while time.monotonic() < deadline:
            lease = _read_json(lease_path)
            holder = (lease or {}).get("host")
            mem = (_read_json(tmp_path / "pod" / "members"
                              / f"{holder}.json") if holder else None)
            # Freeze only once the leader's CHILD exists and has
            # published — otherwise there is no orphan to fence.
            if mem and mem.get("child_pid") \
                    and (mem.get("latest_step") or 0) >= 1:
                leader = holder
                os.kill(procs[leader].pid, signal.SIGSTOP)
                break
            time.sleep(0.05)
        assert leader is not None, "no leader emerged"
        seized_by = None
        while time.monotonic() < deadline:
            lease = _read_json(lease_path)
            if lease and lease.get("host") != leader:
                seized_by = lease["host"]
                break
            time.sleep(0.05)
        assert seized_by is not None, "lease never seized"
        # Fence lands with the post-partition restart; the orphan (still
        # publishing every 0.25s) must hit it. Give it a moment.
        time.sleep(3.0)
    finally:
        if leader is not None:
            try:
                os.kill(procs[leader].pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
    res = _collect(procs)
    assert all(rc == 0 and d["success"] for rc, d, _ in res.values()), res
    assert res[seized_by][1]["leader_terms"] >= 1
    # The orphan's refusal: its attempt log carries the stub's stale-
    # epoch marker (the checkpoint layer's StaleEpochError analog).
    logs = ""
    ldir = tmp_path / "pod" / leader
    for f in os.listdir(ldir):
        if f.startswith("attempt-") and f.endswith(".log"):
            logs += open(ldir / f, encoding="utf-8",
                         errors="replace").read()
    assert "stale epoch" in logs, logs[-800:]
    # Epoch monotonicity across the seizure: the final epoch exceeds 2
    # (initial acquire + launch) because the seizure bumped it.
    assert all(d["epoch"] >= 4 for _, d, _ in res.values())


def test_pod_give_up_exhausts_budget(tmp_path):
    """An unrecoverable member (wedges every attempt, quarantine can't
    help) burns the pod restart budget: the leader gives up, every
    member exits nonzero with action=give_up."""
    res = _collect(_launch(
        tmp_path / "pod", "--max-restarts", "1",
        child=("--chunks", "6", "--chunk-s", "0.05", "--wedge-at", "2",
               "--wedge-always", "--misbehave-host", "h1")))
    assert all(rc == 1 and not d["success"]
               for rc, d, _ in res.values()), res
    assert all(d["action"] == "give_up" for _, d, _ in res.values())


# ---------------------------------------------------------------------------
# Library pieces (no subprocess).
# ---------------------------------------------------------------------------

def test_snapshot_re_mirrors_format():
    """pod.py mirrors the snapshot filename contract (it must stay
    stdlib-only and cannot import the numpy-laden snapshot_format) —
    this is the tripwire for the mirror drifting."""
    from fps_tpu.core import snapshot_format
    from fps_tpu.supervise import pod

    assert pod.SNAPSHOT_RE.pattern == snapshot_format.SNAPSHOT_RE.pattern


def test_pod_module_loads_without_fps_tpu():
    """The jax-free contract: loading pod.py by file path in a bare
    interpreter must import neither fps_tpu nor jax nor numpy."""
    code = (
        "import importlib.util, sys\n"
        f"path = {os.path.join(_ROOT, 'fps_tpu', 'supervise', 'pod.py')!r}\n"
        "spec = importlib.util.spec_from_file_location('_pod', path)\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "sys.modules[spec.name] = mod\n"
        "spec.loader.exec_module(mod)\n"
        "mod.PodConfig(pod_size=2)\n"
        "bad = [m for m in sys.modules if m == 'jax' or m == 'numpy'"
        " or m.startswith(('jax.', 'numpy.', 'fps_tpu'))]\n"
        "assert not bad, bad\n"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-2000:]


def test_lease_acquire_renew_seize(tmp_path):
    """Lease mechanics with a controlled clock: two-tick acquisition,
    renewal keeps the holder, an expired lease is seized with an epoch
    bump, and the deposed holder observes the loss."""
    from fps_tpu.supervise.pod import Lease

    now = [100.0]
    a = Lease(str(tmp_path / "lease.json"), "a", 2.0, clock=lambda: now[0])
    b = Lease(str(tmp_path / "lease.json"), "b", 2.0, clock=lambda: now[0])

    held, _, _ = a.tick()  # claim
    assert not held
    held, rec, seized = a.tick()  # confirm
    assert held and rec["epoch"] == 1 and not b.tick()[0]

    now[0] += 1.0  # fresh enough: b cannot seize, a renews
    assert not b.tick()[0]
    assert a.tick()[0]

    now[0] += 10.0  # expired: b claims...
    held, _, _ = b.tick()
    assert not held
    held, rec, seized = b.tick()  # ...and confirms with a bumped epoch
    assert held and seized and rec["epoch"] == 2
    assert not a.tick()[0]  # the deposed holder steps down


def test_lease_claim_race_single_winner(tmp_path):
    """Two simultaneous claims settle on the single rename winner: the
    later writer holds, the earlier claimant loses its claim."""
    from fps_tpu.supervise.pod import Lease

    now = [10.0]
    a = Lease(str(tmp_path / "l.json"), "a", 2.0, clock=lambda: now[0])
    b = Lease(str(tmp_path / "l.json"), "b", 2.0, clock=lambda: now[0])
    a.tick()  # a claims
    b._write(1)  # b's racing claim rename lands after a's
    b._claimed = True
    assert not a.tick()[0]  # a reads b's record: claim lost
    held, rec, seized = b.tick()
    assert held and rec["host"] == "b"


def test_fence_helpers(tmp_path):
    from fps_tpu.supervise.child import (
        fence_allows,
        read_fence,
        write_fence,
    )

    d = str(tmp_path)
    assert read_fence(d) is None
    assert fence_allows(d, None) == (True, 0)  # unfenced: everyone may
    write_fence(d, 4, 17)
    assert read_fence(d) == {"min_epoch": 4, "step": 17}
    assert fence_allows(d, 5) == (True, 4)
    assert fence_allows(d, 4) == (True, 4)
    assert fence_allows(d, 3) == (False, 4)
    assert fence_allows(d, None) == (False, 4)  # epoch-less writer


def test_latest_valid_snapshot_step_stdlib_verify(tmp_path):
    """The coordinator's stdlib-only snapshot verification: zip CRCs
    catch truncation, non-snapshot names are ignored, and the newest
    INTACT step wins."""
    from fps_tpu.supervise.pod import latest_valid_snapshot_step

    d = str(tmp_path)
    assert latest_valid_snapshot_step(d) is None
    for step in (3, 5):
        with zipfile.ZipFile(
                os.path.join(d, f"ckpt_{step:012d}.npz"), "w") as z:
            z.writestr("x", b"payload" * 64)
    open(os.path.join(d, "not_a_ckpt.npz"), "wb").write(b"junk")
    assert latest_valid_snapshot_step(d) == 5
    # Truncate the newest: the scan falls back to the survivor.
    p5 = os.path.join(d, "ckpt_%012d.npz" % 5)
    with open(p5, "r+b") as f:
        f.truncate(os.path.getsize(p5) // 2)
    cache = {}
    assert latest_valid_snapshot_step(d, cache) == 3
    assert latest_valid_snapshot_step(d, cache) == 3  # cached verdicts


def test_pod_config_validation():
    from fps_tpu.supervise import PodConfig

    with pytest.raises(ValueError):
        PodConfig(pod_size=0)
    with pytest.raises(ValueError):
        PodConfig(lease_ttl_s=0)
    with pytest.raises(ValueError):
        PodConfig(evict_after=0)


def test_pod_member_rejects_bad_host(tmp_path):
    from fps_tpu.supervise import PodMember

    with pytest.raises(ValueError):
        PodMember(["true"], pod_dir=str(tmp_path), host="a/b")
    with pytest.raises(ValueError):
        PodMember(["true"], pod_dir=str(tmp_path), host="")


def test_pod_state_future_schema_refused(tmp_path):
    from fps_tpu.supervise import PodMember

    m = PodMember(["true"], pod_dir=str(tmp_path), host="h0")
    with open(m.pod_state_path, "w", encoding="utf-8") as f:
        json.dump({"schema": 99}, f)
    with pytest.raises(ValueError):
        m._load_pod_state()


def test_child_cmd_host_template(tmp_path):
    from fps_tpu.supervise import PodMember

    m = PodMember(["run", "--dir", "{host}-work", "--plain"],
                  pod_dir=str(tmp_path), host="h7")
    assert m._child_cmd() == ["run", "--dir", "h7-work", "--plain"]


def test_child_env_carries_pod_contract(tmp_path):
    from fps_tpu.supervise import PodMember, child

    m = PodMember(["true"], pod_dir=str(tmp_path), host="h1")
    m._pod_ctx = {"epoch": 4, "world": 3, "step": 7}
    env = m._child_env(2)
    assert env[child.POD_HOST_ENV] == "h1"
    assert env[child.POD_EPOCH_ENV] == "4"
    assert env[child.POD_WORLD_ENV] == "3"
    assert env[child.POD_STEP_ENV] == "7"
    # Quarantine broadcast: the child's carried set comes from the POD
    # state file, not the member's own.
    assert env[child.STATE_ENV] == m.pod_state_path
    assert env[child.ATTEMPT_ENV] == "2"


def test_pod_env_parsing(monkeypatch):
    from fps_tpu.supervise import child

    for var in (child.POD_HOST_ENV, child.POD_EPOCH_ENV,
                child.POD_WORLD_ENV, child.POD_STEP_ENV):
        monkeypatch.delenv(var, raising=False)
    assert child.pod_env() == {"host": None, "epoch": None, "world": None,
                               "step": None}
    monkeypatch.setenv(child.POD_HOST_ENV, "h2")
    monkeypatch.setenv(child.POD_EPOCH_ENV, "5")
    monkeypatch.setenv(child.POD_WORLD_ENV, "3")
    monkeypatch.setenv(child.POD_STEP_ENV, "9")
    assert child.pod_env() == {"host": "h2", "epoch": 5, "world": 3,
                               "step": 9}


def test_cli_pod_flag_validation(tmp_path):
    """--pod-dir and --pod-host must travel together; --state-dir stays
    required outside pod mode."""
    for flags in (["--pod-dir", str(tmp_path)],
                  ["--pod-host", "h0"],
                  []):
        r = subprocess.run(
            [sys.executable, _CLI, *flags, "--", "true"],
            capture_output=True, text=True, timeout=60, cwd=_ROOT)
        assert r.returncode == 2, (flags, r.stderr)


# ---------------------------------------------------------------------------
# Full stack (slow): real jax children under the pod coordinator.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_pod_kill_one_host_bit_identical(tmp_path):
    from fps_tpu.testing.supervised_demo import (
        run_pod_kill_one_host_scenario,
    )

    ok, detail = run_pod_kill_one_host_scenario(str(tmp_path))
    assert ok, detail


@pytest.mark.slow
def test_pod_partition_coordinator_fenced(tmp_path):
    from fps_tpu.testing.supervised_demo import (
        run_pod_partition_coordinator_scenario,
    )

    ok, detail = run_pod_partition_coordinator_scenario(str(tmp_path))
    assert ok, detail


@pytest.mark.slow
def test_pod_flapping_member_quarantine_broadcast(tmp_path):
    from fps_tpu.testing.supervised_demo import (
        run_pod_flapping_member_scenario,
    )

    ok, detail = run_pod_flapping_member_scenario(str(tmp_path))
    assert ok, detail


@pytest.mark.slow
def test_pod_elastic_resize_bit_identical(tmp_path):
    from fps_tpu.testing.supervised_demo import (
        run_pod_elastic_resize_scenario,
    )

    ok, detail = run_pod_elastic_resize_scenario(str(tmp_path))
    assert ok, detail


# ---------------------------------------------------------------------------
# Review-hardening regressions.
# ---------------------------------------------------------------------------

def test_dead_host_keeps_accruing_failures_until_evicted(tmp_path):
    """A PERMANENTLY unreachable host re-fires its staleness incident
    every member_timeout (it must reach the elastic eviction budget —
    one frozen incident would stick its failure count at 1 forever),
    while the pacing stops a single partition from burning the restart
    budget within one poll tick."""
    from fps_tpu.supervise import PodConfig, PodMember, SupervisorConfig
    from fps_tpu.supervise.pod import _atomic_write_json

    cfg = PodConfig(pod_size=2, elastic=True, evict_after=2,
                    member_timeout_s=0.4,
                    member=SupervisorConfig(backoff_base_s=0.05))
    m = PodMember(["true"], pod_dir=str(tmp_path), host="h0", config=cfg)
    assert not m.lease.tick()[0] and m.lease.tick()[0]  # claim + confirm
    m.is_leader = True
    m.pod_state = m._load_pod_state()
    m.pod_state["epoch"] = 1
    m.pod_state["roster"] = m.pod_state["plan"] = ["h0", "h1"]

    def fresh_self(status="running"):
        _atomic_write_json(os.path.join(m.members_dir, "h0.json"),
                           {"host": "h0", "t": time.time(),
                            "epoch": int(m.pod_state["epoch"]),
                            "status": status})

    # h1 never writes a beacon: unreachable from the start.
    fresh_self()
    now = time.time()
    m._leader_tick(now)
    assert m.pod_state["failures"].get("h1") == 1
    # Same tick window: the incident is deduped, no double-count.
    m._leader_tick(now + 0.1)
    assert m.pod_state["failures"].get("h1") == 1
    # Past the pacing window: still unreachable -> counts again -> evicted.
    fresh_self()
    m._leader_tick(now + 1.0)
    assert m.pod_state["failures"].get("h1") == 2
    assert m.pod_state["evicted"] == ["h1"]
    assert m.pod_state["plan"] == ["h0"]


def test_lease_epoch_regression_reseized(tmp_path):
    """A deposed leader frozen mid-renewal can rename a STALE (lower-
    epoch) record over the successor's lease; observers treat the
    regression as expiry and re-seize strictly ABOVE every epoch ever
    seen, keeping the fencing epoch monotone."""
    from fps_tpu.supervise.pod import Lease

    now = [100.0]
    a = Lease(str(tmp_path / "l.json"), "a", 2.0, clock=lambda: now[0])
    b = Lease(str(tmp_path / "l.json"), "b", 2.0, clock=lambda: now[0])
    a.tick(), a.tick()  # a holds at epoch 1
    now[0] += 10.0
    b.tick(), b.tick()  # expired: b seizes at epoch 2
    assert b.tick()[0]
    # a's frozen renewal resumes: last-writer-wins reinstalls epoch 1.
    a._write(1)
    held, rec, _ = b.tick()
    assert not held  # b saw the regression and re-claimed...
    held, rec, seized = b.tick()
    assert held and seized and rec["epoch"] == 3  # ...strictly above max


def test_readmit_deferred_when_sync_fails(tmp_path):
    """A failed catch-up sync DEFERS readmission: admitting an unsynced
    member would roll the whole pod back to its stale frontier via the
    common-step min."""
    from fps_tpu.supervise import PodConfig, PodMember

    cfg = PodConfig(pod_size=2, elastic=True)
    m = PodMember(["true"], pod_dir=str(tmp_path), host="h0", config=cfg)
    assert not m.lease.tick()[0] and m.lease.tick()[0]
    m.pod_state = m._load_pod_state()
    m.pod_state["epoch"] = 3
    m.pod_state["roster"] = ["h0", "h1"]
    m.pod_state["plan"] = ["h0"]
    m.pod_state["evicted"] = ["h1"]
    # The pod HAS canonical progress (a valid snapshot at step 4)...
    with zipfile.ZipFile(
            os.path.join(str(tmp_path), "h0", "ckpt_%012d.npz" % 4),
            "w") as z:
        z.writestr("x", b"y" * 64)
    # ...but the copy into h1 fails.
    m._sync_member = lambda host: None
    m._readmit(time.time(), "h1")
    assert m.pod_state["evicted"] == ["h1"]  # still out
    assert m.pod_state["plan"] == ["h0"]
    assert m.pod_state["readmissions"] == 0
    events = [json.loads(line)["event"] for line in
              open(tmp_path / "journal-pod.jsonl")]
    assert "readmit_deferred" in events


def test_oversize_snapshot_structural_verify_only(tmp_path, monkeypatch):
    """Past FULL_VERIFY_MAX_BYTES the scan checks zip STRUCTURE only
    (bounded stall in the lease-renewing poll loop); under it, member
    CRCs still catch bit rot."""
    from fps_tpu.supervise import pod

    d = str(tmp_path)
    p = os.path.join(d, "ckpt_%012d.npz" % 7)
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("x", b"payload" * 64)
    # Flip a payload byte: CRC now fails, structure still parses.
    with open(p, "r+b") as f:
        f.seek(os.path.getsize(p) // 2)
        byte = f.read(1)[0]
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte ^ 0xFF]))
    assert pod.latest_valid_snapshot_step(d) is None  # full CRC: caught
    monkeypatch.setattr(pod, "FULL_VERIFY_MAX_BYTES", 8)
    assert pod.latest_valid_snapshot_step(d) == 7  # structural only
