"""Resilience layer: on-device step-health guards, host-loop rollback, and
the chaos injectors themselves (snapshot-corruption fallback lives in
tests/test_checkpoint.py, next to the machinery it extends).

Acceptance contract (ISSUE 1):

* a synthetic NaN-poisoned batch under ``guard="mask"`` leaves every table
  finite, increments the ``health`` metrics channel, and final model
  quality matches the clean run within tolerance — while the same run
  with the guard off is demonstrably destroyed (negative control);
* ``guard=None`` (the default) compiles to the identical program as a
  guard-free build — no health-channel cost when the feature is off.
"""

import jax
import numpy as np
import pytest

from fps_tpu.core.driver import Trainer, TrainerConfig, num_workers_of
from fps_tpu.core.resilience import (
    GuardConfig,
    PoisonedStreamError,
    RollbackPolicy,
    as_guard,
    guard_pushes,
    health_total,
)
from fps_tpu.core.api import StepOutput, WorkerLogic
from fps_tpu.core.store import ParamStore, TableSpec
from fps_tpu.models.logistic_regression import (
    LogRegConfig,
    logistic_regression,
)
from fps_tpu.parallel.mesh import make_ps_mesh
from fps_tpu.testing import chaos
from fps_tpu.testing.workloads import (
    NF,
    accuracy as _accuracy,
    health_sum as _health_sum,
    logreg_chunks as _logreg_chunks,
    logreg_data as _logreg_data,
    run_logreg as _run_logreg,
    weights as _weights,
)


def test_poison_mask_survives_and_matches_clean(devices8):
    """ISSUE acceptance: poison batch + guard='mask' -> finite tables,
    health channel incremented, quality within tolerance of the clean run;
    guard=None on the same stream is destroyed."""
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    W = num_workers_of(mesh)
    train, test = _logreg_data()
    clean = _logreg_chunks(train, W)
    poisoned = list(
        chaos.poison_chunks(
            iter(clean), chunk_index=2, column="feat_vals", kind="nan",
            frac=0.5, seed=1,
        )
    )

    _, store_clean, _ = _run_logreg(mesh, clean)
    acc_clean = _accuracy(store_clean, test)

    # Negative control: no guard -> NaN deltas reach the additive fold and
    # destroy the weight table.
    _, store_dead, _ = _run_logreg(mesh, poisoned, guard=None)
    assert not np.all(np.isfinite(_weights(store_dead)))

    # Guarded: the poisoned rows degrade to dropped updates.
    _, store_ok, metrics = _run_logreg(mesh, poisoned, guard="mask")
    w = _weights(store_ok)
    assert np.all(np.isfinite(w))
    assert _health_sum(metrics, "weights", "nonfinite") > 0
    assert _health_sum(metrics, "weights", "masked") > 0
    acc_ok = _accuracy(store_ok, test)
    assert acc_ok > 0.75, acc_ok
    assert abs(acc_clean - acc_ok) < 0.05, (acc_clean, acc_ok)


def test_guard_observe_counts_without_masking(devices8):
    """'observe' surfaces the poison on the health channel but leaves the
    update stream untouched (the table IS destroyed) — the mode rollback
    policies build on."""
    mesh = make_ps_mesh(num_shards=8, num_data=1)
    W = num_workers_of(mesh)
    train, _ = _logreg_data()
    poisoned = list(
        chaos.poison_chunks(
            iter(_logreg_chunks(train, W, epochs=1)), chunk_index=0,
            column="feat_vals", kind="nan", frac=0.5, seed=1,
        )
    )
    _, store, metrics = _run_logreg(mesh, poisoned, guard="observe")
    assert _health_sum(metrics, "weights", "nonfinite") > 0
    assert _health_sum(metrics, "weights", "masked") == 0
    assert not np.all(np.isfinite(_weights(store)))


# ---------------------------------------------------------------------------
# Precise semantics on a controlled pusher worker (1-device mesh).
# ---------------------------------------------------------------------------

class _Pusher(WorkerLogic):
    """Pushes batch['val'] rows verbatim to batch['id'] rows of table 't'."""

    def pull_ids(self, batch):
        return {"t": batch["id"].astype(np.int32)}

    def step(self, batch, pulled, local_state, key):
        return StepOutput(
            pushes={"t": (batch["id"].astype(np.int32), batch["val"])},
            local_state=local_state,
            out={},
        )


def _pusher_trainer(devices8, guard, dim=2):
    mesh = make_ps_mesh(num_shards=1, num_data=1, devices=devices8[:1])
    store = ParamStore(mesh, [TableSpec("t", 16, dim).zeros_init()])
    trainer = Trainer(
        mesh, store, _Pusher(),
        config=TrainerConfig(donate=False, guard=guard),
    )
    return mesh, store, trainer


def test_guard_mask_and_norm_limit_exact(devices8):
    """Row-exact mask semantics: NaN rows and norm-exploded rows drop,
    everything else lands; per-kind health counts are exact."""
    _, store, trainer = _pusher_trainer(
        devices8, GuardConfig(mode="mask", norm_limit=10.0)
    )
    ids = np.array([[0, 1, 2, -1]], np.int32)           # (T=1, B=4)
    val = np.array([[[1.0, 1.0],
                     [np.nan, 0.0],
                     [100.0, 0.0],                      # norm 100 > 10
                     [np.nan, np.nan]]], np.float32)    # padding row
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, m = trainer.run_chunk(
        tables, ls, {"id": ids, "val": val}, jax.random.key(1)
    )
    got = store.dump_model("t")[1]
    want = np.zeros_like(got)
    want[0] = [1.0, 1.0]  # the only surviving push row
    np.testing.assert_array_equal(got, want)
    h = m["health"]["t"]
    assert int(np.sum(np.asarray(h["nonfinite"]))) == 1  # live NaN row only
    assert int(np.sum(np.asarray(h["norm"]))) == 1
    assert int(np.sum(np.asarray(h["masked"]))) == 2
    assert health_total(jax.tree.map(np.asarray, m)) == 2


def test_guard_off_compiles_identical_program(devices8):
    """guard=None must trace the exact guard-free program: no finite-checks
    in the lowered HLO, no health channel in the metrics, and the text is
    identical across fresh trainers (while guard='mask' does change it)."""
    from fps_tpu.parallel.mesh import key_to_replicated

    def lowered_text(guard):
        mesh, store, trainer = _pusher_trainer(devices8, guard)
        tables, ls = trainer.init_state(jax.random.key(0))
        chunk = {
            "id": np.zeros((1, 4), np.int32),
            "val": np.zeros((1, 4, 2), np.float32),
        }
        sharding = trainer._batch_sharding_for("sync")
        from fps_tpu.parallel.mesh import host_to_sharded

        batches = jax.tree.map(
            lambda x: host_to_sharded(x, sharding), chunk
        )
        key = key_to_replicated(jax.random.key(1), mesh)
        fn = trainer._get_compiled("sync")
        return fn.lower(tables, ls, batches, key).as_text()

    text_off = lowered_text(None)
    assert "is_finite" not in text_off
    assert lowered_text(None) == text_off  # deterministic trace
    text_on = lowered_text("mask")
    assert "is_finite" in text_on
    assert text_on != text_off

    # And the metrics tree carries no health entry when the guard is off.
    _, _, trainer = _pusher_trainer(devices8, None)
    tables, ls = trainer.init_state(jax.random.key(0))
    _, _, m = trainer.run_chunk(
        tables, ls,
        {"id": np.zeros((1, 4), np.int32),
         "val": np.zeros((1, 4, 2), np.float32)},
        jax.random.key(1),
    )
    assert "health" not in m
    assert health_total(jax.tree.map(np.asarray, m)) == 0


# ---------------------------------------------------------------------------
# Host-loop degradation: rollback + quarantine.
# ---------------------------------------------------------------------------

def test_rollback_quarantines_poisoned_chunk(devices8):
    """fit_stream + RollbackPolicy: the poisoned chunk is rolled back and
    skipped; the result is bit-identical to running only the clean chunks
    with their original per-chunk keys (PRNG stream intact)."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    train, _ = _logreg_data()
    clean = _logreg_chunks(train, W, epochs=1)[:4]
    poisoned = list(
        chaos.poison_chunks(
            iter(clean), chunk_index=1, column="feat_vals", kind="nan",
            frac=0.5, seed=1,
        )
    )

    policy = RollbackPolicy()
    trainerA, storeA, _ = _run_logreg(
        mesh, poisoned, guard="observe", rollback=policy
    )
    assert policy.quarantined == [1]
    wA = _weights(storeA)
    assert np.all(np.isfinite(wA))

    # Reference: same guard (same compiled program), clean chunks only,
    # with each chunk keyed by its ORIGINAL stream index.
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainerB, storeB = logistic_regression(mesh, cfg, guard="observe")
    tables, ls = trainerB.init_state(jax.random.key(0))
    for i in (0, 2, 3):
        tables, ls, _ = trainerB.run_chunk(
            tables, ls, clean[i], jax.random.fold_in(jax.random.key(1), i)
        )
    np.testing.assert_array_equal(wA, _weights(storeB))


def test_rollback_final_chunk_still_checkpoints(tmp_path, devices8):
    """A quarantined LAST chunk landing on a checkpoint boundary must not
    suppress the end-of-stream save: the last clean state still reaches
    disk (under the final step number, so a resume skips the poison)."""
    from fps_tpu.core.checkpoint import Checkpointer

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    train, _ = _logreg_data()
    clean = _logreg_chunks(train, W, epochs=1)[:4]
    poisoned = list(
        chaos.poison_chunks(
            iter(clean), chunk_index=3, column="feat_vals", kind="nan",
            frac=0.5, seed=1,
        )
    )
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(mesh, cfg, guard="observe")
    tables, ls = trainer.init_state(jax.random.key(0))
    ckpt = Checkpointer(str(tmp_path / "c"))
    policy = RollbackPolicy()
    trainer.fit_stream(
        tables, ls, iter(poisoned), jax.random.key(1),
        checkpointer=ckpt, checkpoint_every=2, rollback=policy,
    )
    assert policy.quarantined == [3]
    # Periodic save at step 2 happened; the i=3 boundary save was skipped
    # by the quarantine, so the end-of-stream save must cover it.
    assert ckpt.steps() == [2, 4]
    _, vals, _, _ = ckpt.read_snapshot(4)
    assert np.all(np.isfinite(vals["weights"]))


def test_rollback_budget_and_guard_requirement(devices8):
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    train, _ = _logreg_data()
    clean = _logreg_chunks(train, W, epochs=1)[:3]
    all_poisoned = [
        next(iter(chaos.poison_chunks(
            iter([c]), chunk_index=0, column="feat_vals", kind="nan",
            frac=0.5, seed=i,
        )))
        for i, c in enumerate(clean)
    ]

    # rollback without a guard: no health channel to act on.
    with pytest.raises(ValueError, match="guard"):
        _run_logreg(mesh, clean, guard=None, rollback=RollbackPolicy())

    # every chunk poisoned + budget 1 -> the stream is declared poisoned.
    with pytest.raises(PoisonedStreamError):
        _run_logreg(
            mesh, all_poisoned, guard="observe",
            rollback=RollbackPolicy(max_rollbacks=1),
        )


def test_rollback_run_indexed_epochs(devices8):
    """run_indexed + RollbackPolicy: a dataset whose ratings are poisoned
    rolls back every epoch — final tables bit-equal the initial ones, and
    the quarantine record names each epoch."""
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    data = synthetic_ratings(57, 31, 800, seed=0)
    data = dict(data, rating=chaos.poison_rows(
        np.asarray(data["rating"], np.float32), np.arange(0, 800, 5), "nan"
    ))
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)
    trainer, store = online_mf(mesh, cfg, guard="observe", donate=False)
    tables, ls = trainer.init_state(jax.random.key(0))
    before = store.dump_model("item_factors")[1].copy()
    plan = DeviceEpochPlan(DeviceDataset(mesh, data), num_workers=W,
                           local_batch=32, route_key="user", seed=5)
    policy = RollbackPolicy(max_rollbacks=4)
    tables, ls, metrics = trainer.run_indexed(
        tables, ls, plan, jax.random.key(1), epochs=2, rollback=policy
    )
    assert policy.quarantined == [0, 1]
    assert metrics == []  # both epochs quarantined -> no metrics entries
    np.testing.assert_array_equal(store.dump_model("item_factors")[1], before)
    assert np.all(np.isfinite(np.asarray(ls)))


# ---------------------------------------------------------------------------
# Worker-LOCAL guard coverage (ISSUE 3: the MF-style mask-mode gap).
# ---------------------------------------------------------------------------

def _mf_poisoned(devices8):
    """(mesh, cfg, poisoned chunk list, clean chunk list) for the standard
    tiny MF workload with NaN ratings planted in chunk 1 — the poison that
    reaches the LOCAL user factors, not just the item pushes."""
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=2)
    W = num_workers_of(mesh)
    data = synthetic_ratings(57, 31, 2000, seed=0)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)
    clean = list(epoch_chunks(data, num_workers=W, local_batch=8,
                              steps_per_chunk=4, route_key="user", seed=0))
    poisoned = list(chaos.poison_chunks(iter(clean), chunk_index=1,
                                        column="rating", kind="nan",
                                        frac=0.5, seed=1))
    return mesh, cfg, poisoned, clean


def _run_mf(mesh, cfg, chunks, guard):
    from fps_tpu.models.matrix_factorization import online_mf

    trainer, store = online_mf(mesh, cfg, guard=guard)
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, m = trainer.fit_stream(tables, ls, chunks, jax.random.key(1))
    return store, np.asarray(ls), m


def test_local_guard_masks_poisoned_local_state(devices8):
    """ISSUE acceptance: a poisoned MF batch under guard='mask' WITHOUT
    the local tier still NaNs the worker-local user factors (the
    documented gap — negative control); with ``local=True`` the local
    rows stay finite and the 'local_state' health entry counts them."""
    mesh, cfg, poisoned, _ = _mf_poisoned(devices8)

    # Negative control: push masking alone leaves the local plane exposed.
    store, ls, _ = _run_mf(mesh, cfg, poisoned, GuardConfig(mode="mask"))
    assert np.all(np.isfinite(store.dump_model("item_factors")[1]))
    assert not np.all(np.isfinite(ls)), "expected the documented local gap"

    store, ls, m = _run_mf(mesh, cfg, poisoned,
                           GuardConfig(mode="mask", local=True))
    assert np.all(np.isfinite(ls))
    assert np.all(np.isfinite(store.dump_model("item_factors")[1]))
    nf = _health_sum(m, "local_state", "nonfinite")
    mk = _health_sum(m, "local_state", "masked")
    assert nf > 0 and mk > 0
    # The push-plane counters still fire independently.
    assert _health_sum(m, "item_factors", "nonfinite") > 0


def test_local_guard_observe_counts_without_touching_state(devices8):
    """local + observe: the update stream (both planes) stays
    byte-identical to a plain observe run; only the counters differ."""
    mesh, cfg, poisoned, _ = _mf_poisoned(devices8)
    store_a, ls_a, m_a = _run_mf(mesh, cfg, poisoned,
                                 GuardConfig(mode="observe", local=True))
    store_b, ls_b, _ = _run_mf(mesh, cfg, poisoned,
                               GuardConfig(mode="observe"))
    np.testing.assert_array_equal(ls_a, ls_b)
    np.testing.assert_array_equal(store_a.dump_model("item_factors")[1],
                                  store_b.dump_model("item_factors")[1])
    assert _health_sum(m_a, "local_state", "nonfinite") > 0
    assert _health_sum(m_a, "local_state", "masked") == 0


def test_local_guard_free_when_no_local_state(devices8):
    """A worker with no float local state (the pusher's empty tuple)
    compiles the IDENTICAL program with local on or off — the tier only
    costs where there is a local plane to guard, and no phantom
    'local_state' health entry appears."""
    from fps_tpu.parallel.mesh import host_to_sharded, key_to_replicated

    def lowered_text(guard):
        mesh, store, trainer = _pusher_trainer(devices8, guard)
        tables, ls = trainer.init_state(jax.random.key(0))
        chunk = {"id": np.zeros((1, 4), np.int32),
                 "val": np.zeros((1, 4, 2), np.float32)}
        sharding = trainer._batch_sharding_for("sync")
        batches = jax.tree.map(lambda x: host_to_sharded(x, sharding), chunk)
        key = key_to_replicated(jax.random.key(1), mesh)
        return trainer._get_compiled("sync").lower(
            tables, ls, batches, key).as_text()

    assert (lowered_text(GuardConfig(mode="mask")) ==
            lowered_text(GuardConfig(mode="mask", local=True)))

    _, _, trainer = _pusher_trainer(
        devices8, GuardConfig(mode="mask", local=True))
    tables, ls = trainer.init_state(jax.random.key(0))
    _, _, m = trainer.run_chunk(
        tables, ls,
        {"id": np.zeros((1, 4), np.int32),
         "val": np.zeros((1, 4, 2), np.float32)},
        jax.random.key(1),
    )
    assert "local_state" not in m["health"]


def test_guard_local_state_unit_semantics():
    """Direct guard_local_state semantics: row-exact nonfinite + norm
    tiers, revert-to-old masking, non-float leaves untouched, empty tree
    reports None (no phantom health entry)."""
    from fps_tpu.core.resilience import guard_local_state

    old = {"f": jnp_arr([[0.0, 0.0], [1.0, 1.0], [2.0, 2.0]]),
           "i": np.array([1, 2, 3], np.int32)}
    new = {"f": jnp_arr([[np.nan, 0.0], [1.0, 1.5], [200.0, 2.0]]),
           "i": np.array([4, 5, 6], np.int32)}
    guard = GuardConfig(mode="mask", norm_limit=10.0, local=True)
    guarded, counts = guard_local_state(old, new, guard)
    # Row 0: nonfinite -> reverted; row 2: delta norm 198 > 10 -> reverted.
    np.testing.assert_array_equal(
        np.asarray(guarded["f"]),
        np.array([[0.0, 0.0], [1.0, 1.5], [2.0, 2.0]], np.float32))
    np.testing.assert_array_equal(np.asarray(guarded["i"]), new["i"])
    assert int(counts["nonfinite"]) == 1
    assert int(counts["norm"]) == 1
    assert int(counts["masked"]) == 2

    # Observe: counts only, state passes through untouched.
    observed, counts = guard_local_state(
        old, new, GuardConfig(mode="observe", norm_limit=10.0, local=True))
    np.testing.assert_array_equal(np.asarray(observed["f"]),
                                  np.asarray(new["f"]))
    assert int(counts["masked"]) == 0

    # No inexact leaves -> (new, None).
    same, counts = guard_local_state((), (), guard)
    assert same == () and counts is None


def jnp_arr(x):
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(x, np.float32))


def test_guard_local_state_touched_unit_semantics():
    """Ids-aware screening (touched_local_rows): row masking restricted
    to the touched set; an untouched non-finite row is still CAUGHT at
    the leaf tier (counted as nonfinite) but never masked — there is
    nothing to revert it to."""
    from fps_tpu.core.resilience import guard_local_state

    # Row 0: pre-existing NaN in old AND new (untouched stale poison).
    # Row 1: touched, this step wrote NaN. Row 3: touched, huge delta.
    # Row 4: untouched, clean.
    old = jnp_arr([[np.nan, 0.0], [1.0, 1.0], [2.0, 2.0],
                   [3.0, 3.0], [4.0, 4.0]])
    new = jnp_arr([[np.nan, 0.0], [np.nan, 1.0], [2.0, 2.0],
                   [300.0, 3.0], [4.0, 4.0]])
    guard = GuardConfig(mode="mask", norm_limit=10.0, local=True)
    touched = (np.array([1, 3, -1], np.int32),)
    guarded, counts = guard_local_state((old,), (new,), guard,
                                        touched=touched)
    got = np.asarray(guarded[0])
    # Touched rows 1 and 3 reverted; untouched NaN row 0 NOT masked.
    np.testing.assert_array_equal(got[1], [1.0, 1.0])
    np.testing.assert_array_equal(got[3], [3.0, 3.0])
    assert np.isnan(got[0, 0])
    np.testing.assert_array_equal(got[4], [4.0, 4.0])
    # nonfinite = touched row 1 + the leaf-tier net's untouched row 0.
    assert int(counts["nonfinite"]) == 2
    assert int(counts["norm"]) == 1
    assert int(counts["masked"]) == 2

    # Duplicate touched ids count per occurrence (the push guard's
    # per-batch-row convention) and revert deterministically.
    dup, counts = guard_local_state(
        (old,), (new,), guard, touched=(np.array([1, 1], np.int32),))
    np.testing.assert_array_equal(np.asarray(dup[0])[1], [1.0, 1.0])
    assert int(counts["masked"]) == 2
    # touched entry count must match the flattened leaves
    with pytest.raises(ValueError, match="one entry per"):
        guard_local_state((old,), (new,), guard, touched=())

    # Out-of-range touched ids are inert like -1 (a WorkerLogic bug —
    # e.g. global ids where local rows are expected — must not screen
    # the clamped last row or count phantom reverts).
    oor, counts = guard_local_state(
        (old,), (new,), guard, touched=(np.array([99, 1], np.int32),))
    np.testing.assert_array_equal(np.asarray(oor[0])[1], [1.0, 1.0])
    np.testing.assert_array_equal(np.asarray(oor[0])[4], [4.0, 4.0])
    # nonfinite = touched row 1 + leaf net's row 0; nothing from id 99.
    assert int(counts["nonfinite"]) == 2
    assert int(counts["masked"]) == 1


def test_local_guard_ids_aware_untouched_rows_caught(devices8):
    """ISSUE 7 satellite: MF exposes touched_local_rows, so the local
    guard masks only rows the batch writes — and a NaN planted in an
    UNTOUCHED user's local row is still caught by the leaf-tier net
    (counted nonfinite every chunk, masked never: full-leaf screening
    would have reported it as masked, which is the distinguishing
    observable)."""
    from fps_tpu.core.ingest import epoch_chunks
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf

    mesh = make_ps_mesh(num_shards=4, num_data=2)
    W = num_workers_of(mesh)
    NU, POISON_USER = 57, 56
    rng = np.random.default_rng(0)
    n = 2000
    data = {  # users only in [0, 40): users 40.. are never touched
        "user": rng.integers(0, 40, n).astype(np.int32),
        "item": rng.integers(0, 31, n).astype(np.int32),
        "rating": rng.normal(0, 1, n).astype(np.float32),
    }
    cfg = MFConfig(num_users=NU, num_items=31, rank=4, learning_rate=0.1)
    trainer, store = online_mf(mesh, cfg,
                               guard=GuardConfig(mode="mask", local=True))
    assert trainer.logic.touched_local_rows(
        {"user": jnp_arr([3]), "weight": jnp_arr([1.0])}) is not None
    tables, ls = trainer.init_state(jax.random.key(0))

    # Plant NaN in the untouched user's local row (owner-major layout).
    rps = -(-NU // W)
    phys = (POISON_USER % W) * rps + POISON_USER // W
    host = np.asarray(ls).copy()
    host[phys] = np.nan
    ls = jax.device_put(host, ls.sharding)

    chunks = list(epoch_chunks(data, num_workers=W, local_batch=8,
                               steps_per_chunk=4, route_key="user", seed=0))
    tables, ls, m = trainer.fit_stream(tables, ls, iter(chunks),
                                       jax.random.key(1))
    nf = _health_sum(m, "local_state", "nonfinite")
    mk = _health_sum(m, "local_state", "masked")
    assert nf > 0, "untouched-row NaN must be caught at the leaf tier"
    assert mk == 0, ("ids-aware screening must not mask outside the "
                     "touched set (full-leaf screening would)")
    out = np.asarray(ls)
    assert np.all(np.isnan(out[phys])), "nothing can revert untouched NaN"
    mask = np.ones(len(out), bool)
    mask[phys] = False
    assert np.all(np.isfinite(out[mask]))


def test_local_guard_reserved_table_name_rejected(devices8):
    """A store table literally named 'local_state' + guard.local would
    collide on the health channel: rejected at Trainer construction."""
    mesh = make_ps_mesh(num_shards=1, num_data=1, devices=devices8[:1])
    store = ParamStore(mesh, [TableSpec("local_state", 16, 2).zeros_init()])
    with pytest.raises(ValueError, match="local_state"):
        Trainer(mesh, store, _Pusher(),
                config=TrainerConfig(guard=GuardConfig(local=True)))


# ---------------------------------------------------------------------------
# Supervisor-carried quarantine: RollbackPolicy.preset.
# ---------------------------------------------------------------------------

def test_preset_skip_without_guard_fit_stream(devices8):
    """A preset-only policy (no guard) is legal and skips exactly the
    preset chunks without dispatching them — bit-identical to running
    only the surviving chunks under their original stream keys."""
    mesh, cfg, poisoned, clean = _mf_poisoned(devices8)
    from fps_tpu.models.matrix_factorization import online_mf

    policy = RollbackPolicy(preset=[1])
    trainer, store = online_mf(mesh, cfg)
    tables, ls = trainer.init_state(jax.random.key(0))
    tables, ls, m = trainer.fit_stream(
        tables, ls, poisoned, jax.random.key(1), rollback=policy)
    assert policy.skipped == [1]
    assert policy.quarantined == []  # nothing health-based happened
    assert len(m) == len(clean) - 1  # no metrics entry for the skip
    assert np.all(np.isfinite(np.asarray(ls)))

    trainer2, store2 = online_mf(mesh, cfg)
    tables2, ls2 = trainer2.init_state(jax.random.key(0))
    for i in [0] + list(range(2, len(clean))):
        tables2, ls2, _ = trainer2.run_chunk(
            tables2, ls2, clean[i], jax.random.fold_in(jax.random.key(1), i))
    np.testing.assert_array_equal(np.asarray(ls), np.asarray(ls2))
    np.testing.assert_array_equal(store.dump_model("item_factors")[1],
                                  store2.dump_model("item_factors")[1])


def test_preset_skip_run_indexed_epoch(devices8):
    """run_indexed honors the preset at epoch granularity: epoch 0
    skipped == starting the same run at epoch 1."""
    from fps_tpu.core.device_ingest import DeviceDataset, DeviceEpochPlan
    from fps_tpu.models.matrix_factorization import MFConfig, online_mf
    from fps_tpu.utils.datasets import synthetic_ratings

    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    data = synthetic_ratings(57, 31, 800, seed=0)
    cfg = MFConfig(num_users=57, num_items=31, rank=4, learning_rate=0.1)

    def fresh():
        trainer, store = online_mf(mesh, cfg, donate=False)
        tables, ls = trainer.init_state(jax.random.key(0))
        plan = DeviceEpochPlan(DeviceDataset(mesh, data), num_workers=W,
                               local_batch=32, route_key="user", seed=5)
        return trainer, store, tables, ls, plan

    trainer, store, tables, ls, plan = fresh()
    policy = RollbackPolicy(preset=[0])
    trainer.run_indexed(tables, ls, plan, jax.random.key(1), epochs=2,
                        rollback=policy)
    assert policy.skipped == [0]
    got = store.dump_model("item_factors")[1].copy()

    trainer2, store2, tables2, ls2, plan2 = fresh()
    trainer2.run_indexed(tables2, ls2, plan2, jax.random.key(1), epochs=1,
                         start_epoch=1)
    np.testing.assert_array_equal(got, store2.dump_model("item_factors")[1])


# ---------------------------------------------------------------------------
# Health channel under user-supplied metrics reductions.
# ---------------------------------------------------------------------------

def test_health_counters_survive_metrics_reduce(devices8):
    """The health channel is ordinary metrics: a user metrics_reduce sees
    it and can aggregate it like any other leaf — the counters must not
    be stripped or zeroed on the way to the reduction."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    train, _ = _logreg_data()
    clean = _logreg_chunks(train, W, epochs=1)[:3]
    poisoned = list(chaos.poison_chunks(
        iter(clean), chunk_index=1, column="feat_vals", kind="nan",
        frac=0.5, seed=1,
    ))
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, _ = logistic_regression(mesh, cfg, guard="mask")
    tables, ls = trainer.init_state(jax.random.key(0))

    def reduce_sum(ms):
        assert len(ms) == 3  # the reduce sees every chunk, unreduced
        assert all("health" in m for m in ms)
        return jax.tree.map(lambda *xs: np.sum(xs), *ms)

    _, _, reduced = trainer.fit_stream(
        tables, ls, iter(poisoned), jax.random.key(1),
        metrics_reduce=reduce_sum,
    )
    assert int(reduced["health"]["weights"]["nonfinite"]) > 0
    assert int(reduced["health"]["weights"]["masked"]) > 0
    assert int(reduced["health"]["weights"]["norm"]) == 0


def test_maybe_quarantine_sees_unreduced_totals(devices8):
    """_maybe_quarantine must act on the PER-CHUNK, unreduced health
    totals: a metrics_reduce that strips the health channel entirely (the
    most adversarial user reduction) must not blind the rollback path —
    the quarantine decision happens before any user reduction runs."""
    mesh = make_ps_mesh(num_shards=4, num_data=1, devices=devices8[:4])
    W = num_workers_of(mesh)
    train, _ = _logreg_data()
    clean = _logreg_chunks(train, W, epochs=1)[:3]
    poisoned = list(chaos.poison_chunks(
        iter(clean), chunk_index=1, column="feat_vals", kind="nan",
        frac=0.5, seed=1,
    ))
    cfg = LogRegConfig(num_features=NF, learning_rate=0.5)
    trainer, store = logistic_regression(mesh, cfg, guard="observe")
    tables, ls = trainer.init_state(jax.random.key(0))
    policy = RollbackPolicy()

    def strip_health(ms):
        return [{k: v for k, v in m.items() if k != "health"} for m in ms]

    _, _, reduced = trainer.fit_stream(
        tables, ls, iter(poisoned), jax.random.key(1),
        rollback=policy, metrics_reduce=strip_health,
    )
    assert policy.quarantined == [1]  # the drop didn't blind the driver
    assert len(reduced) == 2  # quarantined chunk contributes no entry
    assert all("health" not in m for m in reduced)
    assert np.all(np.isfinite(_weights(store)))


# ---------------------------------------------------------------------------
# Guard primitives + chaos injector determinism.
# ---------------------------------------------------------------------------

def test_as_guard_coercion_and_validation():
    assert as_guard(None) is None
    assert as_guard("observe") == GuardConfig(mode="observe")
    g = GuardConfig(mode="mask", norm_limit=1.0)
    assert as_guard(g) is g
    with pytest.raises(ValueError):
        GuardConfig(mode="zap")
    with pytest.raises(ValueError):
        GuardConfig(norm_limit=0.0)
    with pytest.raises(TypeError):
        as_guard(3)


def test_guard_unknown_table_fails_fast(devices8):
    """A typo'd guard.tables would silently disable the guard — the
    trainer must reject it at construction."""
    mesh = make_ps_mesh(num_shards=1, num_data=1, devices=devices8[:1])
    store = ParamStore(mesh, [TableSpec("t", 16, 2).zeros_init()])
    with pytest.raises(ValueError, match="unknown tables"):
        Trainer(mesh, store, _Pusher(),
                config=TrainerConfig(guard=GuardConfig(tables=("typo",))))


def test_guard_pushes_table_scoping():
    import jax.numpy as jnp

    ids = jnp.array([0, 1], jnp.int32)
    bad = jnp.array([[jnp.nan], [1.0]], jnp.float32)
    pushes = {"a": (ids, bad), "b": (ids, bad)}
    out, health = guard_pushes(pushes, GuardConfig(mode="mask", tables=("a",)))
    assert set(health) == {"a"}
    assert int(out["a"][0][0]) == -1      # masked in the guarded table
    assert int(out["b"][0][0]) == 0       # untouched outside the scope
    assert np.isnan(np.asarray(out["b"][1])[0, 0])


def test_poison_chunks_deterministic_and_scoped():
    chunks = [
        {"x": np.zeros((2, 4), np.float32), "y": np.ones(3)},
        {"x": np.zeros((2, 4), np.float32), "y": np.ones(3)},
    ]
    out1 = list(chaos.poison_chunks(iter(chunks), chunk_index=1, column="x",
                                    frac=0.25, seed=9))
    out2 = list(chaos.poison_chunks(iter(chunks), chunk_index=1, column="x",
                                    frac=0.25, seed=9))
    np.testing.assert_array_equal(out1[1]["x"], out2[1]["x"])
    np.testing.assert_array_equal(out1[0]["x"], chunks[0]["x"])  # untouched
    assert np.isnan(out1[1]["x"]).sum() == 2  # 25% of 8 entries
    np.testing.assert_array_equal(out1[1]["y"], chunks[1]["y"])
    # 'huge' stays finite (norm-tier poison, not NaN-tier).
    h = list(chaos.poison_chunks(iter(chunks), chunk_index=0, column="x",
                                 kind="huge", frac=0.25, seed=9))
    assert np.all(np.isfinite(h[0]["x"]))
    assert np.abs(h[0]["x"]).max() > 1e30


def test_bitflip_and_truncate_deterministic(tmp_path):
    p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
    payload = bytes(range(256)) * 64
    for p in (p1, p2):
        with open(p, "wb") as f:
            f.write(payload)
    chaos.bitflip_file(p1, nflips=8, seed=4)
    chaos.bitflip_file(p2, nflips=8, seed=4)
    b1, b2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert b1 == b2 and b1 != payload and len(b1) == len(payload)

    chaos.truncate_file(p1, keep_frac=0.5)
    assert len(open(p1, "rb").read()) == len(payload) // 2
